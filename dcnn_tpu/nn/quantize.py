"""Post-training int8 quantization of the inference graph.

Beyond the reference (which has no quantized path — its inference is the
training graph minus the update), and the natural completion of the
deployment transform chain started by ``fold_batchnorm``: fold BN into the
linear layers, then quantize those layers w8a8 for the v5e MXU's int8 mode
(2× the bf16 peak; kernels and measured numbers in ``ops/quant.py`` /
``benchmarks/bench_int8.py``).

Recipe (standard static PTQ):

- **Weights**: symmetric int8, per output channel
  (``ops.quant.quantize_weight``), computed from the folded weights.
- **Activations**: symmetric int8, per tensor, with a **static** scale
  calibrated from a representative batch — each quantized layer records the
  absmax of its own input during a float calibration pass. Static scales
  keep the quantize op a fused elementwise chain (dynamic ones would add a
  global reduction before every conv).
- Everything between the linear layers (pooling, activations, residual adds,
  softmax) stays in float: the int32 accumulator is dequantized per channel
  right after each conv/GEMM. This is the robust w8a8 arrangement — the
  float glue costs HBM traffic the MXU win dwarfs, and it needs no
  cross-layer scale algebra.

``quantize_model`` mirrors ``fold_batchnorm``'s walk (recursing into
ResidualBlock main/shortcut paths) and returns a NEW (model, params, state)
triple; the original objects are untouched. The quantized layers round-trip
through the layer factory and the checkpoint format like any other layer
(int8 arrays are ordinary npz entries).
"""

from __future__ import annotations

import copy
from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp

from ..ops import quant as quant_ops
from ..ops.conv import conv2d_int8
from .attention_layer import MHAGeometryMixin, MultiHeadAttentionLayer
from .factory import layer_from_config, register_layer
from .layer import ParameterizedLayer
from .layers import (Conv2DGeometryMixin, Conv2DLayer, DenseGeometryMixin,
                     DenseLayer)
from .residual import ResidualBlock
from .sequential import Sequential


class _QuantizedLayer(ParameterizedLayer):
    """Shared plumbing. PTQ layers are materialized by ``quantize_model``;
    ``init`` produces a deterministic ZERO template with the right
    shapes/dtypes — that is what ``load_checkpoint`` needs to restore a
    quantized snapshot (and what a pipeline worker needs to materialize a
    quantized stage from config + shipped weights). Zero weights make an
    uninitialized quant layer loudly useless rather than silently random."""

    def _template(self, w_shape, out_ch):
        params = {"w_q": jnp.zeros(w_shape, jnp.int8),
                  "w_scale": jnp.ones((out_ch,), jnp.float32),
                  "x_scale": jnp.ones((), jnp.float32)}
        if self.use_bias:
            params["b"] = jnp.zeros((out_ch,), jnp.float32)
        return params, {}

    def _dequant(self, y_i32, params, x_dtype, *, channel_axis: int):
        """int32 accumulator → float: per-channel (x_scale · w_scale) multiply
        + bias, cast back to the activation dtype."""
        scale = params["x_scale"] * params["w_scale"]
        shape = [1] * y_i32.ndim
        shape[channel_axis] = -1
        y = y_i32.astype(jnp.float32) * scale.reshape(shape)
        if "b" in params:
            y = y + params["b"].reshape(shape)
        return y.astype(x_dtype)


@register_layer("quant_conv2d")
class QuantConv2DLayer(Conv2DGeometryMixin, _QuantizedLayer):
    """int8 convolution layer produced by PTQ of a (folded) ``Conv2DLayer``.

    Params: ``w_q`` int8 OIHW, ``w_scale`` f32 (O,), ``x_scale`` f32 scalar
    (calibrated), optional ``b`` f32 (O,). Geometry/config/complexity come
    from the shared mixin, so shapes and partitioning keep working."""

    def __init__(self, out_channels: int, kernel_size, stride=1, padding=0,
                 use_bias: bool = True, in_channels: Optional[int] = None,
                 data_format: str = "NCHW", name: Optional[str] = None):
        super().__init__(name)
        self._set_conv_geometry(out_channels, kernel_size, stride, padding,
                                use_bias, in_channels, data_format)

    def init(self, key, input_shape):
        del key
        cin = self._cin(input_shape)
        self.in_channels = cin
        return self._template(
            (self.out_channels, cin, *self.kernel_size), self.out_channels)

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            raise ValueError(f"{self.name}: the PTQ graph is inference-only")
        x_q = quant_ops.quantize_symmetric(x, params["x_scale"])
        y = conv2d_int8(x_q, params["w_q"], stride=self.stride,
                        padding=self.padding, data_format=self.data_format)
        ch = 1 if self.data_format == "NCHW" else 3
        return self._dequant(y, params, x.dtype, channel_axis=ch), state


@register_layer("quant_dense")
class QuantDenseLayer(DenseGeometryMixin, _QuantizedLayer):
    """int8 GEMM layer produced by PTQ of a ``DenseLayer``. Params: ``w_q``
    int8 (out, in), ``w_scale`` f32 (out,), ``x_scale`` f32 scalar,
    optional ``b`` f32 (out,)."""

    def __init__(self, out_features: int, use_bias: bool = True,
                 in_features: Optional[int] = None,
                 name: Optional[str] = None):
        super().__init__(name)
        self._set_dense_geometry(out_features, use_bias, in_features)

    def init(self, key, input_shape):
        del key
        fan_in = self._fan_in(input_shape)
        self.in_features = fan_in
        return self._template((self.out_features, fan_in), self.out_features)

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            raise ValueError(f"{self.name}: the PTQ graph is inference-only")
        x_q = quant_ops.quantize_symmetric(x, params["x_scale"])
        y = quant_ops.dense_int8(x_q, params["w_q"])
        return self._dequant(y, params, x.dtype,
                             channel_axis=y.ndim - 1), state


@register_layer("quant_multi_head_attention")
class QuantMultiHeadAttentionLayer(MHAGeometryMixin, _QuantizedLayer):
    """int8 PTQ twin of ``MultiHeadAttentionLayer``: the four (E, E)
    projections run w8a8 on the MXU int8 path; the attention core itself
    (scores softmax · V) stays float — the projection/core FLOP ratio is
    ~2E/S, so projections dominate for S ≲ 2E (every zoo classifier), and
    the float core needs no cross-head scale algebra.

    Params: per projection p ∈ {q, k, v, o}: ``wp_q`` int8 (E_out, E_in)
    (transposed from the float layer's (in, out) storage so the shared
    ``dense_int8`` GEMM applies), ``wp_s`` f32 (E,), optional ``bp`` f32;
    plus ``x_scale`` (shared by q/k/v — same input tensor) and ``o_scale``
    (the attention-core output feeding the out projection)."""

    def __init__(self, num_heads: int, embed_dim: Optional[int] = None,
                 causal: bool = False, impl: str = "flash",
                 use_bias: bool = True, name: Optional[str] = None):
        super().__init__(name)
        self._set_mha_geometry(num_heads, embed_dim, causal, impl, use_bias)

    def init(self, key, input_shape):
        del key
        e = self._embed(input_shape)
        self.embed_dim = e
        params = {"x_scale": jnp.ones((), jnp.float32),
                  "o_scale": jnp.ones((), jnp.float32)}
        for tag in "qkvo":
            params[f"w{tag}_q"] = jnp.zeros((e, e), jnp.int8)
            params[f"w{tag}_s"] = jnp.ones((e,), jnp.float32)
            if self.use_bias:
                params[f"b{tag}"] = jnp.zeros((e,), jnp.float32)
        return params, {}

    def _proj_int8(self, params, tag, x_q, s_in, out_dtype):
        y = quant_ops.dense_int8(x_q, params[f"w{tag}_q"])
        y = y.astype(jnp.float32) * (s_in * params[f"w{tag}_s"])
        b = params.get(f"b{tag}")
        if b is not None:
            y = y + b
        return y.astype(out_dtype)

    def apply(self, params, state, x, *, training=False, rng=None):
        if training:
            raise ValueError(f"{self.name}: the PTQ graph is inference-only")
        x_q = quant_ops.quantize_symmetric(x, params["x_scale"])
        q, k, v = (self._proj_int8(params, t, x_q, params["x_scale"], x.dtype)
                   for t in "qkv")
        o = self._attend(q, k, v)
        o_q = quant_ops.quantize_symmetric(o, params["o_scale"])
        return (self._proj_int8(params, "o", o_q, params["o_scale"],
                                x.dtype), state)


def _quantize_mha(layer: MultiHeadAttentionLayer, lp, x, act_quantile):
    """Quantize one MHA layer: per-output-channel int8 projections +
    calibrated input/core scales (the core scale needs the float q/k/v and
    attention run, via the float layer's own ``_qkv``/``_attend``). Also
    returns the layer's float output so the walk advances without paying
    the O(S²) attention core a second time."""
    qp = {"x_scale": quant_ops.tensor_scale(x, quantile=act_quantile)}
    for tag in "qkvo":
        w_q, w_s = quant_ops.quantize_weight(
            jnp.asarray(lp[f"w{tag}"]).T)  # (in, out) -> (out, in)
        qp[f"w{tag}_q"], qp[f"w{tag}_s"] = w_q, w_s
        if f"b{tag}" in lp:
            qp[f"b{tag}"] = jnp.asarray(lp[f"b{tag}"], jnp.float32)
    o = layer._attend(*layer._qkv(lp, x))
    qp["o_scale"] = quant_ops.tensor_scale(o, quantile=act_quantile)
    out = layer._project(o, lp["wo"], lp.get("bo"))
    cfg = layer.get_config()
    cfg.pop("type")
    return QuantMultiHeadAttentionLayer(**cfg), qp, out


def _quantize_linear(layer, lp, x, qcls, act_quantile):
    """Build the quantized twin of one conv/dense layer from its float
    params and the calibration activation feeding it."""
    w_q, w_scale = quant_ops.quantize_weight(lp["w"])
    qp = {"w_q": w_q, "w_scale": w_scale,
          "x_scale": quant_ops.tensor_scale(x, quantile=act_quantile)}
    if "b" in lp:
        qp["b"] = jnp.asarray(lp["b"], jnp.float32)
    cfg = layer.get_config()
    cfg.pop("type")
    return qcls(**cfg), qp


def _quantize_list(layers: Sequence, params: Sequence, state: Sequence, x,
                   act_quantile) -> Tuple[List, List, List, Any]:
    """Walk one layer list: emit quantized twins for Conv2D/Dense (recording
    each one's calibrated input scale), recurse into residual blocks, copy
    everything else — while advancing the calibration activation ``x``
    through the ORIGINAL float layers (eval mode), so every scale is
    measured on exactly the tensor the quantized layer will see."""
    out_l: List[Any] = []
    out_p: List[Any] = []
    out_s: List[Any] = []
    for layer, lp, ls in zip(layers, params, state):
        advanced = None  # branch-supplied next activation (avoids re-apply)
        if isinstance(layer, Conv2DLayer):
            ql, qp = _quantize_linear(layer, lp, x, QuantConv2DLayer,
                                      act_quantile)
            out_l.append(ql)
            out_p.append(qp)
            out_s.append({})
        elif isinstance(layer, DenseLayer):
            ql, qp = _quantize_linear(layer, lp, x, QuantDenseLayer,
                                      act_quantile)
            out_l.append(ql)
            out_p.append(qp)
            out_s.append({})
        elif isinstance(layer, MultiHeadAttentionLayer):
            ql, qp, advanced = _quantize_mha(layer, lp, x, act_quantile)
            out_l.append(ql)
            out_p.append(qp)
            out_s.append({})
        elif isinstance(layer, ResidualBlock):
            ml, mp, ms, _ = _quantize_list(layer.layers, lp["main"],
                                           ls["main"], x, act_quantile)
            sl, sp, ss, _ = _quantize_list(layer.shortcut, lp["shortcut"],
                                           ls["shortcut"], x, act_quantile)
            out_l.append(ResidualBlock(ml, sl, activation=layer.activation,
                                       name=layer.name))
            out_p.append({"main": tuple(mp), "shortcut": tuple(sp)})
            out_s.append({"main": tuple(ms), "shortcut": tuple(ss)})
        else:
            try:
                out_l.append(layer_from_config(layer.get_config()))
            except ValueError:
                # pass-through custom layer outside the factory registry:
                # reuse a shallow copy rather than refusing to quantize the
                # whole model — it carries no int8 twin either way, and the
                # copy keeps the returned graph independent of the original
                out_l.append(copy.copy(layer))
            out_p.append(lp)
            out_s.append(ls)
        x = (advanced if advanced is not None
             else layer.apply(lp, ls, x, training=False)[0])
    return out_l, out_p, out_s, x


def quantize_model(model: Sequential, params, state, calib_x, *,
                   fold_bn: bool = True,
                   act_quantile: Optional[float] = None
                   ) -> Tuple[Sequential, Any, Any]:
    """Return (qmodel, qparams, qstate): the int8 PTQ twin of ``model``.

    ``calib_x`` is a representative input batch in the SAME preprocessing the
    eval path uses (decode/scale/normalize) — activation scales are absmax
    over this batch, so it should cover the data's dynamic range (a few
    hundred samples is plenty for the absmax statistic).

    ``fold_bn`` (default) first runs :func:`~dcnn_tpu.nn.fold.fold_batchnorm`
    — quantizing *folded* weights is the standard order (BN rescales per
    channel; folding first lets the per-channel weight scales absorb it).

    ``act_quantile`` (e.g. 0.9999) switches activation calibration from
    absmax to an |x| quantile — robust when the calibration batch carries
    rare outliers that would otherwise stretch every scale
    (``ops.quant.tensor_scale``).
    """
    from .fold import fold_batchnorm

    if fold_bn:
        model, params, state = fold_batchnorm(model, params, state)
    layers, qp, qs, _ = _quantize_list(model.layers, params, state, calib_x,
                                       act_quantile)
    qmodel = Sequential(layers, name=f"{model.name}_int8",
                        input_shape=model.input_shape)
    return qmodel, tuple(qp), tuple(qs)
