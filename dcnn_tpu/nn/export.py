"""StableHLO export of the inference graph — the portable deployment
artifact.

Beyond the reference (its deployment story ends at binary weight files that
only its own C++ runtime can read, ``sequential.hpp:832-915``): here the
whole inference *computation* — after ``fold_batchnorm`` and optionally
``quantize_model`` — serializes to a self-contained StableHLO artifact via
``jax.export``. The artifact embeds the weights as constants and can be
reloaded and executed by any JAX process (or any StableHLO-consuming
runtime) without the model class, the layer registry, or this package's
code: the checkpoint format ships *state*, the artifact ships the *program*.

Batch-polymorphic by default: the batch dimension exports as a symbolic
size, so one artifact serves any batch. The compile happens at load/call
time for the concrete shapes, exactly like a jitted function.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import export as jax_export

from .sequential import Sequential


def export_inference(model: Sequential, params, state, *,
                     batch_size: Optional[int] = None,
                     input_dtype: Any = jnp.float32,
                     platforms: Tuple[str, ...] = ("cpu", "tpu")) -> bytes:
    """Serialize ``model``'s eval-mode forward (weights baked in) to a
    StableHLO artifact.

    ``model`` is exported AS GIVEN — run :func:`~dcnn_tpu.nn.fold.
    fold_batchnorm` and/or :func:`~dcnn_tpu.nn.quantize.quantize_model`
    first; those transforms are deliberate deployment decisions, not
    defaults this function should hide.

    ``batch_size=None`` (default) exports a batch-polymorphic artifact
    (symbolic leading dimension); pass an int to pin it (slightly better
    XLA specialization, one shape only).

    ``platforms`` defaults to ``("cpu", "tpu")`` so the artifact actually
    honors the portability claim — ``jax.export`` otherwise pins lowering
    to the exporting process's backend and the artifact refuses to run
    anywhere else. Note the trace still happens once on the exporting
    backend, so backend-dispatched impl choices (e.g. the flash-attention
    TPU kernel vs its blockwise fallback) are baked at export time; models
    whose traced ops are TPU-only must pass ``platforms=("tpu",)``.
    """
    if model.input_shape is None:
        raise ValueError("model has no input_shape; build it through "
                         "SequentialBuilder.input or set input_shape")

    def fwd(x):
        return model.apply(params, state, x, training=False)[0]

    if batch_size is None:
        b, = jax_export.symbolic_shape("b")
    else:
        b = int(batch_size)
    spec = jax.ShapeDtypeStruct((b, *model.input_shape), input_dtype)
    # serialize() hands back a bytearray; normalize to immutable bytes
    return bytes(jax_export.export(
        jax.jit(fwd), platforms=tuple(platforms))(spec).serialize())


def load_inference(blob: bytes) -> Callable:
    """Reload a serialized artifact as a callable ``f(x) -> logits``.

    Needs only JAX — no model class, layer registry, or checkpoint; the
    weights live inside the artifact as constants. The call is wrapped in
    ``jax.jit`` so repeated same-shape calls hit the compile cache instead
    of re-tracing the deserialized computation per call — the difference
    between a serving loop and a benchmark-of-retracing (cache behavior
    asserted in ``tests/test_export.py``)."""
    return jax.jit(jax_export.deserialize(blob).call)
