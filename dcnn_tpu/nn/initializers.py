"""Parameter initializers.

Reference parity: conv and dense weights AND biases use
``Uniform(-bound, bound)`` with ``bound = 1/sqrt(fan_in)`` (the PyTorch
default Kaiming-uniform; ``conv2d_layer.tpp:71-85``,
``dense_layer.tpp``). BatchNorm/GroupNorm start at gamma=1, beta=0.
"""

from __future__ import annotations

import math
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def kaiming_uniform(key: jax.Array, shape: Sequence[int], fan_in: int,
                    dtype=jnp.float32) -> jax.Array:
    bound = 1.0 / math.sqrt(float(fan_in))
    return jax.random.uniform(key, tuple(shape), dtype=dtype, minval=-bound, maxval=bound)


def conv_fan_in(in_channels: int, kernel_hw: Tuple[int, int]) -> int:
    return in_channels * kernel_hw[0] * kernel_hw[1]


def zeros(shape, dtype=jnp.float32) -> jax.Array:
    return jnp.zeros(shape, dtype)


def ones(shape, dtype=jnp.float32) -> jax.Array:
    return jnp.ones(shape, dtype)
