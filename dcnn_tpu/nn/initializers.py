"""Parameter initializers.

Reference parity: conv and dense weights AND biases use
``Uniform(-bound, bound)`` with ``bound = 1/sqrt(fan_in)`` (the PyTorch
default Kaiming-uniform; ``conv2d_layer.tpp:71-85``,
``dense_layer.tpp``). BatchNorm/GroupNorm start at gamma=1, beta=0.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _default_dtype():
    """Param storage dtype: float64 under the fp64 precision mode (the
    reference's double-kernel path), float32 otherwise (bf16 mixed precision
    keeps fp32 master params and casts at point of use)."""
    from ..core.precision import get_precision_mode
    return jnp.float64 if get_precision_mode() == "fp64" else jnp.float32


def kaiming_uniform(key: jax.Array, shape: Sequence[int], fan_in: int,
                    dtype: Optional[jnp.dtype] = None) -> jax.Array:
    bound = 1.0 / math.sqrt(float(fan_in))
    return jax.random.uniform(key, tuple(shape), dtype=dtype or _default_dtype(),
                              minval=-bound, maxval=bound)


def conv_fan_in(in_channels: int, kernel_hw: Tuple[int, int]) -> int:
    return in_channels * kernel_hw[0] * kernel_hw[1]


def zeros(shape, dtype: Optional[jnp.dtype] = None) -> jax.Array:
    return jnp.zeros(shape, dtype or _default_dtype())


def ones(shape, dtype: Optional[jnp.dtype] = None) -> jax.Array:
    return jnp.ones(shape, dtype or _default_dtype())
