"""PyTorch ResNet-18 Tiny-ImageNet — the measured-baseline model.

Analog of the reference's ``torch/torch_tiny_imagenet_trainer.py`` model
section: an independent PyTorch definition of the exact north-star
architecture (reference ``include/nn/example_models.hpp:306-332``, mirrored
by ``dcnn_tpu/models/zoo.py:create_resnet18_tiny_imagenet``):

- 32-channel 3x3 stem, bias=False, BatchNorm eps 1e-3, ReLU, 2x2 maxpool
- 4 stages of basic residual blocks 32->64, 64->64, 64->128(s2), 128->128,
  128->256(s2), 256->256, 256->512(s2), 512->512
  (block convs bias=True, BN eps 1e-5; projection shortcut conv bias=False)
- 4x4 avgpool (stride 1), flatten, fc-200

Used by ``measure_baseline.py`` to produce the measured img/s figure that
``bench.py`` reports against (BASELINE_MEASURED.json).
"""

from __future__ import annotations

import torch
import torch.nn as nn


class BasicBlock(nn.Module):
    def __init__(self, cin: int, cout: int, stride: int = 1):
        super().__init__()
        self.conv0 = nn.Conv2d(cin, cout, 3, stride, 1, bias=True)
        self.bn0 = nn.BatchNorm2d(cout, eps=1e-5, momentum=0.1)
        self.conv1 = nn.Conv2d(cout, cout, 3, 1, 1, bias=True)
        self.bn1 = nn.BatchNorm2d(cout, eps=1e-5, momentum=0.1)
        self.relu = nn.ReLU(inplace=True)
        if stride != 1 or cin != cout:
            self.shortcut = nn.Sequential(
                nn.Conv2d(cin, cout, 1, stride, 0, bias=False),
                nn.BatchNorm2d(cout, eps=1e-5, momentum=0.1),
            )
        else:
            self.shortcut = nn.Identity()

    def forward(self, x):
        out = self.bn1(self.conv1(self.relu(self.bn0(self.conv0(x)))))
        return self.relu(out + self.shortcut(x))


class ResNet18Tiny(nn.Module):
    def __init__(self, num_classes: int = 200):
        super().__init__()
        self.stem = nn.Sequential(
            nn.Conv2d(3, 32, 3, 1, 1, bias=False),
            nn.BatchNorm2d(32, eps=1e-3, momentum=0.1),
            nn.ReLU(inplace=True),
            nn.MaxPool2d(2, 2),
        )
        self.trunk = nn.Sequential(
            BasicBlock(32, 64, 1), BasicBlock(64, 64, 1),
            BasicBlock(64, 128, 2), BasicBlock(128, 128, 1),
            BasicBlock(128, 256, 2), BasicBlock(256, 256, 1),
            BasicBlock(256, 512, 2), BasicBlock(512, 512, 1),
        )
        self.head = nn.Sequential(
            nn.AvgPool2d(4, 1),
            nn.Flatten(),
            nn.Linear(512, num_classes),
        )

    def forward(self, x):
        return self.head(self.trunk(self.stem(x)))


def make_optimizer(model: nn.Module, lr: float = 1e-3) -> torch.optim.Adam:
    """Adam with the reference's hyperparameters (beta 0.9/0.999, eps 1e-7 —
    reference ``torch/torch_tiny_imagenet_trainer.py`` TrainingConfig)."""
    return torch.optim.Adam(model.parameters(), lr=lr,
                            betas=(0.9, 0.999), eps=1e-7)
