"""Measure the PyTorch baseline train-step throughput and record it.

Analog of the reference's ``torch/`` parity scripts, but it *persists* its
numbers: runs the exact north-star config (ResNet-18, 64x64, 200 classes,
fp32, Adam, CrossEntropy) on synthetic in-memory tensors — the same
isolation ``bench.py`` uses (compute + memory only, no input pipeline) —
and writes ``BASELINE_MEASURED.json`` at the repo root, which ``bench.py``
reads to compute ``vs_baseline`` from a *measured* figure instead of an
estimate.

Run on any host:   python torch_baselines/measure_baseline.py
GPU recipe:        BASELINE_DEVICE=cuda python torch_baselines/measure_baseline.py
                   (records a ``torch_cuda`` entry; needs a CUDA build of torch)
Knobs:             BASELINE_BATCH (default 64 cpu / 256 cuda), BASELINE_STEPS
                   (default 3 cpu / 30 cuda), BASELINE_DEVICE (cpu|cuda)

Existing entries for other devices are preserved, so CPU and GPU figures can
be collected on different hosts into the same committed file.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import time

import torch

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from resnet18_tiny import ResNet18Tiny, make_optimizer  # noqa: E402

OUT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                        "BASELINE_MEASURED.json")


def measure(device: str, batch: int, steps: int) -> dict:
    torch.manual_seed(0)
    dev = torch.device(device)
    model = ResNet18Tiny().to(dev).train()
    opt = make_optimizer(model)
    loss_fn = torch.nn.CrossEntropyLoss()
    x = torch.randn(batch, 3, 64, 64, device=dev)
    y = torch.randint(0, 200, (batch,), device=dev)

    def step():
        opt.zero_grad(set_to_none=True)
        loss = loss_fn(model(x), y)
        loss.backward()
        opt.step()
        return loss

    step()  # warmup (allocator, thread-pool spin-up, cudnn autotune)
    if device.startswith("cuda"):
        torch.cuda.synchronize()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = step()
    if device.startswith("cuda"):
        torch.cuda.synchronize()
    dt = time.perf_counter() - t0

    return {
        "img_per_sec": round(batch * steps / dt, 2),
        "sec_per_step": round(dt / steps, 4),
        "batch": batch,
        "steps": steps,
        "final_loss": round(float(loss.detach()), 4),
        "torch_version": torch.__version__,
        "torch_threads": torch.get_num_threads(),
        "host": platform.node(),
        "cpu_count": os.cpu_count(),
        "device_name": (torch.cuda.get_device_name(0)
                        if device.startswith("cuda") else platform.processor() or "cpu"),
        "config": "resnet18_tiny_imagenet fp32 adam softmax-ce synthetic",
        "measured_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def main() -> None:
    device = os.environ.get("BASELINE_DEVICE", "cpu")
    is_cuda = device.startswith("cuda")
    if is_cuda and not torch.cuda.is_available():
        print("CUDA requested but unavailable", file=sys.stderr)
        sys.exit(1)
    batch = int(os.environ.get("BASELINE_BATCH", "256" if is_cuda else "64"))
    steps = int(os.environ.get("BASELINE_STEPS", "30" if is_cuda else "3"))

    result = measure(device, batch, steps)
    key = "torch_cuda" if is_cuda else "torch_cpu"

    data = {}
    if os.path.exists(OUT_PATH):
        with open(OUT_PATH) as f:
            data = json.load(f)
    data[key] = result
    with open(OUT_PATH, "w") as f:
        json.dump(data, f, indent=2)
        f.write("\n")
    print(json.dumps({key: result}))


if __name__ == "__main__":
    main()
