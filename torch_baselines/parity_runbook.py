"""Accuracy-parity runbook: dcnn_tpu vs PyTorch on real datasets.

The committed, scripted procedure VERDICT r3 item 3b asks for: the moment a
dataset is present (this build environment has zero egress; fetch on a
connected host with ``python -m dcnn_tpu.data.download --root data <name>``
and copy ``data/`` over), one command trains the SAME architecture with the
SAME optimizer/schedule in BOTH frameworks and records top-1 side by side:

    python torch_baselines/parity_runbook.py [mnist cifar10 tiny_imagenet]

Per dataset: torch model (independent definitions mirroring
``dcnn_tpu/models/zoo.py`` — themselves mirrors of the reference
``include/nn/example_models.hpp``) trains on torch's loader of the same
files; the dcnn_tpu model trains through ``examples/accuracy_gates.py``
machinery. Pass = |top1_jax - top1_torch| <= tolerance (default 1.0 pt) AND
both beat the gate floor. Results append to ``PARITY.json`` at the repo root.

Reference training semantics being reproduced: ``include/nn/train.hpp:202-308``.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
sys.path.insert(0, os.path.join(ROOT, "examples"))

TOL_PTS = float(os.environ.get("PARITY_TOL_PTS", "1.0"))


# ---------------------------------------------------------------- torch side

def _torch_mnist_model():
    import torch.nn as nn
    return nn.Sequential(                       # zoo.create_mnist_trainer
        nn.Conv2d(1, 8, 5), nn.BatchNorm2d(8, eps=1e-5), nn.ReLU(),
        nn.MaxPool2d(3, 3),
        nn.Conv2d(8, 16, 1), nn.BatchNorm2d(16, eps=1e-5), nn.ReLU(),
        nn.Conv2d(16, 48, 5), nn.BatchNorm2d(48, eps=1e-5), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        nn.Flatten(), nn.Linear(48 * 2 * 2, 10))


def _torch_resnet9():
    import torch.nn as nn

    class Block(nn.Module):                     # basic_residual_block(c, c, 1)
        def __init__(self, c):
            super().__init__()
            self.c0 = nn.Conv2d(c, c, 3, 1, 1, bias=True)
            self.b0 = nn.BatchNorm2d(c, eps=1e-5)
            self.c1 = nn.Conv2d(c, c, 3, 1, 1, bias=True)
            self.b1 = nn.BatchNorm2d(c, eps=1e-5)
            self.r = nn.ReLU()

        def forward(self, x):
            h = self.r(self.b0(self.c0(x)))
            h = self.b1(self.c1(h))
            return self.r(h + x)

    return nn.Sequential(                       # zoo.create_resnet9_cifar10
        nn.Conv2d(3, 64, 3, 1, 1), nn.BatchNorm2d(64, eps=1e-5), nn.ReLU(),
        nn.Conv2d(64, 128, 3, 1, 1), nn.BatchNorm2d(128, eps=1e-5), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        Block(128), Block(128),
        nn.Conv2d(128, 256, 3, 1, 1), nn.BatchNorm2d(256, eps=1e-5), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        Block(256), Block(256),
        nn.Conv2d(256, 512, 3, 1, 1), nn.BatchNorm2d(512, eps=1e-5), nn.ReLU(),
        nn.MaxPool2d(2, 2),
        Block(512),
        nn.AvgPool2d(4, 1),
        nn.Flatten(), nn.Linear(512, 10))


def _torch_resnet18_tiny():
    from resnet18_tiny import ResNet18Tiny  # noqa: E501 — sibling module
    return ResNet18Tiny()


def _train_torch(model, train_xy, val_xy, *, epochs, lr, batch):
    """Plain Adam + softmax-CE loop — the exact recipe the dcnn_tpu gates
    use (train.hpp:202-308 semantics)."""
    import torch
    import torch.nn as nn
    from torch.utils.data import DataLoader, TensorDataset

    dev = "cuda" if torch.cuda.is_available() else "cpu"
    model = model.to(dev)
    opt = torch.optim.Adam(model.parameters(), lr=lr)
    lossf = nn.CrossEntropyLoss()
    tl = DataLoader(TensorDataset(*train_xy), batch_size=batch, shuffle=True)
    vl = DataLoader(TensorDataset(*val_xy), batch_size=512)
    for _ in range(epochs):
        model.train()
        for xb, yb in tl:
            opt.zero_grad()
            loss = lossf(model(xb.to(dev)), yb.to(dev))
            loss.backward()
            opt.step()
    model.eval()
    hit = n = 0
    with torch.no_grad():
        for xb, yb in vl:
            hit += (model(xb.to(dev)).argmax(1).cpu() == yb).sum().item()
            n += len(yb)
    return hit / n


# ------------------------------------------------------- digits28 (offline)

def _augment_batch_np(xb, rng):
    """The dcnn_tpu digits28 gate's recipe — random_crop(pad 2, p=1.0) +
    rotation(10 deg, p=0.5) — re-implemented independently in numpy/scipy
    with the same parameters (NOT shared code with dcnn_tpu/data/augment.py;
    the point of the parity run is two independent stacks)."""
    from scipy import ndimage
    xb = xb.copy()
    n, _, h, w = xb.shape
    pad = 2
    padded = np.pad(xb, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    for i in range(n):
        oy = rng.integers(0, 2 * pad + 1)
        ox = rng.integers(0, 2 * pad + 1)
        xb[i] = padded[i, :, oy:oy + h, ox:ox + w]
        if rng.random() < 0.5:
            deg = float(rng.uniform(-10.0, 10.0))
            xb[i] = ndimage.rotate(xb[i], deg, axes=(1, 2), reshape=False,
                                   order=1, mode="nearest")
    return xb


def _train_torch_digits28(model, train_xy, val_xy, *, epochs):
    """Torch twin of the dcnn_tpu digits28 gate recipe
    (examples/accuracy_gates.py:gate_digits28): AdamW(1e-3, wd 1e-4),
    cosine annealing to 1e-5 stepped per epoch, batch 64, crop+rotate
    augmentation, best-val model selection. Returns (best_top1, history)."""
    import copy

    import torch
    import torch.nn as nn

    torch.manual_seed(0)
    model = model.train()
    opt = torch.optim.AdamW(model.parameters(), lr=1e-3, weight_decay=1e-4)
    sched = torch.optim.lr_scheduler.CosineAnnealingLR(
        opt, T_max=epochs, eta_min=1e-5)
    lossf = nn.CrossEntropyLoss()
    xtr, ytr = train_xy[0].numpy(), train_xy[1]
    xval, yval = val_xy
    rng = np.random.default_rng(0)
    history = []
    best = (-1.0, None)
    for epoch in range(1, epochs + 1):
        model.train()
        perm = rng.permutation(len(xtr))
        tot = n = hit = 0
        for s in range(0, len(perm) - 63, 64):   # drop_last, like the gate
            idx = perm[s:s + 64]
            xb = torch.from_numpy(_augment_batch_np(xtr[idx], rng))
            yb = ytr[idx]
            opt.zero_grad()
            out = model(xb)
            loss = lossf(out, yb)
            loss.backward()
            opt.step()
            tot += loss.item() * len(idx)
            hit += (out.argmax(1) == yb).sum().item()
            n += len(idx)
        model.eval()
        with torch.no_grad():
            vout = model(xval)
            vloss = lossf(vout, yval).item()
            vacc = (vout.argmax(1) == yval).float().mean().item()
        if vacc > best[0]:
            best = (vacc, copy.deepcopy(model.state_dict()))
        history.append({"epoch": epoch, "train_loss": round(tot / n, 5),
                        "train_acc": round(hit / n, 5),
                        "val_loss": round(vloss, 5),
                        "val_acc": round(vacc, 5),
                        "lr": opt.param_groups[0]["lr"]})
        sched.step()
    model.load_state_dict(best[1])
    model.eval()
    with torch.no_grad():
        top1 = (model(xval).argmax(1) == yval).float().mean().item()
    return top1, history


def run_digits28():
    """The first cross-framework end-to-end parity run that needs NO absent
    dataset (VERDICT r4 #1): bundled digits28 real images, same architecture
    (reference ``example_models.hpp:13-31`` MNIST CNN), same recipe, trained
    independently in torch and in dcnn_tpu; top-1 compared at ±0.5 pt."""
    import torch

    from dcnn_tpu.data import MNISTDataLoader

    import accuracy_gates
    d = accuracy_gates.ensure_digits28_csvs()
    paths = [os.path.join(d, f) for f in ("train.csv", "test.csv")]
    tensors = []
    for p in paths:
        ld = MNISTDataLoader(p, data_format="NCHW", batch_size=64,
                             shuffle=False)
        ld.load_data()
        y = ld._y.argmax(-1) if ld._y.ndim == 2 else ld._y
        tensors.append((torch.from_numpy(ld._x.copy()),
                        torch.from_numpy(y.astype("int64"))))

    epochs = int(os.environ.get("EPOCHS_DIGITS28", "40"))
    t0 = time.time()
    torch_top1, torch_hist = _train_torch_digits28(
        _torch_mnist_model(), tensors[0], tensors[1], epochs=epochs)
    torch_wall = time.time() - t0

    t0 = time.time()
    jax_rec = accuracy_gates.gate_digits28()
    jax_wall = time.time() - t0
    jax_top1 = jax_rec["val_acc"]
    delta = (jax_top1 - torch_top1) * 100
    tol = float(os.environ.get("PARITY_TOL_PTS", "0.5"))
    rec = {"dataset": "digits28", "epochs": epochs,
           "torch_top1": round(torch_top1, 4),
           "jax_top1": round(jax_top1, 4),
           "delta_pts": round(delta, 2),
           "parity": abs(delta) <= tol and jax_top1 >= 0.99,
           "torch_wall_s": round(torch_wall, 1),
           "jax_wall_s": round(jax_wall, 1),
           "torch_history": torch_hist,
           "jax_history": jax_rec.get("history", [])}
    print(f"[digits28] torch {torch_top1:.4f} vs jax {jax_top1:.4f} "
          f"(delta {rec['delta_pts']} pts, parity={rec['parity']})")
    return rec


def write_parity_md(rec):
    """Commit the parity evidence as PARITY.md: the top-1 table plus the two
    loss curves side by side per epoch."""
    md = ["# Cross-framework accuracy parity: dcnn_tpu vs PyTorch", "",
          "Produced by `python torch_baselines/parity_runbook.py digits28`.",
          "Same architecture (reference MNIST CNN, `example_models.hpp:13-31`),",
          "same recipe (AdamW 1e-3 / wd 1e-4 decoupled, cosine to 1e-5 per",
          "epoch, batch 64, crop±2 + rotate±10° p=0.5 augmentation, best-val",
          "selection), independently implemented in both frameworks, trained",
          "on the bundled digits28 real-image set (1438 train / 359 test).", "",
          "| dataset | epochs | torch top-1 | dcnn_tpu top-1 | delta (pts) | parity (±0.5) |",
          "|---|---|---|---|---|---|",
          f"| {rec['dataset']} | {rec['epochs']} | {rec['torch_top1']} "
          f"| {rec['jax_top1']} | {rec['delta_pts']} "
          f"| {'yes' if rec['parity'] else 'NO'} |", "",
          "## Loss curves (per epoch)", "",
          "| epoch | torch train loss | dcnn train loss | torch val loss | dcnn val loss | torch val acc | dcnn val acc |",
          "|---|---|---|---|---|---|---|"]
    jh = {h["epoch"]: h for h in rec["jax_history"]}
    for th in rec["torch_history"]:
        e = th["epoch"]
        j = jh.get(e, {})
        md.append(f"| {e} | {th['train_loss']:.4f} | "
                  f"{j.get('train_loss', float('nan')):.4f} | "
                  f"{th['val_loss']:.4f} | "
                  f"{j.get('val_loss', float('nan')):.4f} | "
                  f"{th['val_acc']:.4f} | {j.get('val_acc', float('nan')):.4f} |")
    md += ["",
           f"Wall clock: torch (CPU) {rec['torch_wall_s']}s, dcnn_tpu "
           f"{rec['jax_wall_s']}s.", ""]
    out = os.path.join(ROOT, "PARITY.md")
    with open(out, "w") as f:
        f.write("\n".join(md))
    print(f"wrote {out}")


# ---------------------------------------------------------------- datasets

def _load_mnist():
    from dcnn_tpu.data import MNISTDataLoader
    paths = [os.path.join(ROOT, "data/mnist", f) for f in
             ("train.csv", "test.csv")]
    if not all(os.path.isfile(p) for p in paths):
        return None
    import torch
    out = []
    for p in paths:
        ld = MNISTDataLoader(p, data_format="NCHW", batch_size=128,
                             shuffle=False)
        ld.load_data()
        out.append((torch.from_numpy(ld._x.copy()),
                    torch.from_numpy(ld._y.argmax(-1).astype("int64"))
                    if ld._y.ndim == 2 else
                    torch.from_numpy(ld._y.astype("int64"))))
    return out


def _load_cifar10():
    from dcnn_tpu.data import CIFAR10DataLoader
    root = os.path.join(ROOT, "data/cifar-10-batches-bin")
    if not os.path.isdir(root):
        return None
    import torch
    train = CIFAR10DataLoader(
        [f"{root}/data_batch_{i}.bin" for i in range(1, 6)],
        batch_size=128, shuffle=False)
    val = CIFAR10DataLoader(f"{root}/test_batch.bin", batch_size=512,
                            shuffle=False)
    train.load_data(); val.load_data()

    def t(ld):
        y = ld._y.argmax(-1) if ld._y.ndim == 2 else ld._y
        return (torch.from_numpy(ld._x.copy()),
                torch.from_numpy(y.astype("int64")))
    return [t(train), t(val)]


def _load_tiny():
    from dcnn_tpu.data import TinyImageNetDataLoader
    root = os.path.join(ROOT, "data/tiny-imagenet-200")
    if not os.path.isdir(root):
        return None
    import torch
    train = TinyImageNetDataLoader(root, split="train", batch_size=128,
                                   shuffle=False, data_format="NCHW")
    val = TinyImageNetDataLoader(root, split="val", batch_size=512,
                                 shuffle=False, data_format="NCHW")
    train.load_data(); val.load_data()

    def t(ld):
        y = ld._y.argmax(-1) if ld._y.ndim == 2 else ld._y
        return (torch.from_numpy(ld._x.copy()),
                torch.from_numpy(y.astype("int64")))
    return [t(train), t(val)]


# ---------------------------------------------------------------- gates

GATES = {
    # name: (loader, torch model, jax gate fn name in accuracy_gates,
    #        epochs env, default epochs, lr, floor)
    "mnist": (_load_mnist, _torch_mnist_model, "gate_mnist",
              "EPOCHS_MNIST", 12, 1e-3, 0.99),
    "cifar10": (_load_cifar10, _torch_resnet9, "gate_cifar10",
                "EPOCHS_CIFAR10", 20, 1e-3, 0.0),
    "tiny_imagenet": (_load_tiny, _torch_resnet18_tiny, "gate_tiny_imagenet",
                      "EPOCHS_TINY", 30, 1e-3, 0.0),
}


def main():
    names = sys.argv[1:] or ["digits28"] + list(GATES)
    records = []
    for name in names:
        if name == "digits28":
            rec = run_digits28()
            write_parity_md(rec)
            records.append({k: v for k, v in rec.items()
                            if not k.endswith("_history")})
            continue
        load, torch_model, jax_gate, eenv, edef, lr, floor = GATES[name]
        data = load()
        if data is None:
            records.append({"dataset": name, "skipped":
                            "dataset absent; fetch with: python -m "
                            f"dcnn_tpu.data.download --root data {name}"})
            print(f"[{name}] SKIPPED (dataset absent)")
            continue
        epochs = int(os.environ.get(eenv, str(edef)))
        t0 = time.time()
        torch_top1 = _train_torch(torch_model(), data[0], data[1],
                                  epochs=epochs, lr=lr, batch=128)
        torch_wall = time.time() - t0

        import accuracy_gates
        gate_fn = getattr(accuracy_gates, jax_gate, None)
        if gate_fn is None:
            records.append({"dataset": name,
                            "skipped": f"no jax gate {jax_gate}"})
            continue
        t0 = time.time()
        jax_rec = gate_fn()
        jax_wall = time.time() - t0
        jax_top1 = jax_rec.get("val_acc")
        delta = (jax_top1 - torch_top1) * 100 if jax_top1 is not None else None
        rec = {"dataset": name, "epochs": epochs,
               "torch_top1": round(torch_top1, 4),
               "jax_top1": (round(jax_top1, 4)
                            if jax_top1 is not None else None),
               "delta_pts": round(delta, 2) if delta is not None else None,
               "parity": (delta is not None and abs(delta) <= TOL_PTS
                          and (jax_top1 or 0) >= floor),
               "torch_wall_s": round(torch_wall, 1),
               "jax_wall_s": round(jax_wall, 1)}
        records.append(rec)
        print(f"[{name}] torch {torch_top1:.4f} vs jax {jax_top1} "
              f"(delta {rec['delta_pts']} pts, parity={rec['parity']})")

    out = os.path.join(ROOT, "PARITY.json")
    existing = []
    if os.path.exists(out):
        with open(out) as f:
            existing = json.load(f)
    existing.extend(records)
    with open(out, "w") as f:
        json.dump(existing, f, indent=1)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
