"""Generate torch-computed golden fixtures for per-layer fwd/bwd parity.

VERDICT r4 directive 1: the layer-value tests were hand-computed only; this
script adds an INDEPENDENT oracle. For each layer type (conv / batchnorm /
maxpool / avgpool / dense) it runs a small fixed-seed case through PyTorch,
records input, params, output, and the backward grads (dx and param grads
under a fixed upstream cotangent), and writes everything to
``tests/fixtures/torch_golden.npz``. ``tests/test_layer_values.py`` replays
the same cases through dcnn_tpu layers and compares.

The fixture file is committed, so the tests run everywhere; re-run this
script only to regenerate (requires torch):

    python torch_baselines/make_golden_fixtures.py

Reference analog: the gtest fixtures in
``unit_tests/conv2d_layer_test.cpp`` compare against precomputed values; here
the precomputation is torch instead of by hand.
"""

from __future__ import annotations

import os

import numpy as np
import torch

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "tests", "fixtures", "torch_golden.npz")

torch.manual_seed(0)
g = {}


def _rand(*shape):
    return torch.randn(*shape, dtype=torch.float32)


def _record(prefix, **arrs):
    for k, v in arrs.items():
        g[f"{prefix}.{k}"] = (v.detach().numpy() if torch.is_tensor(v)
                              else np.asarray(v))


# ---- conv2d: 2 samples, 3->8 ch, 5x5 kernel, stride 2, pad 1, bias ----
x = _rand(2, 3, 12, 12).requires_grad_(True)
conv = torch.nn.Conv2d(3, 8, 5, stride=2, padding=1, bias=True)
y = conv(x)
dy = _rand(*y.shape)
y.backward(dy)
_record("conv", x=x, w=conv.weight, b=conv.bias, dy=dy, y=y,
        dx=x.grad, dw=conv.weight.grad, db=conv.bias.grad)

# ---- batchnorm (training): 4 samples, 6 ch, 5x5; nonzero running stats ----
x = _rand(4, 6, 5, 5).requires_grad_(True)
bn = torch.nn.BatchNorm2d(6, eps=1e-5, momentum=0.1)
with torch.no_grad():
    bn.weight.copy_(_rand(6) * 0.5 + 1.0)
    bn.bias.copy_(_rand(6) * 0.1)
    bn.running_mean.copy_(_rand(6) * 0.2)
    bn.running_var.copy_(torch.rand(6) + 0.5)
rm0, rv0 = bn.running_mean.clone(), bn.running_var.clone()
bn.train()
y = bn(x)
dy = _rand(*y.shape)
y.backward(dy)
_record("bn", x=x, gamma=bn.weight, beta=bn.bias,
        running_mean0=rm0, running_var0=rv0, dy=dy, y=y,
        dx=x.grad, dgamma=bn.weight.grad, dbeta=bn.bias.grad,
        running_mean1=bn.running_mean, running_var1=bn.running_var)

# ---- maxpool: 3x3 kernel stride 2 (overlapping windows) ----
x = _rand(2, 4, 9, 9).requires_grad_(True)
y = torch.nn.functional.max_pool2d(x, 3, stride=2)
dy = _rand(*y.shape)
y.backward(dy)
_record("maxpool", x=x, dy=dy, y=y, dx=x.grad)

# ---- avgpool: 2x2 stride 2 pad 1, count_include_pad=True (the reference
#      semantics dcnn_tpu implements, avgpool2d_layer.tpp) ----
x = _rand(2, 4, 6, 6).requires_grad_(True)
y = torch.nn.functional.avg_pool2d(x, 2, stride=2, padding=1,
                                   count_include_pad=True)
dy = _rand(*y.shape)
y.backward(dy)
_record("avgpool", x=x, dy=dy, y=y, dx=x.grad)

# ---- dense: 3 samples, 7 -> 5 features ----
x = _rand(3, 7).requires_grad_(True)
fc = torch.nn.Linear(7, 5, bias=True)
y = fc(x)
dy = _rand(*y.shape)
y.backward(dy)
_record("dense", x=x, w=fc.weight, b=fc.bias, dy=dy, y=y,
        dx=x.grad, dw=fc.weight.grad, db=fc.bias.grad)

os.makedirs(os.path.dirname(OUT), exist_ok=True)
np.savez_compressed(OUT, **g)
print(f"wrote {OUT}: {len(g)} arrays, "
      f"{os.path.getsize(OUT) / 1024:.1f} KiB")
