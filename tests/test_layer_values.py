"""Hand-computed layer value fixtures + quality/path tests (VERDICT r1 #9;
reference fixture style ``unit_tests/conv2d_layer_test.cpp:23-60``:
analytically known inputs/weights -> exact expected outputs, not just
oracle-vs-oracle comparisons)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.nn.layers import (
    AvgPool2DLayer, BatchNormLayer, Conv2DLayer, DenseLayer, MaxPool2DLayer,
)

KEY = jax.random.PRNGKey(0)


def test_conv2d_hand_computed_values():
    """3x3 input, one 2x2 filter [[1,2],[3,4]], stride 1, no pad.
    out[i,j] = 1*x[i,j] + 2*x[i,j+1] + 3*x[i+1,j] + 4*x[i+1,j+1]."""
    layer = Conv2DLayer(1, 2, stride=1, padding=0, use_bias=True, in_channels=1)
    params, state = layer.init(KEY, (1, 3, 3))
    x = jnp.asarray(np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3))
    w = jnp.asarray([[[[1.0, 2.0], [3.0, 4.0]]]])   # OIHW
    params = dict(params, w=w, b=jnp.asarray([0.5]))
    y, _ = layer.apply(params, state, x)
    # x = [[0,1,2],[3,4,5],[6,7,8]]
    # out[0,0] = 0+2*1+3*3+4*4 = 27; +bias
    want = np.array([[[[27.5, 37.5], [57.5, 67.5]]]], np.float32)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)


def test_conv2d_hand_computed_stride_padding():
    """Same filter, pad 1 stride 2 on a 2x2 input: corners see one x value."""
    layer = Conv2DLayer(1, 2, stride=2, padding=1, use_bias=False, in_channels=1)
    params, state = layer.init(KEY, (1, 2, 2))
    x = jnp.asarray([[[[1.0, 2.0], [3.0, 4.0]]]])
    params = dict(params, w=jnp.asarray([[[[1.0, 2.0], [3.0, 4.0]]]]))
    y, _ = layer.apply(params, state, x)
    # padded x = [[0,0,0,0],[0,1,2,0],[0,3,4,0],[0,0,0,0]], windows at
    # (0,0),(0,2),(2,0),(2,2): sums 4*1, 3*2, 2*3, 1*4
    want = np.array([[[[4.0, 6.0], [6.0, 4.0]]]], np.float32)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-5)


def test_dense_hand_computed():
    layer = DenseLayer(2, use_bias=True, in_features=3)
    params, state = layer.init(KEY, (3,))
    params = dict(params,
                  w=jnp.asarray([[1.0, 0.0, -1.0], [2.0, 1.0, 0.0]]),  # (out,in)
                  b=jnp.asarray([0.5, -0.5]))
    y, _ = layer.apply(params, state, jnp.asarray([[1.0, 2.0, 3.0]]))
    np.testing.assert_allclose(np.asarray(y), [[1 - 3 + 0.5, 2 + 2 - 0.5]],
                               atol=1e-6)


def test_maxpool_values_and_backward_scatter():
    layer = MaxPool2DLayer(2, 2, 0)
    params, state = layer.init(KEY, (1, 4, 4))
    x = jnp.asarray(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    y, _ = layer.apply(params, state, x)
    np.testing.assert_array_equal(np.asarray(y).reshape(2, 2),
                                  [[5.0, 7.0], [13.0, 15.0]])
    # backward: gradient lands only on the argmax positions (reference
    # argmax-cache scatter, maxpool_ops.cpp — here the reduce_window
    # transpose rule)
    g = jax.grad(lambda xx: layer.apply(params, state, xx)[0].sum())(x)
    want = np.zeros((4, 4), np.float32)
    want[1, 1] = want[1, 3] = want[3, 1] = want[3, 3] = 1.0
    np.testing.assert_array_equal(np.asarray(g).reshape(4, 4), want)


def test_avgpool_count_include_pad():
    """Padded window divides by the FULL kernel area (reference
    ``count_include_pad=True`` semantics, avgpool2d_layer.tpp)."""
    layer = AvgPool2DLayer(2, 2, 1)
    params, state = layer.init(KEY, (1, 2, 2))
    x = jnp.asarray([[[[4.0, 8.0], [12.0, 16.0]]]])
    y, _ = layer.apply(params, state, x)
    # padded to 4x4, windows: [0,0;0,4]/4=1, [0,0;8,0]/4=2, ...
    want = np.array([[[[1.0, 2.0], [3.0, 4.0]]]], np.float32)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-6)


def test_batchnorm_hand_computed_stats():
    layer = BatchNormLayer(num_features=1, epsilon=0.0, momentum=0.1)
    params, state = layer.init(KEY, (1, 1, 2))
    x = jnp.asarray([1.0, 3.0, 5.0, 7.0], jnp.float32).reshape(2, 1, 1, 2)
    params = dict(params, gamma=jnp.asarray([2.0]), beta=jnp.asarray([1.0]))
    y, new_state = layer.apply(params, state, x, training=True)
    # batch mean 4, var 5 -> normalized (x-4)/sqrt(5); y = 2*norm + 1
    want = 2.0 * (np.array([1, 3, 5, 7], np.float32) - 4.0) / np.sqrt(5.0) + 1.0
    np.testing.assert_allclose(np.asarray(y).ravel(), want, rtol=1e-5)
    # running stats: (1-m)*old + m*batch with unbiased var 5*4/3
    np.testing.assert_allclose(float(new_state["running_mean"][0]), 0.4, rtol=1e-5)
    np.testing.assert_allclose(float(new_state["running_var"][0]),
                               0.9 * 1.0 + 0.1 * (5.0 * 4 / 3), rtol=1e-5)


def test_flop_balanced_partitioner_quality():
    """FlopBalanced must actually balance: its worst-stage FLOP share on
    ResNet-18 (stem-heavy) must beat the naive even-count split."""
    from dcnn_tpu.models import create_resnet18_tiny_imagenet
    from dcnn_tpu.parallel import FlopBalancedPartitioner, NaivePartitioner

    model = create_resnet18_tiny_imagenet()
    shapes = model.layer_shapes()
    costs = np.array([
        l.forward_complexity(s) + l.backward_complexity(s)
        for l, s in zip(model.layers, shapes)], np.float64)

    def worst_share(parts):
        sums = np.array([costs[a:b].sum() for a, b in parts])
        return sums.max() / costs.sum()

    for n in (2, 4):
        naive = worst_share(NaivePartitioner().get_partitions(model, n))
        bal = worst_share(FlopBalancedPartitioner().get_partitions(model, n))
        assert bal <= naive + 1e-9, (n, bal, naive)
        # and it must be reasonably close to the ideal 1/n
        assert bal < 1.6 / n, (n, bal)


def test_layer_profiler_paths():
    from dcnn_tpu.core.config import ProfilerType
    from dcnn_tpu.models import create_mnist_trainer
    from dcnn_tpu.train.profiling import LayerProfiler

    model = create_mnist_trainer()
    params, state = model.init(KEY)
    x = jnp.zeros((2, 1, 28, 28), jnp.float32)
    prof = LayerProfiler(ProfilerType.CUMULATIVE)
    logits, _ = prof.profile_forward(model, params, state, x,
                                     training=True, rng=KEY)
    assert logits.shape == (2, 10)
    grad = jnp.ones_like(logits)
    prof.profile_backward(model, params, state, x, grad, rng=KEY)
    text = prof.summary()
    assert "conv1" in text and "output" in text
    assert sum(prof.forward_us.values()) > 0
    assert sum(prof.backward_us.values()) > 0


def test_trainer_per_batch_scheduler_stepping():
    """scheduler_step='batch' steps OneCycleLR once per batch so its
    total_steps budget (epochs * batches_per_epoch) is actually consumed
    (VERDICT r1 weak #8: OneCycle is designed around per-batch cadence)."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ArrayDataLoader
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import SGD, OneCycleLR
    from dcnn_tpu.train import Trainer
    from dcnn_tpu.train.trainer import create_train_state

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 1, 8, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]
    ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False)   # 4 batches
    ld.load_data()
    model = (SequentialBuilder("sched_model").input((1, 8, 8))
             .flatten().dense(4).build())

    epochs, batches = 2, 4
    sched = OneCycleLR(max_lr=0.4, total_steps=epochs * batches, pct_start=0.5)
    opt = SGD(sched.lr)
    tr = Trainer(model, opt, "softmax_crossentropy", scheduler=sched,
                 config=TrainingConfig(epochs=epochs, progress_interval=0,
                                       snapshot_dir=None,
                                       scheduler_step="batch"))
    ts = create_train_state(model, opt, KEY)
    tr.fit(ts, ld)
    # all 8 steps consumed: scheduler at the end of its cycle, lr back down
    assert sched.current_step == epochs * batches
    assert tr.lr < 0.4 / 2
    # and the peak (max_lr) was reached mid-cycle: step 4 of 8 with
    # pct_start=0.5 is the top of the triangle
    probe = OneCycleLR(max_lr=0.4, total_steps=8, pct_start=0.5)
    lrs = [probe.step(None) for _ in range(8)]
    np.testing.assert_allclose(max(lrs), 0.4, rtol=1e-6)


def test_trainer_chunked_dispatch_matches_per_batch():
    """steps_per_dispatch=K (PrefetchLoader chunks + make_multi_step) must
    train identically to the per-batch path (same updates, same epoch loss)."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ArrayDataLoader, PrefetchLoader
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.train import Trainer
    from dcnn_tpu.train.trainer import create_train_state

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 1, 8, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 32)]

    def mk_model():
        return (SequentialBuilder("chunk_model").input((1, 8, 8))
                .conv2d(2, 3, 1, 1).activation("relu").flatten().dense(4)
                .build())

    def mk_loader():
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False)
        ld.load_data()
        return ld

    from dcnn_tpu.optim import OneCycleLR

    results = {}
    # per-batch OneCycleLR: the chunked path must ship a [K] lr vector so
    # per-batch schedules stay EXACT under chunked dispatch
    for mode, spd in (("batch", 1), ("chunked", 4)):
        model = mk_model()
        sched = OneCycleLR(max_lr=0.1, total_steps=8, pct_start=0.5)
        opt = SGD(sched.lr)
        tr = Trainer(model, opt, "softmax_crossentropy", scheduler=sched,
                     config=TrainingConfig(epochs=2, progress_interval=0,
                                           snapshot_dir=None,
                                           scheduler_step="batch",
                                           steps_per_dispatch=spd))
        ts = create_train_state(model, opt, KEY)
        loader = (mk_loader() if spd == 1
                  else PrefetchLoader(mk_loader(), stage_batches=spd))
        ts = tr.fit(ts, loader)
        results[mode] = (ts, [h["train_loss"] for h in tr.history], tr.lr)

    for a, b in zip(jax.tree_util.tree_leaves(results["batch"][0].params),
                    jax.tree_util.tree_leaves(results["chunked"][0].params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(results["batch"][1], results["chunked"][1],
                               rtol=1e-5)
    np.testing.assert_allclose(results["batch"][2], results["chunked"][2],
                               rtol=1e-9)


def test_trainer_chunked_rejects_unchunked_loader():
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ArrayDataLoader
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.train import Trainer
    from dcnn_tpu.train.trainer import create_train_state

    model = (SequentialBuilder("c").input((1, 8, 8))
             .flatten().dense(4).build())
    opt = SGD(0.05)
    tr = Trainer(model, opt, "softmax_crossentropy",
                 config=TrainingConfig(epochs=1, progress_interval=0,
                                       snapshot_dir=None,
                                       steps_per_dispatch=4))
    ld = ArrayDataLoader(np.zeros((16, 1, 8, 8), np.float32),
                         np.eye(4, dtype=np.float32)[np.zeros(16, int)],
                         batch_size=8, shuffle=False)
    ld.load_data()
    ts = create_train_state(model, opt, KEY)
    with pytest.raises(ValueError, match="PrefetchLoader"):
        tr.fit(ts, ld)


def test_trainer_fit_best_val_snapshot(tmp_path):
    """Trainer.fit writes the best-val snapshot (reference train.hpp:254-264)
    and the checkpoint round-trips through the factory."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ArrayDataLoader
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.train import Trainer, load_checkpoint
    from dcnn_tpu.train.trainer import create_train_state

    rng = np.random.default_rng(0)
    n = 64
    y_idx = rng.integers(0, 4, n)
    x = rng.normal(0, 0.1, (n, 1, 8, 8)).astype(np.float32)
    x[np.arange(n), 0, y_idx, y_idx] += 3.0
    y = np.eye(4, dtype=np.float32)[y_idx]
    ld = ArrayDataLoader(x, y, batch_size=16, shuffle=False)
    ld.load_data()

    model = (SequentialBuilder("snap_model").input((1, 8, 8))
             .conv2d(4, 3, 1, 1).activation("relu").flatten().dense(4).build())
    opt = Adam(1e-2)
    tr = Trainer(model, opt, "softmax_crossentropy",
                 config=TrainingConfig(epochs=2, progress_interval=0,
                                       snapshot_dir=str(tmp_path)))
    ts = create_train_state(model, opt, KEY)
    tr.fit(ts, ld, val_loader=ld)

    path = os.path.join(str(tmp_path), "snap_model")
    assert os.path.isdir(path)
    m2, p2, s2, opt_state2, opt2, meta = load_checkpoint(path)
    assert meta["epoch"] >= 1 and 0.0 <= meta["val_acc"] <= 1.0
    assert m2.get_config() == model.get_config()
    assert opt_state2 is not None and int(opt_state2["t"]) > 0
    # snapshot corresponds to the best val epoch recorded in history
    best = max(h["val_acc"] for h in tr.history)
    np.testing.assert_allclose(meta["val_acc"], best, atol=1e-9)


# ---- hand-computed fixtures for the remaining layer types (VERDICT r3
#      next-round #3c: per-layer numerics parity airtight without datasets) --

def test_groupnorm_hand_computed():
    """2 groups over 4 channels: each group normalizes over its own
    channels x spatial; affine applies per channel."""
    from dcnn_tpu.nn.layers import GroupNormLayer

    layer = GroupNormLayer(num_groups=2, epsilon=0.0)
    params, state = layer.init(KEY, (4, 1, 1))
    # one sample, 4 channels, 1x1 spatial: groups {1,3} and {5,9}
    x = jnp.asarray([1.0, 3.0, 5.0, 9.0], jnp.float32).reshape(1, 4, 1, 1)
    params = dict(params, gamma=jnp.asarray([1.0, 1.0, 2.0, 2.0]),
                  beta=jnp.asarray([0.0, 0.0, 1.0, 1.0]))
    y, _ = layer.apply(params, state, x)
    # group0: mean 2 var 1 -> [-1, 1]; group1: mean 7 var 4 -> [-1, 1]
    want = [-1.0, 1.0, 2.0 * -1.0 + 1.0, 2.0 * 1.0 + 1.0]
    np.testing.assert_allclose(np.asarray(y).ravel(), want, atol=1e-5)


def test_flatten_hand_computed():
    from dcnn_tpu.nn.layers import FlattenLayer

    layer = FlattenLayer()
    x = jnp.asarray(np.arange(12, dtype=np.float32).reshape(2, 3, 2))
    y, _ = layer.apply({}, {}, x)
    np.testing.assert_array_equal(np.asarray(y),
                                  np.arange(12, dtype=np.float32).reshape(2, 6))


def test_activation_layers_hand_computed():
    from dcnn_tpu.nn.layers import ActivationLayer

    x = jnp.asarray([[-2.0, 0.0, 3.0]])
    cases = {
        "relu": [0.0, 0.0, 3.0],
        "leaky_relu": [-2.0 * 0.01, 0.0, 3.0],
        "sigmoid": 1 / (1 + np.exp([2.0, 0.0, -3.0])),
        "tanh": np.tanh([-2.0, 0.0, 3.0]),
        "elu": [np.expm1(-2.0), 0.0, 3.0],
    }
    for name, want in cases.items():
        y, _ = ActivationLayer(name).apply({}, {}, x)
        np.testing.assert_allclose(np.asarray(y).ravel(), want, atol=1e-6,
                                   err_msg=name)
    # softmax: hand-computed over the row
    e = np.exp(np.array([-2.0, 0.0, 3.0]) - 3.0)
    y, _ = ActivationLayer("softmax").apply({}, {}, x)
    np.testing.assert_allclose(np.asarray(y).ravel(), e / e.sum(), atol=1e-6)


def test_log_softmax_hand_computed():
    from dcnn_tpu.nn.layers import LogSoftmaxLayer

    x = jnp.asarray([[1.0, 2.0, 3.0]])
    y, _ = LogSoftmaxLayer().apply({}, {}, x)
    lse = np.log(np.exp([1.0, 2.0, 3.0]).sum())
    np.testing.assert_allclose(np.asarray(y).ravel(),
                               np.array([1.0, 2.0, 3.0]) - lse, atol=1e-6)


def test_dropout_exact_mask_semantics():
    """Inverted dropout: kept entries are EXACTLY x/keep, dropped are 0,
    eval mode is the identity, and the same key reproduces the same mask
    (reference dropout_layer.tpp seeded-mask semantics)."""
    from dcnn_tpu.nn.layers import DropoutLayer

    layer = DropoutLayer(0.4)
    x = jnp.asarray(np.linspace(1, 24, 24, dtype=np.float32).reshape(2, 12))
    key = jax.random.PRNGKey(5)
    y = np.asarray(layer.forward(x, training=True, rng=key))
    xn = np.asarray(x)
    kept = y != 0
    np.testing.assert_allclose(y[kept], xn[kept] / 0.6, rtol=1e-6)
    assert 0 < kept.sum() < x.size  # mask is non-trivial at p=0.4, n=24
    # deterministic per key; identity in eval; error without key
    np.testing.assert_array_equal(
        y, np.asarray(layer.forward(x, training=True, rng=key)))
    np.testing.assert_array_equal(np.asarray(layer.forward(x)), xn)
    with np.testing.assert_raises(ValueError):
        layer.forward(x, training=True)


def test_multihead_attention_hand_computed():
    """2 tokens, 1 head, identity projections, no bias: the layer must equal
    softmax(q k^T / sqrt(d)) v computed by hand in numpy."""
    from dcnn_tpu.nn.attention_layer import MultiHeadAttentionLayer

    e = 2
    x = np.asarray([[[1.0, 0.0], [0.0, 2.0]]], np.float32)     # (1, 2, 2)
    eye = jnp.eye(e, dtype=jnp.float32)
    for impl in ("naive", "blockwise", "flash"):
        layer = MultiHeadAttentionLayer(num_heads=1, impl=impl, use_bias=False)
        params, state = layer.init(KEY, (2, e))
        params = {"wq": eye, "wk": eye, "wv": eye, "wo": eye}
        y, _ = layer.apply(params, state, jnp.asarray(x))
        scores = x[0] @ x[0].T / np.sqrt(e)
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        np.testing.assert_allclose(np.asarray(y)[0], p @ x[0], atol=1e-4,
                                   err_msg=impl)


def test_residual_block_hand_computed():
    """Main path = one 1x1 conv (x2 weight), empty shortcut: out =
    relu(2x + x) = relu(3x)."""
    from dcnn_tpu.nn.residual import ResidualBlock

    conv = Conv2DLayer(1, 1, stride=1, padding=0, use_bias=False, in_channels=1)
    block = ResidualBlock([conv], activation="relu")
    params, state = block.init(KEY, (1, 2, 2))
    params = {"main": (dict(params["main"][0],
                            w=jnp.asarray([[[[2.0]]]])),),
              "shortcut": ()}
    x = jnp.asarray([[[[1.0, -1.0], [0.5, -2.0]]]])
    y, _ = block.apply(params, state, x)
    want = np.maximum(3.0 * np.asarray(x), 0.0)
    np.testing.assert_allclose(np.asarray(y), want, atol=1e-6)


# ---- torch-generated golden fixtures (VERDICT r4 #1: independent oracle
#      for conv/BN/pool/dense fwd AND bwd, beyond the hand-computed cases).
#      Regenerate with: python torch_baselines/make_golden_fixtures.py ----

_GOLDEN = os.path.join(os.path.dirname(__file__), "fixtures",
                       "torch_golden.npz")


@pytest.fixture(scope="module")
def golden():
    assert os.path.isfile(_GOLDEN), (
        "committed fixture missing; regenerate with "
        "python torch_baselines/make_golden_fixtures.py")
    return np.load(_GOLDEN)


def _vjp_against(layer, params, state, g, prefix, training=False):
    """Forward + VJP of ``sum(y * dy)`` — the same cotangent the torch side
    used — returning (y, dx, param_grads)."""
    x = jnp.asarray(g[f"{prefix}.x"])
    dy = jnp.asarray(g[f"{prefix}.dy"])

    def fwd(p, xx):
        y, _ = layer.apply(p, state, xx, training=training)
        return y
    y, vjp = jax.vjp(fwd, params, x)
    dparams, dx = vjp(dy)
    return y, dx, dparams


def test_conv2d_matches_torch_golden(golden):
    layer = Conv2DLayer(8, 5, stride=2, padding=1, use_bias=True,
                        in_channels=3)
    params, state = layer.init(KEY, (3, 12, 12))
    params = dict(params, w=jnp.asarray(golden["conv.w"]),
                  b=jnp.asarray(golden["conv.b"]))
    y, dx, dp = _vjp_against(layer, params, state, golden, "conv")
    np.testing.assert_allclose(np.asarray(y), golden["conv.y"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), golden["conv.dx"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["w"]), golden["conv.dw"],
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dp["b"]), golden["conv.db"],
                               rtol=1e-4, atol=1e-4)


def test_batchnorm_matches_torch_golden(golden):
    layer = BatchNormLayer(num_features=6, epsilon=1e-5, momentum=0.1)
    params, state = layer.init(KEY, (6, 5, 5))
    params = dict(params, gamma=jnp.asarray(golden["bn.gamma"]),
                  beta=jnp.asarray(golden["bn.beta"]))
    state = dict(state,
                 running_mean=jnp.asarray(golden["bn.running_mean0"]),
                 running_var=jnp.asarray(golden["bn.running_var0"]))
    y, dx, dp = _vjp_against(layer, params, state, golden, "bn",
                             training=True)
    np.testing.assert_allclose(np.asarray(y), golden["bn.y"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), golden["bn.dx"],
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dp["gamma"]), golden["bn.dgamma"],
                               rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(dp["beta"]), golden["bn.dbeta"],
                               rtol=1e-3, atol=1e-4)
    # running-stat update rule matches torch (momentum semantics + unbiased
    # batch variance into the running buffer)
    _, new_state = layer.apply(params, state, jnp.asarray(golden["bn.x"]),
                               training=True)
    np.testing.assert_allclose(np.asarray(new_state["running_mean"]),
                               golden["bn.running_mean1"], rtol=1e-4,
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_state["running_var"]),
                               golden["bn.running_var1"], rtol=1e-4,
                               atol=1e-5)


def test_maxpool_matches_torch_golden(golden):
    layer = MaxPool2DLayer(3, 2, 0)
    params, state = layer.init(KEY, (4, 9, 9))
    y, dx, _ = _vjp_against(layer, params, state, golden, "maxpool")
    np.testing.assert_allclose(np.asarray(y), golden["maxpool.y"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), golden["maxpool.dx"],
                               rtol=1e-5, atol=1e-6)


def test_avgpool_matches_torch_golden(golden):
    layer = AvgPool2DLayer(2, 2, 1)
    params, state = layer.init(KEY, (4, 6, 6))
    y, dx, _ = _vjp_against(layer, params, state, golden, "avgpool")
    np.testing.assert_allclose(np.asarray(y), golden["avgpool.y"],
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dx), golden["avgpool.dx"],
                               rtol=1e-5, atol=1e-6)


def test_dense_matches_torch_golden(golden):
    layer = DenseLayer(5, use_bias=True, in_features=7)
    params, state = layer.init(KEY, (7,))
    params = dict(params, w=jnp.asarray(golden["dense.w"]),
                  b=jnp.asarray(golden["dense.b"]))
    y, dx, dp = _vjp_against(layer, params, state, golden, "dense")
    np.testing.assert_allclose(np.asarray(y), golden["dense.y"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dx), golden["dense.dx"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["w"]), golden["dense.dw"],
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(dp["b"]), golden["dense.db"],
                               rtol=1e-4, atol=1e-5)


def test_residual_block_projection_shortcut_hand_computed():
    """Projection shortcut: out = relu(conv_main(x) + conv_short(x)) with
    1x1 convs x3 and x(-1): relu(3x - x) = relu(2x)."""
    from dcnn_tpu.nn.residual import ResidualBlock

    main = Conv2DLayer(1, 1, stride=1, padding=0, use_bias=False, in_channels=1)
    short = Conv2DLayer(1, 1, stride=1, padding=0, use_bias=False, in_channels=1)
    block = ResidualBlock([main], shortcut=[short], activation="relu")
    params, state = block.init(KEY, (1, 2, 2))
    params = {"main": (dict(params["main"][0], w=jnp.asarray([[[[3.0]]]])),),
              "shortcut": (dict(params["shortcut"][0],
                                w=jnp.asarray([[[[-1.0]]]])),)}
    x = jnp.asarray([[[[1.0, -4.0], [0.25, 2.0]]]])
    y, _ = block.apply(params, state, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.maximum(2.0 * np.asarray(x), 0.0), atol=1e-6)
