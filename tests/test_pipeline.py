"""Pipeline-parallelism tests over the 8-virtual-device CPU mesh.

Reference analog: the in-process coordinator/communicator machinery used as
the no-network test backend (``in_process_coordinator.hpp:23-60``) and the
microbatch-ID stress test (``examples/microbatching_test.cpp``)
(SURVEY.md §4.7).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.models import create_mnist_trainer
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.parallel import (
    FlopBalancedPartitioner, InProcessPipelineCoordinator, NaivePartitioner,
)
from dcnn_tpu.parallel.pipeline import split_microbatches
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import create_train_state

KEY = jax.random.PRNGKey(0)


def _model():
    return (SequentialBuilder("pipe_model")
            .input((1, 8, 8))
            .conv2d(4, 3, 1, 1).activation("relu")
            .conv2d(8, 3, 2, 1).activation("relu")
            .flatten()
            .dense(16).activation("relu")
            .dense(10)
            .build())


def test_naive_partitioner_even_split():
    model = create_mnist_trainer()
    parts = NaivePartitioner().get_partitions(model, 3)
    assert parts[0][0] == 0 and parts[-1][1] == len(model)
    sizes = [e - s for s, e in parts]
    assert max(sizes) - min(sizes) <= 1
    # contiguous, non-overlapping
    for (s1, e1), (s2, e2) in zip(parts, parts[1:]):
        assert e1 == s2


def test_flop_balanced_partitioner_balances_cost():
    model = create_mnist_trainer()
    naive = NaivePartitioner().get_partitions(model, 2)
    flop = FlopBalancedPartitioner().get_partitions(model, 2)
    shapes = model.layer_shapes()
    costs = [l.forward_complexity(s) + l.backward_complexity(s)
             for l, s in zip(model.layers, shapes)]

    def imbalance(parts):
        stage_costs = [sum(costs[s:e]) for s, e in parts]
        return max(stage_costs) / max(min(stage_costs), 1)

    assert flop[0][0] == 0 and flop[-1][1] == len(model)
    assert imbalance(flop) <= imbalance(naive) + 1e-9


def test_split_microbatches():
    x = jnp.arange(10)
    mbs = split_microbatches(x, 3)
    assert [len(m) for m in mbs] == [3, 3, 4]  # remainder in last
    np.testing.assert_array_equal(np.concatenate([np.asarray(m) for m in mbs]),
                                  np.arange(10))
    with pytest.raises(ValueError):
        split_microbatches(jnp.arange(2), 3)


def test_pipeline_forward_matches_single_device():
    model = _model()
    coord = InProcessPipelineCoordinator(model, SGD(0.01), "softmax_crossentropy",
                                         num_stages=3, num_microbatches=2)
    coord.deploy_stages(KEY)
    # same init path as a single-device run → identical params
    params, state = model.init(KEY)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 8, 8))
    ref, _ = model.apply(params, state, x)
    out = coord.forward_only(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("schedule", ["sync", "semi_async"])
def test_pipeline_training_matches_single_device_microbatched(schedule):
    """Pipeline training with N microbatches must match single-device
    training with N-way grad accumulation (the reference's correctness
    criterion for its pipeline: same math, different placement)."""
    model = _model()
    nmb = 2
    coord = InProcessPipelineCoordinator(model, SGD(0.05), "softmax_crossentropy",
                                         num_stages=2, num_microbatches=nmb)
    coord.deploy_stages(KEY)

    # single-device reference with identical init and grad accumulation
    ref_model = _model()
    opt = SGD(0.05)
    ts = create_train_state(ref_model, opt, KEY)
    step = make_train_step(ref_model, lambda p, t: __import__(
        "dcnn_tpu.ops.losses", fromlist=["softmax_cross_entropy"]
    ).softmax_cross_entropy(p, t), opt, num_microbatches=nmb, donate=False)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 1, 8, 8)).astype(np.float32)
    labels = rng.integers(0, 10, size=8)
    y = np.eye(10, dtype=np.float32)[labels]

    fn = coord.train_batch_sync if schedule == "sync" else coord.train_batch_semi_async
    for it in range(3):
        loss_pipe, _ = fn(x, y, lr=0.05)
        ts, loss_ref, _ = step(ts, jnp.asarray(x), jnp.asarray(y),
                               jax.random.PRNGKey(9), 0.05)
        np.testing.assert_allclose(loss_pipe, float(loss_ref), rtol=1e-4, atol=1e-5)

    got_params, _ = coord.gathered_params()
    flat_got = jax.tree_util.tree_leaves(got_params)
    flat_ref = jax.tree_util.tree_leaves(ts.params)
    assert len(flat_got) == len(flat_ref)
    for a, b in zip(flat_got, flat_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)


def test_pipeline_stages_on_distinct_devices():
    """Stages live on distinct devices of the 8-device CPU mesh and still
    produce a correct chained forward — the multi-chip placement test."""
    devs = jax.devices()
    assert len(devs) >= 4, "conftest must provide 8 virtual devices"
    model = _model()
    coord = InProcessPipelineCoordinator(
        model, SGD(0.01), "softmax_crossentropy",
        num_stages=4, devices=devs[:4], num_microbatches=2, track_load=True)
    coord.deploy_stages(KEY)
    for stage, dev in zip(coord.stages, devs[:4]):
        leaf = jax.tree_util.tree_leaves(stage.params)[0]
        assert leaf.devices() == {dev}
    x = np.random.default_rng(0).normal(size=(4, 1, 8, 8)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[[0, 1, 2, 3]]
    loss, logits = coord.train_batch_semi_async(x, y, 0.01)
    assert np.isfinite(loss)
    assert logits.shape == (4, 10)
    reports = coord.collect_load_reports()
    assert len(reports) == 4 and reports[0]["forward_count"] > 0


def test_microbatch_cache_isolation():
    """Microbatch-ID stress (reference examples/microbatching_test.cpp):
    interleaved forwards for many microbatch ids must keep residuals separate
    and backward must consume the matching cache entry."""
    model = _model()
    coord = InProcessPipelineCoordinator(model, SGD(0.01), "softmax_crossentropy",
                                         num_stages=2, num_microbatches=4)
    coord.deploy_stages(KEY)
    stage = coord.stages[0]
    xs = [jax.random.normal(jax.random.fold_in(KEY, i), (2, 1, 8, 8)) for i in range(4)]
    outs = [stage.forward(i, xs[i]) for i in range(4)]
    assert len(stage._cache) == 4
    g = jnp.ones_like(outs[2])
    stage.backward(2, g)
    assert 2 not in stage._cache and len(stage._cache) == 3
    from dcnn_tpu.parallel import PipelineError
    with pytest.raises(PipelineError):
        stage.backward(2, g)


def test_in_process_profiling_collection():
    """In-process collect_profiling mirrors the distributed PRINT_PROFILING
    broadcast: per-layer tables per stage, empty before any batch."""
    model = _model()
    coord = InProcessPipelineCoordinator(model, SGD(0.01), "softmax_crossentropy",
                                         num_stages=2, num_microbatches=2)
    coord.deploy_stages(KEY)
    # before any microbatch: empty tables, formatter copes
    from dcnn_tpu.parallel.pipeline import format_profiling
    empty = coord.collect_profiling()
    assert all(t["layers"] == [] for t in empty)
    assert "no microbatch" in format_profiling(empty)

    x = jax.random.normal(jax.random.PRNGKey(1), (4, 1, 8, 8))
    y = np.eye(10, dtype=np.float32)[np.random.default_rng(0).integers(0, 10, 4)]
    coord.train_batch_sync(x, y, 0.01, jax.random.PRNGKey(2))
    tables = coord.collect_profiling()
    names = [r["name"] for t in tables for r in t["layers"]]
    assert names == [l.name for l in model.layers]
    assert all(r["fwd_us"] > 0 and r["bwd_us"] > 0
               for t in tables for r in t["layers"])
    coord.clear_profiling()
    assert coord.collect_profiling()[0]["layers"][0]["calls"] == 1
