"""Elastic preemption-tolerant DP training (ISSUE 8).

The headline contract, proven in-process: N controllers on threads over
real loopback sockets (the same topology ``tests/test_pipeline_failures``
uses for the pipeline), one killed mid-epoch by a deterministic per-peer
FaultPlan — survivors detect the loss, barrier on a new generation,
restore the newest checkpoint, re-shard the batch plan over the new world
size, and finish with final params matching a never-interrupted
fixed-world run within FP-reassociation tolerance, the global batch
identical pre/post reshard.
"""

import tempfile
import threading

import jax
import numpy as np
import pytest

from dcnn_tpu.core.config import TrainingConfig
from dcnn_tpu.data.loader import ArrayDataLoader, one_hot
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.parallel import comm
from dcnn_tpu.parallel.elastic import (
    ElasticController, EvictedError, PeerSpec, WorldCollapsedError,
    microbatch_span, parse_peers)
from dcnn_tpu.parallel.multihost import PeerLostError
from dcnn_tpu.resilience import FaultPlan
from dcnn_tpu.resilience.faults import InjectedCrash

_rng = np.random.default_rng(0)
X = _rng.normal(size=(48, 16)).astype(np.float32)
Y = one_hot(_rng.integers(0, 4, 48), 4)
BATCH = 12  # 4 global steps/epoch over the 48 rows

RTOL, ATOL = 2e-4, 2e-5  # FP reassociation of the gradient sum only


def _model():
    # stateless layers only: BN batch statistics are documented as
    # approximately (not bit-) preserved across a reshard, so the
    # exactness contract is proven on a state-free model
    return (SequentialBuilder("elastic_model").input((16,))
            .dense(32).activation("relu").dense(4).build())


def _loader():
    return ArrayDataLoader(X, Y, batch_size=BATCH, seed=7)


def _run_fleet(n, *, epochs=3, faults=None, ckpt_dir=None, ckpt_steps=2,
               k=2, min_world=1):
    """N in-process peers over loopback; returns (controllers, results)
    where a result is a TrainState, the string "crashed" (simulated host
    death), or the raised exception."""
    faults = faults or {}
    socks = [comm.listen(0, host="127.0.0.1") for _ in range(n)]
    peers = [PeerSpec(i, "127.0.0.1", s.getsockname()[1])
             for i, s in enumerate(socks)]
    ctls, results = {}, {}

    def runner(i):
        cfg = TrainingConfig(
            epochs=epochs, learning_rate=0.05, seed=3, snapshot_dir=None,
            elastic=True, elastic_microbatches=k, elastic_timeout_s=15.0,
            elastic_heartbeat_s=0.0, elastic_ckpt_steps=ckpt_steps,
            elastic_min_world=min_world, checkpoint_dir=ckpt_dir)
        ctl = ElasticController(
            _model(), SGD(0.05), "softmax_crossentropy", _loader(),
            config=cfg, rank=i, peers=peers, listen_sock=socks[i],
            fault_plan=faults.get(i))
        ctls[i] = ctl
        try:
            results[i] = ctl.fit(epochs=epochs)
        except InjectedCrash:
            results[i] = "crashed"
        except Exception as e:  # surfaced to the asserting test
            results[i] = e

    threads = [threading.Thread(target=runner, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "elastic fleet hung"
    return ctls, results


def _leaves(ts):
    return jax.tree_util.tree_leaves(jax.device_get(ts.params))


@pytest.fixture(scope="module")
def baseline2():
    """Never-interrupted fixed-world run: 2 peers, K=2."""
    ctls, results = _run_fleet(2, k=2)
    # replicated params are BIT-identical across peers (the mean is
    # computed once on the leader and broadcast)
    for a, b in zip(_leaves(results[0]), _leaves(results[1])):
        np.testing.assert_array_equal(a, b)
    return _leaves(results[0]), ctls[0]


@pytest.fixture(scope="module")
def baseline3():
    """Never-interrupted fixed-world run: 3 peers, K=6."""
    _ctls, results = _run_fleet(3, k=6)
    return _leaves(results[0])


# ---------------------------------------------------------------------------
# plan / grid unit coverage
# ---------------------------------------------------------------------------

def test_microbatch_span_partitions_every_world():
    for total in (1, 2, 3, 6, 8):
        for world in range(1, total + 1):
            owned = []
            for p in range(world):
                lo, hi = microbatch_span(total, world, p)
                owned.extend(range(lo, hi))
            assert owned == list(range(total)), (total, world)


def test_shard_batch_indices_union_is_the_global_plan():
    loader = _loader()
    loader.shuffle(5)
    ref = [np.asarray(b) for b in loader.batch_indices()]
    for world in (1, 2, 3, 4, 6):
        shards = []
        for r in range(world):
            loader.shuffle(5)
            shards.append(list(loader.shard_batch_indices(r, world)))
        for bi, batch in enumerate(ref):
            got = np.concatenate([shards[r][bi] for r in range(world)])
            np.testing.assert_array_equal(got, batch)


def test_shard_batch_indices_validation():
    loader = _loader()
    with pytest.raises(ValueError, match="divisible"):
        list(loader.shard_batch_indices(0, 5))  # 12 % 5 != 0
    with pytest.raises(ValueError, match="outside world"):
        list(loader.shard_batch_indices(2, 2))
    ragged = ArrayDataLoader(X, Y, batch_size=12, seed=7, drop_last=False)
    with pytest.raises(ValueError, match="drop_last"):
        list(ragged.shard_batch_indices(0, 2))


def test_host_shard_plan_drives_feed_pool_bit_identically():
    from dcnn_tpu.data.workers import FeedWorkerPool, host_shard_plan

    loader = _loader()
    plan = host_shard_plan(loader, epoch=2, rank=1, world_size=2)
    loader.shuffle(2)
    ref = list(loader.shard_batch_indices(1, 2))
    assert len(plan) == len(ref)
    for a, b in zip(plan, ref):
        np.testing.assert_array_equal(a, b)
    # a reconfiguration re-plans by re-calling with the new world size,
    # resuming at the restored step
    replanned = host_shard_plan(loader, epoch=2, rank=0, world_size=1,
                                start_step=2)
    loader.shuffle(2)
    full = [np.asarray(b) for b in loader.batch_indices()]
    for got, want in zip(replanned, full[2:]):
        np.testing.assert_array_equal(got, want)
    # and the pool's serial path gathers exactly the planned rows
    pool = FeedWorkerPool(X, Y, max_rows=BATCH, num_workers=0)
    for sel, shard in zip(plan, pool.shards(iter(plan), epoch=2)):
        xg, yg = shard.for_put()
        np.testing.assert_array_equal(xg, X[sel])
        np.testing.assert_array_equal(yg, Y[sel])
        shard.release()


def test_parse_peers():
    peers = parse_peers("10.0.0.1:5000, 10.0.0.2:5001,:5002")
    assert peers == [PeerSpec(0, "10.0.0.1", 5000),
                     PeerSpec(1, "10.0.0.2", 5001),
                     PeerSpec(2, "127.0.0.1", 5002)]


# ---------------------------------------------------------------------------
# the headline: kill a host mid-epoch
# ---------------------------------------------------------------------------

def test_solo_elastic_is_deterministic():
    _c1, r1 = _run_fleet(1, k=2)
    _c2, r2 = _run_fleet(1, k=2)
    for a, b in zip(_leaves(r1[0]), _leaves(r2[0])):
        np.testing.assert_array_equal(a, b)


def test_kill_a_host_mid_epoch_params_match_uninterrupted(baseline2):
    base_params, base_ctl = baseline2
    with tempfile.TemporaryDirectory() as d:
        plan = FaultPlan().arm("elastic.heartbeat", at=6, exc=InjectedCrash)
        ctls, results = _run_fleet(2, faults={1: plan}, ckpt_dir=d)
    assert results[1] == "crashed"
    survivor = ctls[0]
    assert not isinstance(results[0], BaseException), results[0]
    # reconfigured exactly once, world 2 -> 1, a fresh generation
    assert survivor.stats["reconfigures"] == 1
    assert survivor.gen == 1 and survivor.world == 1
    # the global batch is identical pre/post reshard: every executed
    # optimizer step — before the kill at world 2 and after at world 1 —
    # consumed exactly the loader's global batch
    rows = {e["global_rows"] for e in survivor.step_log}
    assert rows == {BATCH}
    worlds = {e["world"] for e in survivor.step_log}
    assert worlds == {1, 2}
    # final params match the never-interrupted fixed-world run
    for a, b in zip(base_params, _leaves(results[0])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)
    # and the uninterrupted baseline saw the full 12 steps while the
    # survivor re-ran the rewound ones
    assert len(base_ctl.step_log) == 12
    assert [e["gs"] for e in survivor.step_log][-1] == 12


def test_kill_the_leader_survivor_takes_over(baseline2):
    base_params, _ = baseline2
    with tempfile.TemporaryDirectory() as d:
        plan = FaultPlan().arm("elastic.heartbeat", at=6, exc=InjectedCrash)
        ctls, results = _run_fleet(2, faults={0: plan}, ckpt_dir=d)
    assert results[0] == "crashed"
    new_leader = ctls[1]
    assert not isinstance(results[1], BaseException), results[1]
    assert new_leader.gen == 1 and new_leader.world == 1
    assert new_leader.is_leader()
    for a, b in zip(base_params, _leaves(results[1])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


def test_second_loss_during_recovery_is_survived(baseline3):
    """Reconfigure idempotence: peer 2 is killed mid-epoch; peer 1 is
    armed to die at reconfiguration entry — the leader's first recovery
    wave fails and the protocol re-enters with the shrunken survivor
    set."""
    with tempfile.TemporaryDirectory() as d:
        plans = {
            2: FaultPlan().arm("elastic.heartbeat", at=5,
                               exc=InjectedCrash),
            1: FaultPlan().arm("elastic.reconfigure", exc=InjectedCrash),
        }
        ctls, results = _run_fleet(3, faults=plans, ckpt_dir=d, k=6)
    assert results[2] == "crashed" and results[1] == "crashed"
    leader = ctls[0]
    assert not isinstance(results[0], BaseException), results[0]
    # two reconfiguration waves collapsed into one completed recovery at
    # generation 2 (gen 1 never established — its barrier lost a peer)
    assert leader.gen == 2 and leader.world == 1
    assert leader.stats["peers_lost"] == 2
    for a, b in zip(baseline3, _leaves(results[0])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


def test_uneven_degraded_grid_keeps_global_batch(baseline3_k3):
    """K=3 microbatches over 2 survivors: unequal host shares (2+1
    microbatches) must still sum to the exact global batch — the
    weighted gradient-sum path."""
    with tempfile.TemporaryDirectory() as d:
        plans = {1: FaultPlan().arm("elastic.heartbeat", at=5,
                                    exc=InjectedCrash)}
        ctls, results = _run_fleet(3, faults=plans, ckpt_dir=d, k=3)
    assert results[1] == "crashed"
    for r in (0, 2):
        assert not isinstance(results[r], BaseException), results[r]
        assert ctls[r].world == 2
    # survivors stay bit-identical to each other even with unequal shares
    for a, b in zip(_leaves(results[0]), _leaves(results[2])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(baseline3_k3, _leaves(results[0])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


@pytest.fixture(scope="module")
def baseline3_k3():
    _ctls, results = _run_fleet(3, k=3)
    return _leaves(results[0])


def test_evicted_peer_exits_instead_of_fighting_the_quorum():
    """A peer the surviving quorum timed out joins the RECONF it receives
    as a follower — and finding itself outside the survivor list, raises
    EvictedError rather than escalating generations against hosts that
    already moved on."""
    cfg = TrainingConfig(
        elastic=True, elastic_microbatches=2, elastic_heartbeat_s=0.0,
        snapshot_dir=None)
    ctl = ElasticController(
        _model(), SGD(0.05), "softmax_crossentropy", _loader(),
        config=cfg, rank=1,
        peers=[PeerSpec(0, "127.0.0.1", 0), PeerSpec(1, "127.0.0.1", 0)])
    with pytest.raises(EvictedError, match="excluded from generation 5"):
        ctl._join_reconf({"gen": 5, "survivors": [0], "ckpt_step": -1,
                          "lr": 0.05})


def test_min_world_floor_aborts_instead_of_limping():
    with tempfile.TemporaryDirectory() as d:
        plan = FaultPlan().arm("elastic.heartbeat", at=6, exc=InjectedCrash)
        _ctls, results = _run_fleet(2, faults={1: plan}, ckpt_dir=d,
                                    min_world=2)
    assert results[1] == "crashed"
    assert isinstance(results[0], WorldCollapsedError)


# ---------------------------------------------------------------------------
# membership liveness (fake clock, no sockets)
# ---------------------------------------------------------------------------

def test_membership_timeout_detection_fake_clock():
    from dcnn_tpu.obs.registry import MetricsRegistry
    from dcnn_tpu.parallel.elastic import Membership

    t = [0.0]
    reg = MetricsRegistry()
    m = Membership(0, [PeerSpec(0, "h", 1), PeerSpec(1, "h", 2)],
                   peer_timeout_s=5.0, clock=lambda: t[0], registry=reg)

    class FakeChan:
        def close(self):
            pass

    with m._lock:
        m._channels[1] = FakeChan()
        m._last_heard[1] = t[0]
    assert m.check_peers() == []
    t[0] = 4.0
    m.heard(1)
    t[0] = 8.9  # 4.9s silent — under the timeout
    assert m.check_peers() == []
    assert m.alive() == [0, 1]
    t[0] = 9.1  # 5.1s silent
    assert m.check_peers() == [1]
    assert m.alive() == [0]
    dets = m.pop_detections()
    assert len(dets) == 1
    rank, age = dets[0]
    assert rank == 1 and age == pytest.approx(5.1)
    assert reg.counter("elastic_peers_lost_total").value == 1
    # edge-triggered: already-dead peers are not re-flagged
    t[0] = 20.0
    assert m.check_peers() == []


def test_membership_beat_thread_lifecycle():
    from dcnn_tpu.parallel.elastic import Membership

    m = Membership(0, [PeerSpec(0, "h", 1)], heartbeat_s=0.01)
    m._start_beat_thread()
    assert m._hb_thread is not None and m._hb_thread.is_alive()
    m.close()
    assert m._hb_thread is None
    m.close()  # idempotent


# ---------------------------------------------------------------------------
# multihost satellite: typed PeerLostError instead of hanging/leaking
# ---------------------------------------------------------------------------

class _FakeKv:
    def __init__(self, fail=False):
        self.fail = fail
        self.store = {}
        self.barriers = []

    def key_value_set(self, key, value):
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        if self.fail:
            raise RuntimeError(f"Deadline Exceeded after {timeout_ms}ms")
        return self.store[key]

    def wait_at_barrier(self, name, timeout_ms):
        if self.fail:
            raise RuntimeError(f"Barrier timed out after {timeout_ms}ms")
        self.barriers.append(name)


def test_multihost_barrier_raises_typed_peer_lost(monkeypatch):
    from dcnn_tpu.parallel import multihost

    kv = _FakeKv(fail=True)
    with pytest.raises(PeerLostError, match=r"barrier\('epoch-1'\)"):
        multihost.barrier("epoch-1", timeout_ms=10, client=kv)
    kv_ok = _FakeKv()
    multihost.barrier("epoch-1", timeout_ms=10, client=kv_ok)
    assert kv_ok.barriers == ["epoch-1"]


def test_multihost_broadcast_config_raises_typed_peer_lost(monkeypatch):
    from dcnn_tpu.parallel import multihost

    monkeypatch.setattr(multihost.jax, "process_index", lambda: 1)
    kv = _FakeKv(fail=True)
    with pytest.raises(PeerLostError, match="broadcast_config"):
        multihost.broadcast_config("cfg", {"a": 1}, timeout_ms=10,
                                   client=kv)
    # coordinator publishes; worker receives
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 0)
    kv_ok = _FakeKv()
    assert multihost.broadcast_config("cfg", {"a": 1}, client=kv_ok) \
        == {"a": 1}
    monkeypatch.setattr(multihost.jax, "process_index", lambda: 1)
    assert multihost.broadcast_config("cfg", {}, client=kv_ok) == {"a": 1}


# ---------------------------------------------------------------------------
# comm satellite: the send path rides the shared retry primitive
# ---------------------------------------------------------------------------

def _channel_pair():
    srv = comm.listen(0, host="127.0.0.1")
    tx = comm.connect("127.0.0.1", srv.getsockname()[1], timeout=5.0)
    sock, _ = srv.accept()
    srv.close()
    return tx, comm.Channel(sock)


def test_send_retries_flaky_fault_then_delivers():
    """ISSUE 8 satellite: a transient pre-wire send failure (the armed
    comm.send fault point) is retried with backoff, not fatal — the frame
    arrives intact and the attempts are visible on the registry."""
    from dcnn_tpu.obs import get_registry

    tx, rx = _channel_pair()
    try:
        reg = get_registry()
        before = reg.counter("comm_send_retry_attempts_total").value
        with FaultPlan().arm("comm.send", times=2, exc=OSError) as plan:
            tx.send("PING", {"n": 7}, array=np.arange(4, dtype=np.float32),
                    attempts=4, sleep=lambda s: None)
            assert plan.count("comm.send") == 3
        cmd, meta, payload = rx.recv()
        assert cmd == "PING" and meta["n"] == 7
        np.testing.assert_array_equal(payload,
                                      np.arange(4, dtype=np.float32))
        assert reg.counter("comm_send_retry_attempts_total").value \
            == before + 2
    finally:
        tx.close()
        rx.close()


def test_send_exhausted_retries_reraise():
    tx, rx = _channel_pair()
    try:
        with FaultPlan().arm("comm.send", exc=OSError):
            with pytest.raises(OSError):
                tx.send("PING", {}, attempts=3, sleep=lambda s: None)
    finally:
        tx.close()
        rx.close()


def test_send_on_broken_socket_fails_fast_not_retried():
    """Once sendall has raised, part of a frame may be on the wire: the
    channel marks itself broken and every later send fails immediately —
    resend-after-reconnect is the caller's job, never this socket's."""
    tx, rx = _channel_pair()
    rx.close()
    big = np.zeros(1 << 20, dtype=np.float32)  # overflow the socket buffer
    slept = []
    with pytest.raises(OSError):
        for _ in range(64):
            tx.send("DATA", {}, array=big, attempts=3,
                    sleep=lambda s: slept.append(s))
    assert tx._broken
    assert slept == []  # the broken path never backed off
    with pytest.raises(comm.ChannelClosed):
        tx.send("DATA", {}, attempts=3, sleep=lambda s: None)
    tx.close()


def test_injected_crash_on_send_is_not_retried():
    tx, rx = _channel_pair()
    try:
        with FaultPlan().arm("comm.send", exc=InjectedCrash):
            with pytest.raises(InjectedCrash):
                tx.send("PING", {}, attempts=5, sleep=lambda s: None)
    finally:
        tx.close()
        rx.close()


# ---------------------------------------------------------------------------
# obs satellite: /healthz degrades while reconfiguring
# ---------------------------------------------------------------------------

def test_healthz_degrades_while_reconfiguring():
    from dcnn_tpu.obs import TelemetryServer, elastic_check
    from dcnn_tpu.obs.registry import MetricsRegistry
    from dcnn_tpu.obs.tracer import Tracer

    class FakeController:
        reconfiguring = False
        generation = 3
        world = 2

    ctl = FakeController()
    srv = TelemetryServer(registry=MetricsRegistry(), tracer=Tracer())
    srv.add_check("elastic", elastic_check(ctl))
    code, body = srv.health()
    assert code == 200
    ctl.reconfiguring = True
    code, body = srv.health()
    assert code == 503
    assert any("reconfiguration in flight" in r for r in body["reasons"])
    assert "generation 3" in body["reasons"][0]
    ctl.reconfiguring = False
    code, _ = srv.health()
    assert code == 200


def test_healthz_registry_flag_fallback_without_check():
    from dcnn_tpu.obs import TelemetryServer
    from dcnn_tpu.obs.registry import MetricsRegistry
    from dcnn_tpu.obs.tracer import Tracer

    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg, tracer=Tracer())
    assert srv.health()[0] == 200
    reg.gauge("elastic_reconfiguring", "flag").set(1)
    code, body = srv.health()
    assert code == 503
    assert any("elastic_reconfiguring" in r for r in body["reasons"])
    reg.gauge("elastic_reconfiguring", "flag").set(0)
    assert srv.health()[0] == 200


# ---------------------------------------------------------------------------
# feed pool re-plan + trainer delegation
# ---------------------------------------------------------------------------

def test_elastic_with_feed_pool_matches_plain_path():
    """The FeedWorkerPool-fed controller reproduces the loader-fed run
    bit-exactly (the pool's serial path is the gather reference), proving
    the world-size-parameterized re-plan hands the same rows."""
    from dcnn_tpu.data.workers import FeedWorkerPool

    _c, r_plain = _run_fleet(1, k=2, epochs=2)
    socks = [comm.listen(0, host="127.0.0.1")]
    peers = [PeerSpec(0, "127.0.0.1", socks[0].getsockname()[1])]
    cfg = TrainingConfig(
        epochs=2, learning_rate=0.05, seed=3, snapshot_dir=None,
        elastic=True, elastic_microbatches=2, elastic_timeout_s=15.0,
        elastic_heartbeat_s=0.0)
    pool = FeedWorkerPool(X, Y, max_rows=BATCH, num_workers=0)
    ctl = ElasticController(
        _model(), SGD(0.05), "softmax_crossentropy", _loader(),
        config=cfg, rank=0, peers=peers, listen_sock=socks[0],
        feed_pool=pool)
    ts = ctl.fit(epochs=2)
    for a, b in zip(_leaves(r_plain[0]), _leaves(ts)):
        np.testing.assert_array_equal(a, b)


def test_trainer_fit_delegates_to_elastic():
    from dcnn_tpu.train.trainer import Trainer, create_train_state

    cfg = TrainingConfig(
        epochs=2, learning_rate=0.05, seed=3, snapshot_dir=None,
        elastic=True, elastic_rank=0, elastic_microbatches=1,
        elastic_heartbeat_s=0.0)
    trainer = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
    ts = create_train_state(trainer.model, trainer.optimizer,
                            jax.random.PRNGKey(cfg.seed))
    ts = trainer.fit(ts, _loader())
    assert len(trainer.history) == 2
    assert trainer.history[0]["world"] == 1
    assert np.isfinite(trainer.history[-1]["train_loss"])


def test_elastic_fit_wires_feed_workers(monkeypatch):
    """TrainingConfig.feed_workers must not become a silent no-op on the
    elastic path: elastic_fit builds the FeedWorkerPool and hands it to
    the controller (patched to the serial backend for determinism)."""
    import dcnn_tpu.data.workers as workers_mod
    from dcnn_tpu.train.trainer import Trainer, create_train_state

    created = {}
    real_pool = workers_mod.FeedWorkerPool

    def fake_pool(x, y, max_rows, **kw):
        created.update(kw, max_rows=max_rows)
        return real_pool(x, y, max_rows, num_workers=0,
                         seed=kw.get("seed", 0))

    monkeypatch.setattr(workers_mod, "FeedWorkerPool", fake_pool)
    cfg = TrainingConfig(
        epochs=1, learning_rate=0.05, seed=3, snapshot_dir=None,
        elastic=True, elastic_rank=0, elastic_microbatches=1,
        elastic_heartbeat_s=0.0, feed_workers=3)
    trainer = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
    ts = create_train_state(trainer.model, trainer.optimizer,
                            jax.random.PRNGKey(cfg.seed))
    trainer.fit(ts, _loader())
    assert created["num_workers"] == 3
    assert created["max_rows"] == BATCH
    # and the pooled run matches the plain solo run bit-exactly
    _c, r_plain = _run_fleet(1, k=1, epochs=1)
    t2 = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
    ts2 = create_train_state(t2.model, t2.optimizer,
                             jax.random.PRNGKey(cfg.seed))
    ts2 = t2.fit(ts2, _loader())
    for a, b in zip(_leaves(r_plain[0]), _leaves(ts2)):
        np.testing.assert_array_equal(a, b)


def test_elastic_validates_grid_divisibility():
    cfg = TrainingConfig(elastic=True, elastic_microbatches=5)
    with pytest.raises(ValueError, match="not divisible"):
        ElasticController(
            _model(), SGD(0.05), "softmax_crossentropy", _loader(),
            config=cfg, rank=0, peers=[PeerSpec(0, "127.0.0.1", 0)])
    cfg2 = TrainingConfig(elastic=True, elastic_microbatches=3)
    with pytest.raises(ValueError, match="initial world"):
        ElasticController(
            _model(), SGD(0.05), "softmax_crossentropy", _loader(),
            config=cfg2, rank=0,
            peers=[PeerSpec(0, "127.0.0.1", 0),
                   PeerSpec(1, "127.0.0.1", 1)])
