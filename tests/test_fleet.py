"""Fleet-aggregation tests (dcnn_tpu/obs/fleet.py): render→parse→merge
round trips over real multi-replica expositions, live ephemeral-port
fleet endpoints, scrape self-observability, the autoscaler-on-aggregator
contract, and the ISSUE-15 end-to-end proof — a 3-replica fleet under
open-loop load with an injected latency fault, driven entirely on fake
clocks (no sleeps)."""

import json
import os
import urllib.error
import urllib.request

import numpy as np
import pytest

from dcnn_tpu.obs.fleet import FleetAggregator, HttpScraper
from dcnn_tpu.obs.flight import FlightRecorder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.obs.rules import RuleEngine
from dcnn_tpu.obs.server import TelemetryServer
from dcnn_tpu.obs.trace import inspect_bundle
from dcnn_tpu.obs.tsdb import load_history
from dcnn_tpu.serve.metrics import ServeMetrics
from dcnn_tpu.serve.soak import (ManualClock, make_soak_replica_factory,
                                 run_diurnal_soak)
from dcnn_tpu.serve.traffic import open_loop


def _get_json(url):
    try:
        with urllib.request.urlopen(url, timeout=5.0) as r:
            return r.getcode(), json.loads(r.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read().decode("utf-8"))


# ------------------------------------------------- render→parse→aggregate

def test_render_parse_aggregate_round_trip_real_expositions():
    """Real multi-replica ServeMetrics expositions (the exact bytes
    /metrics serves) through the aggregator: per-replica labeled series
    + sum/max fleet merges equal the source snapshots."""
    fc = ManualClock()
    reps = {}
    for name, n_completed in (("r0", 3), ("r1", 5)):
        m = ServeMetrics(clock=fc)
        for _ in range(n_completed):
            m.record_submit(1)
            fc.advance(0.010)
            m.record_done(0.010)
        m.record_queue_depth(n_completed)  # distinct per-replica gauge
        reps[name] = m
    agg = FleetAggregator(registry=MetricsRegistry(clock=fc), clock=fc)
    for name, m in reps.items():
        agg.add_target(name, scrape=m.prometheus)
    res = agg.poll()
    assert all(r["values"] is not None for r in res.values())
    for name, m in reps.items():
        snap = m.snapshot()
        assert agg.store.latest(
            f'serve_queue_depth{{replica="{name}"}}')[1] \
            == snap["queue_depth"]
    assert agg.store.latest('serve_queue_depth{fleet="sum"}')[1] == 8.0
    assert agg.store.latest('serve_queue_depth{fleet="max"}')[1] == 5.0
    doc = agg.fleet_doc()
    row = doc["series"]["serve_queue_depth"]
    assert row == {"replicas": {"r0": 3.0, "r1": 5.0},
                   "sum": 8.0, "max": 5.0}


def test_fleet_endpoints_over_live_ephemeral_servers():
    """/fleet, /alerts and the roll-up /healthz served over real
    ephemeral-port HTTP, scraping two live replica TelemetryServers (one
    via in-process fast path, one via URL)."""
    r1, r2 = MetricsRegistry(), MetricsRegistry()
    r1.gauge("serve_queue_depth").set(2.0)
    r2.gauge("serve_queue_depth").set(6.0)
    s1 = TelemetryServer(registry=r1).start()
    s2 = TelemetryServer(registry=r2).start()
    freg = MetricsRegistry()
    agg = FleetAggregator(registry=freg)
    eng = RuleEngine(agg.store, registry=freg)
    eng.add_alert(name="deep", series='serve_queue_depth{fleet="max"}',
                  op=">", threshold=4.0, for_s=0.0, window_s=60.0)
    agg.rules = eng
    agg.add_target("r1", server=s1)
    agg.add_target("r2", url=s2.url)
    try:
        agg.poll()
        fsrv = agg.serve()
        code, fleet = _get_json(f"{fsrv.url}/fleet")
        assert code == 200
        assert fleet["targets"]["r1"]["up"] and fleet["targets"]["r2"]["up"]
        assert fleet["series"]["serve_queue_depth"]["sum"] == 8.0
        assert fleet["polls"] == 1
        code, alerts = _get_json(f"{fsrv.url}/alerts")
        assert code == 200 and alerts["firing"] == ["deep"]
        code, health = _get_json(f"{fsrv.url}/healthz")
        assert code == 503
        assert any("deep" in r for r in health["reasons"])
        # per-rule alert_state series ride the fleet /metrics exposition
        with urllib.request.urlopen(f"{fsrv.url}/metrics") as r:
            text = r.read().decode("utf-8")
        assert 'alert_state{rule="deep"} 2' in text
        # 404 body lists the fleet routes
        try:
            urllib.request.urlopen(f"{fsrv.url}/nope")
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as e:
            assert "/fleet" in json.loads(e.read())["routes"]
    finally:
        agg.close()
        s1.stop()
        s2.stop()


def test_half_dead_target_is_visible():
    """A target that stops answering (or serves garbage) must surface on
    counters, the up-series, and the health roll-up — the PR 11
    silent-parse-failure lesson at fleet scope."""
    freg = MetricsRegistry()
    agg = FleetAggregator(registry=freg)
    state = {"text": "ok_total 1\n"}
    agg.add_target("good", scrape=lambda: "g 1\n")
    agg.add_target("flaky", scrape=lambda: state["text"])
    agg.poll()
    assert agg.health_rollup() is None
    state["text"] = None                       # target goes dark
    agg.poll()
    assert freg.snapshot()["fleet_scrape_errors_total"] == 1
    assert agg.store.latest('fleet_target_up{replica="flaky"}')[1] == 0.0
    assert agg.store.latest('fleet_target_up{replica="good"}')[1] == 1.0
    assert "flaky" in agg.health_rollup()
    state["text"] = "torn{ garbage\n"          # now it half-answers
    res = agg.poll()
    assert res["flaky"]["parse_error"] is not None
    assert freg.snapshot()["fleet_scrape_errors_total"] == 2
    assert "flaky" in agg.health_rollup()
    snap = freg.snapshot()
    assert snap["fleet_targets"] == 2
    assert snap["fleet_targets_up"] == 1
    assert snap["fleet_scrape_seconds"]["count"] >= 6


def test_unhealthy_target_degrades_rollup():
    """A reachable target whose own /healthz is 503 degrades the fleet
    roll-up with its reasons quoted."""
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg)
    srv.add_check("stuck", lambda: "wedged on purpose")
    srv.start()
    agg = FleetAggregator(registry=MetricsRegistry())
    agg.add_target("r0", server=srv)
    try:
        agg.poll()
        rollup = agg.health_rollup()
        assert rollup is not None and "wedged on purpose" in rollup
    finally:
        agg.close()
        srv.stop()


def test_scrape_self_observability_per_endpoint():
    """TelemetryServer counts its own scrapes per endpoint: requests,
    errors, and a shared duration histogram on the served registry."""
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg)
    srv.add_route("/boom", lambda: (_ for _ in ()).throw(
        RuntimeError("broken provider")))
    srv.start()
    try:
        for _ in range(2):
            urllib.request.urlopen(f"{srv.url}/metrics").read()
        _get_json(f"{srv.url}/healthz")
        try:
            urllib.request.urlopen(f"{srv.url}/boom")
        except urllib.error.HTTPError as e:
            assert e.code == 500
        try:
            urllib.request.urlopen(f"{srv.url}/unknown")
        except urllib.error.HTTPError:
            pass
        snap = reg.snapshot()
        assert snap["scrape_requests_metrics_total"] == 2
        assert snap["scrape_requests_healthz_total"] == 1
        assert snap["scrape_requests_boom_total"] == 1
        assert snap["scrape_errors_boom_total"] == 1
        assert snap["scrape_requests_other_total"] == 1
        assert snap["scrape_requests_total"] == 5
        assert snap["scrape_errors_total"] == 1
        assert snap["scrape_duration_seconds"]["count"] == 5
        # ...and the counters are visible on the NEXT scrape
        with urllib.request.urlopen(f"{srv.url}/metrics") as r:
            text = r.read().decode("utf-8")
        assert "scrape_requests_metrics_total 2" in text
    finally:
        srv.stop()


def test_http_scraper_reexport_and_add_route_guards():
    # the pre-fleet import path must keep working
    from dcnn_tpu.serve.autoscale import HttpScraper as FromAutoscale
    assert FromAutoscale is HttpScraper
    srv = TelemetryServer(registry=MetricsRegistry())
    with pytest.raises(ValueError):
        srv.add_route("no-slash", dict)
    with pytest.raises(ValueError):
        srv.add_route("/metrics", dict)
    agg = FleetAggregator(registry=MetricsRegistry())
    with pytest.raises(ValueError):
        agg.add_target("x")                      # none of url/server/scrape
    agg.add_target("x", scrape=lambda: None)
    with pytest.raises(ValueError):
        agg.add_target("x", scrape=lambda: None)  # duplicate
    agg.remove_target("x")
    assert agg.targets() == []


def test_dynamic_targets_evict_from_last_poll_view():
    """A replica that disappears from an explicit poll(targets=...) set
    (the autoscaler scaled it away) ages out of /fleet and the health
    roll-up instead of pinning a stale 'scrape failed' 503 forever."""
    agg = FleetAggregator(registry=MetricsRegistry())
    agg.poll(targets={"r0": lambda: "g 1\n", "r1": lambda: None})
    assert "r1" in agg.health_rollup()          # half-dead while present
    agg.poll(targets={"r0": lambda: "g 2\n"})   # r1 scaled away
    assert agg.health_rollup() is None
    assert set(agg.fleet_doc()["targets"]) == {"r0"}


def test_health_rollup_reads_poll_cache_not_live(monkeypatch):
    """The roll-up check must never fetch live — a slow target would
    block every /healthz probe; the verdict comes from poll-time
    cache."""
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg)
    srv.add_check("stuck", lambda: "wedged")
    srv.start()
    agg = FleetAggregator(registry=MetricsRegistry())
    agg.add_target("r0", server=srv)
    try:
        agg.poll()
        monkeypatch.setattr(
            agg, "_fetch_healthz",
            lambda spec: (_ for _ in ()).throw(
                AssertionError("roll-up must not fetch live")))
        assert "wedged" in agg.health_rollup()
    finally:
        agg.close()
        srv.stop()


def test_replica_flight_bundles_carry_history(tmp_path):
    """The batcher's telemetry wiring attaches its store to the flight
    recorder: a serve-side bundle carries history.jsonl INCLUDING the
    derived windowed gauges (p99/shed fraction — they exist only in the
    rendered exposition, so the sampler reads the text contract), and
    shutdown detaches only its own store."""
    import numpy as np

    from dcnn_tpu.obs.flight import get_flight_recorder
    from dcnn_tpu.serve.batcher import DynamicBatcher
    from dcnn_tpu.serve.soak import SyntheticEngine

    rec = get_flight_recorder()
    old = rec.directory, rec._tsdb
    rec.directory, rec._tsdb = str(tmp_path), None
    batcher = DynamicBatcher(SyntheticEngine(), start=False)
    try:
        batcher.start_telemetry(port=0)
        assert rec._tsdb is batcher._tsdb.store
        batcher.submit(np.full((4,), 7, np.float32))
        batcher.step(force=True)
        batcher._tsdb.sample_once()         # deterministic pass
        path = rec.record("healthz_degraded", reasons=["test"])
        assert path is not None
        assert "history.jsonl" in os.listdir(path)
        _meta, series = load_history(os.path.join(path, "history.jsonl"))
        assert "serve_latency_window_p99_ms" in series
        assert "serve_shed_fraction" in series
        assert "serve_queue_depth" in series
    finally:
        batcher.shutdown(drain=False)
        assert rec._tsdb is None            # detached its own store
        rec.directory, rec._tsdb = old


def test_dead_target_costs_one_timeout_no_healthz_fetch(monkeypatch):
    """A target whose metrics fetch failed is NOT probed for /healthz
    too — one dead host costs one timeout, and (with >1 target) fetches
    run concurrently so the pass stays on cadence."""
    agg = FleetAggregator(registry=MetricsRegistry())
    agg.add_target("dead", url="http://127.0.0.1:9")   # discard port
    agg.add_target("live", scrape=lambda: "g 1\n")
    monkeypatch.setattr(
        agg, "_fetch_healthz",
        lambda spec: (_ for _ in ()).throw(
            AssertionError("healthz must not be fetched for a dead "
                           "target")))
    res = agg.poll()
    assert res["live"]["values"] == {"g": 1.0}
    assert not res["dead"]["fetched"]
    assert "dead" in agg.health_rollup()


def test_trailing_slash_counts_as_other_not_endpoint():
    """/healthz/ 404s, so it must land on the `other` counter — counting
    it as healthz would mask the misconfigured probe the self-obs
    counters exist to expose."""
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg).start()
    try:
        try:
            urllib.request.urlopen(f"{srv.url}/healthz/")
        except urllib.error.HTTPError as e:
            assert e.code == 404
        snap = reg.snapshot()
        assert snap.get("scrape_requests_healthz_total", 0) == 0
        assert snap["scrape_requests_other_total"] == 1
    finally:
        srv.stop()


def test_hyphenated_route_slug_mints_valid_counters():
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg)
    srv.add_route("/my-route", lambda: {"ok": True})
    srv.start()
    try:
        _get_json(f"{srv.url}/my-route")
        snap = reg.snapshot()
        assert snap["scrape_requests_my_route_total"] == 1
        assert snap["scrape_requests_total"] == 1
    finally:
        srv.stop()


# ---------------------------------------------- autoscaler-on-aggregator

def test_autoscaler_reads_through_aggregator():
    """Autoscaler.collect is the aggregator's poll: per-replica history
    lands in the scaler's tsdb and the soak gates hold unchanged (the
    equivalence pin for the refactor)."""
    report, scaler, router = run_diurnal_soak()
    # the PR 11 gates, verbatim
    assert report["silently_dropped"] == 0
    assert report["availability"] >= 0.999, report
    assert report["scale_ups"] >= 2, report
    # the new monitoring-plane evidence
    store = scaler.aggregator.store
    assert store.points() > 0
    assert any(k.startswith("serve_latency_window_p99_ms{replica=")
               for k in store.series_names())
    assert store.latest('serve_queue_depth{fleet="sum"}') is not None
    hist = report["history"]
    assert hist["series"] > 0 and hist["points"] > 0
    assert hist["p99_ms_max"]["points"] > 0
    snap = router.metrics.registry.snapshot()
    assert snap["fleet_polls_total"] > 0
    assert snap["fleet_scrape_requests_total"] > 0


# ------------------------------------------------------- the ISSUE-15 e2e

def test_e2e_three_replica_fleet_latency_fault_alert_lifecycle(tmp_path):
    """The acceptance proof: a 3-replica in-process fleet under open-loop
    load with an injected latency fault — the p99 alert transitions
    pending→firing within its for_s budget, the fleet /healthz degrades
    naming the rule, an alert_firing flight bundle lands carrying the
    pre-trigger history window, /fleet serves the merged labeled series,
    and removing the fault resolves the alert. Entirely sleep-free."""
    fc = ManualClock()
    # window=32: the overload ages out of each replica's p99 within a
    # few dozen recovery completions
    factory = make_soak_replica_factory(fc, queue_capacity=64,
                                        window=32)
    state = {"slow": False}
    replicas = [factory(1) for _ in range(3)]

    def pump_all():
        for rep in replicas:
            try:
                rep.step(force=True)
            except Exception:
                pass

    class RoundRobin:
        """Symmetric fan-out (the fleet under test is the monitoring
        plane, not the router's SLO-aware placement — which would
        deliberately starve a slow replica and keep its stale window
        pinned)."""

        def __init__(self):
            self.i = 0

        def submit(self, x):
            self.i += 1
            return replicas[self.i % len(replicas)].submit(x)

    router = RoundRobin()

    freg = MetricsRegistry(clock=fc)
    fl = FlightRecorder(str(tmp_path), registry=freg, clock=fc,
                        min_interval_s=0.0)
    agg = FleetAggregator(registry=freg, clock=fc)
    fl.attach_tsdb(agg.store)
    eng = RuleEngine(agg.store, registry=freg, flight=fl, clock=fc)
    FOR_S, TICK = 3.0, 1.0
    eng.add_alert(name="fleet_p99_slo",
                  series='serve_latency_window_p99_ms{fleet="max"}',
                  op=">", threshold=200.0, for_s=FOR_S, window_s=30.0,
                  description="fleet p99 over SLO")
    agg.rules = eng
    for rep in replicas:
        agg.add_target(rep.name, scrape=rep.metrics.prometheus)
    fsrv = agg.serve()
    try:
        # -- open-loop load; the fault slows SERVICE (pump cadence), so
        # measured latency rises while traffic keeps arriving
        state_t = {"next_pump": 0.0, "next_poll": 0.0}
        alert_log = []

        def drive_sleep(dt):
            t_end = fc.t + dt
            while fc.t < t_end:
                nxt = min(t_end, state_t["next_pump"],
                          state_t["next_poll"])
                if fc.t < nxt:
                    fc.advance(nxt - fc.t)
                if fc.t >= state_t["next_pump"]:
                    pump_all()
                    state_t["next_pump"] += (0.8 if state["slow"]
                                             else 0.05)
                if fc.t >= state_t["next_poll"]:
                    agg.poll()
                    st = eng.alerts()[0]
                    if not alert_log or alert_log[-1][1] != st["state"]:
                        alert_log.append((fc.t, st["state"]))
                    state_t["next_poll"] += TICK

        samples = [np.full((4,), 7, np.float32)]
        open_loop(router, samples, 40.0, 10.0, clock=fc,
                  sleep=drive_sleep)            # healthy phase
        assert eng.alerts()[0]["state"] == "inactive"
        state["slow"] = True                    # inject the latency fault
        open_loop(router, samples, 40.0, 12.0, clock=fc,
                  sleep=drive_sleep)
        # -- pending→firing within the for_s budget
        states = [s for _, s in alert_log]
        assert "pending" in states and "firing" in states
        t_pending = next(t for t, s in alert_log if s == "pending")
        t_firing = next(t for t, s in alert_log if s == "firing")
        assert FOR_S <= t_firing - t_pending <= FOR_S + 2 * TICK, alert_log
        assert eng.alerts()[0]["state"] == "firing"
        # -- fleet /healthz degrades naming the rule
        code, health = _get_json(f"{fsrv.url}/healthz")
        assert code == 503
        assert any("fleet_p99_slo" in r for r in health["reasons"])
        # -- /fleet serves the merged labeled series for all 3 replicas
        code, fleet = _get_json(f"{fsrv.url}/fleet")
        assert code == 200
        row = fleet["series"]["serve_latency_window_p99_ms"]
        assert set(row["replicas"]) == {r.name for r in replicas}
        assert row["max"] > 200.0
        code, alerts = _get_json(f"{fsrv.url}/alerts")
        assert alerts["firing"] == ["fleet_p99_slo"]
        # -- the alert_firing bundle carries the pre-trigger history
        bundles = fl.bundles()
        assert [b["trigger"] for b in bundles] == ["alert_firing"]
        bpath = bundles[0]["path"]
        extra = json.load(open(os.path.join(bpath, "extra.json")))
        window_ts = [t for t, _ in extra["window"]]
        assert window_ts and min(window_ts) < t_firing  # BEFORE the page
        _meta, series = load_history(os.path.join(bpath, "history.jsonl"))
        assert any(k.startswith("serve_latency_window_p99_ms{replica=")
                   for k in series)
        assert inspect_bundle(bpath)["history"]["series"] > 0
        # -- removing the fault resolves the alert and heals /healthz
        state["slow"] = False
        open_loop(router, samples, 40.0, 30.0, clock=fc,
                  sleep=drive_sleep)
        assert eng.alerts()[0]["state"] == "inactive", alert_log
        assert eng.alerts()[0]["resolved_total"] == 1
        code, _health = _get_json(f"{fsrv.url}/healthz")
        assert code == 200
    finally:
        agg.close()
        for rep in replicas:
            try:
                rep.close()
            except Exception:
                pass
