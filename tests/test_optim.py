"""Optimizer + scheduler tests against reference semantics and torch."""


import jax.numpy as jnp
import numpy as np
import pytest
import torch

from dcnn_tpu.optim import (
    SGD, Adam, AdamW, CosineAnnealingLR, CosineAnnealingWarmRestarts,
    ExponentialLR, LinearWarmup, MultiStepLR, OneCycleLR, OptimizerFactory,
    PolynomialLR, ReduceLROnPlateau, SchedulerFactory, StepLR,
    WarmupCosineAnnealing,
)


def _tree(x):
    return {"w": jnp.asarray(x, jnp.float32)}


def test_sgd_plain():
    opt = SGD(0.1)
    params = _tree([1.0, 2.0])
    grads = _tree([0.5, -1.0])
    st = opt.init(params)
    new_params, st = opt.update(grads, st, params)
    np.testing.assert_allclose(np.asarray(new_params["w"]), [0.95, 2.1], rtol=1e-6)


def test_sgd_momentum_matches_reference_form():
    # reference: v = mu*v - lr*g; p += v (sgd_kernels.cpp:22-30)
    opt = SGD(0.1, momentum=0.9)
    params = _tree([1.0])
    st = opt.init(params)
    p, v = 1.0, 0.0
    cur = params
    for g in [0.5, 0.2, -0.3]:
        cur, st = opt.update(_tree([g]), st, cur)
        v = 0.9 * v - 0.1 * g
        p = p + v
        np.testing.assert_allclose(float(cur["w"][0]), p, rtol=1e-6)


def test_adam_matches_torch():
    w0 = np.array([1.0, -2.0, 3.0], np.float32)
    grads_seq = [np.array(g, np.float32) for g in
                 ([0.1, -0.2, 0.3], [0.05, 0.5, -0.1], [-0.3, 0.2, 0.1])]

    opt = Adam(0.01)
    params = _tree(w0)
    st = opt.init(params)
    for g in grads_seq:
        params, st = opt.update(_tree(g), st, params)

    wt = torch.tensor(w0, requires_grad=True)
    topt = torch.optim.Adam([wt], lr=0.01, betas=(0.9, 0.999), eps=1e-8)
    for g in grads_seq:
        wt.grad = torch.tensor(g)
        topt.step()
    np.testing.assert_allclose(np.asarray(params["w"]), wt.detach().numpy(), rtol=1e-5)


def test_adamw_decoupled_decay():
    # AdamW: p -= wd*lr*p applied separately from the moment update
    # (adam_kernels.cpp:46-49)
    opt = AdamW(0.01, weight_decay=0.1)
    params = _tree([1.0])
    st = opt.init(params)
    p1, _ = opt.update(_tree([0.0]), st, params)
    # zero grad → moments stay 0, update = 0; only decay applies
    np.testing.assert_allclose(float(p1["w"][0]), 1.0 - 0.1 * 0.01 * 1.0, rtol=1e-6)


def test_optimizer_factory_roundtrip():
    for opt in (SGD(0.05, 0.9), Adam(0.002, weight_decay=0.01), AdamW(0.003)):
        clone = OptimizerFactory.create_from_config(opt.get_config())
        assert clone.get_config() == opt.get_config()
        assert clone.name() == opt.name()


def test_step_lr():
    s = StepLR(1.0, step_size=2, gamma=0.5)
    lrs = [s.step() for _ in range(4)]
    np.testing.assert_allclose(lrs, [1.0, 0.5, 0.5, 0.25])


def test_multi_step_lr():
    s = MultiStepLR(1.0, milestones=[2, 4], gamma=0.1)
    lrs = [s.step() for _ in range(5)]
    np.testing.assert_allclose(lrs, [1.0, 0.1, 0.1, 0.01, 0.01], rtol=1e-6)


def test_exponential_lr():
    s = ExponentialLR(1.0, gamma=0.5)
    assert s.step() == 0.5 and s.step() == 0.25


def test_cosine_annealing():
    s = CosineAnnealingLR(1.0, T_max=10, eta_min=0.1)
    s10 = [s.step() for _ in range(10)][-1]
    # at step 10 (mod T_max = 0) back at base_lr (reference wraps, :183)
    np.testing.assert_allclose(s10, 1.0, rtol=1e-6)
    s = CosineAnnealingLR(1.0, T_max=10)
    lr5 = [s.step() for _ in range(5)][-1]
    np.testing.assert_allclose(lr5, 0.5, atol=1e-6)


def test_warm_restarts():
    s = CosineAnnealingWarmRestarts(1.0, T_0=4, T_mult=2)
    lrs = [s.step() for _ in range(12)]
    assert lrs[3] == pytest.approx(1.0)  # restart boundary back at base
    assert min(lrs) < 0.2


def test_linear_warmup():
    s = LinearWarmup(1.0, warmup_steps=4, start_lr=0.0)
    lrs = [s.step() for _ in range(6)]
    np.testing.assert_allclose(lrs[:4], [0.25, 0.5, 0.75, 1.0])
    assert lrs[5] == 1.0


def test_warmup_cosine():
    s = WarmupCosineAnnealing(1.0, warmup_steps=2, total_steps=10)
    lrs = [s.step() for _ in range(10)]
    np.testing.assert_allclose(lrs[:2], [0.5, 1.0])
    assert lrs[-1] == pytest.approx(0.0, abs=1e-6)


def test_reduce_on_plateau():
    s = ReduceLROnPlateau(1.0, mode="min", factor=0.5, patience=1)
    assert s.step(1.0) == 1.0
    assert s.step(1.0) == 1.0   # bad epoch 1
    assert s.step(1.0) == 0.5   # bad epoch 2 > patience → decay
    assert s.step(0.5) == 0.5   # improvement resets


def test_polynomial_lr():
    s = PolynomialLR(1.0, total_steps=4, power=1.0)
    lrs = [s.step() for _ in range(5)]
    np.testing.assert_allclose(lrs, [0.75, 0.5, 0.25, 0.0, 0.0], atol=1e-7)


def test_one_cycle():
    s = OneCycleLR(max_lr=1.0, total_steps=10, pct_start=0.3)
    lrs = [s.step() for _ in range(10)]
    assert lrs[2] == pytest.approx(1.0)       # peak at end of up phase
    assert lrs[-1] < 0.01                      # annealed way down
    assert s.initial_lr == pytest.approx(1.0 / 25.0)


def test_scheduler_factory_roundtrip():
    scheds = [
        StepLR(0.1, 5, 0.5), MultiStepLR(0.1, [2, 6]), ExponentialLR(0.1, 0.9),
        CosineAnnealingLR(0.1, 20, 0.001), CosineAnnealingWarmRestarts(0.1, 5, 2),
        LinearWarmup(0.1, 10), WarmupCosineAnnealing(0.1, 5, 50),
        ReduceLROnPlateau(0.1), PolynomialLR(0.1, 100, 2.0),
        OneCycleLR(0.1, 100),
    ]
    for s in scheds:
        clone = SchedulerFactory.create_from_config(s.get_config())
        assert clone.get_config() == s.get_config()
    assert len({type(s) for s in scheds}) == 10  # all ten reference families
