"""Alert/recording-rule tests: the pending→firing→resolved state machine
under a fake clock (no sleeps), the three rule kinds, for_s hold
windows, exposition + healthz integration, and the alert_firing flight
trigger (dcnn_tpu/obs/rules.py)."""

import json
import os

import pytest

from dcnn_tpu.obs.flight import FlightRecorder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.obs.rules import AlertRule, RecordingRule, RuleEngine, \
    rules_check
from dcnn_tpu.obs.server import TelemetryServer
from dcnn_tpu.obs.tsdb import TimeSeriesStore


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_engine(fc, **kw):
    store = TimeSeriesStore(clock=fc)
    reg = kw.pop("registry", None) or MetricsRegistry(clock=fc)
    return RuleEngine(store, registry=reg, clock=fc, **kw), store, reg


def drive(fc, store, eng, series, value, ticks, dt=1.0):
    out = []
    for _ in range(ticks):
        fc.advance(dt)
        if value is not None:
            store.add(series, value)
        out.extend(eng.evaluate())
    return out


# ----------------------------------------------------------- state machine

def test_threshold_pending_firing_resolved_edges():
    """The full life of one alert, with for_s hold: breach -> pending,
    held past for_s -> firing (one fired edge), clear -> inactive (one
    resolved edge) — all under the fake clock."""
    fc = FakeClock()
    eng, store, reg = make_engine(fc)
    eng.add_alert(name="p99", series="p99_ms", op=">", threshold=200.0,
                  for_s=3.0, window_s=60.0)
    assert drive(fc, store, eng, "p99_ms", 100.0, 3) == []
    trs = drive(fc, store, eng, "p99_ms", 500.0, 1)
    assert [(t["from"], t["to"]) for t in trs] == [("inactive", "pending")]
    pending_t = trs[0]["t"]
    trs = drive(fc, store, eng, "p99_ms", 500.0, 5)
    fired = [t for t in trs if t["to"] == "firing"]
    assert len(fired) == 1
    # fires within the for_s budget (+ one evaluation tick of slack)
    assert eng.alerts()[0]["state"] == "firing"
    assert fired[0]["t"] - pending_t == pytest.approx(3.0, abs=1.0)
    trs = drive(fc, store, eng, "p99_ms", 50.0, 1)
    assert [(t["from"], t["to"]) for t in trs] == [("firing", "inactive")]
    snap = reg.snapshot()
    assert snap["alerts_fired_total"] == 1
    assert snap["alerts_resolved_total"] == 1
    assert snap["alerts_firing"] == 0
    # alert_state history rode the tsdb: 0 -> 1 -> 2 -> 0
    states = [v for _, v in store.range('alert_state{rule="p99"}', 100.0)]
    assert 1 in states and 2 in states and states[-1] == 0


def test_short_spike_never_fires():
    """A breach shorter than for_s stays pending and ages out — the hold
    window IS the page-noise filter."""
    fc = FakeClock()
    eng, store, reg = make_engine(fc)
    eng.add_alert(name="p99", series="p99_ms", op=">", threshold=200.0,
                  for_s=5.0, window_s=60.0)
    drive(fc, store, eng, "p99_ms", 100.0, 2)
    trs = drive(fc, store, eng, "p99_ms", 500.0, 3)   # 3 s < for_s
    assert [t["to"] for t in trs] == ["pending"]
    trs = drive(fc, store, eng, "p99_ms", 100.0, 3)
    assert [(t["from"], t["to"]) for t in trs] == [("pending", "inactive")]
    assert reg.snapshot()["alerts_fired_total"] == 0


def test_for_s_zero_fires_immediately():
    fc = FakeClock()
    eng, store, _ = make_engine(fc)
    eng.add_alert(name="hot", series="g", op=">=", threshold=1.0,
                  for_s=0.0, window_s=10.0)
    trs = drive(fc, store, eng, "g", 2.0, 1)
    assert [t["to"] for t in trs] == ["firing"]


def test_rate_rule():
    """kind=rate compares the per-second increase — 'errors are
    climbing' without precomputing a gauge."""
    fc = FakeClock()
    eng, store, _ = make_engine(fc)
    eng.add_alert(name="err_rate", series="errors_total", kind="rate",
                  op=">", threshold=2.0, for_s=0.0, window_s=10.0)
    t = [0.0]
    for i in range(5):                      # +1/s: healthy
        fc.advance(1.0)
        t[0] += 1.0
        store.add("errors_total", t[0])
        assert eng.evaluate() == []
    for i in range(5):                      # +10/s: breach
        fc.advance(1.0)
        t[0] += 10.0
        store.add("errors_total", t[0])
    trs = eng.evaluate()
    assert [t_["to"] for t_ in trs] == ["firing"]
    assert eng.alerts()[0]["value"] > 2.0


def test_absence_rule():
    """kind=absence fires when a series goes stale — the half-dead
    scrape target the PR 11 lesson demands stays visible."""
    fc = FakeClock()
    eng, store, _ = make_engine(fc)
    eng.add_alert(name="target_gone", series="up", kind="absence",
                  window_s=5.0, for_s=0.0)
    # never-seen series is absent from the start
    fc.advance(1.0)
    assert [t["to"] for t in eng.evaluate()] == ["firing"]
    store.add("up", 1.0)
    assert [(t["from"], t["to"]) for t in eng.evaluate()] \
        == [("firing", "inactive")]
    # fresh samples keep it quiet; staleness past window_s re-fires
    drive(fc, store, eng, "up", 1.0, 4)
    assert eng.alerts()[0]["state"] == "inactive"
    trs = drive(fc, store, eng, None, None, 7)
    assert [t["to"] for t in trs] == ["firing"]
    assert eng.alerts()[0]["value"] > 5.0   # the observed staleness age


def test_no_data_is_not_a_threshold_breach():
    fc = FakeClock()
    eng, store, _ = make_engine(fc)
    eng.add_alert(name="p99", series="p99_ms", op=">", threshold=1.0,
                  for_s=0.0, window_s=10.0)
    fc.advance(1.0)
    assert eng.evaluate() == []
    assert eng.alerts()[0]["state"] == "inactive"


def test_quantile_fn_threshold_rule():
    """A threshold rule over quantile_over_time: the honest windowed p99
    straight from histogram buckets."""
    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    store = TimeSeriesStore(clock=fc)
    eng = RuleEngine(store, registry=reg, clock=fc)
    eng.add_alert(name="lat_p99", series="lat_seconds",
                  fn="quantile_over_time", q=0.99, op=">",
                  threshold=0.05, for_s=0.0, window_s=20.0)
    h = reg.histogram("lat_seconds", start=1e-3, factor=2.0, buckets=12)
    from dcnn_tpu.obs.tsdb import TsdbSampler
    sampler = TsdbSampler(store, registry=reg, clock=fc)
    sampler.add_after_sample(eng.evaluate)
    for _ in range(5):
        fc.advance(1.0)
        h.observe(0.002)
        sampler.sample_once()
    assert eng.alerts()[0]["state"] == "inactive"
    for _ in range(3):
        fc.advance(1.0)
        h.observe(0.2)
        sampler.sample_once()
    assert eng.alerts()[0]["state"] == "firing"


# -------------------------------------------------------- recording rules

def test_recording_rule_writes_series():
    fc = FakeClock()
    eng, store, _ = make_engine(fc)
    eng.add_recording(name="req_rate", series="reqs_total", fn="rate",
                      window_s=10.0)
    for i in range(6):
        fc.advance(1.0)
        store.add("reqs_total", 7.0 * (i + 1))
        eng.evaluate()
    assert store.latest("req_rate")[1] == pytest.approx(7.0)
    # recorded series are alertable like any other
    eng.add_alert(name="hot", series="req_rate", op=">", threshold=5.0,
                  for_s=0.0, window_s=10.0)
    fc.advance(1.0)
    store.add("reqs_total", 7.0 * 7)
    assert any(t["to"] == "firing" for t in eng.evaluate())


def test_broken_rule_counted_not_fatal():
    fc = FakeClock()
    eng, store, reg = make_engine(fc)
    eng.add_recording(RecordingRule(name="r", series="x", fn="rate",
                                    window_s=10.0))
    # a rule whose query raises must not kill the pass
    eng.add_alert(name="bad", series="h", fn="quantile_over_time",
                  q=0.99, op=">", threshold=1.0, window_s=10.0)
    store.quantile_over_time = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("boom"))
    fc.advance(1.0)
    assert eng.evaluate() == []
    assert reg.snapshot()["alert_eval_errors_total"] >= 1
    assert eng.alerts()[0]["last_error"] == "RuntimeError: boom"


# -------------------------------------------------- exposition + healthz

def test_prometheus_lines_and_metrics_text():
    fc = FakeClock()
    eng, store, reg = make_engine(fc)
    eng.add_alert(name="a", series="g", op=">", threshold=1.0,
                  for_s=0.0, window_s=10.0)
    eng.add_alert(name="b", series="g", op=">", threshold=100.0,
                  for_s=0.0, window_s=10.0)
    drive(fc, store, eng, "g", 5.0, 1)
    lines = eng.prometheus_lines()
    assert lines[0] == "# TYPE alert_state gauge"
    assert 'alert_state{rule="a"} 2' in lines
    assert 'alert_state{rule="b"} 0' in lines
    text = eng.metrics_text(reg.prometheus)()
    assert 'alert_state{rule="a"} 2' in text
    # the wrapped text still parses under the shared exposition parser
    from dcnn_tpu.obs.exposition import parse_prometheus_text
    fams = parse_prometheus_text(text)
    samples = dict()
    for labels, v in fams["alert_state"]["samples"]:
        samples[labels["rule"]] = v
    assert samples == {"a": 2.0, "b": 0.0}


def test_rules_check_degrades_healthz_with_rule_name():
    fc = FakeClock()
    eng, store, reg = make_engine(fc)
    eng.add_alert(name="queue_deep", series="depth", op=">",
                  threshold=10.0, for_s=0.0, window_s=10.0)
    srv = TelemetryServer(registry=reg, clock=fc)
    srv.add_check("alerts", rules_check(eng))
    code, body = srv.health()
    assert code == 200
    drive(fc, store, eng, "depth", 50.0, 1)
    code, body = srv.health()
    assert code == 503
    assert any("queue_deep" in r for r in body["reasons"])
    drive(fc, store, eng, "depth", 1.0, 1)
    assert srv.health()[0] == 200


# ------------------------------------------------------- flight integration

def test_alert_firing_flight_bundle_carries_window(tmp_path):
    """The firing edge dumps ONE alert_firing bundle with the rule, the
    observed value, and the offending series' recent window — plus the
    store's full history.jsonl when attached."""
    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    store = TimeSeriesStore(clock=fc)
    fl = FlightRecorder(str(tmp_path), registry=reg, clock=fc,
                        min_interval_s=0.0).attach_tsdb(store)
    eng = RuleEngine(store, registry=reg, flight=fl, clock=fc)
    eng.add_alert(name="p99", series="p99_ms", op=">", threshold=200.0,
                  for_s=2.0, window_s=60.0,
                  description="latency SLO")
    drive(fc, store, eng, "p99_ms", 100.0, 5)
    drive(fc, store, eng, "p99_ms", 900.0, 4)
    assert eng.alerts()[0]["state"] == "firing"
    bundles = fl.bundles()
    assert [b["trigger"] for b in bundles] == ["alert_firing"]
    bpath = bundles[0]["path"]
    cfg = json.load(open(os.path.join(bpath, "config.json")))
    assert cfg["rule"] == "p99" and cfg["threshold"] == 200.0
    extra = json.load(open(os.path.join(bpath, "extra.json")))
    assert extra["value"] == 900.0
    window_vals = [v for _, v in extra["window"]]
    assert 100.0 in window_vals and 900.0 in window_vals  # pre-trigger
    assert os.path.isfile(os.path.join(bpath, "history.jsonl"))
    # firing again after resolve dumps a second bundle, not per-tick spam
    drive(fc, store, eng, "p99_ms", 900.0, 5)
    assert len(fl.bundles()) == 1


# ------------------------------------------------------------- validation

def test_rule_validation():
    fc = FakeClock()
    eng, _, _ = make_engine(fc)
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", kind="weird")
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", op="!=")
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", fn="median")
    with pytest.raises(ValueError):
        AlertRule(name="x", series="s", for_s=-1)
    with pytest.raises(ValueError):
        RecordingRule(name="x", series="s", fn="nope")
    eng.add_alert(name="dup", series="s")
    with pytest.raises(ValueError):
        eng.add_alert(name="dup", series="s")
