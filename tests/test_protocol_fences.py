"""Regression tests for the protocol-fence defects PR 14's static
analysis surfaced (PR02 unfenced-stamp, PR01 annotation workflow):

- a straggler ABORT from an older recovery must not regress a stage
  worker's generation (un-fencing the dead batch's in-flight jobs) or
  roll back stage state a newer generation already rebuilt;
- a LOAD_REPORT straggler from a timed-out earlier round must not
  satisfy a later ``collect_load_reports`` join (the nonce round-trip
  the profiling/gather rounds already had).

Both run socket-free: the worker is driven through ``_dispatch`` with a
recording fake channel, the coordinator is assembled around a real
``Inbox`` with instant-echo fake stage channels.
"""

import collections

from dcnn_tpu.parallel.comm import Inbox
from dcnn_tpu.parallel.distributed_pipeline import (
    DistributedPipelineCoordinator)
from dcnn_tpu.parallel.worker import StageWorker


class RecordingChannel:
    def __init__(self):
        self.sent = []

    def send(self, cmd, meta=None, array=None, raw=None, **kw):
        self.sent.append((cmd, dict(meta or {})))


class FakeStage:
    """Just enough PipelineStage surface for ABORT / LOAD_REPORT arms."""

    def __init__(self):
        self.aborts = []
        self.load = self

    def abort(self, snap=None):
        self.aborts.append(snap)

    def report(self):
        return {"fwd_ms": 1.0}


def make_worker():
    w = StageWorker(port=0)
    w.coord = RecordingChannel()
    w.stage = FakeStage()
    return w


# ----------------------------------------------------------- ABORT gen --

def test_stale_abort_does_not_regress_generation():
    w = make_worker()
    w._dispatch("ABORT", {"gen": 3}, None, None)
    assert w.gen == 3
    assert w.coord.sent[-1] == ("ABORTED", {"stage_id": -1, "gen": 3})
    n_acks = len(w.coord.sent)
    n_aborts = len(w.stage.aborts)

    # straggler from an older recovery: dropped — gen unchanged, no
    # state rollback, no ack (the old drain has long moved on)
    w._dispatch("ABORT", {"gen": 2}, None, None)
    assert w.gen == 3
    assert len(w.coord.sent) == n_acks
    assert len(w.stage.aborts) == n_aborts

    # duplicate of the current generation: equally stale
    w._dispatch("ABORT", {"gen": 3}, None, None)
    assert w.gen == 3
    assert len(w.coord.sent) == n_acks

    # a genuinely newer abort still lands
    w._dispatch("ABORT", {"gen": 4}, None, None)
    assert w.gen == 4
    assert w.coord.sent[-1][0] == "ABORTED"
    assert len(w.stage.aborts) == n_aborts + 1


def test_genless_abort_still_advances():
    # legacy/defensive path: an ABORT with no gen key bumps by one
    w = make_worker()
    w.gen = 5
    w._dispatch("ABORT", {}, None, None)
    assert w.gen == 6


def test_stale_job_stays_fenced_after_stale_abort():
    """The actual hazard: before the fix, a stale ABORT regressed
    ``gen``, so a FORWARD_JOB straggler of the dead batch passed the
    ``gen < current`` fence and poisoned residuals."""
    w = make_worker()
    w._dispatch("ABORT", {"gen": 3}, None, None)
    w._dispatch("ABORT", {"gen": 1}, None, None)   # straggler, dropped
    assert w.gen == 3
    # a gen-1 job from the dead batch must still be fenced out (it would
    # hit FakeStage and blow up on .batch_open if dispatched)
    w._dispatch("FORWARD_JOB", {"gen": 1, "mb_id": 0}, None, None)
    assert all(c != "FORWARD_RESULT" for c, _ in w.coord.sent)


# -------------------------------------------------- LOAD_REPORT nonce --

def test_worker_echoes_load_report_nonce():
    w = make_worker()
    w._dispatch("LOAD_REPORT_REQUEST", {"nonce": 42}, None, None)
    cmd, meta = w.coord.sent[-1]
    assert cmd == "LOAD_REPORT"
    assert meta["nonce"] == 42
    assert meta["report"] == {"fwd_ms": 1.0}


class EchoStageChannel:
    """A stage channel whose worker replies instantly into the inbox."""

    def __init__(self, inbox, stage_id, report):
        self.inbox = inbox
        self.stage_id = stage_id
        self.report = report

    def send(self, cmd, meta=None, array=None, raw=None, **kw):
        assert cmd == "LOAD_REPORT_REQUEST"
        self.inbox.post("LOAD_REPORT",
                        {"stage_id": self.stage_id,
                         "nonce": (meta or {}).get("nonce"),
                         "report": self.report})


def make_coordinator(n_stages, reports):
    c = object.__new__(DistributedPipelineCoordinator)
    c.inbox = Inbox()
    c._deferred = collections.deque()
    c.chans = [EchoStageChannel(c.inbox, i, reports[i])
               for i in range(n_stages)]
    c.num_stages = n_stages
    c.timeout = 5.0
    c._gen = 0
    return c


def test_stale_load_report_is_fenced():
    fresh = [{"fwd_ms": 10.0}, {"fwd_ms": 20.0}]
    c = make_coordinator(2, fresh)
    # a straggler from a timed-out earlier round sits in the inbox ahead
    # of everything the new round will produce
    c.inbox.post("LOAD_REPORT", {"stage_id": 0, "nonce": 12345,
                                 "report": {"fwd_ms": 999.0}})
    got = c.collect_load_reports()
    # the stale table must not displace stage 0's fresh reply
    assert got == fresh
    # and the armed nonce is cleared after the round
    assert c._load_nonce is None


def test_nonceless_load_report_is_fenced_too():
    # a reply predating the nonce protocol (meta lacks the key) must
    # also be dropped, not treated as matching None mid-round
    fresh = [{"fwd_ms": 10.0}]
    c = make_coordinator(1, fresh)
    c.inbox.post("LOAD_REPORT", {"stage_id": 0,
                                 "report": {"fwd_ms": 999.0}})
    assert c.collect_load_reports() == fresh


# ------------------------------------- replica error-frame conformance --

def test_replica_server_handler_exception_replies_error_not_teardown():
    """A handler exception is one request's failure: the server must
    reply a typed 'error' frame and keep serving the channel, not unwind
    the reader (which failed every in-flight request of that router
    connection)."""
    from dcnn_tpu.parallel.comm import ChannelClosed
    from dcnn_tpu.serve.replica import ReplicaServer

    class BrokenReplica:
        name = "broken"

        def stats(self):
            raise RuntimeError("stats backend exploded")

    class ScriptedChannel:
        def __init__(self, frames):
            self.frames = list(frames)
            self.sent = []

        def recv(self):
            if not self.frames:
                raise ChannelClosed("done")
            return self.frames.pop(0)

        def send(self, cmd, meta=None, array=None, **kw):
            self.sent.append((cmd, dict(meta or {})))

        def close(self):
            pass

    srv = ReplicaServer(BrokenReplica())
    try:
        ch = ScriptedChannel([
            ("stats", {"id": 7}, None),
            ("stats", {"id": 8}, None),   # channel must still be alive
        ])
        srv._serve(ch)
        errors = [(c, m) for c, m in ch.sent if c == "error"]
        assert [m["id"] for _c, m in errors] == [7, 8]
        assert all(m["etype"] == "RuntimeError" for _c, m in errors)
    finally:
        srv.close()


def test_tcp_replica_error_frame_resolves_stats_future():
    """An error reply carrying a stats id must fail the stats future
    typed — before the fix it was left to strand for its full timeout."""
    import threading

    from dcnn_tpu.serve.replica import ReplicaError, TcpReplica
    from concurrent.futures import Future

    r = object.__new__(TcpReplica)
    r._lock = threading.Lock()
    r._pending = {}
    r._swaps = {}
    r._stats = {}
    fut = Future()
    r._stats[7] = fut
    r._on_error({"id": 7, "etype": "RuntimeError", "emsg": "boom",
                 "dead": False})
    assert not r._stats
    try:
        fut.result(timeout=0)
        raise AssertionError("stats future should have failed typed")
    except ReplicaError as e:
        assert "boom" in str(e)
