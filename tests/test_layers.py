"""Layer-semantics tests.

Reference analog: the per-layer gtest suites (conv2d_layer_test.cpp:23-60
fixture pattern — analytic output shapes, hand-computed values, gradient
checks; SURVEY.md §4.3).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.nn import (
    ActivationLayer, AvgPool2DLayer, BatchNormLayer, Conv2DLayer, DenseLayer,
    DropoutLayer, FlattenLayer, GroupNormLayer, MaxPool2DLayer, ResidualBlock,
)
from dcnn_tpu.nn.layers import LogSoftmaxLayer


KEY = jax.random.PRNGKey(0)


def test_conv2d_layer_shapes_and_init():
    layer = Conv2DLayer(8, 3, stride=2, padding=1)
    assert layer.output_shape((3, 32, 32)) == (8, 16, 16)
    params, state = layer.init(KEY, (3, 32, 32))
    assert params["w"].shape == (8, 3, 3, 3)
    assert params["b"].shape == (8,)
    assert state == {}
    # Kaiming-uniform bound = 1/sqrt(fan_in) (conv2d_layer.tpp:71-72)
    bound = 1.0 / np.sqrt(3 * 3 * 3)
    w = np.asarray(params["w"])
    assert w.min() >= -bound and w.max() <= bound
    assert w.std() > bound / 4  # actually filled, not zeros

    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 32, 32))
    y, _ = layer.apply(params, state, x)
    assert y.shape == (2, 8, 16, 16)


def test_conv2d_channel_mismatch_raises():
    layer = Conv2DLayer(8, 3, in_channels=4)
    with pytest.raises(ValueError):
        layer.init(KEY, (3, 8, 8))


def test_dense_layer():
    layer = DenseLayer(16)
    params, state = layer.init(KEY, (10,))
    assert params["w"].shape == (16, 10)
    x = jnp.ones((4, 10))
    y, _ = layer.apply(params, state, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(x) @ np.asarray(params["w"]).T + np.asarray(params["b"]),
        rtol=1e-5)
    with pytest.raises(ValueError):
        DenseLayer(4).init(KEY, (3, 8, 8))  # needs flatten first


def test_batchnorm_layer_state_threading():
    layer = BatchNormLayer()
    params, state = layer.init(KEY, (4, 6, 6))
    x = jax.random.normal(jax.random.PRNGKey(2), (8, 4, 6, 6)) * 3.0 + 1.0
    y, new_state = layer.apply(params, state, x, training=True)
    # normalized output: per-channel mean ~0, var ~1
    m = np.asarray(y).mean(axis=(0, 2, 3))
    v = np.asarray(y).var(axis=(0, 2, 3))
    np.testing.assert_allclose(m, 0.0, atol=1e-5)
    np.testing.assert_allclose(v, 1.0, atol=1e-3)
    # running stats moved toward batch stats
    assert not np.allclose(np.asarray(new_state["running_mean"]), 0.0)
    # eval mode leaves state untouched
    y2, state2 = layer.apply(params, new_state, x, training=False)
    np.testing.assert_array_equal(np.asarray(state2["running_mean"]),
                                  np.asarray(new_state["running_mean"]))


def test_groupnorm_layer():
    layer = GroupNormLayer(num_groups=2)
    params, state = layer.init(KEY, (4, 5, 5))
    x = jax.random.normal(jax.random.PRNGKey(3), (3, 4, 5, 5))
    y, _ = layer.apply(params, state, x)
    assert y.shape == x.shape


def test_pool_layers():
    mp = MaxPool2DLayer(2)  # stride defaults to kernel (reference semantics)
    assert mp.output_shape((3, 8, 8)) == (3, 4, 4)
    ap = AvgPool2DLayer(3, stride=2, padding=1)
    assert ap.output_shape((3, 8, 8)) == (3, 4, 4)
    x = jnp.arange(16.0).reshape(1, 1, 4, 4)
    y, _ = mp.apply({}, {}, x)
    np.testing.assert_array_equal(np.asarray(y).reshape(2, 2), [[5, 7], [13, 15]])


def test_dropout_layer():
    layer = DropoutLayer(0.5)
    x = jnp.ones((100, 100))
    y_eval, _ = layer.apply({}, {}, x, training=False)
    np.testing.assert_array_equal(np.asarray(y_eval), np.asarray(x))
    y_train, _ = layer.apply({}, {}, x, training=True, rng=jax.random.PRNGKey(0))
    arr = np.asarray(y_train)
    assert set(np.unique(arr)) <= {0.0, 2.0}  # inverted dropout scaling
    assert abs(arr.mean() - 1.0) < 0.05
    with pytest.raises(ValueError):
        layer.apply({}, {}, x, training=True, rng=None)


def test_flatten_and_activation():
    fl = FlattenLayer()
    assert fl.output_shape((3, 4, 5)) == (60,)
    x = jax.random.normal(KEY, (2, 3, 4, 5))
    y, _ = fl.apply({}, {}, x)
    assert y.shape == (2, 60)

    act = ActivationLayer("leaky_relu", negative_slope=0.1)
    y, _ = act.apply({}, {}, jnp.asarray([-1.0, 2.0]))
    np.testing.assert_allclose(np.asarray(y), [-0.1, 2.0], rtol=1e-6)

    ls = LogSoftmaxLayer()
    y, _ = ls.apply({}, {}, jnp.zeros((1, 4)))
    np.testing.assert_allclose(np.asarray(y), np.log(0.25), rtol=1e-5)


def test_residual_block_identity_and_projection():
    # identity shortcut: same channels, stride 1
    block = ResidualBlock(
        layers=[Conv2DLayer(4, 3, 1, 1, name="c0"), BatchNormLayer(name="b0"),
                ActivationLayer("relu", name="r0"),
                Conv2DLayer(4, 3, 1, 1, name="c1"), BatchNormLayer(name="b1")],
        shortcut=[], activation="relu")
    params, state = block.init(KEY, (4, 8, 8))
    x = jax.random.normal(jax.random.PRNGKey(5), (2, 4, 8, 8))
    y, new_state = block.apply(params, state, x, training=True)
    assert y.shape == (2, 4, 8, 8)
    assert np.asarray(y).min() >= 0.0  # final relu

    # projection shortcut required when shapes change
    block2 = ResidualBlock(
        layers=[Conv2DLayer(8, 3, 2, 1, name="c0"), BatchNormLayer(name="b0")],
        shortcut=[Conv2DLayer(8, 1, 2, 0, use_bias=False, name="p"),
                  BatchNormLayer(name="pb")])
    p2, s2 = block2.init(KEY, (4, 8, 8))
    y2, _ = block2.apply(p2, s2, x)
    assert y2.shape == (2, 8, 4, 4)

    # mismatched main/shortcut shapes must raise
    bad = ResidualBlock(layers=[Conv2DLayer(8, 3, 2, 1)], shortcut=[])
    with pytest.raises(ValueError):
        bad.init(KEY, (4, 8, 8))


def test_residual_block_grad_flows():
    block = ResidualBlock(
        layers=[Conv2DLayer(4, 3, 1, 1, name="c0"), BatchNormLayer(name="b0")],
        shortcut=[])
    params, state = block.init(KEY, (4, 6, 6))
    x = jax.random.normal(jax.random.PRNGKey(7), (2, 4, 6, 6))

    def loss(p):
        y, _ = block.apply(p, state, x, training=True)
        return jnp.sum(y * y)

    grads = jax.grad(loss)(params)
    gw = np.asarray(grads["main"][0]["w"])
    assert np.abs(gw).max() > 0.0
