"""Utils tests: compression wire format, env config, hardware info
(reference ``include/utils/`` + ``include/pipeline/compression_impl/``;
SURVEY.md §2.5)."""

import numpy as np
import pytest

from dcnn_tpu.utils.compression import (
    MetaCompressor, RawCompressor, ZlibCompressor,
)
from dcnn_tpu.utils.env import get_env, load_env_file
from dcnn_tpu.utils.hardware import HardwareInfo, get_memory_usage_kb


# -- compression (meta_compressor.hpp:10-35 codec-id framing) --

def test_meta_compressor_roundtrip_all_codecs():
    mc = MetaCompressor()
    payload = bytes(range(256)) * 100
    for codec in mc.codecs.values():
        blob = mc.compress(payload, codec)
        assert blob[0] == codec.codec_id          # wire: 1-byte codec id
        assert mc.decompress(blob) == payload     # dispatch by id


def test_meta_compressor_cross_codec_decompress():
    """A blob compressed with any registered codec decompresses through the
    SAME MetaCompressor regardless of its default — the codec id on the wire
    decides (the worker-deployment contract for mixed-codec peers)."""
    zl = MetaCompressor(default=ZlibCompressor())
    raw = MetaCompressor(default=RawCompressor())
    payload = b"activation bytes" * 512
    assert raw.decompress(zl.compress(payload)) == payload
    assert zl.decompress(raw.compress(payload)) == payload


def test_meta_compressor_unknown_codec():
    mc = MetaCompressor()
    blob = bytearray(mc.compress(b"x" * 64))
    blob[0] = 250
    with pytest.raises(ValueError, match="unknown codec"):
        mc.decompress(bytes(blob))


@pytest.mark.parametrize("dtype", [np.float32, np.int64, np.uint8])
def test_array_framing_roundtrip(dtype):
    """Tensor framing (binary_serializer.hpp:27-35: rank + dims + data)."""
    mc = MetaCompressor()
    arr = (np.arange(2 * 3 * 4) % 7).astype(dtype).reshape(2, 3, 4)
    back = mc.decompress_array(mc.compress_array(arr))
    assert back.dtype == arr.dtype and back.shape == arr.shape
    np.testing.assert_array_equal(back, arr)


# -- env config (env.hpp:41-140) --

def test_load_env_file_parsing(tmp_path, monkeypatch):
    p = tmp_path / ".env"
    p.write_text("# comment\n\nA_KEY = 42\nB_KEY='quoted value'\n"
                 "C_KEY=\"dq\"\nmalformed line\n")
    # setenv-then-delenv records the keys' original absence on monkeypatch's
    # restore stack, so the direct os.environ writes load_env_file makes are
    # cleaned up at teardown instead of leaking into later tests
    for k in ("A_KEY", "B_KEY", "C_KEY"):
        monkeypatch.setenv(k, "placeholder")
        monkeypatch.delenv(k)
    assert load_env_file(str(p)) is True
    assert get_env("A_KEY", 0) == 42
    assert get_env("B_KEY", "") == "quoted value"
    assert get_env("C_KEY", "") == "dq"
    # no-override semantics: existing env wins unless override=True
    monkeypatch.setenv("A_KEY", "7")
    load_env_file(str(p))
    assert get_env("A_KEY", 0) == 7
    load_env_file(str(p), override=True)
    assert get_env("A_KEY", 0) == 42


def test_load_env_file_missing():
    assert load_env_file("/nonexistent/.env") is False


def test_get_env_typed(monkeypatch):
    monkeypatch.setenv("X_INT", "5")
    monkeypatch.setenv("X_FLOAT", "2.5")
    monkeypatch.setenv("X_BOOL", "YES")
    monkeypatch.setenv("X_BAD", "notanint")
    assert get_env("X_INT", 0) == 5
    assert get_env("X_FLOAT", 0.0) == 2.5
    assert get_env("X_BOOL", False) is True
    assert get_env("MISSING_KEY", "fallback") == "fallback"
    with pytest.raises(ValueError):
        get_env("X_BAD", 0)
    # explicit cast wins over default-type parsing
    assert get_env("X_INT", 0, cast=float) == 5.0


# -- hardware info (hardware_info.hpp; slimmed per SURVEY §2.5) --

def test_hardware_info_collect_keys():
    info = HardwareInfo.collect()
    assert info["host"]["cpu_count"] >= 1
    assert info["host"]["ram_total_kb"] > 0
    assert isinstance(info["devices"], list) and info["devices"]
    assert info["default_backend"]
    assert get_memory_usage_kb() > 0


def test_hard_fence_tree_shapes_and_dtypes():
    """hard_fence must handle every leaf shape/dtype the framework fences:
    multi-leaf trees (single jitted probe), typed PRNG keys (extended dtype
    routed to the per-leaf path), bools/ints, scalars, empty leaves, and
    plain numpy leaves (review r5 regressions)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dcnn_tpu.core.fence import hard_fence

    hard_fence({})                                   # empty tree
    hard_fence(jnp.ones(3))                          # single leaf
    hard_fence({"a": jnp.ones(3), "b": jnp.zeros((2, 2)),
                "c": jnp.asarray(1), "d": jnp.asarray(True),
                "e": jnp.ones(0), "f": np.ones(2),
                "rng": jax.random.key(0),            # extended dtype
                "rngs": jax.random.split(jax.random.key(1), 3)})


def test_hard_fence_cross_device_tree():
    """Leaves committed to different devices fence without a jit
    mixed-device error (PipelineCoordinator.join's shape of tree)."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.fence import hard_fence

    devs = jax.devices()
    if len(devs) < 2:
        import pytest
        pytest.skip("needs 2 devices")
    tree = [jax.device_put(jnp.ones(3) * i, devs[i % len(devs)])
            for i in range(4)]
    hard_fence(tree)
