"""Unified observability subsystem tests (dcnn_tpu/obs/).

Contracts:

- registry: O(1) thread-safe recorders with EXACT totals under concurrent
  increments, get-or-create identity, snapshot dict + Prometheus text
  exposition (cumulative histogram buckets);
- tracer: no event lost, duplicated, or torn under many concurrent
  recording threads; exact timestamps/durations under an injected fake
  clock (sleep-free); bounded ring buffer evicting oldest-first; Chrome
  ``trace_event`` export that ``json.load`` accepts with labeled tracks;
  cross-thread begin/end spans; and a DISABLED hot path costing
  < 100 ns/span (the bound that makes always-on instrumentation of
  per-chunk/per-request paths acceptable);
- integrations: one enabled run over the real train / H2D-transfer /
  pipeline-stage / serve code paths lands all span families in ONE
  Chrome trace on their labeled tracks (the BENCH_OBS=1 acceptance shape
  in miniature);
- satellites: ``train.profiling.trace()`` unique-subdir + no-nesting
  contract; ``ServeMetrics`` Prometheus exposition over its registry
  backing.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import jax

from dcnn_tpu.obs import (MetricsRegistry,
                          configure, get_registry, get_tracer)
from dcnn_tpu.obs.tracer import Tracer, _NULL_SPAN

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture
def global_tracer_enabled():
    """Enable the process-global tracer for one test, restore the no-op
    state afterwards (other tests assert the disabled-path bound)."""
    t = configure(enabled=True)
    t.clear()
    yield t
    configure(enabled=False)
    t.clear()


# --------------------------------------------------------------- registry

def test_counter_gauge_histogram_basics():
    r = MetricsRegistry()
    c = r.counter("foo_total")
    c.inc()
    c.inc(3)
    assert c.value == 4 and isinstance(c.value, int)
    with pytest.raises(ValueError):
        c.inc(-1)
    g = r.gauge("depth")
    g.set(7)
    g.add(2)
    assert g.value == 9
    h = r.histogram("lat_seconds")
    for v in (1e-5, 1e-3, 0.5):
        h.observe(v)
    hv = h.value
    assert hv["count"] == 3
    assert hv["sum"] == pytest.approx(0.50101)
    assert hv["min"] == 1e-5 and hv["max"] == 0.5
    assert sum(hv["buckets"].values()) == 3  # all within bounds, no overflow
    assert hv["overflow"] == 0
    h.observe(1e9)  # beyond the last bound -> overflow bucket
    assert h.value["overflow"] == 1


def test_registry_get_or_create_identity_and_kind_collision():
    r = MetricsRegistry()
    assert r.counter("a") is r.counter("a")
    # span-style dotted names map to the same prometheus-legal instrument
    assert r.counter("h2d.bytes") is r.counter("h2d_bytes")
    with pytest.raises(ValueError):
        r.gauge("a")  # registered as Counter
    with pytest.raises(ValueError):
        r.counter("0bad name!")
    with pytest.raises(ValueError):
        r.counter("latencia_µ")  # Unicode alnum, but not Prometheus-legal


def test_registry_concurrent_increments_exact():
    r = MetricsRegistry()
    c = r.counter("hits_total")
    h = r.histogram("obs_seconds")
    N, T = 5000, 8

    def work():
        for _ in range(N):
            c.inc()
            h.observe(1e-3)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == N * T            # no lost increments
    assert h.value["count"] == N * T


def test_registry_snapshot_and_prometheus():
    fc = FakeClock()
    r = MetricsRegistry(clock=fc)
    r.counter("req_total", "requests").inc(5)
    r.gauge("depth").set(3)
    r.histogram("lat_seconds").observe(3e-6)
    fc.advance(2.0)
    s = r.snapshot()
    assert s["req_total"] == 5 and s["depth"] == 3
    assert s["lat_seconds"]["count"] == 1
    assert s["_wall_s"] == pytest.approx(2.0)
    json.dumps(s)  # machine-readable: the bench telemetry block embeds it

    text = r.prometheus()
    assert "# TYPE req_total counter" in text
    assert "# HELP req_total requests" in text
    assert "req_total 5" in text
    assert "# TYPE depth gauge" in text
    assert "# TYPE lat_seconds histogram" in text
    assert 'lat_seconds_bucket{le="+Inf"} 1' in text
    assert "lat_seconds_count 1" in text
    # buckets are CUMULATIVE: every bound >= 4e-6 reports the observation
    assert 'lat_seconds_bucket{le="4e-06"} 1' in text
    assert 'lat_seconds_bucket{le="1e-06"} 0' in text


def test_registry_reset_keeps_instrument_identity():
    r = MetricsRegistry()
    c = r.counter("x_total")
    c.inc(9)
    r.reset()
    assert c.value == 0
    assert r.counter("x_total") is c
    c.inc()
    assert r.snapshot()["x_total"] == 1


# ----------------------------------------------------------------- tracer

_ID_KEYS = ("trace_id", "span_id", "parent_id")


def _user_args(ev):
    """Span args minus the distributed-tracing identity keys (PR 12:
    every recorded span carries trace_id/span_id[/parent_id])."""
    return {k: v for k, v in ev["args"].items() if k not in _ID_KEYS}


def test_tracer_fake_clock_exact():
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    with t.span("a.work", track="x", k=1):
        fc.advance(0.25)
    fc.advance(1.0)
    with t.span("a.work", track="x"):
        fc.advance(0.5)
    evs = t.events()
    assert [e["name"] for e in evs] == ["a.work", "a.work"]
    assert evs[0]["ts_s"] == 0.0 and evs[0]["dur_s"] == 0.25
    assert evs[1]["ts_s"] == 1.25 and evs[1]["dur_s"] == 0.5
    assert evs[0]["track"] == "x"
    # user attrs intact; every span now also carries its trace identity
    assert _user_args(evs[0]) == {"k": 1}
    assert evs[0]["args"]["trace_id"] and evs[0]["args"]["span_id"]
    # the two spans are separate roots: distinct traces, no parent
    assert evs[0]["args"]["trace_id"] != evs[1]["args"]["trace_id"]
    assert "parent_id" not in evs[0]["args"]


def test_tracer_record_span_replay():
    """record_span replays externally-measured intervals (feed workers
    stamp phases in their own process; the parent lands them on per-worker
    tracks) — timestamps interpreted in the tracer's clock domain."""
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    t.record_span("feed.gather", 1.0, 1.5, track="feed-w3", shard=2)
    t.record_span("feed.pack", 2.0, 2.0, track="feed-w3")
    evs = t.events()
    assert evs[0] == {"name": "feed.gather", "ts_s": 1.0, "dur_s": 0.5,
                      "track": "feed-w3", "args": {"shard": 2}}
    assert evs[1]["dur_s"] == 0.0
    # disabled tracer: the swapped-in null fn records nothing
    t.set_enabled(False)
    t.record_span("feed.gather", 3.0, 4.0, track="feed-w3")
    assert len(t.events()) == 2


def test_tracer_cross_thread_begin_end():
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    h = t.begin("q.wait", track="queue", req=7)
    fc.advance(0.125)

    def closer():
        t.end(h, dispatched=True)

    th = threading.Thread(target=closer)
    th.start()
    th.join()
    (ev,) = t.events()
    assert ev["name"] == "q.wait" and ev["dur_s"] == 0.125
    # the event lands on the span's OWN track, not the closing thread's
    assert ev["track"] == "queue"
    assert _user_args(ev) == {"req": 7, "dispatched": True}


def test_tracer_concurrent_spans_none_lost_or_duplicated():
    t = Tracer(capacity=100_000, enabled=True)
    T, N = 8, 200

    def work(tid):
        for i in range(N):
            with t.span("w.op", track=f"t{tid}", tid=tid, i=i):
                pass

    threads = [threading.Thread(target=work, args=(k,)) for k in range(T)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    evs = t.events()
    assert len(evs) == T * N
    seen = {(e["args"]["tid"], e["args"]["i"]) for e in evs}
    assert len(seen) == T * N  # unique -> nothing duplicated, nothing torn


def test_tracer_ring_buffer_bounded_evicts_oldest():
    fc = FakeClock()
    t = Tracer(capacity=100, clock=fc, enabled=True)
    for i in range(250):
        with t.span("s", track="x", i=i):
            fc.advance(0.001)
    assert len(t) == 100
    kept = [e["args"]["i"] for e in t.events()]
    assert kept == list(range(150, 250))  # newest 100, oldest evicted


def test_tracer_instant_and_error_annotation():
    t = Tracer(enabled=True)
    t.instant("boom.mark", track="x", n=3)
    with pytest.raises(RuntimeError):
        with t.span("failing.op", track="x"):
            raise RuntimeError("nope")
    evs = t.events()
    assert evs[0]["dur_s"] is None and evs[0]["args"] == {"n": 3}
    assert evs[1]["args"]["error"] == "RuntimeError"  # span still recorded


def test_chrome_trace_schema(tmp_path):
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    with t.span("a.x", track="alpha", k=1):
        fc.advance(0.002)
    t.instant("a.mark", track="beta")
    path = t.export_chrome(str(tmp_path / "trace.json"))
    with open(path) as f:
        doc = json.load(f)  # Perfetto's minimum bar: valid JSON object form
    evs = doc["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    insts = [e for e in evs if e["ph"] == "i"]
    metas = [e for e in evs if e["ph"] == "M"]
    assert len(xs) == 1 and len(insts) == 1
    assert xs[0]["name"] == "a.x" and xs[0]["dur"] == pytest.approx(2000.0)
    assert xs[0]["ts"] == pytest.approx(0.0)
    assert {"pid", "tid", "cat", "args"} <= set(xs[0])
    assert insts[0]["s"] == "t"
    # labeled tracks: one thread_name metadata record per distinct track,
    # tids consistent between metadata and events
    names = {m["args"]["name"]: m["tid"] for m in metas
             if m["name"] == "thread_name"}
    assert set(names) == {"alpha", "beta"}
    assert xs[0]["tid"] == names["alpha"]
    assert insts[0]["tid"] == names["beta"]
    assert any(m["name"] == "process_name" for m in metas)


def test_jsonl_export_round_trip(tmp_path):
    t = Tracer(enabled=True)
    for i in range(5):
        with t.span("s", track="x", i=i):
            pass
    path = t.export_jsonl(str(tmp_path / "t.jsonl"))
    with open(path) as f:
        lines = [json.loads(l) for l in f]
    # line 1 is the shard header (merge-CLI metadata); events follow
    assert "shard" in lines[0] and lines[0]["shard"]["pid"] == os.getpid()
    events = lines[1:]
    assert [l["args"]["i"] for l in events] == list(range(5))
    assert all(l["dur_s"] >= 0 for l in events)


def test_disabled_tracer_is_noop_and_cheap():
    """THE hot-path bound: a disabled span() must cost < 100 ns, so
    always-on call sites (per H2D chunk, per serve request, per pipeline
    microbatch) are free in production. Measured net of loop overhead,
    min-of-reps (robust to scheduler noise, though not to a uniformly
    much slower host — the absolute bound is this subsystem's acceptance
    contract, with ~2x margin on the tier-1 container)."""
    tracer = get_tracer()
    was_enabled = tracer.enabled
    configure(enabled=False)
    try:
        # functional: everything no-ops, nothing records
        s = tracer.span("x", k=1)
        assert s is _NULL_SPAN
        with tracer.span("x"):
            pass
        h = tracer.begin("y")
        tracer.end(h)
        tracer.instant("z")
        assert len(tracer) == 0

        N = 50_000

        def loop_span():
            t0 = time.perf_counter()
            for _ in range(N):
                tracer.span("x")
            return time.perf_counter() - t0

        def loop_empty():
            t0 = time.perf_counter()
            for _ in range(N):
                pass
            return time.perf_counter() - t0

        # GC off + many short reps + min: a single CPython GC pass or a
        # scheduler preemption inside one rep must not fail the bound —
        # min-of-reps measures the uncontended cost, which is the quantity
        # the contract bounds
        import gc

        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            net = (min(loop_span() for _ in range(25))
                   - min(loop_empty() for _ in range(25))) / N
        finally:
            if gc_was_enabled:
                gc.enable()
        assert net < 100e-9, f"disabled span costs {net * 1e9:.0f} ns"
    finally:
        configure(enabled=was_enabled)


def test_configure_preserves_identity_and_capacity():
    t = get_tracer()
    assert configure(enabled=True) is t  # in-place: hoisted refs stay wired
    try:
        t.clear()
        for i in range(20):
            with t.span("s", i=i):
                pass
        configure(capacity=10)
        assert len(t) == 10  # newest kept
        assert [e["args"]["i"] for e in t.events()] == list(range(10, 20))
    finally:
        configure(enabled=False, capacity=65536)
        t.clear()


# --------------------------------------------- profiling.trace() satellite

def test_profiling_trace_unique_subdirs(tmp_path):
    from dcnn_tpu.train.profiling import trace

    parent = str(tmp_path / "xprof")
    with trace(parent) as d1:
        pass
    with trace(parent) as d2:
        pass
    assert d1 != d2, "back-to-back traces must not clobber each other"
    assert os.path.dirname(d1) == parent and os.path.dirname(d2) == parent
    assert os.path.isdir(d1) and os.path.isdir(d2)


def test_profiling_trace_nested_raises(tmp_path):
    from dcnn_tpu.train.profiling import trace

    with trace(str(tmp_path / "a")):
        with pytest.raises(RuntimeError, match="does not nest"):
            with trace(str(tmp_path / "b")):
                pass
    # the guard must release on exit — a fresh trace works again
    with trace(str(tmp_path / "c")) as d:
        assert os.path.isdir(d)


def test_profiling_trace_emits_obs_span(tmp_path, global_tracer_enabled):
    from dcnn_tpu.train.profiling import trace

    with trace(str(tmp_path / "x")) as d:
        pass
    evs = [e for e in global_tracer_enabled.events()
           if e["name"] == "profiler.xprof"]
    assert len(evs) == 1 and evs[0]["args"]["log_dir"] == d


# ------------------------------------------ ServeMetrics registry backing

def test_serve_metrics_prometheus_exposition():
    from dcnn_tpu.serve import ServeMetrics

    fc = FakeClock()
    m = ServeMetrics(clock=fc)
    m.record_submit(4)
    m.record_shed(1)
    m.record_batch(3, 4)
    m.record_done(0.010, 3)
    fc.advance(1.0)
    text = m.prometheus()
    assert "# TYPE serve_samples_submitted_total counter" in text
    assert "serve_samples_submitted_total 4" in text
    assert "serve_samples_shed_total 1" in text
    assert "# TYPE serve_latency_seconds histogram" in text
    assert "serve_latency_seconds_count 1" in text
    assert "serve_latency_window_p50_ms 10.0" in text
    assert "serve_shed_fraction 0.2" in text
    # snapshot contract untouched by the registry backing
    s = m.snapshot()
    assert s["requests_submitted"] == 4 and s["requests_shed"] == 1
    assert s["p50_ms"] == pytest.approx(10.0)


def test_serve_metrics_shared_registry_injection():
    from dcnn_tpu.serve import ServeMetrics

    reg = MetricsRegistry()
    m = ServeMetrics(registry=reg)
    m.record_submit(2)
    assert reg.snapshot()["serve_samples_submitted_total"] == 2
    # constructing a SECOND instance on the shared registry must not zero
    # the live series (counters never go backwards by accident)
    m2 = ServeMetrics(registry=reg)
    assert reg.snapshot()["serve_samples_submitted_total"] == 2
    assert m2.snapshot()["requests_submitted"] == 0  # per-instance view
    m.reset()  # explicit reset DOES zero the shared series
    assert reg.snapshot()["serve_samples_submitted_total"] == 0


# ----------------------------------------------- end-to-end labeled trace

def test_end_to_end_trace_all_subsystems(tmp_path, global_tracer_enabled):
    """The BENCH_OBS=1 acceptance shape in miniature: training steps, H2D
    chunk puts, pipeline stage microbatches, and serve enqueue→infer all
    recorded by ONE enabled run, exported to ONE Chrome trace that
    json.load accepts, each family on its labeled track."""
    import jax.numpy as jnp

    from dcnn_tpu.data.transfer import TransferEngine
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.parallel.pipeline import PipelineStage
    from dcnn_tpu.serve import DynamicBatcher, InferenceEngine

    tr = global_tracer_enabled

    # 1) training steps: a 2-batch epoch through the real Trainer loop
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import SyntheticClassificationLoader
    from dcnn_tpu.train.trainer import Trainer, create_train_state

    model = (SequentialBuilder(name="obs_e2e", data_format="NHWC")
             .input((4, 4, 1)).flatten().dense(5).build())
    cfg = TrainingConfig(epochs=1, batch_size=16, progress_interval=0)
    loader = SyntheticClassificationLoader(32, (4, 4, 1), 5, batch_size=16,
                                           seed=0)
    loader.load_data()
    trainer = Trainer(model, Adam(1e-3), "softmax_crossentropy", cfg)
    ts = create_train_state(model, trainer.optimizer, jax.random.PRNGKey(0))
    ts = trainer.fit(ts, loader, None, epochs=1)  # donated: use the return

    # 2) H2D chunk transfers
    with TransferEngine(num_chunks=3, num_threads=2) as eng:
        x = np.arange(6 * 4, dtype=np.float32).reshape(6, 4)
        eng.put_shard(x, np.arange(6, dtype=np.int32))

    # 3) pipeline stage forward/backward on its own track
    stage = PipelineStage(0, model, Adam(1e-3))
    stage.initialize(jax.random.PRNGKey(1), model.input_shape)
    y = stage.forward(0, jnp.zeros((2, 4, 4, 1), jnp.float32))
    stage.backward(0, jnp.ones_like(y))

    # 4) serve: enqueue -> dispatch -> infer through the real batcher
    engine = InferenceEngine.from_model(model, ts.params, ts.state,
                                        fold=False, max_batch=2,
                                        name="obs_e2e")
    b = DynamicBatcher(engine, max_batch=2, start=False)
    f = b.submit(np.zeros((4, 4, 1), np.float32))
    assert b.step() == 1
    f.result(timeout=5)
    b.drain()

    path = tr.export_chrome(str(tmp_path / "e2e.json"))
    with open(path) as fh:
        doc = json.load(fh)
    evs = doc["traceEvents"]
    spans = {e["name"] for e in evs if e["ph"] == "X"}
    assert {"train.epoch", "train.step", "h2d.gather", "h2d.put",
            "h2d.shard", "pipe.fwd", "pipe.bwd", "serve.queue",
            "serve.dispatch", "serve.infer",
            "serve.compile"} <= spans, spans
    tracks = {m["args"]["name"] for m in evs
              if m["ph"] == "M" and m["name"] == "thread_name"}
    assert {"train", "h2d", "stage0", "serve", "serve.queue"} <= tracks, tracks
    # registry rollups rode along
    snap = get_registry().snapshot()
    assert snap["h2d_bytes_total"] > 0
    assert snap["train_epochs_total"] >= 1


# ------------------------------------------------- example import smoke

def test_trace_training_example_imports():
    """Import smoke for examples/trace_training.py (same isolation dance as
    the serve_snapshot smoke: the examples dir must resolve its own
    `common`)."""
    import importlib

    ex_dir = os.path.join(REPO, "examples")
    saved_common = sys.modules.pop("common", None)
    sys.path.insert(0, ex_dir)
    try:
        mod = importlib.import_module("trace_training")
        assert callable(mod.main)
        assert callable(mod.train_traced)
        assert callable(mod.validate_chrome_trace)
    finally:
        sys.path.remove(ex_dir)
        sys.modules.pop("trace_training", None)
        sys.modules.pop("common", None)
        if saved_common is not None:
            sys.modules["common"] = saved_common
