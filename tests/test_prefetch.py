"""PrefetchLoader + parallel-decode tests (SURVEY.md §7 hard part 5;
reference input pipeline ``tiny_imagenet_data_loader.hpp:26-132``)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dcnn_tpu.data import ArrayDataLoader, PrefetchLoader


def _loader(n=32, batch=8):
    x = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    y = np.eye(2, dtype=np.float32)[np.arange(n) % 2]
    ld = ArrayDataLoader(x, y, batch_size=batch, shuffle=False)
    ld.load_data()
    return ld


def test_prefetch_yields_same_batches():
    inner = _loader()
    pf = PrefetchLoader(_loader(), depth=2)
    got = list(pf)
    want = list(inner)
    assert len(got) == len(want)
    for (gx, gy), (wx, wy) in zip(got, want):
        assert isinstance(gx, jax.Array)
        np.testing.assert_array_equal(np.asarray(gx), wx)
        np.testing.assert_array_equal(np.asarray(gy), wy)


def test_prefetch_multiple_epochs_and_passthroughs():
    pf = PrefetchLoader(_loader(), depth=2)
    assert pf.batch_size == 8
    assert pf.num_samples == 32
    assert len(pf) == 4
    pf.shuffle(1)  # must not raise
    for _ in range(3):
        assert len(list(pf)) == 4


def test_prefetch_early_break_no_deadlock():
    pf = PrefetchLoader(_loader(n=64, batch=8), depth=1)
    for i, _ in enumerate(pf):
        if i == 1:
            break
    # a second full iteration works (fresh producer thread per epoch)
    assert len(list(pf)) == 8


def test_prefetch_transform_hook():
    pf = PrefetchLoader(_loader(), depth=2,
                        transform=lambda x, y: (x * 2.0, y))
    inner = list(_loader())
    for (gx, _), (wx, _) in zip(pf, inner):
        np.testing.assert_array_equal(np.asarray(gx), wx * 2.0)


def test_prefetch_device_transform_uint8_feed():
    # the idiomatic TPU feed: ship uint8 + int labels, decode on device
    x = np.arange(16 * 4, dtype=np.uint8).reshape(16, 4)
    y = (np.arange(16) % 3).astype(np.int32)
    ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False)
    ld.load_data()
    decode = jax.jit(lambda xu, yi: (xu.astype(jnp.float32) / 255.0,
                                     jax.nn.one_hot(yi, 3)))
    pf = PrefetchLoader(ld, depth=2, device_transform=decode)
    gx, gy = next(iter(pf))
    assert gx.dtype == jnp.float32 and gy.shape == (8, 3)
    np.testing.assert_allclose(np.asarray(gx), x[:8] / 255.0, rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(gy).argmax(-1), y[:8])


def test_prefetch_propagates_producer_error():
    class Boom:
        batch_size = 4
        num_samples = 8

        def __iter__(self):
            yield (np.zeros((4, 2), np.float32), np.zeros((4, 2), np.float32))
            raise RuntimeError("decode failed")

    with pytest.raises(RuntimeError, match="decode failed"):
        list(PrefetchLoader(Boom(), depth=2))


def test_prefetch_chunked_staging():
    pf = PrefetchLoader(_loader(n=64, batch=8), depth=2, stage_batches=3)
    chunks = list(pf)
    # 8 batches in chunks of 3 -> [3, 3, 2]
    assert [c[0].shape[0] for c in chunks] == [3, 3, 2]
    flat_x = np.concatenate([np.asarray(c[0]).reshape(-1, 4) for c in chunks])
    want_x = np.concatenate([x for x, _ in _loader(n=64, batch=8)])
    np.testing.assert_array_equal(flat_x, want_x)


def test_prefetch_chunked_ragged_tail_batch():
    # 20 samples, batch 8, drop_last=False -> batches of 8, 8, 4. The ragged
    # 4-row batch can't stack with the 8-row ones: it must flush the full
    # chunk and ship separately instead of crashing np.stack.
    x = np.arange(20 * 4, dtype=np.float32).reshape(20, 4)
    y = np.eye(2, dtype=np.float32)[np.arange(20) % 2]
    ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False, drop_last=False)
    ld.load_data()
    chunks = list(PrefetchLoader(ld, depth=2, stage_batches=3))
    assert [(c[0].shape[0], c[0].shape[1]) for c in chunks] == [(2, 8), (1, 4)]
    flat_x = np.concatenate([np.asarray(c[0]).reshape(-1, 4) for c in chunks])
    np.testing.assert_array_equal(flat_x, x)


def test_prefetch_early_break_stops_producer():
    consumed = []

    class Tracking:
        batch_size = 4
        num_samples = 400

        def __iter__(self):
            for i in range(100):
                consumed.append(i)
                yield (np.zeros((4, 2), np.float32),
                       np.zeros((4, 2), np.float32))

    for i, _ in enumerate(PrefetchLoader(Tracking(), depth=1)):
        if i == 1:
            break
    # producer must stop near where the consumer broke (depth + a couple in
    # flight), not run out the remaining ~98 batches
    assert len(consumed) < 10


def test_prefetch_sharded_placement():
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("data",))
    sharding = NamedSharding(mesh, P("data"))
    pf = PrefetchLoader(_loader(n=16, batch=8), depth=2, sharding=sharding)
    x, y = next(iter(pf))
    assert x.sharding.is_equivalent_to(sharding, x.ndim)
    assert len(x.addressable_shards) == 4


def test_prefetch_stage_engine_device_transform_bit_identity():
    """The full combination — stage_batches>1 x transfer_engine x
    device_transform — must yield bit-identical arrays to the plain
    (no-engine) path: chunked shipment + on-device concat is pure data
    movement."""
    from dcnn_tpu.data import TransferEngine

    x = np.arange(40 * 4, dtype=np.uint8).reshape(40, 4)
    y = (np.arange(40) % 3).astype(np.int32)

    def mk():
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=True, seed=5)
        ld.load_data()
        return ld

    decode = jax.jit(lambda xu, yi: (xu.astype(jnp.float32) / 255.0,
                                     jax.nn.one_hot(yi, 3)))
    plain = list(PrefetchLoader(mk(), depth=2, stage_batches=2,
                                device_transform=decode))
    with TransferEngine(num_chunks=3, num_threads=2,
                        reassemble="concat") as eng:
        chunked = list(PrefetchLoader(mk(), depth=2, stage_batches=2,
                                      device_transform=decode,
                                      transfer_engine=eng))
    assert len(plain) == len(chunked) == 3  # 5 batches -> [2, 2, 1]
    for (px, py), (cx, cy) in zip(plain, chunked):
        np.testing.assert_array_equal(np.asarray(px), np.asarray(cx))
        np.testing.assert_array_equal(np.asarray(py), np.asarray(cy))


def test_prefetch_staged_engine_producer_error_propagates():
    """A producer-thread failure must reach the consumer through the
    staging + transfer-engine path too, not only the plain one."""
    from dcnn_tpu.data import TransferEngine

    class Boom:
        batch_size = 4
        num_samples = 16

        def __iter__(self):
            yield (np.zeros((4, 2), np.float32), np.zeros((4,), np.int32))
            yield (np.zeros((4, 2), np.float32), np.zeros((4,), np.int32))
            raise RuntimeError("gather exploded")

    with TransferEngine(num_chunks=2, num_threads=1,
                        reassemble="concat") as eng:
        with pytest.raises(RuntimeError, match="gather exploded"):
            list(PrefetchLoader(Boom(), depth=2, stage_batches=2,
                                transfer_engine=eng))


def test_prefetch_pooled_bit_identity_and_close():
    """feed_workers delegation yields bit-identical batches to the serial
    producer (no worker augment), across stage sizes and epochs."""
    x = np.arange(64 * 4, dtype=np.float32).reshape(64, 4)
    y = np.eye(2, dtype=np.float32)[np.arange(64) % 2]

    def mk():
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=True, seed=3)
        ld.load_data()
        return ld

    for stage in (1, 3):
        plain_pf = PrefetchLoader(mk(), depth=2, stage_batches=stage)
        pooled_pf = PrefetchLoader(mk(), depth=2, stage_batches=stage,
                                   feed_workers=2)
        with pooled_pf:
            for epoch in (0, 1):
                plain_pf.shuffle(epoch)
                pooled_pf.shuffle(epoch)
                plain = list(plain_pf)
                pooled = list(pooled_pf)
                assert len(plain) == len(pooled)
                for (px, py), (qx, qy) in zip(plain, pooled):
                    np.testing.assert_array_equal(np.asarray(px),
                                                  np.asarray(qx))
                    np.testing.assert_array_equal(np.asarray(py),
                                                  np.asarray(qy))
        pooled_pf.close()  # idempotent


def test_prefetch_pooled_ragged_tail_matches_plain():
    x = np.arange(20 * 4, dtype=np.float32).reshape(20, 4)
    y = np.eye(2, dtype=np.float32)[np.arange(20) % 2]

    def mk():
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False,
                             drop_last=False)
        ld.load_data()
        return ld

    plain = list(PrefetchLoader(mk(), depth=2, stage_batches=3))
    with PrefetchLoader(mk(), depth=2, stage_batches=3,
                        feed_workers=2) as pf:
        pooled = list(pf)
    assert ([tuple(c[0].shape[:2]) for c in pooled]
            == [tuple(c[0].shape[:2]) for c in plain] == [(2, 8), (1, 4)])
    for (px, _), (qx, _) in zip(plain, pooled):
        np.testing.assert_array_equal(np.asarray(px), np.asarray(qx))


def test_prefetch_pooled_worker_augment_deterministic():
    from dcnn_tpu.data import AugmentationBuilder

    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(64, 8, 8, 1), dtype=np.uint8)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    aug = AugmentationBuilder("NHWC").horizontal_flip(p=0.5).build()

    def run(workers):
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=True, seed=2)
        ld.load_data()
        with PrefetchLoader(ld, depth=2, stage_batches=2,
                            feed_workers=workers,
                            worker_augment=aug) as pf:
            return [(np.asarray(a).copy(), np.asarray(b).copy())
                    for a, b in pf]

    one, four = run(1), run(4)
    for (ax, ay), (bx, by) in zip(one, four):
        np.testing.assert_array_equal(ax, bx)
        np.testing.assert_array_equal(ay, by)


def test_prefetch_pooled_rejects_incompatible_hooks():
    x = np.zeros((16, 4), np.float32)
    y = np.zeros((16, 2), np.float32)
    ld = ArrayDataLoader(x, y, batch_size=4, shuffle=False,
                         augmentation=lambda b, r: b)
    ld.load_data()
    with pytest.raises(ValueError, match="transform"):
        PrefetchLoader(ld, feed_workers=2, transform=lambda a, b: (a, b))
    pf = PrefetchLoader(ld, feed_workers=2)
    with pytest.raises(ValueError, match="worker_augment"):
        list(pf)
    pf.close()

    class NoArrays:
        batch_size = 4
        num_samples = 8

        def __iter__(self):
            return iter([])

    pf = PrefetchLoader(NoArrays(), feed_workers=2)
    with pytest.raises(ValueError, match="BaseDataLoader-style"):
        list(pf)
    pf.close()


def test_parallel_decode_matches_serial(tmp_path):
    PIL = pytest.importorskip("PIL")
    from PIL import Image

    from dcnn_tpu.data.tiny_imagenet import _decode_image, _decode_many

    rng = np.random.default_rng(0)
    paths = []
    for i in range(72):  # >64 so the thread-pool path runs, not the fallback
        arr = rng.integers(0, 256, size=(16, 16, 3), dtype=np.uint8)
        p = str(tmp_path / f"img{i}.png")  # png = lossless, exact compare
        Image.fromarray(arr).save(p)
        paths.append(p)
    serial = [_decode_image(p) for p in paths]
    parallel = _decode_many(paths)
    for s, p in zip(serial, parallel):
        np.testing.assert_array_equal(s, p)
