"""Native kernels under AddressSanitizer + UBSan (slow tier).

``native/build_sanitized.sh`` compiles ``src/*.cpp`` with
``-fsanitize=address,undefined -fno-sanitize-recover=all`` together with
the standalone round-trip driver (``sanitize/main.cpp``: gather, byte
shuffle, LZ4 greedy+HC, dataio decode/parse — each with its reject-path
edges). One passing run means none of those paths touched memory out of
bounds or hit UB; the driver's own value checks also ran.

Skips cleanly (never fails) when the host has no C++ compiler or ships
g++ without the sanitizer runtimes — the build script signals that with
exit code 2.
"""

import os
import subprocess
import sys

import pytest

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "dcnn_tpu", "native")
_SCRIPT = os.path.join(_NATIVE_DIR, "build_sanitized.sh")


@pytest.mark.slow
def test_native_round_trips_under_sanitizers(tmp_path):
    if sys.platform == "win32":
        pytest.skip("bash build script; POSIX only")
    out = tmp_path / "dcnn_sanitize_test"
    proc = subprocess.run(
        ["bash", _SCRIPT, "--run", str(out)],
        capture_output=True, text=True, timeout=600)
    tail = (proc.stdout + proc.stderr)[-4000:]
    if proc.returncode == 2:
        pytest.skip(f"no compiler / sanitizer runtime on this host: {tail}")
    assert proc.returncode == 0, (
        f"sanitized native round-trips failed (rc={proc.returncode}):\n"
        f"{tail}")
    assert "all round-trips clean" in proc.stdout
