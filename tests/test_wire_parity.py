"""Bit-parity of the uint8 feed wire across every path, plus wire codecs.

The wire-dtype contract (docs/performance.md §"The wire-dtype contract"):
image feeds ship raw **uint8** and the consumer decodes
``x.astype(float32) * float32(1/255)`` AFTER the put. The reference here
is ``wire.decode_host`` (the numpy multiply); every feed path — serial
``BaseDataLoader`` iteration, ``serial_shards``, the ``FeedWorkerPool``,
``PrefetchLoader``'s auto-installed device decode, the streaming shard
gather — must land on bit-identical float32 pixels, and the wire payload
must be 4x smaller than the decoded batch (the ISSUE 16 acceptance gate).

The codec half: the byte-shuffle + LZ4 wire codec must round-trip
bit-exactly through a REAL socketpair ``Channel`` (per-frame codec-id
dispatch, no receiver configuration) and reject truncated/garbage
streams instead of decoding nonsense.
"""

import socket
import struct
import threading

import numpy as np
import pytest

import jax.numpy as jnp

from dcnn_tpu.data import (
    ArrayDataLoader, AugmentationBuilder, PrefetchLoader,
    StreamingDeviceDataset, decode_batch, decode_host, one_hot, wire_scale,
)
from dcnn_tpu.data.wire import WIRE_SCALE_U8, decode_fn
from dcnn_tpu.data.workers import (FeedWorkerPool, LocalSlots,
                                   serial_shards)
from dcnn_tpu.parallel.comm import MAGIC, Channel, ChannelClosed, _HEADER
from dcnn_tpu.utils.compression import (
    Lz4Compressor, MetaCompressor, RawCompressor, ShuffleLz4Compressor,
    ZlibCompressor, resolve_codec,
)


def _data(n=192, hw=8, c=3, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, hw, hw, c), dtype=np.uint8)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    return x, y


def _aug():
    return (AugmentationBuilder("NHWC").horizontal_flip(p=0.5)
            .random_crop(2, p=1.0).brightness(0.2, p=0.5).build())


def _shuffle_lz4_or_skip():
    try:
        return ShuffleLz4Compressor()
    except RuntimeError as e:
        pytest.skip(f"native lz4/byte-shuffle unavailable: {e}")


# -- the decode contract -----------------------------------------------------

def test_wire_is_4x_smaller_and_decode_bit_identical():
    """ISSUE 16 acceptance: wire bytes drop >= 4x vs float32 while the
    decoded batch is bit-identical to the host reference multiply."""
    x, y = _data()
    loader = ArrayDataLoader(x, one_hot(y, 10), batch_size=64, shuffle=False)
    assert loader.wire_dtype == np.uint8
    assert loader.scale == WIRE_SCALE_U8
    xb, _ = next(iter(loader))
    ref = decode_host(xb, loader.scale)
    assert ref.dtype == np.float32
    # >= 4x fewer bytes on the wire than the f32 the model consumes
    assert ref.nbytes >= 4 * xb.nbytes
    dev = decode_batch(jnp.asarray(xb), wire_scale(loader))
    np.testing.assert_array_equal(np.asarray(dev), ref)


def test_decode_is_the_multiply_not_the_division():
    """The multiply-by-rounded-reciprocal form is normative: it matches
    the device decode bit-for-bit, while /255 differs by 1 ulp on some
    values (double rounding) — the exact drift the contract forbids."""
    x = np.arange(256, dtype=np.uint8)
    ref = x.astype(np.float32) * np.float32(1.0 / 255.0)
    np.testing.assert_array_equal(decode_host(x), ref)
    np.testing.assert_array_equal(np.asarray(decode_batch(jnp.asarray(x))),
                                  ref)
    div = x.astype(np.float32) / np.float32(255.0)
    assert not np.array_equal(ref, div)  # they really are different series


def test_decode_fn_cached_and_identity_on_floats():
    # one jitted callable per scale (TS06: no per-call closure retrace)
    assert decode_fn(WIRE_SCALE_U8) is decode_fn(WIRE_SCALE_U8)
    xf = np.random.default_rng(0).random((4, 3)).astype(np.float32)
    np.testing.assert_array_equal(np.asarray(decode_batch(jnp.asarray(xf))),
                                  xf)
    np.testing.assert_array_equal(decode_host(xf), xf)
    # float loaders declare the identity decode
    lf = ArrayDataLoader(xf, one_hot(np.zeros(4, np.int64), 2),
                         batch_size=2, shuffle=False)
    assert lf.wire_dtype == np.float32 and lf.scale == 1.0


# -- bit-parity across the feed paths ----------------------------------------

def test_serial_iter_requantize_matches_manual_convention():
    """BaseDataLoader.__iter__ on a uint8 loader augments in float32
    0..255 domain and re-quantizes clip+rint+cast — byte-identical to
    applying the convention by hand with the same rng stream."""
    x, y = _data(n=128)
    aug = _aug()
    loader = ArrayDataLoader(x, one_hot(y, 10), batch_size=32,
                             shuffle=False, augmentation=aug, seed=5)
    got = [xb for xb, _ in loader]
    rng = loader.epoch_rng()
    for i, take in enumerate(loader.batch_indices(rng)):
        xf = aug(x[take].astype(np.float32), rng)
        np.clip(xf, 0.0, 255.0, out=xf)
        np.rint(xf, out=xf)
        want = xf.astype(np.uint8)
        assert got[i].dtype == np.uint8
        np.testing.assert_array_equal(got[i], want)


def test_pool_and_serial_shards_decode_to_identical_floats():
    """serial path vs FeedWorkerPool: identical uint8 wire bytes, hence
    identical decoded float32 — for augmented and plain feeds."""
    x, y = _data()
    rng = np.random.default_rng(1)
    sels = [np.sort(rng.permutation(len(x))[:64]) for _ in range(4)]
    for aug in (None, _aug()):
        ser = [(a.copy(), b.copy()) for a, b, _ in
               serial_shards(x, y, sels, augment=aug, seed=7, epoch=2)]
        pool = FeedWorkerPool(
            x, y, 64, num_workers=2, augment=aug, seed=7,
            backend="thread", poll_s=0.02,
            slots=LocalSlots(4, 64, x.shape[1:], x.dtype,
                             y.shape[1:], y.dtype))
        got = []
        for ps in pool.shards(sels, epoch=2):
            got.append((ps.x.copy(), ps.y.copy()))
            ps.release()
        pool.close()
        for (sx, sy), (gx, gy) in zip(ser, got):
            assert sx.dtype == gx.dtype == np.uint8
            np.testing.assert_array_equal(sx, gx)
            np.testing.assert_array_equal(sy, gy)
            np.testing.assert_array_equal(
                np.asarray(decode_batch(jnp.asarray(gx))), decode_host(sx))


def test_prefetch_auto_decode_bit_identical_to_host_reference():
    """A uint8-wire inner with no explicit device_transform: the staged
    put ships uint8 and the yielded x is already the decoded float32 —
    bit-identical to decoding the serial host batches."""
    x, y = _data(n=128)
    inner = ArrayDataLoader(x, one_hot(y, 10), batch_size=32, shuffle=False)
    want = [decode_host(xb, inner.scale) for xb, _ in inner]
    pf = PrefetchLoader(inner, depth=2)
    assert pf.wire_dtype == np.uint8 and pf.scale == WIRE_SCALE_U8
    got = [(np.asarray(dx), np.asarray(dy)) for dx, dy in pf]
    assert len(got) == len(want)
    for w, (gx, _) in zip(want, got):
        assert gx.dtype == np.float32
        np.testing.assert_array_equal(gx, w)
    # an explicit device_transform still wins over the auto decode
    pf2 = PrefetchLoader(inner, depth=2,
                         device_transform=lambda a, b: (a, b))
    gx2, _ = next(iter(pf2))
    assert np.asarray(gx2).dtype == np.uint8


def test_streaming_shard_gather_decodes_to_reference():
    """The streaming path's shard gather keeps raw uint8 rows; the device
    decode of a gathered shard equals the host reference decode of the
    same selection."""
    x, y = _data(n=256)
    sds = StreamingDeviceDataset(x, y, 10, batch_size=32, shard_batches=2,
                                 seed=3)
    ref = StreamingDeviceDataset(x, y, 10, batch_size=32, shard_batches=2,
                                 seed=3)
    sels = list(ref.shard_selections())
    shards = list(sds.shards())
    assert len(shards) == len(sels) == sds.num_shards
    for (sx, sy), sel in zip(shards, sels):
        assert sx.dtype == np.uint8
        np.testing.assert_array_equal(sx, x[sel])
        np.testing.assert_array_equal(sy, y[sel])
        np.testing.assert_array_equal(
            np.asarray(decode_batch(jnp.asarray(sx))), decode_host(x[sel]))


# -- wire codecs -------------------------------------------------------------

def test_resolve_codec_semantics(monkeypatch):
    assert isinstance(resolve_codec(False), RawCompressor)
    assert isinstance(resolve_codec(None), RawCompressor)
    assert isinstance(resolve_codec(""), RawCompressor)
    assert isinstance(resolve_codec("zlib"), ZlibCompressor)
    inst = ZlibCompressor()
    assert resolve_codec(inst) is inst
    with pytest.raises(ValueError, match="unknown wire codec"):
        resolve_codec("snappy")
    monkeypatch.delenv("DCNN_WIRE_CODEC", raising=False)
    assert resolve_codec(True) is None  # MetaCompressor default
    monkeypatch.setenv("DCNN_WIRE_CODEC", "zlib")
    assert isinstance(resolve_codec(True), ZlibCompressor)


def test_elastic_compress_env_knob(monkeypatch):
    from dcnn_tpu.core.config import TrainingConfig
    assert TrainingConfig().elastic_compress == ""
    monkeypatch.setenv("ELASTIC_COMPRESS", "shuffle-lz4")
    assert TrainingConfig.load_from_env().elastic_compress == "shuffle-lz4"


def test_shuffle_lz4_roundtrip_exact_and_compresses():
    codec = _shuffle_lz4_or_skip()
    assert codec.codec_id == 5
    mc = MetaCompressor()
    # periodic float32 (tied-weights-like): byte-shuffle groups the
    # exponent bytes and LZ4 matches the repeating period
    arr = np.tile(np.linspace(0.0, 1e-3, 1024, dtype=np.float32),
                  16).reshape(64, 256)
    blob = mc.compress_array(arr, codec=codec)
    assert len(blob) < arr.nbytes
    back = mc.decompress_array(blob)
    assert back.dtype == arr.dtype
    np.testing.assert_array_equal(back, arr)
    # odd-length (typesize-indivisible) payloads fall back to typesize 1
    raw = bytes(range(251))
    assert mc.decompress(mc.compress(raw, codec=codec)) == raw


def _pipe_channels(send_codec):
    a, b = socket.socketpair()
    return Channel(a, compress=send_codec), Channel(b)


def _send_async(chan, *args, **kw):
    t = threading.Thread(target=chan.send, args=args, kwargs=kw,
                         daemon=True)
    t.start()
    return t


def test_shuffle_lz4_through_real_channel_and_mixed_codecs():
    """The codec rides a REAL socketpair Channel: sender configured with
    shuffle-lz4, receiver completely unconfigured — per-frame codec-id
    dispatch decodes it, and the raw reply on the same pair proves
    mixed-codec fleets interoperate frame by frame."""
    _shuffle_lz4_or_skip()
    tx, rx = _pipe_channels("shuffle-lz4")
    try:
        grads = (np.random.default_rng(4)
                 .standard_normal((32, 257)).astype(np.float32) * 1e-2)
        t = _send_async(tx, "grads", {"step": 3}, grads)
        cmd, meta, payload = rx.recv()
        t.join(10.0)
        assert cmd == "grads" and meta["step"] == 3
        assert payload.dtype == np.float32
        np.testing.assert_array_equal(payload, grads)
        # reply raw (the receiver's Channel default) — sender decodes it
        # with zero configuration, dispatching on the frame's codec id
        pix = np.random.default_rng(5).integers(
            0, 256, size=(8, 8, 3), dtype=np.uint8)
        t = _send_async(rx, "pixels", None, pix)
        cmd2, _, payload2 = tx.recv()
        t.join(10.0)
        assert cmd2 == "pixels"
        np.testing.assert_array_equal(payload2, pix)
    finally:
        tx.close()
        rx.close()


def test_channel_rejects_truncated_and_garbage_streams():
    """A malformed wire must raise, never decode nonsense: bad magic,
    a frame that dies mid-payload, and a framed payload whose compressed
    bytes are truncated/garbled."""
    _shuffle_lz4_or_skip()
    # 1) garbage magic
    a, b = socket.socketpair()
    chan = Channel(b)
    try:
        a.sendall(_HEADER.pack(0xDEADBEEF, 0, 0, 0))
        with pytest.raises(ConnectionError, match="bad frame magic"):
            chan.recv()
    finally:
        a.close()
        chan.close()
    # 2) truncated frame: header promises bytes that never arrive
    a, b = socket.socketpair()
    chan = Channel(b)
    try:
        meta = b'{"cmd":"x"}'
        a.sendall(_HEADER.pack(MAGIC, 1, len(meta), 1000) + meta + b"par")
        a.close()
        with pytest.raises(ChannelClosed):
            chan.recv()
    finally:
        chan.close()
    # 3) well-framed but corrupt compressed payload: the lz4 layer must
    # reject it (ValueError), not hand back garbage bytes
    mc = MetaCompressor()
    blob = mc.compress_array(np.arange(4096, dtype=np.float32),
                             codec=ShuffleLz4Compressor())
    hdr = blob[:struct.calcsize("<BQ")]
    body = blob[struct.calcsize("<BQ"):]
    for bad in (hdr + body[:len(body) // 2],          # truncated stream
                hdr + bytes(len(body))):              # zeroed garbage
        a, b = socket.socketpair()
        chan = Channel(b)
        try:
            meta = b'{"cmd":"x"}'
            a.sendall(_HEADER.pack(MAGIC, 1, len(meta), len(bad))
                      + meta + bad)
            with pytest.raises(ValueError):
                chan.recv()
        finally:
            a.close()
            chan.close()


def test_unknown_codec_id_rejected():
    mc = MetaCompressor()
    blob = struct.pack("<BQ", 250, 4) + b"abcd"
    with pytest.raises(ValueError, match="unknown codec id"):
        mc.decompress(blob)


def test_lz4_plain_codec_roundtrip():
    try:
        codec = Lz4Compressor()
    except RuntimeError as e:
        pytest.skip(f"native lz4 unavailable: {e}")
    mc = MetaCompressor()
    arr = np.tile(np.arange(64, dtype=np.uint8), 512)
    blob = mc.compress_array(arr, codec=codec)
    assert len(blob) < arr.nbytes
    np.testing.assert_array_equal(mc.decompress_array(blob), arr)
