"""Telemetry-driven autoscaler (dcnn_tpu/serve/autoscale.py + the
elastic-training twin in dcnn_tpu/parallel/autoscale.py).

Contracts:

- **Rate schedules** (`serve/traffic.py`): diurnal/spike/step produce the
  documented instantaneous rates and `open_loop` paces arrivals to the
  schedule exactly (fake clock — arrival counts per window are asserted,
  not approximated).
- **Graceful scale-down**: `Router.decommission` is drain-then-remove —
  the accepted-ledger no-silent-drop guarantee holds through a shrink,
  including a victim killed mid-decommission (its work re-admits).
- **Device leases**: strict-priority broker; a serving shortfall revokes
  from training (edge-triggered, never duplicated), training never
  surrenders below its floor, release un-blocks the claimant.
- **Control loop**: scale-up on SLO breach after `breach_ticks` within
  the cooldown, scale-down only after `idle_ticks` of genuine idleness,
  hysteresis band enforced at construction, HBM watermark guard, canary
  replicas never chosen as scale-down victims, scale-ups join the modal
  *stable* version.
- **The diurnal soak** (acceptance): 10x peak-to-trough over a full
  cycle with a replica preemption and a canary swap injected mid-load,
  all sleep-free under a fake clock — availability >= 0.999, zero
  silent drops, bounded SLO-violation minutes, scale-up reaction within
  the cooldown budget, and the fleet actually breathing (grows at peak,
  shrinks back at trough). A real-time variant runs under `-m slow`.
- **Device-lease handoff** (acceptance): the serving autoscaler's
  scale-up revokes a chip from a live elastic training world, which
  shrinks via the PR-8 reconfiguration protocol and keeps training;
  when load recedes the chip returns and the world re-grows from the
  shared checkpoint root — final params match an uninterrupted
  fixed-world run within the PR-8 reshard tolerance.
"""

import threading
import time

import numpy as np
import pytest

from dcnn_tpu.obs.exposition import (
    parse_prometheus_text, render_scalar, scalar_values,
)
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.serve import (
    Autoscaler, AutoscalerConfig, DeviceLeaseBroker, LocalReplica, Router,
    RouterMetrics, autoscale_check, diurnal, open_loop, spike, step,
)
from dcnn_tpu.serve.replica import ReplicaError
from dcnn_tpu.serve.soak import (
    ManualClock as FakeClock,
    make_soak_replica_factory as make_replica_factory,
    run_diurnal_soak,
    synthetic_engine_factory as fake_engine_factory,
)


# ------------------------------------------------------------ rate schedules

def test_diurnal_schedule_shape():
    rate = diurnal(400.0, 40.0, period_s=600.0)
    assert rate(0.0) == pytest.approx(40.0)        # starts at the trough
    assert rate(300.0) == pytest.approx(400.0)     # peak at half period
    assert rate(600.0) == pytest.approx(40.0)      # full cycle
    assert rate(300.0) / rate(0.0) == pytest.approx(10.0)  # 10x ratio
    with pytest.raises(ValueError, match="trough"):
        diurnal(10.0, 20.0, period_s=60.0)


def test_spike_and_step_schedules():
    r = spike(10.0, 100.0, at_s=5.0, width_s=2.0)
    assert r(4.9) == 10.0 and r(5.0) == 100.0
    assert r(6.9) == 100.0 and r(7.0) == 10.0
    s = step([(0.0, 5.0), (10.0, 50.0), (20.0, 2.0)])
    assert s(0.0) == 5.0 and s(9.9) == 5.0
    assert s(10.0) == 50.0 and s(25.0) == 2.0
    with pytest.raises(ValueError, match="start at t=0"):
        step([(1.0, 5.0)])


def test_open_loop_paces_to_the_schedule():
    """Arrival counts per window match the schedule's integral — the
    offered-load contract every measurement surface shares."""
    fc = FakeClock()

    class CountingSink:
        def __init__(self):
            self.times = []

        def submit(self, x):
            self.times.append(fc.t)
            from concurrent.futures import Future
            f = Future()
            f.set_result(x)
            return f

    sink = CountingSink()
    rate = step([(0.0, 10.0), (5.0, 100.0)])
    open_loop(sink, [np.zeros(4, np.float32)], rate, 10.0,
              clock=fc, sleep=fc.advance)
    first = sum(1 for t in sink.times if t < 4.99)
    second = sum(1 for t in sink.times if t >= 4.99)
    assert abs(first - 50) <= 1       # 10 rps x 5 s
    assert abs(second - 500) <= 1     # 100 rps x 5 s
    # constant-rate back-compat: a float still works unchanged
    sink2 = CountingSink()
    fc.t = 0.0
    open_loop(sink2, [np.zeros(4, np.float32)], 20.0, 2.0,
              clock=fc, sleep=fc.advance)
    assert len(sink2.times) == 40


# --------------------------------------------------- graceful decommission

def make_fleet(n=3, *, queue_capacity=16, pump_on_sleep=True):
    fc = FakeClock()
    factory = make_replica_factory(fc, queue_capacity=queue_capacity,
                                   prefix="r")
    reps = [factory(1) for _ in range(n)]

    def sleep(dt):
        fc.advance(dt)
        if pump_on_sleep:
            for r in reps:
                try:
                    r.step(force=True)
                except Exception:
                    pass
    router = Router(reps, clock=fc, sleep=sleep)
    return router, reps, fc


def pump(reps, rounds=4):
    for _ in range(rounds):
        for r in reps:
            try:
                while r.step():
                    pass
            except Exception:
                pass


def test_decommission_drains_then_removes():
    router, reps, _fc = make_fleet(3)
    futs = [router.submit(np.full((4,), i, np.float32)) for i in range(24)]
    victim = reps[0].name
    report = router.decommission(victim, timeout=5.0)
    assert victim not in router.replica_names()
    assert report["swept"] == 0  # everything drained cleanly
    pump(reps)
    assert router.outstanding() == 0
    for f in futs:
        assert f.done() and f.exception() is None
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_decommissions_total"] == 1
    assert snap["serve_router_decommission_sweeps_total"] == 0


def test_decommission_stops_admission_to_victim_immediately():
    router, reps, _fc = make_fleet(2, pump_on_sleep=False)
    victim = reps[0].name
    # mark draining in a thread; it blocks on outstanding=0 never needed
    # here (no outstanding) — decommission returns immediately
    router.decommission(victim, timeout=1.0)
    for i in range(8):
        router.submit(np.full((4,), i, np.float32))
    pump(reps)
    stats = router.replica_stats()
    assert reps[0].name not in stats          # removed
    assert stats[reps[1].name]["completed"] == 8  # all routed to survivor


def test_kill_draining_replica_mid_decommission_no_silent_drops():
    """The ISSUE's regression case: the victim dies WHILE draining — its
    accepted-but-unanswered requests must fail typed and re-admit to
    survivors, never silently drop."""
    router, reps, fc = make_fleet(2, pump_on_sleep=False)
    victim = reps[0]
    # load work onto both replicas, none of it dispatched yet
    futs = [router.submit(np.full((4,), i, np.float32)) for i in range(16)]

    kills = [0]

    def killer_sleep(dt):
        fc.advance(dt)
        if kills[0] == 0:
            kills[0] = 1
            victim.kill()      # dies mid-drain
        pump([reps[1]])        # survivor keeps serving
    router._sleep = killer_sleep
    router.decommission(victim.name, timeout=5.0)
    pump([reps[1]])
    assert router.outstanding() == 0      # ledger swept
    undone = [f for f in futs if not f.done()]
    assert undone == []                    # zero silent drops
    completed = sum(1 for f in futs if f.exception() is None)
    assert completed == 16                 # everything re-admitted + served
    assert victim.name not in router.replica_names()


def test_decommission_timeout_sweeps_typed():
    router, reps, _fc = make_fleet(2, pump_on_sleep=False)
    victim = reps[0]
    futs = [router.submit(np.full((4,), i, np.float32)) for i in range(8)]

    def sleep(dt):
        _fc.advance(dt)
        pump([reps[1]])  # only the survivor is ever pumped
    router._sleep = sleep
    report = router.decommission(victim.name, timeout=0.5)
    pump([reps[1]])
    # whatever the victim still held was swept typed and re-admitted
    assert router.outstanding() == 0
    assert all(f.done() for f in futs)
    assert sum(1 for f in futs if f.exception() is None) == 8
    if report["swept"]:
        snap = router.metrics.registry.snapshot()
        assert snap["serve_router_decommission_sweeps_total"] == 1


def test_draining_replica_not_flapped_up_by_sweep():
    router, reps, _fc = make_fleet(2, pump_on_sleep=False)
    victim = reps[0]
    for i in range(4):  # least-loaded routing spreads these over both
        router.submit(np.full((4,), i, np.float32))
    done = []
    t = threading.Thread(
        target=lambda: done.append(
            router.decommission(victim.name, timeout=None)), daemon=True)
    # hold the drain open: outstanding > 0 until we pump
    t.start()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        stats = router.replica_stats()
        if victim.name in stats \
                and stats[victim.name]["state"] == "draining":
            break
        time.sleep(0.005)
    report = router.check_replicas()
    assert "draining" in report[victim.name]
    stats = router.replica_stats()
    assert stats[victim.name]["state"] == "draining"  # sweep left it alone
    with pytest.raises(ReplicaError, match="decommissioned"):
        router.swap_replica(victim.name, 2)
    pump(reps)
    t.join(timeout=5.0)
    assert not t.is_alive() and done


# ------------------------------------------------------- device-lease broker

def test_broker_grant_release_and_priority_revocation():
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(4, registry=reg)
    revokes = []
    broker.register("train", priority=0, held=3,
                    on_revoke=lambda k: revokes.append(k))
    broker.register("serve", priority=1, held=1)
    assert broker.free() == 0
    # serving shortfall fires a revocation at the training tenant
    assert broker.request("serve", 1) == 0
    assert revokes == [1]
    assert broker.revoke_pending("train") == 1
    # edge-triggered: a second identical request does not re-revoke
    assert broker.request("serve", 1) == 0
    assert revokes == [1]
    # training surrenders; the claimant's next poll gets the device
    broker.release("train", 1)
    assert broker.revoke_pending("train") == 0
    assert broker.request("serve", 1) == 1
    assert broker.held("serve") == 2 and broker.held("train") == 2
    # release back and training can re-grow
    broker.release("serve", 1)
    assert broker.request("train", 1) == 1
    # training (low priority) shortfall never revokes from serving
    revokes.clear()
    assert broker.request("train", 1) == 0
    assert revokes == []
    with pytest.raises(ValueError, match="cannot release"):
        broker.release("serve", 99)
    with pytest.raises(KeyError):
        broker.request("ghost", 1)
    assert reg.snapshot()["lease_revocations_total"] == 1


def test_train_lease_floor_and_listener():
    from dcnn_tpu.parallel import TrainLease

    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    lease = TrainLease(broker, initial=2, min_hold=1, registry=reg)
    broker.register("serve", priority=1, held=0)
    seen = []
    lease.add_listener(seen.append)
    # asking for 2 only surfaces 1 to training (min_hold floor)
    assert broker.request("serve", 2) == 0
    assert seen == [1]
    lease.surrender(1)
    assert broker.request("serve", 2) == 1   # the surrendered one
    assert lease.held() == 1
    # a further shortfall cannot dig below the floor
    assert broker.request("serve", 1) == 0
    assert seen == [1]
    assert reg.snapshot()["train_lease_preemptions_total"] == 1


# ------------------------------------------------------- control-loop units

def _breach_text(p99=1000.0, depth=30.0, shed=0.0, hbm=None):
    lines = []
    lines += render_scalar("serve_queue_depth", "gauge", depth)
    lines += render_scalar("serve_latency_window_p99_ms", "gauge", p99)
    lines += render_scalar("serve_shed_fraction", "gauge", shed)
    if hbm is not None:
        lines += render_scalar("hbm_bytes_in_use", "gauge", hbm * 100.0)
        lines += render_scalar("hbm_bytes_limit", "gauge", 100.0)
    return "\n".join(lines) + "\n"


def _idle_text():
    return _breach_text(p99=1.0, depth=0.0)


def make_scaler(fc, *, cfg=None, scrape=None, broker=None, n_boot=1,
                factory=None):
    factory = factory if factory is not None else make_replica_factory(fc)
    reps = [factory(1) for _ in range(n_boot)]
    router = Router(reps, clock=fc, sleep=lambda s: fc.advance(s),
                    metrics=RouterMetrics(clock=fc))
    scaler = Autoscaler(
        router, factory,
        config=cfg if cfg is not None else AutoscalerConfig(
            up_cooldown_s=0.0, down_cooldown_s=0.0, breach_ticks=1,
            idle_ticks=2, max_replicas=4),
        broker=broker, clock=fc,
        scrape=scrape if scrape is not None else (lambda n, r: None))
    return scaler, router, reps


def test_http_scraper_reads_real_telemetry_endpoints():
    """HttpScraper — the production scrape path — against a real
    per-replica telemetry server: ``/metrics`` text feeds the same parse
    path as the in-process scrape, ``healthz()`` surfaces both healthy
    bodies and a 503's machine-readable degradation reasons, and an
    unreachable or unknown replica scores as signal-less (``None``),
    never an exception."""
    from dcnn_tpu.obs import TelemetryServer
    from dcnn_tpu.serve.autoscale import HttpScraper
    from dcnn_tpu.serve.batcher import DynamicBatcher

    b = DynamicBatcher(fake_engine_factory(1), start=False)
    srv = b.start_telemetry()
    degraded = TelemetryServer(registry=MetricsRegistry())
    degraded.add_check("scaler", lambda: "scale-up blocked: no lease")
    degraded.start()
    try:
        fut = b.submit(np.zeros((4,), np.float32))
        b.step()
        fut.result(timeout=10)
        scraper = HttpScraper({"r0": srv.url, "bad": degraded.url,
                               "gone": "http://127.0.0.1:9"})
        vals = scalar_values(parse_prometheus_text(scraper("r0", None)))
        assert vals["serve_samples_completed_total"] == 1
        assert "serve_queue_depth" in vals
        assert scraper.healthz("r0")["status"] == "ok"
        # a 503 still yields the parsed degradation body (HTTPError path)
        body = scraper.healthz("bad")
        assert body["status"] == "unhealthy"
        assert any("no lease" in r for r in body["reasons"])
        # unreachable / unregistered -> None, never an exception
        assert scraper("gone", None) is None
        assert scraper.healthz("gone") is None
        assert scraper("unknown", None) is None
    finally:
        degraded.stop()
        b.shutdown(drain=False)


def test_open_loop_rejects_rate_too_fast_for_the_grid():
    """A schedule bug returning inf (or >~2e9 rps) must raise, not spin
    the pacing loop forever on a zero-length nanosecond step."""
    fc = FakeClock()

    class Sink:
        def submit(self, x):
            from concurrent.futures import Future
            f = Future()
            f.set_result(x)
            return f

    rate = step([(0.0, 10.0), (1.0, float("inf"))])
    with pytest.raises(ValueError, match="rounds to zero"):
        open_loop(Sink(), [np.zeros(4, np.float32)], rate, 5.0,
                  clock=fc, sleep=fc.advance)


def test_scaler_scales_up_on_breach_and_down_when_idle():
    fc = FakeClock()
    mode = {"text": _breach_text()}
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: mode["text"])
    out = scaler.tick()
    assert out["action"] == "up" and len(router.replica_names()) == 2
    fc.advance(1.0)
    mode["text"] = _idle_text()
    assert scaler.tick()["action"] == "hold"   # idle_ticks=2: not yet
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "down"
    assert len(router.replica_names()) == 1
    # never below min_replicas
    fc.advance(1.0)
    scaler.tick()
    fc.advance(1.0)
    assert scaler.tick()["action"] == "hold"
    assert len(router.replica_names()) == 1
    snap = scaler.router.metrics.registry.snapshot()
    assert snap["autoscale_scale_ups_total"] == 1
    assert snap["autoscale_scale_downs_total"] == 1


def test_scaler_cooldowns_and_breach_ticks():
    fc = FakeClock()
    cfg = AutoscalerConfig(up_cooldown_s=10.0, breach_ticks=2,
                           max_replicas=4)
    scaler, router, _ = make_scaler(
        fc, cfg=cfg, scrape=lambda n, r: _breach_text())
    assert scaler.tick()["action"] == "hold"       # 1 breach tick < 2
    fc.advance(1.0)
    assert scaler.tick()["action"] == "up"         # 2nd consecutive tick
    fc.advance(1.0)
    assert scaler.tick()["action"] == "hold"       # up cooldown
    fc.advance(10.0)
    assert scaler.tick()["action"] == "up"         # cooldown expired
    assert len(router.replica_names()) == 3


def test_scaler_hbm_watermark_guard_blocks_up():
    fc = FakeClock()
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: _breach_text(hbm=0.97))
    out = scaler.tick()
    assert out["action"] == "blocked" and out["reason"] == "hbm watermark"
    assert len(router.replica_names()) == 1
    assert "hbm" in (autoscale_check(scaler)() or "")
    snap = scaler.router.metrics.registry.snapshot()
    assert snap["autoscale_hbm_blocked_total"] == 1
    # the block is per-turn: once the next tick no longer wants that
    # scale-up, a stale reason must not pin /healthz degraded
    scaler.scrape = lambda n, r: _idle_text()
    fc.advance(1.0)
    scaler.tick()
    assert autoscale_check(scaler)() is None


def test_scaler_lease_blocked_then_granted():
    fc = FakeClock()
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    broker.register("other", priority=0, held=1)
    broker.register("serve", priority=1, held=1)
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: _breach_text(), broker=broker)
    out = scaler.tick()
    assert out["action"] == "blocked" and out["reason"] == "awaiting lease"
    assert "lease" in autoscale_check(scaler)()
    broker.release("other", 1)
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "up"
    assert broker.held("serve") == 2
    # scale-down releases the lease back
    fc.advance(1.0)
    mode_idle = _idle_text()
    scaler.scrape = lambda n, r: mode_idle
    scaler.tick()              # idle_run 1 of 2
    fc.advance(1.0)
    out = scaler.tick()        # idle_run 2 -> down
    assert out["action"] == "down"
    assert broker.held("serve") == 1 and broker.free() == 1


def test_scaler_reaps_dead_owned_replica_and_returns_lease():
    """An owned replica that dies (the soak's preemption) must be
    reclaimed on the next tick — removed from the fleet map, closed, and
    its device lease released — or the lease would leak forever:
    _scale_down only ever considers routable victims."""
    fc = FakeClock()
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    broker.register("serve", priority=1, held=1)
    mode = {"text": _breach_text()}
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: mode["text"], broker=broker)
    out = scaler.tick()
    assert out["action"] == "up" and broker.held("serve") == 2
    victim = out["added"][0]
    router.replicas()[victim].kill()
    mode["text"] = _idle_text()
    fc.advance(1.0)
    scaler.tick()
    assert victim not in router.replica_names()
    assert scaler.owned_replicas() == []
    assert broker.held("serve") == 1 and broker.free() == 1


def test_scaler_version_fn_failure_does_not_strand_leases():
    """A raising version_fn aborts the turn BEFORE any lease is taken —
    the grant must not escape to tick()'s catch-all unreleased."""
    fc = FakeClock()
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    broker.register("serve", priority=1, held=1)

    def bad_version():
        raise RuntimeError("version store unreachable")

    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: _breach_text(), broker=broker)
    scaler.version_fn = bad_version
    out = scaler.tick()
    assert out["action"] == "error"
    assert "version store unreachable" in (autoscale_check(scaler)() or "")
    assert broker.held("serve") == 1 and broker.free() == 1
    assert len(router.replica_names()) == 1


def test_scaler_never_picks_canary_victim_and_joins_stable_version():
    fc = FakeClock()
    factory = make_replica_factory(fc)
    scaler, router, reps = make_scaler(
        fc, factory=factory, n_boot=2,
        cfg=AutoscalerConfig(up_cooldown_s=0.0, down_cooldown_s=0.0,
                             breach_ticks=1, idle_ticks=1,
                             max_replicas=4, min_replicas=1))
    # one replica is mid-canary on v2
    router.swap_replica(reps[0].name, 2, canary=True)
    mode = {"text": _breach_text()}
    scaler.scrape = lambda n, r: mode["text"]
    out = scaler.tick()
    assert out["action"] == "up"
    # the new replica joined the modal STABLE version (1), not the canary
    added = out["added"][0]
    assert router.replica_stats()[added]["version"] == 1
    # scale-down: victim must never be the canary
    mode["text"] = _idle_text()
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "down"
    assert out["removed"] != reps[0].name
    assert router.replica_stats()[reps[0].name]["canary"]


def test_collect_is_read_only_for_out_of_band_callers():
    """A dashboard polling the public collect() between ticks must not
    consume the router's shed delta — only the decision loop commits the
    baseline, so the next tick still sees the breach."""
    fc = FakeClock()
    scaler, router, _ = make_scaler(fc, scrape=lambda n, r: _idle_text())
    scaler.tick()                      # baseline committed at zero
    router.metrics.record_submit("normal", 10)
    router.metrics.record_shed("normal", 10)
    fleet = scaler.collect()           # out-of-band observer
    assert fleet.shed_fraction == pytest.approx(0.5)
    fc.advance(1.0)
    out = scaler.tick()                # the delta was NOT consumed
    assert out["shed_fraction"] == pytest.approx(0.5)
    assert out["action"] == "up"       # shed breach still fires
    # scrape health is decision state too: a dashboard poll seeing a
    # malformed body must not degrade /healthz (and a poll seeing a
    # clean one must not clear a tick's degradation)
    scaler.scrape = lambda n, r: "torn mid-write garbage\n"
    scaler.collect()
    assert autoscale_check(scaler)() is None
    snap = scaler.router.metrics.registry.snapshot()
    assert snap.get("autoscale_scrape_parse_failures_total", 0) == 0


def test_down_guard_refuses_shrink_while_traffic_needs_fleet():
    """Instantaneous queues read ~0 on a fleet that is keeping up: the
    down decision must project the post-shrink per-replica offered rate
    against the last scale-up's demand watermark, not decommission at
    steady peak and pay a breach + re-grow limit cycle."""
    fc = FakeClock()
    mode = {"text": _idle_text()}
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: mode["text"],
        cfg=AutoscalerConfig(up_cooldown_s=0.0, down_cooldown_s=0.0,
                             breach_ticks=1, idle_ticks=1,
                             max_replicas=4))
    scaler.tick()                      # priming tick (dt starts here)
    # breach under 100 rps -> scale up 1 -> 2; watermark = 100/2 = 50
    router.metrics.record_submit("normal", 100)
    mode["text"] = _breach_text()
    fc.advance(1.0)
    assert scaler.tick()["action"] == "up"
    # queues drain (idle text) but traffic continues at peak: shrinking
    # to 1 replica would put 100 rps on a 50-rps watermark -> hold
    mode["text"] = _idle_text()
    router.metrics.record_submit("normal", 100)
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "hold" and out["reason"] == "traffic needs fleet"
    assert len(router.replica_names()) == 2
    # traffic recedes -> the same idle verdict now shrinks the fleet
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "down"
    assert len(router.replica_names()) == 1


def test_miswired_lease_release_surfaces_without_failing_the_turn():
    """An operator who registered the serve tenant with held=0 (the
    convention wants held=<bootstrap fleet>) makes a bootstrap-victim
    scale-down's lease release an accounting error — the shrink already
    happened, so the turn completes and the error surfaces on
    /healthz instead of aborting mid-decommission."""
    fc = FakeClock()
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    broker.register("serve", priority=1, held=0)   # mis-wired: no held
    mode = {"text": _idle_text()}
    scaler, router, _ = make_scaler(
        fc, scrape=lambda n, r: mode["text"], n_boot=2, broker=broker)
    scaler.tick()                      # idle_run 1 of 2
    fc.advance(1.0)
    out = scaler.tick()                # idle_run 2 -> down
    assert out["action"] == "down"     # the shrink landed
    assert len(router.replica_names()) == 1
    assert "lease release failed" in (autoscale_check(scaler)() or "")


def test_scrape_parse_failure_degrades_healthz_not_silent():
    """A malformed /metrics body (truncated by a proxy, torn mid-write)
    must not feed zeroed signals INVISIBLY: the replica scores
    signal-less, the failure is counted, and /healthz degrades via
    autoscale_check until a tick parses clean."""
    fc = FakeClock()
    mode = {"text": "serve_queue_depth not-a-number garbage\n"}
    scaler, router, _ = make_scaler(fc, scrape=lambda n, r: mode["text"])
    out = scaler.tick()
    assert out["action"] != "error"    # the turn itself completes
    reason = autoscale_check(scaler)()
    assert reason is not None and "unparseable" in reason
    snap = scaler.router.metrics.registry.snapshot()
    assert snap["autoscale_scrape_parse_failures_total"] >= 1
    # a clean scrape clears the degradation
    mode["text"] = _idle_text()
    fc.advance(1.0)
    scaler.tick()
    assert autoscale_check(scaler)() is None


def test_scaler_repairs_fleet_below_min():
    fc = FakeClock()
    cfg = AutoscalerConfig(up_cooldown_s=100.0, breach_ticks=3,
                           min_replicas=1, max_replicas=4)
    scaler, router, reps = make_scaler(
        fc, cfg=cfg, scrape=lambda n, r: _idle_text())
    reps[0].kill()
    out = scaler.tick()   # sweep ejects the corpse; repair ignores cooldown
    assert out["action"] == "up"
    assert len([n for n, st in router.replica_stats().items()
                if st["state"] == "up"]) >= 1


def test_autoscaler_config_validation():
    with pytest.raises(ValueError, match="hysteresis"):
        AutoscalerConfig(high_utilization=0.3, low_utilization=0.5)
    with pytest.raises(ValueError, match="min_replicas"):
        AutoscalerConfig(min_replicas=5, max_replicas=2)
    with pytest.raises(ValueError, match="breach_ticks"):
        AutoscalerConfig(breach_ticks=0)


def test_scrape_signals_round_trip_from_real_replica():
    """The in-process scrape path parses a REAL ServeMetrics exposition —
    the same text contract the HTTP scraper reads."""
    fc = FakeClock()
    rep = LocalReplica(fake_engine_factory, 1, name="rt",
                       queue_capacity=16, clock=fc, start=False)
    router = Router([rep], clock=fc, sleep=lambda s: fc.advance(s),
                    metrics=RouterMetrics(clock=fc))
    scaler = Autoscaler(router, lambda v: None, clock=fc)
    for i in range(4):
        router.submit(np.full((4,), i, np.float32))
    fleet = scaler.collect()
    sig = fleet.replicas[0]
    assert sig.queue_depth == 4.0          # scraped, not introspected
    assert fleet.utilization == pytest.approx(4.0 / 16.0)
    rep.step()
    fc.advance(0.010)
    vals = scalar_values(parse_prometheus_text(rep.metrics.prometheus()))
    assert vals["serve_queue_depth"] == 0.0


# ------------------------------------------------------------ the diurnal soak
# The soak driver itself lives in dcnn_tpu/serve/soak.py — shared
# verbatim with bench.py (BENCH_AUTOSCALE) and examples/serve_autoscale.py
# so all three produce identical offered load and gate arithmetic.


def test_diurnal_soak_fake_clock_gates():
    """The ISSUE acceptance soak, entirely sleep-free: 10x peak-to-trough
    with a preemption and a canary swap injected mid-load."""
    report, scaler, router = run_diurnal_soak()
    cfg = scaler.cfg
    # -- availability + ledger gates
    assert report["silently_dropped"] == 0
    assert report["outstanding_after"] == 0
    assert report["availability"] >= 0.999, report
    # -- the fleet actually breathed: grew toward peak, shrank after
    assert report["scale_ups"] >= 2, report
    assert report["peak_fleet"] >= 3, report
    assert report["scale_downs"] >= 1, report
    assert report["final_fleet"] <= 2, report
    # -- SLO-violation minutes bounded (soak is 4 min long)
    assert report["slo_violation_minutes"] <= 1.0, report
    # -- scale-up reaction within the cooldown budget
    if report["reaction_max_s"] is not None:
        assert report["reaction_max_s"] <= cfg.up_cooldown_s + 2.0, report
    # the injected death was survived (PR-9 re-admission) and counted
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_replica_deaths_total"] >= 1
    assert snap["serve_router_swaps_total"] >= 1  # the canary swap landed


@pytest.mark.slow
def test_diurnal_soak_real_time():
    """Real-clock variant (threaded dispatchers, real sleeps): a compact
    diurnal cycle through live LocalReplicas."""
    factory_count = [0]

    def factory(version):
        factory_count[0] += 1
        return LocalReplica(
            fake_engine_factory, 1 if version is None else version,
            name=f"rt{factory_count[0]}", queue_capacity=64,
            max_wait_ms=1.0)

    boot = factory(1)
    router = Router([boot], metrics=RouterMetrics())
    cfg = AutoscalerConfig(
        slo_p99_ms=100.0, high_utilization=0.5, low_utilization=0.1,
        min_replicas=1, max_replicas=4, up_cooldown_s=0.5,
        down_cooldown_s=2.0, breach_ticks=1, idle_ticks=2,
        drain_timeout_s=5.0)
    scaler = Autoscaler(router, factory, config=cfg)
    scaler.start(interval_s=0.25)
    try:
        rate = diurnal(800.0, 80.0, period_s=6.0)
        samples = [np.full((4,), 3, np.float32)]
        futs = open_loop(router, samples, rate, 6.0)
        deadline = time.monotonic() + 10.0
        while router.outstanding() and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        scaler.stop()
    accepted = len(futs)
    done = sum(1 for _, f in futs if f.done())
    completed = sum(1 for _, f in futs
                    if f.done() and f.exception() is None)
    assert accepted - done == 0           # no orphans
    assert completed / accepted >= 0.99
    router.shutdown(drain=False)
    for rep in list(router.replicas().values()):
        try:
            rep.close()
        except Exception:
            pass


# ---------------------------------------------- device-lease handoff (e2e)

RTOL, ATOL = 2e-4, 2e-5  # the PR-8 reshard FP-reassociation contract


def _elastic_bits():
    import jax  # noqa: F401
    from dcnn_tpu.data.loader import ArrayDataLoader, one_hot
    from dcnn_tpu.nn import SequentialBuilder

    rng = np.random.default_rng(0)
    X = rng.normal(size=(48, 16)).astype(np.float32)
    Y = one_hot(rng.integers(0, 4, 48), 4)

    def model():
        return (SequentialBuilder("leased_model").input((16,))
                .dense(32).activation("relu").dense(4).build())

    def loader():
        return ArrayDataLoader(X, Y, batch_size=12, seed=7)
    return model, loader


def _make_controller_factory(model, loader, ckpt_dir, *, epochs=4):
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel.elastic import ElasticController

    def make(rank, peers, sock):
        cfg = TrainingConfig(
            epochs=epochs, learning_rate=0.05, seed=3, snapshot_dir=None,
            elastic=True, elastic_microbatches=2, elastic_timeout_s=15.0,
            elastic_heartbeat_s=0.0, elastic_ckpt_steps=2,
            elastic_min_world=1, checkpoint_dir=ckpt_dir)
        return ElasticController(
            model(), SGD(0.05), "softmax_crossentropy", loader(),
            config=cfg, rank=rank, peers=peers, listen_sock=sock)
    return make


def _leaves(ts):
    import jax
    return jax.tree_util.tree_leaves(jax.device_get(ts.params))


def test_device_lease_handoff_end_to_end(tmp_path):
    """The acceptance handoff: serving scale-up revokes a chip from a
    LIVE elastic training world (which shrinks via the PR-8 reshape and
    keeps training); load recedes, the chip returns, the world re-grows
    from the shared checkpoint root — and the final params match an
    uninterrupted fixed-world run within the reshard tolerance."""
    from dcnn_tpu.parallel import LeasedElasticTrainer, TrainLease

    model, loader = _elastic_bits()

    # --- baseline: uninterrupted fixed-world (2 hosts) run, 4 epochs
    base_trainer = LeasedElasticTrainer(
        _make_controller_factory(model, loader, str(tmp_path / "base")))
    base = base_trainer.run_segment(4, target_world=2, resume=True)
    assert all(not isinstance(r, BaseException) for r in base.values())
    base_params = _leaves(base[0])

    # --- leased run: 3 devices shared between serving (1) and train (2)
    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(3, registry=reg)
    lease = TrainLease(broker, initial=2, min_hold=1, registry=reg)
    broker.register("serve", priority=1, held=1)

    fc = FakeClock()
    rep_factory = make_replica_factory(fc, prefix="ho")
    boot = rep_factory(1)
    router = Router([boot], clock=fc, sleep=lambda s: fc.advance(s),
                    metrics=RouterMetrics(clock=fc))
    mode = {"text": _breach_text()}
    scaler = Autoscaler(
        router, rep_factory,
        config=AutoscalerConfig(up_cooldown_s=0.0, down_cooldown_s=0.0,
                                breach_ticks=1, idle_ticks=1,
                                min_replicas=1, max_replicas=2),
        broker=broker, tenant="serve", clock=fc,
        scrape=lambda n, r: mode["text"])

    trainer = LeasedElasticTrainer(
        _make_controller_factory(model, loader,
                                 str(tmp_path / "leased")),
        lease=lease, min_world=1)

    controllers = {}
    orig_make = trainer.make_controller

    def tracking_make(rank, peers, sock):
        ctl = orig_make(rank, peers, sock)
        controllers[rank] = ctl
        return ctl
    trainer.make_controller = tracking_make

    seg1 = {}
    t1 = threading.Thread(
        target=lambda: seg1.update(
            trainer.run_segment(3, target_world=2, resume=True)),
        daemon=True)
    t1.start()
    # let the world make real progress before the spike lands
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        ctl = controllers.get(0)
        if ctl is not None and len(ctl.step_log) >= 2:
            break
        time.sleep(0.01)
    else:
        pytest.fail("training world never made progress")

    # --- traffic spike: the serving autoscaler wants a second replica
    out = scaler.tick()
    assert out["action"] == "blocked"          # no free chip yet
    assert out["reason"] == "awaiting lease"   # revocation fired at train
    granted = {}
    deadline = time.monotonic() + 60.0
    while time.monotonic() < deadline:
        fc.advance(1.0)
        out = scaler.tick()
        if out["action"] == "up":
            granted.update(out)
            break
        time.sleep(0.02)
    assert granted, "serving scale-up never got the revoked device"
    assert len(router.replica_names()) == 2
    t1.join(timeout=120)
    assert not t1.is_alive()
    # exactly one host was preempted; the survivor reshaped and finished
    assert seg1[1] == "preempted"
    assert not isinstance(seg1[0], BaseException), seg1[0]
    assert controllers[0].world == 1
    assert controllers[0].stats["reconfigures"] >= 1
    assert reg.snapshot()["train_lease_preemptions_total"] == 1

    # --- load recedes: serving shrinks, the chip goes back
    mode["text"] = _idle_text()
    fc.advance(1.0)
    out = scaler.tick()
    assert out["action"] == "down"
    assert broker.free() == 1

    # --- the training world RE-GROWS from the shared checkpoint root
    seg2 = trainer.run_segment(4, target_world=2, resume=True)
    assert trainer.segments[-1]["world"] == 2
    assert all(not isinstance(r, BaseException) for r in seg2.values())
    # replicated params bit-identical across the re-grown world
    for a, b in zip(_leaves(seg2[0]), _leaves(seg2[1])):
        np.testing.assert_array_equal(a, b)
    # ... and match the uninterrupted fixed-world run within the PR-8
    # reshard tolerance: the handoff cost a reshape, not the trajectory
    for a, b in zip(base_params, _leaves(seg2[0])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


def test_preempt_raises_at_next_beat(tmp_path):
    """Unit view of the lease-revocation hook: preempt() surfaces as
    PreemptedError at a step boundary and the run can be resumed."""
    from dcnn_tpu.parallel import LeasedElasticTrainer
    from dcnn_tpu.parallel.elastic import PreemptedError  # noqa: F401

    model, loader = _elastic_bits()
    trainer = LeasedElasticTrainer(
        _make_controller_factory(model, loader, str(tmp_path / "solo")))
    controllers = {}
    orig = trainer.make_controller

    def tracking(rank, peers, sock):
        ctl = orig(rank, peers, sock)
        ctl.preempt("unit test")       # flagged before the first beat
        controllers[rank] = ctl
        return ctl
    trainer.make_controller = tracking
    res = trainer.run_segment(1, target_world=1, resume=True)
    assert res[0] == "preempted"
    # nothing ran, nothing saved — a later segment starts clean
    trainer.make_controller = orig
    res2 = trainer.run_segment(1, target_world=1, resume=True)
    assert not isinstance(res2[0], BaseException)
    assert len(res2) == 1


def test_picked_victim_that_exits_normally_declines_its_chip():
    """A victim picked for preemption whose fit() finishes some other
    way (returns normally before the next beat, evicted, crashed) never
    surrenders — the accepted surrender must be DECLINED back to the
    broker, or the phantom pending count suppresses every future
    revocation and the serving tenant stays lease-blocked forever."""
    from dcnn_tpu.parallel import LeasedElasticTrainer, TrainLease

    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(2, registry=reg)
    lease = TrainLease(broker, initial=2, min_hold=1, registry=reg)
    broker.register("serve", priority=1, held=0)
    release = threading.Event()

    class FakeCtl:
        def __init__(self):
            self.preempted = threading.Event()

        def preempt(self, reason=""):
            self.preempted.set()

        def fit(self, epochs, resume=True):
            release.wait(10.0)
            return "done"          # finishes normally despite the preempt

    ctls = {}

    def make_controller(rank, peers, sock):
        ctl = FakeCtl()
        ctls[rank] = ctl
        return ctl

    trainer = LeasedElasticTrainer(make_controller, lease=lease,
                                   min_world=1, registry=reg)
    seg = threading.Thread(
        target=lambda: trainer.run_segment(1, target_world=2),
        daemon=True)
    seg.start()
    deadline = time.monotonic() + 5.0
    while len(ctls) < 2 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert broker.request("serve", 1) == 0   # shortfall fires revocation
    deadline = time.monotonic() + 5.0
    while not ctls[1].preempted.is_set() \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    assert ctls[1].preempted.is_set()        # highest rank was asked
    release.set()
    seg.join(timeout=30.0)
    assert not seg.is_alive()
    # nobody surrendered, so the pending count must be handed back ...
    assert broker.revoke_pending("train") == 0
    assert lease.held() == 2
    # ... and the claimant's next request re-fires instead of being
    # suppressed by the phantom pending
    rev0 = reg.snapshot()["lease_revocations_total"]
    assert broker.request("serve", 1) == 0
    assert reg.snapshot()["lease_revocations_total"] == rev0 + 1


def test_swap_completion_does_not_resurrect_draining_replica():
    """A decommission landing while a version load is in flight owns the
    handle: the swap's completion must not flip "draining" back to "up"
    (new traffic would route at a replica being drained, which the drain
    then force-kills at timeout — a healthy replica lost)."""
    fc = FakeClock()
    factory = make_replica_factory(fc, prefix="r")
    reps = [factory(1) for _ in range(2)]
    router = Router(reps, clock=fc, sleep=lambda s: fc.advance(s),
                    metrics=RouterMetrics(clock=fc))
    orig_swap = reps[0].swap

    def racing_swap(version):
        # decommission's drain flip lands mid-load (swap_replica's
        # draining guard only covers the other interleaving)
        with router._lock:
            router._handles[reps[0].name].state = "draining"
        return orig_swap(version)

    reps[0].swap = racing_swap
    router.swap_replica(reps[0].name, 2)
    st = router.replica_stats()[reps[0].name]
    assert st["state"] == "draining"    # NOT resurrected to "up"
    assert st["version"] == 2           # the load itself succeeded


def test_unpickable_revocation_declined_under_min_world_floor():
    """min_world can be the stricter floor (the lease clamps acceptance
    only by min_hold): the accepted-but-unpickable remainder must be
    declined back, or the phantom pending suppresses every future
    revocation and the serving tenant is lease-starved forever while
    training idly holds a chip min_hold would permit surrendering."""
    from dcnn_tpu.parallel import LeasedElasticTrainer, TrainLease
    from dcnn_tpu.parallel.elastic import PreemptedError

    reg = MetricsRegistry()
    broker = DeviceLeaseBroker(4, registry=reg)
    lease = TrainLease(broker, initial=3, min_hold=1, registry=reg)
    broker.register("serve", priority=1, held=1)
    release = threading.Event()

    class FakeCtl:
        def __init__(self):
            self.preempted = threading.Event()

        def preempt(self, reason=""):
            self.preempted.set()

        def fit(self, epochs, resume=True):
            while not release.is_set():
                if self.preempted.wait(0.005):
                    raise PreemptedError("preempted")
            return "done"

    ctls = {}

    def make_controller(rank, peers, sock):
        ctl = FakeCtl()
        ctls[rank] = ctl
        return ctl

    trainer = LeasedElasticTrainer(make_controller, lease=lease,
                                   min_world=2, registry=reg)
    seg = threading.Thread(
        target=lambda: trainer.run_segment(1, target_world=3),
        daemon=True)
    seg.start()
    deadline = time.monotonic() + 5.0
    while len(ctls) < 3 and time.monotonic() < deadline:
        time.sleep(0.005)
    # serving asks for 2: only ONE rank is preemptable above min_world=2
    assert broker.request("serve", 2) == 0
    deadline = time.monotonic() + 5.0
    while (broker.revoke_pending("train") != 0
           or broker.free() != 1) and time.monotonic() < deadline:
        time.sleep(0.005)
    # rank 2 surrendered its chip; the undeliverable second revocation
    # was declined — NOT left as phantom pending
    assert lease.held() == 2
    assert broker.revoke_pending("train") == 0
    assert broker.free() == 1
    # the retry collects the freed chip, and the still-short request
    # re-fires a revocation instead of being suppressed
    rev0 = reg.snapshot()["lease_revocations_total"]
    assert broker.request("serve", 2) == 1
    assert reg.snapshot()["lease_revocations_total"] == rev0 + 1
    deadline = time.monotonic() + 5.0
    while broker.revoke_pending("train") != 0 \
            and time.monotonic() < deadline:
        time.sleep(0.005)
    assert broker.revoke_pending("train") == 0   # declined again
    release.set()
    seg.join(timeout=30.0)
    assert not seg.is_alive()
    assert lease.held() == 2                     # min_world floor held


def test_pick_victims_never_repicks_inflight_preemption():
    """A second revocation arriving while a victim is still mid-exit
    must pick a DIFFERENT rank: re-picking the first would consume the
    revocation on an idempotent Event.set that frees no additional chip,
    wedging the lease accounting permanently."""
    from dcnn_tpu.parallel import LeasedElasticTrainer

    class FakeCtl:
        def __init__(self):
            self.preempts = 0

        def preempt(self, reason=""):
            self.preempts += 1

    trainer = LeasedElasticTrainer(lambda *a: None, min_world=1)
    ctls = {r: FakeCtl() for r in range(3)}
    trainer._live.update(ctls)
    trainer._on_revoke(1)
    assert ctls[2].preempts == 1           # highest rank first
    # rank 2 is mid-exit (still registered): the next revocation must
    # land on rank 1, not re-consume on rank 2
    trainer._on_revoke(1)
    assert ctls[2].preempts == 1 and ctls[1].preempts == 1
    assert trainer._deferred_revoke == 0
    # min_world floor counts only ranks actually staying
    trainer._on_revoke(1)
    assert ctls[0].preempts == 0           # floor of 1 holds
    assert trainer._deferred_revoke == 1   # deferred, not dropped
    # a victim that finished exiting clears its pending mark
    with trainer._lock:
        trainer._live.pop(2)
        trainer._preempt_pending.discard(2)
    assert 2 not in trainer._preempt_pending
