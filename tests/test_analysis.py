"""Static-analysis suite: fixture tests per check id + the live gate.

Contract per check id (TS01-TS05, CC01-CC03, AT01), each as its own
test so a disabled/broken detector fails its own named test:

- a minimal positive fixture produces the finding;
- the same fixture with ``# dcnn: disable=<id>`` on the offending line
  is inline-suppressed;
- a baseline entry carrying the finding's stable key suppresses it;
- the corrected/clean twin produces nothing.

Plus: check-id attribution (running only other checks on a positive
fixture yields nothing), CLI exit codes / JSON shape / --write-baseline
round-trip, and the tier-1 gate — the LIVE package analyzed with the
committed baseline has zero unsuppressed findings, in well under the
30 s budget. Fixtures are parsed, never imported or executed.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from dcnn_tpu.analysis import (Baseline, DEFAULT_BASELINE, all_checks,
                               analyze_paths, unsuppressed)

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "dcnn_tpu")


def run_snippet(tmp_path, src, *, rel="snippet.py", checks=None,
                baseline=None, phase="p0"):
    """Write ``src`` at <tmp>/<phase>/pkg/<rel> and analyze the pkg root:
    display paths (= baseline-key paths) come out as ``pkg/<rel>`` for
    EVERY phase, so keys from one phase's findings address another
    phase's file — exactly how the committed baseline addresses the live
    tree — while each phase still analyzes only its own fixture."""
    root = tmp_path / phase / "pkg"
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return analyze_paths([str(root)], checks=checks, baseline=baseline)


def live(findings):
    return unsuppressed(findings)


def _quad(tmp_path, check_id, positive, clean, *, rel="snippet.py"):
    """The four-way contract shared by every check id."""
    hits = live(run_snippet(tmp_path, positive, rel=rel))
    assert [f.check_id for f in hits].count(check_id) >= 1, \
        f"{check_id} positive fixture produced {hits}"
    hit = next(f for f in hits if f.check_id == check_id)

    # inline suppression on the offending line
    lines = textwrap.dedent(positive).splitlines()
    lines[hit.line - 1] += f"  # dcnn: disable={check_id}"
    sup = run_snippet(tmp_path, "\n".join(lines) + "\n", rel=rel,
                      phase="inline")
    sup_hits = [f for f in sup if f.check_id == check_id]
    assert sup_hits and all(f.suppressed_by == "inline" for f in sup_hits)

    # baseline suppression via the stable key (identical display path ->
    # identical key across phases)
    base = Baseline({f.key: "accepted for test" for f in hits})
    based = run_snippet(tmp_path, positive, rel=rel, phase="baseline",
                        baseline=base)
    based_hits = [f for f in based if f.check_id == check_id]
    assert based_hits and all(f.suppressed_by == "baseline"
                              for f in based_hits)

    # the clean twin passes
    assert not [f for f in live(run_snippet(tmp_path, clean, rel=rel,
                                            phase="clean"))
                if f.check_id == check_id]

    # attribution: every OTHER check stays silent on this positive fixture
    others = [c for c in all_checks() if c != check_id]
    others_hits = live(run_snippet(tmp_path, positive, rel=rel,
                                   phase="attr", checks=others))
    assert not [f for f in others_hits if f.check_id == check_id]
    return hit


# ---------------------------------------------------------------- TS01 --
def test_ts01_host_sync(tmp_path):
    hit = _quad(tmp_path, "TS01", """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x).sum()
        """, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.asarray(x).sum()
        """)
    assert hit.detail == "np.asarray"


def test_ts01_item_and_factory_entry(tmp_path):
    # the jax.jit(step, ...) factory idiom must be a root too
    hits = live(run_snippet(tmp_path, """
        import jax

        def make_step(model):
            def step(ts, x):
                loss = model(ts, x)
                host = loss.item()
                return host
            return jax.jit(step, donate_argnums=(0,))
        """))
    assert any(f.check_id == "TS01" and f.detail == "item" for f in hits)


def test_ts01_propagates_through_called_helper(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import jax

        def helper(v):
            return v.block_until_ready()

        @jax.jit
        def step(x):
            return helper(x)
        """))
    assert any(f.check_id == "TS01" and f.symbol == "helper" for f in hits)


# ---------------------------------------------------------------- TS02 --
def test_ts02_host_cast(tmp_path):
    _quad(tmp_path, "TS02", """
        import jax

        @jax.jit
        def step(x):
            return float(x) * 2.0
        """, """
        import jax

        @jax.jit
        def step(x):
            return float(x.shape[0]) * x
        """)


# ---------------------------------------------------------------- TS03 --
def test_ts03_trace_print(tmp_path):
    _quad(tmp_path, "TS03", """
        import jax

        @jax.jit
        def step(x):
            print("loss", x)
            return x
        """, """
        import jax

        @jax.jit
        def step(x):
            jax.debug.print("loss {}", x)
            return x
        """)


# ---------------------------------------------------------------- TS04 --
def test_ts04_global_rng(tmp_path):
    _quad(tmp_path, "TS04", """
        import numpy as np

        def pick(n):
            return np.random.randint(0, 10, size=n)
        """, """
        import numpy as np

        def pick(n, rng: np.random.Generator):
            return rng.integers(0, 10, size=n)
        """, rel="data/augment.py")


def test_ts04_only_in_contract_modules(tmp_path):
    # the same global draw OUTSIDE a determinism-contract module is fine
    hits = live(run_snippet(tmp_path, """
        import numpy as np

        def pick(n):
            return np.random.randint(0, 10, size=n)
        """, rel="util.py"))
    assert not [f for f in hits if f.check_id == "TS04"]


# ---------------------------------------------------------------- TS05 --
def test_ts05_trace_impure(tmp_path):
    _quad(tmp_path, "TS05", """
        import jax

        LOSSES = []

        @jax.jit
        def step(x):
            LOSSES.append(x)
            return x
        """, """
        import jax

        @jax.jit
        def step(x):
            losses = []
            losses.append(x)
            return x
        """)


def test_ts05_api_update_call_not_flagged(tmp_path):
    # opt.update(...) whose result is consumed is an API call returning
    # new state, not a dict mutation (the live make_train_step pattern)
    hits = live(run_snippet(tmp_path, """
        import jax

        def make(opt):
            def step(ts, g):
                new_params, new_opt = opt.update(g, ts)
                return new_params, new_opt
            return jax.jit(step)
        """))
    assert not [f for f in hits if f.check_id == "TS05"]


# ---------------------------------------------------------------- CC01 --
_CC01_POSITIVE = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            self._n += 1

        def read(self):
            return self._n

        def stop(self):
            self._t.join()
    """

_CC01_CLEAN = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # dcnn: guarded_by=_lock
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            with self._lock:
                self._n += 1

        def read(self):
            with self._lock:
                return self._n

        def stop(self):
            self._t.join()
    """


def test_cc01_guarded_by(tmp_path):
    hit = _quad(tmp_path, "CC01", _CC01_POSITIVE, _CC01_CLEAN)
    assert hit.detail == "_n"
    assert "guarded_by" in hit.message


def test_cc01_annotated_but_unlocked_access(tmp_path):
    # annotation alone is not enough: the read outside the lock is flagged
    src = _CC01_CLEAN.replace(
        "        def read(self):\n"
        "            with self._lock:\n"
        "                return self._n",
        "        def read(self):\n"
        "            return self._n")
    hits = live(run_snippet(tmp_path, src))
    assert any(f.check_id == "CC01" and "outside 'with self._lock'"
               in f.message for f in hits)


def test_cc01_nested_thread_body_reaches_methods(tmp_path):
    # Thread(target=<nested fn>) whose body calls self.m — the live
    # StallWatchdog.start shape
    hits = live(run_snippet(tmp_path, """
        import threading

        class Dog:
            def __init__(self):
                self._flagged = False

            def check(self):
                self._flagged = True

            def start(self):
                def loop():
                    self.check()
                t = threading.Thread(target=loop, daemon=True)
                t.start()
                return t

            def beat(self):
                self._flagged = False

            def stop(self):
                pass
        """))
    assert any(f.check_id == "CC01" and f.detail == "_flagged" for f in hits)


# ---------------------------------------------------------------- CC02 --
def test_cc02_thread_lifecycle(tmp_path):
    _quad(tmp_path, "CC02", """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
            return t
        """, """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        """)


def test_cc02_daemon_with_finalizer_ok(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def stop(self):
                pass
        """))
    assert not [f for f in hits if f.check_id == "CC02"]


# ---------------------------------------------------------------- CC03 --
def test_cc03_resource_lifecycle(tmp_path):
    _quad(tmp_path, "CC03", """
        from multiprocessing import shared_memory

        class Ring:
            def __init__(self):
                self.seg = shared_memory.SharedMemory(create=True, size=16)

            def close(self):
                self.seg.close()
        """, """
        from multiprocessing import shared_memory

        class Ring:
            def __init__(self):
                self.seg = shared_memory.SharedMemory(create=True, size=16)

            def close(self):
                self.seg.close()

            def __del__(self):
                self.close()
        """)


def test_cc03_with_block_and_local_close_ok(tmp_path):
    hits = live(run_snippet(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def a():
            with ThreadPoolExecutor(max_workers=1) as pool:
                return pool.submit(len, ()).result()

        def b():
            pool = ThreadPoolExecutor(max_workers=1)
            try:
                return pool.submit(len, ()).result()
            finally:
                pool.shutdown()
        """))
    assert not [f for f in hits if f.check_id == "CC03"]


# ---------------------------------------------------------------- AT01 --
def test_at01_atomic_commit(tmp_path):
    _quad(tmp_path, "AT01", """
        def save(path, text):
            with open(path, "w") as f:
                f.write(text)
        """, """
        import os

        def save(path, text):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        """)


def test_at01_np_save_and_helper_exemption(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import numpy as np

        def cache(path, x):
            np.savez(path, x=x)
        """))
    assert any(f.check_id == "AT01" and f.detail == "np.savez" for f in hits)
    hits = live(run_snippet(tmp_path, """
        from dcnn_tpu.resilience.atomic import write_file_atomic

        def cache(path, data):
            write_file_atomic(path, data)
        """, rel="ok.py", phase="helper"))
    assert not [f for f in hits if f.check_id == "AT01"]


# ---------------------------------------------------------------- DL01 --
_DL01_POSITIVE = """
    import threading

    class A:
        def __init__(self):
            self._la = threading.Lock()
            self.b = B()

        def foo(self):
            with self._la:
                self.b.bar()

        def quux(self):
            with self._la:
                pass

    class B:
        def __init__(self):
            self._lb = threading.Lock()
            self.a = A()

        def bar(self):
            with self._lb:
                pass

        def back(self):
            with self._lb:
                self.a.quux()
    """

_DL01_CLEAN = _DL01_POSITIVE.replace(
    "        def back(self):\n"
    "            with self._lb:\n"
    "                self.a.quux()",
    "        def back(self):\n"
    "            self.a.quux()")


def test_dl01_lock_order_cycle(tmp_path):
    hit = _quad(tmp_path, "DL01", _DL01_POSITIVE, _DL01_CLEAN)
    assert "A._la" in hit.detail and "B._lb" in hit.detail


def test_dl01_edges_and_cycle_canonical(tmp_path):
    """The acquisition graph records cross-class edges (attribute-typed
    resolution) and reports each cycle exactly once."""
    from dcnn_tpu.analysis.core import load_project
    from dcnn_tpu.analysis.locks import LockAnalysis, _cycles
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent(_DL01_POSITIVE))
    a = LockAnalysis(load_project([str(root)]))
    edges = set(a.edges)
    assert ("m.A._la", "m.B._lb") in edges
    assert ("m.B._lb", "m.A._la") in edges
    cycles = _cycles(a.edges)
    assert len(cycles) == 1 and set(cycles[0]) == {"m.A._la", "m.B._lb"}


def test_dl01_annotation_typed_attr_resolves(tmp_path):
    # the deferred-construction idiom: typing comes from the AnnAssign
    hits = live(run_snippet(tmp_path, """
        import threading
        from typing import Optional

        class Chan:
            def __init__(self):
                self._cl = threading.Lock()

            def send(self):
                with self._cl:
                    pass

        class Owner:
            def __init__(self):
                self._ol = threading.Lock()
                self.chan: Optional[Chan] = None

            def push(self):
                with self._ol:
                    self.chan.send()

        class Back:
            def __init__(self):
                self._cl2 = threading.Lock()

        def hold(o: Owner, c: Chan):
            with c._cl:
                pass
        """, checks=["DL01"]))
    # no cycle — but the edge machinery resolved Owner._ol -> Chan._cl
    from dcnn_tpu.analysis.core import load_project
    from dcnn_tpu.analysis.locks import LockAnalysis
    root = tmp_path / "p0" / "pkg"
    a = LockAnalysis(load_project([str(root)]))
    assert ("snippet.Owner._ol", "snippet.Chan._cl") in a.edges
    assert not hits


# ---------------------------------------------------------------- DL02 --
def test_dl02_blocking_under_lock(tmp_path):
    hit = _quad(tmp_path, "DL02", """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    time.sleep(0.5)
        """, """
        import threading
        import time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def spin(self):
                with self._lock:
                    pass
                time.sleep(0.5)
        """)
    assert "sleep" in hit.detail


def test_dl02_transitive_frame_send(tmp_path):
    # the wedge class PRs 8-13 fixed by hand: a framed-channel send
    # reached through a helper while the caller holds its lock
    hits = live(run_snippet(tmp_path, """
        import threading

        class Mesh:
            def __init__(self, chan):
                self._lock = threading.Lock()
                self.chan = chan

            def _ship(self):
                self.chan.send("BEAT", {})

            def beat(self):
                with self._lock:
                    self._ship()
        """, checks=["DL02"]))
    assert any(f.check_id == "DL02" and f.symbol == "Mesh.beat"
               for f in hits)


def test_dl02_queue_get_and_future_result(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import queue
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def a(self):
                with self._lock:
                    return self._q.get(timeout=1.0)

            def b(self, fut):
                with self._lock:
                    return fut.result()

            def ok(self, d):
                with self._lock:
                    return d.get("key")  # dict get: not blocking
        """, checks=["DL02"]))
    assert sum(1 for f in hits if f.check_id == "DL02") == 2


def test_dl01_lexical_nesting_and_multi_item_with(tmp_path):
    """Same-statement orderings must reach the graph: nested ``with``
    blocks in one function, and multi-item ``with A, B:`` (which
    acquires A then B — the textbook AB/BA deadlock shape)."""
    from dcnn_tpu.analysis.core import load_project
    from dcnn_tpu.analysis.locks import LockAnalysis
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def nested(self):
                with self._la:
                    with self._lb:
                        pass

            def multi(self):
                with self._lb, self._la:
                    pass
        """))
    a = LockAnalysis(load_project([str(root)]))
    assert ("m.C._la", "m.C._lb") in a.edges   # lexical nesting
    assert ("m.C._lb", "m.C._la") in a.edges   # multi-item ordering
    hits = live(run_snippet(tmp_path, """
        import threading

        class C:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()

            def nested(self):
                with self._la:
                    with self._lb:
                        pass

            def multi(self):
                with self._lb, self._la:
                    pass
        """, checks=["DL01"], phase="multiwith"))
    assert any(f.check_id == "DL01" for f in hits)


def test_dl01_mutual_recursion_does_not_poison_memo(tmp_path):
    """A cycle-truncated _acquires result must not be cached: after
    resolving a mutually-recursive pair from one entry point, an
    unrelated caller's edge into the pair must still be recorded."""
    from dcnn_tpu.analysis.core import load_project
    from dcnn_tpu.analysis.locks import LockAnalysis
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent("""
        import threading

        class C:
            def __init__(self):
                self._la = threading.Lock()
                self._lb = threading.Lock()
                self._ld = threading.Lock()

            def a(self, n):
                with self._la:
                    pass
                self.b(n)

            def b(self, n):
                with self._lb:
                    pass
                self.a(n)

            def c(self):
                self.a(1)

            def d(self):
                with self._ld:
                    self.b(1)
        """))
    a = LockAnalysis(load_project([str(root)]))
    assert ("m.C._ld", "m.C._la") in a.edges
    assert ("m.C._ld", "m.C._lb") in a.edges


# ---------------------------------------------------------------- PR01 --
_PR01_POSITIVE = """
    class Client:
        def request(self, ch):  # dcnn: protocol=demo role=sender
            ch.send("PING", {})
            ch.send("QUERY", {})

    class Server:
        def pump(self, cmd, meta):  # dcnn: protocol=demo role=handler
            if cmd == "PING":
                return "pong"
    """

_PR01_CLEAN = _PR01_POSITIVE.replace(
    '            if cmd == "PING":\n                return "pong"',
    '            if cmd == "PING":\n                return "pong"\n'
    '            if cmd == "QUERY":\n                return "result"')


def test_pr01_frame_unhandled(tmp_path):
    hit = _quad(tmp_path, "PR01", _PR01_POSITIVE, _PR01_CLEAN)
    assert hit.detail == "demo:QUERY"


def test_pr01_no_handler_and_wildcard(tmp_path):
    hits = live(run_snippet(tmp_path, """
        class OnlySender:
            def go(self, ch):  # dcnn: protocol=orphan role=sender
                ch.send("X", {})
        """, checks=["PR01"], phase="nohandler"))
    assert any(f.detail == "orphan:<no-handler>" for f in hits)
    hits = live(run_snippet(tmp_path, """
        class S:
            def go(self, ch):  # dcnn: protocol=wild role=sender
                ch.send("X", {})

        class H:
            def pump(self, cmd):  # dcnn: protocol=wild role=handler frames=*
                pass
        """, checks=["PR01"], phase="wildcard"))
    assert not hits


def test_pr01_declared_frames_and_line_rebind(tmp_path):
    # frames= declares dynamically-consumed arms; a line-scoped
    # annotation rebinds one send to another protocol
    hits = live(run_snippet(tmp_path, """
        class S:
            def go(self, ch):  # dcnn: protocol=a role=sender
                ch.send("X", {})
                ch.send("Y", {})  # dcnn: protocol=b

        class HA:
            def pump(self, cmd):  # dcnn: protocol=a role=handler frames=X
                pass

        class HB:
            def pump(self, cmd):  # dcnn: protocol=b role=handler
                if cmd == "Y":
                    return 1
        """, checks=["PR01"], phase="declared"))
    assert not hits


def test_pr01_line_rebind_does_not_leak_to_adjacent_send(tmp_path):
    """A trailing line annotation on one send must not rebind the send
    starting on the very next line."""
    hits = live(run_snippet(tmp_path, """
        class S:
            def go(self, ch):  # dcnn: protocol=main role=sender
                ch.send("A", {})  # dcnn: protocol=side
                ch.send("B", {})

        class HM:
            def pump(self, cmd):  # dcnn: protocol=main role=handler
                if cmd == "B":
                    return 1

        class HS:
            def pump(self, cmd):  # dcnn: protocol=side role=handler
                if cmd == "A":
                    return 1
        """, checks=["PR01"], phase="adjacent"))
    # B stays on 'main' (handled), A rebinds to 'side' (handled) — a
    # leak would move B to 'side' where it has no arm
    assert not hits


# ---------------------------------------------------------------- PR02 --
_PR02_POSITIVE = """
    class Coord:
        def kick(self, ch):  # dcnn: protocol=gens role=sender
            ch.send("JOB", {"gen": 3, "mb": 1})

    class Worker:
        def __init__(self):
            self.gen = 0

        def pump(self, cmd, meta, payload):  # dcnn: protocol=gens role=handler
            if cmd == "JOB":
                return payload * 2
    """

_PR02_CLEAN = _PR02_POSITIVE.replace(
    '            if cmd == "JOB":\n                return payload * 2',
    '            if cmd == "JOB":\n'
    '                if meta.get("gen") != self.gen:\n'
    '                    return None\n'
    '                return payload * 2')


def test_pr02_unfenced_stamp(tmp_path):
    hit = _quad(tmp_path, "PR02", _PR02_POSITIVE, _PR02_CLEAN)
    assert hit.detail == "gens:JOB:gen"


def test_pr02_global_fence_and_drop_arm(tmp_path):
    # a loop-level fence (outside every arm) covers every frame; a
    # drop-only arm needs no fence
    hits = live(run_snippet(tmp_path, """
        class Coord:
            def kick(self, ch):  # dcnn: protocol=g2 role=sender
                ch.send("JOB", {"gen": 3})
                ch.send("TICK", {"gen": 3})

        class W:
            def __init__(self):
                self.gen = 0

            def pump(self, cmd, meta):  # dcnn: protocol=g2 role=handler
                if meta.get("gen") != self.gen:
                    return None
                if cmd == "JOB":
                    return 1
                if cmd == "TICK":
                    pass
        """, checks=["PR02"], phase="globalfence"))
    assert not hits


def test_pr02_echo_does_not_leak_across_elif_arms(tmp_path):
    # an echo in a LATER elif arm must not exempt an EARLIER arm's
    # unfenced use of the same stamp key (the elif chain nests in the
    # first If's orelse — a whole-node walk would swallow it)
    hits = live(run_snippet(tmp_path, """
        class Coord:
            def kick(self, ch):  # dcnn: protocol=leak role=sender
                ch.send("JOB", {"gen": 1})
                ch.send("CHECK", {"gen": 1})

        class W:
            def pump(self, cmd, meta, payload, ch):  # dcnn: protocol=leak role=handler
                if cmd == "JOB":
                    return payload * 2
                elif cmd == "CHECK":
                    ch.send("ACK", {"gen": meta.get("gen")})
        """, checks=["PR02"], phase="eleak"))
    assert [f.detail for f in hits] == ["leak:JOB:gen"]


def test_cli_only_filter_keeps_whole_project_accuracy(tmp_path):
    """--only analyzes everything but reports just the named files — a
    sender-only file must NOT produce a '<no-handler>' PR01 finding when
    its handler lives in an unreported sibling (the check.sh
    --changed-only contract)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "sender.py").write_text(textwrap.dedent("""
        class S:
            def go(self, ch):  # dcnn: protocol=x role=sender
                ch.send("PING", {})
    """))
    (pkg / "handler.py").write_text(textwrap.dedent("""
        class H:
            def pump(self, cmd):  # dcnn: protocol=x role=handler
                if cmd == "PING":
                    return 1
    """))
    r = _cli(str(pkg), "--no-baseline", "--only", "pkg/sender.py")
    assert r.returncode == 0, r.stdout + r.stderr
    # scoping to the handler file with the handler arm REMOVED must
    # still flag — the filter narrows the report, not the analysis
    (pkg / "handler.py").write_text(textwrap.dedent("""
        class H:
            def pump(self, cmd):  # dcnn: protocol=x role=handler
                pass
    """))
    r = _cli(str(pkg), "--no-baseline", "--only", "pkg/handler.py")
    assert r.returncode == 1 and "PR01" in r.stdout


def test_pr02_echo_exempt(tmp_path):
    # the responder half of a nonce round-trip echoes the stamp for the
    # REQUESTER to fence — no comparison required on the responder
    hits = live(run_snippet(tmp_path, """
        class Coord:
            def probe(self, ch):  # dcnn: protocol=g3 role=sender
                ch.send("CHECK", {"nonce": 7})

        class W:
            def pump(self, cmd, meta, ch):  # dcnn: protocol=g3 role=handler
                if cmd == "CHECK":
                    ch.send("ACK", {"nonce": meta.get("nonce")})
        """, checks=["PR02"], phase="echo"))
    assert not hits


def test_protocol_map_stamps_and_aliases(tmp_path):
    from dcnn_tpu.analysis.core import load_project
    from dcnn_tpu.analysis.protocol import ProtocolMap
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "m.py").write_text(textwrap.dedent("""
        class S:
            def ship(self, ch):  # dcnn: protocol=pm role=sender
                meta = {"gen": 1, "size": 4}
                ch.send("CONFIG", meta, raw=b"x")

            def round(self, ch, req):  # dcnn: protocol=pm role=sender frames=ASK
                ch.send(req, {"nonce": 9})
        """))
    pm = ProtocolMap(load_project([str(root)]))
    assert set(pm.emitted["pm"]) == {"CONFIG", "ASK"}
    assert pm.stamps["pm"]["CONFIG"] == {"gen"}   # dict-literal alias
    assert pm.stamps["pm"]["ASK"] == {"nonce"}    # declared-frame stamp


# ---------------------------------------------------------------- TS06 --
def test_ts06_jit_of_lambda(tmp_path):
    hit = _quad(tmp_path, "TS06", """
        import jax

        def make():
            return jax.jit(lambda x: x * 2)
        """, """
        import jax

        def _double(x):
            return x * 2

        step = jax.jit(_double)
        """)
    assert hit.detail == "lambda"


def test_ts06_jit_per_call_and_in_loop(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import jax

        def f(x):
            return x + 1

        def run(xs):
            out = []
            for x in xs:
                out.append(jax.jit(f)(x))
            return out
        """, checks=["TS06"], phase="percall"))
    details = {f.detail for f in hits if f.check_id == "TS06"}
    assert "jit-per-call" in details


def test_ts06_static_churn_and_shape_varying(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import jax

        def f(x, n):
            return x * n

        step = jax.jit(f, static_argnums=(1,))

        def drive(x, batch):
            a = step(x, len(batch))     # static churn: recompile per len
            b = step(x[:len(batch)], 1)  # shape-varying traced arg
            return a, b
        """, checks=["TS06"], phase="churn"))
    details = {f.detail for f in hits if f.check_id == "TS06"}
    assert "step:static#1" in details
    assert "step:shape#0" in details
    # constants and bare names in static positions are fine
    clean = live(run_snippet(tmp_path, """
        import jax

        def f(x, n):
            return x * n

        step = jax.jit(f, static_argnums=(1,))

        def drive(x, flag):
            return step(x, 4), step(x, flag)
        """, checks=["TS06"], phase="churnclean"))
    assert not [f for f in clean if f.check_id == "TS06"]


def test_ts06_static_argnames_kwarg(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import jax

        def f(x, mode=0):
            return x * mode

        step = jax.jit(f, static_argnames=("mode",))

        def drive(x, items):
            return step(x, mode=len(items))
        """, checks=["TS06"], phase="kwname"))
    assert any(f.detail == "step:static:mode" for f in hits)


# ------------------------------------------------- coverage lints (CLI) --
def test_fault_coverage_lint(tmp_path):
    from dcnn_tpu.analysis.coverage import check_fault_coverage
    pkg = tmp_path / "pkg"
    tests = tmp_path / "tests"
    pkg.mkdir()
    tests.mkdir()
    (pkg / "prod.py").write_text(textwrap.dedent("""
        from resilience import faults as _faults

        def save():
            _faults.trip("ckpt.demo_write")

        def ship():
            _faults.trip("net.demo_send")
        """))
    (tests / "test_x.py").write_text(
        'def test_armed(plan):\n    plan.arm("ckpt.demo_write")\n')
    findings = check_fault_coverage(str(pkg), str(tests))
    assert [f.detail for f in findings] == ["net.demo_send"]
    # arming the second point clears the lint
    (tests / "test_y.py").write_text('POINT = "net.demo_send"\n')
    assert not check_fault_coverage(str(pkg), str(tests))


def test_metric_drift_lint(tmp_path):
    from dcnn_tpu.analysis.coverage import check_metric_drift
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "prod.py").write_text(textwrap.dedent("""
        def emit(reg, p):
            reg.counter("demo_requests_total").inc()
            reg.gauge(f"demo_depth_{p}").set(1)
        """))
    doc = tmp_path / "observability.md"
    doc.write_text("The series `demo_requests_total` and "
                   "`demo_depth_<class>` plus `demo_dead_total`.\n")
    findings = check_metric_drift(str(pkg), str(doc))
    assert [f.detail for f in findings] == ["demo_dead_total"]
    doc.write_text("`demo_requests_total` `demo_depth_<class>`\n")
    assert not check_metric_drift(str(pkg), str(doc))
    # an unresolvable dynamic name is itself a finding, and the
    # # dcnn: metric= declaration resolves it
    (pkg / "dyn.py").write_text(textwrap.dedent("""
        def emit(reg, name):
            reg.counter(name).inc()
        """))
    findings = check_metric_drift(str(pkg), str(doc))
    assert any(f.detail == "<unresolvable>" for f in findings)
    (pkg / "dyn.py").write_text(textwrap.dedent("""
        def emit(reg, name):
            reg.counter(name).inc()  # dcnn: metric=demo_requests_total
        """))
    assert not check_metric_drift(str(pkg), str(doc))


def test_cli_lint_flags(tmp_path):
    pkg = tmp_path / "pkg"
    tests = tmp_path / "tests"
    pkg.mkdir()
    tests.mkdir()
    (pkg / "prod.py").write_text(
        'from x import trip\n\ndef f():\n    trip("demo.point")\n')
    doc = tmp_path / "obs.md"
    doc.write_text("nothing\n")
    r = _cli(str(pkg), "--fault-coverage", "--tests", str(tests))
    assert r.returncode == 1 and "demo.point" in r.stdout
    (tests / "test_a.py").write_text('P = "demo.point"\n')
    r = _cli(str(pkg), "--fault-coverage", "--tests", str(tests))
    assert r.returncode == 0
    (pkg / "m.py").write_text(
        'def f(reg):\n    reg.counter("demo_x_total").inc()\n')
    r = _cli(str(pkg), "--metric-drift", "--doc", str(doc))
    assert r.returncode == 1 and "demo_x_total" in r.stdout
    doc.write_text("`demo_x_total`\n")
    r = _cli(str(pkg), "--metric-drift", "--doc", str(doc))
    assert r.returncode == 0


# ------------------------------------------------------------ plumbing --
def test_parse_error_is_a_finding(tmp_path):
    hits = live(run_snippet(tmp_path, "def broken(:\n"))
    assert [f.check_id for f in hits] == ["PARSE"]


def test_unknown_check_id_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown check"):
        run_snippet(tmp_path, "x = 1\n", checks=["NOPE"])


def test_every_check_id_registered():
    assert set(all_checks()) == {"TS01", "TS02", "TS03", "TS04", "TS05",
                                 "TS06", "CC01", "CC02", "CC03", "AT01",
                                 "DL01", "DL02", "PR01", "PR02"}


# ------------------------------------------------------------------ CLI --
def _cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, "-m", "dcnn_tpu.analysis", *args],
        capture_output=True, text=True, timeout=300,
        cwd=cwd or os.path.dirname(PKG_DIR))


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def save(p, t):\n"
                   "    with open(p, 'w') as f:\n"
                   "        f.write(t)\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")

    r = _cli(str(bad), "--no-baseline")
    assert r.returncode == 1
    assert "AT01" in r.stdout

    r = _cli(str(good), "--no-baseline")
    assert r.returncode == 0

    r = _cli(str(tmp_path), "--no-baseline", "--json")
    report = json.loads(r.stdout)
    assert r.returncode == 1
    assert report["unsuppressed"] == 1
    assert report["findings"][0]["check_id"] == "AT01"
    assert report["findings"][0]["key"].startswith(
        f"AT01::{tmp_path.name}/bad.py::save")

    r = _cli(str(bad), "--checks", "BOGUS")
    assert r.returncode == 2

    r = _cli("--list-checks")
    assert r.returncode == 0 and "AT01" in r.stdout

    r = _cli(str(tmp_path / "missing.py"))
    assert r.returncode == 2


def test_cli_write_baseline_round_trip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def save(p, t):\n"
                   "    with open(p, 'w') as f:\n"
                   "        f.write(t)\n")
    base = tmp_path / "baseline.json"
    r = _cli(str(bad), "--no-baseline", "--write-baseline", str(base))
    assert r.returncode == 0
    data = json.loads(base.read_text())
    assert len(data["findings"]) == 1
    # the skeleton suppresses the finding on the next run
    r = _cli(str(bad), "--baseline", str(base))
    assert r.returncode == 0


# ------------------------------------------------------- the live gate --
def test_live_package_zero_unsuppressed():
    """THE acceptance gate: the shipped package, analyzed with the
    committed baseline, is clean — and fast enough for tier-1."""
    t0 = time.perf_counter()
    findings = analyze_paths([PKG_DIR],
                             baseline=Baseline.load(DEFAULT_BASELINE))
    wall = time.perf_counter() - t0
    bad = unsuppressed(findings)
    assert not bad, "unsuppressed findings in the live tree:\n" + "\n".join(
        f.render() for f in bad)
    # every baseline entry must still match a real finding — a stale key
    # is a fixed defect whose baseline entry now hides nothing and rots
    matched = {f.key for f in findings if f.suppressed_by == "baseline"}
    stale = set(Baseline.load(DEFAULT_BASELINE).entries) - matched
    assert not stale, f"stale baseline entries: {sorted(stale)}"
    assert wall < 30.0, f"analysis took {wall:.1f}s (budget 30s)"


def test_live_cli_exit_zero():
    r = _cli("dcnn_tpu")
    assert r.returncode == 0, r.stdout + r.stderr
