"""Static-analysis suite: fixture tests per check id + the live gate.

Contract per check id (TS01-TS05, CC01-CC03, AT01), each as its own
test so a disabled/broken detector fails its own named test:

- a minimal positive fixture produces the finding;
- the same fixture with ``# dcnn: disable=<id>`` on the offending line
  is inline-suppressed;
- a baseline entry carrying the finding's stable key suppresses it;
- the corrected/clean twin produces nothing.

Plus: check-id attribution (running only other checks on a positive
fixture yields nothing), CLI exit codes / JSON shape / --write-baseline
round-trip, and the tier-1 gate — the LIVE package analyzed with the
committed baseline has zero unsuppressed findings, in well under the
30 s budget. Fixtures are parsed, never imported or executed.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from dcnn_tpu.analysis import (Baseline, DEFAULT_BASELINE, all_checks,
                               analyze_paths, unsuppressed)

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "dcnn_tpu")


def run_snippet(tmp_path, src, *, rel="snippet.py", checks=None,
                baseline=None, phase="p0"):
    """Write ``src`` at <tmp>/<phase>/pkg/<rel> and analyze the pkg root:
    display paths (= baseline-key paths) come out as ``pkg/<rel>`` for
    EVERY phase, so keys from one phase's findings address another
    phase's file — exactly how the committed baseline addresses the live
    tree — while each phase still analyzes only its own fixture."""
    root = tmp_path / phase / "pkg"
    path = root / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    return analyze_paths([str(root)], checks=checks, baseline=baseline)


def live(findings):
    return unsuppressed(findings)


def _quad(tmp_path, check_id, positive, clean, *, rel="snippet.py"):
    """The four-way contract shared by every check id."""
    hits = live(run_snippet(tmp_path, positive, rel=rel))
    assert [f.check_id for f in hits].count(check_id) >= 1, \
        f"{check_id} positive fixture produced {hits}"
    hit = next(f for f in hits if f.check_id == check_id)

    # inline suppression on the offending line
    lines = textwrap.dedent(positive).splitlines()
    lines[hit.line - 1] += f"  # dcnn: disable={check_id}"
    sup = run_snippet(tmp_path, "\n".join(lines) + "\n", rel=rel,
                      phase="inline")
    sup_hits = [f for f in sup if f.check_id == check_id]
    assert sup_hits and all(f.suppressed_by == "inline" for f in sup_hits)

    # baseline suppression via the stable key (identical display path ->
    # identical key across phases)
    base = Baseline({f.key: "accepted for test" for f in hits})
    based = run_snippet(tmp_path, positive, rel=rel, phase="baseline",
                        baseline=base)
    based_hits = [f for f in based if f.check_id == check_id]
    assert based_hits and all(f.suppressed_by == "baseline"
                              for f in based_hits)

    # the clean twin passes
    assert not [f for f in live(run_snippet(tmp_path, clean, rel=rel,
                                            phase="clean"))
                if f.check_id == check_id]

    # attribution: every OTHER check stays silent on this positive fixture
    others = [c for c in all_checks() if c != check_id]
    others_hits = live(run_snippet(tmp_path, positive, rel=rel,
                                   phase="attr", checks=others))
    assert not [f for f in others_hits if f.check_id == check_id]
    return hit


# ---------------------------------------------------------------- TS01 --
def test_ts01_host_sync(tmp_path):
    hit = _quad(tmp_path, "TS01", """
        import jax
        import numpy as np

        @jax.jit
        def step(x):
            return np.asarray(x).sum()
        """, """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(x):
            return jnp.asarray(x).sum()
        """)
    assert hit.detail == "np.asarray"


def test_ts01_item_and_factory_entry(tmp_path):
    # the jax.jit(step, ...) factory idiom must be a root too
    hits = live(run_snippet(tmp_path, """
        import jax

        def make_step(model):
            def step(ts, x):
                loss = model(ts, x)
                host = loss.item()
                return host
            return jax.jit(step, donate_argnums=(0,))
        """))
    assert any(f.check_id == "TS01" and f.detail == "item" for f in hits)


def test_ts01_propagates_through_called_helper(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import jax

        def helper(v):
            return v.block_until_ready()

        @jax.jit
        def step(x):
            return helper(x)
        """))
    assert any(f.check_id == "TS01" and f.symbol == "helper" for f in hits)


# ---------------------------------------------------------------- TS02 --
def test_ts02_host_cast(tmp_path):
    _quad(tmp_path, "TS02", """
        import jax

        @jax.jit
        def step(x):
            return float(x) * 2.0
        """, """
        import jax

        @jax.jit
        def step(x):
            return float(x.shape[0]) * x
        """)


# ---------------------------------------------------------------- TS03 --
def test_ts03_trace_print(tmp_path):
    _quad(tmp_path, "TS03", """
        import jax

        @jax.jit
        def step(x):
            print("loss", x)
            return x
        """, """
        import jax

        @jax.jit
        def step(x):
            jax.debug.print("loss {}", x)
            return x
        """)


# ---------------------------------------------------------------- TS04 --
def test_ts04_global_rng(tmp_path):
    _quad(tmp_path, "TS04", """
        import numpy as np

        def pick(n):
            return np.random.randint(0, 10, size=n)
        """, """
        import numpy as np

        def pick(n, rng: np.random.Generator):
            return rng.integers(0, 10, size=n)
        """, rel="data/augment.py")


def test_ts04_only_in_contract_modules(tmp_path):
    # the same global draw OUTSIDE a determinism-contract module is fine
    hits = live(run_snippet(tmp_path, """
        import numpy as np

        def pick(n):
            return np.random.randint(0, 10, size=n)
        """, rel="util.py"))
    assert not [f for f in hits if f.check_id == "TS04"]


# ---------------------------------------------------------------- TS05 --
def test_ts05_trace_impure(tmp_path):
    _quad(tmp_path, "TS05", """
        import jax

        LOSSES = []

        @jax.jit
        def step(x):
            LOSSES.append(x)
            return x
        """, """
        import jax

        @jax.jit
        def step(x):
            losses = []
            losses.append(x)
            return x
        """)


def test_ts05_api_update_call_not_flagged(tmp_path):
    # opt.update(...) whose result is consumed is an API call returning
    # new state, not a dict mutation (the live make_train_step pattern)
    hits = live(run_snippet(tmp_path, """
        import jax

        def make(opt):
            def step(ts, g):
                new_params, new_opt = opt.update(g, ts)
                return new_params, new_opt
            return jax.jit(step)
        """))
    assert not [f for f in hits if f.check_id == "TS05"]


# ---------------------------------------------------------------- CC01 --
_CC01_POSITIVE = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            self._n += 1

        def read(self):
            return self._n

        def stop(self):
            self._t.join()
    """

_CC01_CLEAN = """
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self._n = 0  # dcnn: guarded_by=_lock
            self._t = threading.Thread(target=self._loop, daemon=True)
            self._t.start()

        def _loop(self):
            with self._lock:
                self._n += 1

        def read(self):
            with self._lock:
                return self._n

        def stop(self):
            self._t.join()
    """


def test_cc01_guarded_by(tmp_path):
    hit = _quad(tmp_path, "CC01", _CC01_POSITIVE, _CC01_CLEAN)
    assert hit.detail == "_n"
    assert "guarded_by" in hit.message


def test_cc01_annotated_but_unlocked_access(tmp_path):
    # annotation alone is not enough: the read outside the lock is flagged
    src = _CC01_CLEAN.replace(
        "        def read(self):\n"
        "            with self._lock:\n"
        "                return self._n",
        "        def read(self):\n"
        "            return self._n")
    hits = live(run_snippet(tmp_path, src))
    assert any(f.check_id == "CC01" and "outside 'with self._lock'"
               in f.message for f in hits)


def test_cc01_nested_thread_body_reaches_methods(tmp_path):
    # Thread(target=<nested fn>) whose body calls self.m — the live
    # StallWatchdog.start shape
    hits = live(run_snippet(tmp_path, """
        import threading

        class Dog:
            def __init__(self):
                self._flagged = False

            def check(self):
                self._flagged = True

            def start(self):
                def loop():
                    self.check()
                t = threading.Thread(target=loop, daemon=True)
                t.start()
                return t

            def beat(self):
                self._flagged = False

            def stop(self):
                pass
        """))
    assert any(f.check_id == "CC01" and f.detail == "_flagged" for f in hits)


# ---------------------------------------------------------------- CC02 --
def test_cc02_thread_lifecycle(tmp_path):
    _quad(tmp_path, "CC02", """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
            return t
        """, """
        import threading

        def spawn(fn):
            t = threading.Thread(target=fn)
            t.start()
            t.join()
        """)


def test_cc02_daemon_with_finalizer_ok(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import threading

        class Server:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def stop(self):
                pass
        """))
    assert not [f for f in hits if f.check_id == "CC02"]


# ---------------------------------------------------------------- CC03 --
def test_cc03_resource_lifecycle(tmp_path):
    _quad(tmp_path, "CC03", """
        from multiprocessing import shared_memory

        class Ring:
            def __init__(self):
                self.seg = shared_memory.SharedMemory(create=True, size=16)

            def close(self):
                self.seg.close()
        """, """
        from multiprocessing import shared_memory

        class Ring:
            def __init__(self):
                self.seg = shared_memory.SharedMemory(create=True, size=16)

            def close(self):
                self.seg.close()

            def __del__(self):
                self.close()
        """)


def test_cc03_with_block_and_local_close_ok(tmp_path):
    hits = live(run_snippet(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def a():
            with ThreadPoolExecutor(max_workers=1) as pool:
                return pool.submit(len, ()).result()

        def b():
            pool = ThreadPoolExecutor(max_workers=1)
            try:
                return pool.submit(len, ()).result()
            finally:
                pool.shutdown()
        """))
    assert not [f for f in hits if f.check_id == "CC03"]


# ---------------------------------------------------------------- AT01 --
def test_at01_atomic_commit(tmp_path):
    _quad(tmp_path, "AT01", """
        def save(path, text):
            with open(path, "w") as f:
                f.write(text)
        """, """
        import os

        def save(path, text):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(text)
            os.replace(tmp, path)
        """)


def test_at01_np_save_and_helper_exemption(tmp_path):
    hits = live(run_snippet(tmp_path, """
        import numpy as np

        def cache(path, x):
            np.savez(path, x=x)
        """))
    assert any(f.check_id == "AT01" and f.detail == "np.savez" for f in hits)
    hits = live(run_snippet(tmp_path, """
        from dcnn_tpu.resilience.atomic import write_file_atomic

        def cache(path, data):
            write_file_atomic(path, data)
        """, rel="ok.py", phase="helper"))
    assert not [f for f in hits if f.check_id == "AT01"]


# ------------------------------------------------------------ plumbing --
def test_parse_error_is_a_finding(tmp_path):
    hits = live(run_snippet(tmp_path, "def broken(:\n"))
    assert [f.check_id for f in hits] == ["PARSE"]


def test_unknown_check_id_raises(tmp_path):
    with pytest.raises(ValueError, match="unknown check"):
        run_snippet(tmp_path, "x = 1\n", checks=["NOPE"])


def test_every_check_id_registered():
    assert set(all_checks()) == {"TS01", "TS02", "TS03", "TS04", "TS05",
                                 "CC01", "CC02", "CC03", "AT01"}


# ------------------------------------------------------------------ CLI --
def _cli(*args, cwd=None):
    return subprocess.run(
        [sys.executable, "-m", "dcnn_tpu.analysis", *args],
        capture_output=True, text=True, timeout=300,
        cwd=cwd or os.path.dirname(PKG_DIR))


def test_cli_exit_codes_and_json(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def save(p, t):\n"
                   "    with open(p, 'w') as f:\n"
                   "        f.write(t)\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")

    r = _cli(str(bad), "--no-baseline")
    assert r.returncode == 1
    assert "AT01" in r.stdout

    r = _cli(str(good), "--no-baseline")
    assert r.returncode == 0

    r = _cli(str(tmp_path), "--no-baseline", "--json")
    report = json.loads(r.stdout)
    assert r.returncode == 1
    assert report["unsuppressed"] == 1
    assert report["findings"][0]["check_id"] == "AT01"
    assert report["findings"][0]["key"].startswith(
        f"AT01::{tmp_path.name}/bad.py::save")

    r = _cli(str(bad), "--checks", "BOGUS")
    assert r.returncode == 2

    r = _cli("--list-checks")
    assert r.returncode == 0 and "AT01" in r.stdout

    r = _cli(str(tmp_path / "missing.py"))
    assert r.returncode == 2


def test_cli_write_baseline_round_trip(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def save(p, t):\n"
                   "    with open(p, 'w') as f:\n"
                   "        f.write(t)\n")
    base = tmp_path / "baseline.json"
    r = _cli(str(bad), "--no-baseline", "--write-baseline", str(base))
    assert r.returncode == 0
    data = json.loads(base.read_text())
    assert len(data["findings"]) == 1
    # the skeleton suppresses the finding on the next run
    r = _cli(str(bad), "--baseline", str(base))
    assert r.returncode == 0


# ------------------------------------------------------- the live gate --
def test_live_package_zero_unsuppressed():
    """THE acceptance gate: the shipped package, analyzed with the
    committed baseline, is clean — and fast enough for tier-1."""
    t0 = time.perf_counter()
    findings = analyze_paths([PKG_DIR],
                             baseline=Baseline.load(DEFAULT_BASELINE))
    wall = time.perf_counter() - t0
    bad = unsuppressed(findings)
    assert not bad, "unsuppressed findings in the live tree:\n" + "\n".join(
        f.render() for f in bad)
    # every baseline entry must still match a real finding — a stale key
    # is a fixed defect whose baseline entry now hides nothing and rots
    matched = {f.key for f in findings if f.suppressed_by == "baseline"}
    stale = set(Baseline.load(DEFAULT_BASELINE).entries) - matched
    assert not stale, f"stale baseline entries: {sorted(stale)}"
    assert wall < 30.0, f"analysis took {wall:.1f}s (budget 30s)"


def test_live_cli_exit_zero():
    r = _cli("dcnn_tpu")
    assert r.returncode == 0, r.stdout + r.stderr
