"""Fault-tolerance subsystem tests (dcnn_tpu/resilience/; ISSUE 4).

Every claim the resilience layer makes is proven here under injected
faults, not assumed:

- atomicity: a crash at ANY FaultPlan point in a checkpoint save leaves
  ``restore_latest`` a checksum-valid checkpoint (previous one for
  pre-commit crashes, the new one for post-commit), and the v1
  ``save_checkpoint`` torn-write regression stays fixed;
- bit-exact resume: kill mid-run, restart with ``resume="auto"``, and the
  remaining loss trajectory equals an uninterrupted reference run
  float-for-float (digits28 fixture — the acceptance criterion);
- the non-finite guard: an injected NaN at step j with ``skip_step``
  leaves params/opt_state bit-identical to step j-1 and counts it; with
  ``raise`` it aborts naming the step;
- async saves never block the step loop on disk (gated fake writer: the
  training thread keeps stepping while the filesystem is wedged);
- the shared retry primitive's backoff schedule is exact (seeded rng,
  injected clock/sleep — nothing here sleeps for real).
"""

import json
import os
import random
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.config import TrainingConfig
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.obs import get_registry
from dcnn_tpu.optim import Adam, SGD
from dcnn_tpu.ops.losses import get_loss
from dcnn_tpu.resilience import (
    CheckpointManager, FaultPlan, InjectedCrash, InjectedFault, NonFiniteError,
    StallWatchdog, StepGuard, backoff_delays, restore_latest, retry_call,
    retriable,
)
from dcnn_tpu.resilience import faults
from dcnn_tpu.train.checkpoint import load_checkpoint, save_checkpoint
from dcnn_tpu.train.trainer import (
    Trainer, create_train_state, make_train_step)

CE = get_loss("softmax_crossentropy")


@pytest.fixture(autouse=True)
def _no_leftover_plan():
    yield
    faults.clear()  # a failing test must not leave a plan armed for others


def _model(name="rsl"):
    return (SequentialBuilder(name)
            .input((1, 8, 8))
            .conv2d(2, 3, 1, 1).batchnorm().activation("relu")
            .flatten().dense(4)
            .build())


def _batch(n=8, seed=0, poison=False):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, 1, 8, 8)).astype(np.float32)
    if poison:
        x[:] = np.nan
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, n)]
    return jnp.asarray(x), jnp.asarray(y)


def _host_copy(tree):
    return jax.tree_util.tree_map(
        lambda a: np.array(jax.device_get(a), copy=True), tree)


def _assert_trees_equal(a, b):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, z in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(z))


# ===================================================== FaultPlan semantics

def test_fault_plan_arming_at_times_and_counts():
    plan = FaultPlan(seed=0)
    plan.arm("p", at=1, times=1)  # exactly the second invocation fires
    with plan:
        faults.trip("p")         # invocation 0: below at=1 -> no fire
        with pytest.raises(InjectedFault) as ei:
            faults.trip("p", step=7)
        assert ei.value.invocation == 1 and ei.value.context["step"] == 7
        faults.trip("p")         # times=1 consumed
    assert plan.count("p") == 3
    # times= disarms after firing
    plan2 = FaultPlan().arm("q", times=2, exc=OSError)
    with plan2:
        for _ in range(2):
            with pytest.raises(OSError):
                faults.trip("q")
        faults.trip("q")         # disarmed
    # cleared: no active plan, trip is free
    faults.trip("p")


def test_fault_plan_bit_flip_is_seeded_and_corrupts(tmp_path):
    p = tmp_path / "blob.bin"
    p.write_bytes(bytes(range(64)))
    off1 = FaultPlan(seed=5).bit_flip(str(p))
    p.write_bytes(bytes(range(64)))
    off2 = FaultPlan(seed=5).bit_flip(str(p))
    assert off1 == off2                       # deterministic from the seed
    assert p.read_bytes() != bytes(range(64))


# ============================================================== retry.py

def test_retry_backoff_schedule_exact_and_bounded():
    sleeps, calls = [], []
    expected = list(backoff_delays(4, base=0.1, cap=0.5,
                                   rng=random.Random(3)))
    assert all(d <= 0.5 for d in expected)          # cap respected
    assert expected[0] >= 0.05                      # equal jitter >= d/2

    def flaky():
        calls.append(1)
        if len(calls) < 5:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, attempts=5, base=0.1, cap=0.5,
                      rng=random.Random(3), sleep=sleeps.append,
                      name="t_exact") == "ok"
    assert sleeps == expected                       # exact schedule

    # attempts exhausted: the last exception re-raises unwrapped
    with pytest.raises(OSError, match="always"):
        retry_call(lambda: (_ for _ in ()).throw(OSError("always")),
                   attempts=3, base=0.01, sleep=lambda s: None,
                   name="t_exhaust")


def test_retry_deadline_and_counters():
    reg = get_registry()
    before = reg.counter("retry_attempts_total").value
    t = [0.0]

    def clock():
        return t[0]

    def sleep(s):
        t[0] += s

    calls = []

    def never():
        calls.append(1)
        raise OSError("down")

    with pytest.raises(OSError):
        retry_call(never, attempts=100, base=1.0, cap=1.0, timeout=3.0,
                   sleep=sleep, clock=clock, rng=random.Random(0),
                   name="t_deadline")
    assert len(calls) < 100          # the deadline, not attempts, bounded it
    assert t[0] <= 3.0 + 1.0
    assert reg.counter("retry_attempts_total").value > before
    assert reg.counter("t_deadline_retry_attempts_total").value == \
        len(calls) - 1

    # non-matching exceptions propagate immediately, no retry
    with pytest.raises(ValueError):
        retry_call(lambda: (_ for _ in ()).throw(ValueError("no")),
                   attempts=5, retry_on=(OSError,), sleep=lambda s: None)


def test_retriable_decorator():
    calls = []

    @retriable(attempts=3, base=0.01, sleep=lambda s: None, name="t_deco")
    def sometimes(v):
        calls.append(v)
        if len(calls) < 2:
            raise OSError("once")
        return v * 2

    assert sometimes(21) == 42
    assert calls == [21, 21]


# ===================================== v1 save_checkpoint torn-write fix

def test_v1_crash_mid_save_leaves_previous_checkpoint_loadable(tmp_path):
    """Regression (ISSUE 4 satellite 1): the old open()+write left a torn
    arrays.msgpack on preemption; now a simulated crash mid-save must leave
    the PREVIOUS checkpoint fully loadable."""
    d = str(tmp_path / "ck")
    model = _model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    save_checkpoint(d, model, ts.params, ts.state, ts.opt_state, opt,
                    {"epoch": 1})
    ref = _host_copy({"p": ts.params, "o": ts.opt_state})

    step = make_train_step(model, CE, opt, donate=False)
    x, y = _batch()
    ts2, *_ = step(ts, x, y, jax.random.PRNGKey(1), 1e-3)

    with FaultPlan().arm("ckpt.write", exc=InjectedCrash):
        with pytest.raises(InjectedCrash):
            save_checkpoint(d, model, ts2.params, ts2.state, ts2.opt_state,
                            opt, {"epoch": 2})
    _, params, _, opt_state, _, md = load_checkpoint(d)
    _assert_trees_equal(ref["p"], params)
    _assert_trees_equal(ref["o"], opt_state)
    assert md["epoch"] == 1
    # and no torn tmp file shadows the real ones
    assert sorted(f for f in os.listdir(d) if not f.startswith(".")) == \
        ["arrays.msgpack", "model.json"]


# ==================================================== CheckpointManager v2

def _mgr_state(seed=0):
    model = _model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(seed))
    return model, opt, ts


def test_manager_roundtrip_manifest_and_retention(tmp_path):
    d = str(tmp_path)
    model, opt, ts = _mgr_state()
    with CheckpointManager(d, keep=2) as cm:
        for s in (1, 2, 3):
            cm.save(s, model, ts.params, ts.state, ts.opt_state, opt,
                    {"epoch": s})
        assert sorted(os.listdir(d)) == ["ckpt-00000002", "ckpt-00000003"]
        r = cm.restore_latest()
    assert r.step == 3 and r.metadata == {"epoch": 3}
    _assert_trees_equal(ts.params, r.params)
    _assert_trees_equal(ts.opt_state, r.opt_state)
    man = json.loads(open(os.path.join(r.path, "MANIFEST.json")).read())
    assert man["step"] == 3
    assert set(man["files"]) == {"model.json", "arrays.msgpack"}
    # duplicate steps are immutable
    with CheckpointManager(d, keep=2) as cm2, pytest.raises(FileExistsError):
        cm2.save(3, model, ts.params, ts.state, ts.opt_state, opt)


@pytest.mark.parametrize("point,survivor", [
    ("ckpt.write", 1),          # crash mid-stage: files partial in tmp
    ("ckpt.before_rename", 1),  # staged + manifested, never committed
    ("ckpt.after_rename", 2),   # committed: the NEW checkpoint is the truth
])
def test_crash_recovery_invariant_every_crash_point(tmp_path, point,
                                                    survivor):
    """Acceptance criterion: for EVERY crash point in a save,
    restore_latest returns a checksum-valid checkpoint — the previous one
    when the crash hit before the commit rename, the new one after."""
    d = str(tmp_path)
    model, opt, ts = _mgr_state()
    with CheckpointManager(d, keep=3) as cm:
        cm.save(1, model, ts.params, ts.state, ts.opt_state, opt,
                {"epoch": 1})
        with FaultPlan().arm(point, exc=InjectedCrash):
            with pytest.raises(InjectedCrash):
                cm.save(2, model, ts.params, ts.state, ts.opt_state, opt,
                        {"epoch": 2})
    # "restart": a fresh manager sweeps stale tmp dirs, restore scans
    with CheckpointManager(d, keep=3) as cm2:
        r = cm2.restore_latest()
        assert r is not None and r.step == survivor
        assert not [f for f in os.listdir(d) if f.startswith("tmp-")]
    _assert_trees_equal(ts.params, r.params)


def test_restore_skips_bit_flipped_checkpoint_to_newest_valid(tmp_path):
    d = str(tmp_path)
    model, opt, ts = _mgr_state()
    reg = get_registry()
    before = reg.counter("ckpt_restore_skipped_total").value
    with CheckpointManager(d, keep=3) as cm:
        cm.save(1, model, ts.params, ts.state, ts.opt_state, opt)
        cm.save(2, model, ts.params, ts.state, ts.opt_state, opt)
        FaultPlan(seed=7).bit_flip(
            os.path.join(d, "ckpt-00000002", "arrays.msgpack"))
        with pytest.warns(UserWarning, match="torn/corrupt"):
            r = cm.restore_latest()
    assert r.step == 1
    assert reg.counter("ckpt_restore_skipped_total").value == before + 1
    # both files corrupted -> nothing valid -> None
    FaultPlan(seed=8).bit_flip(
        os.path.join(d, "ckpt-00000001", "model.json"))
    with pytest.warns(UserWarning):
        assert restore_latest(d) is None


def test_corrupt_checkpoint_is_quarantined_not_blocking_resave(tmp_path):
    """Review fix: a checksum-failed newest checkpoint must be quarantined
    (renamed corrupt-*) so the resumed run can commit that step number
    again instead of dying on FileExistsError."""
    d = str(tmp_path)
    model, opt, ts = _mgr_state()
    with CheckpointManager(d, keep=3) as cm:
        cm.save(1, model, ts.params, ts.state, ts.opt_state, opt)
        cm.save(2, model, ts.params, ts.state, ts.opt_state, opt)
        FaultPlan(seed=9).bit_flip(
            os.path.join(d, "ckpt-00000002", "arrays.msgpack"))
        with pytest.warns(UserWarning, match="quarantined"):
            r = cm.restore_latest()
        assert r.step == 1
        assert any(n.startswith("corrupt-ckpt-00000002")
                   for n in os.listdir(d))
        # the recovery path's first save: same step number, no collision
        cm.save(2, model, ts.params, ts.state, ts.opt_state, opt)
        assert cm.restore_latest().step == 2
    # a fresh manager (restart) sweeps the quarantine litter
    with CheckpointManager(d, keep=3):
        assert not [n for n in os.listdir(d) if n.startswith("corrupt-")]


def test_async_check_nonblocking_probe(tmp_path):
    """Review fix: check() is the per-epoch non-blocking probe — a failed
    async save raises at the NEXT checkpoint cadence (the Trainer calls it
    before every save), not after the last epoch."""
    model, opt, ts = _mgr_state()
    gate = threading.Event()

    def broken(path, data):
        if not gate.wait(timeout=30):
            raise TimeoutError("gate never released")
        raise OSError("quota exceeded")

    cm = CheckpointManager(str(tmp_path), keep=2, io_write=broken)
    fut = cm.save_async(1, model, ts.params, ts.state, ts.opt_state, opt)
    cm.check()   # save still in flight (gated): probe keeps it, no raise
    gate.set()
    assert isinstance(fut.exception(timeout=30), OSError)  # non-raising wait
    with pytest.raises(OSError, match="quota"):
        cm.check()
    cm.check()   # inspected futures are dropped: no double-raise
    cm.close()


def test_async_metadata_is_frozen_at_save_time(tmp_path):
    """Review fix: metadata is deep-frozen on the calling thread — the
    Trainer keeps appending to its history list while the saver thread
    serializes, and the checkpoint must carry the list as it was at save
    time."""
    model, opt, ts = _mgr_state()
    gate = threading.Event()

    def gated_write(path, data):
        if not gate.wait(timeout=30):
            raise TimeoutError("gate never released")
        with open(path, "wb") as f:
            f.write(data)

    cm = CheckpointManager(str(tmp_path), keep=2, io_write=gated_write)
    history = [{"epoch": 1, "loss": 0.5}]
    cm.save_async(1, model, ts.params, ts.state, ts.opt_state, opt,
                  {"history": history})
    history.append({"epoch": 2, "loss": 0.25})   # mutate while save parked
    gate.set()
    cm.wait(timeout=30)
    cm.close()
    r = restore_latest(str(tmp_path))
    assert r.metadata["history"] == [{"epoch": 1, "loss": 0.5}]


def test_rollback_policy_requires_checkpoint_dir():
    cfg = TrainingConfig(nonfinite_policy="rollback", checkpoint_dir=None)
    with pytest.raises(ValueError, match="rollback.*checkpoint_dir"):
        Trainer(_model("nodir"), Adam(1e-3), "softmax_crossentropy",
                config=cfg)


def test_retry_if_predicate_blocks_permanent_errors():
    class FakeHTTPError(OSError):
        def __init__(self, code):
            super().__init__(f"HTTP {code}")
            self.code = code

    calls = []

    def dead_mirror():
        calls.append(1)
        raise FakeHTTPError(404)

    transient = lambda e: getattr(e, "code", None) not in range(400, 500)
    with pytest.raises(FakeHTTPError):
        retry_call(dead_mirror, attempts=4, base=0.01,
                   retry_if=transient, sleep=lambda s: None, name="t_perm")
    assert len(calls) == 1      # permanent: failed immediately, no retries


def test_restore_latest_empty_and_missing_dir(tmp_path):
    assert restore_latest(str(tmp_path)) is None
    assert restore_latest(str(tmp_path / "never_made")) is None


def test_async_save_never_blocks_on_slow_filesystem(tmp_path):
    """Acceptance criterion: the step loop's save cost is the device_get
    snapshot only. With the filesystem WEDGED (writer gated on an event
    that is not set), save_async must return and training must keep
    stepping; releasing the gate commits the checkpoint."""
    d = str(tmp_path)
    model, opt, ts = _mgr_state()
    gate = threading.Event()
    wrote = []

    def gated_write(path, data):
        if not gate.wait(timeout=30):
            raise TimeoutError("test gate never released")
        wrote.append(os.path.basename(path))
        with open(path, "wb") as f:
            f.write(data)

    cm = CheckpointManager(d, keep=2, io_write=gated_write)
    fut = cm.save_async(1, model, ts.params, ts.state, ts.opt_state, opt,
                        {"epoch": 1})
    # filesystem is hung, yet the training thread is free: run real steps
    step = make_train_step(model, CE, opt, donate=False)
    x, y = _batch()
    for i in range(3):
        ts, loss, _ = step(ts, x, y, jax.random.PRNGKey(i), 1e-3)
        assert np.isfinite(float(loss))
    assert not fut.done()            # the save is *still* parked on disk I/O
    assert cm.latest_step() is None  # nothing committed yet
    gate.set()
    cm.wait(timeout=30)
    assert fut.result(timeout=0).endswith("ckpt-00000001")
    assert cm.latest_step() == 1
    assert wrote[-1] == "MANIFEST.json"   # manifest is written last
    cm.close()


def test_async_save_failure_surfaces_in_wait(tmp_path):
    model, opt, ts = _mgr_state()

    def broken_write(path, data):
        raise OSError("disk full")

    cm = CheckpointManager(str(tmp_path), keep=2, io_write=broken_write)
    cm.save_async(1, model, ts.params, ts.state, ts.opt_state, opt)
    with pytest.raises(OSError, match="disk full"):
        cm.wait(timeout=30)
    cm.close()
    assert cm.latest_step() is None
    # a failed stage must not leave tmp litter
    assert not [f for f in os.listdir(str(tmp_path))
                if f.startswith("tmp-")]


# ========================================================== step guards

def test_guarded_step_skip_is_bit_identical_to_previous_step():
    """Acceptance criterion: a NaN step under skip_step leaves
    params/opt_state bit-identical to step j-1 and counts the skip."""
    model, opt, ts = _mgr_state()
    step = make_train_step(model, CE, opt, guard=True, donate=False)
    x, y = _batch()
    ts, loss, _, bad = step(ts, x, y, jax.random.PRNGKey(1), 1e-3)
    assert not bool(bad) and np.isfinite(float(loss))
    ref = _host_copy({"p": ts.params, "o": ts.opt_state, "s": ts.state})
    step_before = int(ts.step)

    xp, yp = _batch(poison=True)
    ts2, loss2, _, bad2 = step(ts, xp, yp, jax.random.PRNGKey(2), 1e-3)
    assert bool(bad2) and not np.isfinite(float(loss2))
    _assert_trees_equal(ref["p"], ts2.params)
    _assert_trees_equal(ref["o"], ts2.opt_state)
    _assert_trees_equal(ref["s"], ts2.state)
    assert int(ts2.step) == step_before     # the step did not count

    reg = get_registry()
    before = reg.counter("train_skipped_steps_total").value
    guard = StepGuard("skip_step")
    with pytest.warns(UserWarning, match="skipped"):
        assert guard.observe(7, True) == "skipped"
    assert reg.counter("train_skipped_steps_total").value == before + 1
    assert guard.observe(8, False) == "ok"
    assert guard.consecutive_bad == 0


def test_guard_raise_policy_names_the_step():
    guard = StepGuard("raise")
    with pytest.raises(NonFiniteError, match="step 41"):
        guard.observe(41, True, loss=float("nan"))


@pytest.mark.filterwarnings("ignore::UserWarning")  # every skip warns by design
def test_guard_rollback_after_n_consecutive():
    guard = StepGuard("rollback", rollback_after=3)
    assert guard.observe(1, True) == "skipped"
    assert guard.observe(2, True) == "skipped"
    assert guard.observe(3, True) == "rollback"
    assert guard.consecutive_bad == 0       # reset after rollback
    assert guard.observe(4, True) == "skipped"
    guard.observe(5, False)
    assert guard.observe(6, True) == "skipped"  # streak broken by good step


def test_step_guard_validation():
    with pytest.raises(ValueError, match="nonfinite_policy"):
        StepGuard("explode")
    with pytest.raises(ValueError, match="rollback_after"):
        StepGuard("rollback", rollback_after=0)


def test_stall_watchdog_flags_via_registry_sleep_free():
    t = [0.0]
    reg = get_registry()
    wd = StallWatchdog(10.0, clock=lambda: t[0], registry=reg)
    before = reg.counter("train_stall_flags_total").value
    assert not wd.check()
    t[0] = 9.0
    assert not wd.check()
    t[0] = 11.0
    with pytest.warns(UserWarning, match="stalled"):
        assert wd.check()
    assert wd.check()                        # still stalled, flagged once
    assert reg.counter("train_stall_flags_total").value == before + 1
    assert reg.gauge("train_stalled").value == 1
    t[0] = 12.0
    wd.beat()
    assert reg.gauge("train_stalled").value == 0
    assert not wd.check()


# ============================================ Trainer-level guard wiring

def _loader(n=32, seed=0):
    from dcnn_tpu.data import SyntheticClassificationLoader
    ld = SyntheticClassificationLoader(n, (1, 8, 8), 4, batch_size=8,
                                       seed=seed)
    ld.load_data()
    return ld


def test_trainer_skip_step_policy_survives_injected_nan():
    cfg = TrainingConfig(learning_rate=1e-3, snapshot_dir=None,
                        nonfinite_policy="skip_step", progress_interval=0)
    model = _model("guarded")
    opt = Adam(1e-3)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    reg = get_registry()
    before = reg.counter("train_skipped_steps_total").value
    with FaultPlan().arm("train.nonfinite_input", at=2, times=1):
        with pytest.warns(UserWarning, match="skipped"):
            ts = trainer.fit(ts, _loader(), epochs=1)
    assert reg.counter("train_skipped_steps_total").value == before + 1
    assert trainer.guard.total_skipped == 1
    assert np.isfinite(trainer.history[-1]["train_loss"])
    # and params came out finite: the NaN batch never touched state
    for leaf in jax.tree_util.tree_leaves(ts.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_trainer_raise_policy_aborts_naming_step():
    cfg = TrainingConfig(learning_rate=1e-3, snapshot_dir=None,
                        nonfinite_policy="raise", progress_interval=0)
    model = _model("raising")
    opt = Adam(1e-3)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    with FaultPlan().arm("train.nonfinite_input", at=1, times=1):
        with pytest.raises(NonFiniteError, match="step 2"):
            trainer.fit(ts, _loader(), epochs=1)


def test_trainer_rollback_policy_restores_checkpoint(tmp_path):
    cfg = TrainingConfig(learning_rate=1e-3, snapshot_dir=None,
                        nonfinite_policy="rollback", rollback_after=2,
                        checkpoint_dir=str(tmp_path), checkpoint_every=1,
                        checkpoint_async=False, progress_interval=0)
    model = _model("rollback")
    opt = Adam(1e-3)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    reg = get_registry()
    before = reg.counter("train_rollbacks_total").value
    # 32 samples / batch 8 = 4 steps/epoch; epoch 1 commits ckpt-00000001,
    # then two consecutive poisoned steps in epoch 2 (invocations 4,5 =
    # steps 5,6) push the guard past rollback_after=2
    plan = FaultPlan().arm("train.nonfinite_input", at=4, times=2)
    with plan:
        with pytest.warns(UserWarning, match="skipped"):
            ts = trainer.fit(ts, _loader(), epochs=2)
    assert reg.counter("train_rollbacks_total").value == before + 1
    assert trainer.guard.total_skipped == 2
    for leaf in jax.tree_util.tree_leaves(ts.params):
        assert np.all(np.isfinite(np.asarray(leaf)))


def test_trainer_guard_rejects_incompatible_modes():
    cfg = TrainingConfig(nonfinite_policy="skip_step", steps_per_dispatch=4)
    with pytest.raises(ValueError, match="steps_per_dispatch"):
        Trainer(_model("inc"), Adam(1e-3), "softmax_crossentropy",
                config=cfg)
    from dcnn_tpu.data.device_dataset import DeviceDataset
    cfg2 = TrainingConfig(nonfinite_policy="skip_step", snapshot_dir=None)
    tr = Trainer(_model("inc2"), Adam(1e-3), "softmax_crossentropy",
                 config=cfg2)
    rng = np.random.default_rng(0)
    ds = DeviceDataset(rng.normal(size=(32, 1, 8, 8)).astype(np.float32),
                       rng.integers(0, 4, 32), 4, batch_size=8)
    ts = create_train_state(tr.model, tr.optimizer, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="resident"):
        tr.train_epoch(ts, ds, jax.random.PRNGKey(0))


# ==================================== end-to-end: kill + resume, bit-exact

def _digits_loaders():
    from dcnn_tpu.data import MNISTDataLoader
    from dcnn_tpu.data.digits28 import ensure_digits28_csvs

    d = ensure_digits28_csvs(REPO_ROOT)
    train = MNISTDataLoader(os.path.join(d, "train.csv"),
                            data_format="NCHW", batch_size=128, seed=0)
    val = MNISTDataLoader(os.path.join(d, "test.csv"), data_format="NCHW",
                          batch_size=256, shuffle=False, drop_last=False)
    train.load_data()
    val.load_data()
    return train, val


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _digits_model(name):
    return (SequentialBuilder(name)
            .input((1, 28, 28))
            .conv2d(4, 3, 1, 1).batchnorm().activation("relu")
            .maxpool2d(2).flatten().dense(10)
            .build())


def _fit_run(name, tmpdir, epochs, resume="never", fault_plan=None):
    cfg = TrainingConfig(learning_rate=1e-3, snapshot_dir=None,
                        checkpoint_dir=tmpdir, checkpoint_every=1,
                        resume=resume, progress_interval=0, seed=11)
    model = _digits_model(name)
    opt = Adam(1e-3)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(cfg.seed))
    train, val = _digits_loaders()
    if fault_plan is not None:
        with fault_plan:
            ts = trainer.fit(ts, train, val, epochs=epochs)
    else:
        ts = trainer.fit(ts, train, val, epochs=epochs)
    return trainer, ts


def test_kill_midepoch_resume_bit_exact_digits28(tmp_path):
    """THE acceptance criterion: SIGKILL-style death mid-epoch, restart
    with resume="auto", and the loss trajectory continues bit-exact
    (float-equal per epoch) versus an uninterrupted reference run — on the
    digits28 real-image fixture."""
    ref_dir, crash_dir = str(tmp_path / "ref"), str(tmp_path / "crash")

    ref_trainer, ref_ts = _fit_run("digits_ref", ref_dir, epochs=3)

    # run 2: die mid-epoch-2 (a trip point armed as a CRASH — the process
    # would be gone; nothing after the kill point runs). digits28 train =
    # 1438 samples / batch 128 (drop_last) = 11 steps/epoch, so invocation
    # 14 = step 15 = epoch 2, step 4: epoch 1's checkpoint is committed,
    # epoch 2's never will be.
    plan = FaultPlan().arm("train.nonfinite_input", at=14,
                           exc=InjectedCrash)
    with pytest.raises(InjectedCrash):
        _fit_run("digits_kill", crash_dir, epochs=3, fault_plan=plan)
    resumed, res_ts = _fit_run("digits_res", crash_dir, epochs=3,
                               resume="auto")

    ref_h = ref_trainer.history
    res_h = resumed.history
    assert [h["epoch"] for h in res_h] == [h["epoch"] for h in ref_h]
    for hr, hc in zip(ref_h, res_h):
        assert hr["train_loss"] == hc["train_loss"], (hr, hc)  # bit-exact
        assert hr["val_acc"] == hc["val_acc"]
    _assert_trees_equal(ref_ts.params, res_ts.params)
    _assert_trees_equal(ref_ts.opt_state, res_ts.opt_state)


def test_resume_auto_restores_lr_history_and_epoch(tmp_path):
    """The cheap tier-1 twin of the slow digits28 test: synthetic data,
    2+2 epochs, same bit-exactness contract plus lr-decay continuity."""
    ref_dir, crash_dir = str(tmp_path / "ref"), str(tmp_path / "crash")

    def run(name, d, epochs, resume="never", plan=None):
        cfg = TrainingConfig(learning_rate=1e-2, lr_decay_factor=0.5,
                            snapshot_dir=None, checkpoint_dir=d,
                            checkpoint_every=1, resume=resume,
                            progress_interval=0, seed=5)
        model = _model(name)
        opt = SGD(1e-2)
        tr = Trainer(model, opt, "softmax_crossentropy", config=cfg)
        ts = create_train_state(model, opt, jax.random.PRNGKey(cfg.seed))
        if plan is not None:
            with plan:
                ts = tr.fit(ts, _loader(64, seed=2), epochs=epochs)
        else:
            ts = tr.fit(ts, _loader(64, seed=2), epochs=epochs)
        return tr, ts

    ref_tr, ref_ts = run("rs_ref", ref_dir, 4)
    plan = FaultPlan().arm("train.nonfinite_input", at=13, exc=InjectedCrash)
    with pytest.raises(InjectedCrash):
        run("rs_kill", crash_dir, 4, plan=plan)
    res_tr, res_ts = run("rs_res", crash_dir, 4, resume="auto")

    assert len(res_tr.history) == len(ref_tr.history) == 4
    for hr, hc in zip(ref_tr.history, res_tr.history):
        assert hr["train_loss"] == hc["train_loss"]
        assert hr["lr"] == hc["lr"]          # decay continued, not restarted
    _assert_trees_equal(ref_ts.params, res_ts.params)
    _assert_trees_equal(ref_ts.opt_state, res_ts.opt_state)
    # resuming a finished run trains nothing: the restored history IS the
    # full run and the epoch loop has no epochs left
    res2, _ = run("rs_noop", crash_dir, 4, resume="auto")
    assert [h["epoch"] for h in res2.history] == [1, 2, 3, 4]


# ------------------------------------------------- example import smoke

def test_resume_training_example_imports():
    """Import smoke for examples/resume_training.py (same isolation dance
    as the serve_snapshot/trace_training smokes: the examples dir must
    resolve its own `common`)."""
    import importlib
    import sys

    ex_dir = os.path.join(REPO_ROOT, "examples")
    saved_common = sys.modules.pop("common", None)
    sys.path.insert(0, ex_dir)
    try:
        mod = importlib.import_module("resume_training")
        assert callable(mod.main)
        assert callable(mod.run_training)
        assert callable(mod.demo_kill_and_resume)
    finally:
        sys.path.remove(ex_dir)
        sys.modules.pop("resume_training", None)
        sys.modules.pop("common", None)
        if saved_common is not None:
            sys.modules["common"] = saved_common


# ==================================================== streaming producer

def test_streaming_producer_fault_surfaces_to_training_loop():
    from dcnn_tpu.data.streaming import (
        StreamingDeviceDataset, make_shard_step, train_streaming_epoch)

    model = _model("stream")
    opt = SGD(1e-2)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(64, 1, 8, 8), dtype=np.uint8)
    y = rng.integers(0, 4, size=64).astype(np.int32)
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=2)
    step = make_shard_step(model, CE, opt, num_classes=4, batch_size=8,
                           shard_batches=2)
    with FaultPlan().arm("stream.produce", at=1):
        with pytest.raises(InjectedFault, match="stream.produce"):
            train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(0),
                                  lr=1e-2)
    # and the next epoch (no plan) trains clean: nothing wedged. The failed
    # epoch's shard-0 step consumed (donated) ts, so restart from a fresh
    # state — exactly what a real restart does.
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ts2, loss = train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(1),
                                      lr=1e-2)
    assert np.isfinite(loss)
