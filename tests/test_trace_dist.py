"""Cross-process distributed tracing + flight recorder (ISSUE 12).

Coverage map:

- **Trace identity / propagation units**: span id minting, nesting,
  ``inject``/``activate`` carriers, begin/end cross-thread identity, the
  disabled path staying a no-op (the <100 ns bound itself lives in
  tests/test_obs.py).
- **Wire propagation**: ``Channel.send`` auto-injects ``_trace`` over a
  real socket pair; the serving chain shares one trace_id from the
  router's ``serve.request`` root through queue → dispatch → infer, both
  in-process and across the framed TCP hop.
- **Elastic correlation**: a 3-peer fleet loses a host mid-epoch; the
  follower's restore/rebuild spans join the leader's
  ``elastic.reconfigure`` trace (the RECONF frame's ``_trace`` carrier).
- **Flight recorder**: bundle atomicity/layout, keep-K GC, per-trigger
  cooldown, disabled no-op, and the full trigger matrix — healthz
  200→503 edge, watchdog stall, non-finite guard, replica death, canary
  rollback, autoscaler SLO breach — each producing exactly one bundle
  per episode, sleep-free via injected clocks.
- **Merge CLI**: shard parsing, offset-based clock alignment, Chrome
  schema validation, bundle inspect, subprocess exit codes.
- **ACCEPTANCE**: a real kill-a-replica soak across three OS processes
  (router + two TCP replica servers, tracing on) yields ONE merged
  Perfetto-loadable trace in which the router-side request span and the
  replica-side dispatch/infer spans share a trace_id across the process
  boundary, and the injected death produces a flight bundle containing
  the correlated spans, the registry snapshot, and the 503 healthz
  reasons.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dcnn_tpu.obs import configure, get_tracer
from dcnn_tpu.obs.flight import FlightRecorder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.obs.server import TelemetryServer
from dcnn_tpu.obs.trace import (
    inspect_bundle, merge_shards, read_shard, validate_chrome,
)
from dcnn_tpu.obs.tracer import Tracer
from dcnn_tpu.parallel import comm
from dcnn_tpu.resilience.faults import FaultPlan, InjectedFault
from dcnn_tpu.serve.replica import LocalReplica, ReplicaServer, TcpReplica
from dcnn_tpu.serve.router import Router

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_ID_KEYS = ("trace_id", "span_id", "parent_id")


class FakeClock:
    __name__ = "fake_clock"

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeEngine:
    """Batcher-compatible engine without jax: logits = x + version."""

    def __init__(self, version=1, name="fake"):
        self.input_shape = (4,)
        self.max_batch = 8
        self.bucket_sizes = [1, 2, 4, 8]
        self.name = name
        self.version = version
        self.batch_invariant = True

    def pad_to_bucket(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        b = next(s for s in self.bucket_sizes if s >= n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n, 4), np.float32)])
        return x, n

    def run_padded(self, x):
        return np.asarray(x, np.float32) + self.version


def fake_factory(version):
    return FakeEngine(1 if version is None else version)


@pytest.fixture
def tracer_on():
    """Process-global tracer enabled for one test; restored to the no-op
    state afterwards (other suites assert the disabled-path bound)."""
    t = configure(enabled=True)
    t.clear()
    yield t
    configure(enabled=False)
    t.clear()


def _by_name(events):
    out = {}
    for e in events:
        out.setdefault(e["name"], []).append(e)
    return out


# ---------------------------------------------------------------- identity

def test_span_identity_and_nesting():
    t = Tracer(enabled=True)
    with t.span("outer") as o:
        with t.span("inner") as i:
            assert i.trace_id == o.trace_id
            assert i.parent_id == o.span_id
            assert i.span_id != o.span_id
    with t.span("sibling") as s:
        assert s.trace_id != o.trace_id  # fresh root = fresh trace
        assert s.parent_id is None
    evs = t.events()
    assert all(e["args"]["trace_id"] and e["args"]["span_id"]
               for e in evs)


def test_inject_activate_round_trip():
    t = Tracer(enabled=True)
    assert t.inject() is None  # nothing active
    with t.span("root") as r:
        carrier = t.inject()
        assert carrier == {"trace_id": r.trace_id, "span_id": r.span_id}
    assert t.inject() is None  # exited: context popped
    # a carrier adopted on another "thread" (same thread here) parents
    # children under the foreign trace; instants inherit it too
    with t.activate(carrier):
        with t.span("child") as c:
            assert c.trace_id == r.trace_id and c.parent_id == r.span_id
        t.instant("blip")
    blip = [e for e in t.events() if e["name"] == "blip"][0]
    assert blip["args"]["trace_id"] == r.trace_id
    # malformed / absent carriers are no-op context managers
    with t.activate(None):
        assert t.inject() is None
    with t.activate({"nonsense": 1}):
        assert t.inject() is None


def test_begin_end_cross_thread_keeps_identity():
    t = Tracer(enabled=True)
    with t.span("req") as root:
        h = t.begin("q.wait", track="queue")
        assert h.trace_id == root.trace_id  # parent captured at begin

    def closer():
        # ending on another thread must not need (or touch) that
        # thread's context stack
        t.end(h, done=True)

    th = threading.Thread(target=closer)
    th.start()
    th.join()
    ev = [e for e in t.events() if e["name"] == "q.wait"][0]
    assert ev["args"]["trace_id"] == root.trace_id
    assert ev["args"]["parent_id"] == root.span_id


def test_disabled_tracer_propagation_is_noop():
    t = Tracer(enabled=False)
    assert t.inject() is None
    cm = t.activate({"trace_id": "x", "span_id": "y"})
    with cm:
        assert t.inject() is None
    sp = t.span("z")
    assert sp.context() is None  # null handle
    assert len(t) == 0


def test_explicit_parent_kwarg():
    t = Tracer(enabled=True)
    with t.span("a") as a:
        pass
    with t.span("b", parent=a.context()):
        pass
    with t.span("c", parent=a):  # a handle works as a carrier too
        pass
    evs = _by_name(t.events())
    assert evs["b"][0]["args"]["trace_id"] == a.trace_id
    assert evs["c"][0]["args"]["parent_id"] == a.span_id


# ------------------------------------------------------------- saturation

def test_ring_eviction_counts_drops_and_exports_gauges():
    t = Tracer(enabled=True, capacity=4)
    for i in range(10):
        t.instant("i", n=i)
    assert t.dropped == 6 and len(t) == 4
    reg = MetricsRegistry()
    t.export_gauges(reg)
    snap = reg.snapshot()
    assert snap["trace_events_dropped_total"] == 6
    assert snap["trace_buffer_events"] == 4
    assert snap["trace_buffer_capacity"] == 4
    # delta sync: a second export without new drops adds nothing
    t.export_gauges(reg)
    assert reg.snapshot()["trace_events_dropped_total"] == 6
    t.instant("i")
    t.export_gauges(reg)
    assert reg.snapshot()["trace_events_dropped_total"] == 7


def test_metrics_scrape_surfaces_tracer_saturation():
    t = Tracer(enabled=True, capacity=2)
    for _ in range(5):
        t.instant("x")
    reg = MetricsRegistry()
    srv = TelemetryServer(registry=reg, tracer=t, port=0)
    body = srv.metrics_body()  # the /metrics handler body, no HTTP needed
    assert "trace_events_dropped_total 3" in body
    assert "trace_buffer_events 2" in body
    snap = srv.snapshot()
    assert snap["process"]["pid"] == os.getpid()
    assert snap["process"]["trace_events_dropped"] == 3


# --------------------------------------------------------- wire propagation

def test_channel_send_injects_trace_carrier(tracer_on):
    srv = comm.listen(0, host="127.0.0.1")
    port = srv.getsockname()[1]
    ch_out = comm.connect("127.0.0.1", port, timeout=10)
    sock, _ = srv.accept()
    ch_in = comm.Channel(sock)
    try:
        with tracer_on.span("send.op") as sp:
            ch_out.send("PING", {"k": 1})
        cmd, meta, _ = ch_in.recv()
        assert cmd == "PING" and meta["k"] == 1
        assert meta["_trace"] == {"trace_id": sp.trace_id,
                                  "span_id": sp.span_id}
        # no active span -> no carrier; explicit carrier wins over active
        ch_out.send("PING", {})
        _, meta, _ = ch_in.recv()
        assert "_trace" not in meta
        with tracer_on.span("other"):
            ch_out.send("PING", {"_trace": {"trace_id": "T",
                                            "span_id": "S"}})
        _, meta, _ = ch_in.recv()
        assert meta["_trace"] == {"trace_id": "T", "span_id": "S"}
    finally:
        ch_out.close()
        ch_in.close()
        srv.close()


def test_channel_send_no_carrier_when_disabled():
    assert not get_tracer().enabled
    srv = comm.listen(0, host="127.0.0.1")
    ch_out = comm.connect("127.0.0.1", srv.getsockname()[1], timeout=10)
    sock, _ = srv.accept()
    ch_in = comm.Channel(sock)
    try:
        ch_out.send("PING", {"k": 1})
        _, meta, _ = ch_in.recv()
        assert "_trace" not in meta
    finally:
        ch_out.close()
        ch_in.close()
        srv.close()


def test_router_request_trace_spans_local_replica(tracer_on):
    """In-process chain: serve.request (router root) → serve.queue →
    serve.dispatch → serve.infer all share one trace_id; parentage is a
    chain, not a flat fan."""
    rep = LocalReplica(fake_factory, 1, name="r0", start=False)
    router = Router([rep])
    fut = router.submit(np.zeros(4, np.float32))
    rep.step(force=True)
    assert fut.result(timeout=5) is not None
    evs = _by_name(tracer_on.events())
    req = evs["serve.request"][0]["args"]
    tid = req["trace_id"]
    q = evs["serve.queue"][0]["args"]
    d = evs["serve.dispatch"][0]["args"]
    inf = evs["serve.infer"][0]["args"]
    assert q["trace_id"] == d["trace_id"] == inf["trace_id"] == tid
    assert q["parent_id"] == req["span_id"]       # queue under request
    assert d["parent_id"] == q["span_id"]         # dispatch under queue
    assert inf["parent_id"] == d["span_id"]       # infer under dispatch
    assert evs["serve.request"][0]["args"]["outcome"] == "ok"
    router.shutdown()
    rep.close()


def test_router_request_trace_crosses_tcp_boundary(tracer_on):
    """The framed hop: the infer frame's _trace carrier parents the
    server-side spans under the router's request trace (same process,
    real sockets — the cross-OS-process version is the acceptance soak).
    Mixed-trace batches keep honest parentage (trace_ids list instead of
    a fake single parent)."""
    rep = LocalReplica(fake_factory, 1, name="r0", start=True)
    srv = ReplicaServer(rep, port=0)
    cli = TcpReplica("127.0.0.1", srv.port, name="tcp0")
    router = Router([cli])
    try:
        # handshake measured a clock offset (same process: ~0)
        assert cli.clock_offset_s is not None
        assert abs(cli.clock_offset_s) < 1.0
        fut = router.submit(np.zeros(4, np.float32))
        assert fut.result(timeout=10) is not None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            evs = _by_name(tracer_on.events())
            if "serve.infer" in evs:
                break
            time.sleep(0.01)
        req = evs["serve.request"][0]["args"]
        tid = req["trace_id"]
        assert any(e["args"].get("trace_id") == tid
                   for e in evs["serve.queue"])
        assert any(e["args"].get("trace_id") == tid
                   for e in evs["serve.dispatch"])
        assert any(e["args"].get("trace_id") == tid
                   for e in evs["serve.infer"])
    finally:
        router.shutdown()
        cli.close()
        srv.close()
        rep.close()


def test_mixed_trace_batch_records_trace_ids_list(tracer_on):
    """Two requests with different traces coalescing into one dispatch:
    the dispatch span cannot claim a single parent — it records the
    trace-id list instead."""
    rep = LocalReplica(fake_factory, 1, name="r0", start=False)
    router = Router([rep])
    f1 = router.submit(np.zeros(4, np.float32))
    f2 = router.submit(np.ones(4, np.float32))
    rep.step(force=True)
    assert f1.result(timeout=5) is not None
    assert f2.result(timeout=5) is not None
    evs = _by_name(tracer_on.events())
    reqs = {e["args"]["trace_id"] for e in evs["serve.request"]}
    assert len(reqs) == 2
    d = evs["serve.dispatch"][0]["args"]
    assert set(d["trace_ids"]) == reqs
    assert "parent_id" not in d
    router.shutdown()
    rep.close()


# --------------------------------------------------------------- merge CLI

def _write_shard(path, epoch, spans, clock_name="fake"):
    fc = FakeClock(epoch)
    t = Tracer(clock=fc, enabled=True)
    for (name, t0, t1, track, attrs) in spans:
        t.record_span(name, t0, t1, track=track, **attrs)
    t.export_jsonl(path)
    return t


def test_merge_aligns_clocks_and_validates(tmp_path):
    """Two shards whose clocks disagree by exactly 100 s merge onto one
    timeline when the handshake-measured offset is passed — the span
    that happened 0.1 s after the request lands 0.1 s after it in the
    merged trace, in a Chrome file that passes schema validation."""
    a = str(tmp_path / "router.jsonl")
    b = str(tmp_path / "replica.jsonl")
    _write_shard(a, 0.0, [("serve.request", 1.0, 1.5, "router",
                           {"trace_id": "T1", "span_id": "S1"})])
    _write_shard(b, 100.0, [("serve.dispatch", 101.1, 101.3, "serve",
                             {"trace_id": "T1", "parent_id": "S1"})])
    out = str(tmp_path / "merged.json")
    summary = merge_shards([a, b], out,
                           offsets={"replica.jsonl": 100.0})
    assert validate_chrome(out) == []
    assert summary["events"] == 2 and summary["trace_ids"] == 1
    doc = json.load(open(out))
    spans = {e["name"]: e for e in doc["traceEvents"] if e["ph"] == "X"}
    req, dsp = spans["serve.request"], spans["serve.dispatch"]
    assert req["args"]["trace_id"] == dsp["args"]["trace_id"] == "T1"
    assert req["ts"] == 0.0                      # normalized to t=0
    assert abs(dsp["ts"] - 100_000.0) < 1e-6     # 0.1 s later, in µs
    assert req["pid"] != dsp["pid"]              # one pid per process
    names = [e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"]
    assert len(names) == 2


def test_merge_reads_header_offset_and_reports_drops(tmp_path):
    p = str(tmp_path / "s.jsonl")
    t = _write_shard(p, 10.0, [("op", 10.0, 10.5, "x",
                                {"trace_id": "T", "span_id": "S"})])
    # rewrite with a header-carried offset + a fake drop count
    meta, events = read_shard(p)
    assert meta["epoch_s"] == 10.0 and meta["clock"] == "fake_clock"
    t._dropped = 3
    t.export_jsonl(p)
    meta, _ = read_shard(p)
    assert meta["dropped"] == 3
    out = str(tmp_path / "m.json")
    summary = merge_shards([p], out)
    assert summary["events_dropped_by_writers"] == 3
    assert validate_chrome(out) == []


def test_merge_cli_subprocess_and_inspect(tmp_path):
    shard = str(tmp_path / "s.jsonl")
    _write_shard(shard, 0.0, [("op", 0.0, 1.0, "x",
                               {"trace_id": "T", "span_id": "S"})])
    out = str(tmp_path / "m.json")
    r = subprocess.run(
        [sys.executable, "-m", "dcnn_tpu.obs.trace", "merge", shard,
         "-o", out, "--json"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, r.stderr
    assert json.loads(r.stdout)["events"] == 1
    assert validate_chrome(out) == []
    # bad usage -> exit 2; unreadable shard -> exit 1
    r = subprocess.run([sys.executable, "-m", "dcnn_tpu.obs.trace"],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 2
    r = subprocess.run(
        [sys.executable, "-m", "dcnn_tpu.obs.trace", "merge",
         str(tmp_path / "missing.jsonl"), "-o", out],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 1
    # inspect a flight bundle end to end
    t = Tracer(enabled=True)
    with t.span("a.b", trace_marker=1):
        pass
    rec = FlightRecorder(str(tmp_path / "flight"), tracer=t,
                         registry=MetricsRegistry(), min_interval_s=0.0)
    bundle = rec.record("unit_test", reasons=["because"],
                        health={"status": "unhealthy",
                                "reasons": ["because"]})
    info = inspect_bundle(bundle)
    assert info["manifest"]["trigger"] == "unit_test"
    assert info["spans"] == 1 and info["trace_ids"] == 1
    assert info["healthz"]["status"] == "unhealthy"
    r = subprocess.run(
        [sys.executable, "-m", "dcnn_tpu.obs.trace", "inspect", bundle],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0
    assert json.loads(r.stdout)["manifest"]["trigger"] == "unit_test"
    # a bundle's spans.jsonl merges like any live shard
    summary = merge_shards([os.path.join(bundle, "spans.jsonl")],
                           str(tmp_path / "bm.json"))
    assert summary["events"] == 1


def test_validate_chrome_flags_garbage(tmp_path):
    p = str(tmp_path / "bad.json")
    with open(p, "w") as f:
        json.dump({"traceEvents": [{"ph": "X", "name": "a"}]}, f)
    problems = validate_chrome(p)
    assert problems  # missing pid/tid/ts/dur all flagged
    with open(p, "w") as f:
        f.write("not json")
    assert validate_chrome(p)


# ---------------------------------------------------------- flight recorder

def test_flight_bundle_layout_gc_cooldown(tmp_path):
    clk = FakeClock()
    t = Tracer(enabled=True)
    with t.span("x.y"):
        pass
    reg = MetricsRegistry()
    reg.counter("some_total").inc(5)
    rec = FlightRecorder(str(tmp_path / "fl"), keep=2, min_interval_s=10.0,
                         tracer=t, registry=reg, clock=clk,
                         wall_clock=lambda: 1000.0 + clk.t)
    p = rec.record("replica_death", reasons=["r0 died"],
                   health={"status": "unhealthy", "reasons": ["r0"]},
                   config={"knob": 1}, extra={"replica": "r0"})
    assert p is not None
    files = set(os.listdir(p))
    assert {"MANIFEST.json", "spans.jsonl", "metrics.json",
            "healthz.json", "config.json", "extra.json"} <= files
    man = json.load(open(os.path.join(p, "MANIFEST.json")))
    assert man["trigger"] == "replica_death"
    assert man["reasons"] == ["r0 died"]
    assert json.load(open(os.path.join(p, "metrics.json")))[
        "some_total"] == 5
    # cooldown: same trigger within min_interval_s is suppressed
    assert rec.record("replica_death") is None
    # ...but a different trigger is not
    assert rec.record("watchdog_stall") is not None
    clk.advance(11.0)
    assert rec.record("replica_death") is not None
    # keep-K GC: only the 2 newest remain, newest first in the listing
    bundles = rec.bundles()
    assert len(bundles) == 2
    assert bundles[0]["trigger"] == "replica_death"
    assert reg.snapshot()["flight_records_total"] == 3
    assert reg.snapshot()["flight_records_suppressed_total"] == 1
    # no stray staging dirs after commits
    assert not [n for n in os.listdir(rec.directory)
                if n.startswith("tmp-")]


def test_flight_disabled_and_never_raises(tmp_path):
    rec = FlightRecorder(None)
    assert not rec.enabled
    assert rec.record("anything") is None
    assert rec.bundles() == []
    # a recorder pointed at an unwritable path swallows the failure and
    # counts it — record() must never raise into a dispatch callback
    reg = MetricsRegistry()
    bad = FlightRecorder("/proc/definitely/not/writable",
                         registry=reg, min_interval_s=0.0)
    assert bad.record("x") is None
    assert reg.snapshot()["flight_record_failures_total"] == 1


def test_flight_failed_dump_releases_the_cooldown(tmp_path):
    """A failed dump must not consume the per-trigger cooldown: the
    NEXT edge of the same trigger (e.g. the real replica death right
    after a transient ENOSPC) still records its evidence."""
    reg = MetricsRegistry()
    rec = FlightRecorder(str(tmp_path / "fl"), registry=reg,
                         min_interval_s=3600.0)  # huge window on purpose
    orig = rec._dump
    fail_next = [True]

    def flaky_dump(*a, **kw):
        if fail_next[0]:
            fail_next[0] = False
            raise OSError("disk full")
        return orig(*a, **kw)

    rec._dump = flaky_dump
    assert rec.record("replica_death") is None  # failed, counted
    assert reg.snapshot()["flight_record_failures_total"] == 1
    # within the (hour-long) cooldown window, yet NOT suppressed —
    # the failed claim was released
    assert rec.record("replica_death") is not None
    assert "flight_records_suppressed_total" not in reg.snapshot()
    # a third call IS suppressed: the successful dump owns the window
    assert rec.record("replica_death") is None
    assert reg.snapshot()["flight_records_suppressed_total"] == 1


def test_healthz_edge_dumps_exactly_one_bundle_per_episode(tmp_path):
    reg = MetricsRegistry()
    t = Tracer(enabled=True)
    rec = FlightRecorder(str(tmp_path / "fl"), tracer=t, registry=reg,
                         min_interval_s=0.0)
    healthy = [True]
    srv = TelemetryServer(registry=reg, tracer=t, port=0)
    srv.set_identity(component="unit", name="edge-test")
    srv.attach_flight(rec)
    srv.add_check("unit", lambda: None if healthy[0] else "broken: x")
    code, _ = srv.health()
    assert code == 200 and rec.bundles() == []
    healthy[0] = False
    code, body = srv.health()
    assert code == 503
    assert rec.bundles()[0]["trigger"] == "healthz_degraded"
    # still degraded: NO second bundle (edge, not level)
    srv.health()
    assert len(rec.bundles()) == 1
    # recover, degrade again: a new episode records again
    healthy[0] = True
    srv.health()
    healthy[0] = False
    srv.health()
    assert len(rec.bundles()) == 2
    hz = json.load(open(os.path.join(rec.bundles()[0]["path"],
                                     "healthz.json")))
    assert hz["status"] == "unhealthy"
    assert any("broken" in r for r in hz["reasons"])
    # /snapshot lists the bundles + the process trace identity
    snap = srv.snapshot()
    assert snap["flight"]["enabled"]
    assert len(snap["flight"]["bundles"]) == 2
    assert snap["process"]["component"] == "unit"
    assert snap["process"]["name"] == "edge-test"


def test_watchdog_stall_trigger(tmp_path):
    from dcnn_tpu.resilience.guards import StallWatchdog

    fc = FakeClock()
    reg = MetricsRegistry()
    rec = FlightRecorder(str(tmp_path / "fl"), registry=reg,
                         min_interval_s=0.0)
    wd = StallWatchdog(5.0, clock=fc, registry=reg, flight=rec)
    wd.beat()
    fc.advance(6.0)
    with pytest.warns(UserWarning):
        assert wd.check()
    bundles = rec.bundles()
    assert [b["trigger"] for b in bundles] == ["watchdog_stall"]
    # repeated checks during ONE stall: edge-triggered, no new bundle
    assert wd.check()
    assert len(rec.bundles()) == 1
    wd.beat()
    fc.advance(6.0)
    with pytest.warns(UserWarning):
        wd.check()
    assert len(rec.bundles()) == 2


def test_nonfinite_guard_trigger(tmp_path):
    from dcnn_tpu.resilience.guards import NonFiniteError, StepGuard

    reg = MetricsRegistry()
    rec = FlightRecorder(str(tmp_path / "fl"), registry=reg,
                         min_interval_s=0.0)
    g = StepGuard("skip_step", registry=reg, flight=rec)
    assert g.observe(1, bad=False) == "ok"
    assert rec.bundles() == []
    with pytest.warns(UserWarning):
        assert g.observe(2, bad=True, loss=float("nan")) == "skipped"
    assert [b["trigger"] for b in rec.bundles()] == ["nonfinite_guard"]
    # mid-streak: no new bundle (edge = streak start)
    with pytest.warns(UserWarning):
        g.observe(3, bad=True)
    assert len(rec.bundles()) == 1
    # recovery then a new streak records again
    g.observe(4, bad=False)
    with pytest.warns(UserWarning):
        g.observe(5, bad=True)
    assert len(rec.bundles()) == 2
    # policy 'raise' records before aborting
    g2 = StepGuard("raise", registry=reg, flight=rec)
    with pytest.raises(NonFiniteError):
        g2.observe(9, bad=True, loss=float("inf"))
    assert len(rec.bundles()) == 3


def test_replica_death_trigger_through_router(tmp_path):
    reg_rec = FlightRecorder(str(tmp_path / "fl"), min_interval_s=0.0)
    rep0 = LocalReplica(fake_factory, 1, name="r0", start=False)
    rep1 = LocalReplica(fake_factory, 1, name="r1", start=False)
    router = Router([rep0, rep1], flight=reg_rec, min_routable=1)
    rep1.kill()
    router.check_replicas()
    bundles = reg_rec.bundles()
    assert [b["trigger"] for b in bundles] == ["replica_death"]
    extra = json.load(open(os.path.join(bundles[0]["path"],
                                        "extra.json")))
    assert extra["replica"] == "r1"
    # metrics.json is the ROUTER's registry (death already counted)
    metrics = json.load(open(os.path.join(bundles[0]["path"],
                                          "metrics.json")))
    assert metrics["serve_router_replica_deaths_total"] == 1
    # the sweep seeing the same dead replica again is not a new edge
    router.check_replicas()
    assert len(reg_rec.bundles()) == 1
    router.shutdown()
    rep0.close()


def test_canary_rollback_trigger(tmp_path):
    from dcnn_tpu.serve.swap import ModelVersionManager

    fc = FakeClock()
    rec = FlightRecorder(str(tmp_path / "fl"), min_interval_s=0.0)
    plans = {f"r{i}": FaultPlan() for i in range(4)}

    class Factory:
        newest_version = 2

        def newest(self):
            return self.newest_version

        def __call__(self, version):
            return FakeEngine(version)

    reps = [LocalReplica(Factory(), 1, name=f"r{i}", clock=fc,
                         fault_plan=plans[f"r{i}"], start=False)
            for i in range(4)]
    router = Router(reps, clock=fc, sleep=lambda s: fc.advance(s))
    mvm = ModelVersionManager(router, Factory(), canary_fraction=0.25,
                              observe_s=10.0, min_canary_requests=5,
                              max_error_delta=0.02, clock=fc, flight=rec)
    res = mvm.poll()
    assert res["action"] == "canary"
    canary = res["canaries"][0]
    plans[canary].arm("serve.replica_infer", exc=InjectedFault)
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(32)]
    for _ in range(6):
        for r in reps:
            r.step(force=True)
    assert all(f.exception(timeout=5) is None for f in futs)
    res = mvm.poll()
    assert res["action"] == "rolled_back"
    bundles = rec.bundles()
    assert "canary_rollback" in [b["trigger"] for b in bundles]
    cb = [b for b in bundles if b["trigger"] == "canary_rollback"][0]
    cfg = json.load(open(os.path.join(cb["path"], "config.json")))
    assert cfg["version"] == 2 and canary in cfg["canaries"]
    router.shutdown()
    for r in reps:
        try:
            r.close()
        except Exception:
            pass


def test_autoscale_slo_breach_trigger(tmp_path, monkeypatch):
    from dcnn_tpu.serve.autoscale import Autoscaler, AutoscalerConfig
    from dcnn_tpu.serve.autoscale import FleetSignals

    fc = FakeClock()
    rec = FlightRecorder(str(tmp_path / "fl"), min_interval_s=0.0)
    boot = LocalReplica(fake_factory, 1, name="boot", clock=fc,
                        start=False)
    router = Router([boot], clock=fc, sleep=lambda s: fc.advance(s))
    made = [0]

    def factory(version):
        made[0] += 1
        return LocalReplica(fake_factory, version, name=f"as{made[0]}",
                            clock=fc, start=False)

    scaler = Autoscaler(router, factory,
                        config=AutoscalerConfig(breach_ticks=1,
                                                up_cooldown_s=0.0),
                        clock=fc, flight=rec)
    signals = {"p99": 1000.0}

    def fake_collect(*, _commit=False):
        return FleetSignals(routable=1, utilization=0.5,
                            p99_ms=signals["p99"], shed_fraction=0.0)

    monkeypatch.setattr(scaler, "collect", fake_collect)
    fc.advance(1.0)
    scaler.tick()  # p99 1000ms > slo default: breach edge
    assert [b["trigger"] for b in rec.bundles()] == ["autoscale_slo_breach"]
    hz = json.load(open(os.path.join(rec.bundles()[0]["path"],
                                     "extra.json")))
    assert hz["p99_ms"] == 1000.0
    fc.advance(1.0)
    scaler.tick()  # still breaching: same episode, no new bundle
    assert len(rec.bundles()) == 1
    signals["p99"] = 1.0
    fc.advance(1.0)
    scaler.tick()  # recovered
    signals["p99"] = 1000.0
    fc.advance(1.0)
    scaler.tick()  # new episode
    assert len(rec.bundles()) == 2
    router.shutdown()


# ------------------------------------------------------ elastic correlation

@pytest.mark.parametrize("victim", [2])
def test_elastic_reconfiguration_is_one_trace(tmp_path, tracer_on,
                                              victim):
    """3 peers, one killed mid-epoch: the follower's restore/rebuild
    spans join the LEADER's elastic.reconfigure trace via the RECONF
    frame's _trace carrier — a reconfiguration reads as one cross-host
    timeline. (In-process controllers share the global tracer, but the
    context still travels through real loopback sockets: without the
    carrier the follower thread has no ancestry at all.)"""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data.loader import ArrayDataLoader, one_hot
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel.elastic import ElasticController, PeerSpec
    from dcnn_tpu.resilience.faults import InjectedCrash

    rng = np.random.default_rng(0)
    x = rng.normal(size=(48, 16)).astype(np.float32)
    y = one_hot(rng.integers(0, 4, 48), 4)
    n = 3
    socks = [comm.listen(0, host="127.0.0.1") for _ in range(n)]
    peers = [PeerSpec(i, "127.0.0.1", s.getsockname()[1])
             for i, s in enumerate(socks)]
    faults = {victim: FaultPlan().arm("elastic.heartbeat", at=5,
                                      exc=InjectedCrash)}
    ckpt = str(tmp_path / "ckpt")
    results = {}

    def runner(i):
        cfg = TrainingConfig(
            epochs=2, learning_rate=0.05, seed=3, snapshot_dir=None,
            elastic=True, elastic_microbatches=6, elastic_timeout_s=15.0,
            elastic_heartbeat_s=0.0, elastic_ckpt_steps=2,
            checkpoint_dir=ckpt)
        model = (SequentialBuilder("elastic_model").input((16,))
                 .dense(32).activation("relu").dense(4).build())
        ctl = ElasticController(
            model, SGD(0.05), "softmax_crossentropy",
            ArrayDataLoader(x, y, batch_size=12, seed=7),
            config=cfg, rank=i, peers=peers, listen_sock=socks[i],
            fault_plan=faults.get(i))
        try:
            results[i] = ctl.fit(epochs=2)
        except InjectedCrash:
            results[i] = "crashed"
        except Exception as e:
            results[i] = e

    threads = [threading.Thread(target=runner, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not any(t.is_alive() for t in threads), "elastic fleet hung"
    assert results[victim] == "crashed"
    for r in (0, 1):
        assert not isinstance(results[r], (str, Exception)), results[r]

    evs = _by_name(tracer_on.events())
    # the leader (rank 0) drove a reconfiguration to a generation > 0
    recs = [e for e in evs.get("elastic.reconfigure", [])
            if e["args"].get("rank") == 0 and e["args"].get("gen", 0) > 0]
    assert recs, evs.keys()
    lead_tid = recs[-1]["args"]["trace_id"]
    # the follower's (rank 1) restore AND rebuild joined that trace
    for phase in ("elastic.restore", "elastic.rebuild"):
        joined = [e for e in evs.get(phase, [])
                  if e["args"].get("rank") == 1
                  and e["args"].get("trace_id") == lead_tid]
        assert joined, (phase, [e["args"] for e in evs.get(phase, [])])
    # generation steps carry the same trace (the per-generation timeline)
    stepped = [e for e in evs.get("elastic.step", [])
               if e["args"].get("trace_id") == lead_tid]
    assert stepped


# ------------------------------------------------------------- ACCEPTANCE

_CHILD = """\
import os, signal, sys, time
sys.path.insert(0, {repo!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from dcnn_tpu.obs import configure
from dcnn_tpu.serve.replica import LocalReplica, ReplicaServer
from dcnn_tpu.serve.soak import synthetic_engine_factory

shard, name = sys.argv[1], sys.argv[2]
tracer = configure(enabled=True)
tracer.process_name = name
rep = LocalReplica(synthetic_engine_factory, 1, name=name, start=True)
srv = ReplicaServer(rep, port=0)
print(srv.port, flush=True)

def _flush(*_a):
    try:
        tracer.export_jsonl(shard)
    finally:
        os._exit(0)

signal.signal(signal.SIGTERM, _flush)
while True:
    time.sleep(0.05)
    tracer.export_jsonl(shard)
"""


def _spawn_replica_process(tmp_path, name):
    shard = str(tmp_path / f"{name}.jsonl")
    script = str(tmp_path / f"{name}_main.py")
    with open(script, "w") as f:
        f.write(_CHILD.format(repo=REPO))
    proc = subprocess.Popen(
        [sys.executable, script, shard, name],
        stdout=subprocess.PIPE, text=True, cwd=REPO)
    port_line = proc.stdout.readline().strip()
    assert port_line, "replica child died before binding"
    return proc, int(port_line), shard


def test_acceptance_kill_a_replica_merged_trace_and_flight(tmp_path,
                                                           tracer_on):
    """ISSUE-12 ACCEPTANCE: a kill-a-replica router soak with tracing on
    across three OS processes yields ONE merged Perfetto-loadable trace
    in which the router-side request span and the replica-side
    dispatch/infer spans share a trace_id across the process boundary,
    and the injected death produces a flight bundle containing the
    correlated spans, the registry snapshot, and the 503 healthz
    reasons."""
    tracer_on.process_name = "router"
    flight_dir = str(tmp_path / "flight")
    rec = FlightRecorder(flight_dir, min_interval_s=0.0,
                         tracer=tracer_on)
    proc_a = proc_b = None
    router = None
    clients = []
    try:
        proc_a, port_a, shard_a = _spawn_replica_process(tmp_path, "repA")
        proc_b, port_b, shard_b = _spawn_replica_process(tmp_path, "repB")
        cli_a = TcpReplica("127.0.0.1", port_a, name="repA",
                           timeout_s=30.0, connect_timeout=60.0)
        cli_b = TcpReplica("127.0.0.1", port_b, name="repB",
                           timeout_s=30.0, connect_timeout=60.0)
        clients = [cli_a, cli_b]
        # min_routable=2: losing one replica degrades /healthz — the 503
        # whose reasons the flight bundle must carry
        router = Router([cli_a, cli_b], min_routable=2, flight=rec)
        srv = router.start_telemetry(port=0)

        # soak phase 1: traffic over the healthy fleet (both replicas)
        sample = np.zeros((4,), np.float32)
        futs = [router.submit(sample) for _ in range(24)]
        results = [f.result(timeout=30) for f in futs]
        assert all(np.asarray(r) is not None for r in results)
        code, _ = srv.health()
        assert code == 200

        # the injected death: SIGTERM repB (its handler exports the
        # trace shard, then exits — the kernel closing its sockets is
        # what the router's liveness layer sees)
        proc_b.send_signal(signal.SIGTERM)
        proc_b.wait(timeout=30)

        # the scrape-driven sweep detects the death, ejects, and the
        # healthz edge fires: poll the REAL health endpoint body
        deadline = time.monotonic() + 30.0
        code, body = 200, {}
        while time.monotonic() < deadline:
            code, body = srv.health()
            if code == 503:
                break
            time.sleep(0.05)
        assert code == 503, body
        assert any("routable" in r for r in body["reasons"])

        # soak phase 2: survivors absorb traffic (no silent drops)
        futs = [router.submit(sample) for _ in range(8)]
        for f in futs:
            assert f.result(timeout=30) is not None
        assert router.outstanding() == 0

        # ---- ONE merged Perfetto-loadable trace ----
        shard_r = str(tmp_path / "router.jsonl")
        tracer_on.export_jsonl(shard_r)
        merged = str(tmp_path / "merged_trace.json")
        summary = merge_shards([shard_r, shard_a, shard_b], merged)
        assert validate_chrome(merged) == []
        assert summary["events"] > 0

        # cross-process correlation: a router-side serve.request span
        # shares its trace_id with a replica-side dispatch/infer span
        _meta_r, evs_r = read_shard(shard_r)
        req_tids = {e["args"]["trace_id"] for e in evs_r
                    if e["name"] == "serve.request"}
        assert req_tids
        replica_side_tids = set()
        for shard in (shard_a, shard_b):
            _m, evs = read_shard(shard)
            for e in evs:
                if e["name"] in ("serve.dispatch", "serve.infer",
                                 "serve.queue"):
                    tid = (e.get("args") or {}).get("trace_id")
                    if tid:
                        replica_side_tids.add(tid)
        shared = req_tids & replica_side_tids
        assert shared, (sorted(req_tids)[:3],
                        sorted(replica_side_tids)[:3])
        # and the merged artifact itself carries both sides of one trace
        doc = json.load(open(merged))
        tid = next(iter(shared))
        pids = {e["pid"] for e in doc["traceEvents"]
                if e["ph"] != "M" and e["args"].get("trace_id") == tid}
        assert len(pids) >= 2  # the SAME trace spans >= 2 processes

        # ---- the flight bundle ----
        triggers = {b["trigger"]: b for b in rec.bundles()}
        assert "replica_death" in triggers
        assert "healthz_degraded" in triggers
        hb = triggers["healthz_degraded"]["path"]
        hz = json.load(open(os.path.join(hb, "healthz.json")))
        assert hz["status"] == "unhealthy"
        assert any("routable" in r for r in hz["reasons"])  # 503 reasons
        metrics = json.load(open(os.path.join(hb, "metrics.json")))
        assert metrics["serve_router_replica_deaths_total"] >= 1
        # correlated spans: the bundle's span shard holds serve.request
        # spans whose trace_id the replica-side shards also carry
        _bm, bundle_evs = read_shard(os.path.join(hb, "spans.jsonl"))
        bundle_tids = {(e.get("args") or {}).get("trace_id")
                       for e in bundle_evs
                       if e["name"] == "serve.request"}
        assert bundle_tids & replica_side_tids
    finally:
        if router is not None:
            try:
                router.shutdown(drain=False)
            except Exception:
                pass
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        for p in (proc_a, proc_b):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=10)
