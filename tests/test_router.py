"""Router-tier tests (dcnn_tpu/serve/router.py + replica.py + swap.py).

Contracts:

- **No silent drops** (acceptance): every request the router *accepts*
  (enters the ledger) resolves — with the result, or with a typed error.
  Asserted by a ledger sweep after every scenario, including the chaos
  test that kills a replica mid-soak via an armed FaultPlan.
- **Priority admission**: low-priority requests shed first under load
  (class shares over the fleet's aggregate batcher capacity), and a
  router shed is a ``QueueFullError`` — the open-loop generator and all
  existing backpressure handlers work unchanged.
- **Death / rejoin**: a dead replica (injected crash, direct kill, TCP
  connection close, last-heard timeout) is ejected; its
  accepted-but-unanswered requests are re-admitted to survivors; a
  restarted replica rejoins on the next sweep.
- **Hot-swap / canary / rollback** (acceptance): a canary rollout serves
  mixed-version traffic with zero shed increase and auto-promotes on
  clean metrics; a deliberately degraded canary (injected error rate)
  triggers instant rollback with the fleet converging back — all driven
  by fake clocks, sleep-free.

Replicas here wrap a jax-free ``FakeEngine`` (the batcher only needs
``input_shape``/``max_batch``/``pad_to_bucket``/``run_padded``), so the
whole protocol suite runs in milliseconds; bit-identity of hot-swap over
REAL engines + CheckpointManager commits lives in tests/test_swap.py.
"""

import time

import numpy as np
import pytest

from dcnn_tpu.resilience.faults import (
    FaultPlan, InjectedCrash, InjectedFault, install, clear,
)
from dcnn_tpu.serve import (
    LocalReplica, ModelVersionManager, NoReplicasError, QueueFullError,
    ReplicaDeadError, ReplicaServer, Router, RouterMetrics,
    RouterShedError, SwapError, TcpReplica, open_loop,
)
from dcnn_tpu.serve.batcher import DrainingError


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeEngine:
    """Batcher-compatible engine without jax: logits = x + version, so a
    result proves WHICH model version served it."""

    def __init__(self, version=1, name="fake"):
        self.input_shape = (4,)
        self.max_batch = 8
        self.bucket_sizes = [1, 2, 4, 8]
        self.name = name
        self.version = version
        self.batch_invariant = True

    def bucket_for(self, n):
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise ValueError(n)

    def pad_to_bucket(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        b = self.bucket_for(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n, 4), np.float32)])
        return x, n

    def run_padded(self, x):
        return np.asarray(x, np.float32) + self.version


class FakeFactory:
    """EngineFactory stand-in: ``newest()`` is a settable attribute, and
    every built engine encodes its version in its outputs."""

    def __init__(self, newest_version=1):
        self.newest_version = newest_version
        self.built = []

    def newest(self):
        return self.newest_version

    def __call__(self, version):
        self.built.append(version)
        return FakeEngine(version)


def make_fleet(n=3, *, version=1, queue_capacity=16, clock=None,
               shares=None, max_readmits=3, failure_eject_threshold=0):
    """(router, replicas, plans, clock) — start=False replicas pumped by
    hand, router backoff sleeps advance the fake clock."""
    fc = clock if clock is not None else FakeClock()
    factory = FakeFactory(newest_version=version)
    plans, reps = {}, []
    for i in range(n):
        plans[f"r{i}"] = FaultPlan()
        reps.append(LocalReplica(
            factory, version, name=f"r{i}", queue_capacity=queue_capacity,
            clock=fc, fault_plan=plans[f"r{i}"], start=False))
    router = Router(reps, clock=fc, sleep=lambda s: fc.advance(s),
                    shares=shares, max_readmits=max_readmits,
                    failure_eject_threshold=failure_eject_threshold)
    return router, reps, plans, fc


def pump(reps, rounds=4):
    """Dispatch every queued request, including re-admissions landing on
    other replicas mid-round."""
    for _ in range(rounds):
        for r in reps:
            while r.step():
                pass


# ------------------------------------------------------------- basic routing

def test_router_results_match_and_distribute():
    router, reps, _, _ = make_fleet(3)
    futs = [router.submit(np.full((4,), i, np.float32)) for i in range(12)]
    pump(reps)
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=0),
                                      np.full((4,), i + 1, np.float32))
    assert router.outstanding() == 0  # ledger swept
    stats = router.replica_stats()
    # least-loaded routing spreads 12 singles over 3 replicas
    assert all(st["completed"] >= 1 for st in stats.values())
    assert sum(st["completed"] for st in stats.values()) == 12


def test_router_batch_requests_and_single_shape():
    router, reps, _, _ = make_fleet(2)
    fb = router.submit(np.zeros((3, 4), np.float32))
    fs = router.submit(np.zeros((4,), np.float32))
    pump(reps)
    assert fb.result(0).shape == (3, 4)
    assert fs.result(0).shape == (4,)  # single in, single out


def test_router_unknown_priority_raises():
    router, _, _, _ = make_fleet(1)
    with pytest.raises(ValueError, match="unknown priority"):
        router.submit(np.zeros(4, np.float32), priority="urgent")


def test_router_submit_after_shutdown_is_typed():
    router, reps, _, _ = make_fleet(1)
    router.shutdown(drain=False)
    with pytest.raises(DrainingError):
        router.submit(np.zeros(4, np.float32))


# ------------------------------------------------------- priority admission

def test_low_priority_sheds_first_under_load():
    """ACCEPTANCE (SLO admission): with the fleet substantially
    committed, low is shed while normal and high still admit; with the
    fleet nearly full only high admits. Per-class counters record it."""
    router, reps, _, _ = make_fleet(
        2, queue_capacity=8,
        shares={"high": 1.0, "normal": 0.85, "low": 0.6})
    cap = 16

    # fill to 10/16 rows (62.5% > low's 60% share; < normal's 85%)
    held = [router.submit(np.zeros((2, 4), np.float32)) for _ in range(5)]
    assert router.outstanding() == 10
    with pytest.raises(RouterShedError):
        router.submit(np.zeros(4, np.float32), priority="low")
    ok_n = router.submit(np.zeros(4, np.float32), priority="normal")
    ok_h = router.submit(np.zeros(4, np.float32), priority="high")

    # fill to 14/16 (87.5% > normal's 85% share) — only high admits
    more = [router.submit(np.zeros(4, np.float32), priority="high")
            for _ in range(2)]
    with pytest.raises(RouterShedError):
        router.submit(np.zeros(4, np.float32), priority="normal")
    ok_h2 = router.submit(np.zeros(4, np.float32), priority="high")

    pump(reps)
    for f in held + more + [ok_n, ok_h, ok_h2]:
        assert f.exception(timeout=0) is None
    snap = router.metrics.snapshot()
    assert snap["low"]["shed"] == 1 and snap["low"]["completed"] == 0
    assert snap["normal"]["shed"] == 1
    assert snap["high"]["shed"] == 0 and snap["high"]["completed"] == 4
    assert snap["total"]["shed_fraction"] > 0
    assert router.outstanding() == 0
    assert router.metrics.capacity_rows.value == cap


def test_shed_is_queue_full_error_for_open_loop():
    """RouterShedError must subclass QueueFullError so the shared
    open-loop generator (and every existing handler) absorbs router
    backpressure identically."""
    assert issubclass(RouterShedError, QueueFullError)


def test_every_replica_full_sheds_and_unadmits():
    """Aggregate admission can pass while every individual batcher is
    full — the router must shed (typed) and UN-admit: ledger and
    outstanding return to their prior values, and the request counts
    ONLY as shed (never double-counted in offered traffic)."""
    router, reps, _, _ = make_fleet(2, queue_capacity=4)
    held = [router.submit(np.zeros((3, 4), np.float32)) for _ in range(2)]
    assert router.outstanding() == 6  # 3 rows on each replica (cap 8)
    with pytest.raises(RouterShedError):
        # admission: 6+2=8 <= 8 OK; but each replica has 3/4 used — a
        # 2-row request fits neither
        router.submit(np.zeros((2, 4), np.float32))
    assert router.outstanding() == 6
    snap = router.metrics.snapshot()["normal"]
    assert snap["requests"] == 6 and snap["shed"] == 2  # rows, not 8/2
    pump(reps)
    assert router.outstanding() == 0
    for f in held:
        assert f.exception(timeout=0) is None


def test_cancelled_then_failed_request_retires_ledger():
    """A caller-cancelled future whose replica-side request then FAILS
    must still leave the ledger (the cancel resolved it; the settle must
    not leak outstanding rows)."""
    router, reps, plans, _ = make_fleet(1)
    plans["r0"].arm("serve.replica_infer", exc=InjectedFault, times=1)
    f = router.submit(np.zeros(4, np.float32))
    assert f.cancel()  # resolved by the caller while queued
    pump(reps)
    assert router.outstanding() == 0
    f2 = router.submit(np.zeros(4, np.float32))  # capacity not poisoned
    pump(reps)
    assert f2.exception(timeout=0) is None


# ------------------------------------------------------ death + re-admission

def test_kill_reroutes_queued_requests_to_survivors():
    router, reps, _, _ = make_fleet(3)
    futs = [router.submit(np.full((4,), i, np.float32)) for i in range(9)]
    reps[0].kill()           # 3 queued requests die with the replica
    router.check_replicas()  # eject
    pump(reps)
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=0),
                                      np.full((4,), i + 1, np.float32))
    assert router.outstanding() == 0
    assert router.replica_stats()["r0"]["state"] == "dead"
    assert router.metrics.registry.snapshot()[
        "serve_router_replica_deaths_total"] == 1


def test_all_replicas_dead_fails_typed_not_silent():
    router, reps, _, _ = make_fleet(2, max_readmits=1)
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(4)]
    for r in reps:
        r.kill()
    router.check_replicas()
    for f in futs:
        with pytest.raises((ReplicaDeadError, NoReplicasError)):
            f.result(timeout=0)
    assert router.outstanding() == 0  # failed TYPED, ledger swept
    with pytest.raises(RouterShedError):
        router.submit(np.zeros(4, np.float32))  # capacity is 0 now
    assert router.health_reason() is not None  # degraded


def test_restarted_replica_rejoins_and_serves():
    router, reps, _, _ = make_fleet(2)
    reps[1].kill()
    router.check_replicas()
    assert router.replica_stats()["r1"]["state"] == "dead"
    reps[1].restart()
    report = router.check_replicas()
    assert report["r1"] == "rejoined"
    assert router.replica_stats()["r1"]["state"] == "up"
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(8)]
    pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)
    assert router.replica_stats()["r1"]["completed"] >= 1  # really serving
    assert router.metrics.registry.snapshot()[
        "serve_router_rejoins_total"] == 1


def test_chaos_faultplan_kill_mid_open_loop_soak():
    """ACCEPTANCE (chaos): open-loop load, a FaultPlan-injected replica
    crash mid-soak. Every accepted request completes or fails with a
    typed error (ledger sweep), survivors absorb the load, and the dead
    replica rejoins after restart — fully sleep-free."""
    fc = FakeClock()
    router, reps, plans, _ = make_fleet(3, queue_capacity=64, clock=fc)
    # the victim's 20th dispatch is an InjectedCrash = process death
    plans["r1"].arm("serve.replica_infer", at=19, exc=InjectedCrash)

    ticks = {"n": 0}

    def soak_sleep(dt):
        # open_loop pacing hook: advance virtual time, pump dispatch,
        # run the router's liveness sweep every ~10 ticks
        fc.advance(dt)
        pump(reps, rounds=1)
        ticks["n"] += 1
        if ticks["n"] % 10 == 0:
            router.check_replicas()

    samples = [np.full((4,), i, np.float32) for i in range(16)]
    futs = open_loop(router, samples, offered_rps=200.0, seconds=1.0,
                     clock=fc, sleep=soak_sleep)
    router.check_replicas()
    pump(reps)
    router.check_replicas()  # late crash detection
    pump(reps)

    assert len(futs) > 100          # the load was really offered
    accepted = len(futs)
    completed = failed = 0
    for i, f in futs:
        assert f.done(), "accepted request neither completed nor failed"
        if f.exception() is None:
            np.testing.assert_array_equal(
                f.result(), np.asarray(samples[i]) + 1.0)
            completed += 1
        else:
            assert isinstance(f.exception(),
                              (ReplicaDeadError, NoReplicasError))
            failed += 1
    assert router.outstanding() == 0  # accepted-ledger swept clean
    # the crash kills at most the in-flight batch; everything else is
    # re-admitted to survivors
    assert completed >= accepted - 8
    stats = router.replica_stats()
    assert stats["r1"]["state"] == "dead"
    assert stats["r0"]["completed"] + stats["r2"]["completed"] >= \
        completed - stats["r1"]["completed"]
    # restart: the replica rejoins and serves again
    reps[1].restart()
    assert router.check_replicas()["r1"] == "rejoined"
    f = router.submit(np.zeros(4, np.float32))
    pump(reps)
    assert f.exception(timeout=0) is None


def test_transient_replica_fault_is_retried_elsewhere():
    """An InjectedFault (one failing request, replica stays up) is
    re-admitted to another replica — user-invisible; the failure is
    counted against the replica for the canary judge."""
    router, reps, plans, _ = make_fleet(2)
    plans["r0"].arm("serve.replica_infer", exc=InjectedFault, times=1)
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(4)]
    pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)
    stats = router.replica_stats()
    assert stats["r0"]["failed"] >= 1 and stats["r0"]["state"] == "up"
    assert router.metrics.registry.snapshot()[
        "serve_router_readmits_total"] >= 1


def test_failure_eject_threshold():
    """A replica that answers health but fails every request is ejected
    once its consecutive-failure run crosses the threshold — and the
    liveness sweep must NOT flap it back in (its health probe was lying);
    only an explicit rejoin() re-admits it."""
    router, reps, plans, _ = make_fleet(2, failure_eject_threshold=3)
    plans["r0"].arm("serve.replica_infer", exc=InjectedFault)  # always
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(12)]
    pump(reps, rounds=6)
    assert all(f.exception(timeout=0) is None for f in futs)
    assert router.replica_stats()["r0"]["state"] == "dead"
    report = router.check_replicas()  # health passes, but no auto-rejoin
    assert "explicit rejoin" in report["r0"]
    assert router.replica_stats()["r0"]["state"] == "dead"
    plans["r0"].disarm("serve.replica_infer")
    router.rejoin("r0")
    assert router.replica_stats()["r0"]["state"] == "up"
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(6)]
    pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)
    assert router.replica_stats()["r0"]["completed"] >= 1


def test_malformed_request_unadmits_no_ledger_leak():
    """A request the replica's own validation rejects (e.g. oversized
    batch) propagates to the CALLER — and is un-admitted: the ledger and
    outstanding count are restored, so bad requests can't poison
    admission capacity or hang drain()."""
    router, reps, _, _ = make_fleet(2, queue_capacity=64)
    with pytest.raises(ValueError, match="outside"):
        router.submit(np.zeros((9, 4), np.float32))  # > max_batch 8
    assert router.outstanding() == 0
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(4)]
    pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)
    assert router.outstanding() == 0


def test_serve_route_fault_point():
    plan = FaultPlan().arm("serve.route", at=1, times=1)
    router, reps, _, _ = make_fleet(1)
    install(plan)
    try:
        router.submit(np.zeros(4, np.float32))       # invocation 0: clean
        with pytest.raises(InjectedFault):
            router.submit(np.zeros(4, np.float32))   # invocation 1: boom
    finally:
        clear()
    pump(reps)
    assert router.outstanding() == 0


# ------------------------------------------------------------------ hot-swap

def test_swap_replica_drain_load_rejoin():
    router, reps, _, _ = make_fleet(2, version=1)
    router.swap_replica("r0", 2)
    stats = router.replica_stats()
    assert stats["r0"]["version"] == 2 and stats["r0"]["state"] == "up"
    assert stats["r1"]["version"] == 1
    # mixed-version fleet serves; results prove which version answered
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(8)]
    pump(reps)
    served = {float(f.result(timeout=0)[0]) for f in futs}
    assert served <= {1.0, 2.0} and len(served) == 2
    assert router.metrics.registry.snapshot()[
        "serve_router_swaps_total"] == 1


def test_swap_failure_rejoins_old_version():
    router, reps, plans, _ = make_fleet(1, version=1)
    plans["r0"].arm("serve.swap", exc=InjectedFault, times=1)
    with pytest.raises(SwapError):
        router.swap_replica("r0", 2)
    stats = router.replica_stats()
    assert stats["r0"]["version"] == 1 and stats["r0"]["state"] == "up"
    f = router.submit(np.zeros(4, np.float32))
    pump(reps)
    assert float(f.result(timeout=0)[0]) == 1.0  # old version serving
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_swap_failures_total"] == 1
    assert snap["serve_router_swaps_total"] == 0


def test_mvm_canary_then_promote_zero_shed():
    """ACCEPTANCE (hot-swap, clean path): canary rollout serves
    mixed-version traffic with zero shed, then auto-promotes on a clean
    observation window — fake clock, sleep-free."""
    fc = FakeClock()
    router, reps, _, _ = make_fleet(4, version=1, clock=fc)
    factory = FakeFactory(newest_version=1)
    mvm = ModelVersionManager(router, factory, canary_fraction=0.25,
                              observe_s=10.0, min_canary_requests=5,
                              clock=fc)
    assert mvm.poll()["action"] == "none"

    factory.newest_version = 2
    res = mvm.poll()
    assert res["action"] == "canary" and len(res["canaries"]) == 1
    canary = res["canaries"][0]
    assert router.replica_stats()[canary]["version"] == 2
    assert router.metrics.canary_replicas.value == 1

    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(24)]
    pump(reps)
    served = {float(f.result(timeout=0)[0]) for f in futs}
    assert served == {1.0, 2.0}  # mixed-version traffic really happened
    assert router.metrics.snapshot()["total"]["shed"] == 0  # zero shed

    assert mvm.poll()["action"] == "canary_wait"  # window not elapsed
    fc.advance(11.0)
    res = mvm.poll()
    assert res["action"] == "promoted"
    assert all(st["version"] == 2
               for st in router.replica_stats().values())
    assert mvm.current_version == 2 and mvm.state == "idle"
    assert router.metrics.canary_replicas.value == 0
    assert router.metrics.registry.snapshot()[
        "serve_router_promotions_total"] == 1


def test_mvm_degraded_canary_instant_rollback():
    """ACCEPTANCE (hot-swap, regression path): a deliberately degraded
    canary (injected error rate) triggers instant rollback; the fleet
    converges back to the old version; the bad version is quarantined
    and never auto-retried; end users see zero failures."""
    fc = FakeClock()
    router, reps, plans, _ = make_fleet(4, version=1, clock=fc)
    factory = FakeFactory(newest_version=2)
    mvm = ModelVersionManager(router, factory, canary_fraction=0.25,
                              observe_s=10.0, min_canary_requests=5,
                              max_error_delta=0.02, clock=fc)
    res = mvm.poll()
    assert res["action"] == "canary"
    canary = res["canaries"][0]
    plans[canary].arm("serve.replica_infer", exc=InjectedFault)  # degrade

    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(32)]
    pump(reps, rounds=6)
    assert all(f.exception(timeout=0) is None for f in futs)  # users fine

    res = mvm.poll()
    assert res["action"] == "rolled_back"
    assert "error ratio" in res["reason"]
    plans[canary].disarm("serve.replica_infer")
    assert all(st["version"] == 1
               for st in router.replica_stats().values())  # converged back
    assert mvm.current_version == 1 and mvm.quarantined == {2}
    assert router.metrics.registry.snapshot()[
        "serve_router_rollbacks_total"] == 1
    assert mvm.poll()["action"] == "none"  # quarantined: no re-canary
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(8)]
    pump(reps)
    assert {float(f.result(timeout=0)[0]) for f in futs} == {1.0}


def test_mvm_single_transient_canary_failure_no_rollback():
    """One transient failure on a canary's first request must NOT
    quarantine the version (min_error_samples floor): the canary stays,
    and with clean traffic afterwards the version still promotes."""
    fc = FakeClock()
    router, reps, plans, _ = make_fleet(4, version=1, clock=fc)
    factory = FakeFactory(newest_version=2)
    mvm = ModelVersionManager(router, factory, canary_fraction=0.25,
                              observe_s=10.0, min_canary_requests=5,
                              min_error_samples=5, clock=fc)
    res = mvm.poll()
    canary = res["canaries"][0]
    plans[canary].arm("serve.replica_infer", exc=InjectedFault, times=1)
    f = router.submit(np.zeros(4, np.float32))
    pump(reps)
    assert f.exception(timeout=0) is None  # re-admitted elsewhere
    assert mvm.poll()["action"] == "canary_wait"  # 1 failure < floor
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(24)]
    pump(reps)
    assert all(fu.exception(timeout=0) is None for fu in futs)
    fc.advance(11.0)
    assert mvm.poll()["action"] == "promoted"
    assert mvm.quarantined == set()


def test_mvm_reconciles_replica_that_missed_promote():
    """A replica dead through a promote rejoins serving the pre-promote
    version; the idle watch heals it to current instead of leaving the
    fleet mixed-version forever."""
    fc = FakeClock()
    router, reps, _, _ = make_fleet(4, version=1, clock=fc)
    factory = FakeFactory(newest_version=2)
    mvm = ModelVersionManager(router, factory, canary_fraction=0.25,
                              observe_s=1.0, min_canary_requests=2,
                              clock=fc)
    res = mvm.poll()
    assert res["action"] == "canary"
    reps[3].kill()              # misses the whole rollout
    router.check_replicas()
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(8)]
    pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)
    fc.advance(2.0)
    assert mvm.poll()["action"] == "promoted"
    reps[3].restart()
    assert router.check_replicas()["r3"] == "rejoined"
    assert router.replica_stats()["r3"]["version"] == 1  # stale!
    res = mvm.poll()
    assert res["action"] == "reconciled" and res["reconciled"] == ["r3"]
    assert all(st["version"] == 2
               for st in router.replica_stats().values())
    assert mvm.poll()["action"] == "none"  # converged: nothing to heal


def test_mvm_unloadable_version_quarantined():
    """A version whose engine cannot even load (serve.swap fault) is
    quarantined at canary time; the fleet stays on the old version."""
    fc = FakeClock()
    router, reps, plans, _ = make_fleet(2, version=1, clock=fc)
    factory = FakeFactory(newest_version=2)
    for p in plans.values():
        p.arm("serve.swap", exc=InjectedFault, times=1)
    mvm = ModelVersionManager(router, factory, clock=fc)
    res = mvm.poll()
    assert res["action"] == "swap_failed"
    assert mvm.quarantined == {2} and mvm.state == "idle"
    assert all(st["version"] == 1
               for st in router.replica_stats().values())
    assert mvm.poll()["action"] == "none"


# ----------------------------------------------------------------- TCP tier

@pytest.fixture()
def tcp_pair():
    backend = LocalReplica(FakeEngine(version=7), name="backend",
                           queue_capacity=32, max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    client = TcpReplica("127.0.0.1", server.port, name="remote")
    yield backend, server, client
    client.close()
    server.close()
    backend.close()


def test_tcp_replica_end_to_end(tcp_pair):
    backend, server, client = tcp_pair
    futs = [client.submit(np.full((4,), i, np.float32)) for i in range(6)]
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(f.result(timeout=30),
                                      np.full((4,), i + 7, np.float32))
    # pong metadata populated the remote identity
    client.ping()
    deadline = time.monotonic() + 10
    while client.version is None and time.monotonic() < deadline:
        time.sleep(0.005)
    assert client.version == 7
    assert client.input_shape == (4,)
    assert client.health() is None and not client.is_dead()
    st = client.stats()
    assert st["version"] == 7 and st["state"] == "up"


def test_tcp_replica_connection_close_fails_pending(tcp_pair):
    """Replica-process death = connection close: pending request futures
    fail with ReplicaDeadError (typed, re-admittable) and the client
    reports dead — immediately, not by timeout."""
    backend, server, client = tcp_pair
    backend.kill()  # server-side batcher gone: queued work errors back
    server.close()  # and the host closes its sockets
    deadline = time.monotonic() + 10
    while not client.is_dead() and time.monotonic() < deadline:
        time.sleep(0.005)
    assert client.is_dead()
    with pytest.raises(ReplicaDeadError):
        client.submit(np.zeros(4, np.float32))


def test_tcp_replica_last_heard_timeout():
    """The partitioned-but-open case: silence past the window makes
    health() PROBE first (never convicting an idle-but-healthy replica),
    then escalate to dead once the probe itself goes unanswered for the
    window — sleep-free via a fake clock, with the 'network' black-holed
    by dropping sends."""
    backend = LocalReplica(FakeEngine(), name="backend", queue_capacity=8,
                           max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    fc = FakeClock(100.0)
    client = TcpReplica("127.0.0.1", server.port, name="remote",
                        timeout_s=5.0, clock=fc)
    try:
        deadline = time.monotonic() + 10
        while client.version is None and time.monotonic() < deadline:
            time.sleep(0.005)  # initial ping answered: last_heard fresh
        assert client.health() is None
        # black-hole the link: frames leave but never arrive anywhere
        client._chan.send = lambda *a, **k: None
        fc.advance(6.0)          # idle past the window
        assert client.health() is None   # asks (ping), does NOT convict
        assert not client.is_dead()
        fc.advance(6.0)          # the probe itself went unanswered
        reason = client.health()
        assert reason is not None and "unresponsive" in reason
        assert client.is_dead()
    finally:
        client.close()
        server.close()
        backend.close()


def test_router_sweep_convicts_partitioned_tcp_replica():
    """Through the ROUTER's own sweep (ping-then-health every pass): a
    partitioned-but-open TCP replica is convicted on the second sweep —
    the sweep's fresh ping must not rewind the probe clock (the first
    probe since the last frame is the one that counts)."""
    backend = LocalReplica(FakeEngine(), name="backend", queue_capacity=8,
                           max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    fc = FakeClock(100.0)
    client = TcpReplica("127.0.0.1", server.port, name="tcp0",
                        timeout_s=5.0, clock=fc)
    local = LocalReplica(FakeEngine(), name="local0", queue_capacity=8,
                         max_wait_ms=0.0)
    router = Router([client, local])
    try:
        deadline = time.monotonic() + 10
        while client.version is None and time.monotonic() < deadline:
            time.sleep(0.005)
        assert router.check_replicas()["tcp0"] == "up"
        client._chan.send = lambda *a, **k: None  # black-hole the link
        fc.advance(6.0)
        router.check_replicas()     # probes (clock not rewound)
        assert not client.is_dead()
        fc.advance(6.0)
        report = router.check_replicas()  # probe unanswered: convict
        assert client.is_dead()
        assert "ejected" in report["tcp0"]
        assert router.replica_stats()["tcp0"]["state"] == "dead"
    finally:
        router.shutdown(drain=False)
        client.close()
        server.close()
        backend.close()
        local.close()


def test_tcp_replica_slow_sweep_does_not_false_eject():
    """A sweep cadence slower than timeout_s must NOT kill a healthy
    idle replica: the probe the first health() sends is answered, so the
    next look sees a fresh frame."""
    backend = LocalReplica(FakeEngine(), name="backend", queue_capacity=8,
                           max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    fc = FakeClock(100.0)
    client = TcpReplica("127.0.0.1", server.port, name="remote",
                        timeout_s=2.0, clock=fc)
    try:
        deadline = time.monotonic() + 10
        while client.version is None and time.monotonic() < deadline:
            time.sleep(0.005)
        for _ in range(3):       # sweeps spaced 3x the timeout window
            fc.advance(6.0)
            client.health()      # probes; the live server answers
            deadline = time.monotonic() + 10
            # wait for the pong to land so last_heard refreshes
            while time.monotonic() < deadline:
                with client._lock:
                    if client._last_heard >= fc() - 0.1:
                        break
                time.sleep(0.005)
            assert client.health() is None
            assert not client.is_dead()
    finally:
        client.close()
        server.close()
        backend.close()


def test_router_over_tcp_replicas_kill_and_failover():
    """Router fronting one TCP + one local replica: killing the TCP
    host mid-queue reroutes accepted work to the survivor."""
    backend = LocalReplica(FakeEngine(version=1), name="backend",
                           queue_capacity=32, max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    client = TcpReplica("127.0.0.1", server.port, name="tcp0")
    local = LocalReplica(FakeEngine(version=1), name="local0",
                         queue_capacity=32, max_wait_ms=0.0)
    router = Router([client, local])
    try:
        futs = [router.submit(np.full((4,), i, np.float32))
                for i in range(12)]
        server.close()  # the TCP host dies mid-traffic
        backend.kill()
        router.check_replicas()
        for i, f in enumerate(futs):
            exc = None
            try:
                y = f.result(timeout=30)
                np.testing.assert_array_equal(
                    y, np.full((4,), i + 1, np.float32))
            except (ReplicaDeadError, NoReplicasError) as e:
                exc = e  # typed — acceptable for in-flight rows
            assert f.done() and (exc is None or f.exception() is exc)
        # death lands in the router only at a sweep: the reader thread
        # marks the replica dead on ChannelClosed, and on a loaded host
        # a single sweep can race it — keep sweeping within the deadline
        deadline = time.monotonic() + 10
        while ((router.outstanding()
                or router.replica_stats()["tcp0"]["state"] != "dead")
               and time.monotonic() < deadline):
            router.check_replicas()
            time.sleep(0.005)
        assert router.outstanding() == 0
        assert router.replica_stats()["tcp0"]["state"] == "dead"
    finally:
        router.shutdown(drain=False)
        client.close()
        local.close()


def test_tcp_remote_swap(tcp_pair):
    """The swap command crosses the wire: a remote replica built on a
    factory hot-swaps and serves the new version."""
    backend = LocalReplica(FakeFactory(), 1, name="versioned",
                           queue_capacity=8, max_wait_ms=0.0)
    server = ReplicaServer(backend, port=0)
    client = TcpReplica("127.0.0.1", server.port, name="remote2")
    try:
        f = client.submit(np.zeros(4, np.float32))
        np.testing.assert_array_equal(f.result(timeout=30),
                                      np.ones(4, np.float32))
        client.swap(5, timeout=30)
        f = client.submit(np.zeros(4, np.float32))
        np.testing.assert_array_equal(f.result(timeout=30),
                                      np.full((4,), 5.0, np.float32))
    finally:
        client.close()
        server.close()
        backend.close()


# ----------------------------------------------------- telemetry + metrics

def test_router_healthz_degrades_and_recovers():
    import json
    from urllib.request import urlopen
    from urllib.error import HTTPError

    router, reps, _, _ = make_fleet(2, queue_capacity=8)
    srv = router.start_telemetry(port=0)
    try:
        with urlopen(f"{srv.url}/healthz", timeout=10) as resp:
            body = json.loads(resp.read())
        assert resp.status == 200 and body["status"] == "ok"
        assert body["flags"]["serve_router_replicas"] == 2

        for r in reps:
            r.kill()
        # /healthz runs a live sweep: the scrape itself sees the deaths
        with pytest.raises(HTTPError) as ei:
            urlopen(f"{srv.url}/healthz", timeout=10)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        assert any("routable" in r for r in body["reasons"])
        assert body["flags"]["serve_router_replicas_routable"] == 0

        with urlopen(f"{srv.url}/metrics", timeout=10) as resp:
            text = resp.read().decode()
        assert "serve_router_replica_deaths_total 2" in text

        for r in reps:
            r.restart()
        with urlopen(f"{srv.url}/healthz", timeout=10) as resp:
            body = json.loads(resp.read())
        assert body["status"] == "ok"
    finally:
        router.shutdown(drain=False)  # stops the telemetry server


def test_router_metrics_prometheus_conformance():
    """Satellite: the new serve_router_* series render through the shared
    exposition module — counters end _total with HELP/TYPE headers,
    per-priority families all present, histograms carry cumulative
    buckets ending +Inf."""
    fc = FakeClock()
    m = RouterMetrics(clock=fc)
    m.record_submit("high", 2)
    m.record_shed("low", 1)
    m.record_done("high", 0.01, 2)
    m.record_failed("normal", 1)
    m.record_replica_death()
    m.record_rollback()
    text = m.prometheus()
    lines = text.splitlines()
    for p in ("high", "normal", "low"):
        for family in (f"serve_router_requests_{p}_total",
                       f"serve_router_shed_{p}_total",
                       f"serve_router_completed_{p}_total",
                       f"serve_router_failed_{p}_total",
                       f"serve_router_latency_seconds_{p}"):
            assert f"# TYPE {family}" in text, family
    assert "serve_router_requests_high_total 2" in lines
    assert "serve_router_shed_low_total 1" in lines
    assert "serve_router_replica_deaths_total 1" in lines
    assert "serve_router_rollbacks_total 1" in lines
    # histogram family: cumulative buckets ending +Inf, _sum/_count pair
    assert 'serve_router_latency_seconds_high_bucket{le="+Inf"} 1' in lines
    assert "serve_router_latency_seconds_high_count 1" in lines
    # derived windowed percentile gauges appear once data exists
    assert "serve_router_latency_window_p99_ms_high 10.0" in lines
    # counters never render without the _total suffix
    for ln in lines:
        if ln.startswith("# TYPE") and ln.endswith(" counter"):
            assert ln.split()[2].endswith("_total"), ln


def test_router_metrics_snapshot_totals():
    fc = FakeClock()
    m = RouterMetrics(clock=fc)
    m.record_submit("normal", 3)
    m.record_done("normal", 0.002, 3)
    m.record_shed("low", 2)
    fc.advance(1.0)
    s = m.snapshot()
    assert s["normal"]["completed"] == 3
    assert s["normal"]["p50_ms"] == pytest.approx(2.0)
    assert s["low"]["shed"] == 2 and s["low"]["p50_ms"] is None
    assert s["total"]["shed_fraction"] == pytest.approx(2 / 5)
    assert s["total"]["throughput_rps"] == pytest.approx(3.0)


def test_bench_router_section_structure():
    """bench.py's router block over injected jax-free engines: the
    BENCH_SERVE=1 acceptance shape — capacity probe (1 vs N + scaling),
    >= 3-point latency-vs-load curve, and the kill-a-replica sub-soak
    with availability + silent-drop accounting. Sub-second windows."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if repo not in sys.path:
        sys.path.insert(0, repo)
    import bench

    class SlowFakeEngine(FakeEngine):
        # a ~0.3ms dispatch bounds the fake's capacity so the open-loop
        # phases offer a sane rate (a zero-cost engine would make the
        # load loop iterate offered_rps x seconds ~ millions of times)
        def run_padded(self, x):
            time.sleep(3e-4)
            return super().run_padded(x)

    engines = [SlowFakeEngine(version=1, name=f"e{i}") for i in range(2)]
    doc = bench.router_section(None, engines=engines, seconds=0.2)
    assert doc["replicas"] == 2
    assert doc["capacity_1_img_per_sec"] > 0
    assert doc["capacity_img_per_sec"] > 0
    assert doc["capacity_scaling_x"] is not None
    assert len(doc["loads"]) >= 3
    for pt in doc["loads"]:
        assert set(pt) >= {"offered_img_per_sec", "achieved_rps",
                           "p50_ms", "p99_ms", "shed_fraction"}
    ks = doc["kill_soak"]
    assert ks["accepted"] == ks["completed"] + ks["typed_failures"]
    assert ks["silently_dropped"] == 0
    assert ks["replica_deaths"] == 1
    assert ks["rejoined_after_restart"] is True
    assert ks["availability"] is not None and ks["availability"] > 0.9


def test_serve_router_example_imports():
    """Import smoke for examples/serve_router.py (no main() execution),
    with the examples dir resolving its `common` module."""
    import importlib
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ex_dir = os.path.join(repo, "examples")
    saved_common = sys.modules.pop("common", None)
    sys.path.insert(0, ex_dir)
    try:
        mod = importlib.import_module("serve_router")
        assert callable(mod.main)
        assert callable(mod.build_versions)
    finally:
        sys.path.remove(ex_dir)
        sys.modules.pop("serve_router", None)
        sys.modules.pop("common", None)
        if saved_common is not None:
            sys.modules["common"] = saved_common


def test_router_drain_completes_ledger():
    fc = FakeClock()
    router, reps, _, _ = make_fleet(2, clock=fc)
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(6)]

    # drain's wait loop runs on the injected sleep: pump the replicas
    # from inside it so the ledger empties (sleep-free)
    def pump_sleep(dt):
        fc.advance(dt)
        pump(reps, rounds=1)

    router._sleep = pump_sleep
    router.drain(timeout=5.0)
    assert all(f.exception(timeout=0) is None for f in futs)
    with pytest.raises(DrainingError):
        router.submit(np.zeros(4, np.float32))
