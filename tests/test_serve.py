"""Serving subsystem tests (dcnn_tpu/serve/).

Contracts:

- engine: one pre-compiled warm session per bucket, pad-to-bucket exactness
  within a session, cross-bucket BIT-IDENTITY for int8 engines (integer
  accumulation is reduction-order-free), checkpoint/artifact constructors
  agree with the live model;
- batcher: output bit-identical to running each request alone through the
  engine (acceptance criterion — asserted on the int8 serving graph, where
  it holds across buckets by construction); backpressure sheds beyond
  queue capacity while accepted requests complete through drain();
- metrics: exact, sleep-free accounting under an injected fake clock.

Everything tier-1 here is sleep-free: deadline/latency logic is driven by
the fake clock through the synchronous ``step(force=False)`` path (the same
``_pop_due`` core the dispatcher thread runs), and threaded tests use
``max_wait_ms=0`` so dispatch is purely event-driven. The real-time
open-loop soak is marked ``slow``.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.nn import SequentialBuilder, export_inference
from dcnn_tpu.serve import (
    DynamicBatcher, InferenceEngine, QueueFullError, ServeMetrics,
    serve_buckets,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    """Injectable monotonic clock: tests advance it by hand, so latency
    and deadline assertions are exact equalities and nothing sleeps."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _tiny_model():
    return (SequentialBuilder(name="srv", data_format="NHWC")
            .input((8, 8, 3))
            .conv2d(4, 3, padding=1).batchnorm().activation("relu")
            .maxpool2d(2).flatten().dense(5)
            .build())


@pytest.fixture(scope="module")
def tiny():
    model = _tiny_model()
    params, state = model.init(jax.random.PRNGKey(0), model.input_shape)
    rng = np.random.default_rng(0)
    calib = jnp.asarray(rng.normal(size=(16, 8, 8, 3)).astype(np.float32))
    pool = rng.normal(size=(16, 8, 8, 3)).astype(np.float32)
    return model, params, state, calib, pool


@pytest.fixture(scope="module")
def int8_engine(tiny):
    model, params, state, calib, _ = tiny
    return InferenceEngine.from_model(model, params, state,
                                      int8_calib=calib, max_batch=8)


@pytest.fixture(scope="module")
def float_engine(tiny):
    model, params, state, _, _ = tiny
    return InferenceEngine.from_model(model, params, state, max_batch=8)


# ---------------------------------------------------------------- buckets

def test_serve_buckets():
    assert serve_buckets(1) == [1]
    assert serve_buckets(8) == [1, 2, 4, 8]
    assert serve_buckets(32) == [1, 2, 4, 8, 16, 32]
    # non-power-of-two cap becomes its own last bucket, not an over-pad
    assert serve_buckets(6) == [1, 2, 4, 6]
    with pytest.raises(ValueError):
        serve_buckets(0)


# ----------------------------------------------------------------- engine

def test_engine_precompiles_warm_sessions(float_engine):
    assert float_engine.bucket_sizes == [1, 2, 4, 8]
    assert sorted(float_engine.compile_stats) == [1, 2, 4, 8]
    for st in float_engine.compile_stats.values():
        assert st["compile_s"] >= 0 and st["warmup_s"] >= 0
    # run_padded accepts exactly the bucket shapes
    y = float_engine.run_padded(jnp.zeros((4, 8, 8, 3), jnp.float32))
    assert y.shape == (4, 5)
    with pytest.raises(ValueError, match="no session"):
        float_engine.run_padded(jnp.zeros((3, 8, 8, 3), jnp.float32))


def test_engine_bucket_math(float_engine):
    assert [float_engine.bucket_for(n) for n in (1, 2, 3, 5, 8)] == \
        [1, 2, 4, 8, 8]
    with pytest.raises(ValueError):
        float_engine.bucket_for(0)
    with pytest.raises(ValueError):
        float_engine.bucket_for(9)


def test_engine_infer_shapes_and_chunking(float_engine, tiny):
    *_, pool = tiny
    assert float_engine.infer(pool[0]).shape == (5,)       # single sample
    assert float_engine.infer(pool[:3]).shape == (3, 5)    # padded batch
    # beyond max_batch: chunked through the biggest bucket, rows preserved
    y = float_engine.infer(pool)  # 16 rows > max_batch 8
    assert y.shape == (16, 5)
    np.testing.assert_array_equal(np.asarray(y[:8]),
                                  np.asarray(float_engine.infer(pool[:8])))
    with pytest.raises(ValueError, match="trailing dims"):
        float_engine.infer(np.zeros((2, 4, 4, 3), np.float32))


def test_engine_padding_is_row_exact_within_bucket(float_engine, tiny):
    """Zero-pad rows ride along and are sliced off; the real rows are
    bit-identical to the same content unpadded at the same bucket."""
    *_, pool = tiny
    x5 = pool[:5]
    padded, n = float_engine.pad_to_bucket(x5)
    assert padded.shape == (8, 8, 8, 3) and n == 5
    full = np.zeros((8, 8, 8, 3), np.float32)
    full[:5] = x5
    np.testing.assert_array_equal(
        np.asarray(float_engine.run_padded(padded))[:5],
        np.asarray(float_engine.run_padded(jnp.asarray(full)))[:5])


def test_engine_int8_is_batch_invariant(int8_engine, tiny):
    """The int8 graph's cross-row-shape reductions are exact integer
    accumulations: a request's logits are bit-identical no matter which
    bucket served it. This is the property the batcher's bit-identity
    guarantee rests on."""
    *_, pool = tiny
    assert int8_engine.batch_invariant
    ref = np.asarray(int8_engine.infer(pool[:8]))
    for i in range(8):
        np.testing.assert_array_equal(
            np.asarray(int8_engine.infer(pool[i])), ref[i])


def test_engine_float_is_allclose_across_buckets(float_engine, tiny):
    """Float graphs are NOT promised bit-identity across buckets (XLA
    retiles fp32 reductions per shape) — only tight allclose. Documented
    here so the int8 guarantee above reads as the deliberate contrast."""
    *_, pool = tiny
    assert not float_engine.batch_invariant
    ref = np.asarray(float_engine.infer(pool[:8]))
    for i in range(8):
        np.testing.assert_allclose(np.asarray(float_engine.infer(pool[i])),
                                   ref[i], rtol=1e-5, atol=1e-5)


def test_engine_from_checkpoint(tiny, tmp_path):
    from dcnn_tpu.train.checkpoint import save_checkpoint

    model, params, state, _, pool = tiny
    save_checkpoint(str(tmp_path / "ck"), model, params, state)
    eng = InferenceEngine.from_checkpoint(str(tmp_path / "ck"), max_batch=4)
    ref = InferenceEngine.from_model(model, params, state, max_batch=4)
    np.testing.assert_array_equal(np.asarray(eng.infer(pool[:4])),
                                  np.asarray(ref.infer(pool[:4])))


def test_engine_from_artifact(tiny, float_engine):
    from dcnn_tpu.nn import fold_batchnorm

    model, params, state, _, pool = tiny
    fm, fp, fs = fold_batchnorm(model, params, state)
    blob = export_inference(fm, fp, fs)
    eng = InferenceEngine.from_artifact(blob, max_batch=8)
    assert eng.input_shape == (8, 8, 3)
    # same program, same backend, same bucket -> bit-identical to the
    # checkpoint-built engine
    np.testing.assert_array_equal(np.asarray(eng.infer(pool[:4])),
                                  np.asarray(float_engine.infer(pool[:4])))
    # pinned-batch artifacts can't serve buckets: explicit error
    pinned = export_inference(fm, fp, fs, batch_size=4)
    with pytest.raises(ValueError, match="batch-polymorphic"):
        InferenceEngine.from_artifact(pinned)


# ---------------------------------------------------------------- batcher

def test_batcher_bit_identical_to_engine_alone(int8_engine, tiny):
    """ACCEPTANCE: DynamicBatcher output is bit-identical to running each
    request alone through the engine. Asserted on the int8 engine — the
    serving graph of record — where batch-invariance makes it hold
    regardless of how requests were grouped into buckets."""
    *_, pool = tiny
    b = DynamicBatcher(int8_engine, max_batch=4, queue_capacity=64,
                       start=False)
    futs = [b.submit(pool[i]) for i in range(7)]  # batches of 4 + 3
    b.drain()
    for i, f in enumerate(futs):
        np.testing.assert_array_equal(
            np.asarray(f.result(timeout=1)),
            np.asarray(int8_engine.infer(pool[i])))


def test_batcher_mixed_size_requests(int8_engine, tiny):
    *_, pool = tiny
    b = DynamicBatcher(int8_engine, max_batch=8, queue_capacity=64,
                       start=False)
    f2 = b.submit(pool[:2])
    f3 = b.submit(pool[2:5])
    f1 = b.submit(pool[5])
    b.drain()
    np.testing.assert_array_equal(np.asarray(f2.result(1)),
                                  np.asarray(int8_engine.infer(pool[:2])))
    np.testing.assert_array_equal(np.asarray(f3.result(1)),
                                  np.asarray(int8_engine.infer(pool[2:5])))
    np.testing.assert_array_equal(np.asarray(f1.result(1)),
                                  np.asarray(int8_engine.infer(pool[5])))
    assert f1.result(1).shape == (5,)  # single in, single out


def test_batcher_float_same_bucket_exact(float_engine, tiny):
    """A full batch through the batcher runs the same session as the same
    rows through engine.infer: bit-identical even for float. Singles run
    at bucket 1 instead, so only allclose is promised there."""
    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=4, queue_capacity=64,
                       start=False)
    futs = [b.submit(pool[i]) for i in range(4)]
    assert b.step() == 4  # one batch of 4 -> bucket 4
    got = np.stack([np.asarray(f.result(1)) for f in futs])
    np.testing.assert_array_equal(got,
                                  np.asarray(float_engine.infer(pool[:4])))
    for i in range(4):
        np.testing.assert_allclose(np.asarray(float_engine.infer(pool[i])),
                                   got[i], rtol=1e-5, atol=1e-5)


def test_batcher_backpressure_sheds_and_drain_completes(int8_engine, tiny):
    """ACCEPTANCE: requests beyond queue capacity are rejected
    (QueueFullError, counted as shed) while everything accepted completes
    through drain()."""
    *_, pool = tiny
    mets = ServeMetrics()
    b = DynamicBatcher(int8_engine, max_batch=4, queue_capacity=6,
                       metrics=mets, start=False)
    accepted = [b.submit(pool[i]) for i in range(6)]
    with pytest.raises(QueueFullError):
        b.submit(pool[6])
    with pytest.raises(QueueFullError):
        b.submit(pool[:2])  # batch requests shed identically
    assert b.queue_depth == 6
    b.drain()
    for i, f in enumerate(accepted):
        np.testing.assert_array_equal(
            np.asarray(f.result(timeout=1)),
            np.asarray(int8_engine.infer(pool[i])))
    snap = mets.snapshot()
    assert snap["requests_completed"] == 6
    assert snap["requests_shed"] == 3  # 1 single + 1 two-sample request
    assert snap["shed_fraction"] == pytest.approx(3 / 9)
    assert snap["queue_depth"] == 0
    # drained batcher refuses new work
    with pytest.raises(RuntimeError, match="draining or shut down"):
        b.submit(pool[0])


def test_batcher_deadline_batching_fake_clock(int8_engine, tiny):
    """The batching window, sleep-free: nothing dispatches before the
    oldest request's deadline or a full batch; latencies recorded from the
    injected clock are exact."""
    *_, pool = tiny
    fc = FakeClock()
    mets = ServeMetrics(clock=fc)
    b = DynamicBatcher(int8_engine, max_batch=4, max_wait_ms=10.0,
                       queue_capacity=64, metrics=mets, clock=fc,
                       start=False)
    f0 = b.submit(pool[0])              # t = 0, deadline t = 0.010
    assert b.step(force=False) == 0     # not due: not full, not expired
    fc.advance(0.004)
    f1 = b.submit(pool[1])              # t = 0.004
    assert b.step(force=False) == 0
    fc.advance(0.007)                   # t = 0.011 > deadline
    assert b.step(force=False) == 2     # one batch of 2 (bucket 2)
    assert f0.done() and f1.done()
    snap = mets.snapshot()
    # exact latencies through the fake clock: 11 ms and 7 ms
    assert snap["p99_ms"] == pytest.approx(11.0)
    assert snap["p50_ms"] == pytest.approx(11.0)  # nearest-rank of [7, 11]
    assert snap["mean_ms"] == pytest.approx(9.0)
    assert snap["batches"] == 1 and snap["batch_occupancy"] == 1.0
    # a full batch is due immediately, no deadline wait
    futs = [b.submit(pool[i]) for i in range(4)]
    assert b.step(force=False) == 4
    assert all(f.done() for f in futs)


def test_batcher_threaded_event_driven(int8_engine, tiny):
    """Dispatcher-thread mode: max_wait_ms=0 makes dispatch purely
    event-driven (no timed waits), so this runs sleep-free while proving
    the thread path end to end — results still bit-identical."""
    *_, pool = tiny
    b = DynamicBatcher(int8_engine, max_batch=8, max_wait_ms=0.0,
                       queue_capacity=256)
    futs = [b.submit(pool[i % 16]) for i in range(48)]
    got = [np.asarray(f.result(timeout=30)) for f in futs]
    b.shutdown()
    for i, y in enumerate(got):
        np.testing.assert_array_equal(
            y, np.asarray(int8_engine.infer(pool[i % 16])))
    snap = b.metrics.snapshot()
    assert snap["requests_completed"] == 48
    assert snap["requests_shed"] == 0
    assert snap["batches"] >= 1 and snap["p99_ms"] is not None


def test_batcher_thread_survives_concurrent_step(int8_engine, tiny):
    """Regression: a step() call emptying the queue while the dispatcher
    waits out the batching window must not kill the thread (the window
    loop re-checks the queue each wakeup). The batcher must keep serving
    afterwards."""
    *_, pool = tiny
    b = DynamicBatcher(int8_engine, max_batch=8, max_wait_ms=50.0,
                       queue_capacity=64)
    f0 = b.submit(pool[0])   # thread now holds it for the 50 ms window
    b.step(force=True)       # steal the queue out from under the wait
    np.testing.assert_array_equal(np.asarray(f0.result(timeout=5)),
                                  np.asarray(int8_engine.infer(pool[0])))
    f1 = b.submit(pool[1])   # dispatcher must still be alive to serve it
    np.testing.assert_array_equal(np.asarray(f1.result(timeout=5)),
                                  np.asarray(int8_engine.infer(pool[1])))
    b.shutdown()


def test_batcher_submit_validation(float_engine, tiny):
    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=4, start=False)
    with pytest.raises(ValueError, match="expected"):
        b.submit(np.zeros((4, 4, 3), np.float32))
    with pytest.raises(ValueError, match="outside"):
        b.submit(pool[:5])  # > max_batch must be chunked by the caller
    b.drain()


def test_batcher_scatter_failure_to_futures(float_engine, tiny,
                                            monkeypatch):
    """An engine failure resolves every grouped future with the exception
    instead of hanging callers or killing the dispatcher."""
    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=4, start=False)
    futs = [b.submit(pool[i]) for i in range(2)]
    monkeypatch.setattr(b.engine.__class__, "run_padded",
                        lambda self, x: (_ for _ in ()).throw(
                            RuntimeError("boom")), raising=True)
    assert b.step() == 2
    for f in futs:
        with pytest.raises(RuntimeError, match="boom"):
            f.result(timeout=1)


def test_batcher_user_cancel_while_queued(float_engine, tiny):
    """A future the caller cancels while queued is dropped at dispatch —
    the rest of its batch still completes normally."""
    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=4, start=False)
    f0 = b.submit(pool[0])
    f1 = b.submit(pool[1])
    assert f0.cancel()
    assert b.step() == 1  # only the live request is served
    assert f0.cancelled()
    np.testing.assert_allclose(np.asarray(f1.result(1)),
                               np.asarray(float_engine.infer(pool[1])),
                               rtol=1e-5, atol=1e-5)
    b.drain()


def test_batcher_shutdown_without_drain_fails_pending(float_engine, tiny):
    """shutdown(drain=False) must FAIL still-pending futures with
    ShutdownError — a caller blocked on result() is released with a clear
    error, never orphaned on a forever-pending future."""
    from dcnn_tpu.serve.batcher import ShutdownError

    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=4, start=False)
    futs = [b.submit(pool[i]) for i in range(3)]
    b.shutdown(drain=False)
    for f in futs:
        assert f.done() and not f.cancelled()
        with pytest.raises(ShutdownError):
            f.result(timeout=0)
    assert b.queue_depth == 0
    with pytest.raises(RuntimeError):
        b.submit(pool[0])


def test_batcher_drain_timeout_fails_pending_not_orphans(float_engine, tiny):
    """A drain(timeout=) that trips must release every still-pending
    future with ShutdownError — including one held by a dispatch stuck in
    a hung engine — then raise TimeoutError. No future is left
    forever-pending, and the late engine completion is absorbed."""
    import threading

    from dcnn_tpu.serve.batcher import ShutdownError

    *_, pool = tiny
    b = DynamicBatcher(float_engine, max_batch=2, max_wait_ms=0,
                       queue_capacity=8)
    gate = threading.Event()
    real_run = b.engine.run_padded

    def hung_run(padded):
        gate.wait(timeout=30)  # a wedged accelerator tunnel
        return real_run(padded)

    from types import SimpleNamespace
    b.engine = SimpleNamespace(  # shadow only what submit/_run touch
        run_padded=hung_run, pad_to_bucket=float_engine.pad_to_bucket,
        input_shape=float_engine.input_shape, name=float_engine.name,
        max_batch=float_engine.max_batch)

    f0 = b.submit(pool[0])          # dispatched, stuck in hung_run
    import time as _t
    for _ in range(100):            # wait for the dispatcher to pick it up
        if f0.running():
            break
        _t.sleep(0.01)
    f1 = b.submit(pool[1])          # still queued behind the hung dispatch
    with pytest.raises(TimeoutError):
        b.drain(timeout=0.2)
    for f in (f0, f1):
        assert f.done()
        with pytest.raises(ShutdownError):
            f.result(timeout=0)
    gate.set()                      # un-wedge: late set_result is absorbed
    b._thread.join(timeout=30)
    assert not b._thread.is_alive()


# ---------------------------------------------------------------- metrics

def test_metrics_fake_clock_exact():
    fc = FakeClock()
    m = ServeMetrics(clock=fc)
    for lat_ms in (1, 2, 3, 4, 5, 6, 7, 8, 9, 10):
        m.record_done(lat_ms / 1e3)
    m.record_submit(10)
    m.record_shed(2)
    m.record_batch(6, 8)
    m.record_queue_depth(3)
    fc.advance(2.0)
    s = m.snapshot()
    assert s["throughput_rps"] == pytest.approx(5.0)  # 10 done / 2 s
    assert s["p50_ms"] == pytest.approx(6.0)   # nearest-rank on 10 samples
    assert s["p95_ms"] == pytest.approx(10.0)
    assert s["p99_ms"] == pytest.approx(10.0)
    assert s["mean_ms"] == pytest.approx(5.5)
    assert s["batch_occupancy"] == pytest.approx(0.75)
    assert s["shed_fraction"] == pytest.approx(2 / 12)
    assert s["queue_depth"] == 3 and s["wall_s"] == pytest.approx(2.0)
    m.reset()
    s = m.snapshot()
    assert s["requests_completed"] == 0 and s["p50_ms"] is None
    assert s["throughput_rps"] is None  # no wall elapsed yet


def test_metrics_rolling_window():
    m = ServeMetrics(window=4)
    for lat_ms in (100, 100, 100, 1, 1, 1, 1):  # spike ages out
        m.record_done(lat_ms / 1e3)
    s = m.snapshot()
    assert s["p99_ms"] == pytest.approx(1.0)
    assert s["requests_completed"] == 7  # counters stay cumulative


def test_metrics_empty_snapshot_is_unambiguous():
    m = ServeMetrics(clock=FakeClock())
    s = m.snapshot()
    assert s["p50_ms"] is None and s["batch_occupancy"] is None
    assert s["requests_completed"] == 0 and s["shed_fraction"] == 0.0


# ------------------------------------------------- example / bench surface

def test_serve_snapshot_example_imports():
    """Import smoke for examples/serve_snapshot.py: the module must import
    (no main() execution) with the examples dir resolving its `common`,
    not benchmarks/common which other tests may have loaded first."""
    import importlib

    ex_dir = os.path.join(REPO, "examples")
    saved_common = sys.modules.pop("common", None)
    sys.path.insert(0, ex_dir)
    try:
        mod = importlib.import_module("serve_snapshot")
        assert callable(mod.main)
        assert callable(mod.run_open_loop)
    finally:
        sys.path.remove(ex_dir)
        sys.modules.pop("serve_snapshot", None)
        sys.modules.pop("common", None)
        if saved_common is not None:
            sys.modules["common"] = saved_common


def test_bench_serve_curve_structure(int8_engine, tiny):
    """bench.py's serving section over an injected tiny engine: the result
    block must carry >= 3 offered-load points with latency, throughput,
    occupancy, and shed keys (the BENCH_SERVE=1 acceptance shape). Runs
    with sub-second traffic windows."""
    if REPO not in sys.path:
        sys.path.insert(0, REPO)
    import bench

    doc = bench.serve_section(None, engine=int8_engine,
                              loads=(200.0, 400.0, 800.0), seconds=0.25)
    assert doc["max_batch"] == int8_engine.max_batch
    assert len(doc["loads"]) >= 3
    for pt in doc["loads"]:
        assert set(pt) >= {"offered_rps", "achieved_rps", "p50_ms",
                           "p99_ms", "batch_occupancy", "shed_fraction"}
        assert pt["achieved_rps"] is None or pt["achieved_rps"] > 0


@pytest.mark.slow
def test_batcher_real_time_open_loop_soak(int8_engine, tiny):
    """Real-clock variant: open-loop arrivals with real sleeps, deadline
    waits exercised for real. Everything accepted must complete and the
    latency accounting must be populated."""
    from dcnn_tpu.serve import open_loop

    *_, pool = tiny
    b = DynamicBatcher(int8_engine, max_batch=8, max_wait_ms=2.0,
                       queue_capacity=64)
    futs = open_loop(b, pool, 400.0, 0.5)  # ~200 requests offered
    b.drain(timeout=30)
    for i, f in futs:
        np.testing.assert_array_equal(
            np.asarray(f.result(timeout=1)),
            np.asarray(int8_engine.infer(pool[i])))
    snap = b.metrics.snapshot()
    assert snap["requests_completed"] + snap["requests_shed"] >= len(futs)
    assert snap["requests_completed"] == len(futs)
    assert snap["p99_ms"] is not None and snap["throughput_rps"] > 0
