"""Pipeline failure-path + load-tracking tests (VERDICT r1 #8; reference
``coordinator.hpp:253-265`` timeout joins, ``pipeline_stage.hpp:199-229``
load tracking, ``:276-282`` error reports)."""

import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.parallel import InProcessPipelineCoordinator, PipelineError

KEY = jax.random.PRNGKey(0)


def _model():
    # batchnorm in BOTH halves of the 2-stage split (8 layers -> 4+4) so
    # abort must roll back mutated layer state (BN running stats) on every
    # stage, not just caches/grads
    return (SequentialBuilder("fail_model")
            .input((1, 8, 8))
            .conv2d(4, 3, 1, 1).batchnorm().activation("relu")
            .conv2d(4, 3, 1, 1).batchnorm().activation("relu")
            .flatten()
            .dense(10)
            .build())


def _coord(**kw):
    coord = InProcessPipelineCoordinator(
        _model(), SGD(0.05), "softmax_crossentropy",
        num_stages=2, num_microbatches=2, **kw)
    coord.deploy_stages(KEY)
    return coord


def _batch(n=8):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(n, 1, 8, 8)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, n)]
    return x, y


@pytest.mark.parametrize("schedule", ["sync", "semi_async"])
def test_stage_failure_aborts_and_recovers(schedule):
    """A stage raising mid-schedule must (a) surface as PipelineError with
    stage context, (b) leave no stale microbatch caches or partial grads,
    (c) let the next batch train identically to a never-failed coordinator."""
    coord = _coord()
    ref = _coord()
    x, y = _batch()
    fn = coord.train_batch_sync if schedule == "sync" else coord.train_batch_semi_async
    ref_fn = ref.train_batch_sync if schedule == "sync" else ref.train_batch_semi_async

    # break stage 1's backward for one batch
    victim = coord.stages[1]
    orig_bwd = victim._bwd

    def boom(*a, **k):
        raise RuntimeError("injected device failure")

    victim._bwd = boom
    with pytest.raises(PipelineError) as ei:
        fn(x, y, lr=0.05)
    assert ei.value.stage_id == 1
    assert ei.value.phase == "backward"
    victim._bwd = orig_bwd

    # consistent idle state: no cached microbatches, no partial grads, and
    # layer state (BN running stats) rolled back to batch start
    for s, r in zip(coord.stages, ref.stages):
        assert s._cache == {}
        assert s._grad_count == 0
        for g in jax.tree_util.tree_leaves(s._grad_acc):
            np.testing.assert_array_equal(np.asarray(g), 0.0)
        for a, b in zip(jax.tree_util.tree_leaves(s.state),
                        jax.tree_util.tree_leaves(r.state)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # the failed batch must not have perturbed training state
    loss_after, _ = fn(x, y, lr=0.05)
    loss_ref, _ = ref_fn(x, y, lr=0.05)
    np.testing.assert_allclose(loss_after, loss_ref, rtol=1e-5, atol=1e-6)


def test_forward_failure_context():
    coord = _coord()
    x, y = _batch()
    coord.stages[0]._fwd = lambda *a, **k: (_ for _ in ()).throw(
        ValueError("bad input"))
    with pytest.raises(PipelineError) as ei:
        coord.train_batch_sync(x, y, lr=0.05)
    assert ei.value.stage_id == 0 and ei.value.phase == "forward"


def test_unknown_microbatch_is_pipeline_error():
    coord = _coord()
    with pytest.raises(PipelineError) as ei:
        coord.stages[0].backward(99, jnp.zeros((4, 10)))
    assert ei.value.mb_id == 99


def test_join_and_timeout(monkeypatch):
    coord = _coord()
    x, y = _batch()
    coord.train_batch_sync(x, y, lr=0.05)
    assert coord.join() is True
    assert coord.join(timeout=30.0) is True

    # force expiry: make the fence hang
    import dcnn_tpu.parallel.pipeline as pl

    def slow_fence(tree):
        import time
        time.sleep(1.0)

    monkeypatch.setattr(pl, "hard_fence", slow_fence)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert coord.join(timeout=0.05) is False
    assert any("timed out" in str(x.message) for x in w)


def test_sampled_load_tracking():
    coord = _coord(track_load="sample")
    x, y = _batch(32)
    # SAMPLE_EVERY=8: run enough microbatches that each stage samples >=2
    for _ in range(10):
        coord.train_batch_sync(x, y, lr=0.05)
    reports = coord.collect_load_reports()
    assert len(reports) == 2
    for r in reports:
        assert r["forward_count"] >= 2
        assert r["backward_count"] >= 2
        assert r["avg_forward_ms"] > 0.0
        assert r["avg_backward_ms"] > 0.0
    # sampling must not fence every call
    assert coord.stages[0]._fwd_calls > coord.stages[0].load.forward_count


def test_track_load_validation():
    with pytest.raises(ValueError):
        _coord(track_load="always")


def test_health_join_defers_batch_messages():
    """A health probe racing an in-flight batch message must defer it for
    the next join, not raise and drop it (ADVICE r3 #3)."""
    from dcnn_tpu.parallel.comm import Inbox
    from dcnn_tpu.parallel.distributed_pipeline import (
        DistributedPipelineCoordinator)

    co = DistributedPipelineCoordinator.__new__(DistributedPipelineCoordinator)
    co.inbox = Inbox()
    co.timeout = 1.0
    co._gen = 0
    import collections
    co._deferred = collections.deque()
    co._health_nonce = 42
    # arrival order: a straggling batch result lands before the health acks
    co.inbox._q.put(("FORWARD_RESULT", {"mb_id": 0, "gen": 0}, "act", None))
    co.inbox._q.put(("HEALTH_ACK", {"stage_id": 0, "nonce": 42}, None, None))
    co.inbox._q.put(("HEALTH_ACK", {"stage_id": 1, "nonce": 42}, None, None))
    acks = co._join("HEALTH_ACK", 2, buffer_others=True)
    assert [m["stage_id"] for m, _ in acks] == [0, 1]
    # the batch message was deferred, not lost: the next join consumes it
    co._health_nonce = None
    got = co._join("FORWARD_RESULT", 1)
    assert got[0][1] == "act"


def test_strict_join_still_rejects_protocol_errors():
    from dcnn_tpu.parallel.comm import Inbox
    from dcnn_tpu.parallel.distributed_pipeline import (
        DistributedPipelineCoordinator)
    import collections

    co = DistributedPipelineCoordinator.__new__(DistributedPipelineCoordinator)
    co.inbox = Inbox()
    co.timeout = 1.0
    co._gen = 0
    co._deferred = collections.deque()
    co.inbox._q.put(("LOAD_REPORT", {"stage_id": 0}, None, None))
    with pytest.raises(RuntimeError, match="expected PARAMETERS_UPDATED"):
        co._join("PARAMETERS_UPDATED", 1)


def test_connect_retries_flaky_socket_with_backoff():
    """ISSUE 4 satellite: comm.connect rides the shared bounded-backoff
    primitive — a flaky listener (refuses k times, then accepts) is
    survived, the delays grow exponentially (jittered, capped), and the
    attempts land on the obs registry. Sleep-free via injected
    sleep/clock."""
    from dcnn_tpu.obs import get_registry
    from dcnn_tpu.parallel import comm

    class FakeSock:
        def setsockopt(self, *a):
            pass

        def settimeout(self, t):
            self.timeout = t

    flaky = {"left": 3}
    dialed = []

    def fake_create_connection(addr, timeout=None):
        dialed.append(addr)
        if flaky["left"] > 0:
            flaky["left"] -= 1
            raise ConnectionRefusedError("worker still importing jax")
        return FakeSock()

    sleeps = []
    t = [0.0]
    real = comm.socket.create_connection
    comm.socket.create_connection = fake_create_connection
    try:
        reg = get_registry()
        before = reg.counter("pipeline_connect_retry_attempts_total").value
        chan = comm.connect("10.0.0.7", 5555, timeout=30.0, delay=0.1,
                            sleep=lambda s: (sleeps.append(s),
                                             t.__setitem__(0, t[0] + s)),
                            clock=lambda: t[0])
        assert isinstance(chan._sock, FakeSock)
        assert dialed == [("10.0.0.7", 5555)] * 4          # 3 failures + 1 ok
        assert len(sleeps) == 3
        assert reg.counter(
            "pipeline_connect_retry_attempts_total").value == before + 3
        # bounded exponential with equal jitter: each delay in [d/2, d),
        # d = min(cap, base * 2**i)
        for i, s in enumerate(sleeps):
            d = min(2.0, 0.1 * 2 ** i)
            assert d / 2 <= s <= d, (i, s)
    finally:
        comm.socket.create_connection = real


def test_connect_gives_up_after_deadline_with_clear_error():
    from dcnn_tpu.parallel import comm

    def always_down(addr, timeout=None):
        raise ConnectionRefusedError("nobody home")

    t = [0.0]
    real = comm.socket.create_connection
    comm.socket.create_connection = always_down
    try:
        with pytest.raises(ConnectionError, match="cannot connect.*9:9999"):
            comm.connect("9", 9999, timeout=5.0, delay=0.5,
                         sleep=lambda s: t.__setitem__(0, t[0] + s),
                         clock=lambda: t[0])
        assert t[0] <= 5.0 + 2.0   # deadline bounded the loop, not attempts
    finally:
        comm.socket.create_connection = real


def test_connect_fault_point_drives_retry_then_recovers():
    """The comm.connect FaultPlan point: armed to fail twice, the third
    attempt succeeds — the deterministic-retry idiom the cookbook
    documents."""
    from dcnn_tpu.parallel import comm
    from dcnn_tpu.resilience import FaultPlan

    class FakeSock:
        def setsockopt(self, *a):
            pass

        def settimeout(self, t):
            pass

    real = comm.socket.create_connection
    comm.socket.create_connection = lambda addr, timeout=None: FakeSock()
    try:
        with FaultPlan().arm("comm.connect", times=2, exc=OSError) as plan:
            chan = comm.connect("w", 7777, timeout=10.0, delay=0.01,
                                sleep=lambda s: None)
            assert isinstance(chan._sock, FakeSock)
            assert plan.count("comm.connect") == 3
    finally:
        comm.socket.create_connection = real


def test_stale_profiling_reply_is_dropped():
    """A PROFILING_REPORT from a timed-out earlier round (wrong/absent nonce)
    must be dropped at consumption, never satisfying a later join or leaking
    into a batch join (review r4)."""
    from dcnn_tpu.parallel.comm import Inbox
    from dcnn_tpu.parallel.distributed_pipeline import (
        DistributedPipelineCoordinator)
    import collections

    co = DistributedPipelineCoordinator.__new__(DistributedPipelineCoordinator)
    co.inbox = Inbox()
    co.timeout = 0.2
    co._gen = 0
    co._deferred = collections.deque()
    co._profiling_nonce = 7

    # straggler from a previous round (nonce 3) then the real reply (nonce 7)
    co.inbox._q.put(("PROFILING_REPORT", {"stage_id": 0, "nonce": 3,
                                          "profile": {"stale": True}}, None, None))
    co.inbox._q.put(("PROFILING_REPORT", {"stage_id": 0, "nonce": 7,
                                          "profile": {"stale": False}}, None, None))
    got = co._join("PROFILING_REPORT", 1, buffer_others=True)
    assert got[0][0]["profile"] == {"stale": False}

    # outside any round (_profiling_nonce None) stragglers are dropped too
    co._profiling_nonce = None
    co.inbox._q.put(("PROFILING_CLEARED", {"stage_id": 0, "nonce": 3}, None, None))
    with pytest.raises(TimeoutError):
        co._join("ANYTHING", 1)
