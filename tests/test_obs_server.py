"""Telemetry-plane tests: HTTP exposition server, shared Prometheus
rendering, XLA cost/HBM introspection, tracer flush/truncation.

Contracts:

- **exposition conformance** (one shared renderer — serve/metrics.py and
  obs/registry.py may not drift): HELP/TYPE header lines, counters named
  ``*_total``, histograms with CUMULATIVE ``le`` buckets ending in
  ``+Inf`` and a ``_sum``/``_count`` pair whose count equals the ``+Inf``
  bucket;
- **TelemetryServer**: a live process exposes ``/metrics`` (valid
  Prometheus text), ``/healthz`` (200 healthy / **503 with a
  machine-readable reason** on watchdog-stall and corrupt-checkpoint
  states — injectable fakes, no sleeps) and ``/snapshot`` over HTTP on an
  ephemeral port, end to end via real GETs; graceful + idempotent stop;
- **wiring**: ``DynamicBatcher.start_telemetry`` serves the per-replica
  scrape surface and flips 503 on drain (the router contract); a live
  ``Trainer.fit`` with ``metrics_port=0`` scrapes mid-epoch;
- **obs/xla**: normalized cost analysis of real compiled executables
  (flops/bytes/roofline ratio), compile counters, HBM sampling latch;
- **tracer satellites**: ``flush_jsonl`` (plain + gzip, buffer cleared
  only after the write) and ``export_chrome(max_events=)`` with an
  explicit truncation note — never a silent drop.
"""

import gzip
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from dcnn_tpu.obs import MetricsRegistry, TelemetryServer
from dcnn_tpu.obs.exposition import CONTENT_TYPE
from dcnn_tpu.obs.server import checkpoint_check, watchdog_check
from dcnn_tpu.obs.tracer import Tracer
from dcnn_tpu.obs import xla as obs_xla


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _get(url, timeout=10):
    """(status, headers, body_bytes) for a GET, 4xx/5xx included."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, dict(r.headers), r.read()
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), e.read()


# ------------------------------------------------ exposition conformance

def assert_exposition_conformant(text: str):
    """The format rules every scraper assumes, checked line by line."""
    lines = [l for l in text.splitlines() if l]
    types = {}   # series name -> declared type
    helped = set()
    samples = {}  # name -> value str (scalar series)
    buckets = {}  # hist name -> list[(le_str, cum_int)]
    for line in lines:
        if line.startswith("# HELP "):
            name = line.split()[2]
            assert name not in types, f"HELP after TYPE for {name}"
            helped.add(name)
        elif line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            assert kind in ("counter", "gauge", "histogram")
            assert name not in types, f"duplicate TYPE for {name}"
            types[name] = kind
        else:
            name, _, value = line.partition(" ")
            if "{" in name:
                base, _, rest = name.partition("{")
                assert base.endswith("_bucket"), name
                assert rest.startswith('le="') and rest.endswith('"}'), name
                buckets.setdefault(base[: -len("_bucket")], []).append(
                    (rest[4:-2], int(value)))
            else:
                float(value)  # every sample parses as a number
                samples[name] = value
    for name, kind in types.items():
        if kind == "counter":
            assert name.endswith("_total"), \
                f"counter {name} missing _total suffix"
            assert name in samples
        elif kind == "histogram":
            cums = buckets.get(name)
            assert cums, f"histogram {name} has no _bucket series"
            assert cums[-1][0] == "+Inf", f"{name} buckets must end at +Inf"
            counts = [c for _, c in cums]
            assert counts == sorted(counts), f"{name} buckets not cumulative"
            assert f"{name}_sum" in samples and f"{name}_count" in samples
            assert int(samples[f"{name}_count"]) == cums[-1][1], \
                f"{name}_count != +Inf bucket"
    return types, samples


def test_registry_exposition_conformant():
    r = MetricsRegistry()
    r.counter("reqs_total", "requests\nserved").inc(5)
    r.gauge("depth", "queue depth").set(3)
    h = r.histogram("lat_seconds", "latency")
    for v in (1e-5, 2e-3, 0.7, 1e9):  # incl. the +Inf overflow bucket
        h.observe(v)
    types, samples = assert_exposition_conformant(r.prometheus())
    assert types == {"reqs_total": "counter", "depth": "gauge",
                     "lat_seconds": "histogram"}
    # HELP newline escaped per the exposition spec, never a raw newline
    assert "# HELP reqs_total requests\\nserved" in r.prometheus()


def test_serve_metrics_exposition_conformant_and_shared():
    from dcnn_tpu.serve import ServeMetrics

    fc = FakeClock()
    m = ServeMetrics(clock=fc)
    m.record_submit(4)
    m.record_queue_depth(4)
    m.record_batch(4, 8)
    fc.advance(0.25)
    m.record_done(0.25, 4)
    text = m.prometheus()
    types, samples = assert_exposition_conformant(text)
    # derived windowed gauges carry TYPE headers through the SAME renderer
    assert types["serve_latency_window_p99_ms"] == "gauge"
    assert samples["serve_samples_completed_total"] == "4"
    assert types["serve_latency_seconds"] == "histogram"


def test_builtin_guard_counter_name_conforms():
    # the StepGuard skip counter is part of the /healthz flag contract —
    # its name must carry the counter suffix
    from dcnn_tpu.resilience.guards import StepGuard

    reg = MetricsRegistry()
    g = StepGuard("skip_step", registry=reg)
    with pytest.warns(UserWarning):
        assert g.observe(1, True) == "skipped"
    assert reg.counter("train_skipped_steps_total").value == 1
    assert_exposition_conformant(reg.prometheus())


# ------------------------------------------------------- TelemetryServer

def test_server_end_to_end_ephemeral_port():
    reg = MetricsRegistry()
    reg.counter("pings_total", "pings").inc(2)
    tr = Tracer(enabled=True)
    with tr.span("unit.op", track="t", k=1):
        pass
    srv = TelemetryServer(registry=reg, tracer=tr, port=0).start()
    try:
        assert srv.port > 0
        code, hdrs, body = _get(srv.url + "/metrics")
        assert code == 200 and hdrs["Content-Type"] == CONTENT_TYPE
        assert_exposition_conformant(body.decode())
        assert "pings_total 2" in body.decode()

        code, _, body = _get(srv.url + "/healthz")
        h = json.loads(body)
        assert code == 200 and h["status"] == "ok" and h["reasons"] == []

        code, _, body = _get(srv.url + "/snapshot")
        s = json.loads(body)
        assert code == 200
        assert s["metrics"]["pings_total"] == 2
        assert s["span_counts"] == {"unit.op": 1}
        assert s["spans"][0]["name"] == "unit.op"
        args = s["spans"][0]["args"]
        assert args["k"] == 1
        assert args["trace_id"] and args["span_id"]  # PR 12 identity

        code, _, body = _get(srv.url + "/nope")
        assert code == 404 and "routes" in json.loads(body)
    finally:
        srv.stop()
    srv.stop()  # idempotent
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(srv.url + "/metrics", timeout=2)


def test_snapshot_events_bounded():
    tr = Tracer(enabled=True)
    for i in range(5):
        with tr.span("op", i=i):
            pass
    srv = TelemetryServer(registry=MetricsRegistry(), tracer=tr,
                          snapshot_events=2)
    snap = srv.snapshot()  # body builder exercised directly — no socket
    assert [e["args"]["i"] for e in snap["spans"]] == [3, 4]
    assert snap["span_counts"] == {"op": 5}


def test_healthz_watchdog_stall_flips_503():
    from dcnn_tpu.resilience.guards import StallWatchdog

    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    wd = StallWatchdog(5.0, clock=fc, registry=reg)  # never start()ed
    srv = TelemetryServer(registry=reg, clock=fc).add_check(
        "watchdog", watchdog_check(wd)).start()
    try:
        code, _, body = _get(srv.url + "/healthz")
        assert code == 200
        fc.advance(6.0)  # past timeout_s, no beat: stalled
        with pytest.warns(UserWarning):
            code, _, body = _get(srv.url + "/healthz")
        h = json.loads(body)
        assert code == 503 and h["status"] == "unhealthy"
        assert h["checks"]["watchdog"]["ok"] is False
        assert "stalled" in h["reasons"][0]
        # the registry stall flags ride along for the scraper
        assert h["flags"]["train_stalled"] == 1
        wd.beat()  # recovery: next scrape is healthy again
        code, _, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["flags"][
            "train_stalled"] == 0
    finally:
        srv.stop()


def test_healthz_corrupt_checkpoint_flips_503():
    class RottingManager:  # injectable fake: check() is the real contract
        def check(self):
            raise RuntimeError("async save failed: checksum mismatch")

    class HealthyManager:
        def check(self):
            return None

    srv = TelemetryServer(registry=MetricsRegistry()).add_check(
        "checkpoint", checkpoint_check(HealthyManager())).start()
    try:
        code, _, _ = _get(srv.url + "/healthz")
        assert code == 200
    finally:
        srv.stop()

    srv = TelemetryServer(registry=MetricsRegistry()).add_check(
        "checkpoint", checkpoint_check(RottingManager())).start()
    try:
        code, _, body = _get(srv.url + "/healthz")
        h = json.loads(body)
        assert code == 503
        assert "checkpoint save failing" in h["checks"]["checkpoint"][
            "reason"]
        assert "checksum mismatch" in h["reasons"][0]
    finally:
        srv.stop()


def test_checkpoint_health_probe_is_latching_and_non_consuming(tmp_path):
    """A real CheckpointManager with a failing async save: the /healthz
    probe must (a) stay degraded across repeated scrapes, and (b) NOT
    steal the failure from the trainer's own one-shot check() fail-fast."""
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.resilience.checkpoint import CheckpointManager
    from dcnn_tpu.train.trainer import create_train_state

    model = (SequentialBuilder("ck").input((4,)).dense(2).build())
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))

    def bad_write(path, data):
        raise OSError("disk full")

    cm = CheckpointManager(str(tmp_path), io_write=bad_write,
                           registry=MetricsRegistry())
    try:
        fut = cm.save_async(1, model, ts.params, ts.state, ts.opt_state,
                            opt, {})
        assert isinstance(fut.exception(timeout=30), OSError)
        chk = checkpoint_check(cm)
        assert "disk full" in chk()
        assert "disk full" in chk()  # second scrape: still degraded
        with pytest.raises(OSError):
            cm.check()               # trainer fail-fast NOT disarmed
        assert "disk full" in chk()  # latched even after check() consumed
    finally:
        cm.close()


def test_healthz_registry_stall_flag_without_check():
    # a process that wired a watchdog to the registry but not to the
    # server still degrades: the gauge alone flips /healthz
    reg = MetricsRegistry()
    reg.gauge("train_stalled").set(1)
    code, body = TelemetryServer(registry=reg).health()
    assert code == 503 and "train_stalled" in body["reasons"][0]


def test_health_check_exception_counts_as_degraded():
    srv = TelemetryServer(registry=MetricsRegistry())
    srv.add_check("boom", lambda: (_ for _ in ()).throw(OSError("disk")))
    code, body = srv.health()
    assert code == 503 and "OSError" in body["checks"]["boom"]["reason"]


# ------------------------------------------------------------ serve wiring

def _tiny_engine(max_batch=4):
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.serve import InferenceEngine
    from dcnn_tpu.train.trainer import create_train_state

    model = (SequentialBuilder("obs_srv").input((1, 8, 8))
             .conv2d(4, 3, 1, 1).activation("relu").flatten().dense(10)
             .build())
    ts = create_train_state(model, Adam(1e-3), jax.random.PRNGKey(0))
    return InferenceEngine.from_model(model, ts.params, ts.state,
                                      max_batch=max_batch)


def test_engine_cost_stats_and_compile_counters():
    from dcnn_tpu.obs import get_registry

    before = get_registry().counter("compile_total").value
    eng = _tiny_engine(max_batch=4)
    # one compile per bucket, all counted on the shared registry
    assert get_registry().counter("compile_total").value \
        == before + len(eng.bucket_sizes)
    top = eng.compile_stats[eng.max_batch]
    # XLA cost analysis attached per bucket (CPU backend exposes it)
    assert top["flops"] > 0 and top["bytes_accessed"] > 0
    assert top["bytes_per_flop"] == pytest.approx(
        top["bytes_accessed"] / top["flops"])
    assert get_registry().gauge("serve_flops_per_sample").value > 0


def test_batcher_telemetry_lifecycle():
    from dcnn_tpu.serve import DynamicBatcher

    eng = _tiny_engine()
    b = DynamicBatcher(eng, start=False)  # synchronous: fully deterministic
    srv = b.start_telemetry()
    try:
        fut = b.submit(np.zeros((1, 8, 8), np.float32))
        b.step()
        assert fut.result(timeout=10).shape == (10,)

        code, hdrs, body = _get(srv.url + "/metrics")
        text = body.decode()
        assert code == 200
        assert_exposition_conformant(text)
        # the serve exposition (registry + windowed gauges), not the bare
        # global registry — the exact-percentile series must be present
        assert "serve_samples_completed_total 1" in text
        assert "serve_latency_window_p99_ms" in text
        # engine cost gauges AND compile accounting mirrored onto the
        # (private) scrape registry
        assert "serve_flops_per_sample" in text
        assert f"compile_total {len(eng.bucket_sizes)}" in text

        code, _, _ = _get(srv.url + "/healthz")
        assert code == 200

        code, _, body = _get(srv.url + "/snapshot")
        s = json.loads(body)
        assert s["serve"]["requests_completed"] == 1
        assert s["engine"]["buckets"] == eng.bucket_sizes
        assert s["engine"]["compile_stats"][str(eng.max_batch)]["flops"] > 0

        b.drain()  # draining replica: scrapeable but unhealthy — the
        # router contract: stop routing BEFORE requests fail
        code, _, body = _get(srv.url + "/healthz")
        h = json.loads(body)
        assert code == 503 and "draining" in h["reasons"][0]
        code, _, _ = _get(srv.url + "/metrics")
        assert code == 200
    finally:
        b.shutdown()
    assert b._telemetry is None
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(srv.url + "/healthz", timeout=2)


# ----------------------------------------------------------- train wiring

def test_trainer_live_scrape(tmp_path):
    """A LIVE training process (mid-epoch, gated on an event — no sleeps)
    answers /metrics, /healthz and /snapshot on its ephemeral port; the
    server is gone after fit() returns."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.train.trainer import Trainer, create_train_state

    model = (SequentialBuilder("obs_live").input((1, 8, 8))
             .conv2d(2, 3, 1, 1).activation("relu").flatten().dense(10)
             .build())
    x = np.zeros((4, 1, 8, 8), np.float32)
    y = np.eye(10, dtype=np.float32)[np.zeros(4, int)]

    class GatedLoader:
        batch_size = 4

        def __init__(self):
            self.mid_epoch = threading.Event()
            self.release = threading.Event()

        def __iter__(self):
            yield x, y
            self.mid_epoch.set()
            assert self.release.wait(60)
            yield x, y

    cfg = TrainingConfig(epochs=1, snapshot_dir=None, metrics_port=0,
                         progress_interval=0)
    trainer = Trainer(model, Adam(1e-3), softmax_cross_entropy, cfg)
    ts = create_train_state(model, Adam(1e-3), jax.random.PRNGKey(0))
    loader = GatedLoader()
    err = []

    def run():
        try:
            trainer.fit(ts, loader, epochs=1)
        except BaseException as e:  # surfaced after join
            err.append(e)

    th = threading.Thread(target=run)
    th.start()
    try:
        assert loader.mid_epoch.wait(60), "training never reached batch 1"
        srv = trainer.telemetry
        assert srv is not None
        code, hdrs, body = _get(srv.url + "/metrics")
        assert code == 200 and hdrs["Content-Type"] == CONTENT_TYPE
        assert_exposition_conformant(body.decode())
        code, _, body = _get(srv.url + "/healthz")
        assert code == 200 and json.loads(body)["status"] == "ok"
        code, _, body = _get(srv.url + "/snapshot")
        assert code == 200 and "metrics" in json.loads(body)
        url = srv.url
    finally:
        loader.release.set()
        th.join(120)
    assert not err, err
    assert trainer.telemetry is None  # stopped by fit()'s finally
    with pytest.raises(urllib.error.URLError):
        urllib.request.urlopen(url + "/healthz", timeout=2)


def test_start_telemetry_twice_replaces_not_leaks():
    from dcnn_tpu.serve import DynamicBatcher

    eng = _tiny_engine()
    b = DynamicBatcher(eng, start=False)
    try:
        first = b.start_telemetry()
        first_url = first.url
        second = b.start_telemetry()
        assert b._telemetry is second
        # the first server's port is released, the second one answers
        with pytest.raises(urllib.error.URLError):
            urllib.request.urlopen(first_url + "/healthz", timeout=2)
        code, _, body = _get(second.url + "/metrics")
        assert code == 200
        # compile counters mirrored exactly once across both calls
        assert f"compile_total {len(eng.bucket_sizes)}" in body.decode()
    finally:
        b.shutdown()


def test_trainer_server_bind_failure_stops_watchdog():
    """A failed telemetry bind (fixed port already in use) must not leak
    the already-started stall watchdog."""
    import socket

    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.train.trainer import Trainer, create_train_state

    blocker = socket.socket()
    try:
        blocker.bind(("127.0.0.1", 0))
        blocker.listen(1)
        port = blocker.getsockname()[1]
        model = SequentialBuilder("bindfail").input((4,)).dense(2).build()
        cfg = TrainingConfig(epochs=1, snapshot_dir=None,
                             metrics_port=port, stall_timeout_s=60,
                             progress_interval=0)
        trainer = Trainer(model, Adam(1e-3), softmax_cross_entropy, cfg)
        ts = create_train_state(model, Adam(1e-3), jax.random.PRNGKey(0))
        with pytest.raises(OSError):
            trainer.fit(ts, [], epochs=1)
        assert trainer.watchdog is None and trainer.telemetry is None
    finally:
        blocker.close()


# --------------------------------------------------------------- obs/xla

def test_jit_cost_of_real_executable():
    import jax.numpy as jnp

    f = jax.jit(lambda a, b: jnp.tanh(a @ b).sum())
    a = jax.ShapeDtypeStruct((32, 32), jnp.float32)
    cost = obs_xla.jit_cost(f, a, a)
    assert cost is not None and cost["flops"] > 2 * 32 ** 3 * 0.9
    assert cost["bytes_accessed"] > 0
    assert cost["bytes_per_flop"] == pytest.approx(
        cost["bytes_accessed"] / cost["flops"])


def test_jit_cost_failure_is_none():
    class NotJitted:
        def lower(self, *a, **k):
            raise TypeError("nope")

    assert obs_xla.jit_cost(NotJitted(), 1) is None
    assert obs_xla.executable_cost(object()) is None


def test_record_compile_counters():
    reg = MetricsRegistry()
    obs_xla.record_compile(2.5, what="unit", registry=reg)
    obs_xla.record_compile(1.5, what="unit", registry=reg)
    snap = reg.snapshot()
    assert snap["compile_total"] == 2
    assert snap["compile_seconds_total"] == pytest.approx(4.0)
    assert snap["compile_unit_seconds_total"] == pytest.approx(4.0)


def test_analytic_mfu():
    assert obs_xla.analytic_mfu(2e9, 1000.0, 197.0) == pytest.approx(
        2e12 / 197e12)
    assert obs_xla.analytic_mfu(None, 1000.0, 197.0) is None
    assert obs_xla.analytic_mfu(2e9, 1000.0, None) is None


def test_sample_hbm_watermark_and_latch(monkeypatch):
    class Dev:
        def __init__(self, in_use, peak):
            self._s = {"bytes_in_use": in_use, "bytes_limit": 16 << 30,
                       "peak_bytes_in_use": peak}

        def memory_stats(self):
            return self._s

    monkeypatch.setattr(obs_xla, "_HBM_SUPPORTED", None)
    reg = MetricsRegistry()
    s = obs_xla.sample_hbm(reg, devices=[Dev(1 << 30, 2 << 30),
                                         Dev(3 << 30, 4 << 30)])
    assert s["hbm_bytes_in_use"] == 4 << 30
    assert s["hbm_bytes_limit"] == 32 << 30
    assert s["hbm_peak_bytes"] == 4 << 30
    # the watermark is monotone: a lower later sample never regresses it
    obs_xla.sample_hbm(reg, devices=[Dev(1 << 20, 1 << 20)])
    assert reg.gauge("hbm_peak_bytes").value == 4 << 30

    # CPU (no stats) latches unsupported: later calls are free no-ops
    monkeypatch.setattr(obs_xla, "_HBM_SUPPORTED", None)
    assert obs_xla.sample_hbm(reg) is None  # jax CPU devices: stats None
    assert obs_xla._HBM_SUPPORTED is False
    assert obs_xla.sample_hbm(reg) is None


# ------------------------------------------------------ tracer satellites

def _jsonl_events(lines):
    """Parsed JSONL events, skipping the shard-header line PR 12's merge
    CLI reads (detected by its "shard" key — events always carry "name")."""
    out = []
    for line in lines:
        obj = json.loads(line)
        if "shard" in obj and "name" not in obj:
            continue
        out.append(obj)
    return out


def test_flush_jsonl_plain_and_gzip(tmp_path):
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    for i in range(4):
        with t.span("op", i=i):
            fc.advance(0.5)
    plain = str(tmp_path / "t.jsonl")
    t.export_jsonl(plain)  # export does NOT clear
    assert len(t) == 4
    gz = str(tmp_path / "t.jsonl.gz")
    t.flush_jsonl(gz, gzip=True)  # flush writes then clears
    assert len(t) == 0
    with open(plain) as f:
        plain_evs = _jsonl_events(f)
    with gzip.open(gz, "rt") as f:
        gz_evs = _jsonl_events(f)
    assert plain_evs == gz_evs
    assert [e["args"]["i"] for e in gz_evs] == [0, 1, 2, 3]
    assert all(e["dur_s"] == 0.5 for e in gz_evs)


def test_flush_jsonl_concurrent_events_survive_and_epoch_persists(
        tmp_path, monkeypatch):
    """Events recorded DURING the flush write land in the buffer for the
    next flush (never lost, never duplicated), and the tracer epoch is
    untouched so timestamps stay monotone across flushes."""
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    with t.span("a"):
        fc.advance(1.0)
    orig = t._write_jsonl

    def write_and_record(evs, path, gz):  # a recorder wins the race
        orig(evs, path, gz)
        with t.span("b"):
            fc.advance(1.0)

    monkeypatch.setattr(t, "_write_jsonl", write_and_record)
    p1 = str(tmp_path / "f1.jsonl")
    t.flush_jsonl(p1)
    monkeypatch.setattr(t, "_write_jsonl", orig)
    assert [e["name"] for e in t.events()] == ["b"]  # survived the flush
    with open(p1) as f:
        assert [e["name"] for e in _jsonl_events(f)] == ["a"]
    p2 = str(tmp_path / "f2.jsonl")
    t.flush_jsonl(p2)
    with open(p2) as f:
        evs2 = _jsonl_events(f)
    assert [e["name"] for e in evs2] == ["b"]
    assert evs2[0]["ts_s"] == 1.0  # same epoch as before the first flush
    assert len(t) == 0


def test_flush_jsonl_saturated_ring_never_overpops(tmp_path, monkeypatch):
    """Ring AT CAPACITY during the flush write: eviction removes exported
    events from the left while new ones arrive — the drain must stop at
    the first unexported event instead of popping len(snapshot) blindly
    (which would eat never-exported events)."""
    fc = FakeClock()
    t = Tracer(capacity=4, clock=fc, enabled=True)
    for i in range(4):  # ring full: snapshot will be exactly capacity
        with t.span("old", i=i):
            fc.advance(1.0)
    orig = t._write_jsonl

    def write_and_record(evs, path, gz):
        orig(evs, path, gz)
        for j in range(2):  # evicts two exported 'old' events
            with t.span("new", j=j):
                fc.advance(1.0)

    monkeypatch.setattr(t, "_write_jsonl", write_and_record)
    p = str(tmp_path / "sat.jsonl")
    t.flush_jsonl(p)
    with open(p) as f:
        assert [e["name"] for e in _jsonl_events(f)] == ["old"] * 4
    # both never-exported events survive; all exported ones are gone
    assert [(e["name"], e["args"]["j"]) for e in t.events()] == [
        ("new", 0), ("new", 1)]


def test_flush_jsonl_failed_write_keeps_events(tmp_path):
    t = Tracer(enabled=True)
    with t.span("op"):
        pass
    bad = str(tmp_path / "dir_not_file")
    os.makedirs(bad)
    with pytest.raises(IsADirectoryError):
        t.flush_jsonl(bad)
    assert len(t) == 1  # clear only happens after a successful write


def test_export_chrome_truncation_note(tmp_path):
    fc = FakeClock()
    t = Tracer(clock=fc, enabled=True)
    for i in range(10):
        with t.span("op", i=i):
            fc.advance(0.1)
    path = str(tmp_path / "trace.json")
    t.export_chrome(path, max_events=4)
    with open(path) as f:
        evs = json.load(f)["traceEvents"]
    real = [e for e in evs if e["ph"] in ("X", "i")]
    note, spans = real[0], real[1:]
    # newest 4 survive, and the drop is explicit — log-truncation style
    assert [e["args"]["i"] for e in spans] == [6, 7, 8, 9]
    assert note["name"] == "tracer.truncated" and note["ph"] == "i"
    assert note["args"]["dropped_older_events"] == 6
    assert "6 older events truncated" in note["args"]["note"]

    # under the cap: no note, nothing dropped
    t.export_chrome(path, max_events=100)
    with open(path) as f:
        evs = json.load(f)["traceEvents"]
    assert [e["name"] for e in evs if e["ph"] != "M"] == ["op"] * 10

    with pytest.raises(ValueError):
        t.export_chrome(path, max_events=0)


# ------------------------------------- render→parse round trip (PR 11)
# parse_prometheus_text is the autoscaler's scrape client: its only
# contract with a replica is the exposition text itself, so the inverse
# must round-trip everything the shared renderer emits.

def test_parse_round_trips_registry_exposition():
    from dcnn_tpu.obs.exposition import (
        parse_prometheus_text, render_histogram, scalar_values,
    )

    r = MetricsRegistry()
    r.counter("reqs_total", "requests\nserved").inc(5)
    r.gauge("depth", "queue depth").set(3)
    h = r.histogram("lat_seconds", "latency")
    for v in (1e-5, 2e-3, 0.7, 1e9):  # incl. the +Inf overflow bucket
        h.observe(v)
    fams = parse_prometheus_text(r.prometheus())
    assert fams["reqs_total"]["kind"] == "counter"
    assert fams["reqs_total"]["value"] == 5.0
    # HELP unescaping is the exact inverse of the renderer's escaping
    assert fams["reqs_total"]["help"] == "requests\nserved"
    assert fams["depth"]["kind"] == "gauge" and fams["depth"]["value"] == 3.0
    hist = fams["lat_seconds"]
    assert hist["kind"] == "histogram"
    assert hist["count"] == 4
    assert hist["sum"] == pytest.approx(1e9 + 0.7 + 2e-3 + 1e-5)
    assert hist["buckets"][-1] == (float("inf"), 4)
    cums = [c for _, c in hist["buckets"]]
    assert cums == sorted(cums)
    # render(parse(render(x))) is the identity on values: the parsed
    # buckets/sum/count ARE render_histogram's input shape
    again = "\n".join(render_histogram(
        "lat_seconds", hist["buckets"], hist["sum"], hist["count"],
        help=hist["help"]))
    assert parse_prometheus_text(again)["lat_seconds"] == hist
    # the flattened scalar view the autoscaler's signal extraction reads
    flat = scalar_values(fams)
    assert flat["reqs_total"] == 5.0 and flat["depth"] == 3.0
    assert "lat_seconds" not in flat  # histograms are not scalars


def test_parse_round_trips_serve_metrics_exposition():
    from dcnn_tpu.obs.exposition import parse_prometheus_text, scalar_values
    from dcnn_tpu.serve import ServeMetrics

    fc = FakeClock()
    m = ServeMetrics(clock=fc)
    m.record_submit(4)
    m.record_queue_depth(4)
    m.record_batch(4, 8)
    fc.advance(0.25)
    m.record_done(0.25, 4)
    fams = parse_prometheus_text(m.prometheus())
    vals = scalar_values(fams)
    # exactly the signals the autoscaler's collect() reads
    assert vals["serve_queue_depth"] == 4.0
    assert vals["serve_samples_completed_total"] == 4.0
    assert "serve_latency_window_p99_ms" in vals
    assert fams["serve_latency_seconds"]["kind"] == "histogram"
    assert fams["serve_latency_seconds"]["count"] == \
        fams["serve_latency_seconds"]["buckets"][-1][1]


def test_parse_label_escapes_and_untyped_series():
    from dcnn_tpu.obs.exposition import (
        escape_label_value, parse_prometheus_text,
    )

    raw = 'a "quoted\\path"\nline2'
    text = (f'weird{{path="{escape_label_value(raw)}",x="1"}} 2.5\n'
            "no_type_series 7\n")
    fams = parse_prometheus_text(text)
    labels, value = fams["weird"]["samples"][0]
    assert labels == {"path": raw, "x": "1"}
    assert value == 2.5
    assert fams["no_type_series"]["kind"] == "untyped"
    assert fams["no_type_series"]["value"] == 7.0


def test_parse_rejects_malformed_lines():
    from dcnn_tpu.obs.exposition import parse_prometheus_text

    # a scrape that half-parses must not feed a scaling decision
    with pytest.raises(ValueError, match="line 2"):
        parse_prometheus_text("ok 1\nbroken_series_without_value\n")
    with pytest.raises(ValueError, match="unparseable"):
        parse_prometheus_text("bad_value nope\n")
