"""Sequential container + checkpoint + end-to-end training tests.

Reference analog: ``sequential_residual_block_test.cpp``,
``layer_buffer_reuse_test.cpp`` and the MNIST trainer e2e (SURVEY.md §4.5).
"""


import jax
import jax.numpy as jnp
import numpy as np

from dcnn_tpu.models import create_mnist_trainer, create_model
from dcnn_tpu.nn import Sequential, SequentialBuilder
from dcnn_tpu.optim import SGD, Adam
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train import (
    TrainState, load_checkpoint, make_train_step, save_checkpoint,
)
from dcnn_tpu.train.trainer import create_train_state

KEY = jax.random.PRNGKey(0)


def _small_model():
    return (SequentialBuilder("small")
            .input((1, 8, 8))
            .conv2d(4, 3, 1, 1).batchnorm().activation("relu")
            .maxpool2d(2)
            .flatten()
            .dropout(0.25)
            .dense(10)
            .build())


def test_builder_shape_inference_and_apply():
    model = _small_model()
    assert model.output_shape() == (10,)
    params, state = model.init(KEY)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 1, 8, 8))
    y, new_state = model.apply(params, state, x, training=True, rng=jax.random.PRNGKey(2))
    assert y.shape == (2, 10)


def test_unique_layer_names():
    m = Sequential()
    from dcnn_tpu.nn import FlattenLayer
    m.add(FlattenLayer(name="f")).add(FlattenLayer(name="f")).add(FlattenLayer(name="f"))
    assert [l.name for l in m.layers] == ["f", "f_1", "f_2"]


def test_config_roundtrip_preserves_architecture():
    model = create_mnist_trainer()
    cfg = model.get_config()
    clone = Sequential.from_config(cfg)
    assert clone.get_config() == cfg
    # same param structure and shapes after init
    p1, s1 = model.init(KEY)
    p2, s2 = clone.init(KEY)
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a.shape, b.shape), p1, p2)
    # identical seeds → identical params
    jax.tree_util.tree_map(lambda a, b: np.testing.assert_array_equal(a, b), p1, p2)


def test_config_roundtrip_resnet_nested_blocks():
    model = create_model("resnet9_cifar10")
    clone = Sequential.from_config(model.get_config())
    assert clone.get_config() == model.get_config()
    x = jax.random.normal(KEY, (1, 3, 32, 32))
    p, s = model.init(KEY)
    y1, _ = model.apply(p, s, x)
    y2, _ = clone.apply(p, s, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_cifar100_model_shapes_and_roundtrip():
    """cnn_cifar100 (reference examples/cifar100_cnn_trainer.cpp:40-79;
    100-class head, correcting the reference's dense(10) quirk)."""
    model = create_model("cnn_cifar100")
    assert model.output_shape() == (100,)
    clone = Sequential.from_config(model.get_config())
    assert clone.get_config() == model.get_config()
    x = jax.random.normal(KEY, (2, 3, 32, 32))
    p, s = model.init(KEY)
    y, _ = model.apply(p, s, x)
    assert y.shape == (2, 100)


def test_split_partitions():
    model = create_mnist_trainer()
    n = len(model)
    stages = model.split([(0, 5), (5, n)])
    assert len(stages[0]) == 5 and len(stages[1]) == n - 5
    assert stages[0].input_shape == (1, 28, 28)
    assert stages[1].input_shape == stages[0].output_shape()
    # stage-chained forward == full forward
    params, state = model.init(KEY)
    sp = model.split_params(params, [(0, 5), (5, n)])
    ss = model.split_params(state, [(0, 5), (5, n)])
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 1, 28, 28))
    full, _ = model.apply(params, state, x)
    h = x
    for stage, p, s in zip(stages, sp, ss):
        h, _ = stage.apply(p, s, h)
    np.testing.assert_allclose(np.asarray(h), np.asarray(full), rtol=1e-5, atol=1e-6)


def test_checkpoint_roundtrip(tmp_path):
    model = _small_model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, KEY)
    # take one step so opt state is non-trivial
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    x = jax.random.normal(KEY, (4, 1, 8, 8))
    y = jax.nn.one_hot(jnp.array([1, 2, 3, 4]), 10)
    ts, loss, _ = step(ts, x, y, jax.random.PRNGKey(1), 1e-3)

    path = str(tmp_path / "ckpt")
    save_checkpoint(path, model, ts.params, ts.state, ts.opt_state, opt,
                    {"epoch": 1})
    model2, params2, state2, opt_state2, opt2, meta = load_checkpoint(path)
    assert meta["epoch"] == 1
    assert opt2.get_config() == opt.get_config()
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        ts.params, params2)
    # Adam moments restored (improvement over reference which drops them)
    np.testing.assert_array_equal(np.asarray(opt_state2["t"]), np.asarray(ts.opt_state["t"]))
    # restored model is functionally identical
    y1, _ = model.apply(ts.params, ts.state, x)
    y2, _ = model2.apply(params2, state2, x)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), rtol=1e-6)


def test_training_reduces_loss_mnist_synthetic():
    """End-to-end slice: a few steps on separable synthetic data must reduce
    loss and reach high accuracy (stands in for MNIST ≥99% until real data is
    present; reference e2e = mnist_cnn_trainer)."""
    model = create_mnist_trainer()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, KEY)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)

    rng = np.random.default_rng(0)
    n, ncls = 64, 10
    labels = rng.integers(0, ncls, size=n)
    # class-dependent blob pattern: trivially separable
    x = rng.normal(size=(n, 1, 28, 28)).astype(np.float32) * 0.1
    for i, c in enumerate(labels):
        x[i, 0, c, c] += 3.0
    y = np.eye(ncls, dtype=np.float32)[labels]

    losses = []
    for it in range(30):
        ts, loss, logits = step(ts, jnp.asarray(x), jnp.asarray(y),
                                jax.random.fold_in(KEY, it), 1e-3)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, losses
    preds = np.argmax(np.asarray(logits), axis=-1)
    assert (preds == labels).mean() > 0.8


def test_microbatched_step_matches_sgd_full_batch():
    """Grad accumulation over microbatches must equal the full-batch gradient
    for BN-free models (with BN the reference also differs batch-vs-microbatch
    — that's expected semantics)."""
    model = (SequentialBuilder("nobn").input((4,)).dense(8).activation("relu")
             .dense(3).build())
    opt = SGD(0.1)
    ts1 = create_train_state(model, opt, KEY)
    ts2 = TrainState(ts1.params, ts1.state, ts1.opt_state, ts1.step)

    x = jax.random.normal(jax.random.PRNGKey(5), (8, 4))
    y = jax.nn.one_hot(jnp.arange(8) % 3, 3)
    step_full = make_train_step(model, softmax_cross_entropy, opt, 1, donate=False)
    step_mb = make_train_step(model, softmax_cross_entropy, opt, 4, donate=False)
    ts1, loss1, _ = step_full(ts1, x, y, KEY, 0.1)
    ts2, loss2, _ = step_mb(ts2, x, y, KEY, 0.1)
    # softmax-CE mean over each microbatch then averaged == full-batch mean
    np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                rtol=1e-4, atol=1e-6),
        ts1.params, ts2.params)
