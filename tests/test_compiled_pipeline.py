"""Compiled (single-jit, shard_map+ppermute) pipeline schedule tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
from dcnn_tpu.nn import Conv2DLayer, GroupNormLayer, ResidualBlock
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.parallel.compiled_pipeline import (
    SequentialStageStack, make_compiled_pipeline_forward,
    make_compiled_pipeline_train_step, shard_stacked, )

KEY = jax.random.PRNGKey(0)
S = 4       # stages
MB = 6      # microbatches


def _mesh():
    return make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])


def _block():
    return ResidualBlock(
        layers=[Conv2DLayer(4, 3, 1, 1, name="c0"),
                GroupNormLayer(2, name="g0")],
        shortcut=[], activation="relu")


def test_compiled_forward_matches_sequential_chain():
    mesh = _mesh()
    stack = SequentialStageStack(_block(), S, (4, 8, 8))
    params = stack.init(KEY)

    mbs = jax.random.normal(jax.random.PRNGKey(1), (MB, 2, 4, 8, 8))
    fwd = make_compiled_pipeline_forward(stack.stage_fn, S, MB, mesh)
    out = fwd(shard_stacked(params, mesh), mbs)

    # reference: run each microbatch through the 4 stages sequentially
    per_stage = [jax.tree_util.tree_map(lambda x: x[i], params) for i in range(S)]
    for i in range(MB):
        h = mbs[i]
        for sp in per_stage:
            h = stack.stage_fn(sp, h)
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(h),
                                   rtol=1e-4, atol=1e-5)


def test_compiled_train_step_matches_unpipelined_grads():
    mesh = _mesh()
    stack = SequentialStageStack(_block(), S, (4, 8, 8))
    params = stack.init(KEY)
    opt = SGD(0.05)

    rng = np.random.default_rng(0)
    mb_x = jnp.asarray(rng.normal(size=(MB, 2, 4, 8, 8)).astype(np.float32))
    # fake per-microbatch "labels": flatten conv output to logits via mean —
    # use an elementwise regression-style loss on the activation itself
    mb_y = jnp.asarray(rng.normal(size=(MB, 2, 4, 8, 8)).astype(np.float32))

    def loss_fn(pred, tgt):
        return jnp.mean((pred - tgt) ** 2)

    step = make_compiled_pipeline_train_step(stack.stage_fn, loss_fn, opt, S, MB, mesh)
    p_sharded = shard_stacked(params, mesh)
    opt_state = opt.init(p_sharded)
    new_params, _, loss, outs = step(p_sharded, opt_state, mb_x, mb_y,
                                     jnp.float32(0.05))

    # unpipelined reference: same math without the schedule
    def ref_loss(p):
        per_stage = [jax.tree_util.tree_map(lambda x: x[i], p) for i in range(S)]
        losses = []
        for i in range(MB):
            h = mb_x[i]
            for sp in per_stage:
                h = stack.stage_fn(sp, h)
            losses.append(loss_fn(h, mb_y[i]))
        return jnp.mean(jnp.stack(losses))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    ref_new = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, ref_g)
    for a, b in zip(jax.tree_util.tree_leaves(new_params),
                    jax.tree_util.tree_leaves(ref_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_compiled_pipeline_data_parallel_composition():
    """DP×PP in one jit: a ('data','stage') mesh with the batch sharded over
    'data' must produce bit-identical loss and updated params to the
    pipeline-only run on the same global batch (shard_map's transpose
    inserts the gradient psum over 'data')."""
    from jax.sharding import Mesh

    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh2d = Mesh(devs, ("data", STAGE_AXIS))
    mesh1d = _mesh()

    stack = SequentialStageStack(_block(), S, (4, 8, 8))
    params = stack.init(KEY)
    rng = np.random.default_rng(0)
    mb_x = jnp.asarray(rng.normal(size=(MB, 4, 4, 8, 8)).astype(np.float32))
    mb_y = jnp.asarray(rng.normal(size=(MB, 4, 4, 8, 8)).astype(np.float32))

    def loss_fn(pred, tgt):
        return jnp.mean((pred - tgt) ** 2)

    results = {}
    for name, mesh, dax in (("pp", mesh1d, None), ("dpxpp", mesh2d, "data")):
        opt = SGD(0.05)
        step = make_compiled_pipeline_train_step(
            stack.stage_fn, loss_fn, opt, S, MB, mesh, data_axis=dax)
        p = shard_stacked(params, mesh)
        new_p, _, loss, outs = step(p, opt.init(p), mb_x, mb_y,
                                    jnp.float32(0.05))
        results[name] = (float(loss), new_p, np.asarray(outs))

    np.testing.assert_allclose(results["pp"][0], results["dpxpp"][0],
                               rtol=1e-6)
    np.testing.assert_allclose(results["pp"][2], results["dpxpp"][2],
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(results["pp"][1]),
                    jax.tree_util.tree_leaves(results["dpxpp"][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


def test_stage_stack_rejects_shape_changing_block():
    with pytest.raises(ValueError):
        SequentialStageStack(Conv2DLayer(8, 3, 2, 1), S, (4, 8, 8))


def test_stage_stack_rejects_stateful_block():
    from dcnn_tpu.nn import BatchNormLayer
    with pytest.raises(ValueError):
        SequentialStageStack(BatchNormLayer(), S, (4, 8, 8)).init(KEY)


# ---------------------------------------------------------------------------
# Heterogeneous compiled pipeline (flat-padded stages + lax.switch)
# ---------------------------------------------------------------------------

from dcnn_tpu.nn import SequentialBuilder  # noqa: E402
from dcnn_tpu.optim import Adam  # noqa: E402
from dcnn_tpu.parallel import InProcessPipelineCoordinator  # noqa: E402
from dcnn_tpu.parallel.compiled_pipeline import HeteroCompiledPipeline  # noqa: E402


def _hetero_model():
    """Deliberately heterogeneous: conv stem w/ BN, downsampling pool, dense
    head — stages differ in params structure, activation shape and state."""
    return (SequentialBuilder("hetero_pipe")
            .input((3, 8, 8))
            .conv2d(4, 3, 1, 1).batchnorm().activation("relu")
            .maxpool2d(2)
            .conv2d(8, 3, 1, 1).batchnorm().activation("relu")
            .flatten()
            .dense(16).activation("relu")
            .dense(5)
            .build())


@pytest.fixture(scope="module")
def hetero_setup():
    S, M = 2, 2
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    model = _hetero_model()
    pipe = HeteroCompiledPipeline(model, S, M, mesh)
    return pipe, S, M


def test_hetero_matches_host_driven_pipeline(hetero_setup):
    """One compiled-GPipe step == one host-driven sync-schedule step: same
    loss, same updated params, same BN running stats.

    Momentum SGD (not Adam) for the update parity: Adam's first step is
    ~lr*sign(grad), which amplifies fp-noise on mathematically-zero grads
    (conv bias feeding BN) into ±lr flips — grads themselves agree to ~1e-8.
    """
    pipe, S, M = hetero_setup
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 3, 8, 8)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, size=8)]
    key = jax.random.PRNGKey(3)
    lr = 0.05

    # host-driven reference (NaivePartitioner on both sides)
    coord = InProcessPipelineCoordinator(
        _hetero_model(), SGD(lr, momentum=0.9), "softmax_crossentropy",
        num_stages=S, num_microbatches=M)
    coord.deploy_stages(key)
    ref_loss, _ = coord.train_batch_sync(x, y, lr, jax.random.PRNGKey(9))

    # compiled
    opt = SGD(lr, momentum=0.9)
    fp, fs = pipe.init(key)
    opt_state = opt.init(fp)
    step = pipe.make_train_step(softmax_cross_entropy, opt)
    mb_x = jnp.asarray(x.reshape(M, 4, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, 4, 5))
    fp, opt_state, fs, loss, logits = step(
        fp, opt_state, fs, mb_x, mb_y, jax.random.PRNGKey(9),
        jnp.float32(lr))

    assert abs(float(loss) - ref_loss) < 1e-5, (float(loss), ref_loss)

    # updated params + BN state match stage-for-stage
    ps, ss = pipe.unpack_params(fp, fs)
    for sid in range(S):
        ref_p = jax.device_get(coord.stages[sid].params)
        ref_s = jax.device_get(coord.stages[sid].state)
        for a, b in zip(jax.tree_util.tree_leaves(ps[sid]),
                        jax.tree_util.tree_leaves(ref_p)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)
        for a, b in zip(jax.tree_util.tree_leaves(ss[sid]),
                        jax.tree_util.tree_leaves(ref_s)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=2e-5, rtol=2e-5)


def test_hetero_multi_step_loss_decreases(hetero_setup):
    pipe, S, M = hetero_setup
    rng = np.random.default_rng(1)
    x = rng.normal(size=(8, 3, 8, 8)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, size=8)]
    opt = Adam(0.01)
    fp, fs = pipe.init(jax.random.PRNGKey(0))
    opt_state = opt.init(fp)
    step = pipe.make_train_step(softmax_cross_entropy, opt)
    mb_x = jnp.asarray(x.reshape(M, 4, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, 4, 5))
    losses = []
    for i in range(8):
        fp, opt_state, fs, loss, _ = step(
            fp, opt_state, fs, mb_x, mb_y, jax.random.PRNGKey(i),
            jnp.float32(0.01))
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_hetero_runs_flagship_resnet18():
    """The flagship ResNet-18 Tiny-ImageNet trains through the compiled
    schedule (VERDICT r1 item 5c) — tiny microbatches, 4 stages."""
    from dcnn_tpu.models import create_resnet18_tiny_imagenet
    from dcnn_tpu.parallel import FlopBalancedPartitioner

    S, M = 4, 4
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    model = create_resnet18_tiny_imagenet()
    pipe = HeteroCompiledPipeline(model, S, M, mesh,
                                  partitioner=FlopBalancedPartitioner())
    opt = SGD(0.01)
    fp, fs = pipe.init(jax.random.PRNGKey(0))
    opt_state = opt.init(fp)
    step = pipe.make_train_step(softmax_cross_entropy, opt)
    rng = np.random.default_rng(0)
    mb_x = jnp.asarray(rng.normal(size=(M, 2, 3, 64, 64)).astype(np.float32))
    mb_y = jnp.asarray(np.eye(200, dtype=np.float32)[
        rng.integers(0, 200, size=(M, 2))])
    fp, opt_state, fs, loss, logits = step(
        fp, opt_state, fs, mb_x, mb_y, jax.random.PRNGKey(1),
        jnp.float32(0.01))
    assert np.isfinite(float(loss))
    assert logits.shape == (M, 2, 200)


def test_hetero_bf16_wire_parity(hetero_setup):
    """bf16 rotate buffers (wire_dtype): loss tracks the fp32-wire engine to
    bf16 tolerance and training still converges — the ICI payload halves."""
    pipe, S, M = hetero_setup
    mesh = pipe.mesh
    model = _hetero_model()
    pipe16 = HeteroCompiledPipeline(model, S, M, mesh,
                                    wire_dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 3, 8, 8)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, size=8)]
    key = jax.random.PRNGKey(3)
    mb_x = jnp.asarray(x.reshape(M, 4, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, 4, 5))

    losses = {}
    for name, p in (("fp32", pipe), ("bf16", pipe16)):
        opt = SGD(0.05)
        fp, fs = p.init(key)
        opt_state = opt.init(fp)
        step = p.make_train_step(softmax_cross_entropy, opt)
        ls = []
        for i in range(4):
            fp, opt_state, fs, loss, _ = step(
                fp, opt_state, fs, mb_x, mb_y, jax.random.PRNGKey(9),
                jnp.float32(0.05))
            ls.append(float(loss))
        losses[name] = ls

    assert abs(losses["bf16"][0] - losses["fp32"][0]) < 0.05
    assert losses["bf16"][-1] < losses["bf16"][0]


def test_hetero_wire_ships_exact_boundary_bytes():
    """The rotate path must ship each stage boundary at its EXACT width
    (VERDICT r3 weak #4): the lowered program's collective-permutes carry
    tensors sized to each boundary activation, never the padded max-width
    rotate buffer, and there is no S-1 -> 0 wrap transfer."""
    import re

    from dcnn_tpu.parallel.compiled_pipeline import _prod

    S, M, mb = 3, 3, 2
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    # three stages with distinct boundary sizes: flatten+dense head shrinks
    model = (SequentialBuilder("wire_exact")
             .input((3, 8, 8))
             .conv2d(4, 3, 1, 1).activation("relu")
             .maxpool2d(2)
             .conv2d(8, 3, 1, 1).activation("relu")
             .flatten()
             .dense(16).activation("relu")
             .dense(5)
             .build())
    pipe = HeteroCompiledPipeline(model, S, M, mesh)
    opt = SGD(0.05)
    fp, fs = pipe.init(jax.random.PRNGKey(0))
    opt_state = opt.init(fp)
    step = pipe.make_train_step(softmax_cross_entropy, opt)
    mb_x = jnp.zeros((M, mb, 3, 8, 8), jnp.float32)
    mb_y = jnp.zeros((M, mb, 5), jnp.float32)

    lowered = step.lower(fp, opt_state, fs, mb_x, mb_y,
                         jax.random.PRNGKey(0), jnp.float32(0.05)).as_text()
    sizes = set()
    pairs = set()
    for ln in lowered.splitlines():
        if "collective_permute" not in ln:
            continue
        m = re.search(r"tensor<(\d+)xf32>", ln)
        if m:
            sizes.add(int(m.group(1)))
        for sp in re.findall(r"\[(\d+), (\d+)\]", ln):
            pairs.add((int(sp[0]), int(sp[1])))

    boundary = set(pipe.boundary_elems(mb))
    max_width = mb * max([_prod(pipe.in_shapes[0])]
                         + [_prod(s) for s in pipe.out_shapes])
    assert boundary, "test model must have stage boundaries"
    assert len(boundary) > 1, "boundaries must differ in size for this test"
    # every collective is an exact boundary width; the padded buffer never
    # crosses the wire (fwd rotation and its autodiff transpose alike)
    assert sizes == boundary, (sizes, boundary)
    assert max_width not in sizes
    # no wrap pair in any direction
    assert (S - 1, 0) not in pairs and (0, S - 1) not in pairs, pairs
    # forward pairs present (and their transposes)
    assert (0, 1) in pairs and (1, 2) in pairs, pairs


# ------------------------------------------------------------------- 1F1B

def _gn_stack_model(S):
    """GroupNorm residual stack, heterogeneous head — safe at any stage
    count (stateless norm keeps per-stage structure varied but robust)."""
    b = (SequentialBuilder("gn_stack")
         .input((3, 8, 8))
         .conv2d(8, 3, 1, 1).groupnorm(4).activation("relu"))
    for _ in range(max(S - 2, 1)):
        b = b.conv2d(8, 3, 1, 1).groupnorm(4).activation("relu")
    return b.flatten().dense(10).build()


@pytest.mark.parametrize("S_M", [(2, 4), (4, 8), (8, 8)])
def test_1f1b_matches_gpipe_and_host_driven(S_M):
    """Loss parity of the compiled 1F1B engine against BOTH the compiled
    GPipe engine and the host-driven coordinator at 2/4/8 stages
    (VERDICT r3 next-round #2)."""
    S, M = S_M
    if len(jax.devices()) < S:
        pytest.skip(f"needs {S} devices")
    mb = 2
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(5)
    x = rng.normal(size=(M * mb, 3, 8, 8)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, M * mb)]
    mb_x = jnp.asarray(x.reshape(M, mb, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, mb, 10))

    losses = {}
    for name, maker in (("gpipe", "make_train_step"),
                        ("1f1b", "make_train_step_1f1b")):
        pipe = HeteroCompiledPipeline(_gn_stack_model(S), S, M, mesh)
        opt = SGD(0.05)
        fp, fs = pipe.init(key)
        ost = opt.init(fp)
        step = getattr(pipe, maker)(softmax_cross_entropy, opt)
        _, _, _, loss, _ = step(fp, ost, fs, mb_x, mb_y,
                                jax.random.PRNGKey(9), jnp.float32(0.05))
        losses[name] = float(loss)

    coord = InProcessPipelineCoordinator(
        _gn_stack_model(S), SGD(0.05), "softmax_crossentropy",
        num_stages=S, num_microbatches=M)
    coord.deploy_stages(key)
    ref_loss, _ = coord.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(9))

    assert abs(losses["1f1b"] - losses["gpipe"]) < 1e-5, losses
    assert abs(losses["1f1b"] - ref_loss) < 1e-5, (losses, ref_loss)


def test_1f1b_full_parity_with_bn_state(hetero_setup):
    """Exact parity incl. updated params and BN running stats against the
    GPipe engine on the BN-bearing hetero model."""
    pipe_g, S, M = hetero_setup
    mesh = pipe_g.mesh
    key = jax.random.PRNGKey(3)
    rng = np.random.default_rng(0)
    mb = 4
    x = rng.normal(size=(M * mb, 3, 8, 8)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, M * mb)]
    mb_x = jnp.asarray(x.reshape(M, mb, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, mb, 5))

    out = {}
    for name, maker in (("gpipe", "make_train_step"),
                        ("1f1b", "make_train_step_1f1b")):
        pipe = HeteroCompiledPipeline(_hetero_model(), S, M, mesh)
        opt = SGD(0.05, momentum=0.9)
        fp, fs = pipe.init(key)
        ost = opt.init(fp)
        step = getattr(pipe, maker)(softmax_cross_entropy, opt)
        fp, ost, fs, loss, logits = step(fp, ost, fs, mb_x, mb_y,
                                         jax.random.PRNGKey(9),
                                         jnp.float32(0.05))
        out[name] = (float(loss), np.asarray(logits),
                     pipe.unpack_params(fp, fs))

    l_g, logits_g, (p_g, s_g) = out["gpipe"]
    l_f, logits_f, (p_f, s_f) = out["1f1b"]
    assert abs(l_g - l_f) < 1e-6
    np.testing.assert_allclose(logits_f, logits_g, atol=2e-5, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(p_f),
                    jax.tree_util.tree_leaves(p_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)
    for a, b in zip(jax.tree_util.tree_leaves(s_f),
                    jax.tree_util.tree_leaves(s_g)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


def test_1f1b_peak_memory_below_gpipe():
    """The structural claim that motivates 1F1B: peak temp memory of the
    compiled step at M=8, S=4 is measurably below GPipe's, whose autodiff
    through the schedule keeps O(M+S) tick activations live
    (VERDICT r3 next-round #2 'done' criterion)."""
    S, M, mb = 4, 8, 4
    if len(jax.devices()) < S:
        pytest.skip(f"needs {S} devices")
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    mems = {}
    for name, maker in (("gpipe", "make_train_step"),
                        ("1f1b", "make_train_step_1f1b")):
        pipe = HeteroCompiledPipeline(_gn_stack_model(S), S, M, mesh)
        opt = SGD(0.05)
        fp, fs = pipe.init(jax.random.PRNGKey(0))
        ost = opt.init(fp)
        step = getattr(pipe, maker)(softmax_cross_entropy, opt)
        mb_x = jnp.zeros((M, mb, 3, 8, 8), jnp.float32)
        mb_y = jnp.zeros((M, mb, 10), jnp.float32)
        compiled = step.lower(fp, ost, fs, mb_x, mb_y, jax.random.PRNGKey(0),
                              jnp.float32(0.05)).compile()
        ma = compiled.memory_analysis()
        if ma is None or not hasattr(ma, "temp_size_in_bytes"):
            pytest.skip("backend provides no memory analysis")
        mems[name] = int(ma.temp_size_in_bytes)
    assert mems["1f1b"] < mems["gpipe"], mems


def test_1f1b_bf16_wire_tracks_fp32(hetero_setup):
    """bf16-wire 1F1B must track the bf16-wire GPipe loss (wire-dtype
    quantization applied at the same points — review r4 #2)."""
    _, S, M = hetero_setup
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    rng = np.random.default_rng(2)
    mb = 4
    x = rng.normal(size=(M * mb, 3, 8, 8)).astype(np.float32)
    y = np.eye(5, dtype=np.float32)[rng.integers(0, 5, M * mb)]
    mb_x = jnp.asarray(x.reshape(M, mb, 3, 8, 8))
    mb_y = jnp.asarray(y.reshape(M, mb, 5))
    losses = {}
    for name, maker in (("gpipe", "make_train_step"),
                        ("1f1b", "make_train_step_1f1b")):
        pipe = HeteroCompiledPipeline(_hetero_model(), S, M, mesh,
                                      wire_dtype=jnp.bfloat16)
        opt = SGD(0.05)
        fp, fs = pipe.init(jax.random.PRNGKey(3))
        ost = opt.init(fp)
        step = getattr(pipe, maker)(softmax_cross_entropy, opt)
        _, _, _, loss, logits = step(fp, ost, fs, mb_x, mb_y,
                                     jax.random.PRNGKey(9), jnp.float32(0.05))
        # returned loss must be consistent with returned logits
        relosses = jax.vmap(softmax_cross_entropy)(jnp.asarray(logits), mb_y)
        assert abs(float(jnp.mean(relosses)) - float(loss)) < 1e-4
        losses[name] = float(loss)
    assert abs(losses["1f1b"] - losses["gpipe"]) < 0.05, losses
