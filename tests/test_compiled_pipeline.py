"""Compiled (single-jit, shard_map+ppermute) pipeline schedule tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
from dcnn_tpu.nn import Conv2DLayer, GroupNormLayer, ResidualBlock
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.parallel.compiled_pipeline import (
    SequentialStageStack, make_compiled_pipeline_forward,
    make_compiled_pipeline_train_step, shard_stacked, stack_stage_params,
)

KEY = jax.random.PRNGKey(0)
S = 4       # stages
MB = 6      # microbatches


def _mesh():
    return make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])


def _block():
    return ResidualBlock(
        layers=[Conv2DLayer(4, 3, 1, 1, name="c0"),
                GroupNormLayer(2, name="g0")],
        shortcut=[], activation="relu")


def test_compiled_forward_matches_sequential_chain():
    mesh = _mesh()
    stack = SequentialStageStack(_block(), S, (4, 8, 8))
    params = stack.init(KEY)

    mbs = jax.random.normal(jax.random.PRNGKey(1), (MB, 2, 4, 8, 8))
    fwd = make_compiled_pipeline_forward(stack.stage_fn, S, MB, mesh)
    out = fwd(shard_stacked(params, mesh), mbs)

    # reference: run each microbatch through the 4 stages sequentially
    per_stage = [jax.tree_util.tree_map(lambda x: x[i], params) for i in range(S)]
    for i in range(MB):
        h = mbs[i]
        for sp in per_stage:
            h = stack.stage_fn(sp, h)
        np.testing.assert_allclose(np.asarray(out[i]), np.asarray(h),
                                   rtol=1e-4, atol=1e-5)


def test_compiled_train_step_matches_unpipelined_grads():
    mesh = _mesh()
    stack = SequentialStageStack(_block(), S, (4, 8, 8))
    params = stack.init(KEY)
    opt = SGD(0.05)

    rng = np.random.default_rng(0)
    mb_x = jnp.asarray(rng.normal(size=(MB, 2, 4, 8, 8)).astype(np.float32))
    # fake per-microbatch "labels": flatten conv output to logits via mean —
    # use an elementwise regression-style loss on the activation itself
    mb_y = jnp.asarray(rng.normal(size=(MB, 2, 4, 8, 8)).astype(np.float32))

    def loss_fn(pred, tgt):
        return jnp.mean((pred - tgt) ** 2)

    step = make_compiled_pipeline_train_step(stack.stage_fn, loss_fn, opt, S, MB, mesh)
    p_sharded = shard_stacked(params, mesh)
    opt_state = opt.init(p_sharded)
    new_params, _, loss, outs = step(p_sharded, opt_state, mb_x, mb_y,
                                     jnp.float32(0.05))

    # unpipelined reference: same math without the schedule
    def ref_loss(p):
        per_stage = [jax.tree_util.tree_map(lambda x: x[i], p) for i in range(S)]
        losses = []
        for i in range(MB):
            h = mb_x[i]
            for sp in per_stage:
                h = stack.stage_fn(sp, h)
            losses.append(loss_fn(h, mb_y[i]))
        return jnp.mean(jnp.stack(losses))

    ref_l, ref_g = jax.value_and_grad(ref_loss)(params)
    np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
    ref_new = jax.tree_util.tree_map(lambda p, g: p - 0.05 * g, params, ref_g)
    for a, b in zip(jax.tree_util.tree_leaves(new_params),
                    jax.tree_util.tree_leaves(ref_new)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_stage_stack_rejects_shape_changing_block():
    with pytest.raises(ValueError):
        SequentialStageStack(Conv2DLayer(8, 3, 2, 1), S, (4, 8, 8))


def test_stage_stack_rejects_stateful_block():
    from dcnn_tpu.nn import BatchNormLayer
    with pytest.raises(ValueError):
        SequentialStageStack(BatchNormLayer(), S, (4, 8, 8)).init(KEY)
