"""Fail-slow (gray-failure) tolerance (ISSUE 19).

The shared :class:`SlownessDetector` contract under fake clocks, the
``FaultPlan.slow`` delay-injection twin of ``arm``, and the three
mitigation surfaces end to end:

- **elastic DP straggler eviction** — a 3-peer in-process fleet with one
  peer armed slow at ``elastic.slow_peer``: the leader convicts and
  evicts it through the generation-fenced reconfiguration, and the
  survivors' final params match the uninterrupted fixed-world run within
  the PR-8 reshard tolerance. A fleet-wide slowdown convicts nobody.
- **pipeline stage rebalance** — a 3-stage TCP pipeline with one stage
  armed slow at ``pipeline.slow_stage``: the coordinator repartitions
  layer ranges proportional to measured walls (rebalance, never evict)
  and training lands on the uninterrupted run's params.
- **router hedging + slow-replica probation** — fully fake-clock,
  sleep-free: the hedge fires after the p99-derived delay, the ledger's
  exactly-once retire dedupes the pair (the late loser resolves
  nothing), a hedged request whose primary fails is NOT re-admitted
  while the hedge is live, and a convicted replica is demoted to
  probation then auto-rejoined after the cooldown + clean probe.
- **feed-worker recycle** — a convicted slow worker (armed at
  ``feed.slow_worker``) is retired through the worker-death fallback
  with bit-identical output.
"""

import tempfile
import threading

import numpy as np
import pytest

from dcnn_tpu.resilience.faults import (
    FaultPlan, InjectedFault, clear, install, slowdown,
)
from dcnn_tpu.resilience.slowness import SlownessConfig, SlownessDetector

RTOL, ATOL = 2e-4, 2e-5  # PR-8 reshard tolerance: FP reassociation only


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


# ---------------------------------------------------------------------------
# SlownessConfig validation + env plumbing
# ---------------------------------------------------------------------------

def test_slowness_config_validation():
    with pytest.raises(ValueError, match="min_peers"):
        SlownessConfig(min_peers=1)
    with pytest.raises(ValueError, match="ratio must be > 1"):
        SlownessConfig(ratio=1.0)
    with pytest.raises(ValueError, match="exit_ratio"):
        SlownessConfig(ratio=2.0, exit_ratio=2.5)
    with pytest.raises(ValueError, match="ewma_alpha"):
        SlownessConfig(ewma_alpha=0.0)
    with pytest.raises(ValueError, match="dwell_s"):
        SlownessConfig(dwell_s=-0.1)
    with pytest.raises(ValueError, match="min_samples"):
        SlownessConfig(min_samples=0)


def test_slowness_config_from_env(monkeypatch):
    monkeypatch.setenv("DCNN_SLOW_RATIO", "3.5")
    monkeypatch.setenv("DCNN_SLOW_MIN_PEERS", "4")
    cfg = SlownessConfig.from_env(SlownessConfig(dwell_s=0.7))
    assert cfg.ratio == 3.5
    assert cfg.min_peers == 4
    assert cfg.dwell_s == 0.7          # base fields survive the overlay
    assert cfg.mad_k == 4.0            # untouched default


# ---------------------------------------------------------------------------
# detector state machine (fake clock, sleep-free)
# ---------------------------------------------------------------------------

def _det(fc, **kw):
    kw.setdefault("ewma_alpha", 1.0)   # score == last sample: exact tests
    kw.setdefault("min_samples", 1)
    kw.setdefault("dwell_s", 5.0)
    return SlownessDetector(SlownessConfig(**kw), clock=fc)


def _feed(det, walls):
    for c, w in walls.items():
        det.observe(c, w)


def test_outlier_convicts_only_after_dwell():
    fc = FakeClock()
    det = _det(fc)
    _feed(det, {"a": 1.0, "b": 1.0, "c": 1.0, "d": 10.0})
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("d", "probation")]
    assert trs[0]["median"] == 1.0
    fc.advance(4.9)                    # inside the dwell: one GC pause
    _feed(det, {"a": 1.0, "b": 1.0, "c": 1.0, "d": 10.0})
    assert det.evaluate() == []
    assert det.state("d") == "probation"
    fc.advance(0.2)                    # sustained past dwell_s
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("d", "convicted")]
    assert det.convicted() == ["d"]
    # recovery: below the exit band -> healthy again
    det.observe("d", 1.4)              # <= exit_ratio(1.5) * median(1.0)
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("d", "healthy")]


def test_exit_hysteresis_band_does_not_flap():
    """Between ``exit_ratio*median`` and the entry threshold, a component
    neither clears nor re-enters — the band gap is the flap filter, and
    the original probation stamp keeps the dwell clock honest."""
    fc = FakeClock()
    det = _det(fc)
    _feed(det, {"a": 1.0, "b": 1.0, "c": 1.0, "d": 10.0})
    det.evaluate()                     # d -> probation at t=0
    fc.advance(3.0)
    det.observe("d", 1.8)              # in the band: 1.5 < 1.8 < 2.0
    assert det.evaluate() == []        # no transition either way
    assert det.state("d") == "probation"
    fc.advance(3.0)                    # 6 s since entry: dwell elapsed
    det.observe("d", 10.0)             # outlier again
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("d", "convicted")]


def test_fleet_wide_slowdown_convicts_nobody():
    """THE hard rule: everyone slow together moves the median with them
    — no outlier, no verdict (the input got bigger, nobody gray-failed)."""
    fc = FakeClock()
    det = _det(fc)
    _feed(det, {"a": 1.0, "b": 1.0, "c": 1.1, "d": 0.9})
    assert det.evaluate() == []
    for _ in range(5):
        fc.advance(10.0)               # far past any dwell
        _feed(det, {"a": 10.0, "b": 10.0, "c": 11.0, "d": 9.0})
        assert det.evaluate() == []
    assert set(det.states().values()) == {"healthy"}


def test_below_min_peers_nobody_judged_and_probation_unflags():
    fc = FakeClock()
    det = _det(fc, min_peers=3)
    _feed(det, {"a": 1.0, "b": 100.0})
    assert det.evaluate() == []        # 2 scored < min_peers: no median
    assert det.state("b") == "healthy"
    # grow the fleet -> b becomes a judged outlier
    det.observe("c", 1.0)
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("b", "probation")]
    # shrink it again (eviction elsewhere): probation un-flags — the
    # fleet b was an outlier of no longer exists
    det.forget("c")
    trs = det.evaluate()
    assert [(t["component"], t["to"]) for t in trs] == [("b", "healthy")]


def test_min_samples_gates_scoring():
    fc = FakeClock()
    det = _det(fc, min_samples=3)
    for _ in range(2):
        _feed(det, {"a": 1.0, "b": 1.0, "c": 50.0})
    assert det.fleet_median() is None  # nobody has 3 samples yet
    assert det.evaluate() == []
    _feed(det, {"a": 1.0, "b": 1.0, "c": 50.0})
    assert det.fleet_median() == 1.0
    assert [t["to"] for t in det.evaluate()] == ["probation"]


def test_probe_ok_excludes_probed_component_and_fails_open():
    fc = FakeClock()
    det = _det(fc)
    _feed(det, {"a": 1.0, "b": 1.0, "c": 1.0})
    assert det.probe_ok("d", 1.2)      # <= exit_ratio * median
    assert not det.probe_ok("d", 2.0)
    # the probed component's own (stale, huge) score must not judge it
    det.observe("d", 50.0)
    assert det.probe_ok("d", 1.2)
    # no fleet to compare against: fail open, like the min_peers rule
    lone = _det(FakeClock())
    _feed(lone, {"a": 1.0})
    assert lone.probe_ok("a", 100.0)


def test_observe_ignores_negative_walls_and_snapshot_shape():
    fc = FakeClock()
    det = _det(fc)
    det.observe("a", -1.0)             # clock-skew artifact
    assert det.fleet_median() is None
    _feed(det, {"a": 2.0, "b": 2.0, "c": 4.0})
    snap = det.snapshot()
    assert snap["c"]["ratio_to_median"] == pytest.approx(2.0)
    assert snap["a"]["state"] == "healthy"
    assert snap["a"]["samples"] == 1
    det.forget("a")
    assert "a" not in det.snapshot()


# ---------------------------------------------------------------------------
# FaultPlan.slow — the delay-injection twin of arm()
# ---------------------------------------------------------------------------

def test_faultplan_slow_validation():
    with pytest.raises(ValueError, match="exactly one"):
        FaultPlan().slow("p")
    with pytest.raises(ValueError, match="exactly one"):
        FaultPlan().slow("p", factor=2.0, delay_s=1.0)
    with pytest.raises(ValueError, match="factor"):
        FaultPlan().slow("p", factor=0.5)
    with pytest.raises(ValueError, match="delay_s"):
        FaultPlan().slow("p", delay_s=-1.0)


def test_faultplan_slow_factor_and_delay():
    plan = FaultPlan().slow("p", factor=3.0)
    assert plan.slowdown("p", 2.0) == pytest.approx(4.0)  # base*(f-1)
    plan.unslow("p")
    assert plan.slowdown("p", 2.0) == 0.0
    plan.slow("p", delay_s=0.5)
    assert plan.slowdown("p", 100.0) == pytest.approx(0.5)  # fixed stall
    assert plan.slowdown("other", 1.0) == 0.0


def test_faultplan_slow_at_times_window():
    plan = FaultPlan().slow("p", delay_s=1.0, at=1, times=2)
    got = [plan.slowdown("p") for _ in range(4)]
    assert got == [0.0, 1.0, 1.0, 0.0]  # fires at invocations 1 and 2
    assert plan.slow_count("p") == 4    # every query counted


def test_module_global_slowdown_hook():
    plan = FaultPlan().slow("p", delay_s=0.25)
    assert slowdown("p", 1.0) == 0.0    # nothing installed
    install(plan)
    try:
        assert slowdown("p", 1.0) == pytest.approx(0.25)
    finally:
        clear()
    assert slowdown("p", 1.0) == 0.0


# ---------------------------------------------------------------------------
# elastic DP: straggler eviction (in-process fleet over loopback)
# ---------------------------------------------------------------------------

_rng = np.random.default_rng(0)
EX = _rng.normal(size=(48, 16)).astype(np.float32)
BATCH = 12


def _e_model():
    from dcnn_tpu.nn import SequentialBuilder
    return (SequentialBuilder("slow_elastic").input((16,))
            .dense(32).activation("relu").dense(4).build())


def _e_loader():
    from dcnn_tpu.data.loader import ArrayDataLoader, one_hot
    ey = one_hot(np.random.default_rng(1).integers(0, 4, 48), 4)
    return ArrayDataLoader(EX, ey, batch_size=BATCH, seed=7)


def _run_elastic(n, *, epochs=4, faults=None, ckpt_dir=None, slow=False,
                 join_ranks=None):
    """N in-process elastic peers over loopback; joins ``join_ranks``
    (default all) and returns (controllers, results, threads)."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel import comm
    from dcnn_tpu.parallel.elastic import ElasticController, PeerSpec

    faults = faults or {}
    socks = [comm.listen(0, host="127.0.0.1") for _ in range(n)]
    peers = [PeerSpec(i, "127.0.0.1", s.getsockname()[1])
             for i, s in enumerate(socks)]
    ctls, results = {}, {}

    def runner(i):
        cfg = TrainingConfig(
            epochs=epochs, learning_rate=0.05, seed=3, snapshot_dir=None,
            elastic=True, elastic_microbatches=6, elastic_timeout_s=20.0,
            elastic_heartbeat_s=0.0, elastic_ckpt_steps=2,
            checkpoint_dir=ckpt_dir, slow_detect=slow, slow_dwell_s=0.2,
            slow_min_samples=2)
        ctl = ElasticController(
            _e_model(), SGD(0.05), "softmax_crossentropy", _e_loader(),
            config=cfg, rank=i, peers=peers, listen_sock=socks[i],
            fault_plan=faults.get(i))
        ctls[i] = ctl
        try:
            results[i] = ctl.fit(epochs=epochs)
        except Exception as e:  # surfaced to the asserting test
            results[i] = e

    threads = [threading.Thread(target=runner, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for i in (join_ranks if join_ranks is not None else range(n)):
        threads[i].join(timeout=180)
        assert not threads[i].is_alive(), f"elastic rank {i} hung"
    return ctls, results, threads


def _leaves(ts):
    import jax
    return jax.tree_util.tree_leaves(jax.device_get(ts.params))


@pytest.fixture(scope="module")
def elastic_baseline3():
    """Never-interrupted fixed-world run: 3 peers, K=6, detector off."""
    _ctls, results, _ = _run_elastic(3)
    return _leaves(results[0])


def test_slow_peer_convicted_and_evicted_params_match(elastic_baseline3):
    """ACCEPTANCE: rank 2 armed ``elastic.slow_peer`` (a fixed 50 ms
    stall per step — a thermally-throttled host). The leader convicts it
    as a sustained relative outlier, evicts it through the normal
    generation-fenced reconfiguration, and the 2 survivors finish with
    params matching the uninterrupted 3-peer run."""
    victim_plan = FaultPlan().slow("elastic.slow_peer", delay_s=0.05)
    with tempfile.TemporaryDirectory() as d:
        ctls, results, _ = _run_elastic(
            3, faults={2: victim_plan}, ckpt_dir=d, slow=True,
            join_ranks=[0, 1])  # the evictee may linger on its timeout
    leader = ctls[0]
    for r in (0, 1):
        assert not isinstance(results[r], BaseException), results[r]
    # the injection really ran on the victim's step loop
    assert victim_plan.slow_count("elastic.slow_peer") > 0
    # conviction: exactly one straggler eviction, world 3 -> 2
    assert leader.stats["stragglers_evicted"] == 1
    assert leader.world == 2 and leader.gen >= 1
    assert sorted(leader.survivors) == [0, 1]
    # the global batch stayed exact across the reshard
    assert {e["global_rows"] for e in leader.step_log} == {BATCH}
    # survivors bit-identical to each other, close to the baseline
    for a, b in zip(_leaves(results[0]), _leaves(results[1])):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(elastic_baseline3, _leaves(results[0])):
        np.testing.assert_allclose(a, b, rtol=RTOL, atol=ATOL)


def test_fleet_wide_slowdown_evicts_nobody_elastic():
    """Every peer armed with the same slowdown: the median moves with
    the fleet, nobody is an outlier, training completes at world 3 with
    zero evictions — the detector's hard rule, end to end."""
    plans = {i: FaultPlan().slow("elastic.slow_peer", factor=2.5)
             for i in range(3)}
    ctls, results, _ = _run_elastic(3, epochs=2, faults=plans, slow=True)
    for i in range(3):
        assert not isinstance(results[i], BaseException), results[i]
        assert ctls[i].stats["stragglers_evicted"] == 0
        assert ctls[i].world == 3 and ctls[i].gen == 0


# ---------------------------------------------------------------------------
# pipeline: measured repartition (rebalance, never evict)
# ---------------------------------------------------------------------------

def _p_model():
    from dcnn_tpu.nn import SequentialBuilder
    b = SequentialBuilder("slow_pipe").input((16,))
    for _ in range(6):
        b = b.dense(16)
    return b.dense(4).build()


def test_measured_partitioner_sheds_layers_off_slow_stage():
    from dcnn_tpu.parallel.partitioner import (
        FlopBalancedPartitioner, MeasuredPartitioner, NaivePartitioner)

    model = _p_model()                  # 7 layers
    naive = NaivePartitioner().get_partitions(model, 3)
    part = MeasuredPartitioner(naive, [1.0, 30.0, 1.0])
    new = part.get_partitions(model, 3)
    assert new != naive
    # the slow stage sheds layers in proportion to its measured wall
    old_mid = naive[1][1] - naive[1][0]
    new_mid = new[1][1] - new[1][0]
    assert new_mid < old_mid
    # ranges still tile the model exactly
    assert new[0][0] == 0 and new[-1][1] == len(model.layers)
    for (_, e), (s, _) in zip(new, new[1:]):
        assert e == s
    # no measurements -> degrades to the FLOP-balanced walk
    flat = MeasuredPartitioner(naive, [0.0, 0.0, 0.0])
    assert flat.get_partitions(model, 3) == \
        FlopBalancedPartitioner().get_partitions(model, 3)
    with pytest.raises(ValueError, match="partitions vs"):
        MeasuredPartitioner(naive, [1.0, 2.0])


def _pipe_batches(n=8, rows=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.normal(size=(rows, 16)).astype(np.float32),
             np.eye(4, dtype=np.float32)[rng.integers(0, 4, rows)])
            for _ in range(n)]


def _pipe_fleet(n=3, plans=None):
    from dcnn_tpu.parallel import StageWorker, comm
    from dcnn_tpu.resilience.faults import InjectedCrash

    socks = [comm.listen(0, host="127.0.0.1") for _ in range(n)]
    addrs = [f"127.0.0.1:{s.getsockname()[1]}" for s in socks]
    plans = plans or [FaultPlan() for _ in range(n)]
    workers = [StageWorker(0, listen_sock=s, fault_plan=p)
               for s, p in zip(socks, plans)]

    def serve(w):
        try:
            w.serve()
        except InjectedCrash:
            pass

    threads = [threading.Thread(target=serve, args=(w,), daemon=True)
               for w in workers]
    for t in threads:
        t.start()

    def close():
        for w in workers:
            w.stop()
        for t in threads:
            t.join(timeout=10)

    return addrs, close


def _pipe_run(addrs, *, batches, rebalance=False, **kw):
    import jax
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel import DistributedPipelineCoordinator

    co = DistributedPipelineCoordinator(
        _p_model(), SGD(0.05, momentum=0.9), "softmax_crossentropy",
        workers=addrs, num_microbatches=2, timeout=60.0, **kw)
    co.deploy_stages(jax.random.PRNGKey(0))
    for b, (x, y) in enumerate(batches):
        co.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(b))
        if rebalance:
            co.maybe_rebalance()
    params, state = co.gathered_params()
    co.shutdown()
    return co, jax.device_get(params)


def test_slow_stage_triggers_measured_rebalance(tmp_path):
    """ACCEPTANCE: stage 1 armed ``pipeline.slow_stage`` (50 ms per
    fwd/bwd job — big enough to dominate the warm-up walls the
    cumulative load averages carry). The between-batch sweep convicts it as a sustained
    outlier and ships a measured repartition through the recovery
    machinery — exact momentum, zero rewind: final params match the
    uninterrupted run, zero batches lost, and the evidence (imbalance
    gauge, counter, flight bundle) is all recorded."""
    import jax
    from dcnn_tpu.obs.flight import FlightRecorder
    from dcnn_tpu.obs.registry import MetricsRegistry

    batches = _pipe_batches(8)
    # reference: same batches, no fault, no rebalance sweeps
    addrs, close = _pipe_fleet(3)
    try:
        _co, ref_params = _pipe_run(addrs, batches=batches,
                                    track_load=True)
    finally:
        close()

    plans = [FaultPlan() for _ in range(3)]
    plans[1].slow("pipeline.slow_stage", delay_s=0.05)
    reg = MetricsRegistry()
    flight = FlightRecorder(str(tmp_path / "flight"))
    addrs, close = _pipe_fleet(3, plans)
    try:
        co, params = _pipe_run(
            addrs, batches=batches, rebalance=True, track_load=True,
            registry=reg, flight=flight,
            slow_config=SlownessConfig(min_peers=2, min_samples=2,
                                       dwell_s=0.05))
    finally:
        close()

    assert plans[1].slow_count("pipeline.slow_stage") > 0
    assert co.stats["rebalances"] >= 1
    assert co.stats["batches_lost"] == 0
    snap = reg.snapshot()
    assert snap["pipeline_rebalances_total"] == co.stats["rebalances"]
    assert snap["pipeline_stage_imbalance"] > 1.5  # the outlier was real
    assert any(b["trigger"] == "pipeline_rebalance"
               for b in flight.bundles())
    # rebalance preserved the training trajectory exactly
    for a, b in zip(jax.tree_util.tree_leaves(ref_params),
                    jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=RTOL, atol=ATOL)


# ---------------------------------------------------------------------------
# router: hedged requests + slow-replica probation (fake clock)
# ---------------------------------------------------------------------------

class FakeEngine:
    """Batcher-compatible engine without jax: logits = x + 1."""

    input_shape = (4,)
    max_batch = 8
    bucket_sizes = [1, 2, 4, 8]
    version = 1
    batch_invariant = True

    def bucket_for(self, n):
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise ValueError(n)

    def pad_to_bucket(self, x):
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        b = self.bucket_for(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n, 4), np.float32)])
        return x, n

    def run_padded(self, x):
        return np.asarray(x, np.float32) + 1.0


def _router_fleet(n=3, **kw):
    from dcnn_tpu.serve import LocalReplica, Router

    fc = FakeClock()
    plans, reps = {}, []
    for i in range(n):
        plans[f"r{i}"] = FaultPlan()
        reps.append(LocalReplica(
            FakeEngine(), name=f"r{i}", queue_capacity=64, clock=fc,
            fault_plan=plans[f"r{i}"], start=False))
    router = Router(reps, clock=fc, sleep=lambda s: fc.advance(s), **kw)
    return router, reps, plans, fc


def _pump(reps, rounds=4):
    for _ in range(rounds):
        for r in reps:
            while r.step():
                pass


def _prime_p99(router, reps, fc, n=20, lat=0.01):
    """Feed the windowed p99 so the hedge delay resolves (floored at
    hedge_min_s here: 3 * 10 ms < 50 ms)."""
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(n)]
    fc.advance(lat)
    _pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)


def _one_outstanding(router, exclude=()):
    return [name for name, st in router.replica_stats().items()
            if st["outstanding"] > 0 and name not in exclude]


def test_serve_slow_replica_point_is_on_the_dispatch_path():
    """The ``serve.slow_replica`` delay hook wraps the engine dispatch —
    armed with a zero stall it must still be queried per batch."""
    router, reps, plans, _ = _router_fleet(1)
    plans["r0"].slow("serve.slow_replica", delay_s=0.0)
    f = router.submit(np.zeros(4, np.float32))
    _pump(reps)
    assert f.exception(timeout=0) is None
    assert plans["r0"].slow_count("serve.slow_replica") >= 1


def test_hedge_fires_after_delay_and_loser_resolves_nothing():
    """ACCEPTANCE (hedging dedupe): the duplicate launches only past the
    p99-derived delay; the first settle wins the ledger exactly once; the
    late loser resolves nothing — no silent drop AND no double-resolve."""
    router, reps, _, fc = _router_fleet(3, hedge=True, hedge_min_s=0.05)
    _prime_p99(router, reps, fc)
    done_before = sum(
        st["completed"] for st in router.replica_stats().values())

    f = router.submit(np.zeros(4, np.float32))
    primary = _one_outstanding(router)
    assert len(primary) == 1
    fc.advance(0.04)
    assert router.check_hedges() == 0   # younger than the delay
    fc.advance(0.02)
    assert router.check_hedges() == 1   # one duplicate launched
    assert router.check_hedges() == 0   # claimed: never double-hedged
    hedge = _one_outstanding(router, exclude=primary)
    assert len(hedge) == 1 and hedge != primary
    by_name = {r.name: r for r in reps}
    # the hedge settles first and wins ...
    while by_name[hedge[0]].step():
        pass
    np.testing.assert_array_equal(f.result(timeout=0),
                                  np.ones(4, np.float32))
    assert router.outstanding() == 0    # retired exactly once
    # ... the primary's late settle resolves nothing
    while by_name[primary[0]].step():
        pass
    assert router.outstanding() == 0
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_hedges_total"] == 1
    assert snap["serve_router_hedge_wins_total"] == 1
    # router-level completion counted once despite two replica settles
    done = sum(v for k, v in snap.items()
               if k.startswith("serve_router_completed_"))
    assert done == 21
    assert sum(st["completed"]
               for st in router.replica_stats().values()) == done_before + 2


def test_hedged_request_not_readmitted_while_hedge_inflight():
    """A hedged pair whose primary FAILS must not re-admit: the live
    hedge owns settlement (re-admitting would triple-dispatch)."""
    router, reps, plans, fc = _router_fleet(3, hedge=True, hedge_min_s=0.05)
    _prime_p99(router, reps, fc)
    f = router.submit(np.zeros(4, np.float32))
    primary = _one_outstanding(router)[0]
    fc.advance(0.06)
    assert router.check_hedges() == 1
    plans[primary].arm("serve.replica_infer", exc=InjectedFault, times=1)
    by_name = {r.name: r for r in reps}
    while by_name[primary].step():   # primary fails first
        pass
    assert not f.done()              # the hedge still owns the request
    assert router.metrics.registry.snapshot().get(
        "serve_router_readmits_total", 0) == 0
    _pump(reps)                      # the hedge settles it
    assert f.exception(timeout=0) is None
    assert router.outstanding() == 0


def test_hedge_cancellation_safe():
    router, reps, _, fc = _router_fleet(3, hedge=True, hedge_min_s=0.05)
    _prime_p99(router, reps, fc)
    f = router.submit(np.zeros(4, np.float32))
    fc.advance(0.06)
    assert router.check_hedges() == 1
    assert f.cancel()
    _pump(reps)                      # both settles find a resolved future
    assert router.outstanding() == 0  # ledger swept, nothing leaked


def test_hedge_with_no_spare_replica_is_opportunistic():
    """A hedge that cannot place (single replica already holds the
    request) is dropped silently — never extra failure."""
    router, reps, _, fc = _router_fleet(1, hedge=True, hedge_min_s=0.05)
    _prime_p99(router, reps, fc, n=20)
    f = router.submit(np.zeros(4, np.float32))
    fc.advance(0.06)
    assert router.check_hedges() == 0
    _pump(reps)
    assert f.exception(timeout=0) is None
    assert router.metrics.registry.snapshot()[
        "serve_router_hedges_total"] == 0


def test_hedging_off_until_p99_exists():
    router, _, _, fc = _router_fleet(2, hedge=True, hedge_min_s=0.05)
    router.submit(np.zeros(4, np.float32))
    fc.advance(10.0)
    assert router.check_hedges() == 0   # no p99 yet: hedging disarmed


def _slow_round(router, reps, fc, slow="r0", fast_lat=0.01, slow_lat=1.0):
    """One traffic round: every replica serves one request; ``slow``
    answers after ``slow_lat`` on the shared fake clock."""
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(len(reps))]
    fc.advance(fast_lat)
    for r in reps:
        if r.name != slow:
            while r.step():
                pass
    fc.advance(slow_lat - fast_lat)
    for r in reps:
        if r.name == slow:
            while r.step():
                pass
    assert all(f.exception(timeout=0) is None for f in futs)


def test_slow_replica_probation_and_auto_rejoin():
    """ACCEPTANCE (probation round-trip): a convicted latency outlier is
    demoted (hard-sorted last in routing, still up), held for the
    cooldown, then auto-rejoined on a clean probe with its score
    forgotten — all on the fake clock, sleep-free."""
    router, reps, _, fc = _router_fleet(
        3, slow_detect=True, probation_cooldown_s=5.0,
        slow_config=SlownessConfig(min_peers=3, min_samples=2,
                                   dwell_s=0.5))
    for _ in range(3):
        _slow_round(router, reps, fc)
        router.check_probation()
    stats = router.replica_stats()
    assert stats["r0"]["probation"] is True
    assert stats["r0"]["state"] == "up"      # demoted, not ejected
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_probations_total"] == 1
    assert snap["serve_router_probation_replicas"] == 1
    # routing avoids the probation replica entirely: everything resolves
    # with r0 never pumped
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(4)]
    _pump([r for r in reps if r.name != "r0"])
    assert all(f.exception(timeout=0) is None for f in futs)
    assert router.replica_stats()["r0"]["outstanding"] == 0
    # held while the cooldown runs ...
    assert router.check_probation() == ["r0"]
    # ... released after it, on a clean health probe, score forgotten
    fc.advance(6.0)
    assert router.check_probation() == []
    stats = router.replica_stats()
    assert stats["r0"]["probation"] is False
    snap = router.metrics.registry.snapshot()
    assert snap["serve_router_probation_rejoins_total"] == 1
    assert snap["serve_router_probation_replicas"] == 0
    assert router.slowness.state("r0") == "healthy"
    # fresh traffic re-judges from scratch: r0 serves again
    futs = [router.submit(np.zeros(4, np.float32)) for _ in range(3)]
    _pump(reps)
    assert all(f.exception(timeout=0) is None for f in futs)


def test_probation_sweep_rides_check_replicas():
    router, reps, _, fc = _router_fleet(
        3, slow_detect=True, probation_cooldown_s=50.0,
        slow_config=SlownessConfig(min_peers=3, min_samples=2,
                                   dwell_s=0.5))
    for _ in range(3):
        _slow_round(router, reps, fc)
        router.check_replicas()
    report = router.check_replicas()
    assert report["r0"] == "up (probation)"


# ---------------------------------------------------------------------------
# feed pool: slow-worker recycle through the worker-death fallback
# ---------------------------------------------------------------------------

def _feed_data(n=96):
    rng = np.random.default_rng(3)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=n).astype(np.int32)
    return x, y


def test_feed_slow_worker_point_inflates_walls_bit_identically():
    """``feed.slow_worker`` stretches the reported prep wall INSIDE the
    worker (a genuinely slow worker, not a lying fast one) and never
    touches the output bytes."""
    from dcnn_tpu.data.workers import FeedWorkerPool, serial_shards

    x, y = _feed_data()
    sels = [np.arange(i * 12, (i + 1) * 12) for i in range(4)]
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, seed=5, epoch=1)]
    plan = FaultPlan().slow("feed.slow_worker", delay_s=0.004)
    install(plan)
    try:
        pool = FeedWorkerPool(x, y, 12, num_workers=2, backend="thread",
                              seed=5, poll_s=0.02)
        got, walls = [], []
        for ps in pool.shards(iter(sels), epoch=1):
            got.append((ps.x.copy(), ps.y.copy()))
            walls.append(ps.stats["prep_s"])
            ps.release()
        pool.close()
    finally:
        clear()
    assert plan.slow_count("feed.slow_worker") >= 1
    assert max(walls) >= 0.004          # the stall is in the report
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


def test_convicted_slow_worker_recycled_bit_identically():
    """A convicted worker is retired through the worker-death fallback:
    it refuses its next claim and exits, its shard is produced inline,
    the counter records it, and the epoch's bytes are untouched (shard
    RNG never involves the worker id)."""
    from dcnn_tpu.data.workers import FeedWorkerPool, serial_shards
    from dcnn_tpu.obs.registry import MetricsRegistry

    x, y = _feed_data()
    sels = [np.arange(i * 12, (i + 1) * 12) for i in range(6)]
    reg = MetricsRegistry()
    pool = FeedWorkerPool(
        x, y, 12, num_workers=3, backend="thread", seed=5, poll_s=0.02,
        registry=reg, slow_detect=True,
        slow_config=SlownessConfig(min_peers=2, min_samples=2,
                                   dwell_s=0.0))
    try:
        # drive the recycler exactly as _pump does, with synthetic walls:
        # w2 is a sustained 20x outlier, w0/w1 the healthy fleet
        for _ in range(3):
            pool._note_worker_wall(0, 0.001)
            pool._note_worker_wall(1, 0.001)
            pool._note_worker_wall(2, 0.02)
        assert 2 in pool._retired
        assert reg.snapshot()["feed_worker_recycled_total"] == 1
        # the retired worker's score no longer shifts the fleet median
        assert "w2" not in pool._slowness.snapshot()
        # the epoch still lands, bit-identical to the serial reference
        ser = [(a.copy(), b.copy()) for a, b, _ in
               serial_shards(x, y, sels, seed=5, epoch=2)]
        got, producers = [], []
        for ps in pool.shards(iter(sels), epoch=2):
            got.append((ps.x.copy(), ps.y.copy()))
            producers.append(ps.stats.get("worker"))
            ps.release()
        for (sx, sy), (gx, gy) in zip(ser, got):
            np.testing.assert_array_equal(sx, gx)
            np.testing.assert_array_equal(sy, gy)
        # the retired worker never produces again: any task it claims is
        # refused and rescued inline (it may idle-block on an empty queue
        # rather than exit, so assert on output, not thread liveness)
        assert producers and 2 not in producers
    finally:
        pool.close()


def test_last_producer_is_never_recycled():
    from dcnn_tpu.data.workers import FeedWorkerPool
    from dcnn_tpu.obs.registry import MetricsRegistry

    x, y = _feed_data(24)
    reg = MetricsRegistry()
    pool = FeedWorkerPool(x, y, 12, num_workers=1, backend="thread",
                          seed=5, poll_s=0.02, registry=reg,
                          slow_detect=True)
    try:
        pool._recycle_worker(0)          # even a direct conviction
        assert pool._retired == set()
        assert reg.snapshot()["feed_worker_recycled_total"] == 0
        assert pool.alive_workers() == 1
    finally:
        pool.close()


# ---------------------------------------------------------------------------
# shipped gray-failure alert pack
# ---------------------------------------------------------------------------

def test_gray_failure_alert_rules_shape_and_fire():
    from dcnn_tpu.obs.registry import MetricsRegistry
    from dcnn_tpu.obs.rules import RuleEngine, gray_failure_alert_rules
    from dcnn_tpu.obs.tsdb import TimeSeriesStore

    rules = gray_failure_alert_rules()
    assert [r.name for r in rules] == [
        "gray_straggler_convicted", "gray_stage_imbalance_sustained",
        "gray_hedge_rate_high", "gray_replica_probation"]
    by_name = {r.name: r for r in rules}
    assert by_name["gray_straggler_convicted"].severity == "page"
    assert by_name["gray_straggler_convicted"].for_s == 0.0
    assert by_name["gray_replica_probation"].fn == "min_over_time"

    # a conviction pages on the very next evaluation (for_s=0)
    fc = FakeClock()
    store = TimeSeriesStore(clock=fc)
    eng = RuleEngine(store, registry=MetricsRegistry(clock=fc), clock=fc)
    for r in rules:
        eng.add_alert(r)
    for v in (0.0, 0.0, 1.0):
        fc.advance(10.0)
        store.add("elastic_stragglers_evicted_total", v)
    trs = eng.evaluate()
    fired = [t for t in trs if t["to"] == "firing"]
    assert [t["rule"] for t in fired] == ["gray_straggler_convicted"]
