"""Chunked multi-stream H2D transfer engine tests (data/transfer.py).

Contracts: chunking math handles ragged tails; chunked shipment is
BIT-IDENTICAL to the monolithic ``device_put`` path (both raw arrays and
full train epochs); a failure inside any chunk-pool task propagates to the
caller; the per-shipment stats demonstrate real transfer concurrency; the
engine drops into ``PrefetchLoader``/``DeviceDataset``/``make_shard_step``
without changing a single value.
"""

import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.data import (
    PrefetchLoader, ArrayDataLoader, StreamingDeviceDataset, TransferEngine,
    chunk_bounds, make_shard_step, max_inflight, train_streaming_epoch,
)
from dcnn_tpu.data import transfer as transfer_mod
from dcnn_tpu.nn.builder import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train.trainer import create_train_state


# ------------------------------------------------------------ chunking math

def test_chunk_bounds_exact_division():
    assert chunk_bounds(12, 4) == [(0, 3), (3, 6), (6, 9), (9, 12)]


def test_chunk_bounds_ragged_tail_spread():
    # remainder spread over the LEADING chunks, sizes differ by at most 1
    b = chunk_bounds(10, 4)
    assert b == [(0, 3), (3, 6), (6, 8), (8, 10)]
    sizes = [hi - lo for lo, hi in b]
    assert max(sizes) - min(sizes) <= 1
    assert b[0][0] == 0 and b[-1][1] == 10
    assert all(b[i][1] == b[i + 1][0] for i in range(len(b) - 1))


def test_chunk_bounds_more_chunks_than_rows():
    assert chunk_bounds(3, 8) == [(0, 1), (1, 2), (2, 3)]
    assert chunk_bounds(0, 4) == []


def test_chunk_bounds_prime_cases():
    for n, c in [(17, 4), (31, 7), (1, 1), (2, 3), (97, 10)]:
        b = chunk_bounds(n, c)
        assert sum(hi - lo for lo, hi in b) == n
        assert all(hi > lo for lo, hi in b)
        sizes = [hi - lo for lo, hi in b]
        assert max(sizes) - min(sizes) <= 1


def test_chunk_bounds_validation():
    with pytest.raises(ValueError, match="num_chunks"):
        chunk_bounds(4, 0)
    with pytest.raises(ValueError, match="negative"):
        chunk_bounds(-1, 2)


def test_max_inflight_interval_math():
    spans = [{"put_start_t": 0.0, "put_end_t": 1.0},
             {"put_start_t": 0.5, "put_end_t": 1.5},
             {"put_start_t": 0.9, "put_end_t": 2.0},
             {"put_start_t": 3.0, "put_end_t": 4.0}]
    assert max_inflight(spans) == 3
    assert max_inflight([]) == 0


# ------------------------------------------------- chunked == monolithic

def _host_blob(n=40, shape=(6, 6, 2), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, *shape), dtype=np.uint8)
    y = rng.integers(0, 4, size=n).astype(np.int32)
    return x, y


def test_put_array_bit_identical_to_device_put():
    x, _ = _host_blob(n=23)
    with TransferEngine(num_chunks=4, num_threads=2,
                        reassemble="concat") as eng:
        dx = eng.put_array(x)
    np.testing.assert_array_equal(np.asarray(dx), x)
    assert np.asarray(dx).dtype == x.dtype


def test_put_shard_selection_matches_fancy_index():
    x, y = _host_blob(n=50, seed=1)
    sel = np.sort(np.random.default_rng(2).choice(50, size=24,
                                                  replace=False)).astype(
        np.int64)
    for chunks, threads, mode in [(1, 1, "concat"), (3, 2, "concat"),
                                  (5, 3, "chunks")]:
        with TransferEngine(num_chunks=chunks, num_threads=threads,
                            reassemble=mode) as eng:
            dx, dy, stats = eng.put_shard(x, y, sel)
        got = (np.concatenate([np.asarray(c) for c in dx])
               if isinstance(dx, tuple) else np.asarray(dx))
        np.testing.assert_array_equal(got, x[sel])
        np.testing.assert_array_equal(np.asarray(dy), y[sel])
        assert len(stats["chunks"]) == min(chunks, len(sel))
        assert stats["bytes"] == x[sel].nbytes


def test_put_shard_without_selection_ships_whole_array():
    x, y = _host_blob(n=17, seed=3)
    with TransferEngine(num_chunks=4, num_threads=2,
                        reassemble="chunks") as eng:
        dx, dy, stats = eng.put_shard(x, y)
    np.testing.assert_array_equal(np.concatenate([np.asarray(c) for c in dx]),
                                  x)
    np.testing.assert_array_equal(np.asarray(dy), y)
    # ragged: 17 rows over 4 chunks -> 5,4,4,4
    assert [c["rows"] for c in stats["chunks"]] == [5, 4, 4, 4]


def test_put_array_empty_input_matches_device_put():
    # a zero-row array (e.g. an empty filtered tail) must come back as a
    # well-formed empty device array, like a bare device_put would
    empty = np.empty((0, 5, 2), np.uint8)
    with TransferEngine(num_chunks=4, num_threads=2,
                        reassemble="concat") as eng:
        d = eng.put_array(empty)
        dx, dy, stats = eng.put_shard(empty, np.empty(0, np.int32))
    assert np.asarray(d).shape == (0, 5, 2)
    assert np.asarray(dx).shape == (0, 5, 2)
    assert np.asarray(dy).shape == (0,)
    assert stats["bytes"] == 0


def test_engine_validation_and_close():
    with pytest.raises(ValueError, match="num_chunks"):
        TransferEngine(num_chunks=0)
    with pytest.raises(ValueError, match="num_threads"):
        TransferEngine(num_threads=0)
    with pytest.raises(ValueError, match="reassemble"):
        TransferEngine(reassemble="weird")
    eng = TransferEngine()
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.put_array(np.zeros((4, 2), np.uint8))


# ------------------------------------------------------ error propagation

def test_chunk_pool_error_propagates_out_of_range_index():
    x, y = _host_blob(n=10)
    sel = np.array([0, 1, 2, 99], np.int64)  # 99 lands in the LAST chunk
    with TransferEngine(num_chunks=4, num_threads=2) as eng:
        with pytest.raises(IndexError):
            eng.put_shard(x, y, sel)


def test_chunk_pool_error_propagates_from_gather(monkeypatch):
    """A failure inside a pool task (here: the gather of chunk 2) must
    re-raise at the put_shard call after the other chunks settle — never a
    silent partial shard."""
    calls = {"n": 0}
    real = transfer_mod.native.gather_rows

    def flaky(src, idx):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("gather blew up")
        return real(src, idx)

    monkeypatch.setattr(transfer_mod.native, "gather_rows", flaky)
    x, y = _host_blob(n=40, seed=4)
    sel = np.arange(40, dtype=np.int64)
    with TransferEngine(num_chunks=4, num_threads=2) as eng:
        with pytest.raises(RuntimeError, match="gather blew up"):
            eng.put_shard(x, y, sel)


def test_streaming_epoch_propagates_chunk_pool_error(monkeypatch):
    """Producer-error propagation end-to-end: a chunk-pool failure inside
    the engine surfaces as the consumer's exception, promptly (no parked
    q.get, no leaked producer thread)."""
    x, y = _host_blob(n=70, shape=(8, 8, 1), seed=5)
    model = (SequentialBuilder(name="xfer_err", data_format="NHWC")
             .input((8, 8, 1)).flatten().dense(4).build())
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)

    def broken(src, idx):
        raise RuntimeError("wire dropped")

    monkeypatch.setattr(transfer_mod.native, "gather_rows", broken)
    t0 = time.perf_counter()
    with pytest.raises(RuntimeError, match="wire dropped"):
        train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(1), 0.05)
    assert time.perf_counter() - t0 < 30.0


# ------------------------------------------------------ concurrency proof

def test_transfers_overlap_at_least_two_in_flight(monkeypatch):
    """With a 2-thread pool and a put that takes real time, two chunk
    transfers must be in flight simultaneously — the pipelining the engine
    exists for. Evidence from both the live counter and the recorded
    spans."""
    real_put = jax.device_put

    def slow_put(a, *args, **kwargs):
        time.sleep(0.05)
        return real_put(a, *args, **kwargs)

    monkeypatch.setattr(transfer_mod.jax, "device_put", slow_put)
    x, y = _host_blob(n=64, seed=6)
    with TransferEngine(num_chunks=4, num_threads=2) as eng:
        _, _, stats = eng.put_shard(x, y, np.arange(64, dtype=np.int64))
    assert stats["inflight_max"] >= 2
    assert max_inflight(stats["chunks"]) >= 2
    assert stats["h2d_gbps"] is not None and stats["h2d_gbps"] > 0
    # the union wall must be shorter than the serial sum (overlap is real)
    assert stats["put_s"] < sum(c["put_s"] for c in stats["chunks"])


# -------------------------------------- end-to-end numerics (bit identity)

def _stream_model(hw=8):
    return (SequentialBuilder(name="xfer_cnn", data_format="NHWC")
            .input((hw, hw, 1))
            .conv2d(8, 3, padding=1).batchnorm().activation("relu")
            .flatten().dense(4)
            .build())


def _stream_blobs(n, hw=8, n_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n)
    base = (y[:, None, None, None] * 50 + 20).astype(np.float32)
    x = np.clip(base + rng.normal(0, 10, size=(n, hw, hw, 1)), 0, 255)
    return x.astype(np.uint8), y.astype(np.int64)


def _run_epoch(engine):
    x, y = _stream_blobs(n=70, seed=7)
    model = _stream_model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4,
                                seed=123)
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    tl = []
    ts, loss = train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(9),
                                     0.05, timeline=tl, engine=engine)
    return ts, loss, tl


def test_chunked_epoch_bit_identical_to_monolithic():
    """The acceptance gate: the chunked multi-stream feed must produce
    BIT-IDENTICAL train state and loss to the monolithic one-device_put
    path (num_chunks=1 + concat == the r5 feed exactly). Chunking is pure
    data movement, so even float train math sees identical inputs in
    identical order."""
    with TransferEngine(num_chunks=1, num_threads=1,
                        reassemble="concat") as mono:
        ts_m, loss_m, _ = _run_epoch(mono)
    with TransferEngine(num_chunks=4, num_threads=2,
                        reassemble="chunks") as chunked:
        ts_c, loss_c, tl = _run_epoch(chunked)
    assert float(loss_m) == float(loss_c)
    for a, b in zip(jax.tree_util.tree_leaves(ts_m.params),
                    jax.tree_util.tree_leaves(ts_c.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(ts_m.opt_state),
                    jax.tree_util.tree_leaves(ts_c.opt_state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the concat reassembly mode matches too
    with TransferEngine(num_chunks=3, num_threads=2,
                        reassemble="concat") as conc:
        ts_cc, loss_cc, _ = _run_epoch(conc)
    assert float(loss_m) == float(loss_cc)
    for a, b in zip(jax.tree_util.tree_leaves(ts_m.params),
                    jax.tree_util.tree_leaves(ts_cc.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_streaming_timeline_carries_chunk_spans():
    with TransferEngine(num_chunks=4, num_threads=2) as eng:
        _, _, tl = _run_epoch(eng)
    assert len(tl) == 2  # 70 samples, 32/shard -> 2 shards
    for e in tl:
        for key in ("gather_s", "put_s", "feed_wall_s", "chunks",
                    "inflight_max", "h2d_gbps", "bytes", "dispatch_s",
                    "queue_wait_s"):
            assert key in e, f"timeline missing {key}"
        assert len(e["chunks"]) == 4
        for c in e["chunks"]:
            assert c["put_end_t"] >= c["put_start_t"]
            assert c["rows"] == 8
    assert sum(c["bytes"] for c in tl[0]["chunks"]) == tl[0]["bytes"]


def test_streaming_default_engine_trains():
    """engine=None builds (and closes) a private default engine — the
    epoch must still train and cover every shard."""
    x, y = _stream_blobs(n=70, seed=8)
    model = _stream_model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    n0 = threading.active_count()
    losses = []
    for epoch in range(4):
        ts, loss = train_streaming_epoch(step, ts, ds,
                                         jax.random.PRNGKey(epoch), 0.05)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # the private engine's pool threads must not leak across epochs
    deadline = time.time() + 10
    while threading.active_count() > n0 and time.time() < deadline:
        time.sleep(0.1)
    assert threading.active_count() <= n0


# -------------------------------------------------- integration: loaders

def test_prefetch_loader_with_engine_bit_identical():
    x = np.arange(64 * 4, dtype=np.uint8).reshape(64, 4)
    y = (np.arange(64) % 3).astype(np.int32)

    def mk():
        ld = ArrayDataLoader(x, y, batch_size=8, shuffle=False)
        ld.load_data()
        return ld

    plain = list(PrefetchLoader(mk(), depth=2, stage_batches=3))
    with TransferEngine(num_chunks=2, num_threads=2,
                        reassemble="concat") as eng:
        chunked = list(PrefetchLoader(mk(), depth=2, stage_batches=3,
                                      transfer_engine=eng))
    assert len(plain) == len(chunked)
    for (px, py), (cx, cy) in zip(plain, chunked):
        np.testing.assert_array_equal(np.asarray(px), np.asarray(cx))
        np.testing.assert_array_equal(np.asarray(py), np.asarray(cy))


def test_device_dataset_engine_staging_bit_identical():
    from dcnn_tpu.data import DeviceDataset

    x, y = _host_blob(n=32, seed=9)
    plain = DeviceDataset(x, y, 4, batch_size=8)
    with TransferEngine(num_chunks=4, num_threads=2) as eng:
        staged = DeviceDataset(x, y, 4, batch_size=8, transfer_engine=eng)
    np.testing.assert_array_equal(np.asarray(plain.x), np.asarray(staged.x))
    np.testing.assert_array_equal(np.asarray(plain.y), np.asarray(staged.y))


def test_make_shard_step_chunk_tuple_matches_monolithic():
    """Feeding the shard step a chunk tuple (in-dispatch concatenate) is
    numerically identical to feeding the concatenated array."""
    x, y = _stream_blobs(n=24, seed=10)
    model = _stream_model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts_a = create_train_state(model, opt, key)
    ts_b = create_train_state(model, opt, key)
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=3)
    rng = jax.random.PRNGKey(5)
    xs, ys = jnp.asarray(x), jnp.asarray(y.astype(np.int32))
    ts_a, loss_a = step(ts_a, xs, ys, rng, 0.05)
    parts = tuple(jnp.asarray(x[lo:hi]) for lo, hi in chunk_bounds(24, 3))
    ts_b, loss_b = step(ts_b, parts, ys, rng, 0.05)
    assert float(loss_a) == float(loss_b)
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # geometry validation still fires through the tuple path
    bad = tuple(jnp.asarray(x[lo:hi]) for lo, hi in chunk_bounds(16, 2))
    with pytest.raises(ValueError, match="exactly"):
        step(create_train_state(model, opt, key), bad,
             jnp.asarray(y[:16].astype(np.int32)), rng, 0.05)
