"""Attention ops + sequence-parallelism tests.

Strategy mirrors the reference's kernel-test pattern (SURVEY.md §4.2): run
the optimised implementation, compare against the naive materialising oracle
elementwise. Ring/Ulysses run on the 8-virtual-device CPU mesh from conftest
and must match single-device full attention exactly.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.mesh import make_mesh, SEQ_AXIS
from dcnn_tpu.nn import MultiHeadAttentionLayer, SequentialBuilder
from dcnn_tpu.nn.factory import layer_from_config
from dcnn_tpu.ops.attention import (
    attention, blockwise_attention, flash_attention,
)
from dcnn_tpu.parallel import (
    make_ring_attention, make_ulysses_attention, shard_sequence,
)


def _qkv(rng, b=2, h=4, s=64, d=16):
    q = jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, h, s, d)).astype(np.float32))
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_matches_naive(rng, causal):
    q, k, v = _qkv(rng)
    ref = attention(q, k, v, causal=causal)
    out = blockwise_attention(q, k, v, causal=causal, block_kv=16)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_blockwise_unpadded_block_edge(rng):
    # kv length not a multiple of the block: padding mask must zero the tail
    q, k, v = _qkv(rng, s=50)
    ref = attention(q, k, v)
    out = blockwise_attention(q, k, v, block_kv=16)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_arbitrary_mask_matches_naive(rng, causal):
    """Padding/segment masks on the memory-efficient path (ADVICE r1 #4)."""
    q, k, v = _qkv(rng, s=48)
    # per-batch key-padding mask: batch 0 attends to first 33 keys only
    kmask = np.ones((2, 1, 1, 48), bool)
    kmask[0, ..., 33:] = False
    kmask = jnp.asarray(kmask)
    ref = attention(q, k, v, causal=causal, mask=kmask)
    out = blockwise_attention(q, k, v, causal=causal, block_kv=16, mask=kmask)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)
    # flash routes masked calls to blockwise (Pallas kernel is causal-only)
    out_f = flash_attention(q, k, v, causal=causal, mask=kmask)
    np.testing.assert_allclose(out_f, ref, atol=1e-5, rtol=1e-5)


def test_fully_masked_rows_return_zero(rng):
    """Oracle and blockwise agree: zero output for fully-masked rows."""
    q, k, v = _qkv(rng, s=32)
    mask = np.ones((1, 1, 32, 32), bool)
    mask[..., 5, :] = False                     # query 5 attends to nothing
    mask = jnp.asarray(mask)
    ref = attention(q, k, v, mask=mask)
    out = blockwise_attention(q, k, v, block_kv=16, mask=mask)
    np.testing.assert_array_equal(np.asarray(ref[:, :, 5]), 0.0)
    np.testing.assert_array_equal(np.asarray(out[:, :, 5]), 0.0)
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_blockwise_mask_validation(rng):
    q, k, v = _qkv(rng, s=32)
    with pytest.raises(ValueError, match="mask last dim"):
        blockwise_attention(q, k, v, mask=jnp.ones((1, 1, 32, 7), bool))


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_gradients_match_naive(rng, causal):
    q, k, v = _qkv(rng, b=1, h=2, s=24, d=8)

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=causal) ** 2)

    def loss_blk(q, k, v):
        return jnp.sum(blockwise_attention(q, k, v, causal=causal,
                                           block_kv=8) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    g_blk = jax.grad(loss_blk, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_blk):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_naive(rng, causal):
    from dcnn_tpu.ops.attention import _HAVE_PALLAS
    if not _HAVE_PALLAS and jax.default_backend() != "tpu":
        pytest.skip("Pallas unavailable in this jax build")
    q, k, v = _qkv(rng, s=48)
    ref = attention(q, k, v, causal=causal)
    # interpret=True: exercise the Pallas kernel itself on CPU (without it
    # the off-TPU path falls back to blockwise_attention)
    out = flash_attention(q, k, v, causal=causal, block_q=16, block_kv=16,
                          interpret=jax.default_backend() != "tpu")
    np.testing.assert_allclose(out, ref, atol=1e-5, rtol=1e-5)


def test_flash_gradients_match_naive(rng):
    from dcnn_tpu.ops.attention import _HAVE_PALLAS
    if not _HAVE_PALLAS and jax.default_backend() != "tpu":
        pytest.skip("Pallas unavailable in this jax build")
    q, k, v = _qkv(rng, b=1, h=2, s=32, d=8)

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, block_q=16, block_kv=16,
                        interpret=jax.default_backend() != "tpu") ** 2),
        argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("causal,sq,sk", [
    (True, 40, 40),    # padding: 40 % 16 != 0
    (False, 24, 56),   # cross-attention, Sq != Sk, both padded
    (True, 48, 32),    # Sq > Sk: leading causal rows fully masked
])
def test_flash_pallas_backward_cases(rng, causal, sq, sk):
    """The Pallas dq/dk/dv kernels (round 3) vs the materialising oracle:
    padding, cross-attention shapes, and fully-masked rows (whose lse is
    ~NEG_INF — the backward must mask P explicitly, never via exp)."""
    from dcnn_tpu.ops.attention import _HAVE_PALLAS
    if not _HAVE_PALLAS and jax.default_backend() != "tpu":
        pytest.skip("Pallas unavailable in this jax build")
    b, h, d = 2, 2, 8
    q = jnp.asarray(rng.normal(size=(b, h, sq, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, h, sk, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, h, sk, d)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(b, h, sq, d)).astype(np.float32))

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a, causal=causal) * w),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=causal, block_q=16, block_kv=16,
                        interpret=jax.default_backend() != "tpu") * w),
        argnums=(0, 1, 2))(q, k, v)
    for a, b_ in zip(g_ref, g_fl):
        np.testing.assert_allclose(a, b_, atol=2e-4, rtol=1e-4)


def test_flash_pallas_backward_bf16(rng):
    """bf16 inputs: fp32 accumulators inside the kernels keep gradients close
    to the fp32 oracle (bf16-level tolerance)."""
    from dcnn_tpu.ops.attention import _HAVE_PALLAS
    if not _HAVE_PALLAS and jax.default_backend() != "tpu":
        pytest.skip("Pallas unavailable in this jax build")
    q, k, v = _qkv(rng, b=1, h=2, s=32, d=8)
    qb, kb, vb = (a.astype(jnp.bfloat16) for a in (q, k, v))

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a, causal=True) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(lambda *a: jnp.sum(
        flash_attention(*a, causal=True, block_q=16, block_kv=16,
                        interpret=jax.default_backend() != "tpu"
                        ).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2))(qb, kb, vb)
    for a, b_ in zip(g_ref, g_fl):
        np.testing.assert_allclose(np.asarray(b_, np.float32), a,
                                   atol=0.15, rtol=0.1)


def test_flash_off_tpu_defaults_to_blockwise(rng, monkeypatch):
    """ADVICE r1 (medium): off-TPU without explicit interpret, flash must
    route to the exact blockwise path, never the Pallas interpreter."""
    # NB: `dcnn_tpu.ops.attention` the *attribute* is shadowed by the
    # function of the same name re-exported in ops/__init__ — fetch the
    # module itself
    import importlib
    A = importlib.import_module("dcnn_tpu.ops.attention")
    q, k, v = _qkv(rng, b=1, h=1, s=16, d=8)
    if jax.default_backend() == "tpu":
        pytest.skip("off-TPU routing test")
    calls = {}
    real = A.blockwise_attention

    def spy(*a, **kw):
        calls["hit"] = True
        return real(*a, **kw)

    monkeypatch.setattr(A, "blockwise_attention", spy)
    A.flash_attention(q, k, v)
    assert calls.get("hit")


def test_blockwise_bf16_accumulates_fp32(rng):
    """ADVICE r1: bf16 inputs must produce near-fp32-quality softmax output
    (state carried in fp32), and output dtype matches input dtype."""
    q, k, v = _qkv(rng, b=1, h=2, s=64, d=8)
    ref = attention(q, k, v, causal=True)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out = blockwise_attention(qb, kb, vb, causal=True, block_kv=16)
    assert out.dtype == jnp.bfloat16
    # tolerance dominated by the bf16 *inputs*, not the accumulator
    np.testing.assert_allclose(np.asarray(out, np.float32), ref,
                               atol=5e-2, rtol=5e-2)


# ---------------------------------------------------------------------------
# sequence parallelism over the 8-device mesh
# ---------------------------------------------------------------------------

@pytest.fixture
def seq_mesh():
    return make_mesh((8,), (SEQ_AXIS,))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(rng, seq_mesh, causal):
    q, k, v = _qkv(rng, b=2, h=2, s=64, d=8)
    ref = attention(q, k, v, causal=causal)
    ring = make_ring_attention(seq_mesh, causal=causal)
    qs, ks, vs = shard_sequence((q, k, v), seq_mesh)
    out = ring(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_ring_attention_grads_match_full(rng, seq_mesh):
    q, k, v = _qkv(rng, b=1, h=2, s=32, d=8)
    ring = make_ring_attention(seq_mesh, causal=True)

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a, causal=True) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.grad(lambda *a: jnp.sum(ring(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(b), a, atol=1e-4, rtol=1e-4)


def test_zigzag_ring_matches_full(rng, seq_mesh):
    from dcnn_tpu.parallel import (make_zigzag_ring_attention,
                                   zigzag_permutation, zigzag_shard)

    q, k, v = _qkv(rng, b=2, h=2, s=64, d=8)
    ref = attention(q, k, v, causal=True)
    n = seq_mesh.shape["seq"]
    zz = make_zigzag_ring_attention(seq_mesh)
    qs, ks, vs = zigzag_shard((q, k, v), seq_mesh)
    out_zz = zz(qs, ks, vs)
    inv = jnp.argsort(zigzag_permutation(64, n))
    out = jnp.take(out_zz, inv, axis=2)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_zigzag_ring_grads_match_full(rng, seq_mesh):
    from dcnn_tpu.parallel import (make_zigzag_ring_attention,
                                   zigzag_permutation)

    q, k, v = _qkv(rng, b=1, h=2, s=32, d=8)
    n = seq_mesh.shape["seq"]
    perm = zigzag_permutation(32, n)
    inv = jnp.argsort(perm)
    zz = make_zigzag_ring_attention(seq_mesh)

    def loss_zz(q, k, v):
        out = zz(jnp.take(q, perm, 2), jnp.take(k, perm, 2),
                 jnp.take(v, perm, 2))
        return jnp.sum(jnp.take(out, inv, 2) ** 2)

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a, causal=True) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_zz = jax.grad(loss_zz, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_zz):
        np.testing.assert_allclose(np.asarray(b), a, atol=1e-4, rtol=1e-4)


def test_zigzag_balance_property():
    """The zigzag layout's reason to exist: live (unmasked) chunk-pairs per
    device are equal across the ring — the plain causal ring's live-round
    count is i+1 (maximally imbalanced)."""
    for n in (2, 4, 8):
        live = []
        for i in range(n):
            cnt = 0
            for t in range(n):
                src = (i - t) % n
                for off_q in (i, 2 * n - 1 - i):
                    for off_k in (src, 2 * n - 1 - src):
                        if off_k <= off_q:   # chunk-level any-allowed
                            cnt += 1
            live.append(cnt)
        assert len(set(live)) == 1, (n, live)
        assert live[0] == 2 * n + 1


def test_zigzag_ring_validation(rng, seq_mesh):
    from dcnn_tpu.parallel import make_zigzag_ring_attention

    q, k, v = _qkv(rng, s=24)   # 24 % 16 != 0
    with pytest.raises(ValueError, match="divisible"):
        make_zigzag_ring_attention(seq_mesh)(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_full(rng, seq_mesh, causal):
    q, k, v = _qkv(rng, b=2, h=8, s=64, d=8)  # heads divisible by 8
    ref = attention(q, k, v, causal=causal)
    uly = make_ulysses_attention(seq_mesh, causal=causal)
    qs, ks, vs = shard_sequence((q, k, v), seq_mesh)
    out = uly(qs, ks, vs)
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5, rtol=1e-5)


def test_ulysses_grads_match_full(rng, seq_mesh):
    """Gradients through Ulysses: custom-VJP flash kernels (forced Pallas
    interpreter off-TPU) composed with all_to_all's transpose rule — the
    exact composition TPU training runs (review r3 finding)."""
    from dcnn_tpu.ops.attention import _HAVE_PALLAS
    if not _HAVE_PALLAS and jax.default_backend() != "tpu":
        pytest.skip("Pallas unavailable in this jax build")
    q, k, v = _qkv(rng, b=1, h=8, s=32, d=8)
    interp = jax.default_backend() != "tpu"
    uly = make_ulysses_attention(seq_mesh, causal=True, interpret=interp)

    g_ref = jax.grad(lambda *a: jnp.sum(attention(*a, causal=True) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.grad(lambda *a: jnp.sum(uly(*a) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_uly):
        np.testing.assert_allclose(np.asarray(b), a, atol=1e-4, rtol=1e-4)


def test_ulysses_rejects_indivisible_heads(rng, seq_mesh):
    q, k, v = _qkv(rng, h=3)
    with pytest.raises(ValueError, match="divisible"):
        make_ulysses_attention(seq_mesh)(q, k, v)


def test_ring_rejects_indivisible_sequence(rng, seq_mesh):
    """ADVICE r1: uneven sequence shards must fail with a clear error, not
    an opaque shard_map one."""
    q, k, v = _qkv(rng, s=60)  # 60 % 8 != 0
    with pytest.raises(ValueError, match="divisible"):
        make_ring_attention(seq_mesh)(q, k, v)


# ---------------------------------------------------------------------------
# MultiHeadAttention layer
# ---------------------------------------------------------------------------

def test_mha_layer_impls_agree(rng):
    x = jnp.asarray(rng.normal(size=(2, 32, 64)).astype(np.float32))
    key = jax.random.PRNGKey(0)
    outs = {}
    for impl in ("naive", "blockwise", "flash"):
        layer = MultiHeadAttentionLayer(num_heads=4, impl=impl, causal=True)
        params, state = layer.init(key, (32, 64))
        outs[impl], _ = layer.apply(params, state, x)
    np.testing.assert_allclose(outs["blockwise"], outs["naive"],
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(outs["flash"], outs["naive"],
                               atol=1e-5, rtol=1e-5)


def test_mha_classifier_trains_end_to_end(rng):
    """Zoo MHA model through the full Trainer stack: an attention-friendly
    synthetic task (class = position of the marked token) must reach >90%
    train accuracy in a few epochs (the verify-recipe gate)."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ArrayDataLoader
    from dcnn_tpu.models import create_mha_classifier
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.train import Trainer
    from dcnn_tpu.train.trainer import create_train_state

    n, s, e = 256, 32, 64
    y_idx = rng.integers(0, 10, n)
    x = rng.normal(0, 0.1, (n, s, e)).astype(np.float32)
    x[np.arange(n), y_idx * 3, :8] += 2.5     # class marker at position 3*c
    y = np.eye(10, dtype=np.float32)[y_idx]
    ld = ArrayDataLoader(x, y, batch_size=32, shuffle=True)
    ld.load_data()

    model = create_mha_classifier()
    opt = Adam(1e-3)
    tr = Trainer(model, opt, "softmax_crossentropy",
                 config=TrainingConfig(epochs=6, progress_interval=0,
                                       snapshot_dir=None))
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    tr.fit(ts, ld)
    assert tr.history[-1]["train_acc"] > 0.9, tr.history[-1]

    # and it round-trips through the factory like every zoo model
    from dcnn_tpu.nn import Sequential
    clone = Sequential.from_config(model.get_config())
    assert clone.get_config() == model.get_config()


def test_mha_layer_config_roundtrip_and_builder(rng):
    layer = MultiHeadAttentionLayer(num_heads=4, causal=True, impl="blockwise")
    params, _ = layer.init(jax.random.PRNGKey(0), (16, 32))
    rebuilt = layer_from_config(layer.get_config())
    assert rebuilt.num_heads == 4 and rebuilt.causal and rebuilt.impl == "blockwise"

    model = (SequentialBuilder("attn_model")
             .input((16, 32))
             .add_layer(MultiHeadAttentionLayer(num_heads=4, impl="blockwise"))
             .add_layer(MultiHeadAttentionLayer(num_heads=2, impl="blockwise"))
             .build())
    p, s = model.init(jax.random.PRNGKey(1))
    x = jnp.asarray(rng.normal(size=(3, 16, 32)).astype(np.float32))
    y, _ = model.apply(p, s, x, training=False)
    assert y.shape == (3, 16, 32)
    assert np.all(np.isfinite(np.asarray(y)))


def test_flash_geometry_safety_gate(rng):
    """VMEM-safety routing for the Pallas backward (VERDICT r4 #5): tiny
    head dims at long sequence must take the blockwise fallback instead of
    failing Mosaic compilation; the measured-good geometries stay on the
    Pallas path."""
    from dcnn_tpu.ops.attention import _flash_geometry_safe

    # measured failure on v5e: E=128/H=8 -> d=16 at S=8192 (b=2, h=8)
    assert not _flash_geometry_safe(2, 8, 8192, 8192, 16)
    # the proven long-context config: d=64 at S=8192 streams fine
    assert _flash_geometry_safe(4, 8, 8192, 8192, 64)
    # small-S d=16 fits comfortably
    assert _flash_geometry_safe(2, 8, 512, 512, 16)
    # and the fallback is the same math: flash == naive on an unsafe-shaped
    # (scaled-down d) geometry, gradients included
    q, k, v = _qkv(rng, b=1, h=2, s=96, d=16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention(q, k, v, causal=True) ** 2)

    np.testing.assert_allclose(loss_flash(q, k, v), loss_ref(q, k, v),
                               rtol=1e-5)
    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)
