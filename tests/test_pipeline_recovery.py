"""Self-healing TCP pipeline: stage-failure recovery matrix (ISSUE 13).

In-process fleets: N ``StageWorker`` threads over loopback sockets, one
``DistributedPipelineCoordinator`` with fast heartbeats, victims killed by
per-worker ``FaultPlan``s arming the deterministic ``pipeline.stage_death``
dispatch point with ``InjectedCrash`` (the SIGKILL stand-in — the worker's
sockets close exactly like a dead process's).

Contract pinned here (mirrors the PR-8 elastic matrix):
- killing ANY stage position mid-batch yields a run that detects within
  the heartbeat budget, repartitions over the survivors (or a respawned
  worker), replays the journal + the aborted batch, and finishes with
  final params matching an uninterrupted run within the PR-8 reshard
  tolerance — zero lost batches, one ``pipeline_stage_death`` flight
  bundle;
- the respawn path (same worker count, same partitions) is BIT-exact;
- a second fault during recovery re-enters idempotently (worker death
  mid-re-ship AND coordinator-side torn weight-ship);
- a worker outlives a dead coordinator and serves a replacement.
"""

import threading
import time

import jax
import numpy as np
import pytest

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD, Adam
from dcnn_tpu.parallel import (
    DistributedPipelineCoordinator, PipelineTimeouts, StageWorker, comm,
)
from dcnn_tpu.resilience import FaultPlan
from dcnn_tpu.resilience.faults import InjectedCrash

RTOL, ATOL = 2e-4, 2e-5  # PR-8 reshard tolerance: FP reassociation only

T = PipelineTimeouts(batch_s=60.0, heartbeat_s=0.05, respawn_s=0.5)


def _model():
    return (SequentialBuilder("heal_pipe").input((16,))
            .dense(32).activation("relu")
            .dense(24).activation("relu")
            .dense(4).build())


def _batches(n=6, n_rows=8, seed=0):
    rng = np.random.default_rng(seed)
    return [(rng.normal(size=(n_rows, 16)).astype(np.float32),
             np.eye(4, dtype=np.float32)[rng.integers(0, 4, n_rows)])
            for _ in range(n)]


class _Fleet:
    """N StageWorker threads on pre-bound loopback sockets + teardown."""

    def __init__(self, n=3, plans=None):
        self.socks = [comm.listen(0, host="127.0.0.1") for _ in range(n)]
        self.addrs = [f"127.0.0.1:{s.getsockname()[1]}" for s in self.socks]
        self.plans = plans or [FaultPlan() for _ in range(n)]
        self.workers = [StageWorker(0, listen_sock=s, fault_plan=p)
                        for s, p in zip(self.socks, self.plans)]
        self.threads = [threading.Thread(target=self._serve, args=(w,),
                                         daemon=True) for w in self.workers]
        for t in self.threads:
            t.start()

    @staticmethod
    def _serve(w):
        try:
            w.serve()
        except InjectedCrash:
            pass  # the simulated kill — sockets already closed by serve()

    def close(self):
        for w in self.workers:
            w.stop()
        for t in self.threads:
            t.join(timeout=10)


def _coordinator(addrs, optimizer=None, **kw):
    kw.setdefault("timeouts", T)
    return DistributedPipelineCoordinator(
        _model(), optimizer or SGD(0.05, momentum=0.9),
        "softmax_crossentropy", workers=addrs, num_microbatches=2, **kw)


def _run(co, n=6):
    co.deploy_stages(jax.random.PRNGKey(0))
    losses = []
    for b, (x, y) in enumerate(_batches(n)):
        loss, _ = co.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(b))
        losses.append(loss)
    params, state = co.gathered_params()
    return losses, jax.device_get(params), jax.device_get(state)


@pytest.fixture(scope="module")
def uninterrupted():
    fleet = _Fleet(3)
    try:
        co = _coordinator(fleet.addrs)
        out = _run(co)
        co.shutdown()
        return out
    finally:
        fleet.close()


def _assert_close(p, ref_p):
    for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                    jax.tree_util.tree_leaves(p)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=RTOL, atol=ATOL)


# -- the kill matrix: every victim position, mid-batch ---------------------

@pytest.mark.parametrize("victim", [0, 1, 2])
def test_kill_any_stage_mid_batch_params_match(victim, uninterrupted,
                                               tmp_path):
    """Kill stage ``victim`` on a mid-batch BACKWARD_JOB: the run must
    detect within the heartbeat budget, repartition over the 2 survivors,
    replay the journal + aborted batch, and land on the uninterrupted
    run's params — zero lost batches, evidence recorded."""
    from dcnn_tpu.obs.flight import FlightRecorder

    _, ref_p, _ = uninterrupted
    plans = [FaultPlan() for _ in range(3)]
    # per-victim dispatch sequence: CONFIG@0, per batch F,F,B,B,U (+GATHER
    # at the batch-2 commit) — at=14 is a batch-3 job on every position
    plans[victim].arm("pipeline.stage_death", at=14, exc=InjectedCrash)
    fleet = _Fleet(3, plans)
    flight = FlightRecorder(str(tmp_path / "flight"))
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2, flight=flight)
        _losses, p, _s = _run(co)
        co.shutdown()
    finally:
        fleet.close()

    _assert_close(p, ref_p)
    assert co.stats["recoveries"] == 1
    assert co.stats["batches_lost"] == 0
    assert co.num_stages == 2 and co.generation >= 1
    # detection: bounded by the convict+probe budget, never the batch wall
    assert co.stats["detection_s"], "no detection recorded"
    assert max(co.stats["detection_s"]) <= T.convict() + T.probe() + 1.0
    bundles = flight.bundles()
    assert [b["trigger"] for b in bundles] == ["pipeline_stage_death"]


def test_semi_async_schedule_recovers_too(uninterrupted, tmp_path):
    _, ref_p, _ = uninterrupted
    plans = [FaultPlan() for _ in range(3)]
    plans[1].arm("pipeline.stage_death", at=14, exc=InjectedCrash)
    fleet = _Fleet(3, plans)
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2)
        co.deploy_stages(jax.random.PRNGKey(0))
        for b, (x, y) in enumerate(_batches(6)):
            co.train_batch_semi_async(x, y, 0.05, jax.random.PRNGKey(b))
        p, _ = co.gathered_params()
        co.shutdown()
    finally:
        fleet.close()
    # semi-async backward dispatch order is arrival-driven: grads
    # accumulate in a different order than sync, so compare against the
    # sync reference only within the FP-reassociation tolerance
    _assert_close(jax.device_get(p), ref_p)
    assert co.stats["recoveries"] == 1 and co.stats["batches_lost"] == 0


# -- respawn path: bit-exact replay ----------------------------------------

def test_respawned_worker_rejoins_bit_exact(uninterrupted, tmp_path):
    """A supervisor-style respawn: when the dead worker's port comes back
    within ``respawn_s``, the pipeline keeps all 3 stages and identical
    partitions — the replayed trajectory is BIT-exact vs uninterrupted
    (same jit graphs, same inputs; weights round-trip losslessly)."""
    _, ref_p, _ = uninterrupted
    plans = [FaultPlan() for _ in range(3)]
    plans[1].arm("pipeline.stage_death", at=14, exc=InjectedCrash)
    fleet = _Fleet(3, plans)
    respawned = {}

    def respawn():
        fleet.threads[1].join(timeout=30)  # the victim's serve() exits
        host, port = comm.parse_addr(fleet.addrs[1])
        sock = comm.listen(port, host=host)  # SO_REUSEADDR rebind
        w = StageWorker(0, listen_sock=sock)
        respawned["worker"] = w
        _Fleet._serve(w)

    watcher = threading.Thread(target=respawn, daemon=True)
    watcher.start()
    try:
        co = _coordinator(
            fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=2,
            timeouts=PipelineTimeouts(batch_s=60.0, heartbeat_s=0.05,
                                      respawn_s=8.0))
        _losses, p, _s = _run(co)
        co.shutdown()
    finally:
        fleet.close()
        if "worker" in respawned:
            respawned["worker"].stop()
        watcher.join(timeout=10)

    assert co.num_stages == 3, "respawned worker should keep 3 stages"
    assert co.stats["respawns"] >= 1
    assert co.stats["batches_lost"] == 0
    for a, b in zip(jax.tree_util.tree_leaves(ref_p),
                    jax.tree_util.tree_leaves(p)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- double faults ---------------------------------------------------------

def test_second_death_during_recovery_reenters(uninterrupted, tmp_path):
    """Victim A dies mid-batch-3; victim B dies on the RECOVERY's
    CONFIG_TRANSFER re-ship — the recovery loop must re-enter with the
    shrunken set and finish on 1 stage, params still matching."""
    _, ref_p, _ = uninterrupted
    plans = [FaultPlan() for _ in range(3)]
    # stage 0 dies at its batch-3 backward (mb1): count 15
    plans[0].arm("pipeline.stage_death", at=15, exc=InjectedCrash)
    # stage 2's counts: CONFIG@0, batches 1-2 @1-10, GATHER@11, batch 3
    # F@12,F@13,B@14,B@15 — the recovery re-ship CONFIG lands at 16
    plans[2].arm("pipeline.stage_death", at=16, exc=InjectedCrash)
    fleet = _Fleet(3, plans)
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2)
        _losses, p, _s = _run(co)
        co.shutdown()
    finally:
        fleet.close()
    assert co.num_stages == 1
    assert co.stats["batches_lost"] == 0
    _assert_close(p, ref_p)


def test_torn_weight_ship_reenters_idempotently(uninterrupted, tmp_path):
    """The ``pipeline.weight_ship`` fault point armed ``exc=OSError`` on
    the coordinator: the FIRST recovery's re-ship fails mid-send, the
    channel is marked broken, and recovery re-enters idempotently
    (fresh generation, fresh sweep) — the run still completes and
    matches."""
    _, ref_p, _ = uninterrupted
    wplans = [FaultPlan() for _ in range(3)]
    wplans[1].arm("pipeline.stage_death", at=14, exc=InjectedCrash)
    fleet = _Fleet(3, wplans)
    # deploy ships stages 0..2 (trips 0-2); the first recovery's first
    # re-ship is trip 3 — fail exactly that one
    cplan = FaultPlan().arm("pipeline.weight_ship", at=3, times=1,
                            exc=OSError)
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2, fault_plan=cplan)
        _losses, p, _s = _run(co)
        co.shutdown()
    finally:
        fleet.close()
    assert cplan.count("pipeline.weight_ship") > 4  # re-entered + re-shipped
    assert co.generation >= 2  # two aborts: the death + the torn ship
    assert co.stats["batches_lost"] == 0
    _assert_close(p, ref_p)


# -- worker outlives a dead coordinator ------------------------------------

def test_worker_outlives_dead_coordinator():
    """Coordinator A dies abruptly (channels closed, no SHUTDOWN): the
    worker convicts it, drops the channel, KEEPS its stage, and keeps
    listening — coordinator B deploys onto the same fleet and trains."""
    fleet = _Fleet(2)
    try:
        a = _coordinator(fleet.addrs)
        a.deploy_stages(jax.random.PRNGKey(0))
        x, y = _batches(1)[0]
        a.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(0))
        # abrupt death: beat thread stopped, sockets closed, no SHUTDOWN
        a._beat_stop.set()
        for ch in a.chans:
            ch.close()

        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                any(w._coord_chan() is not None for w in fleet.workers):
            time.sleep(0.02)
        assert all(w._coord_chan() is None for w in fleet.workers), \
            "workers did not convict the dead coordinator"
        assert all(w.stage is not None for w in fleet.workers), \
            "workers must keep their stage across a coordinator loss"

        b = _coordinator(fleet.addrs)
        b.deploy_stages(jax.random.PRNGKey(1))
        loss, _ = b.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(1))
        assert np.isfinite(loss)
        assert b.health_check()[0]["configured"]
        b.shutdown()
    finally:
        fleet.close()


def test_wedged_coordinator_convicted_by_silence():
    """Unit: coordinator silence (no BEATs, connection still open) past
    ``coord_timeout_s`` resets the worker's coordinator — the
    probe-then-convict treatment the worker-side inbox waits get."""
    clock = [0.0]
    w = StageWorker(0, clock=lambda: clock[0])
    closed = []

    class FakeChan:
        def close(self):
            closed.append(True)
    with w._lock:
        w.coord = FakeChan()
        w._hb_s = 0.05
        w._coord_timeout_s = 0.4
        w._coord_heard = 0.0
    clock[0] = 0.3
    w._check_coordinator()
    assert w._coord_chan() is not None  # still within budget
    clock[0] = 0.5
    # silence is only judged when the inbox is DRAINED: a long dispatch
    # (first-job XLA compile) leaves BEATs queued unread, and convicting
    # before consuming them would drop a healthy coordinator
    w._check_coordinator(drained=False)
    assert w._coord_chan() is not None
    w._check_coordinator(drained=True)
    assert w._coord_chan() is None and closed


# -- coordinator-side liveness units ---------------------------------------

class _FakeChan:
    def __init__(self):
        self.sent = []
        self.timeout = None

    def send(self, cmd, meta=None, array=None, raw=None, **kw):
        self.sent.append((cmd, meta))

    def set_send_timeout(self, s):
        self.timeout = s

    def close(self):
        pass


def test_probe_then_convict_unit():
    """Silence > convict_s sends ONE probe; an unanswered probe past
    probe_s convicts (StageLostError); any frame heard in between
    disarms the probe."""
    from dcnn_tpu.parallel.distributed_pipeline import StageLostError

    clock = [0.0]
    co = _coordinator(["127.0.0.1:1"],
                      timeouts=PipelineTimeouts(batch_s=60.0,
                                                heartbeat_s=1.0),
                      clock=lambda: clock[0])
    ch = _FakeChan()
    co._install_workers([("127.0.0.1:1", ch)])

    clock[0] = 4.0          # silence 4s < convict 5s
    co._check_liveness()
    assert not ch.sent
    clock[0] = 5.5          # past convict: exactly one probe
    co._check_liveness()
    assert [c for c, _ in ch.sent] == ["HEALTH_CHECK"]
    co._check_liveness()
    assert len(ch.sent) == 1  # probe not re-sent while armed
    co._heard(ch)           # a BEAT arrives: probe disarmed
    clock[0] = 9.0
    co._check_liveness()    # silence re-measured from the beat
    assert len(ch.sent) == 1
    clock[0] = 11.0         # silent again past convict: second probe
    co._check_liveness()
    assert len(ch.sent) == 2
    clock[0] = 14.5         # probe unanswered past probe_s (3s): convict
    with pytest.raises(StageLostError, match="unanswered probe"):
        co._check_liveness()
    assert co.stats == co.stats  # coordinator object still consistent


def test_connection_close_is_immediate():
    from dcnn_tpu.parallel.distributed_pipeline import StageLostError

    clock = [0.0]
    co = _coordinator(["127.0.0.1:1"],
                      timeouts=T, clock=lambda: clock[0])
    ch = _FakeChan()
    co._install_workers([("127.0.0.1:1", ch)])
    co._on_close(ch)
    with pytest.raises(StageLostError, match="closed"):
        co._check_liveness()


# -- the timeout contract --------------------------------------------------

def test_timeouts_contract_derivations():
    t = PipelineTimeouts(heartbeat_s=0.5)
    assert t.convict() == pytest.approx(2.5)
    assert t.probe() == pytest.approx(1.5)
    assert t.coord_timeout() == pytest.approx(4.0)
    assert t.drain() == pytest.approx(2.0)
    t2 = PipelineTimeouts(heartbeat_s=2.0, convict_s=3.0, probe_s=1.0,
                          drain_s=0.5, worker_coord_timeout_s=9.0)
    assert (t2.convict(), t2.probe(), t2.drain(), t2.coord_timeout()) == \
        (3.0, 1.0, 0.5, 9.0)
    # legacy constructor arg maps onto the contract
    co = DistributedPipelineCoordinator(
        _model(), SGD(0.05), "softmax_crossentropy",
        workers=["127.0.0.1:1"], timeout=42.0)
    assert co.t.batch_s == 42.0 and co.timeout == 42.0


# -- optimizer state split/merge (repartition preserves momentum) ----------

@pytest.mark.parametrize("opt", [SGD(0.05, momentum=0.9), Adam(1e-3),
                                 SGD(0.05)])
def test_optimizer_state_split_merge_roundtrip(opt):
    model = _model()
    params, _ = model.init(jax.random.PRNGKey(0))
    full = opt.init(params)
    # make the state non-trivial so the roundtrip proves value transport
    full = jax.tree_util.tree_map(lambda v: v + 1.0, full)
    partitions = [(0, 2), (2, 4), (4, 5)]
    merged = opt.merge_state(opt.split_state(full, partitions), partitions)
    fa = jax.tree_util.tree_leaves(full)
    fb = jax.tree_util.tree_leaves(merged)
    assert len(fa) == len(fb)
    for a, b in zip(fa, fb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- checkpoint cadence + journal ------------------------------------------

def test_commit_cadence_and_journal_trim(tmp_path):
    from dcnn_tpu.resilience.checkpoint import list_steps

    fleet = _Fleet(2)
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2, checkpoint_keep=2)
        co.deploy_stages(jax.random.PRNGKey(0))
        for b, (x, y) in enumerate(_batches(6)):
            co.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(b))
        steps = sorted(list_steps(str(tmp_path / "ck")))
        assert steps == [4, 6]  # keep=2 of the cadence commits 2,4,6
        # journal keeps one extra commit window (corrupt-newest insurance)
        assert [e["batch"] for e in co._journal] == [5, 6]
        r = co.checkpoints.restore_latest()
        assert r.metadata["batch"] == 6
        co.shutdown()
    finally:
        fleet.close()


def test_gather_vintage_and_momentum_roundtrip(tmp_path):
    """The commit gather reassembles params/state AND optimizer momentum:
    restore of a commit must carry velocity, proven by comparing against
    the live stage opt_state."""
    fleet = _Fleet(2)
    try:
        co = _coordinator(fleet.addrs, checkpoint_dir=str(tmp_path / "ck"),
                          checkpoint_every=2)
        co.deploy_stages(jax.random.PRNGKey(0))
        for b, (x, y) in enumerate(_batches(2)):
            co.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(b))
        r = co.checkpoints.restore_latest()
        vel = r.opt_state.get("velocity")
        assert vel is not None
        # momentum after 2 batches is nonzero and full-model shaped
        assert len(vel) == len(jax.tree_util.tree_leaves(
            dict(enumerate(vel)))) or len(vel) == 5
        assert any(float(np.abs(np.asarray(v)).max()) > 0
                   for v in jax.tree_util.tree_leaves(vel))
        co.shutdown()
    finally:
        fleet.close()


# -- wire format regression ------------------------------------------------

def test_bf16_activation_survives_wire_framing():
    """DCNN_PRECISION=bf16 makes stage activations bfloat16; the tensor
    framing must round-trip them (it silently produced 2-byte void
    before — the pipeline wire was unusable under the bench's default
    precision mode)."""
    import jax.numpy as jnp
    from dcnn_tpu.utils.compression import MetaCompressor

    mc = MetaCompressor()
    a = np.asarray(jnp.asarray(np.random.default_rng(0)
                               .standard_normal((4, 8)).astype(np.float32),
                               jnp.bfloat16))
    back = mc.decompress_array(mc.compress_array(a))
    assert back.dtype == a.dtype
    np.testing.assert_array_equal(back, a)


# -- healthz adapter -------------------------------------------------------

def test_pipeline_check_degrades_while_recovering():
    from dcnn_tpu.obs.server import pipeline_check

    class Co:
        recovering = False
        generation = 3
        num_stages = 2
    check = pipeline_check(Co())
    assert check() is None
    Co.recovering = True
    reason = check()
    assert "recovery in flight" in reason and "generation 3" in reason
