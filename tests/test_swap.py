"""Hot-swap over REAL engines + CheckpointManager commits
(dcnn_tpu/serve/swap.py).

Contracts (the engine-hot-swap-in-isolation satellite):

- drain → load the newest checksum-valid ``CheckpointManager`` commit →
  rejoin produces an engine **bit-identical to a freshly constructed
  one at every serve bucket**;
- a torn/corrupt newest commit is skipped to the previous valid version
  — no crash, warned + counted (``serve_swap_versions_skipped_total``)
  and, unlike the training-side restore, never renamed/quarantined (the
  serving tier is a read-only consumer of the checkpoint root);
- a ``serve.swap`` fault mid-load leaves the replica serving its OLD
  version.
"""

import numpy as np
import pytest

import jax

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.resilience.checkpoint import CheckpointManager, list_steps
from dcnn_tpu.resilience.faults import FaultPlan, InjectedFault
from dcnn_tpu.serve import (
    EngineFactory, InferenceEngine, LocalReplica, SwapError,
    newest_valid_version,
)


def _tiny_model():
    return (SequentialBuilder(name="swp", data_format="NHWC")
            .input((8, 8, 3))
            .conv2d(4, 3, padding=1).batchnorm().activation("relu")
            .maxpool2d(2).flatten().dense(5)
            .build())


@pytest.fixture(scope="module")
def versions(tmp_path_factory):
    """A checkpoint root with two committed versions of the tiny model —
    step 1, and step 2 with visibly different params — plus a probe
    batch."""
    root = str(tmp_path_factory.mktemp("ckpts"))
    model = _tiny_model()
    params1, state = model.init(jax.random.PRNGKey(0), model.input_shape)
    params2 = jax.tree_util.tree_map(lambda a: a + 0.25, params1)
    mgr = CheckpointManager(root, keep=5)
    mgr.save(1, model, params1, state)
    mgr.save(2, model, params2, state)
    mgr.close()
    rng = np.random.default_rng(3)
    pool = rng.normal(size=(8, 8, 8, 3)).astype(np.float32)
    return root, model, (params1, params2), state, pool


def test_newest_valid_version_picks_newest(versions):
    root, *_ = versions
    found = newest_valid_version(root)
    assert found is not None
    step, path = found
    assert step == 2 and path.endswith("ckpt-00000002")


def test_corrupt_newest_skipped_to_previous_valid(versions, tmp_path):
    """ACCEPTANCE (satellite): a bit-flipped newest commit is skipped to
    the previous valid version — no crash, logged + counted, nothing
    renamed (read-only consumer)."""
    import os
    import shutil

    root, *_ = versions
    work = str(tmp_path / "root")
    shutil.copytree(root, work)
    plan = FaultPlan(seed=7)
    plan.bit_flip(os.path.join(work, "ckpt-00000002", "arrays.msgpack"))

    reg = MetricsRegistry()
    with pytest.warns(UserWarning, match="torn/corrupt"):
        found = newest_valid_version(work, registry=reg)
    assert found is not None and found[0] == 1
    assert reg.snapshot()["serve_swap_versions_skipped_total"] == 1
    # the corrupt dir is still there under its own name — no quarantine
    assert sorted(list_steps(work)) == [1, 2]

    # the factory refuses to load the corrupt version explicitly...
    factory = EngineFactory(work, max_batch=4, registry=reg)
    with pytest.raises(Exception, match="checksum|missing"):
        factory(2)
    # ...and newest() already steered to the valid one (same skip warning)
    with pytest.warns(UserWarning, match="torn/corrupt"):
        assert factory.newest() == 1
    eng = factory(1)
    assert eng.version == 1


def test_factory_engine_bit_identical_to_fresh(versions):
    """ACCEPTANCE (satellite): the factory-loaded newest commit is
    bit-identical to a freshly constructed engine at EVERY serve
    bucket."""
    root, model, (_, params2), state, pool = versions
    factory = EngineFactory(root, max_batch=4)
    eng = factory(factory.newest())
    assert eng.version == 2 and eng.bucket_sizes == [1, 2, 4]
    fresh = InferenceEngine.from_model(model, params2, state, max_batch=4)
    for b in fresh.bucket_sizes:
        np.testing.assert_array_equal(
            np.asarray(eng.infer(pool[:b])),
            np.asarray(fresh.infer(pool[:b])))


def test_replica_hot_swap_bit_identity_every_bucket(versions):
    """ACCEPTANCE (satellite): drain → load newest → rejoin through a
    LocalReplica serves results bit-identical to a fresh engine of the
    new version at every bucket; the old version's results differ
    (the swap really happened)."""
    root, model, (params1, params2), state, pool = versions
    factory = EngineFactory(root, max_batch=4)
    rep = LocalReplica(factory, 1, name="swapper", queue_capacity=32,
                       start=False)
    try:
        fresh1 = InferenceEngine.from_model(model, params1, state,
                                            max_batch=4)
        f = rep.submit(pool[:2])
        rep.step()
        np.testing.assert_array_equal(np.asarray(f.result(timeout=0)),
                                      np.asarray(fresh1.infer(pool[:2])))

        rep.swap(2)  # drain -> load ckpt-2 -> rejoin
        assert rep.version == 2

        fresh2 = InferenceEngine.from_model(model, params2, state,
                                            max_batch=4)
        for b in fresh2.bucket_sizes:
            f = rep.submit(pool[:b] if b > 1 else pool[0])
            rep.step()
            got = np.asarray(f.result(timeout=0))
            want = np.asarray(fresh2.infer(pool[:b] if b > 1 else pool[0]))
            np.testing.assert_array_equal(got, want)
        # and it is genuinely the NEW version: v1 answers differ
        assert not np.array_equal(np.asarray(fresh1.infer(pool[:2])),
                                  np.asarray(fresh2.infer(pool[:2])))
    finally:
        rep.close()


def test_swap_fault_mid_load_rejoins_old_version(versions):
    root, model, (params1, _), state, pool = versions
    factory = EngineFactory(root, max_batch=4)
    plan = FaultPlan().arm("serve.swap", exc=InjectedFault, times=1)
    rep = LocalReplica(factory, 1, name="sticky", queue_capacity=32,
                       fault_plan=plan, start=False)
    try:
        with pytest.raises(SwapError, match="rejoined on old version"):
            rep.swap(2)
        assert rep.version == 1 and rep.health() is None
        fresh1 = InferenceEngine.from_model(model, params1, state,
                                            max_batch=4)
        f = rep.submit(pool[:2])
        rep.step()
        np.testing.assert_array_equal(np.asarray(f.result(timeout=0)),
                                      np.asarray(fresh1.infer(pool[:2])))
        rep.swap(2)  # plan disarmed (times=1): now it succeeds
        assert rep.version == 2
    finally:
        rep.close()


def test_factory_missing_version_raises(versions):
    root, *_ = versions
    factory = EngineFactory(root, max_batch=4)
    with pytest.raises(Exception, match="missing|checksum"):
        factory(99)
