"""Continuous-batching decode tests (dcnn_tpu/serve/decode.py + kvcache.py
+ models/decoder.py + the nn attention decode path).

Contracts (ISSUE 20 acceptance):

- ORACLE: the single-token decode path (paged engine AND dense
  ``decode_dense``) reproduces the full-sequence causal forward's greedy
  choices exactly — same mask convention, same precision;
- BIT-IDENTITY: a sequence's continuously-batched greedy output is
  bit-identical to the same sequence decoded alone
  (``decode_reference``), asserted across MULTIPLE admission
  interleavings (everything-up-front vs staggered mid-flight admission)
  and under forced preemption;
- ZERO RECOMPILES: admitting into a running batch triggers no compile
  once the (batch-bucket, page-bucket) set is warmed — asserted via the
  engine registry's ``compile_total`` delta;
- NO ORPHANS: an injected crash at ``decode.step`` fails every accepted
  sequence (active AND queued) typed; an ``InjectedFault`` at
  ``decode.admit`` fails exactly that sequence and the rest complete;
- the page pool allocates all-or-nothing, recycles through its free
  list, and never hands out the null page.

Engine construction compiles a bucket lattice (~seconds on CPU), so the
module builds TWO engines total (module-scoped fixtures): the main one
and a page-starved one for eviction.
"""

import os
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.models import MHADecoder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.resilience import FaultPlan
from dcnn_tpu.resilience.faults import InjectedCrash, InjectedFault
from dcnn_tpu.serve import (
    ContinuousBatcher, DecodeEngine, DrainingError, KVPagePool,
    OutOfPagesError, QueueFullError, decode_reference, suggest_num_pages,
)
from dcnn_tpu.serve.metrics import DecodeMetrics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PROMPTS = [[1, 5, 2], [3, 3], [7, 1, 2, 4], [2], [9, 8, 7, 1, 2], [4, 6]]


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


@pytest.fixture(scope="module")
def model():
    return MHADecoder(vocab_size=13, embed_dim=16, num_heads=2,
                      num_layers=2, max_seq_len=32)


@pytest.fixture(scope="module")
def params(model):
    return model.init(jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def engine(model, params):
    """Main engine: 4 slots x 4 pages of 4 — plus a private registry so
    compile accounting is observable without the process-global one."""
    reg = MetricsRegistry()
    eng = DecodeEngine(model, params, max_slots=4, page_size=4,
                       max_pages_per_seq=4, aot_cache=False, registry=reg)
    return eng


@pytest.fixture(scope="module")
def starved_engine(model, params):
    """Page-starved twin: 4 slots that cannot all hold max-length
    sequences (7 usable pages for up to 16 demanded) — forces the
    preempt-and-recompute path."""
    return DecodeEngine(model, params, max_slots=4, page_size=4,
                        max_pages_per_seq=4, num_pages=8, aot_cache=False,
                        warmup=False, registry=MetricsRegistry())


def greedy_oracle(model, params, prompt, max_new):
    """Greedy decode via the full-sequence causal forward — the slow
    reference everything else must reproduce exactly."""
    toks = list(prompt)
    for _ in range(max_new):
        logits = model.apply(params, jnp.asarray([toks], jnp.int32))
        toks.append(int(jnp.argmax(logits[0, -1])))
    return np.asarray(toks[len(prompt):], np.int32)


# ------------------------------------------------------------ oracle

def test_reference_matches_full_forward_oracle(model, params, engine):
    for prompt in PROMPTS[:3]:
        want = greedy_oracle(model, params, prompt, 6)
        got = decode_reference(engine, prompt, max_new_tokens=6)
        assert np.array_equal(got, want), (prompt, got, want)


def test_decode_dense_matches_oracle(model, params):
    """The un-paged dense-cache decode path (models/decoder.decode_dense
    over nn decode_qkv/decode/decode_attend) replays a sequence to the
    same greedy choices as the full forward."""
    prompt = [1, 5, 2, 9]
    b, t, e = 1, 16, model.embed_dim
    k = [jnp.zeros((b, t, e)) for _ in range(model.num_layers)]
    v = [jnp.zeros((b, t, e)) for _ in range(model.num_layers)]
    toks = list(prompt)
    generated = []
    for pos in range(len(prompt) + 5 - 1):
        x_t = model.embed_tokens(params, jnp.asarray([toks[pos]], jnp.int32))
        logits, k, v = model.decode_dense(
            params, x_t, k, v, jnp.asarray([pos], jnp.int32))
        if pos == len(toks) - 1:
            nxt = int(jnp.argmax(logits[0]))
            toks.append(nxt)
            generated.append(nxt)
    want = greedy_oracle(model, params, prompt, 5)
    assert np.array_equal(np.asarray(generated, np.int32), want)


def test_inactive_rows_fully_masked(model, params, engine):
    """A position of -1 marks an inactive row: its attention output is
    exactly zero (the NEG_INF mask underflows to 0.0), so padding rows
    cannot perturb anything."""
    blk, bp = model.blocks[0], params["blocks"][0]
    x = jax.random.normal(jax.random.PRNGKey(1), (2, model.embed_dim))
    q, _, _ = blk.decode_qkv(bp, x)
    ctx = jax.random.normal(jax.random.PRNGKey(2), (2, 8, model.embed_dim))
    out = blk.decode_attend(bp, q, ctx, ctx,
                            jnp.asarray([-1, -1], jnp.int32))
    # fully-masked rows: softmax zeroed, so only the output projection
    # bias survives — identical for any context content
    out2 = blk.decode_attend(bp, q, ctx * 100.0, ctx * -3.0,
                             jnp.asarray([-1, -1], jnp.int32))
    assert np.array_equal(np.asarray(out), np.asarray(out2))


# ------------------------------------------------- bit-identity

def _run_continuous(engine, submit_plan, max_new=5, **kw):
    """Drive a sync-mode batcher through `submit_plan`: a list of
    (step_at, prompt) pairs — each prompt submitted after `step_at`
    scheduler steps have run. Returns {prompt_index: result}."""
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock(), **kw)
    futs = {}
    plan = sorted(range(len(submit_plan)), key=lambda i: submit_plan[i][0])
    steps = 0
    while plan or cb.active_slots or cb.queue_depth:
        while plan and submit_plan[plan[0]][0] <= steps:
            i = plan.pop(0)
            futs[i] = cb.submit(submit_plan[i][1], max_new_tokens=max_new)
        if cb.step() == 0 and not plan:
            break
        steps += 1
    return {i: f.result(timeout=5) for i, f in futs.items()}


def test_continuous_bit_identical_upfront(engine):
    """Interleaving 1: everything submitted before the first step."""
    plan = [(0, p) for p in PROMPTS]
    got = _run_continuous(engine, plan)
    for i, p in enumerate(PROMPTS):
        want = decode_reference(engine, p, max_new_tokens=5)
        assert np.array_equal(got[i], want), (i, got[i], want)


def test_continuous_bit_identical_staggered(engine):
    """Interleaving 2: sequences admitted MID-FLIGHT into a running
    batch at different step boundaries — the continuous-batching case.
    Output must still be bit-identical per sequence."""
    plan = [(0, PROMPTS[0]), (0, PROMPTS[1]), (2, PROMPTS[2]),
            (3, PROMPTS[3]), (5, PROMPTS[4]), (7, PROMPTS[5])]
    got = _run_continuous(engine, plan)
    for i, (_, p) in enumerate(plan):
        want = decode_reference(engine, p, max_new_tokens=5)
        assert np.array_equal(got[i], want), (i, got[i], want)


def test_preemption_recompute_bit_identical(starved_engine):
    """Under page starvation the scheduler preempts the newest sequence
    and replays it after readmission — still bit-identical, and the
    eviction counter proves the path actually ran."""
    metrics = DecodeMetrics(clock=FakeClock())
    prompts = [[1, 5, 2, 4, 6], [3, 3, 1, 1], [7, 1, 2, 4, 5, 6],
               [2, 9, 8, 4], [9, 8, 7, 1, 2]]
    plan = [(0, p) for p in prompts]
    got = _run_continuous(starved_engine, plan, max_new=8, metrics=metrics)
    for i, p in enumerate(prompts):
        want = decode_reference(starved_engine, p, max_new_tokens=8)
        assert np.array_equal(got[i], want), (i, got[i], want)
    s = metrics.snapshot()
    assert s["evictions"] > 0, "starved pool must have preempted"
    assert s["completions"] == len(prompts)


def test_eos_stops_decode(model, params, engine):
    """eos_id terminates a sequence early, EOS token included."""
    ref = decode_reference(engine, [1, 5, 2], max_new_tokens=8)
    eos = int(ref[0])  # first generated token as EOS -> length-1 output
    got = _run_continuous(engine, [(0, [1, 5, 2])], max_new=8)[0]
    cb_ref = decode_reference(engine, [1, 5, 2], max_new_tokens=8,
                              eos_id=eos)
    assert np.array_equal(cb_ref, ref[:1])
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock())
    fut = cb.submit([1, 5, 2], max_new_tokens=8, eos_id=eos)
    while cb.step():
        pass
    assert np.array_equal(fut.result(timeout=5), ref[:1])
    assert np.array_equal(got, ref)


# ------------------------------------------------- zero recompiles

def test_admission_never_recompiles(engine):
    """Acceptance: once the (batch, page) bucket lattice is warm,
    admitting sequences into a running batch causes ZERO new compiles —
    the engine registry's compile_total is flat across a staggered run
    that exercises batch sizes 1..4 and growing page tables."""
    before = engine.registry.snapshot().get("compile_total")
    assert before == len(engine.compile_stats)  # one per (b, mp) session
    plan = [(0, PROMPTS[0]), (1, PROMPTS[1]), (2, PROMPTS[2]),
            (3, PROMPTS[3]), (4, PROMPTS[4]), (6, PROMPTS[5])]
    got = _run_continuous(engine, plan, max_new=7)
    assert len(got) == len(plan)
    after = engine.registry.snapshot().get("compile_total")
    assert after == before, (
        f"admission recompiled: compile_total {before} -> {after}")


# ------------------------------------------------- fault injection

def test_injected_crash_mid_step_fails_all_typed(engine):
    """resilience/faults.py trip point "decode.step": a crash mid-decode
    fails EVERY accepted sequence — active and still-queued — with the
    injected exception. Nothing is silently dropped, mirroring the
    DynamicBatcher accepted-ledger contract."""
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock(),
                           max_slots=2)
    futs = [cb.submit(p, max_new_tokens=5) for p in PROMPTS[:4]]
    assert cb.step() > 0  # step 0 runs clean
    with FaultPlan().arm("decode.step", exc=InjectedCrash):
        with pytest.raises(InjectedCrash):
            cb.step()
    for fut in futs:  # active (2) AND queued (2): all resolved, typed
        assert fut.done()
        with pytest.raises(InjectedCrash):
            fut.result(timeout=0)
    assert cb.engine.pool.pages_in_use == 0  # pages all recycled
    assert cb.health_reason() is not None
    with pytest.raises(DrainingError):
        cb.submit([1, 2], max_new_tokens=2)


def test_injected_fault_at_admit_fails_one_sequence(engine):
    """Trip point "decode.admit" with a plain InjectedFault: exactly the
    tripped sequence's future fails (typed), every other sequence decodes
    to the bit-identical reference."""
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock())
    with FaultPlan().arm("decode.admit", at=1, times=1):  # 2nd admission
        futs = [cb.submit(p, max_new_tokens=4) for p in PROMPTS[:3]]
        while cb.step():
            pass
    with pytest.raises(InjectedFault):
        futs[1].result(timeout=5)
    for i in (0, 2):
        want = decode_reference(engine, PROMPTS[i], max_new_tokens=4)
        assert np.array_equal(futs[i].result(timeout=5), want)


# ------------------------------------------------- intake contract

def test_submit_validation(engine):
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock())
    with pytest.raises(ValueError):
        cb.submit([], max_new_tokens=2)
    with pytest.raises(ValueError):
        cb.submit([1, 2], max_new_tokens=0)
    with pytest.raises(ValueError):
        cb.submit([99], max_new_tokens=2)  # token outside vocab
    with pytest.raises(ValueError):  # prompt + max_new > max context
        cb.submit([1] * 10, max_new_tokens=engine.max_context)


def test_queue_full_sheds_typed(engine):
    metrics = DecodeMetrics(clock=FakeClock())
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock(),
                           queue_capacity=2, metrics=metrics)
    cb.submit([1], max_new_tokens=2)
    cb.submit([2], max_new_tokens=2)
    with pytest.raises(QueueFullError):
        cb.submit([3], max_new_tokens=2)
    assert metrics.snapshot()["sequences_shed"] == 1
    while cb.step():
        pass


def test_shutdown_without_drain_fails_pending(engine):
    from dcnn_tpu.serve import ShutdownError
    cb = ContinuousBatcher(engine, start=False, clock=FakeClock())
    futs = [cb.submit(p, max_new_tokens=4) for p in PROMPTS[:3]]
    cb.shutdown(drain=False)
    for fut in futs:
        with pytest.raises(ShutdownError):
            fut.result(timeout=0)
    with pytest.raises(DrainingError):
        cb.submit([1], max_new_tokens=2)
    assert engine.pool.pages_in_use == 0


def test_threaded_drain_completes_everything(engine):
    """The threaded mode (the only sleep-ful decode test): submit, drain,
    every future resolves to the reference."""
    cb = ContinuousBatcher(engine, queue_capacity=8)
    futs = [cb.submit(p, max_new_tokens=4) for p in PROMPTS[:4]]
    cb.drain(timeout=60)
    for p, fut in zip(PROMPTS, futs):
        want = decode_reference(engine, p, max_new_tokens=4)
        assert np.array_equal(fut.result(timeout=5), want)
    assert cb.health_reason() is not None  # drained = not accepting


# ------------------------------------------------- page pool

def test_page_pool_geometry_and_allocation():
    pool = KVPagePool(num_layers=2, embed_dim=8, page_size=4, num_pages=6)
    assert pool.pages_for(0) == 0
    assert pool.pages_for(1) == 1
    assert pool.pages_for(4) == 1
    assert pool.pages_for(5) == 2
    assert pool.page_bytes == 2 * 2 * 4 * 8 * 4
    assert pool.ensure("a", 3) == 1
    assert pool.ensure("a", 3) == 1  # idempotent
    assert pool.ensure("a", 9) == 3
    assert pool.pages_in_use == 3 and pool.pages_free == 2
    t = pool.table("a", 4)
    assert t.dtype == np.int32 and t.shape == (4,)
    assert 0 not in t[:3]  # the null page is never allocated
    assert t[3] == 0  # padding IS the null page
    with pytest.raises(ValueError):
        pool.table("a", 2)  # table wider than the bucket = caller bug


def test_page_pool_all_or_nothing_and_recycle():
    pool = KVPagePool(num_layers=1, embed_dim=4, page_size=2, num_pages=4)
    pool.ensure("a", 4)  # 2 of 3 usable pages
    with pytest.raises(OutOfPagesError):
        pool.ensure("b", 4)  # needs 2, only 1 free
    assert pool.num_seq_pages("b") == 0  # nothing leaked
    assert pool.pages_free == 1
    assert pool.release("a") == 2
    assert pool.release("a") == 0  # unknown/already-released: no-op
    assert pool.ensure("b", 4) == 2  # recycled pages satisfy it now
    snap = pool.snapshot()
    assert snap["pages_in_use"] == 2 and snap["sequences"] == 1


def test_suggest_num_pages_defaults_on_cpu():
    # CPU backends report no memory stats -> the explicit default
    assert suggest_num_pages(1024, default=37) == 37
    with pytest.raises(ValueError):
        suggest_num_pages(0)
    with pytest.raises(ValueError):
        suggest_num_pages(1024, fraction=0.0)


# ------------------------------------------------- metrics

def test_decode_metrics_none_until_data():
    m = DecodeMetrics(clock=FakeClock())
    s = m.snapshot()
    assert s["ttft_p50_ms"] is None and s["slot_occupancy"] is None
    assert s["tokens"] == 0 and s["completions"] == 0


def test_decode_metrics_exact_under_fake_clock():
    clk = FakeClock()
    m = DecodeMetrics(clock=clk)
    m.record_submit()
    m.record_admit()
    clk.advance(0.25)
    m.record_ttft(0.25)
    for _ in range(4):
        m.record_token()
    m.record_step(2, 4)
    m.record_step(4, 4)
    m.record_pages(6)
    clk.advance(0.75)
    s = m.snapshot()
    assert s["ttft_p50_ms"] == 250.0 and s["ttft_p99_ms"] == 250.0
    assert s["slot_occupancy"] == 0.75
    assert s["tokens_per_sec"] == 4.0  # 4 tokens over 1.0s
    assert s["pages_in_use"] == 6


def test_decode_metrics_prometheus_surface():
    clk = FakeClock()
    m = DecodeMetrics(clock=clk)
    m.record_submit()
    m.record_token()
    m.record_ttft(0.1)
    m.record_step(1, 2)
    clk.advance(1.0)
    text = m.prometheus()
    for name in ("decode_tokens_total", "decode_sequences_submitted_total",
                 "decode_steps_total", "decode_active_slots",
                 "decode_pages_in_use", "decode_queue_depth",
                 "decode_ttft_seconds", "decode_admissions_total",
                 "decode_evictions_total", "decode_completions_total",
                 "decode_prefill_tokens_total", "decode_sequences_shed_total",
                 "decode_ttft_window_p50_ms", "decode_ttft_window_p99_ms",
                 "decode_slot_occupancy", "decode_tokens_per_sec"):
        assert f"\n{name}" in text or text.startswith(name), name
    assert text.endswith("\n")


# ------------------------------------------------- engine surface

def test_engine_bucket_math(engine):
    assert engine.bucket_sizes == [1, 2, 4]
    assert engine.page_buckets == [1, 2, 4]
    assert engine.bucket_for(3) == 4
    assert engine.page_bucket_for(0) == 1
    assert engine.page_bucket_for(3) == 4
    with pytest.raises(ValueError):
        engine.bucket_for(5)
    with pytest.raises(ValueError):
        engine.page_bucket_for(5)
    with pytest.raises(ValueError):  # unbucketed shape: typed, no retrace
        engine.run_step(np.zeros(3, np.int32), np.zeros(3, np.int32),
                        np.zeros((3, 1), np.int32), engine.pool.k,
                        engine.pool.v)


def test_engine_rejects_context_beyond_model(model, params):
    with pytest.raises(ValueError):
        DecodeEngine(model, params, max_slots=1, page_size=32,
                     max_pages_per_seq=2, aot_cache=False)  # 64 > 32


def test_engine_compile_stats_cover_lattice(engine):
    assert set(engine.compile_stats) == {
        (b, mp) for b in engine.bucket_sizes for mp in engine.page_buckets}
    for st in engine.compile_stats.values():
        assert st["compile_s"] >= 0


# ------------------------------------------------- example smoke

def test_serve_decode_example_imports():
    """Import smoke for examples/serve_decode.py (same isolation dance as
    the other example smokes: the examples dir must resolve `common`)."""
    import importlib

    ex_dir = os.path.join(REPO, "examples")
    saved_common = sys.modules.pop("common", None)
    sys.path.insert(0, ex_dir)
    try:
        mod = importlib.import_module("serve_decode")
        assert callable(mod.main)
    finally:
        sys.path.remove(ex_dir)
        sys.modules.pop("serve_decode", None)
        sys.modules.pop("common", None)
        if saved_common is not None:
            sys.modules["common"] = saved_common
