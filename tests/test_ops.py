"""Kernel-level numeric tests.

Reference analog: the kernel test suites (``cuda_kernels_test.cpp``,
``cuda_conv2d_ops_test.cpp`` …) which run each device kernel against a naive
reference implementation (SURVEY.md §4.2). Here numpy is the naive reference
and torch (CPU) is the cross-framework oracle for conv/pool/norm.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch
import torch.nn.functional as F

from dcnn_tpu.ops import (
    accuracy, avg_pool2d, batch_norm, conv2d, cross_entropy, elementwise as ew,
    group_norm, huber_loss, log_softmax_cross_entropy, mae_loss, max_pool2d,
    mse_loss, softmax_cross_entropy,
)
from dcnn_tpu.ops.conv import conv2d_bias_grad, conv2d_input_grad, conv2d_weight_grad
from dcnn_tpu.ops.losses import (
    cross_entropy_grad, huber_grad, log_softmax_cross_entropy_grad, mae_grad,
    mse_grad, softmax_cross_entropy_grad,
)


def test_elementwise_suite(rng):
    a = jnp.asarray(rng.normal(size=(4, 5)).astype(np.float32))
    b = jnp.asarray(rng.normal(size=(4, 5)).astype(np.float32)) + 3.0
    np.testing.assert_allclose(ew.add(a, b), np.asarray(a) + np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(ew.fmadd(a, b, a), np.asarray(a) * np.asarray(b) + np.asarray(a), rtol=1e-5)
    np.testing.assert_allclose(ew.fnmadd(a, b, a), np.asarray(a) - np.asarray(a) * np.asarray(b), rtol=1e-5)
    np.testing.assert_allclose(ew.axpy(2.5, a, b), 2.5 * np.asarray(a) + np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(ew.rsqrt(b), 1.0 / np.sqrt(np.asarray(b)), rtol=1e-5)
    np.testing.assert_allclose(ew.clamp(a, -0.5, 0.5), np.clip(np.asarray(a), -0.5, 0.5))
    np.testing.assert_allclose(ew.dot_product(a, a), np.vdot(np.asarray(a), np.asarray(a)), rtol=1e-5)
    np.testing.assert_allclose(ew.sum_squared_diff(a, b), np.sum((np.asarray(a) - np.asarray(b)) ** 2), rtol=1e-5)
    x = jnp.asarray(rng.normal(size=(2, 3, 4, 5)).astype(np.float32))
    np.testing.assert_array_equal(ew.nchw_to_cnhw(x), np.transpose(np.asarray(x), (1, 0, 2, 3)))
    np.testing.assert_array_equal(ew.cnhw_to_nchw(ew.nchw_to_cnhw(x)), np.asarray(x))


@pytest.mark.parametrize("stride,padding", [(1, 0), (1, 1), (2, 1), (2, 0)])
def test_conv2d_vs_torch(rng, stride, padding):
    x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    w = rng.normal(size=(5, 3, 3, 3)).astype(np.float32)
    b = rng.normal(size=(5,)).astype(np.float32)
    ours = conv2d(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), stride=stride, padding=padding)
    ref = F.conv2d(torch.from_numpy(x), torch.from_numpy(w), torch.from_numpy(b),
                   stride=stride, padding=padding).numpy()
    np.testing.assert_allclose(np.asarray(ours), ref, rtol=1e-4, atol=1e-5)


def test_conv2d_nhwc_matches_nchw(rng):
    x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    w = rng.normal(size=(4, 3, 3, 3)).astype(np.float32)
    out_nchw = conv2d(jnp.asarray(x), jnp.asarray(w), stride=1, padding=1)
    out_nhwc = conv2d(jnp.asarray(np.transpose(x, (0, 2, 3, 1))), jnp.asarray(w),
                      stride=1, padding=1, data_format="NHWC")
    np.testing.assert_allclose(np.transpose(np.asarray(out_nhwc), (0, 3, 1, 2)),
                               np.asarray(out_nchw), rtol=1e-4, atol=1e-5)


def test_conv2d_explicit_grads_match_autodiff(rng):
    """The explicit grad kernels must agree with autodiff — the analog of the
    reference testing CUDA kernels against the naive CPU path."""
    x = jnp.asarray(rng.normal(size=(2, 3, 6, 6)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(4, 3, 3, 3)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=(2, 4, 6, 6)).astype(np.float32))

    def loss(x_, w_):
        return jnp.sum(conv2d(x_, w_, stride=1, padding=1) * g)

    gx_auto, gw_auto = jax.grad(loss, argnums=(0, 1))(x, w)
    gw = conv2d_weight_grad(x, g, (3, 3), stride=1, padding=1)
    gx = conv2d_input_grad(w, g, x.shape, stride=1, padding=1)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_auto), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_auto), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(conv2d_bias_grad(g)),
                               np.asarray(g).sum(axis=(0, 2, 3)), rtol=1e-4)


def test_pools_vs_torch(rng):
    x = rng.normal(size=(2, 3, 8, 8)).astype(np.float32)
    xt = torch.from_numpy(x)
    np.testing.assert_allclose(
        np.asarray(max_pool2d(jnp.asarray(x), 2, 2)),
        F.max_pool2d(xt, 2, 2).numpy(), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(avg_pool2d(jnp.asarray(x), 2, 2)),
        F.avg_pool2d(xt, 2, 2).numpy(), rtol=1e-6)
    # padded avg with count_include_pad=True (reference semantics)
    np.testing.assert_allclose(
        np.asarray(avg_pool2d(jnp.asarray(x), 3, 2, 1)),
        F.avg_pool2d(xt, 3, 2, 1, count_include_pad=True).numpy(), rtol=1e-5)


def test_batch_norm_train_and_eval_vs_torch(rng):
    x = rng.normal(size=(4, 3, 5, 5)).astype(np.float32)
    gamma = rng.normal(size=(3,)).astype(np.float32)
    beta = rng.normal(size=(3,)).astype(np.float32)
    rm = np.zeros(3, np.float32)
    rv = np.ones(3, np.float32)

    y, new_m, new_v = batch_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
                                 jnp.asarray(rm), jnp.asarray(rv), training=True)
    bn = torch.nn.BatchNorm2d(3, eps=1e-5, momentum=0.1)
    with torch.no_grad():
        bn.weight.copy_(torch.from_numpy(gamma))
        bn.bias.copy_(torch.from_numpy(beta))
    bn.train()
    yt = bn(torch.from_numpy(x)).detach().numpy()
    np.testing.assert_allclose(np.asarray(y), yt, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(new_m), bn.running_mean.numpy(), rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_v), bn.running_var.numpy(), rtol=1e-4, atol=1e-5)

    # eval path uses running stats
    y_eval, m2, v2 = batch_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta),
                                new_m, new_v, training=False)
    bn.eval()
    np.testing.assert_allclose(np.asarray(y_eval),
                               bn(torch.from_numpy(x)).detach().numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(m2), np.asarray(new_m))


def test_batch_norm_one_pass_stats_stability(rng):
    """The one-pass sum/sumsq statistics are centered on running_mean
    (norm.py): with rm tracking the batch mean (steady state), variance stays
    accurate even at |mean|/std ~ 1e5 where the raw E[x2]-mean^2 form loses
    every significant bit."""
    x = (1000.0 + 0.01 * rng.normal(size=(16, 8, 8, 4))).astype(np.float32)
    c = 4
    ones = np.ones(c, np.float32)
    rm = np.full(c, 1000.0, np.float32)  # steady state: rm ~ batch mean
    y, nm, nv = batch_norm(jnp.asarray(x), jnp.asarray(ones),
                           jnp.asarray(np.zeros(c, np.float32)),
                           jnp.asarray(rm), jnp.asarray(ones),
                           training=True, momentum=1.0, data_format="NHWC")
    n = x.size // c
    true_var = x.reshape(-1, c).astype(np.float64).var(axis=0) * n / (n - 1)
    np.testing.assert_allclose(np.asarray(nv), true_var, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(nm), x.reshape(-1, c).mean(axis=0),
                               rtol=1e-6)
    # normalized output is standard-scaled (eps-dominated floor accepted)
    assert 0.5 < float(np.asarray(y).std()) <= 1.01


def test_group_norm_vs_torch(rng):
    x = rng.normal(size=(2, 6, 4, 4)).astype(np.float32)
    gamma = rng.normal(size=(6,)).astype(np.float32)
    beta = rng.normal(size=(6,)).astype(np.float32)
    y = group_norm(jnp.asarray(x), jnp.asarray(gamma), jnp.asarray(beta), num_groups=3)
    yt = F.group_norm(torch.from_numpy(x), 3, torch.from_numpy(gamma),
                      torch.from_numpy(beta), eps=1e-5).numpy()
    np.testing.assert_allclose(np.asarray(y), yt, rtol=1e-4, atol=1e-5)


def _onehot(labels, n):
    out = np.zeros((len(labels), n), np.float32)
    out[np.arange(len(labels)), labels] = 1.0
    return out


def test_losses_vs_torch(rng):
    logits = rng.normal(size=(8, 10)).astype(np.float32)
    labels = rng.integers(0, 10, size=8)
    onehot = _onehot(labels, 10)

    ours = softmax_cross_entropy(jnp.asarray(logits), jnp.asarray(onehot))
    ref = F.cross_entropy(torch.from_numpy(logits), torch.from_numpy(labels)).item()
    assert abs(float(ours) - ref) < 1e-5

    logp = np.asarray(jax.nn.log_softmax(jnp.asarray(logits)))
    ours_lsce = log_softmax_cross_entropy(jnp.asarray(logp), jnp.asarray(onehot))
    assert abs(float(ours_lsce) - ref) < 1e-5

    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits)))
    ours_ce = cross_entropy(jnp.asarray(probs), jnp.asarray(onehot))
    assert abs(float(ours_ce) - ref) < 1e-4

    pred = rng.normal(size=(8, 3)).astype(np.float32)
    target = rng.normal(size=(8, 3)).astype(np.float32)
    assert abs(float(mse_loss(jnp.asarray(pred), jnp.asarray(target))) -
               F.mse_loss(torch.from_numpy(pred), torch.from_numpy(target)).item()) < 1e-6
    assert abs(float(mae_loss(jnp.asarray(pred), jnp.asarray(target))) -
               F.l1_loss(torch.from_numpy(pred), torch.from_numpy(target)).item()) < 1e-6
    assert abs(float(huber_loss(jnp.asarray(pred), jnp.asarray(target))) -
               F.huber_loss(torch.from_numpy(pred), torch.from_numpy(target), delta=1.0).item()) < 1e-6


def test_loss_grads_match_autodiff(rng):
    """Explicit grad kernels (used by the pipeline coordinator to seed the
    backward stream, sync_pipeline_coordinator.cpp:144-156) must equal
    autodiff of the loss value."""
    logits = jnp.asarray(rng.normal(size=(4, 7)).astype(np.float32))
    onehot = jnp.asarray(_onehot(rng.integers(0, 7, size=4), 7))
    pairs = [
        (softmax_cross_entropy, softmax_cross_entropy_grad, logits),
        (mse_loss, mse_grad, logits),
        (mae_loss, mae_grad, logits),
        (huber_loss, huber_grad, logits),
    ]
    for loss_fn, grad_fn, pred in pairs:
        g_auto = jax.grad(lambda p, _fn=loss_fn: _fn(p, onehot))(pred)
        np.testing.assert_allclose(np.asarray(grad_fn(pred, onehot)), np.asarray(g_auto),
                                   rtol=1e-4, atol=1e-6)

    # The reference's CE/LogSoftmax-CE grad kernels are FUSED: they return the
    # end-to-end gradient at the logits (softmax jacobian folded in), not
    # ∂loss/∂input (loss_ops.cpp compute_crossentropy_gradient). Verify the
    # fused kernels against the logits-gradient of the composed function.
    g_logits = jax.grad(
        lambda z: log_softmax_cross_entropy(jax.nn.log_softmax(z), onehot))(logits)
    np.testing.assert_allclose(
        np.asarray(log_softmax_cross_entropy_grad(jax.nn.log_softmax(logits), onehot)),
        np.asarray(g_logits), rtol=1e-4, atol=1e-6)
    g_logits2 = jax.grad(
        lambda z: cross_entropy(jax.nn.softmax(z), onehot))(logits)
    np.testing.assert_allclose(
        np.asarray(cross_entropy_grad(jax.nn.softmax(logits), onehot)),
        np.asarray(g_logits2), rtol=1e-4, atol=1e-5)


def test_accuracy(rng):
    logits = np.zeros((4, 3), np.float32)
    logits[np.arange(4), [0, 1, 2, 0]] = 1.0
    onehot = _onehot(np.array([0, 1, 0, 0]), 3)
    assert float(accuracy(jnp.asarray(logits), jnp.asarray(onehot))) == 0.75
    assert float(accuracy(jnp.asarray(logits), jnp.asarray(np.array([0, 1, 0, 0])))) == 0.75
