"""Streaming feed path tests (data/streaming.py — VERDICT r3 missing #6):
shard-step numerics parity with manual base steps, double-buffered epoch
semantics, and geometry validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.data import StreamingDeviceDataset, make_shard_step, \
    train_streaming_epoch, one_hot
from dcnn_tpu.nn.builder import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train.trainer import create_train_state, make_train_step


def _model(n_classes=4, hw=8):
    return (SequentialBuilder(name="stream_cnn", data_format="NHWC")
            .input((hw, hw, 1))
            .conv2d(8, 3, padding=1).batchnorm().activation("relu")
            .flatten().dense(n_classes)
            .build())


def _blobs(n, hw=8, n_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n)
    base = (y[:, None, None, None] * 50 + 20).astype(np.float32)
    x = np.clip(base + rng.normal(0, 10, size=(n, hw, hw, 1)), 0, 255)
    return x.astype(np.uint8), y.astype(np.int64)


def test_shard_step_matches_manual_steps():
    """One shard dispatch == K manual base-step calls over the same
    permutation/rng derivation (same pattern the resident engine pins)."""
    x, y = _blobs(n=24)
    model = _model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts0 = create_train_state(model, opt, key)
    ts0b = create_train_state(model, opt, key)

    K, B = 3, 8
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=B, shard_batches=K)
    rng = jax.random.PRNGKey(7)
    xs = jnp.asarray(x)
    ys = jnp.asarray(y.astype(np.int32))
    ts1, mean_loss = step(ts0, xs, ys, rng, 0.05)

    kperm, kstep = jax.random.split(rng)
    idx = np.asarray(jax.random.permutation(kperm, K * B)).reshape(K, B)
    base = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    losses = []
    ts = ts0b
    for i in range(K):
        xb = jnp.asarray(x[idx[i]].astype(np.float32) / 255.0)
        yb = jnp.asarray(one_hot(y[idx[i]], 4))
        ts, loss, _ = base(ts, xb, yb, jax.random.fold_in(kstep, i), 0.05)
        losses.append(float(loss))

    assert float(mean_loss) == pytest.approx(np.mean(losses), abs=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_streaming_epoch_trains_and_covers_shards():
    x, y = _blobs(n=70, seed=1)            # 2 full shards of 32, 6 dropped
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    assert ds.num_shards == 2 and ds.steps_per_epoch == 8
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    losses = []
    for epoch in range(4):
        ts, loss = train_streaming_epoch(step, ts, ds,
                                         jax.random.PRNGKey(epoch), 0.05)
        losses.append(loss)
    assert losses[-1] < losses[0]          # separable blobs learn quickly
    # epoch shard membership rotates (remainder handling): two epochs'
    # shard contents differ
    s1 = [ys.tobytes() for _, ys in ds.shards()]
    s2 = [ys.tobytes() for _, ys in ds.shards()]
    assert s1 != s2


def test_streaming_geometry_validation():
    x, y = _blobs(n=30)
    with pytest.raises(ValueError, match="smaller than one shard"):
        StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    model = _model()
    step = make_shard_step(model, softmax_cross_entropy, SGD(0.05),
                           num_classes=4, batch_size=8, shard_batches=4)
    ts = create_train_state(model, SGD(0.05), jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="exactly"):
        step(ts, jnp.asarray(x[:24]), jnp.asarray(y[:24].astype(np.int32)),
             jax.random.PRNGKey(1), 0.05)


def test_streaming_producer_failure_propagates():
    """A producer-side failure (raising shards()) must surface as a
    re-raised exception in the consumer, not a silent hang or a missing
    epoch (review r5: the sentinel carries the exception)."""
    x, y = _blobs(n=70, seed=2)
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)

    class Boom(RuntimeError):
        pass

    def bad_selections():
        yield next(iter(ds.__class__.shard_selections(ds)))
        raise Boom("host feed died")
    ds.shard_selections = bad_selections
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    import time
    t0 = time.perf_counter()
    with pytest.raises(Boom, match="host feed died"):
        train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(1), 0.05)
    # must fail promptly (the old code would park 60 s in join or forever
    # in q.get)
    assert time.perf_counter() - t0 < 30.0


def _capture_step(fed):
    """A 'train step' that just records the host view of what was fed —
    the bit-identity probe for the worker-pool feed paths."""
    def step(ts, sx, sy, rng, lr):
        sx = jnp.concatenate(sx, 0) if isinstance(sx, (tuple, list)) else sx
        fed.append((np.asarray(sx).copy(), np.asarray(sy).copy()))
        return ts, jnp.float32(0.0)
    return step


def _run_streaming(x, y, *, workers=None, aug=None, pool=None, timeline=None):
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4,
                                seed=5)
    fed = []
    train_streaming_epoch(_capture_step(fed), {}, ds, jax.random.PRNGKey(0),
                          0.05, workers=workers, host_augment=aug,
                          worker_pool=pool, epoch=2, timeline=timeline)
    return fed


def test_streaming_workers_bit_identical_with_prep_timeline():
    """The workers= feed ships byte-identical shards to the serial path,
    and the timeline carries the per-shard worker-prep stats."""
    x, y = _blobs(n=256, seed=4)
    base = _run_streaming(x, y, workers=0)
    tl = []
    pooled = _run_streaming(x, y, workers=2, timeline=tl)
    assert len(base) == len(pooled) == 8
    for (a, b), (c, d) in zip(base, pooled):
        np.testing.assert_array_equal(a, c)
        np.testing.assert_array_equal(b, d)
    assert all("prep" in e for e in tl)
    assert {e["prep"]["worker"] for e in tl} <= {0, 1, "inline"}
    assert all(e["prep"]["prep_s"] >= 0 for e in tl)


def test_streaming_host_augment_pool_matches_serial():
    from dcnn_tpu.data import AugmentationBuilder

    x, y = _blobs(n=256, seed=5)
    aug = (AugmentationBuilder("NHWC").horizontal_flip(p=0.5)
           .random_crop(1, p=1.0).build())
    ser = _run_streaming(x, y, workers=0, aug=aug)
    par = _run_streaming(x, y, workers=3, aug=aug)
    plain = _run_streaming(x, y, workers=0)
    for (a, b), (c, d) in zip(ser, par):
        np.testing.assert_array_equal(a, c)
        np.testing.assert_array_equal(b, d)
    # augmentation actually changed the fed bytes vs the raw path
    assert not np.array_equal(ser[0][0], plain[0][0])


def test_streaming_worker_crash_mid_epoch_completes():
    """Acceptance: a worker crash mid-epoch degrades gracefully — the
    epoch completes bit-identically via inline fallback and the failure
    counter increments — proven under a FaultPlan trip point."""
    from dcnn_tpu.data import FeedWorkerPool
    from dcnn_tpu.obs import get_registry
    from dcnn_tpu.resilience import faults

    x, y = _blobs(n=256, seed=6)
    base = _run_streaming(x, y, workers=0)
    reg = get_registry()
    f0 = reg.counter("feed_worker_failures_total").value
    plan = faults.FaultPlan().arm("feed.prepare", at=1, times=1,
                                  exc=faults.InjectedCrash)
    with plan:
        pool = FeedWorkerPool(x, y, 32, num_workers=2, seed=5,
                              backend="thread", poll_s=0.02)
        try:
            got = _run_streaming(x, y, pool=pool)
            assert pool.alive_workers() == 1
        finally:
            pool.close()
    assert reg.counter("feed_worker_failures_total").value > f0
    assert len(got) == len(base)
    for (a, b), (c, d) in zip(base, got):
        np.testing.assert_array_equal(a, c)
        np.testing.assert_array_equal(b, d)


def test_streaming_unfenced_engine_rejected_with_pool():
    from dcnn_tpu.data import FeedWorkerPool, TransferEngine

    x, y = _blobs(n=256, seed=7)
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    pool = FeedWorkerPool(x, y, 32, num_workers=1, backend="thread",
                          poll_s=0.02)
    try:
        with TransferEngine(num_chunks=1, num_threads=1,
                            fence=False) as eng:
            with pytest.raises(ValueError, match="fenced"):
                train_streaming_epoch(_capture_step([]), {}, ds,
                                      jax.random.PRNGKey(0), 0.05,
                                      engine=eng, worker_pool=pool)
    finally:
        pool.close()


def test_streaming_consumer_failure_unblocks_producer():
    """If the training step raises, the producer thread must exit quickly
    (stop-event checked inside its blocking put) instead of pinning staged
    device buffers forever."""
    import threading

    x, y = _blobs(n=134, seed=3)   # 4 shards of 32
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)

    calls = {"n": 0}

    def bad_step(ts, sx, sy, rng, lr):
        calls["n"] += 1
        raise ValueError("consumer died")
    n0 = threading.active_count()
    with pytest.raises(ValueError, match="consumer died"):
        train_streaming_epoch(bad_step, ts, ds, jax.random.PRNGKey(1), 0.05)
    assert calls["n"] == 1
    # the producer must have exited (join succeeded inside the finally)
    import time
    deadline = time.time() + 10
    while threading.active_count() > n0 and time.time() < deadline:
        time.sleep(0.1)
    assert threading.active_count() <= n0
