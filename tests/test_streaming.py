"""Streaming feed path tests (data/streaming.py — VERDICT r3 missing #6):
shard-step numerics parity with manual base steps, double-buffered epoch
semantics, and geometry validation."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.data import StreamingDeviceDataset, make_shard_step, \
    train_streaming_epoch, one_hot
from dcnn_tpu.nn.builder import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train.trainer import create_train_state, make_train_step


def _model(n_classes=4, hw=8):
    return (SequentialBuilder(name="stream_cnn", data_format="NHWC")
            .input((hw, hw, 1))
            .conv2d(8, 3, padding=1).batchnorm().activation("relu")
            .flatten().dense(n_classes)
            .build())


def _blobs(n, hw=8, n_classes=4, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n)
    base = (y[:, None, None, None] * 50 + 20).astype(np.float32)
    x = np.clip(base + rng.normal(0, 10, size=(n, hw, hw, 1)), 0, 255)
    return x.astype(np.uint8), y.astype(np.int64)


def test_shard_step_matches_manual_steps():
    """One shard dispatch == K manual base-step calls over the same
    permutation/rng derivation (same pattern the resident engine pins)."""
    x, y = _blobs(n=24)
    model = _model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts0 = create_train_state(model, opt, key)
    ts0b = create_train_state(model, opt, key)

    K, B = 3, 8
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=B, shard_batches=K)
    rng = jax.random.PRNGKey(7)
    xs = jnp.asarray(x)
    ys = jnp.asarray(y.astype(np.int32))
    ts1, mean_loss = step(ts0, xs, ys, rng, 0.05)

    kperm, kstep = jax.random.split(rng)
    idx = np.asarray(jax.random.permutation(kperm, K * B)).reshape(K, B)
    base = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    losses = []
    ts = ts0b
    for i in range(K):
        xb = jnp.asarray(x[idx[i]].astype(np.float32) / 255.0)
        yb = jnp.asarray(one_hot(y[idx[i]], 4))
        ts, loss, _ = base(ts, xb, yb, jax.random.fold_in(kstep, i), 0.05)
        losses.append(float(loss))

    assert float(mean_loss) == pytest.approx(np.mean(losses), abs=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_streaming_epoch_trains_and_covers_shards():
    x, y = _blobs(n=70, seed=1)            # 2 full shards of 32, 6 dropped
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    assert ds.num_shards == 2 and ds.steps_per_epoch == 8
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    losses = []
    for epoch in range(4):
        ts, loss = train_streaming_epoch(step, ts, ds,
                                         jax.random.PRNGKey(epoch), 0.05)
        losses.append(loss)
    assert losses[-1] < losses[0]          # separable blobs learn quickly
    # epoch shard membership rotates (remainder handling): two epochs'
    # shard contents differ
    s1 = [ys.tobytes() for _, ys in ds.shards()]
    s2 = [ys.tobytes() for _, ys in ds.shards()]
    assert s1 != s2


def test_streaming_geometry_validation():
    x, y = _blobs(n=30)
    with pytest.raises(ValueError, match="smaller than one shard"):
        StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)
    model = _model()
    step = make_shard_step(model, softmax_cross_entropy, SGD(0.05),
                           num_classes=4, batch_size=8, shard_batches=4)
    ts = create_train_state(model, SGD(0.05), jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="exactly"):
        step(ts, jnp.asarray(x[:24]), jnp.asarray(y[:24].astype(np.int32)),
             jax.random.PRNGKey(1), 0.05)


def test_streaming_producer_failure_propagates():
    """A producer-side failure (raising shards()) must surface as a
    re-raised exception in the consumer, not a silent hang or a missing
    epoch (review r5: the sentinel carries the exception)."""
    x, y = _blobs(n=70, seed=2)
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)

    class Boom(RuntimeError):
        pass

    def bad_selections():
        yield next(iter(ds.__class__.shard_selections(ds)))
        raise Boom("host feed died")
    ds.shard_selections = bad_selections
    step = make_shard_step(model, softmax_cross_entropy, opt, num_classes=4,
                           batch_size=8, shard_batches=4)
    import time
    t0 = time.perf_counter()
    with pytest.raises(Boom, match="host feed died"):
        train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(1), 0.05)
    # must fail promptly (the old code would park 60 s in join or forever
    # in q.get)
    assert time.perf_counter() - t0 < 30.0


def test_streaming_consumer_failure_unblocks_producer():
    """If the training step raises, the producer thread must exit quickly
    (stop-event checked inside its blocking put) instead of pinning staged
    device buffers forever."""
    import threading

    x, y = _blobs(n=134, seed=3)   # 4 shards of 32
    model = _model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 4, batch_size=8, shard_batches=4)

    calls = {"n": 0}

    def bad_step(ts, sx, sy, rng, lr):
        calls["n"] += 1
        raise ValueError("consumer died")
    n0 = threading.active_count()
    with pytest.raises(ValueError, match="consumer died"):
        train_streaming_epoch(bad_step, ts, ds, jax.random.PRNGKey(1), 0.05)
    assert calls["n"] == 1
    # the producer must have exited (join succeeded inside the finally)
    import time
    deadline = time.time() + 10
    while threading.active_count() > n0 and time.time() < deadline:
        time.sleep(0.1)
    assert threading.active_count() <= n0
