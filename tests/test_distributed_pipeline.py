"""Cross-process pipeline: worker subprocesses + TCP coordinator.

Pins the multi-process pipeline to the in-process coordinator's numerics
(VERDICT r1 item 3): same model, same seed, same schedule must produce the
same losses/logits whether stages live in this process or in spawned worker
processes (reference deployment: ``network_worker.cpp`` +
``sync_pipeline_coordinator.cpp``, simulated by ``docker-compose.yml``).
"""

import os
import socket
import subprocess
import sys

import jax
import numpy as np
import pytest

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.parallel import (
    DistributedPipelineCoordinator, InProcessPipelineCoordinator,
    PipelineWorkerError,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_ports(n):
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        ports.append(s.getsockname()[1])
        socks.append(s)
    for s in socks:
        s.close()
    return ports


def _tiny_model():
    return (SequentialBuilder("dist_pipe_test")
            .input((3, 8, 8))
            .conv2d(4, 3, 1, 1).activation("relu")
            .conv2d(4, 3, 1, 1).activation("relu")
            .flatten()
            .dense(16).activation("relu")
            .dense(4)
            .build())


def _batch(rng, n=8):
    x = rng.normal(size=(n, 3, 8, 8)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=n)]
    return x, y


@pytest.fixture(scope="module")
def workers():
    """Two stage-worker subprocesses on free ports (CPU backend)."""
    ports = _free_ports(2)
    env = dict(os.environ)
    env["DCNN_PLATFORM"] = "cpu"
    env.pop("XLA_FLAGS", None)
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(ROOT, "examples", "network_worker.py"),
             "--port", str(p), "--platform", "cpu"],
            env=env, cwd=ROOT,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for p in ports
    ]
    yield [f"127.0.0.1:{p}" for p in ports], procs
    for pr in procs:
        if pr.poll() is None:
            pr.terminate()
        try:
            pr.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pr.kill()


@pytest.fixture(scope="module")
def coord(workers):
    addrs, _ = workers
    c = DistributedPipelineCoordinator(
        _tiny_model(), SGD(0.05, momentum=0.9), "softmax_crossentropy",
        workers=addrs, num_microbatches=2, track_load=True, timeout=180.0)
    c.deploy_stages(jax.random.PRNGKey(3))
    yield c
    c.shutdown()


def _reference_losses(schedule, n_batches=3):
    rng = np.random.default_rng(7)
    ref = InProcessPipelineCoordinator(
        _tiny_model(), SGD(0.05, momentum=0.9), "softmax_crossentropy",
        num_stages=2, num_microbatches=2)
    ref.deploy_stages(jax.random.PRNGKey(3))
    fn = (ref.train_batch_semi_async if schedule == "semi_async"
          else ref.train_batch_sync)
    out = []
    for b in range(n_batches):
        x, y = _batch(rng)
        loss, logits = fn(x, y, 0.05, jax.random.PRNGKey(100 + b))
        out.append((loss, np.asarray(logits)))
    return out


def test_sync_matches_in_process(coord):
    rng = np.random.default_rng(7)
    ref = _reference_losses("sync")
    for b, (ref_loss, ref_logits) in enumerate(ref):
        x, y = _batch(rng)
        loss, logits = coord.train_batch_sync(x, y, 0.05,
                                              jax.random.PRNGKey(100 + b))
        assert abs(loss - ref_loss) < 1e-5, (b, loss, ref_loss)
        np.testing.assert_allclose(logits, ref_logits, atol=1e-5)


def test_semi_async_after_sync_trains(coord):
    """Semi-async schedule across processes runs and reduces loss."""
    rng = np.random.default_rng(11)
    x, y = _batch(rng, n=16)
    losses = [coord.train_batch_semi_async(x, y, 0.05, jax.random.PRNGKey(b))[0]
              for b in range(6)]
    assert losses[-1] < losses[0]


def test_forward_only_and_load_reports(coord, rng):
    x, _ = _batch(rng)
    out = coord.forward_only(x)
    assert out.shape == (8, 4)
    reports = coord.collect_load_reports()
    assert len(reports) == 2
    assert all(r["forward_count"] > 0 for r in reports)


def test_health_check_heartbeat(coord):
    """HEALTH_CHECK round trip: every worker answers with vitals (the
    command the reference reserves but never wires)."""
    vitals = coord.health_check()
    assert [v["stage_id"] for v in vitals] == [0, 1]
    assert all(v["configured"] for v in vitals)
    # rss_kb is 0 on platforms without /proc/self/status; the protocol field
    # must exist either way
    assert all(v["rss_kb"] >= 0 for v in vitals)
    # repeatable (fresh nonce each time)
    assert len(coord.health_check()) == 2


def test_profiling_broadcast(coord):
    """PRINT_PROFILING round trip (VERDICT r3 missing #2): per-layer
    fwd/bwd tables arrive from BOTH workers, layer names match each stage's
    partition, and CLEAR_PROFILING resets the accumulation."""
    rng = np.random.default_rng(17)
    x, y = _batch(rng)
    coord.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(5))  # seed probes

    tables = coord.collect_profiling()
    assert [t["stage_id"] for t in tables] == [0, 1]
    all_names = []
    for t in tables:
        assert t["layers"], f"stage {t['stage_id']} returned an empty table"
        assert all(r["calls"] >= 1 for r in t["layers"])
        # timings are wall-clock µs of real fenced executions — positive
        assert all(r["fwd_us"] > 0 for r in t["layers"])
        assert all(r["bwd_us"] > 0 for r in t["layers"])
        all_names += [r["name"] for r in t["layers"]]
    # the union of stage tables is exactly the full model's layer set
    assert all_names == [l.name for l in _tiny_model().layers]

    # accumulation across requests, reset by CLEAR_PROFILING
    t2 = coord.collect_profiling()
    assert t2[0]["layers"][0]["calls"] > tables[0]["layers"][0]["calls"]
    coord.clear_profiling()
    t3 = coord.collect_profiling()
    assert t3[0]["layers"][0]["calls"] == 1

    # the formatter renders every stage's rows
    from dcnn_tpu.parallel.pipeline import format_profiling
    txt = format_profiling(t3)
    assert "stage" in txt and all_names[0] in txt and all_names[-1] in txt


def test_worker_error_reported_and_recoverable(coord):
    """A bad input shape must surface as PipelineWorkerError with the remote
    traceback, and the pipeline must keep working afterwards (abort clears
    stage caches/grads — VERDICT r1 weak #5)."""
    rng = np.random.default_rng(13)
    bad_x = rng.normal(size=(8, 3, 5, 5)).astype(np.float32)  # wrong H,W
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=8)]
    with pytest.raises(PipelineWorkerError):
        coord.train_batch_sync(bad_x, y, 0.05, jax.random.PRNGKey(0))
    # recovered: a good batch still trains
    x, y = _batch(rng)
    loss, _ = coord.train_batch_sync(x, y, 0.05, jax.random.PRNGKey(1))
    assert np.isfinite(loss)
