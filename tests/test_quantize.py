"""int8 PTQ tests (ops/quant.py + nn/quantize.py).

Beyond-reference feature (the reference has no quantized path). Contracts:
kernel-level int8 conv/GEMM agree with a numpy dequantized oracle exactly;
the quantized model tracks the float folded model closely on realistic
trained-ish weights (logit cosine + top-1 agreement, not exact equality —
int8 is lossy by design); configs/params round-trip through the factory and
checkpoint; training through a PTQ graph is refused.
"""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.nn import (
    QuantConv2DLayer, QuantDenseLayer, Sequential, SequentialBuilder,
    quantize_model,
)
from dcnn_tpu.ops import conv2d, conv2d_int8
from dcnn_tpu.ops import quant as quant_ops

from test_fold import _train_a_bit


def _quant_layer_count(layers):
    n = 0
    for l in layers:
        if isinstance(l, (QuantConv2DLayer, QuantDenseLayer)):
            n += 1
        if hasattr(l, "layers") and hasattr(l, "shortcut"):
            n += _quant_layer_count(l.layers) + _quant_layer_count(l.shortcut)
    return n


# ---------------------------------------------------------------- kernels

def test_quantize_symmetric_roundtrip_error_bound():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(64, 32)).astype(np.float32)) * 3.0
    s = quant_ops.tensor_scale(x)
    x_q = quant_ops.quantize_symmetric(x, s)
    assert x_q.dtype == jnp.int8
    # symmetric round-to-nearest: |x - s*q| <= s/2 everywhere in range
    err = np.abs(np.asarray(x) - np.asarray(s) * np.asarray(x_q, np.float32))
    assert err.max() <= float(s) / 2 + 1e-7


def test_tensor_scale_quantile_rejects_outlier():
    """One stray activation must not stretch the quantile-calibrated scale;
    bulk quantization error drops accordingly."""
    rng = np.random.default_rng(8)
    bulk = rng.normal(size=4095).astype(np.float32)
    x = jnp.asarray(np.concatenate([bulk, [1000.0]]))
    s_max = quant_ops.tensor_scale(x)
    s_q = quant_ops.tensor_scale(x, quantile=0.999)
    assert float(s_q) < float(s_max) / 50  # outlier rejected
    # mean bulk error under the quantile scale beats the absmax scale by
    # a wide margin (values past the quantile clip — that is the tradeoff)
    errs = {}
    for name, s in (("max", s_max), ("q", s_q)):
        xq = quant_ops.quantize_symmetric(jnp.asarray(bulk), s)
        errs[name] = np.abs(
            bulk - np.asarray(s) * np.asarray(xq, np.float32)).mean()
    assert errs["q"] < errs["max"] / 20, errs


def test_quantize_model_act_quantile_plumbs_through():
    model = (SequentialBuilder(name="qq", data_format="NHWC")
             .input((6, 6, 1))
             .conv2d(4, 3, padding=1).activation("relu").flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    calib = np.random.default_rng(10).normal(
        size=(16, 6, 6, 1)).astype(np.float32)
    calib[0, 0, 0, 0] = 1e4  # poison one calibration sample
    qm, qp_max, _ = quantize_model(model, ts.params, ts.state,
                                   jnp.asarray(calib))
    # 0.99 of 576 calib elements: the single poisoned element is safely
    # outside the quantile (0.999 would still interpolate into it)
    _, qp_q, _ = quantize_model(model, ts.params, ts.state,
                                jnp.asarray(calib), act_quantile=0.99)
    assert float(qp_q[0]["x_scale"]) < float(qp_max[0]["x_scale"]) / 10


def test_channel_scales_zero_channel_guard():
    w = jnp.zeros((4, 3, 3, 3), jnp.float32)
    s = quant_ops.channel_scales(w)
    assert np.all(np.asarray(s) > 0)
    w_q, _ = quant_ops.quantize_weight(w)
    assert np.all(np.asarray(w_q) == 0)


def test_conv2d_int8_matches_integer_oracle():
    """int8 conv must be EXACT integer arithmetic (int32 accumulate)."""
    rng = np.random.default_rng(1)
    x_q = jnp.asarray(rng.integers(-127, 128, (2, 4, 5, 5), dtype=np.int8))
    w_q = jnp.asarray(rng.integers(-127, 128, (3, 4, 3, 3), dtype=np.int8))
    got = conv2d_int8(x_q, w_q, stride=1, padding=1, data_format="NCHW")
    assert got.dtype == jnp.int32
    want = conv2d(jnp.asarray(x_q, jnp.float32), jnp.asarray(w_q, jnp.float32),
                  stride=1, padding=1, data_format="NCHW")
    np.testing.assert_array_equal(np.asarray(got),
                                  np.asarray(want).astype(np.int64))


def test_conv2d_int8_rejects_float():
    x = jnp.zeros((1, 1, 4, 4), jnp.float32)
    w = jnp.zeros((1, 1, 3, 3), jnp.int8)
    with pytest.raises(TypeError):
        conv2d_int8(x, w)


def test_dense_int8_matches_integer_oracle():
    rng = np.random.default_rng(2)
    x_q = jnp.asarray(rng.integers(-127, 128, (8, 16), dtype=np.int8))
    w_q = jnp.asarray(rng.integers(-127, 128, (5, 16), dtype=np.int8))
    got = quant_ops.dense_int8(x_q, w_q)
    assert got.dtype == jnp.int32
    want = np.asarray(x_q, np.int64) @ np.asarray(w_q, np.int64).T
    np.testing.assert_array_equal(np.asarray(got, np.int64), want)


# ---------------------------------------------------------------- transform

def _agreement(model, ts, qm, qp, qs, bs=16, seed=7):
    x = jnp.asarray(np.random.default_rng(seed).normal(
        size=(bs, *model.input_shape)).astype(np.float32))
    y0, _ = model.apply(ts.params, ts.state, x, training=False)
    y1, _ = qm.apply(qp, qs, x, training=False)
    y0, y1 = np.asarray(y0, np.float64), np.asarray(y1, np.float64)
    cos = (y0.ravel() @ y1.ravel()) / (
        np.linalg.norm(y0) * np.linalg.norm(y1) + 1e-12)
    top1 = float(np.mean(y0.argmax(-1) == y1.argmax(-1)))
    return cos, top1


def test_quantize_conv_bn_dense_model():
    model = (SequentialBuilder(name="qcbn", data_format="NHWC")
             .input((8, 8, 3))
             .conv2d(16, 3, padding=1).batchnorm().activation("relu")
             .conv2d(8, 3, padding=1, use_bias=False).batchnorm()
             .activation("relu")
             .maxpool2d(2).flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    calib = jnp.asarray(np.random.default_rng(3).normal(
        size=(32, 8, 8, 3)).astype(np.float32))
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib)
    assert _quant_layer_count(qm.layers) == 3  # 2 convs + 1 dense
    # folded-then-quantized: the bias-less second conv (index 2 after the
    # BN layers fold away) carries the BN shift as its bias
    assert "b" in qp[2] and qp[2]["w_q"].dtype == jnp.int8
    cos, top1 = _agreement(model, ts, qm, qp, qs)
    assert cos > 0.995, f"logit cosine {cos}"
    assert top1 >= 0.9, f"top-1 agreement {top1}"


def test_quantize_residual_recursion():
    from dcnn_tpu.models import create_resnet9_cifar10

    model = create_resnet9_cifar10("NHWC")
    ts = _train_a_bit(model, n_steps=3, bs=4)
    calib = jnp.asarray(np.random.default_rng(4).normal(
        size=(8, 32, 32, 3)).astype(np.float32))
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib)
    assert _quant_layer_count(qm.layers) >= 8  # all resnet9 convs + head
    cos, _ = _agreement(model, ts, qm, qp, qs, bs=8)
    assert cos > 0.98, f"logit cosine {cos}"


def test_quantize_without_fold():
    model = (SequentialBuilder(name="nofold", data_format="NHWC")
             .input((6, 6, 1))
             .conv2d(4, 3, padding=1).activation("relu").flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    calib = jnp.asarray(np.random.default_rng(5).normal(
        size=(16, 6, 6, 1)).astype(np.float32))
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib,
                                fold_bn=False)
    cos, _ = _agreement(model, ts, qm, qp, qs)
    assert cos > 0.995


def test_quantize_mha_classifier():
    """PTQ covers the attention family: the zoo's mha_classifier (MHA blocks
    inside ResidualBlocks) quantizes its projections w8a8 and tracks the
    float model."""
    from dcnn_tpu.models import create_mha_classifier
    from dcnn_tpu.nn import QuantMultiHeadAttentionLayer

    model = create_mha_classifier()
    ts = _train_a_bit(model, n_steps=3, bs=8)
    calib = jnp.asarray(np.random.default_rng(11).normal(
        size=(16, 32, 64)).astype(np.float32))
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib)

    def count_qmha(layers):
        n = 0
        for l in layers:
            if isinstance(l, QuantMultiHeadAttentionLayer):
                n += 1
            if hasattr(l, "layers") and hasattr(l, "shortcut"):
                n += count_qmha(l.layers) + count_qmha(l.shortcut)
        return n

    assert count_qmha(qm.layers) == 2
    # per-projection int8 weights + the two calibrated activation scales
    mha_p = qp[0]["main"][0]
    assert mha_p["wq_q"].dtype == jnp.int8
    assert float(mha_p["x_scale"]) > 0 and float(mha_p["o_scale"]) > 0
    cos, top1 = _agreement(model, ts, qm, qp, qs, bs=16)
    assert cos > 0.98, f"logit cosine {cos}"

    # zero-template init (checkpoint restoration path) + config round-trip
    qm2 = Sequential.from_config(qm.get_config())
    tp, _ = qm2.init(jax.random.PRNGKey(0))
    t_mha = tp[0]["main"][0]
    assert t_mha["wo_q"].shape == mha_p["wo_q"].shape
    assert not np.any(np.asarray(t_mha["wo_q"]))


def test_quantized_model_refuses_training():
    model = (SequentialBuilder(name="ro", data_format="NHWC")
             .input((6, 6, 1))
             .conv2d(4, 3, padding=1).flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    calib = jnp.ones((4, 6, 6, 1), jnp.float32)
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib)
    with pytest.raises(ValueError, match="inference-only"):
        qm.apply(qp, qs, calib, training=True)
    # init is a deterministic ZERO template (the load_checkpoint /
    # pipeline-worker materialization path), never random weights
    tp, _ = qm.init(jax.random.PRNGKey(0))
    assert tp[0]["w_q"].dtype == jnp.int8
    assert not np.any(np.asarray(tp[0]["w_q"]))
    assert tp[0]["w_q"].shape == qp[0]["w_q"].shape


def test_quantized_config_and_checkpoint_roundtrip(tmp_path):
    from dcnn_tpu.train import load_checkpoint, save_checkpoint

    model = (SequentialBuilder(name="ckpt", data_format="NHWC")
             .input((8, 8, 3))
             .conv2d(8, 3, padding=1, stride=2).batchnorm()
             .activation("relu").flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    calib = jnp.asarray(np.random.default_rng(6).normal(
        size=(8, 8, 8, 3)).astype(np.float32))
    qm, qp, qs = quantize_model(model, ts.params, ts.state, calib)

    # config round-trip through the factory (registry keys quant_conv2d /
    # quant_dense), matching the pipeline worker materialization path
    qm2 = Sequential.from_config(qm.get_config())
    assert [l.type_name for l in qm2.layers] == \
        [l.type_name for l in qm.layers]
    assert qm2.layers[0].stride == qm.layers[0].stride

    # checkpoint round-trip: int8 params are ordinary npz entries
    path = os.path.join(tmp_path, "q")
    save_checkpoint(path, qm, qp, qs)
    _, qp2, qs2, _, _, _ = load_checkpoint(path)
    np.testing.assert_array_equal(np.asarray(qp2[0]["w_q"]),
                                  np.asarray(qp[0]["w_q"]))
    assert qp2[0]["w_q"].dtype == jnp.int8

    x = jnp.asarray(np.random.default_rng(9).normal(
        size=(4, 8, 8, 3)).astype(np.float32))
    y0, _ = qm.apply(qp, qs, x, training=False)
    y1, _ = qm2.apply(qp2, qs2, x, training=False)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-6)


def test_quantize_does_not_mutate_original():
    model = (SequentialBuilder(name="orig_q", data_format="NHWC")
             .input((8, 8, 3))
             .conv2d(4, 3, padding=1, use_bias=False).batchnorm()
             .flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    w_before = np.asarray(ts.params[0]["w"]).copy()
    quantize_model(model, ts.params, ts.state,
                   jnp.ones((4, 8, 8, 3), jnp.float32))
    np.testing.assert_array_equal(np.asarray(ts.params[0]["w"]), w_before)
    assert not model.layers[0].use_bias


def test_quantize_passes_through_unregistered_custom_layer():
    """A custom layer whose type is outside the factory registry must pass
    through quantization as a (copied) pass-through, not abort the whole
    model with "unknown layer type" (ADVICE r5): PTQ only needs to rebuild
    the layers it quantizes."""
    from dcnn_tpu.nn import (DenseLayer, FlattenLayer, Sequential,
                             StatelessLayer)
    from dcnn_tpu.nn.factory import LayerFactory

    class DoubleLayer(StatelessLayer):
        type_name = "test_unregistered_double"

        def forward(self, x, *, training=False, rng=None):
            return x * 2.0

    assert "test_unregistered_double" not in LayerFactory.registered()

    model = Sequential([FlattenLayer(), DoubleLayer(), DenseLayer(10)],
                       name="custom_q", input_shape=(4, 4, 1))
    params, state = model.init(jax.random.PRNGKey(0), (4, 4, 1))
    calib = jnp.asarray(np.random.default_rng(11).normal(
        size=(16, 4, 4, 1)).astype(np.float32))
    qm, qp, qs = quantize_model(model, params, state, calib)

    # the custom layer survives as a pass-through COPY (the returned graph
    # stays independent of the original), the dense still quantizes
    assert isinstance(qm.layers[1], DoubleLayer)
    assert qm.layers[1] is not model.layers[1]
    assert isinstance(qm.layers[2], QuantDenseLayer)

    x = jnp.asarray(np.random.default_rng(12).normal(
        size=(4, 4, 4, 1)).astype(np.float32))
    y_f, _ = model.apply(params, state, x, training=False)
    y_q, _ = qm.apply(qp, qs, x, training=False)
    cos = float(np.sum(np.asarray(y_f) * np.asarray(y_q)) /
                (np.linalg.norm(y_f) * np.linalg.norm(y_q) + 1e-12))
    assert cos > 0.99, f"quantized custom-layer model diverged: cosine {cos}"
