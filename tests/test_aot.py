"""AOT executable cache tests (dcnn_tpu/aot/).

Contracts pinned here:

- key derivation is stable across processes and sensitive to donation /
  precision / config (an under-keyed hit would serve the wrong program);
- commit/lookup round-trips through the checksum MANIFEST; a bit-flipped
  payload is quarantined and transparently recompiled (the
  CheckpointManager torn-checkpoint contract, applied to executables);
- a stale-version entry (jaxlib bump) is a miss, never a crash;
- keep-K GC retains the most-recently-used entries;
- ``aot.commit`` / ``aot.load`` FaultPlan points drive the failure paths
  (crash-before-commit leaves no entry; a load fault degrades to a
  recompile);
- the warm path is bit-identical to the compiled path, and — the
  acceptance headline — an executable compiled and cached in process A
  is loaded in fresh process B with **no compile events** and
  bit-identical outputs, for both the train step and a serve engine's
  bucket set;
- Trainer / InferenceEngine / pipeline wiring is on only when asked, and
  default runs see the exact pre-subsystem behavior.
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.aot import (ExecutableCache, WarmCallable, cache_key, digest,
                          maybe_warm, warm_or_compile)
from dcnn_tpu.aot.keys import backend_fingerprint, callable_id
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.obs.registry import MetricsRegistry
from dcnn_tpu.optim import Adam, SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.resilience import FaultPlan
from dcnn_tpu.resilience.faults import InjectedCrash
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import Trainer, create_train_state

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model():
    return (SequentialBuilder("aot_t").input((6,))
            .dense(16).activation("relu").dense(4).build())


def _data(batch=8):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(batch, 6)).astype(np.float32))
    y = jnp.asarray(np.eye(4, dtype=np.float32)[rng.integers(0, 4, batch)])
    return x, y


def _step_setup():
    model = _model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, softmax_cross_entropy, opt)
    cfg = digest({"model": model.get_config(), "opt": opt.get_config(),
                  "loss": callable_id(softmax_cross_entropy)})
    return model, opt, ts, step, cfg


def _warm(step, ts, x, y, cache, cfg, reg=None):
    return warm_or_compile(step, ts, x, y, jax.random.PRNGKey(1), 1e-3,
                           cache=cache, what="train", config=cfg,
                           donate=(0,), registry=reg)


# ------------------------------------------------------------------- keys

def test_cache_key_stable_and_sensitive():
    _, _, ts, _, cfg = _step_setup()
    x, y = _data()
    args = (ts, x, y, jax.random.PRNGKey(1), 1e-3)
    k1, m1 = cache_key(args, config=cfg, donate=(0,))
    k2, _ = cache_key(args, config=cfg, donate=(0,))
    assert k1 == k2
    # donation, config, and avals each change the key
    assert cache_key(args, config=cfg, donate=())[0] != k1
    assert cache_key(args, config="other", donate=(0,))[0] != k1
    x2, y2 = _data(batch=4)
    assert cache_key((ts, x2, y2, jax.random.PRNGKey(1), 1e-3),
                     config=cfg, donate=(0,))[0] != k1
    # the material records what went in (MANIFEST debuggability)
    assert m1["donate"] == [0] and m1["config"] == cfg
    assert m1["fingerprint"]["jaxlib"]


def test_callable_id_has_no_addresses():
    cid = callable_id(softmax_cross_entropy)
    assert "0x" not in cid and "softmax_cross_entropy" in cid
    import functools
    cid2 = callable_id(functools.partial(softmax_cross_entropy))
    assert "partial" in cid2 and "0x" not in cid2


def test_callable_id_bound_method_folds_in_owner_config():
    """Two SequentialStageStacks whose blocks differ must key their bound
    ``stage_fn`` differently even when every param shape coincides — the
    qualname alone is 'SequentialStageStack.stage_fn' for both, and a
    collision would silently serve the wrong architecture."""
    from dcnn_tpu.nn.layers import GroupNormLayer
    from dcnn_tpu.parallel import SequentialStageStack

    shape = (16, 8, 8)
    s4 = SequentialStageStack(GroupNormLayer(4, 16), 2, shape)
    s8 = SequentialStageStack(GroupNormLayer(8, 16), 2, shape)
    i4, i8 = callable_id(s4.stage_fn), callable_id(s8.stage_fn)
    assert i4 != i8
    assert "0x" not in i4 and "0x" not in i8
    # stable across instances with the same config (no per-object state)
    s4b = SequentialStageStack(GroupNormLayer(4, 16), 2, shape)
    assert callable_id(s4b.stage_fn) == i4


def test_train_step_key_material_lr_invariant_and_shared():
    """The canonical train-step key (keys.train_step_key_material) must
    hit across base-lr variants (lr is a runtime argument, not key
    material — a prewarmed fleet must not pay the compile wall for
    Adam(3e-4) vs Adam(1e-3)) while still splitting on kind and on real
    optimizer hyperparameters."""
    from dcnn_tpu.aot.keys import optimizer_id, train_step_key_material

    model = _model()
    m1 = train_step_key_material(model, Adam(1e-3), softmax_cross_entropy)
    m2 = train_step_key_material(model, Adam(3e-4), softmax_cross_entropy)
    assert digest(m1) == digest(m2)
    assert "learning_rate" not in json.dumps(m1)
    m3 = train_step_key_material(model, Adam(1e-3), softmax_cross_entropy,
                                 kind="multi_step")
    assert digest(m1) != digest(m3)
    m4 = train_step_key_material(model, Adam(1e-3, beta1=0.8),
                                 softmax_cross_entropy)
    assert digest(m1) != digest(m4)
    assert digest(m1) != digest(train_step_key_material(
        model, SGD(1e-3), softmax_cross_entropy))
    # optimizer_id falls back to type identity without get_config
    class Bare:
        pass
    assert "Bare" in optimizer_id(Bare())


# ------------------------------------------------------- cache mechanics

def test_untrusted_root_refused(tmp_path):
    """Hits pickle.loads executable bytes, so a root another user could
    have planted or can SWAP OUT must be refused (callers degrade to
    uncached compilation): world-writable non-sticky mode — on the root
    or any ancestor — or foreign ownership. Sticky world-writable
    (``/tmp`` itself, 1777) is trusted: the kernel forbids other users
    renaming entries they don't own. Fresh roots are created 0700."""
    ww = tmp_path / "ww"
    ww.mkdir()
    os.chmod(ww, 0o777)
    with pytest.raises(ValueError, match="world-writable"):
        ExecutableCache(str(ww))
    # a 0700 root under a world-writable NON-sticky parent: the parent's
    # owner can replace the whole root between check and load
    nested = ww / "aot"
    with pytest.raises(ValueError, match="world-writable"):
        ExecutableCache(str(nested))
    # ... but under a sticky 1777 parent (the /tmp shape) it is fine
    sticky = tmp_path / "sticky"
    sticky.mkdir()
    os.chmod(sticky, 0o1777)
    ExecutableCache(str(sticky / "aot"))
    if hasattr(os, "getuid") and os.getuid() == 0:
        foreign = tmp_path / "foreign"
        foreign.mkdir()
        os.chown(foreign, 12345, 12345)
        with pytest.raises(ValueError, match="owned by uid"):
            ExecutableCache(str(foreign))
    fresh = tmp_path / "fresh"
    ExecutableCache(str(fresh))
    assert (os.stat(fresh).st_mode & 0o777) == 0o700


def test_commit_lookup_roundtrip_and_idempotence(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"), registry=MetricsRegistry())
    assert cache.commit("k" * 64, b"payload-bytes", {"what": "t"})
    assert cache.lookup("k" * 64) == b"payload-bytes"
    # second writer loses gracefully (a sibling process already committed)
    assert not cache.commit("k" * 64, b"payload-bytes", {"what": "t"})
    rows = cache.entries()
    assert len(rows) == 1 and rows[0]["what"] == "t"
    assert rows[0]["hits"] == 1  # the lookup above


def test_bitflip_quarantined_and_recompiled(tmp_path):
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path / "aot"), registry=reg)
    _, opt, ts, step, cfg = _step_setup()
    x, y = _data()
    exe, info = _warm(step, ts, x, y, cache, cfg, reg)
    assert info["committed"] and not info["hit"]
    key = info["key"]
    # corrupt the committed payload in place (the canonical fixture)
    FaultPlan(seed=3).bit_flip(str(tmp_path / "aot" / key / "payload.bin"))
    ts2 = create_train_state(_model(), opt, jax.random.PRNGKey(0))
    step2 = make_train_step(_model(), softmax_cross_entropy, opt)
    with pytest.warns(UserWarning, match="quarantined"):
        exe2, info2 = _warm(step2, ts2, x, y, cache, cfg, reg)
    # transparently recompiled AND recommitted under the same key
    assert not info2["hit"] and info2["committed"] and info2["key"] == key
    assert reg.snapshot().get("aot_quarantined_total") == 1
    corrupt = [n for n in os.listdir(tmp_path / "aot")
               if n.startswith("corrupt-")]
    assert len(corrupt) == 1
    # and the fresh entry now hits
    ts3 = create_train_state(_model(), opt, jax.random.PRNGKey(0))
    step3 = make_train_step(_model(), softmax_cross_entropy, opt)
    _, info3 = _warm(step3, ts3, x, y, cache, cfg, reg)
    assert info3["hit"]


def test_stale_version_entry_is_miss_not_crash(tmp_path):
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path / "aot"), registry=reg)
    _, opt, ts, step, cfg = _step_setup()
    x, y = _data()
    _, info = _warm(step, ts, x, y, cache, cfg, reg)
    key = info["key"]
    # doctor the MANIFEST to look like another jaxlib's entry (a
    # hand-copied cache dir / key-schema drift simulation)
    mp = tmp_path / "aot" / key / "MANIFEST.json"
    m = json.loads(mp.read_text())
    m["material"]["fingerprint"]["jaxlib"] = "0.0.0"
    mp.write_text(json.dumps(m))
    assert cache.lookup(key, fingerprint=backend_fingerprint()) is None
    assert reg.snapshot().get("aot_stale_total") == 1
    # skipped, not quarantined: the entry is intact for its own version
    assert (tmp_path / "aot" / key / "payload.bin").exists()


def test_keep_k_gc_retains_most_recently_used(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"), keep=10)
    for i in range(5):
        assert cache.commit(f"key{i:061d}", f"p{i}".encode(), {"what": "t"})
    cache.lookup("key" + "0" * 61)  # bump entry 0's LRU position
    removed = cache.gc(keep=2)
    assert removed == 3
    kept = {r["key"] for r in cache.entries()}
    assert "key" + "0" * 61 in kept and len(kept) == 2


def test_gc_validates_keep(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"))
    with pytest.raises(ValueError):
        cache.gc(keep=0)
    with pytest.raises(ValueError):
        ExecutableCache(str(tmp_path / "aot2"), keep=0)


# ------------------------------------------------------------ fault points

def test_commit_crash_leaves_no_entry(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"))
    _, _, ts, step, cfg = _step_setup()
    x, y = _data()
    with FaultPlan().arm("aot.commit", exc=InjectedCrash):
        with pytest.raises(InjectedCrash):
            _warm(step, ts, x, y, cache, cfg)
    assert cache.entries() == []
    # after the "restart": a clean run commits normally
    ts2 = create_train_state(_model(), Adam(1e-3), jax.random.PRNGKey(0))
    step2 = make_train_step(_model(), softmax_cross_entropy, Adam(1e-3))
    _, info = _warm(step2, ts2, x, y, cache, cfg)
    assert info["committed"]


def test_commit_fault_degrades_to_uncached_compile(tmp_path):
    reg = MetricsRegistry()
    cache = ExecutableCache(str(tmp_path / "aot"), registry=reg)
    _, _, ts, step, cfg = _step_setup()
    x, y = _data()
    with FaultPlan().arm("aot.commit"):
        exe, info = _warm(step, ts, x, y, cache, cfg, reg)
    assert not info["committed"] and cache.entries() == []
    assert reg.snapshot().get("aot_fallback_total") == 1
    out = exe(ts, x, y, jax.random.PRNGKey(1), 1e-3)
    assert np.isfinite(float(out[1]))  # the executable still works


def test_load_fault_degrades_to_recompile(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"))
    _, _, ts, step, cfg = _step_setup()
    x, y = _data()
    _, info = _warm(step, ts, x, y, cache, cfg)
    assert info["committed"]
    ts2 = create_train_state(_model(), Adam(1e-3), jax.random.PRNGKey(0))
    step2 = make_train_step(_model(), softmax_cross_entropy, Adam(1e-3))
    with FaultPlan().arm("aot.load"):
        exe, info2 = _warm(step2, ts2, x, y, cache, cfg)
    assert not info2["hit"]  # the fault made it a miss, not an error
    out = exe(ts2, x, y, jax.random.PRNGKey(1), 1e-3)
    assert np.isfinite(float(out[1]))


# ------------------------------------------------------------ warm dispatch

def test_warm_hit_is_bit_identical_to_compiled(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"))
    _, opt, _, step, cfg = _step_setup()
    x, y = _data()
    ts_a = create_train_state(_model(), opt, jax.random.PRNGKey(0))
    exe_a, info_a = _warm(step, ts_a, x, y, cache, cfg)
    out_a = exe_a(ts_a, x, y, jax.random.PRNGKey(1), 1e-3)
    step_b = make_train_step(_model(), softmax_cross_entropy, opt)
    ts_b = create_train_state(_model(), opt, jax.random.PRNGKey(0))
    exe_b, info_b = _warm(step_b, ts_b, x, y, cache, cfg)
    assert not info_a["hit"] and info_b["hit"]
    out_b = exe_b(ts_b, x, y, jax.random.PRNGKey(1), 1e-3)
    for a, b in zip(jax.tree_util.tree_leaves(out_a),
                    jax.tree_util.tree_leaves(out_b)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_warm_callable_dispatch_and_fallthrough(tmp_path):
    cache = ExecutableCache(str(tmp_path / "aot"))
    _, opt, _, step, cfg = _step_setup()
    wc = WarmCallable(step, cache, what="train", config=cfg, donate=(0,))
    x, y = _data()
    ts = create_train_state(_model(), opt, jax.random.PRNGKey(0))
    ts, loss, _ = wc(ts, x, y, jax.random.PRNGKey(1), 1e-3)
    assert wc.last_info["committed"]
    # a second signature (different batch) falls through per-signature
    x2, y2 = _data(batch=4)
    ts, loss2, _ = wc(ts, x2, y2, jax.random.PRNGKey(1), 1e-3)
    assert len(wc._exes) == 2
    assert np.isfinite(float(loss)) and np.isfinite(float(loss2))
    # .lower forwards (the pipeline HLO tests rely on this shape)
    assert hasattr(wc, "lower")


def test_maybe_warm_is_passthrough_when_disabled(monkeypatch):
    monkeypatch.delenv("AOT_CACHE", raising=False)
    jitted = jax.jit(lambda a: a + 1)
    assert maybe_warm(jitted, what="x") is jitted


def test_trainer_wiring_warm_starts(tmp_path):
    from dcnn_tpu.core.config import TrainingConfig

    root = str(tmp_path)
    cfg = TrainingConfig(aot_cache_dir=root, snapshot_dir=None)
    x, y = _data()
    t1 = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
    assert isinstance(t1.train_step, WarmCallable)
    ts1 = create_train_state(t1.model, t1.optimizer, jax.random.PRNGKey(0))
    ts1, loss1, _ = t1.train_step(ts1, x, y, jax.random.PRNGKey(1), 0.05)
    assert t1.train_step.last_info["committed"]
    # a "restarted" trainer warm-starts from the committed executable
    t2 = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
    ts2 = create_train_state(t2.model, t2.optimizer, jax.random.PRNGKey(0))
    ts2, loss2, _ = t2.train_step(ts2, x, y, jax.random.PRNGKey(1), 0.05)
    assert t2.train_step.last_info["hit"]
    np.testing.assert_array_equal(np.asarray(loss1), np.asarray(loss2))
    # default config: the plain jitted step, no wrapper
    t3 = Trainer(_model(), SGD(0.05), "softmax_crossentropy",
                 TrainingConfig(snapshot_dir=None))
    assert not isinstance(t3.train_step, WarmCallable)


def test_engine_buckets_hit_across_rebuilds(tmp_path):
    from dcnn_tpu.serve.engine import InferenceEngine

    cache = ExecutableCache(str(tmp_path / "aot"))
    model = _model()
    params, state = model.init(jax.random.PRNGKey(0))
    eng1 = InferenceEngine.from_model(model, params, state, fold=False,
                                      max_batch=4, warmup=False,
                                      aot_cache=cache)
    assert all("aot_hit" in s for s in eng1.compile_stats.values())
    eng2 = InferenceEngine.from_model(model, params, state, fold=False,
                                      max_batch=4, warmup=False,
                                      aot_cache=cache)
    assert all(s["aot_hit"] for s in eng2.compile_stats.values())
    x = np.asarray(_data(batch=3)[0])
    np.testing.assert_array_equal(np.asarray(eng1.infer(x)),
                                  np.asarray(eng2.infer(x)))
    # DIFFERENT weights must not hit the first engine's entries
    params2, state2 = model.init(jax.random.PRNGKey(9))
    eng3 = InferenceEngine.from_model(model, params2, state2, fold=False,
                                      max_batch=4, warmup=False,
                                      aot_cache=cache)
    assert not any(s["aot_hit"] for s in eng3.compile_stats.values())


def test_engine_refuses_cache_without_weights_digest(tmp_path):
    from dcnn_tpu.serve.engine import InferenceEngine

    cache = ExecutableCache(str(tmp_path / "aot"))
    model = _model()
    params, state = model.init(jax.random.PRNGKey(0))

    def apply_fn(x):
        return model.apply(params, state, x, training=False)[0]

    with pytest.warns(UserWarning, match="aot_config"):
        eng = InferenceEngine(apply_fn, model.input_shape, max_batch=2,
                              warmup=False, aot_cache=cache)
    assert not any("aot_hit" in s for s in eng.compile_stats.values())
    assert cache.entries() == []


def test_engine_default_is_uncached(monkeypatch):
    from dcnn_tpu.serve.engine import InferenceEngine

    monkeypatch.delenv("AOT_CACHE", raising=False)
    model = _model()
    params, state = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine.from_model(model, params, state, fold=False,
                                     max_batch=2, warmup=False)
    assert not any("aot_hit" in s for s in eng.compile_stats.values())


def test_compiled_pipeline_dispatcher_with_cache(tmp_path, monkeypatch):
    from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
    from dcnn_tpu.nn import Conv2DLayer, GroupNormLayer, ResidualBlock
    from dcnn_tpu.parallel.compiled_pipeline import (
        SequentialStageStack, make_compiled_pipeline_train_step,
        shard_stacked)

    monkeypatch.setenv("AOT_CACHE", str(tmp_path))
    S, MB = 2, 2
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    block = ResidualBlock(layers=[Conv2DLayer(2, 3, 1, 1, name="c0"),
                                  GroupNormLayer(2, name="g0")],
                          shortcut=[], activation="relu")
    stack = SequentialStageStack(block, S, (2, 4, 4))
    params = stack.init(jax.random.PRNGKey(0))
    rng = np.random.default_rng(0)
    mb_x = jnp.asarray(rng.normal(size=(MB, 2, 2, 4, 4)).astype(np.float32))
    mb_y = jnp.asarray(rng.normal(size=(MB, 2, 2, 4, 4)).astype(np.float32))
    loss_fn = lambda p, t: jnp.mean((p - t) ** 2)  # noqa: E731

    def one(opt):
        step = make_compiled_pipeline_train_step(
            stack.stage_fn, loss_fn, opt, S, MB, mesh)
        ps = shard_stacked(params, mesh)
        _, _, loss, _ = step(ps, opt.init(ps), mb_x, mb_y, jnp.float32(0.05))
        return float(loss)

    # two independently-built dispatchers (second may deserialize from
    # cache or fall back if the sharded executable can't serialize on
    # this backend — both paths must be numerically identical)
    l1, l2 = one(SGD(0.05)), one(SGD(0.05))
    assert l1 == l2 and np.isfinite(l1)


def test_elastic_solo_with_cache_matches_plain(tmp_path):
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data.loader import ArrayDataLoader, one_hot

    rng = np.random.default_rng(0)
    x = rng.normal(size=(32, 6)).astype(np.float32)
    y = one_hot(rng.integers(0, 4, 32), 4)

    def run(aot_root):
        cfg = TrainingConfig(
            epochs=1, learning_rate=0.05, seed=3, snapshot_dir=None,
            elastic=True, elastic_rank=0, elastic_microbatches=1,
            elastic_heartbeat_s=0.0, aot_cache_dir=aot_root)
        t = Trainer(_model(), SGD(0.05), "softmax_crossentropy", cfg)
        ts = create_train_state(t.model, t.optimizer,
                                jax.random.PRNGKey(cfg.seed))
        return t.fit(ts, ArrayDataLoader(x, y, batch_size=16, seed=7))

    plain = run(None)
    warm1 = run(str(tmp_path))   # seeds the cache
    warm2 = run(str(tmp_path))   # consumes it
    for a, b, c in zip(jax.tree_util.tree_leaves(plain.params),
                       jax.tree_util.tree_leaves(warm1.params),
                       jax.tree_util.tree_leaves(warm2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        np.testing.assert_array_equal(np.asarray(a), np.asarray(c))


# ------------------------------------------------------------------ CLI

def test_cli_list_gc_json(tmp_path, capsys):
    from dcnn_tpu.aot.__main__ import main

    root = str(tmp_path)
    cache = ExecutableCache(os.path.join(root, "aot"))
    _, _, ts, step, cfg = _step_setup()
    x, y = _data()
    _warm(step, ts, x, y, cache, cfg)

    assert main(["--dir", root, "--json"]) == 0
    report = json.loads(capsys.readouterr().out)
    assert len(report["entries"]) == 1
    row = report["entries"][0]
    assert row["what"] == "train" and row["size"] > 0
    assert row["avals"].startswith("f32[")

    assert main(["--dir", root]) == 0  # human listing renders
    assert "train" in capsys.readouterr().out

    assert main(["--dir", root, "--gc", "--keep", "1", "--json"]) == 0
    assert json.loads(capsys.readouterr().out)["removed"] == 0

    assert main(["--dir", root, "--prewarm", "no-such-model"]) == 1
    assert "prewarm failed" in capsys.readouterr().err


def test_cli_prewarm_zoo_model(tmp_path, capsys):
    from dcnn_tpu.aot.__main__ import main

    root = str(tmp_path)
    rc = main(["--dir", root, "--prewarm", "mnist_cnn", "--max-batch", "2",
               "--json"])
    out = capsys.readouterr().out
    if rc != 0:
        pytest.skip(f"zoo prewarm unavailable here: {out}")
    report = json.loads(out)
    assert report["prewarm"]["buckets"] == [1, 2]
    # second prewarm hits every bucket
    assert main(["--dir", root, "--prewarm", "mnist_cnn", "--max-batch",
                 "2", "--json"]) == 0
    report2 = json.loads(capsys.readouterr().out)
    assert all(s.get("aot_hit")
               for s in report2["prewarm"]["bucket_stats"].values())


# -------------------------------------------- the acceptance round trip

_SUBPROC = textwrap.dedent("""
    import json, os, sys
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import jax, jax.numpy as jnp
    sys.path.insert(0, {repo!r})
    from dcnn_tpu.aot import ExecutableCache, digest, warm_or_compile
    from dcnn_tpu.aot.keys import callable_id
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.obs.registry import MetricsRegistry
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.serve.engine import InferenceEngine
    from dcnn_tpu.train import make_train_step
    from dcnn_tpu.train.trainer import create_train_state

    cache_dir, out_path = sys.argv[1], sys.argv[2]
    reg = MetricsRegistry()
    cache = ExecutableCache(cache_dir, registry=reg)
    model = (SequentialBuilder("aot_rt").input((6,))
             .dense(16).activation("relu").dense(4).build())
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, softmax_cross_entropy, opt)
    cfg = digest({{"model": model.get_config(), "opt": opt.get_config(),
                   "loss": callable_id(softmax_cross_entropy)}})
    rng0 = np.random.default_rng(0)
    x = jnp.asarray(rng0.normal(size=(8, 6)).astype(np.float32))
    y = jnp.asarray(np.eye(4, dtype=np.float32)[rng0.integers(0, 4, 8)])
    exe, info = warm_or_compile(step, ts, x, y, jax.random.PRNGKey(1),
                                1e-3, cache=cache, what="train",
                                config=cfg, donate=(0,), registry=reg)
    new_ts, loss, logits = exe(ts, x, y, jax.random.PRNGKey(1), 1e-3)
    flat_params = np.concatenate(
        [np.asarray(l, np.float32).ravel()
         for l in jax.tree_util.tree_leaves(new_ts.params)])

    # serve bucket set over the same weights
    params, state = model.init(jax.random.PRNGKey(0))
    eng = InferenceEngine.from_model(model, params, state, fold=False,
                                     max_batch=4, warmup=False,
                                     aot_cache=cache, registry=reg)
    serve_logits = np.asarray(eng.infer(np.asarray(x[:3])))
    snap = reg.snapshot()
    json.dump({{
        "train_hit": info["hit"],
        "train_key": info["key"],
        "serve_hits": sum(1 for s in eng.compile_stats.values()
                          if s.get("aot_hit")),
        "serve_buckets": len(eng.bucket_sizes),
        "compile_total": int(snap.get("compile_total", 0)),
        "aot_hits_total": int(snap.get("aot_hits_total", 0)),
        "loss": float(loss),
        "flat_params": flat_params.tolist(),
        "serve_logits": serve_logits.tolist(),
    }}, open(out_path, "w"))
""")


def test_subprocess_round_trip_bit_identical_no_recompile(tmp_path):
    """Acceptance: compile+commit in process A; a FRESH process B loads
    the executables with ZERO compile events and produces bit-identical
    train-step params/loss and serve logits — for the train step and the
    whole serve bucket set."""
    cache_dir = str(tmp_path / "aot")
    script = _SUBPROC.format(repo=REPO)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("AOT_CACHE", None)

    def run(tag):
        out = str(tmp_path / f"{tag}.json")
        r = subprocess.run([sys.executable, "-c", script, cache_dir, out],
                           capture_output=True, text=True, env=env,
                           timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        with open(out) as f:
            return json.load(f)
    a = run("a")
    b = run("b")
    # process A compiled (train step + every bucket); B compiled NOTHING
    assert not a["train_hit"]
    assert b["train_hit"]
    assert b["serve_hits"] == b["serve_buckets"] == a["serve_buckets"]
    assert a["compile_total"] > 0
    assert b["compile_total"] == 0          # no retrace-to-compile in B
    assert b["aot_hits_total"] == 1 + b["serve_buckets"]
    assert b["train_key"] == a["train_key"]  # cross-process key stability
    # bit-identical results
    assert a["loss"] == b["loss"]
    np.testing.assert_array_equal(np.asarray(a["flat_params"]),
                                  np.asarray(b["flat_params"]))
    np.testing.assert_array_equal(np.asarray(a["serve_logits"]),
                                  np.asarray(b["serve_logits"]))
