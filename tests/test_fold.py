"""Inference-time BatchNorm folding tests (nn/fold.py).

The folded model must reproduce the original eval-mode outputs to float
tolerance on models with realistic (non-identity) running statistics,
including residual blocks and bias-less convolutions.
"""

import numpy as np

import jax
import jax.numpy as jnp

from dcnn_tpu.nn import BatchNormLayer, SequentialBuilder, fold_batchnorm
from dcnn_tpu.optim import Adam
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train.trainer import create_train_state, make_train_step


def _train_a_bit(model, n_steps=4, n_classes=10, bs=8):
    """Run a few real train steps so BN running stats are non-trivial."""
    opt = Adam(1e-2)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    rng = np.random.default_rng(0)
    shape = (bs, *model.input_shape)
    for i in range(n_steps):
        x = jnp.asarray(rng.normal(size=shape).astype(np.float32))
        y = jnp.asarray(np.eye(n_classes, dtype=np.float32)[
            rng.integers(0, n_classes, size=bs)])
        ts, _, _ = step(ts, x, y, jax.random.fold_in(jax.random.PRNGKey(1), i),
                        1e-2)
    return ts


def _check_fold(model, n_classes=10, bs=4, atol=2e-5):
    ts = _train_a_bit(model, n_classes=n_classes)
    folded, fp, fs = fold_batchnorm(model, ts.params, ts.state)

    x = jnp.asarray(np.random.default_rng(7).normal(
        size=(bs, *model.input_shape)).astype(np.float32))
    y0, _ = model.apply(ts.params, ts.state, x, training=False)
    y1, _ = folded.apply(fp, fs, x, training=False)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-4, atol=atol)
    return folded, fp, fs


def test_fold_conv_bn_chain():
    model = (SequentialBuilder(name="cbn", data_format="NHWC")
             .input((8, 8, 3))
             .conv2d(16, 3, padding=1).batchnorm().activation("relu")
             .conv2d(8, 3, padding=1, use_bias=False).batchnorm()
             .activation("relu")
             .flatten().dense(10)
             .build())
    folded, fp, fs = _check_fold(model)
    assert not any(isinstance(l, BatchNormLayer) for l in folded.layers)
    # bias-less conv gained the BN shift as a bias
    assert "b" in fp[2]


def test_fold_dense_bn():
    model = (SequentialBuilder(name="dbn", data_format="NHWC")
             .input((6, 6, 1))
             .flatten().dense(32).batchnorm().activation("relu").dense(10)
             .build())
    folded, _, _ = _check_fold(model)
    assert not any(isinstance(l, BatchNormLayer) for l in folded.layers)


def test_fold_residual_recursion():
    from dcnn_tpu.models import create_resnet9_cifar10

    model = create_resnet9_cifar10("NHWC")
    folded, fp, fs = _check_fold(model, bs=2, atol=5e-4)

    def count_bn(layers):
        n = 0
        for l in layers:
            if isinstance(l, BatchNormLayer):
                n += 1
            if hasattr(l, "layers") and hasattr(l, "shortcut"):
                n += count_bn(l.layers) + count_bn(l.shortcut)
        return n

    assert count_bn(folded.layers) == 0


def test_fold_keeps_unpaired_bn():
    """BN after pooling has no foldable predecessor and must survive."""
    model = (SequentialBuilder(name="ubn", data_format="NHWC")
             .input((8, 8, 3))
             .maxpool2d(2).batchnorm().flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    folded, fp, fs = fold_batchnorm(model, ts.params, ts.state)
    assert any(isinstance(l, BatchNormLayer) for l in folded.layers)
    x = jnp.asarray(np.random.default_rng(7).normal(
        size=(4, 8, 8, 3)).astype(np.float32))
    y0, _ = model.apply(ts.params, ts.state, x, training=False)
    y1, _ = folded.apply(fp, fs, x, training=False)
    np.testing.assert_allclose(np.asarray(y1), np.asarray(y0),
                               rtol=1e-5, atol=1e-5)


def test_fold_does_not_mutate_original():
    model = (SequentialBuilder(name="orig", data_format="NHWC")
             .input((8, 8, 3))
             .conv2d(4, 3, padding=1, use_bias=False).batchnorm()
             .flatten().dense(10)
             .build())
    ts = _train_a_bit(model)
    w_before = np.asarray(ts.params[0]["w"]).copy()
    n_layers = len(model.layers)
    fold_batchnorm(model, ts.params, ts.state)
    assert len(model.layers) == n_layers
    np.testing.assert_array_equal(np.asarray(ts.params[0]["w"]), w_before)
    assert not model.layers[0].use_bias
