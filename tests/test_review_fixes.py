"""Regression tests for review findings."""

import jax
import jax.numpy as jnp
import numpy as np

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD, WarmupCosineAnnealing
from dcnn_tpu.ops.losses import mse_loss, softmax_cross_entropy
from dcnn_tpu.parallel import InProcessPipelineCoordinator, make_data_parallel_train_step
from dcnn_tpu.core.mesh import make_mesh
from dcnn_tpu.parallel.data_parallel import replicate, shard_batch
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import create_train_state

KEY = jax.random.PRNGKey(0)


def test_warmup_cosine_equal_steps_no_crash():
    s = WarmupCosineAnnealing(0.1, warmup_steps=10, total_steps=10)
    lrs = [s.step() for _ in range(12)]
    assert all(np.isfinite(lrs))


def test_microbatch_step_handles_indivisible_batch():
    model = SequentialBuilder("m").input((4,)).dense(3).build()
    opt = SGD(0.1)
    ts = create_train_state(model, opt, KEY)
    step = make_train_step(model, softmax_cross_entropy, opt,
                           num_microbatches=4, donate=False)
    # 10 % 4 != 0 → falls back to single microbatch instead of crashing,
    # and warns at trace time (BN statistics semantics change)
    import warnings

    x = jax.random.normal(KEY, (10, 4))
    y = jax.nn.one_hot(jnp.arange(10) % 3, 3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ts, loss, logits = step(ts, x, y, KEY, 0.1)
    assert any("not divisible" in str(x.message) for x in w)
    assert np.isfinite(float(loss)) and logits.shape == (10, 3)


def test_data_parallel_2d_input():
    model = SequentialBuilder("mlp").input((8,)).dense(4).build()
    opt = SGD(0.1)
    mesh = make_mesh((8,), ("data",))
    ts = create_train_state(model, opt, KEY)
    from dcnn_tpu.train.trainer import TrainState
    ts = TrainState(replicate(ts.params, mesh), replicate(ts.state, mesh),
                    replicate(ts.opt_state, mesh), replicate(ts.step, mesh))
    step = make_data_parallel_train_step(model, mse_loss, opt, mesh)
    x = jax.random.normal(KEY, (16, 8))
    y = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    xs, ys = shard_batch((x, y), mesh)
    ts, loss, _ = step(ts, xs, ys, KEY, 0.1)
    assert np.isfinite(float(loss))


def test_attention_rejects_3d_mask():
    """ADVICE r2 #5: a (B, Sq, Sk) mask silently broadcast head-aligned when
    B == H; both attention entry points must reject rank-3 masks."""
    import pytest

    from dcnn_tpu.ops.attention import attention, blockwise_attention

    q = jax.random.normal(KEY, (2, 2, 8, 4))
    bad = jnp.ones((2, 8, 8), bool)
    with pytest.raises(ValueError, match="3-D attention masks"):
        attention(q, q, q, mask=bad)
    with pytest.raises(ValueError, match="3-D attention masks"):
        blockwise_attention(q, q, q, mask=bad)
    with pytest.raises(ValueError, match="rank 5"):
        attention(q, q, q, mask=jnp.ones((1, 2, 2, 8, 8), bool))
    # rank-2 and rank-4 still accepted
    ok2 = attention(q, q, q, mask=jnp.ones((8, 8), bool))
    ok4 = blockwise_attention(q, q, q, mask=jnp.ones((2, 1, 8, 8), bool))
    assert ok2.shape == q.shape and ok4.shape == q.shape


def test_blockwise_attention_retraced_on_precision_switch():
    """ADVICE r2 #4: parity<->fast switches must hit different jit cache
    entries (fp32 inputs hash identically, so the mode is a static key)."""
    from dcnn_tpu.core.precision import get_precision_mode, set_precision
    from dcnn_tpu.ops.attention import _blockwise_attention_jit, blockwise_attention

    q = jax.random.normal(KEY, (1, 1, 32, 16))
    cache = _blockwise_attention_jit._jitted._cache_size
    mode0 = get_precision_mode()
    try:
        set_precision("parity")
        blockwise_attention(q, q, q)
        n0 = cache()
        blockwise_attention(q, q, q)
        assert cache() == n0  # same mode: cached
        set_precision("fast")
        blockwise_attention(q, q, q)
        assert cache() == n0 + 1  # re-traced
    finally:
        set_precision(mode0)


def test_chunked_first_chunk_scheduler_metric_is_none():
    """ADVICE r2 #1: metric-driven schedulers must not see a spurious 0.0
    loss from the first chunk of a chunked epoch."""
    from dcnn_tpu.optim.schedulers import ReduceLROnPlateau
    from dcnn_tpu.train.trainer import Trainer, TrainingConfig

    model = SequentialBuilder("m").input((4,)).dense(3).build()
    sched = ReduceLROnPlateau(0.1, patience=0, factor=0.5, threshold=0.0)
    cfg = TrainingConfig(epochs=1, batch_size=4, scheduler_step="batch",
                         steps_per_dispatch=2, progress_interval=0)
    tr = Trainer(model, SGD(0.1), "softmax_crossentropy", cfg, sched)
    ts = create_train_state(model, SGD(0.1), KEY)
    rng = np.random.default_rng(0)
    # one [K=2, B=4, 4] chunk; with the old 0.0 first-chunk metric the
    # plateau scheduler records best=0.0 and every later real loss counts
    # as "no improvement"
    xs = rng.normal(size=(2, 4, 4)).astype(np.float32)
    ys = np.eye(3, dtype=np.float32)[rng.integers(0, 3, size=(2, 4))]
    class Probe(ReduceLROnPlateau):
        metrics = []

        def _compute_lr(self, metric):
            Probe.metrics.append(metric)
            return super()._compute_lr(metric)

    sched = Probe(0.1, patience=0, factor=0.5, threshold=0.0)
    tr.scheduler = sched
    # two chunks in one epoch: chunk 0 must feed None×K (no loss exists
    # yet); chunk 1 must feed the running loss ONCE then None — K-1
    # duplicate metrics would count spurious "no improvement" plateau steps
    tr._train_epoch_chunked(ts, [(xs, ys), (xs, ys)], KEY)
    assert Probe.metrics[:2] == [None, None]
    assert Probe.metrics[2] is not None and np.isfinite(Probe.metrics[2])
    assert Probe.metrics[3] is None
    assert np.isfinite(sched.best) and sched.bad_epochs == 0


def test_pipeline_loss_grad_correct_through_log_softmax():
    """A model ENDING in log-softmax trained with logsoftmax_crossentropy via
    the pipeline must match single-device autodiff — guards against the
    double-softmax-jacobian bug (the coordinator must seed backward with the
    true dL/d(output), not the reference's fused kernel)."""
    def build():
        return (SequentialBuilder("ls").input((6,))
                .dense(8, name="d0").activation("relu")
                .dense(4, name="d1").log_softmax().build())

    model = build()
    coord = InProcessPipelineCoordinator(model, SGD(0.1), "logsoftmax_crossentropy",
                                         num_stages=2, num_microbatches=2)
    coord.deploy_stages(KEY)

    ref_model = build()
    opt = SGD(0.1)
    ts = create_train_state(ref_model, opt, KEY)
    from dcnn_tpu.ops.losses import log_softmax_cross_entropy
    step = make_train_step(ref_model, log_softmax_cross_entropy, opt,
                           num_microbatches=2, donate=False)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 6)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=8)]
    for _ in range(2):
        loss_p, _ = coord.train_batch_sync(x, y, 0.1)
        ts, loss_r, _ = step(ts, jnp.asarray(x), jnp.asarray(y), KEY, 0.1)
        np.testing.assert_allclose(loss_p, float(loss_r), rtol=1e-5)
    got, _ = coord.gathered_params()
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
