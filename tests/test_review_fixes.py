"""Regression tests for review findings."""

import jax
import jax.numpy as jnp
import numpy as np

from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD, WarmupCosineAnnealing
from dcnn_tpu.ops.losses import mse_loss, softmax_cross_entropy
from dcnn_tpu.parallel import InProcessPipelineCoordinator, make_data_parallel_train_step
from dcnn_tpu.core.mesh import make_mesh
from dcnn_tpu.parallel.data_parallel import replicate, shard_batch
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import create_train_state

KEY = jax.random.PRNGKey(0)


def test_warmup_cosine_equal_steps_no_crash():
    s = WarmupCosineAnnealing(0.1, warmup_steps=10, total_steps=10)
    lrs = [s.step() for _ in range(12)]
    assert all(np.isfinite(lrs))


def test_microbatch_step_handles_indivisible_batch():
    model = SequentialBuilder("m").input((4,)).dense(3).build()
    opt = SGD(0.1)
    ts = create_train_state(model, opt, KEY)
    step = make_train_step(model, softmax_cross_entropy, opt,
                           num_microbatches=4, donate=False)
    # 10 % 4 != 0 → falls back to single microbatch instead of crashing,
    # and warns at trace time (BN statistics semantics change)
    import warnings

    x = jax.random.normal(KEY, (10, 4))
    y = jax.nn.one_hot(jnp.arange(10) % 3, 3)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        ts, loss, logits = step(ts, x, y, KEY, 0.1)
    assert any("not divisible" in str(x.message) for x in w)
    assert np.isfinite(float(loss)) and logits.shape == (10, 3)


def test_data_parallel_2d_input():
    model = SequentialBuilder("mlp").input((8,)).dense(4).build()
    opt = SGD(0.1)
    mesh = make_mesh((8,), ("data",))
    ts = create_train_state(model, opt, KEY)
    from dcnn_tpu.train.trainer import TrainState
    ts = TrainState(replicate(ts.params, mesh), replicate(ts.state, mesh),
                    replicate(ts.opt_state, mesh), replicate(ts.step, mesh))
    step = make_data_parallel_train_step(model, mse_loss, opt, mesh)
    x = jax.random.normal(KEY, (16, 8))
    y = jax.random.normal(jax.random.PRNGKey(1), (16, 4))
    xs, ys = shard_batch((x, y), mesh)
    ts, loss, _ = step(ts, xs, ys, KEY, 0.1)
    assert np.isfinite(float(loss))


def test_pipeline_loss_grad_correct_through_log_softmax():
    """A model ENDING in log-softmax trained with logsoftmax_crossentropy via
    the pipeline must match single-device autodiff — guards against the
    double-softmax-jacobian bug (the coordinator must seed backward with the
    true dL/d(output), not the reference's fused kernel)."""
    def build():
        return (SequentialBuilder("ls").input((6,))
                .dense(8, name="d0").activation("relu")
                .dense(4, name="d1").log_softmax().build())

    model = build()
    coord = InProcessPipelineCoordinator(model, SGD(0.1), "logsoftmax_crossentropy",
                                         num_stages=2, num_microbatches=2)
    coord.deploy_stages(KEY)

    ref_model = build()
    opt = SGD(0.1)
    ts = create_train_state(ref_model, opt, KEY)
    from dcnn_tpu.ops.losses import log_softmax_cross_entropy
    step = make_train_step(ref_model, log_softmax_cross_entropy, opt,
                           num_microbatches=2, donate=False)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(8, 6)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, size=8)]
    for _ in range(2):
        loss_p, _ = coord.train_batch_sync(x, y, 0.1)
        ts, loss_r, _ = step(ts, jnp.asarray(x), jnp.asarray(y), KEY, 0.1)
        np.testing.assert_allclose(loss_p, float(loss_r), rtol=1e-5)
    got, _ = coord.gathered_params()
    for a, b in zip(jax.tree_util.tree_leaves(got),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)
