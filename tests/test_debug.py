"""Debug-mode tests (VERDICT r1 #10; reference ENABLE_DEBUG ASan build,
``CMakeLists.txt:22,30-32``)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.debug import checked, debug_mode


def test_debug_mode_catches_nan():
    @jax.jit
    def f(x):
        return jnp.log(x)  # log(-1) -> nan

    with debug_mode():
        with pytest.raises(FloatingPointError, match="[Nn]a[Nn]"):
            f(jnp.asarray(-1.0)).block_until_ready()
    # restored afterwards: same computation silently yields nan
    assert jnp.isnan(f(jnp.asarray(-1.0)))


def test_debug_mode_restores_flags_on_error():
    prev = jax.config.jax_debug_nans
    try:
        with debug_mode():
            raise RuntimeError("boom")
    except RuntimeError:
        pass
    assert jax.config.jax_debug_nans == prev


def test_checked_step_locates_nan():
    from jax.experimental import checkify

    def step(x, y):
        return x / y  # 0/0 -> nan

    safe = checked(step)
    out = safe(jnp.asarray(1.0), jnp.asarray(2.0))
    np.testing.assert_allclose(out, 0.5)
    with pytest.raises(checkify.JaxRuntimeError, match="division by zero|nan"):
        safe(jnp.asarray(0.0), jnp.asarray(0.0))


def test_checked_train_step_on_model():
    """A full train step wrapped in checkify: poisoned input raises a located
    error instead of training on garbage."""
    from dcnn_tpu.nn import SequentialBuilder
    from dcnn_tpu.ops.losses import get_loss
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.train import make_train_step
    from dcnn_tpu.train.trainer import create_train_state
    from jax.experimental import checkify

    model = (SequentialBuilder("dbg").input((1, 4, 4))
             .conv2d(2, 3, 1, 1).activation("relu").flatten().dense(3).build())
    opt = SGD(0.1)
    step = checked(make_train_step(model, get_loss("softmax_crossentropy"),
                                   opt, jit=False))
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    x = np.random.default_rng(0).normal(size=(4, 1, 4, 4)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[[0, 1, 2, 0]]
    ts, loss, _ = step(ts, jnp.asarray(x), jnp.asarray(y),
                       jax.random.PRNGKey(1), 0.1)
    assert np.isfinite(float(loss))

    x_bad = x.copy()
    x_bad[0, 0, 0, 0] = np.inf
    with pytest.raises(checkify.JaxRuntimeError):
        step(ts, jnp.asarray(x_bad), jnp.asarray(y), jax.random.PRNGKey(1), 0.1)


def test_trainer_config_enables_debug():
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.models import create_mnist_trainer
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.train import Trainer

    prev = jax.config.jax_debug_nans
    try:
        Trainer(create_mnist_trainer(), Adam(1e-3), "softmax_crossentropy",
                config=TrainingConfig(debug=True))
        assert jax.config.jax_debug_nans is True
    finally:
        jax.config.update("jax_debug_nans", prev)
        jax.config.update("jax_enable_checks", False)


def test_config_env_debug(monkeypatch):
    from dcnn_tpu.core.config import TrainingConfig

    monkeypatch.setenv("DCNN_DEBUG", "1")
    assert TrainingConfig.load_from_env().debug is True
    monkeypatch.setenv("DCNN_DEBUG", "0")
    assert TrainingConfig.load_from_env().debug is False
