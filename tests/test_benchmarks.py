"""Benchmark-suite smoke tests (VERDICT r1 #7; reference
``benchmarks/gemm_benchmark.cpp:16-50`` correctness-gate pattern)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))


def test_check_match_gate():
    from common import check_match

    ok, err = check_match(np.ones(4), np.ones(4) + 1e-7, 1e-5)
    assert ok and isinstance(ok, bool) and err < 1e-5
    ok, _ = check_match(np.ones(4), np.ones(4) + 1.0, 1e-5)
    assert not ok
    ok, err = check_match(np.ones(4), np.ones(5), 1e-5)
    assert not ok and err == float("inf")


def test_serialization_section_runs_and_gates():
    import bench_serialization

    os.environ["BENCH_TINY"] = "1"
    try:
        doc = bench_serialization.run()
    finally:
        os.environ.pop("BENCH_TINY", None)
    assert doc["all_correct"] is True
    names = {r["name"] for r in doc["results"]}
    assert {"checkpoint_save", "checkpoint_load"} <= names
    assert any(n.startswith("compress_") for n in names)
    # machine-readable: every row JSON-serializable
    import json

    json.dumps(doc)


def test_time_chained_roofline_gate(monkeypatch):
    """The return contract: ALWAYS (seconds, sane) — sane=True when no
    roofline gate fired (ADVICE r5: the old polymorphic bare-float return
    invited silent tuple-as-number bugs) — and an implied FLOP rate above
    1.05x peak is retried then flagged sane=False rather than silently
    returned (the guard behind the int8 e2e rows; see RESULTS.md
    measurement-spread postmortem). The backend is pinned to the CPU
    per-dispatch fallback so the forced-insane case never chases the TPU
    noise-floor escalation (minutes on a real chip for a trivial op)."""
    import jax
    import jax.numpy as jnp

    from common import dep_feed, time_chained

    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    x = jnp.ones((8, 8), jnp.float32)
    op = lambda a: a * 2.0

    dt, sane = time_chained(op, (x,), dep_feed(0), length=4)
    assert isinstance(dt, float) and dt > 0
    assert sane is True

    # absurdly high peak -> any measurement is sane
    dt, sane = time_chained(op, (x,), dep_feed(0), length=4,
                            roofline=(1.0, 1e30))
    assert sane is True and dt > 0
    # peak=None skips the check but keeps the tuple shape
    dt, sane = time_chained(op, (x,), dep_feed(0), length=4,
                            roofline=(1e30, None))
    assert sane is True
    # absurdly low peak -> implied rate always "impossible": retried, then
    # flagged, never silently returned as a bare float
    dt, sane = time_chained(op, (x,), dep_feed(0), length=4,
                            roofline=(1e30, 1.0))
    assert sane is False and dt > 0


def test_e2e_chain_length_contract(monkeypatch):
    """Both branches pinned explicitly (the real backend varies by host):
    TPU gets the long jitter-proof chain unless tiny mode; CPU keeps the
    caller's short length always."""
    import jax

    from common import e2e_chain_length

    monkeypatch.setattr(jax, "default_backend", lambda: "cpu")
    assert e2e_chain_length(8) == 8

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert e2e_chain_length(8) == 1024
    monkeypatch.setenv("BENCH_TINY", "1")
    assert e2e_chain_length(4) == 4


@pytest.mark.slow
def test_run_all_tiny_subprocess():
    """Full suite in tiny mode as one command (the 'one command emits a
    machine-readable benchmark report' done-criterion)."""
    env = dict(os.environ, BENCH_TINY="1", DCNN_PLATFORM="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "run_all.py"),
         "--only", "bench_gemm", "--out", "/tmp/bench_results_test.json"],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    import json

    with open("/tmp/bench_results_test.json") as f:
        doc = json.load(f)
    assert doc["all_correct"] is True
