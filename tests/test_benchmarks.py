"""Benchmark-suite smoke tests (VERDICT r1 #7; reference
``benchmarks/gemm_benchmark.cpp:16-50`` correctness-gate pattern)."""

import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "benchmarks"))


def test_check_match_gate():
    from common import check_match

    ok, err = check_match(np.ones(4), np.ones(4) + 1e-7, 1e-5)
    assert ok and isinstance(ok, bool) and err < 1e-5
    ok, _ = check_match(np.ones(4), np.ones(4) + 1.0, 1e-5)
    assert not ok
    ok, err = check_match(np.ones(4), np.ones(5), 1e-5)
    assert not ok and err == float("inf")


def test_serialization_section_runs_and_gates():
    import bench_serialization

    os.environ["BENCH_TINY"] = "1"
    try:
        doc = bench_serialization.run()
    finally:
        os.environ.pop("BENCH_TINY", None)
    assert doc["all_correct"] is True
    names = {r["name"] for r in doc["results"]}
    assert {"checkpoint_save", "checkpoint_load"} <= names
    assert any(n.startswith("compress_") for n in names)
    # machine-readable: every row JSON-serializable
    import json

    json.dumps(doc)


@pytest.mark.slow
def test_run_all_tiny_subprocess():
    """Full suite in tiny mode as one command (the 'one command emits a
    machine-readable benchmark report' done-criterion)."""
    env = dict(os.environ, BENCH_TINY="1", DCNN_PLATFORM="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "run_all.py"),
         "--only", "bench_gemm", "--out", "/tmp/bench_results_test.json"],
        env=env, capture_output=True, text=True, timeout=600)
    assert out.returncode == 0, out.stderr[-2000:]
    import json

    with open("/tmp/bench_results_test.json") as f:
        doc = json.load(f)
    assert doc["all_correct"] is True
