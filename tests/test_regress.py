"""Bench-history regression gate tests (dcnn_tpu/obs/regress.py +
benchmarks/compare.py).

Contracts:

- the REAL committed BENCH_r01–r05 trajectory passes the gate (no false
  alarm on the project's own history, including the 3x-noisy h2d series);
- a planted ≥20% img/s regression appended to that same trajectory is
  flagged, by name, with a nonzero CLI exit code;
- direction (lower-is-better compile_s), the compile-cache-warmth
  comparability guard, missing-metric skips, and window bounds behave as
  documented;
- ``benchmarks/compare.py --self-test`` (the fixture run CI executes)
  passes — the gate is itself regression-tested.
"""

import copy
import json
import os
import shutil
import subprocess
import sys

import pytest

from dcnn_tpu.obs import regress

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
COMPARE = os.path.join(REPO, "benchmarks", "compare.py")


def _real_files():
    files = regress.find_bench_files(REPO)
    if len(files) < 2:
        pytest.skip("repo carries < 2 BENCH_r*.json captures")
    return files


# ----------------------------------------------------------- unit: compare

def _hist(*values, extra=()):
    out = [{"value": v} for v in values]
    for i, d in enumerate(extra):
        out[i].update(d)
    return out


def test_improvement_and_in_tolerance_pass():
    report = regress.compare(_hist(100.0, 110.0, 120.0))
    assert report["ok"] and report["regressions"] == []
    # 15% below the window best at 20% tolerance: pass
    report = regress.compare(_hist(100.0, 120.0, 102.0))
    assert report["ok"]


def test_regression_past_tolerance_flagged():
    report = regress.compare(_hist(100.0, 120.0, 90.0))  # -25% vs best
    assert not report["ok"] and report["regressions"] == ["img_per_sec"]
    row = next(r for r in report["metrics"] if r["metric"] == "img_per_sec")
    assert row["verdict"] == "REGRESSED" and row["best"] == 120.0


def test_baseline_is_window_best_not_mean():
    # a weak early capture must not dilute the baseline: best-of-window
    # is 120, and 90 regresses against it even though the mean is ~103
    report = regress.compare(_hist(90.0, 100.0, 120.0, 90.0))
    assert not report["ok"]


def test_lower_is_better_direction():
    hist = [{"phases": {"compile_s": 100.0, "compile_cache_hit": None}},
            {"phases": {"compile_s": 160.0, "compile_cache_hit": None}}]
    report = regress.compare(hist)  # +60% past the 50% tolerance
    assert "compile_s" in report["regressions"]
    hist[1]["phases"]["compile_s"] = 140.0  # +40%: within tolerance
    assert regress.compare(hist)["ok"]


def test_cache_warmth_guard_blocks_comparison():
    hist = [{"phases": {"compile_s": 3.0, "compile_cache_hit": True}},
            {"phases": {"compile_s": 150.0, "compile_cache_hit": False}}]
    report = regress.compare(hist)
    row = next(r for r in report["metrics"] if r["metric"] == "compile_s")
    assert row["verdict"].startswith("skipped")
    assert report["ok"]


def test_missing_metric_and_empty_window_skip():
    report = regress.compare([{"value": 10.0}, {"mfu": 0.4}])
    rows = {r["metric"]: r["verdict"] for r in report["metrics"]}
    assert rows["img_per_sec"].startswith("skipped")  # absent from newest
    # mfu_formula reads the legacy `mfu` key via its fallback, but the
    # prior capture carries neither -> still no comparable window
    assert rows["mfu_formula"].startswith("skipped")
    assert rows["mfu_analytic"].startswith("skipped")
    assert report["ok"]


def test_window_bounds_lookback():
    # the ancient 1000.0 capture is outside window=2 and must not gate
    report = regress.compare(_hist(1000.0, 100.0, 105.0, 103.0), window=2)
    assert report["ok"]
    report = regress.compare(_hist(1000.0, 100.0, 105.0, 103.0), window=3)
    assert not report["ok"]


def test_compare_input_validation():
    with pytest.raises(ValueError):
        regress.compare([])
    with pytest.raises(ValueError):
        regress.compare(_hist(1.0, 2.0), window=0)
    with pytest.raises(ValueError):
        regress.compare(_hist(1.0, 2.0), tolerance=1.5)


def test_get_path_and_load_capture(tmp_path):
    assert regress.get_path({"a": {"b": 3}}, "a.b") == 3
    assert regress.get_path({"a": 1}, "a.b") is None
    wrapped = tmp_path / "BENCH_r01.json"
    wrapped.write_text(json.dumps({"parsed": {"value": 5}}))
    bare = tmp_path / "bare.json"
    bare.write_text(json.dumps({"metric": "m", "value": 7}))
    junk = tmp_path / "junk.json"
    junk.write_text("{nope")
    assert regress.load_capture(str(wrapped)) == {"value": 5}
    assert regress.load_capture(str(bare))["value"] == 7
    assert regress.load_capture(str(junk)) is None


# ------------------------------------------- the committed real trajectory

def test_real_trajectory_passes():
    report = regress.compare_files(_real_files())
    assert report["ok"], regress.format_report(report)
    assert report["unparseable_files"] == []


def test_planted_regression_on_real_trajectory_flagged(tmp_path):
    """The acceptance shape: BENCH_r01–r05 as the fixture history, one
    planted ≥20% img/s drop appended — the gate must name it."""
    files = _real_files()
    for f in files:
        shutil.copy(f, tmp_path / os.path.basename(f))
    newest = regress.load_capture(files[-1])
    planted = copy.deepcopy(newest)
    planted["value"] = round(newest["value"] * 0.75, 1)  # -25%
    n = len(files) + 1
    (tmp_path / f"BENCH_r{n:02d}.json").write_text(
        json.dumps({"n": n, "parsed": planted}))
    report = regress.compare_files(regress.find_bench_files(str(tmp_path)))
    assert not report["ok"]
    assert "img_per_sec" in report["regressions"]

    # CLI twin: nonzero exit on the planted file, zero on the real set
    rc = subprocess.run(
        [sys.executable, COMPARE, "--json"]
        + regress.find_bench_files(str(tmp_path)),
        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 1, rc.stdout + rc.stderr
    assert "img_per_sec" in json.loads(rc.stdout)["regressions"]


def test_gate_current_embeds_report():
    files = _real_files()
    current = regress.load_capture(files[-1])
    report = regress.gate_current(current, REPO)
    assert report is not None and "error" not in report
    # the newest real capture re-gated against history incl. itself: ok
    assert report["ok"]
    assert report["baseline_files"] == files
    assert regress.gate_current({"value": 1.0}, str(os.path.join(
        REPO, "nonexistent-dir"))) is None  # no history -> None, no raise


# ------------------------------------------------------------------- CLI

def test_cli_self_test_passes():
    rc = subprocess.run([sys.executable, COMPARE, "--self-test"],
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert "self-test: PASS" in rc.stdout


def test_cli_real_files_exit_zero():
    _real_files()
    rc = subprocess.run([sys.executable, COMPARE],
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert "OK: no regressions" in rc.stdout


def test_cli_usage_errors():
    rc = subprocess.run([sys.executable, COMPARE, "one.json"],
                        capture_output=True, text=True, timeout=120)
    assert rc.returncode == 2


# ------------------------------------------- autoscale.* gate keys (PR 11)

def _autoscale_cap(availability=1.0, slo_min=0.2, reaction=1.0,
                   cooldown=5.0, **extra):
    return {"value": 100.0, "autoscale": {
        "availability": availability,
        "slo_violation_minutes": slo_min,
        "scale_up_reaction_s": reaction,
        "up_cooldown_s": cooldown, **extra}}


def test_autoscale_keys_skip_for_pre_pr11_captures():
    """Skips-not-lies: a history of captures without the autoscale block
    neither gates nor fails the new keys."""
    report = regress.compare([{"value": 100.0}, {"value": 101.0},
                              _autoscale_cap()])
    assert report["ok"]
    rows = {r["metric"]: r for r in report["metrics"]}
    assert rows["autoscale.availability"]["verdict"] \
        == "skipped: no comparable prior capture"
    # and a newest capture WITHOUT the block skips against one that has it
    report = regress.compare([_autoscale_cap(), {"value": 100.0}])
    assert report["ok"]
    rows = {r["metric"]: r for r in report["metrics"]}
    assert "absent from newest" in rows["autoscale.availability"]["verdict"]


def test_autoscale_availability_regression_flagged():
    report = regress.compare([_autoscale_cap(availability=1.0),
                              _autoscale_cap(availability=0.97)])
    assert "autoscale.availability" in report["regressions"]
    # within the 1% tolerance: passes
    report = regress.compare([_autoscale_cap(availability=1.0),
                              _autoscale_cap(availability=0.995)])
    assert report["ok"]


def test_autoscale_reaction_guarded_on_cooldown_budget():
    """A different up_cooldown_s budget is a config change, not a
    regression — the guard refuses the comparison."""
    slow = _autoscale_cap(reaction=12.0, cooldown=15.0)
    fast = _autoscale_cap(reaction=3.0, cooldown=5.0)
    report = regress.compare([fast, slow])
    rows = {r["metric"]: r for r in report["metrics"]}
    assert rows["autoscale.scale_up_reaction_s"]["verdict"] \
        == "skipped: no comparable prior capture"
    assert report["ok"]
    # same budget: a 4x reaction blowup IS flagged
    report = regress.compare([fast, _autoscale_cap(reaction=12.0,
                                                   cooldown=5.0)])
    assert "autoscale.scale_up_reaction_s" in report["regressions"]


def test_autoscale_slo_minutes_lower_is_better():
    report = regress.compare([_autoscale_cap(slo_min=0.2),
                              _autoscale_cap(slo_min=0.1)])
    assert report["ok"]   # improvement always passes
    report = regress.compare([_autoscale_cap(slo_min=0.2),
                              _autoscale_cap(slo_min=2.0)])
    assert "autoscale.slo_violation_minutes" in report["regressions"]


def test_autoscale_zero_best_window_uses_absolute_slack():
    """A perfect capture (0.0 minutes, un-delayed reaction) in the window
    must not flag every later legitimate nonzero forever — the relative
    band collapses at best=0, so the absolute slack (the soak's own
    budget) carries the verdict."""
    perfect = _autoscale_cap(slo_min=0.0, reaction=0.0)
    report = regress.compare([perfect,
                              _autoscale_cap(slo_min=0.75, reaction=4.0)])
    assert report["ok"]   # inside the budget = operating as designed
    report = regress.compare([perfect,
                              _autoscale_cap(slo_min=3.0, reaction=30.0)])
    assert "autoscale.slo_violation_minutes" in report["regressions"]
    assert "autoscale.scale_up_reaction_s" in report["regressions"]


# ------------------------------------------- decode.* gate keys (PR 20)

def _decode_cap(tps=5000.0, ttft=50.0, occ=0.8, slots=8, **extra):
    return {"value": 100.0, "decode": {
        "tokens_per_sec": tps,
        "ttft_p99_ms": ttft,
        "slot_occupancy": occ,
        "max_slots": slots, **extra}}


def test_decode_keys_skip_for_pre_pr20_captures():
    """Skips-not-lies: histories without the BENCH_DECODE block neither
    gate nor fail the decode keys, in either direction."""
    report = regress.compare([{"value": 100.0}, {"value": 101.0},
                              _decode_cap()])
    assert report["ok"]
    rows = {r["metric"]: r for r in report["metrics"]}
    assert rows["decode.tokens_per_sec"]["verdict"] \
        == "skipped: no comparable prior capture"
    report = regress.compare([_decode_cap(), {"value": 100.0}])
    assert report["ok"]
    rows = {r["metric"]: r for r in report["metrics"]}
    assert "absent from newest" in rows["decode.tokens_per_sec"]["verdict"]


def test_decode_throughput_and_occupancy_regressions_flagged():
    report = regress.compare([_decode_cap(tps=5000.0),
                              _decode_cap(tps=2000.0)])
    assert "decode.tokens_per_sec" in report["regressions"]
    report = regress.compare([_decode_cap(occ=0.8), _decode_cap(occ=0.4)])
    assert "decode.slot_occupancy" in report["regressions"]
    # within tolerance: passes
    report = regress.compare([_decode_cap(tps=5000.0, occ=0.8),
                              _decode_cap(tps=4500.0, occ=0.75)])
    assert report["ok"]


def test_decode_ttft_lower_is_better_with_absolute_slack():
    """TTFT is a sub-100ms loopback wall: the atol shields sub-10ms
    scheduler jitter, but a real blowup is flagged."""
    report = regress.compare([_decode_cap(ttft=5.0), _decode_cap(ttft=12.0)])
    assert report["ok"]   # within 1.0 rel + 10ms atol slack
    report = regress.compare([_decode_cap(ttft=50.0),
                              _decode_cap(ttft=300.0)])
    assert "decode.ttft_p99_ms" in report["regressions"]


def test_decode_keys_guarded_on_slot_count():
    """A different max_slots is a different probe — guard refuses the
    comparison instead of calling a config change a regression."""
    report = regress.compare([_decode_cap(tps=8000.0, slots=16),
                              _decode_cap(tps=5000.0, slots=8)])
    rows = {r["metric"]: r for r in report["metrics"]}
    assert rows["decode.tokens_per_sec"]["verdict"] \
        == "skipped: no comparable prior capture"
    assert report["ok"]
