"""Parallel host input pipeline tests (data/workers.py): bit-identity of
the worker-pool feed vs the serial path for every worker count, slot-ring
back-pressure, worker-failure fallback under FaultPlan trip points, and
the multiprocess soak (slow).

Tier-1 tests run the THREAD backend over the LocalSlots fake allocator —
same scheduler, ordering, rng derivation and fallback machinery as the
process backend, with no interpreter forks and no sleeps; the real
multiprocess pool is covered by the ``slow``-marked soak."""

import os
import threading

import numpy as np
import pytest

from dcnn_tpu.data import AugmentationBuilder
from dcnn_tpu.data.workers import (FeedWorkerPool, LocalSlots, ShmSlots,
                                   prepare_shard, serial_shards, shard_rng)
from dcnn_tpu.obs import Tracer, get_registry
from dcnn_tpu.resilience import faults


def _data(n=256, hw=8, c=3, classes=10, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 256, size=(n, hw, hw, c), dtype=np.uint8)
    y = rng.integers(0, classes, size=n).astype(np.int32)
    return x, y


def _sels(n, rows, k, seed=1):
    rng = np.random.default_rng(seed)
    return [np.sort(rng.permutation(n)[:rows]) for _ in range(k)]


def _aug():
    return (AugmentationBuilder("NHWC").horizontal_flip(p=0.5)
            .random_crop(2, p=1.0).brightness(0.2, p=0.5).build())


def _local_slots(x, y, rows, num_slots):
    return LocalSlots(num_slots, rows, x.shape[1:], x.dtype,
                      y.shape[1:], y.dtype)


def _collect(pool, sels, epoch=0):
    out = []
    for ps in pool.shards(sels, epoch=epoch):
        out.append((ps.x.copy(), ps.y.copy()))
        ps.release()
    return out


# -- deterministic preparation ----------------------------------------------

def test_shard_rng_depends_on_cell_not_worker():
    a = shard_rng(7, 2, 5).random(8)
    b = shard_rng(7, 2, 5).random(8)
    np.testing.assert_array_equal(a, b)
    assert not np.array_equal(a, shard_rng(7, 2, 6).random(8))
    assert not np.array_equal(a, shard_rng(7, 3, 5).random(8))
    assert not np.array_equal(a, shard_rng(8, 2, 5).random(8))


def test_prepare_shard_matches_fancy_index():
    x, y = _data()
    sel = _sels(len(x), 64, 1)[0]
    xg, yg, t = prepare_shard(x, y, sel)
    np.testing.assert_array_equal(xg, x[sel])
    np.testing.assert_array_equal(yg, y[sel])
    assert t["augment_s"] == 0.0 and t["rows"] == 64
    # gathering straight into out buffers is bit-identical
    out_x = np.empty_like(xg)
    out_y = np.empty_like(yg)
    prepare_shard(x, y, sel, out_x=out_x, out_y=out_y)
    np.testing.assert_array_equal(out_x, x[sel])
    np.testing.assert_array_equal(out_y, y[sel])


def test_prepare_shard_augment_deterministic_and_nonmutating():
    x, y = _data()
    x0 = x.copy()
    sel = _sels(len(x), 64, 1)[0]
    aug = _aug()
    a, _, _ = prepare_shard(x, y, sel, augment=aug, rng=shard_rng(3, 1, 0))
    b, _, _ = prepare_shard(x, y, sel, augment=aug, rng=shard_rng(3, 1, 0))
    np.testing.assert_array_equal(a, b)
    assert a.dtype == np.uint8            # uint8 wire format survives
    assert not np.array_equal(a, x[sel])  # augmentation actually applied
    np.testing.assert_array_equal(x, x0)  # source dataset untouched
    with pytest.raises(ValueError, match="requires rng"):
        prepare_shard(x, y, sel, augment=aug)


def test_prepare_shard_float_dataset_keeps_dtype():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 4, 4, 1)).astype(np.float32)
    y = rng.integers(0, 3, size=64).astype(np.int32)
    sel = np.arange(16, dtype=np.int64)
    xg, _, _ = prepare_shard(x, y, sel, augment=_aug(),
                             rng=shard_rng(0, 0, 0))
    assert xg.dtype == np.float32


# -- bit-identity across worker counts (the hard contract) ------------------

@pytest.mark.parametrize("augmented", [False, True])
def test_pool_bit_identical_to_serial_any_worker_count(augmented):
    x, y = _data()
    sels = _sels(len(x), 64, 6)
    aug = _aug() if augmented else None
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, augment=aug, seed=7, epoch=3)]
    for nw in (1, 4):
        pool = FeedWorkerPool(
            x, y, 64, num_workers=nw, augment=aug, seed=7,
            backend="thread", poll_s=0.02,
            slots=_local_slots(x, y, 64, nw + 2))
        got = _collect(pool, sels, epoch=3)
        pool.close()
        assert len(got) == len(ser)
        for (sx, sy), (gx, gy) in zip(ser, got):
            np.testing.assert_array_equal(sx, gx)
            np.testing.assert_array_equal(sy, gy)


def test_pool_zero_workers_is_serial_path():
    x, y = _data()
    sels = _sels(len(x), 32, 3)
    pool = FeedWorkerPool(x, y, 32, num_workers=0, augment=_aug(), seed=2)
    got = _collect(pool, sels, epoch=1)
    ser = [(a, b) for a, b, _ in
           serial_shards(x, y, sels, augment=_aug(), seed=2, epoch=1)]
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)
    pool.close()


def test_pool_epoch_changes_augment_draws():
    x, y = _data()
    sels = _sels(len(x), 32, 2)
    with FeedWorkerPool(x, y, 32, num_workers=2, augment=_aug(), seed=2,
                        backend="thread", poll_s=0.02) as pool:
        e0 = _collect(pool, sels, epoch=0)
        e1 = _collect(pool, sels, epoch=1)
    assert not all(np.array_equal(a, c) for (a, _), (c, _) in zip(e0, e1))


# -- slot ring: back-pressure + bookkeeping ---------------------------------

def test_backpressure_bounded_by_slots():
    x, y = _data()
    sels = _sels(len(x), 32, 4)
    pool = FeedWorkerPool(x, y, 32, num_workers=1, seed=0,
                          backend="thread", poll_s=0.02, num_slots=2)
    it = pool.shards(sels)
    ps0 = next(it)
    ps1 = next(it)
    assert pool._free.qsize() == 0  # both slots leased, nothing free
    got = {}

    def pull():
        got["ps"] = next(it)

    t = threading.Thread(target=pull, daemon=True)
    t.start()
    t.join(0.3)
    assert t.is_alive(), "third shard yielded without a free slot"
    ps0.release()                    # free one slot -> shard 2 can flow
    t.join(10.0)
    assert not t.is_alive() and got["ps"].idx == 2
    ps1.release()
    got["ps"].release()
    for ps in it:
        ps.release()
    assert pool._free.qsize() == 2   # ring fully recycled
    pool.close()


def test_pool_rejects_oversized_shard_and_double_iter():
    x, y = _data()
    pool = FeedWorkerPool(x, y, 16, num_workers=1, backend="thread",
                          poll_s=0.02)
    with pytest.raises(ValueError, match="exceeds"):
        list(pool.shards([np.arange(32, dtype=np.int64)]))
    pool.close()
    with pytest.raises(RuntimeError, match="closed"):
        list(pool.shards([np.arange(4, dtype=np.int64)]))


def test_registry_instruments_settle():
    x, y = _data()
    reg = get_registry()
    shards0 = reg.counter("feed_shards_total").value
    sels = _sels(len(x), 32, 5)
    with FeedWorkerPool(x, y, 32, num_workers=2, backend="thread",
                        poll_s=0.02) as pool:
        for ps in pool.shards(sels):
            ps.release()
    assert reg.counter("feed_shards_total").value == shards0 + 5
    assert reg.gauge("feed_queue_depth").value == 0
    assert reg.gauge("feed_workers_busy").value == 0


def test_worker_spans_on_per_worker_tracks():
    x, y = _data()
    tracer = Tracer(enabled=True)
    sels = _sels(len(x), 32, 4)
    with FeedWorkerPool(x, y, 32, num_workers=2, augment=_aug(), seed=0,
                        backend="thread", poll_s=0.02,
                        tracer=tracer) as pool:
        for ps in pool.shards(sels):
            ps.release()
    evs = tracer.events()
    names = {e["name"] for e in evs}
    assert {"feed.gather", "feed.augment", "feed.pack"} <= names
    tracks = {e["track"] for e in evs if e["name"] == "feed.gather"}
    assert tracks <= {"feed-w0", "feed-w1"} and tracks
    for e in evs:
        assert e["dur_s"] >= 0.0


# -- failure paths ----------------------------------------------------------

def test_worker_error_falls_back_inline_bit_identical():
    x, y = _data()
    sels = _sels(len(x), 64, 6)
    aug = _aug()
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, augment=aug, seed=7, epoch=0)]
    reg = get_registry()
    f0 = reg.counter("feed_worker_failures_total").value
    plan = faults.FaultPlan().arm("feed.prepare", at=2, times=1)
    with plan:
        with FeedWorkerPool(x, y, 64, num_workers=2, augment=aug, seed=7,
                            backend="thread", poll_s=0.02) as pool:
            got = _collect(pool, sels)
            assert pool.alive_workers() == 2  # error != death
    assert reg.counter("feed_worker_failures_total").value == f0 + 1
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


def test_worker_crash_detected_and_epoch_completes():
    x, y = _data()
    sels = _sels(len(x), 64, 6)
    aug = _aug()
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, augment=aug, seed=7, epoch=0)]
    reg = get_registry()
    f0 = reg.counter("feed_worker_failures_total").value
    plan = faults.FaultPlan().arm("feed.prepare", at=1, times=1,
                                  exc=faults.InjectedCrash)
    with plan:
        with FeedWorkerPool(x, y, 64, num_workers=2, augment=aug, seed=7,
                            backend="thread", poll_s=0.02) as pool:
            got = _collect(pool, sels)
            assert pool.alive_workers() == 1  # one worker died silently
    assert reg.counter("feed_worker_failures_total").value > f0
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


def test_all_workers_dead_degrades_to_inline():
    x, y = _data()
    sels = _sels(len(x), 64, 5)
    ser = [(a.copy(), b.copy()) for a, b, _ in serial_shards(x, y, sels)]
    plan = faults.FaultPlan().arm("feed.prepare", exc=faults.InjectedCrash)
    with plan:
        with FeedWorkerPool(x, y, 64, num_workers=2, seed=0,
                            backend="thread", poll_s=0.02) as pool:
            got = _collect(pool, sels)
            assert pool.alive_workers() == 0
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


def test_stall_rescue_settles_slot_and_respects_busy_workers():
    """White-box: the stall scavenger (a) skips rescue while any live
    worker is mid-shard (queued tasks are waiting, not lost), (b) rescues
    unclaimed shards out of inflight — so the epoch TERMINATES — into the
    poisoned-slot ledger, and (c) recycles the slot when the late worker
    result eventually lands."""
    x, y = _data()
    pool = FeedWorkerPool(x, y, 32, num_workers=1, backend="thread",
                          poll_s=0.02)
    sel = np.arange(32, dtype=np.int64)
    try:
        sid = pool._free.get_nowait()
        inflight = {0: {"slot": sid, "sel": sel, "wid": None}}
        # (a) a live worker is busy -> no rescue
        pool._busy.add(0)
        pool._rescue_stalled(inflight, {}, epoch=9)
        assert 0 in inflight
        # (b) all idle -> rescued inline, inflight emptied (termination),
        # slot parked in the poisoned ledger
        pool._busy.clear()
        ready = {}
        pool._rescue_stalled(inflight, ready, epoch=9)
        assert inflight == {} and ready[0]["arrays"] is not None
        np.testing.assert_array_equal(ready[0]["arrays"][0], x[sel])
        assert pool._poisoned == {(9, 0): sid}
        # (c) the late worker result finally releases the slot
        free0 = pool._free.qsize()
        pool._result_q.put(("done", 0, 9, 0, {"worker": 0}))
        pool._pump({}, {}, epoch=9)
        assert pool._free.qsize() == free0 + 1 and pool._poisoned == {}
    finally:
        pool.close()


def test_abandoned_epoch_reclaims_slots():
    x, y = _data()
    sels = _sels(len(x), 32, 6)
    with FeedWorkerPool(x, y, 32, num_workers=2, backend="thread",
                        poll_s=0.02, num_slots=3) as pool:
        it = pool.shards(sels)
        ps = next(it)
        ps.release()
        it.close()                      # consumer bails mid-epoch
        got = _collect(pool, sels)      # ring must be whole again
        assert len(got) == 6
        assert pool._free.qsize() == 3


# -- process backend (kept small for tier-1; the soak is slow) --------------

@pytest.mark.skipif("fork" not in __import__("multiprocessing")
                    .get_all_start_methods(),
                    reason="no fork on this platform")
def test_process_pool_bit_identity_small():
    x, y = _data(n=128)
    sels = _sels(len(x), 32, 4)
    aug = _aug()
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, augment=aug, seed=5, epoch=1)]
    with FeedWorkerPool(x, y, 32, num_workers=2, augment=aug, seed=5,
                        poll_s=0.05) as pool:
        got = _collect(pool, sels, epoch=1)
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


def test_shm_slots_lifecycle():
    slots = ShmSlots(2, 8, (4, 4, 3), np.uint8, (), np.int32)
    spec = slots.spec()
    att = ShmSlots.attach(spec)
    v = slots.x_view(0, 8)
    v[...] = 7
    np.testing.assert_array_equal(att.x_view(0, 8), v)
    yv = slots.y_view(1, 8)
    yv[...] = np.arange(8, dtype=np.int32)
    np.testing.assert_array_equal(att.y_view(1, 8), yv)
    del v, yv
    att.close()
    slots.close()  # owner unlinks; attach after unlink must fail
    with pytest.raises(FileNotFoundError):
        ShmSlots.attach(spec)


# -- slow: the real multiprocess soak ---------------------------------------

@pytest.mark.slow
def test_multiprocess_soak_bit_identity_and_crash():
    x, y = _data(n=1024, hw=16)
    sels = _sels(len(x), 128, 8)
    aug = _aug()
    ser = [(a.copy(), b.copy()) for a, b, _ in
           serial_shards(x, y, sels, augment=aug, seed=9, epoch=4)]
    # several epochs through one pool (slot recycling under load)
    with FeedWorkerPool(x, y, 128, num_workers=4, augment=aug, seed=9,
                        poll_s=0.05) as pool:
        for _ in range(3):
            got = _collect(pool, sels, epoch=4)
            for (sx, sy), (gx, gy) in zip(ser, got):
                np.testing.assert_array_equal(sx, gx)
                np.testing.assert_array_equal(sy, gy)
    # crash soak: fork inherits the armed plan; each worker hard-exits
    # (os._exit) on its second task — the epoch must still complete
    # bit-identically via inline fallback
    reg = get_registry()
    f0 = reg.counter("feed_worker_failures_total").value
    plan = faults.FaultPlan().arm("feed.prepare", at=1, times=1,
                                  exc=faults.InjectedCrash)
    with plan:
        with FeedWorkerPool(x, y, 128, num_workers=2, augment=aug, seed=9,
                            poll_s=0.05, mp_context="fork") as pool:
            got = _collect(pool, sels, epoch=4)
            assert pool.alive_workers() == 0
    assert reg.counter("feed_worker_failures_total").value > f0
    for (sx, sy), (gx, gy) in zip(ser, got):
        np.testing.assert_array_equal(sx, gx)
        np.testing.assert_array_equal(sy, gy)


@pytest.mark.slow
@pytest.mark.skipif((os.cpu_count() or 1) < 4,
                    reason="speedup gate needs >= 4 cores")
def test_parallel_prep_speedup_over_serial():
    """Acceptance gate: gather+augment+pack throughput with 4 workers is
    >= 2x serial on a >= 4-core host, augmentation enabled."""
    import time

    rng = np.random.default_rng(0)
    x = rng.integers(0, 256, size=(4096, 32, 32, 3), dtype=np.uint8)
    y = rng.integers(0, 100, size=4096).astype(np.int32)
    sels = _sels(len(x), 512, 8, seed=2)
    aug = (AugmentationBuilder("NHWC").horizontal_flip(p=0.5)
           .random_crop(2, p=1.0).rotation(10.0, p=1.0).build())

    t0 = time.perf_counter()
    for _ in serial_shards(x, y, sels, augment=aug, seed=1, epoch=0):
        pass
    serial_s = time.perf_counter() - t0

    with FeedWorkerPool(x, y, 512, num_workers=4, augment=aug, seed=1,
                        poll_s=0.05) as pool:
        # warm pass: fork + fault-free path settled before timing
        for ps in pool.shards(sels, epoch=0):
            ps.release()
        t0 = time.perf_counter()
        for ps in pool.shards(sels, epoch=0):
            ps.release()
        pool_s = time.perf_counter() - t0

    speedup = serial_s / pool_s
    assert speedup >= 2.0, (f"parallel prep speedup {speedup:.2f}x < 2x "
                            f"(serial {serial_s:.2f}s, pool {pool_s:.2f}s)")
