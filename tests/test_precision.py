"""Mixed-precision (bf16) mode tests.

The reference trains in pure fp32 (SURVEY.md §7 hard part 6); the TPU-native
framework adds a ``bf16`` mode (core/precision.py) where activations and
params-at-use are bfloat16 while master params, optimizer state, BN running
statistics and the loss stay fp32. These tests pin the invariants that make
that mode safe.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from dcnn_tpu.core.precision import (
    cast_to_compute, get_compute_dtype, set_precision)
from dcnn_tpu.models import create_resnet9_cifar10
from dcnn_tpu.nn.builder import SequentialBuilder
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.ops.norm import batch_norm
from dcnn_tpu.optim import Adam
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import create_train_state


@pytest.fixture
def bf16_mode():
    set_precision("bf16")
    yield
    set_precision("parity")


def _tiny_model():
    return (SequentialBuilder(data_format="NHWC")
            .input((8, 8, 3))
            .conv2d(16, 3, padding=1).batchnorm().activation("relu")
            .maxpool2d(2)
            .flatten().dense(10)
            .build())


def test_compute_dtype_selection(bf16_mode):
    assert get_compute_dtype() == jnp.bfloat16
    set_precision("parity")
    assert get_compute_dtype() is None


def test_cast_to_compute_only_floats(bf16_mode):
    tree = {"w": jnp.ones((2, 2), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
    out = cast_to_compute(tree)
    assert out["w"].dtype == jnp.bfloat16
    assert out["i"].dtype == jnp.int32


def test_bf16_forward_emits_bf16_fp32_state(bf16_mode):
    model = _tiny_model()
    key = jax.random.PRNGKey(0)
    params, state = model.init(key)
    x = jnp.ones((4, 8, 8, 3), jnp.float32)
    y, new_state = model.apply(params, state, x, training=True, rng=key)
    assert y.dtype == jnp.bfloat16
    # BN running stats must remain fp32 master copies
    bn_state = [s for s in new_state if s and "running_mean" in s][0]
    assert bn_state["running_mean"].dtype == jnp.float32
    assert bn_state["running_var"].dtype == jnp.float32


def test_bf16_train_step_keeps_fp32_masters_and_learns(bf16_mode):
    model = _tiny_model()
    opt = Adam(1e-2)
    key = jax.random.PRNGKey(0)
    ts = create_train_state(model, opt, key)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(32, 8, 8, 3)).astype(np.float32))
    labels = rng.integers(0, 10, size=32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[labels])

    losses = []
    for i in range(30):
        ts, loss, logits = step(ts, x, y, jax.random.fold_in(key, i), 1e-2)
        losses.append(float(loss))
    # loss is computed in fp32 and must drop on a memorizable batch
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.5
    # master params and optimizer state stay fp32
    for leaf in jax.tree_util.tree_leaves(ts.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    for leaf in jax.tree_util.tree_leaves(ts.opt_state):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    assert logits.dtype == jnp.float32


def test_batch_norm_bf16_stats_accuracy():
    """bf16 input, but statistics must be fp32-accurate: compare against the
    fp32 batch_norm on the same (bf16-rounded) data."""
    rng = np.random.default_rng(1)
    # large-ish spatial so a bf16 accumulator would visibly drift
    x32 = jnp.asarray(rng.normal(3.0, 1.0, size=(8, 16, 16, 32)).astype(np.float32))
    xb = x32.astype(jnp.bfloat16)
    g = jnp.ones((32,), jnp.float32)
    b = jnp.zeros((32,), jnp.float32)
    rm = jnp.zeros((32,), jnp.float32)
    rv = jnp.ones((32,), jnp.float32)

    y_ref, m_ref, v_ref = batch_norm(
        xb.astype(jnp.float32), g, b, rm, rv, training=True, data_format="NHWC")
    y_b, m_b, v_b = batch_norm(
        xb, g, b, rm, rv, training=True, data_format="NHWC")
    # running stats identical (both computed in fp32 from identical values)
    np.testing.assert_allclose(np.asarray(m_b), np.asarray(m_ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(v_b), np.asarray(v_ref), rtol=1e-6)
    # normalized output agrees to bf16 resolution
    np.testing.assert_allclose(
        np.asarray(y_b, dtype=np.float32), np.asarray(y_ref), atol=0.05)


def test_bf16_resnet9_step_runs(bf16_mode):
    """Flagship-family model compiles and steps in bf16 on the CPU mesh."""
    model = create_resnet9_cifar10("NHWC")
    opt = Adam(1e-3)
    key = jax.random.PRNGKey(0)
    ts = create_train_state(model, opt, key)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    x = jnp.zeros((8, 32, 32, 3), jnp.float32)
    y = jnp.asarray(np.eye(10, dtype=np.float32)[np.zeros(8, dtype=int)])
    ts, loss, _ = step(ts, x, y, key, 1e-3)
    assert np.isfinite(float(loss))


def test_multi_step_matches_sequential_steps():
    """make_multi_step(K batches, one dispatch) must be semantically identical
    to K sequential make_train_step calls (per-batch BN stats + updates)."""
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.train import make_multi_step, make_train_step

    model = _tiny_model()
    # SGD+momentum, not Adam: Adam's m/(sqrt(v)+eps) amplifies the
    # reassociation-level numeric noise between the scanned and unrolled
    # compilations by orders of magnitude while v ~ 0, which would force a
    # meaninglessly loose tolerance here.
    opt = SGD(1e-2, momentum=0.9)
    key = jax.random.PRNGKey(0)
    ts_a = create_train_state(model, opt, key)
    ts_b = create_train_state(model, opt, key)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    multi = make_multi_step(model, softmax_cross_entropy, opt, donate=False)

    rng = np.random.default_rng(2)
    K, B = 3, 8
    xs = jnp.asarray(rng.normal(size=(K, B, 8, 8, 3)).astype(np.float32))
    ys = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, size=(K, B))])

    losses = []
    data_rng = jax.random.PRNGKey(7)
    for i in range(K):
        ts_a, loss, _ = step(ts_a, xs[i], ys[i],
                             jax.random.fold_in(data_rng, i), 1e-3)
        losses.append(float(loss))
    ts_b, mean_loss = multi(ts_b, xs, ys, data_rng, 1e-3)

    np.testing.assert_allclose(float(mean_loss), np.mean(losses), rtol=1e-5)
    # scan-vs-unrolled compiles different fusion orders, so allow
    # reassociation-level noise only.
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.params),
                    jax.tree_util.tree_leaves(ts_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts_a.state),
                    jax.tree_util.tree_leaves(ts_b.state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_fp64_mode_subprocess():
    """DCNN_PRECISION=fp64 (the reference's double-kernel path,
    src/math/cpu/dgemm.cpp): params init as float64, a train step runs in
    double, and dense forward matches numpy float64 to 1e-12. Runs in a
    subprocess because jax_enable_x64 is process-global."""
    import os
    import subprocess
    import sys

    script = r"""
import os
os.environ["DCNN_PRECISION"] = "fp64"
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import dcnn_tpu  # applies platform override
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
from dcnn_tpu.core.precision import get_compute_dtype, get_precision_mode
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train.trainer import create_train_state, make_train_step

assert get_precision_mode() == "fp64"
assert get_compute_dtype() == jnp.float64
assert jax.config.jax_enable_x64

model = (SequentialBuilder(name="fp64_mlp", data_format="NHWC")
         .input((6,)).dense(8).activation("relu").dense(4).build())
opt = SGD(0.1)
ts = create_train_state(model, opt, jax.random.PRNGKey(0))
for leaf in jax.tree_util.tree_leaves(ts.params):
    assert leaf.dtype == jnp.float64, leaf.dtype

x = jnp.asarray(np.random.default_rng(0).normal(size=(5, 6)))
assert x.dtype == jnp.float64
y, _ = model.apply(ts.params, ts.state, x, training=False)
assert y.dtype == jnp.float64

# forward parity vs numpy float64 (weights stored (out, in))
h = np.asarray(x, np.float64)
h = np.maximum(h @ np.asarray(ts.params[0]["w"]).T + np.asarray(ts.params[0]["b"]), 0.0)
ref = h @ np.asarray(ts.params[2]["w"]).T + np.asarray(ts.params[2]["b"])
np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-12, atol=1e-12)

# one double train step: finite loss, params stay float64
step = make_train_step(model, softmax_cross_entropy, opt)
targets = jnp.asarray(np.eye(4)[np.random.default_rng(1).integers(0, 4, 5)])
ts, loss, logits = step(ts, x, targets, jax.random.PRNGKey(1), 0.1)
assert np.isfinite(float(loss))
# the loss boundary must not quantize doubles (upcast_logits passthrough)
assert logits.dtype == jnp.float64, logits.dtype
assert loss.dtype == jnp.float64, loss.dtype
for leaf in jax.tree_util.tree_leaves(ts.params):
    assert leaf.dtype == jnp.float64
print("FP64-OK")
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=300)
    assert "FP64-OK" in out.stdout, (out.stdout, out.stderr[-2000:])
