"""Data loader + augmentation tests with synthesized dataset files.

Reference analog: ``tiny_imagenet_loader_test.cpp`` (SURVEY.md §4.6).
"""

import os

import numpy as np

from dcnn_tpu.data import (
    ArrayDataLoader, AugmentationBuilder, CIFAR10DataLoader, CIFAR100DataLoader,
    MNISTDataLoader, SyntheticClassificationLoader, TinyImageNetDataLoader,
    UJIWiFiDataLoader, one_hot,
)


def test_one_hot():
    y = one_hot(np.array([0, 2]), 3)
    np.testing.assert_array_equal(y, [[1, 0, 0], [0, 0, 1]])


def test_array_loader_batching_and_shuffle():
    x = np.arange(10 * 4, dtype=np.float32).reshape(10, 4)
    y = one_hot(np.arange(10) % 3, 3)
    loader = ArrayDataLoader(x, y, batch_size=3, shuffle=True, drop_last=True, seed=1)
    batches = list(loader)
    assert len(batches) == 3 == len(loader)
    assert all(b[0].shape == (3, 4) for b in batches)
    # different epoch → different order; same epoch → same order (determinism)
    order1 = np.concatenate([b[0][:, 0] for b in loader])
    loader.shuffle(5)
    order2 = np.concatenate([b[0][:, 0] for b in loader])
    assert not np.array_equal(order1, order2)
    order2b = np.concatenate([b[0][:, 0] for b in loader])
    np.testing.assert_array_equal(order2, order2b)


def test_mnist_csv_loader(tmp_path):
    rng = np.random.default_rng(0)
    rows = []
    labels = [3, 7, 1]
    for lb in labels:
        pix = rng.integers(0, 256, size=784)
        rows.append(",".join([str(lb)] + [str(p) for p in pix]))
    csv = tmp_path / "mnist.csv"
    csv.write_text("label," + ",".join(f"p{i}" for i in range(784)) + "\n" +
                   "\n".join(rows))
    loader = MNISTDataLoader(str(csv), batch_size=3, shuffle=False)
    x, y = next(iter(loader))
    assert x.shape == (3, 1, 28, 28)
    # uint8-first wire contract: pixels stay raw uint8 on the host,
    # decode (x * 1/255) happens on device after the put
    assert x.dtype == np.uint8
    assert loader.wire_dtype == np.uint8
    assert loader.scale == 1.0 / 255.0
    np.testing.assert_array_equal(np.argmax(y, -1), labels)


def test_mnist_csv_float_pixels_fallback(tmp_path):
    """CSV with float-formatted pixels must load via the tolerant numpy
    fallback (the strict native parser declines integer-only input)."""
    rows = ["label," + ",".join(f"p{i}" for i in range(784))]
    rows.append(",".join(["7"] + ["0.5"] * 784))
    csv = tmp_path / "floats.csv"
    csv.write_text("\n".join(rows))
    loader = MNISTDataLoader(str(csv), batch_size=1, shuffle=False, drop_last=False)
    x, y = next(iter(loader))
    assert x.shape == (1, 1, 28, 28)
    # fractional pixels can't ride the uint8 wire: normalized at load,
    # float32 wire dtype, identity decode (scale 1.0)
    assert x.dtype == np.float32
    assert loader.wire_dtype == np.float32 and loader.scale == 1.0
    np.testing.assert_allclose(x, 0.5 / 255.0, rtol=1e-6)
    assert np.argmax(y) == 7


def test_cifar10_bin_loader(tmp_path):
    rng = np.random.default_rng(0)
    n = 7
    recs = []
    labels = rng.integers(0, 10, size=n)
    for lb in labels:
        recs.append(np.concatenate([[lb], rng.integers(0, 256, size=3072)]).astype(np.uint8))
    path = tmp_path / "data_batch_1.bin"
    np.concatenate(recs).tofile(path)
    loader = CIFAR10DataLoader(str(path), batch_size=7, shuffle=False, drop_last=False)
    x, y = next(iter(loader))
    assert x.shape == (7, 3, 32, 32)
    assert x.dtype == np.uint8 and loader.wire_dtype == np.uint8
    # raw record bytes survive untouched (no float round trip)
    np.testing.assert_array_equal(
        x[0].ravel(), recs[0][1:])
    np.testing.assert_array_equal(np.argmax(y, -1), labels)


def test_cifar100_bin_loader_fine_and_coarse(tmp_path):
    rng = np.random.default_rng(0)
    n = 5
    coarse = rng.integers(0, 20, size=n)
    fine = rng.integers(0, 100, size=n)
    recs = []
    for c, f in zip(coarse, fine):
        recs.append(np.concatenate([[c, f], rng.integers(0, 256, size=3072)]).astype(np.uint8))
    path = tmp_path / "train.bin"
    np.concatenate(recs).tofile(path)
    lf = CIFAR100DataLoader(str(path), label_mode="fine", batch_size=5,
                            shuffle=False, drop_last=False)
    _, y = next(iter(lf))
    np.testing.assert_array_equal(np.argmax(y, -1), fine)
    lc = CIFAR100DataLoader(str(path), label_mode="coarse", batch_size=5,
                            shuffle=False, drop_last=False)
    _, y = next(iter(lc))
    np.testing.assert_array_equal(np.argmax(y, -1), coarse)


def _write_tiny_imagenet(root, wnids=("n001", "n002"), per_class=3):
    from PIL import Image
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "wnids.txt"), "w") as f:
        f.write("\n".join(wnids))
    with open(os.path.join(root, "words.txt"), "w") as f:
        f.write("\n".join(f"{w}\tname of {w}" for w in wnids))
    rng = np.random.default_rng(0)
    for w in wnids:
        d = os.path.join(root, "train", w, "images")
        os.makedirs(d, exist_ok=True)
        for i in range(per_class):
            arr = rng.integers(0, 256, size=(64, 64, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{w}_{i}.JPEG"))
    vd = os.path.join(root, "val", "images")
    os.makedirs(vd, exist_ok=True)
    lines = []
    for i, w in enumerate(wnids):
        arr = rng.integers(0, 256, size=(64, 64, 3), dtype=np.uint8)
        fn = f"val_{i}.JPEG"
        Image.fromarray(arr).save(os.path.join(vd, fn))
        lines.append(f"{fn}\t{w}\t0\t0\t10\t10")
    with open(os.path.join(root, "val", "val_annotations.txt"), "w") as f:
        f.write("\n".join(lines))


def test_tiny_imagenet_loader(tmp_path):
    root = str(tmp_path / "tin")
    _write_tiny_imagenet(root)
    train = TinyImageNetDataLoader(root, "train", batch_size=6, shuffle=False,
                                   drop_last=False, cache=True)
    x, y = next(iter(train))
    assert x.shape == (6, 3, 64, 64)
    # uint8 wire: decoded pixels stay raw bytes; decode lives on device
    assert x.dtype == np.uint8
    assert train.wire_dtype == np.uint8 and train.scale == 1.0 / 255.0
    assert y.shape == (6, 200)
    # labels 0..1 used (two wnids)
    assert set(np.argmax(y, -1)) == {0, 1}
    # cache file written and reused
    assert os.path.isfile(train._cache_path())
    val = TinyImageNetDataLoader(root, "val", batch_size=2, shuffle=False,
                                 drop_last=False, cache=False)
    xv, yv = next(iter(val))
    assert xv.shape == (2, 3, 64, 64)


def test_uji_wifi_loader(tmp_path):
    rows = ["ap1,ap2,ap3,lon,lat"]
    rng = np.random.default_rng(0)
    for _ in range(6):
        rssi = rng.integers(-90, -30, size=3)
        # include sentinel 100 = not detected
        rssi[rng.integers(0, 3)] = 100
        rows.append(",".join(map(str, list(rssi) + [round(rng.uniform(-7700, -7600), 2),
                                                    round(rng.uniform(4864700, 4864900), 2)])))
    path = tmp_path / "uji.csv"
    path.write_text("\n".join(rows))
    loader = UJIWiFiDataLoader(str(path), batch_size=6, shuffle=False)
    x, y = next(iter(loader))
    assert x.shape == (6, 3) and y.shape == (6, 2)
    assert x.min() >= 0.0 and x.max() <= 1.0   # sentinel remapped then scaled
    # normalized targets ~ zero-mean
    np.testing.assert_allclose(y.mean(axis=0), 0.0, atol=1e-3)
    denorm = loader.denormalize_targets(y)
    assert abs(denorm[:, 0].mean() - (-7650)) < 60


def test_regression_loader_arrays():
    from dcnn_tpu.data import RegressionDataLoader
    rng = np.random.default_rng(1)
    x = rng.normal(5.0, 3.0, (40, 7)).astype(np.float32)
    y = (x @ rng.normal(size=(7, 2))).astype(np.float32)
    loader = RegressionDataLoader(features=x, targets=y, batch_size=16,
                                  shuffle=False, normalize_features=True)
    xb, yb = next(iter(loader))
    assert loader.num_features == 7 and loader.num_outputs == 2
    assert loader.is_normalized
    # both sides z-normalized; stats kept for round-trip
    np.testing.assert_allclose(loader._x.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(loader.denormalize_features(loader._x), x,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(loader.denormalize_targets(loader._y), y,
                               rtol=1e-4, atol=1e-4)
    assert xb.shape == (16, 7) and yb.shape == (16, 2)


def test_regression_loader_csv(tmp_path):
    from dcnn_tpu.data import RegressionDataLoader
    path = tmp_path / "reg.csv"
    path.write_text("f1,f2,target\n1,2,10\n3,4,20\n5,6,30\n")
    loader = RegressionDataLoader(csv_path=str(path), num_targets=1,
                                  batch_size=3, shuffle=False,
                                  normalize_targets=False)
    x, y = next(iter(loader))
    np.testing.assert_allclose(x, [[1, 2], [3, 4], [5, 6]])
    np.testing.assert_allclose(y, [[10], [20], [30]])
    assert not loader.is_normalized
    # headerless CSV sniffed correctly too
    path2 = tmp_path / "reg2.csv"
    path2.write_text("1,2,10\n3,4,20\n")
    loader2 = RegressionDataLoader(csv_path=str(path2), num_targets=1,
                                   batch_size=2, shuffle=False)
    x2, _ = next(iter(loader2))
    assert x2.shape == (2, 2)


def test_augmentations_shapes_and_effects():
    rng = np.random.default_rng(0)
    x = rng.random((8, 3, 16, 16)).astype(np.float32)
    strategy = (AugmentationBuilder()
                .brightness(0.5, p=1.0)
                .contrast(0.5, 1.5, p=1.0)
                .cutout(4, p=1.0)
                .gaussian_noise(0.1, p=1.0)
                .horizontal_flip(p=1.0)
                .vertical_flip(p=1.0)
                .random_crop(2, p=1.0)
                .rotation(10.0, p=1.0)
                .normalization([0.5, 0.5, 0.5], [0.25, 0.25, 0.25])
                .build())
    assert len(strategy.ops) == 9  # all nine reference augmentation families
    out = strategy(x.copy(), rng)
    assert out.shape == x.shape
    assert not np.allclose(out, x)


def test_flip_determinism_and_correctness():
    from dcnn_tpu.data import horizontal_flip
    x = np.arange(2 * 1 * 2 * 3, dtype=np.float32).reshape(2, 1, 2, 3)
    flipped = horizontal_flip(p=1.0)(x.copy(), np.random.default_rng(0))
    np.testing.assert_array_equal(flipped, x[..., ::-1])


def test_augment_ops_never_mutate_input():
    """Regression (r6): cutout/flips/rotation/random_crop used to write
    into the caller's batch, corrupting the source array for
    non-augmented consumers sharing it. Every op is copy-on-write now."""
    from dcnn_tpu.data import (brightness, contrast, cutout, gaussian_noise,
                               horizontal_flip, normalization, random_crop,
                               rotation, vertical_flip)

    ops = [brightness(0.5, p=1.0), contrast(0.5, 1.5, p=1.0),
           cutout(4, p=1.0), gaussian_noise(0.1, p=1.0),
           horizontal_flip(p=1.0), vertical_flip(p=1.0),
           normalization([0.5] * 3, [0.25] * 3), random_crop(2, p=1.0),
           rotation(10.0, p=1.0)]
    rng_src = np.random.default_rng(3)
    x = rng_src.random((6, 3, 12, 12)).astype(np.float32)
    x0 = x.copy()
    for op in ops:
        out = op(x, np.random.default_rng(0))
        np.testing.assert_array_equal(
            x, x0, err_msg=f"{type(op).__name__} mutated its input")
        assert not np.array_equal(out, x), type(op).__name__
    # p=0 ops return the input unchanged (no pointless copy)
    for op in [cutout(4, p=0.0), horizontal_flip(p=0.0),
               vertical_flip(p=0.0), rotation(10.0, p=0.0)]:
        assert op(x, np.random.default_rng(0)) is x


def test_random_crop_vectorized_matches_windowed_reference():
    """The batched-offset random_crop picks the same windows a per-image
    loop with the same draw order would (mask draw, then the two batched
    offset draws), for both layouts."""
    from dcnn_tpu.data import random_crop

    for fmt, shape in (("NCHW", (5, 2, 9, 7)), ("NHWC", (5, 9, 7, 2))):
        x = np.random.default_rng(1).random(shape).astype(np.float32)
        pad = 2
        out = random_crop(pad, p=1.0, data_format=fmt)(
            x, np.random.default_rng(42))
        ref_rng = np.random.default_rng(42)
        n = len(x)
        _ = ref_rng.random(n)                 # the apply mask (p=1 -> all)
        oy = ref_rng.integers(0, 2 * pad + 1, size=n)
        ox = ref_rng.integers(0, 2 * pad + 1, size=n)
        ha, wa = (2, 3) if fmt == "NCHW" else (1, 2)
        h, w = shape[ha], shape[wa]
        pad_spec = [(0, 0)] * 4
        pad_spec[ha] = pad_spec[wa] = (pad, pad)
        padded = np.pad(x, pad_spec)
        for i in range(n):
            if fmt == "NCHW":
                want = padded[i, :, oy[i]:oy[i] + h, ox[i]:ox[i] + w]
            else:
                want = padded[i, oy[i]:oy[i] + h, ox[i]:ox[i] + w, :]
            np.testing.assert_array_equal(out[i], want)


def test_augment_strategy_picklable():
    """Worker processes receive the augmentation recipe by pickle under
    spawn — every built-in op must round-trip and draw identically."""
    import pickle

    strategy = (AugmentationBuilder("NHWC")
                .brightness(0.3, p=0.7).contrast(0.7, 1.3, p=0.5)
                .cutout(3, p=0.5).gaussian_noise(0.05, p=0.5)
                .horizontal_flip(p=0.5).vertical_flip(p=0.5)
                .normalization([0.5], [0.25]).random_crop(2, p=1.0)
                .rotation(5.0, p=0.5).build())
    clone = pickle.loads(pickle.dumps(strategy))
    x = np.random.default_rng(2).random((4, 8, 8, 1)).astype(np.float32)
    a = strategy(x, np.random.default_rng(9))
    b = clone(x, np.random.default_rng(9))
    np.testing.assert_array_equal(a, b)


def test_loader_augmentation_hook_applied():
    x = np.ones((8, 3, 8, 8), np.float32)
    y = one_hot(np.zeros(8, np.int64), 2)
    aug = AugmentationBuilder().brightness(0.5, p=1.0).build()
    loader = ArrayDataLoader(x, y, batch_size=4, shuffle=False, augmentation=aug)
    xb, _ = next(iter(loader))
    assert not np.allclose(xb, 1.0)


def test_synthetic_loader_trains():
    loader = SyntheticClassificationLoader(num_samples=32, image_shape=(1, 8, 8),
                                           num_classes=4, batch_size=16)
    x, y = next(iter(loader))
    assert x.shape == (16, 1, 8, 8) and y.shape == (16, 4)


def test_download_idx_to_csv_roundtrip(tmp_path):
    """The downloader's IDX->CSV conversion must produce exactly what
    MNISTDataLoader expects (reference Kaggle CSV schema: header +
    label,784 pixel rows)."""
    import struct

    from dcnn_tpu.data.download import _idx_to_csv
    from dcnn_tpu.data import MNISTDataLoader

    rng = np.random.default_rng(0)
    n, rows, cols = 5, 28, 28
    imgs = rng.integers(0, 256, size=(n, rows, cols), dtype=np.uint8)
    labels = rng.integers(0, 10, size=n, dtype=np.uint8)
    idx_imgs = struct.pack(">IIII", 2051, n, rows, cols) + imgs.tobytes()
    idx_labels = struct.pack(">II", 2049, n) + labels.tobytes()

    out_csv = str(tmp_path / "train.csv")
    _idx_to_csv(idx_imgs, idx_labels, out_csv)

    loader = MNISTDataLoader(out_csv, batch_size=5, shuffle=False)
    loader.load_data()
    x, y = next(iter(loader))
    assert x.shape == (5, 1, 28, 28)
    # the uint8 wire makes the round trip exact — not atol-close
    assert x.dtype == np.uint8
    np.testing.assert_array_equal(x.reshape(5, 28, 28), imgs)
    np.testing.assert_array_equal(np.argmax(y, axis=1), labels)
