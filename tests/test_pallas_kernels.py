"""Pallas kernel parity tests (interpret mode on CPU; compiled on TPU).

Reference analog: kernel-vs-naive-reference comparison suites
(SURVEY.md §4.2).
"""

import jax.numpy as jnp
import numpy as np

from dcnn_tpu.ops.pallas import fused_scale_bias_relu


def test_fused_scale_bias_relu_matches_jnp(rng):
    x = jnp.asarray(rng.normal(size=(4, 8, 8, 16)).astype(np.float32))
    scale = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    got = fused_scale_bias_relu(x, scale, bias)
    want = jnp.maximum(x * scale + bias, 0.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_fused_scale_bias_relu_ragged_rows(rng):
    # row count not a multiple of the block size exercises grid padding
    x = jnp.asarray(rng.normal(size=(3, 700)).astype(np.float32))
    scale = jnp.ones((700,), jnp.float32) * 2.0
    bias = jnp.zeros((700,), jnp.float32)
    got = fused_scale_bias_relu(x, scale, bias)
    np.testing.assert_allclose(np.asarray(got), np.maximum(np.asarray(x) * 2.0, 0.0),
                               rtol=1e-6)


# -- implicit-GEMM conv (ops/pallas/conv.py; VERDICT r3 experiment) --

def test_conv3x3_matches_xla_conv(rng):
    from jax import lax
    from dcnn_tpu.ops.pallas.conv import conv3x3_s1

    for (n, h, w, cin, cout, bt) in [(4, 8, 8, 8, 16, 1), (4, 6, 10, 4, 8, 2),
                                     (2, 5, 5, 3, 4, 1)]:
        x = jnp.asarray(rng.normal(size=(n, h, w, cin)).astype(np.float32))
        wt = jnp.asarray(rng.normal(size=(3, 3, cin, cout)).astype(np.float32)
                         * 0.1)
        ref = lax.conv_general_dilated(
            x, wt, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        got = conv3x3_s1(x, wt, batch_tile=bt)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)


def test_conv3x3_bnrelu_input_fusion(rng):
    from jax import lax
    from dcnn_tpu.ops.pallas.conv import conv3x3_s1_bnrelu_in

    n, h, w, cin, cout = 3, 7, 9, 8, 8
    x = jnp.asarray(rng.normal(size=(n, h, w, cin)).astype(np.float32))
    wt = jnp.asarray(rng.normal(size=(3, 3, cin, cout)).astype(np.float32) * 0.1)
    sc = jnp.asarray(rng.normal(size=(cin,)).astype(np.float32))
    sh = jnp.asarray(rng.normal(size=(cin,)).astype(np.float32))
    ref = lax.conv_general_dilated(
        jnp.maximum(x * sc + sh, 0.0), wt, (1, 1), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    got = conv3x3_s1_bnrelu_in(x, wt, sc, sh)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_conv3x3_shape_validation():
    from dcnn_tpu.ops.pallas.conv import conv3x3_s1

    x = jnp.zeros((4, 8, 8, 8))
    with np.testing.assert_raises(ValueError):
        conv3x3_s1(x, jnp.zeros((5, 5, 8, 8)))         # not 3x3
    with np.testing.assert_raises(ValueError):
        conv3x3_s1(x, jnp.zeros((3, 3, 4, 8)))         # cin mismatch
    with np.testing.assert_raises(ValueError):
        conv3x3_s1(x, jnp.zeros((3, 3, 8, 8)), batch_tile=3)  # 4 % 3


def test_conv3x3_pairs_matches_xla_conv(rng):
    """Output-column-pair formulation (fused block-sparse weights, even/odd
    column planes) must equal the direct conv on every shape class."""
    from jax import lax
    from dcnn_tpu.ops.pallas.conv import conv3x3_s1_pairs, fuse_pair_weights

    for (n, h, w, cin, cout, bt, th) in [(2, 8, 8, 8, 16, 1, 4),
                                         (4, 8, 10, 4, 8, 2, 8),
                                         (2, 6, 6, 8, 8, 1, 2)]:
        x = jnp.asarray(rng.normal(size=(n, h, w, cin)).astype(np.float32))
        wt = jnp.asarray(rng.normal(size=(3, 3, cin, cout)).astype(np.float32)
                         * 0.1)
        ref = lax.conv_general_dilated(
            x, wt, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
        got = conv3x3_s1_pairs(x, wt, batch_tile=bt, h_tile=th)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-5, rtol=2e-5)
    # fused weights carry each tap to exactly two (offset, output) slots
    w1 = jnp.asarray(rng.normal(size=(3, 3, 2, 2)).astype(np.float32))
    w2 = fuse_pair_weights(w1)
    assert w2.shape == (3, 4, 2, 4)
    np.testing.assert_array_equal(np.asarray(w2[:, 0, :, :2]),
                                  np.asarray(w1[:, 0]))   # kw0 -> even
    np.testing.assert_array_equal(np.asarray(w2[:, 1, :, 2:]),
                                  np.asarray(w1[:, 0]))   # kw0 -> odd
    np.testing.assert_array_equal(np.asarray(w2[:, 0, :, 2:]), 0.0)


def test_conv_bnrelu_in_shape_validation():
    from dcnn_tpu.ops.pallas.conv import conv3x3_s1_bnrelu_in

    x = jnp.zeros((2, 4, 4, 4))
    s = jnp.zeros((4,))
    with np.testing.assert_raises(ValueError):
        conv3x3_s1_bnrelu_in(x, jnp.zeros((5, 5, 4, 4)), s, s)   # not 3x3
    with np.testing.assert_raises(ValueError):
        conv3x3_s1_bnrelu_in(x, jnp.zeros((3, 3, 2, 4)), s, s)   # cin mismatch
