"""Pallas kernel parity tests (interpret mode on CPU; compiled on TPU).

Reference analog: kernel-vs-naive-reference comparison suites
(SURVEY.md §4.2).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dcnn_tpu.ops.pallas import fused_scale_bias_relu


def test_fused_scale_bias_relu_matches_jnp(rng):
    x = jnp.asarray(rng.normal(size=(4, 8, 8, 16)).astype(np.float32))
    scale = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    bias = jnp.asarray(rng.normal(size=(16,)).astype(np.float32))
    got = fused_scale_bias_relu(x, scale, bias)
    want = jnp.maximum(x * scale + bias, 0.0)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6, atol=1e-6)


def test_fused_scale_bias_relu_ragged_rows(rng):
    # row count not a multiple of the block size exercises grid padding
    x = jnp.asarray(rng.normal(size=(3, 700)).astype(np.float32))
    scale = jnp.ones((700,), jnp.float32) * 2.0
    bias = jnp.zeros((700,), jnp.float32)
    got = fused_scale_bias_relu(x, scale, bias)
    np.testing.assert_allclose(np.asarray(got), np.maximum(np.asarray(x) * 2.0, 0.0),
                               rtol=1e-6)
