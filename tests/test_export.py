"""StableHLO export tests (nn/export.py).

Beyond-reference deployment capability: the folded / quantized inference
graph serializes to a self-contained artifact that reloads and runs with
only JAX — no model class or checkpoint. Contracts: output identity vs the
live model, batch polymorphism, int8-graph export, and the artifact's
independence from the source objects (mutating them after export must not
change the artifact's outputs).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.nn import (
    SequentialBuilder, export_inference, fold_batchnorm, load_inference,
    quantize_model,
)

from test_fold import _train_a_bit


def _small_model():
    return (SequentialBuilder(name="exp", data_format="NHWC")
            .input((8, 8, 3))
            .conv2d(8, 3, padding=1).batchnorm().activation("relu")
            .maxpool2d(2).flatten().dense(10)
            .build())


def test_export_roundtrip_matches_live_model():
    model = _small_model()
    ts = _train_a_bit(model)
    fmodel, fp, fs = fold_batchnorm(model, ts.params, ts.state)
    blob = export_inference(fmodel, fp, fs)
    assert isinstance(blob, bytes) and len(blob) > 0

    f = load_inference(blob)
    x = jnp.asarray(np.random.default_rng(0).normal(
        size=(4, 8, 8, 3)).astype(np.float32))
    want, _ = fmodel.apply(fp, fs, x, training=False)
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_export_batch_polymorphic():
    model = _small_model()
    ts = _train_a_bit(model)
    blob = export_inference(model, ts.params, ts.state)
    f = load_inference(blob)
    rng = np.random.default_rng(1)
    for b in (1, 3, 16):
        y = f(jnp.asarray(rng.normal(size=(b, 8, 8, 3)).astype(np.float32)))
        assert y.shape == (b, 10)


def test_export_pinned_batch_rejects_other_batches():
    model = _small_model()
    ts = _train_a_bit(model)
    blob = export_inference(model, ts.params, ts.state, batch_size=4)
    f = load_inference(blob)
    assert f(jnp.zeros((4, 8, 8, 3), jnp.float32)).shape == (4, 10)
    with pytest.raises(Exception):
        f(jnp.zeros((2, 8, 8, 3), jnp.float32))


def test_export_quantized_graph():
    model = _small_model()
    ts = _train_a_bit(model)
    calib = jnp.asarray(np.random.default_rng(2).normal(
        size=(16, 8, 8, 3)).astype(np.float32))
    qmodel, qp, qs = quantize_model(model, ts.params, ts.state, calib)
    blob = export_inference(qmodel, qp, qs)
    f = load_inference(blob)
    x = calib[:4]
    want, _ = qmodel.apply(qp, qs, x, training=False)
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_export_is_self_contained():
    """Only the blob (plus JAX) is needed: the live logits computed BEFORE
    export must be reproduced after every source object (model, params,
    state) is deleted and collected — the artifact carries the weights."""
    import gc

    model = _small_model()
    ts = _train_a_bit(model)
    x = jnp.asarray(np.random.default_rng(3).normal(
        size=(2, 8, 8, 3)).astype(np.float32))
    want = np.asarray(model.apply(ts.params, ts.state, x,
                                  training=False)[0])
    blob = export_inference(model, ts.params, ts.state)
    del model, ts
    gc.collect()
    got = np.asarray(load_inference(blob)(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert np.abs(want).sum() > 0  # the baked weights are the trained ones


def test_export_mha_model():
    """The attention family exports too. Backend-dispatched impl choices
    bake at trace time: on the CPU test backend the flash layer traces its
    blockwise fallback, which is platform-neutral — so the artifact stays
    portable. (A TPU-side export of the Pallas kernel needs
    platforms=("tpu",); see the export_inference docstring.)"""
    from dcnn_tpu.models import create_mha_classifier

    model = create_mha_classifier()
    ts = _train_a_bit(model, n_steps=2, bs=8)
    blob = export_inference(model, ts.params, ts.state)
    f = load_inference(blob)
    x = jnp.asarray(np.random.default_rng(5).normal(
        size=(4, 32, 64)).astype(np.float32))
    want, _ = model.apply(ts.params, ts.state, x, training=False)
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_committed_artifacts_hit_committed_accuracy():
    """The deployable unit of record: the StableHLO artifacts committed
    next to the digits28 snapshot must reproduce its accuracy on the real
    test split using ONLY jax + the blob — no model class, registry, or
    checkpoint machinery. (Reference analog: mnist_cnn_test.cpp evaluates
    a saved snapshot; here the saved *program* is what evaluates.)"""
    import os

    from dcnn_tpu.data import MNISTDataLoader, decode_host
    from dcnn_tpu.data.digits28 import ensure_digits28_csvs

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    snap = os.path.join(repo, "model_snapshots", "mnist_cnn_model")
    csv = os.path.join(ensure_digits28_csvs(repo), "test.csv")
    val = MNISTDataLoader(csv, data_format="NCHW", batch_size=512,
                          shuffle=False, drop_last=False)
    val.load_data()
    # the loader serves raw uint8 (wire contract, docs/performance.md §5);
    # the committed artifacts were traced for float32, so this consumer
    # decodes per the contract before feeding them
    xs, ys = [], []
    for xb, yb in val:
        xs.append(decode_host(np.asarray(xb), val.scale))
        ys.append(np.asarray(yb))
    x = jnp.asarray(np.concatenate(xs))
    y = np.concatenate(ys).argmax(-1)

    for tag in ("folded", "int8"):
        path = os.path.join(snap, f"mnist_cnn_model_{tag}.stablehlo")
        with open(path, "rb") as f:
            logits = load_inference(f.read())(x)
        acc = float(np.mean(np.asarray(logits).argmax(-1) == y))
        assert acc >= 0.99, f"{tag} artifact top-1 {acc}"


def test_export_requires_input_shape():
    from dcnn_tpu.nn import Sequential

    with pytest.raises(ValueError, match="input_shape"):
        export_inference(Sequential([], name="noshape"), (), ())


def test_load_inference_jits_and_caches():
    """load_inference returns a jitted callable: a second same-shape call
    must be served from the compile cache (cache size stays 1), not
    re-traced — the property the serving engine's session reuse rests on."""
    model = _small_model()
    ts = _train_a_bit(model)
    f = load_inference(export_inference(model, ts.params, ts.state))
    x = jnp.zeros((2, 8, 8, 3), jnp.float32)
    f(x)
    assert f._cache_size() == 1
    f(x)
    assert f._cache_size() == 1  # second call hit the cache
    f(jnp.zeros((4, 8, 8, 3), jnp.float32))
    assert f._cache_size() == 2  # new shape = new entry, old one kept


def test_roundtrip_bit_identical_at_every_serve_bucket():
    """Folded and int8 graphs round-trip through export_inference/
    load_inference at every serve bucket size with BIT-IDENTICAL logits vs
    the live model at the same batch shape: serialization must not perturb
    the program (same StableHLO, same backend, same compile), at any
    bucket a serving engine will ever run. The live side is the *jitted*
    forward — what export traces and what serving executes; op-by-op eager
    dispatch compiles each op separately and can tile fp32 reductions
    differently (observed at batch 1 on CPU), which is an eager-vs-compiled
    artifact, not an export infidelity."""
    from dcnn_tpu.serve import serve_buckets

    model = _small_model()
    ts = _train_a_bit(model)
    calib = jnp.asarray(np.random.default_rng(7).normal(
        size=(16, 8, 8, 3)).astype(np.float32))
    fmodel, fp, fs = fold_batchnorm(model, ts.params, ts.state)
    qmodel, qp, qs = quantize_model(model, ts.params, ts.state, calib)
    rng = np.random.default_rng(8)
    for tag, (m, p, s) in (("folded", (fmodel, fp, fs)),
                           ("int8", (qmodel, qp, qs))):
        f = load_inference(export_inference(m, p, s))
        live_fn = jax.jit(
            lambda x, m=m, p=p, s=s: m.apply(p, s, x, training=False)[0])
        for b in serve_buckets(8):
            x = jnp.asarray(rng.normal(size=(b, 8, 8, 3)).astype(np.float32))
            live = np.asarray(live_fn(x))
            art = np.asarray(f(x))
            assert np.array_equal(art, live), (tag, b)
