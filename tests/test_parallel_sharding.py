"""Data-parallel / spatial-sharding tests over the 8-virtual-device mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dcnn_tpu.core.mesh import make_mesh
from dcnn_tpu.nn import SequentialBuilder
from dcnn_tpu.optim import SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.parallel import make_data_parallel_train_step, replicate, shard_batch
from dcnn_tpu.train import make_train_step
from dcnn_tpu.train.trainer import TrainState, create_train_state

KEY = jax.random.PRNGKey(0)


def _model():
    return (SequentialBuilder("dp_model")
            .input((1, 8, 8))
            .conv2d(4, 3, 1, 1).activation("relu")
            .maxpool2d(2)
            .flatten()
            .dense(10)
            .build())


def test_make_mesh_shapes():
    mesh = make_mesh()
    assert mesh.devices.size == len(jax.devices())
    mesh2 = make_mesh((4, 2), ("data", "stage"))
    assert mesh2.shape == {"data": 4, "stage": 2}
    with pytest.raises(ValueError):
        make_mesh((3, 2), ("data", "stage"))


def test_data_parallel_step_matches_single_device():
    model = _model()
    opt = SGD(0.1)
    mesh = make_mesh((8,), ("data",))

    ts_ref = create_train_state(model, opt, KEY)
    ts_dp = TrainState(ts_ref.params, ts_ref.state, ts_ref.opt_state, ts_ref.step)

    step_ref = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    step_dp = make_data_parallel_train_step(model, softmax_cross_entropy, opt, mesh)

    rng = np.random.default_rng(0)
    x = rng.normal(size=(16, 1, 8, 8)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=16)]

    ts_dp = TrainState(replicate(ts_dp.params, mesh), replicate(ts_dp.state, mesh),
                       replicate(ts_dp.opt_state, mesh), replicate(ts_dp.step, mesh))
    xs, ys = shard_batch((jnp.asarray(x), jnp.asarray(y)), mesh)

    for it in range(2):
        ts_ref, loss_ref, _ = step_ref(ts_ref, jnp.asarray(x), jnp.asarray(y), KEY, 0.1)
        ts_dp, loss_dp, _ = step_dp(ts_dp, xs, ys, KEY, 0.1)
        np.testing.assert_allclose(float(loss_dp), float(loss_ref), rtol=1e-5)

    for a, b in zip(jax.tree_util.tree_leaves(ts_dp.params),
                    jax.tree_util.tree_leaves(ts_ref.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6)


def test_spatial_sharding_conv_halo():
    """Shard H over 'sp' axis: GSPMD must insert conv halo exchange and match
    the unsharded result — the CNN analog of sequence parallelism."""
    model = (SequentialBuilder("sp_model").input((3, 16, 16))
             .conv2d(4, 3, 1, 1).activation("relu")
             .conv2d(4, 3, 1, 1).build())
    params, state = model.init(KEY)
    x = jax.random.normal(jax.random.PRNGKey(1), (2, 3, 16, 16))
    ref, _ = model.apply(params, state, x)

    mesh = make_mesh((4,), ("sp",), devices=jax.devices()[:4])
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = jax.device_put(x, NamedSharding(mesh, P(None, None, "sp", None)))
    ps = replicate(params, mesh)
    ss = replicate(state, mesh)

    @jax.jit
    def fwd(p, s, xin):
        y, _ = model.apply(p, s, xin)
        return y

    out = fwd(ps, ss, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)
