"""HBM-resident dataset + on-device augmentation tests.

Covers the TPU-native analog of the reference's decode-once loading strategy
(``include/data_loading/tiny_imagenet_data_loader.hpp:26-132``): staging,
the one-dispatch epoch's exact step semantics vs the base train step, the
padded-eval masking, on-device augmentation ops, and the Trainer integration.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dcnn_tpu.data import (
    ArrayDataLoader, DeviceAugment, DeviceAugmentBuilder, DeviceDataset,
    one_hot,
)
from dcnn_tpu.data import augment_device as ad
from dcnn_tpu.nn.builder import SequentialBuilder
from dcnn_tpu.optim import Adam, SGD
from dcnn_tpu.ops.losses import softmax_cross_entropy
from dcnn_tpu.train import Trainer
from dcnn_tpu.train.trainer import (
    create_train_state, evaluate_classification, make_train_step,
)


def _small_model(n_classes=4, hw=8, c=1):
    return (SequentialBuilder(name="dd_cnn", data_format="NHWC")
            .input((hw, hw, c))
            .conv2d(8, 3, padding=1).batchnorm().activation("relu")
            .maxpool2d(2)
            .flatten().dense(16).activation("relu").dense(n_classes)
            .build())


def _blob_data(n=96, hw=8, n_classes=4, seed=0):
    """Linearly separable uint8 blobs: class k has mean intensity ~k-band."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n)
    base = (y[:, None, None, None] * (200 // n_classes) + 20).astype(np.float32)
    x = np.clip(base + rng.normal(0, 10, size=(n, hw, hw, 1)), 0, 255)
    return x.astype(np.uint8), y.astype(np.int64)


# ---------------------------------------------------------------- staging

def test_stage_and_geometry():
    x, y = _blob_data(n=50)
    ds = DeviceDataset(x, y, 4, batch_size=16)
    assert ds.steps_per_epoch == 3
    assert ds.num_samples == 50
    assert ds.x.dtype == jnp.uint8          # stays uint8 in device memory
    assert ds.hbm_bytes == x.nbytes + 50 * 4
    assert ds.scale == pytest.approx(1 / 255)


def test_onehot_y_collapsed_and_validation():
    x, y = _blob_data(n=20)
    ds = DeviceDataset(x, one_hot(y, 4), 4, batch_size=4)
    np.testing.assert_array_equal(np.asarray(ds.y), y)
    with pytest.raises(ValueError):
        DeviceDataset(x, y[:-1], 4, batch_size=4)
    with pytest.raises(ValueError):
        DeviceDataset(x, y, 4, batch_size=21)


# ------------------------------------------------- resident epoch semantics

def test_resident_epoch_matches_manual_steps():
    """The one-dispatch epoch is bit-for-bit the same computation as K manual
    base-step calls over the same permutation/rng derivation."""
    from dcnn_tpu.data.device_dataset import make_resident_epoch

    x, y = _blob_data(n=40, hw=8)
    model = _small_model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts0 = create_train_state(model, opt, key)
    ts0b = create_train_state(model, opt, key)

    epoch_fn = make_resident_epoch(model, softmax_cross_entropy, opt,
                                   num_classes=4, batch_size=8)
    rng = jax.random.PRNGKey(7)
    ts1, mean_loss = epoch_fn(ts0, jnp.asarray(x), jnp.asarray(y.astype(np.int32)),
                              rng, 0.05)

    # replicate on the host: same perm + per-step rng derivation
    kperm, kstep = jax.random.split(rng)
    perm = np.asarray(jax.random.permutation(jax.random.fold_in(kperm, 0), 40))
    idx = perm[:5 * 8].reshape(5, 8)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    losses = []
    ts = ts0b
    for i in range(5):
        xb = jnp.asarray(x[idx[i]].astype(np.float32) / 255.0)
        yb = jnp.asarray(one_hot(y[idx[i]], 4))
        ts, loss, _ = step(ts, xb, yb, jax.random.fold_in(kstep, i), 0.05)
        losses.append(float(loss))

    assert float(mean_loss) == pytest.approx(np.mean(losses), abs=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_resident_epoch_lr_vector_and_multi_epoch_steps():
    from dcnn_tpu.data.device_dataset import make_resident_epoch

    x, y = _blob_data(n=32)
    model = _small_model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    # steps > N//B: permutation tiling keeps all indices in range
    epoch_fn = make_resident_epoch(model, softmax_cross_entropy, opt,
                                   num_classes=4, batch_size=8, steps=10)
    lrs = jnp.linspace(0.05, 0.01, 10)
    ts, mean_loss = epoch_fn(ts, jnp.asarray(x),
                             jnp.asarray(y.astype(np.int32)),
                             jax.random.PRNGKey(1), lrs)
    assert np.isfinite(float(mean_loss))


# ------------------------------------------------------------ resident eval

def test_resident_eval_matches_host_eval_with_padding():
    """Whole-split eval == host loader eval (drop_last=False), exactly:
    full batches scan + a statically-shaped remainder batch, no padding."""
    x, y = _blob_data(n=37, seed=2)   # 37 % 8 != 0 → exercises the remainder
    model = _small_model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))

    ds = DeviceDataset(x, y, 4, batch_size=8)
    loss_r, acc_r = evaluate_classification(
        model, ts.params, ts.state, softmax_cross_entropy, ds)

    host = ArrayDataLoader(x.astype(np.float32) / 255.0, one_hot(y, 4),
                           batch_size=8, shuffle=False, drop_last=False)
    host.load_data()
    loss_h, acc_h = evaluate_classification(
        model, ts.params, ts.state, softmax_cross_entropy, host)

    assert acc_r == pytest.approx(acc_h, abs=1e-9)
    assert loss_r == pytest.approx(loss_h, abs=1e-4)


def test_resident_eval_exact_for_non_ce_loss():
    """Remainder-batch eval (no padding rows) is exact for ANY mean-reducing
    loss — e.g. MSE over one-hot targets (review r3 finding #2)."""
    from dcnn_tpu.ops.losses import mse_loss

    x, y = _blob_data(n=37, seed=5)
    model = _small_model()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))

    ds = DeviceDataset(x, y, 4, batch_size=8)
    loss_r, acc_r = evaluate_classification(
        model, ts.params, ts.state, mse_loss, ds)

    host = ArrayDataLoader(x.astype(np.float32) / 255.0, one_hot(y, 4),
                           batch_size=8, shuffle=False, drop_last=False)
    host.load_data()
    loss_h, acc_h = evaluate_classification(
        model, ts.params, ts.state, mse_loss, host)

    assert acc_r == pytest.approx(acc_h, abs=1e-9)
    assert loss_r == pytest.approx(loss_h, rel=1e-5)


def test_resident_epoch_microbatching_threaded():
    """config.num_microbatches reaches the resident step (review r3 #1):
    microbatched resident epoch == manual microbatched steps."""
    from dcnn_tpu.data.device_dataset import make_resident_epoch

    x, y = _blob_data(n=32)
    model = _small_model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts0 = create_train_state(model, opt, key)
    ts0b = create_train_state(model, opt, key)

    epoch_fn = make_resident_epoch(model, softmax_cross_entropy, opt,
                                   num_classes=4, batch_size=16,
                                   num_microbatches=4)
    rng = jax.random.PRNGKey(11)
    ts1, _ = epoch_fn(ts0, jnp.asarray(x), jnp.asarray(y.astype(np.int32)),
                      rng, 0.05)

    kperm, kstep = jax.random.split(rng)
    perm = np.asarray(jax.random.permutation(jax.random.fold_in(kperm, 0), 32))
    idx = perm.reshape(2, 16)
    step = make_train_step(model, softmax_cross_entropy, opt,
                           num_microbatches=4, donate=False)
    ts = ts0b
    for i in range(2):
        xb = jnp.asarray(x[idx[i]].astype(np.float32) / 255.0)
        yb = jnp.asarray(one_hot(y[idx[i]], 4))
        ts, _, _ = step(ts, xb, yb, jax.random.fold_in(kstep, i), 0.05)

    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(ts.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


# ------------------------------------------------------- trainer integration

def test_trainer_fit_resident_end_to_end():
    from dcnn_tpu.core.config import TrainingConfig

    x, y = _blob_data(n=128, seed=1)
    xv, yv = _blob_data(n=40, seed=9)
    model = _small_model()
    opt = Adam(2e-3)
    cfg = TrainingConfig(learning_rate=2e-3, snapshot_dir=None)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))

    train_ds = DeviceDataset(x, y, 4, batch_size=16)
    val_ds = DeviceDataset(xv, yv, 4, batch_size=16)
    ts = trainer.fit(ts, train_ds, val_ds, epochs=8)

    # convergence is asserted on the BEST epoch, not the last: with a
    # 40-sample val split one misclassified sample moves acc by 0.025, and
    # the last epoch of an 8-epoch run routinely wobbles below a peak the
    # run already hit (seed-dependent: observed 1.00 at epoch 7 → 0.775 at
    # epoch 8). Best-epoch ≥ 0.9 is the statistically stable statement of
    # "this configuration trains", alongside a strictly decreasing loss.
    assert max(h["val_acc"] for h in trainer.history) >= 0.9
    assert trainer.history[-1]["train_loss"] < trainer.history[0]["train_loss"]


def test_resident_epoch_rejects_sub_batch_split():
    from dcnn_tpu.data.device_dataset import make_resident_epoch

    x, y = _blob_data(n=4)
    model = _small_model()
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    epoch_fn = make_resident_epoch(model, softmax_cross_entropy, opt,
                                   num_classes=4, batch_size=8)
    with pytest.raises(ValueError, match="at least one batch"):
        epoch_fn(ts, jnp.asarray(x), jnp.asarray(y.astype(np.int32)),
                 jax.random.PRNGKey(1), 0.05)


def test_trainer_resident_snapshot_roundtrip(tmp_path):
    """Best-val snapshot save works with resident eval (metrics must be
    Python floats for the JSON manifest — review r3 pass 2 finding #1)."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.train.checkpoint import load_checkpoint

    x, y = _blob_data(n=64, seed=1)
    model = _small_model()
    opt = Adam(2e-3)
    cfg = TrainingConfig(learning_rate=2e-3, snapshot_dir=str(tmp_path))
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = DeviceDataset(x, y, 4, batch_size=16)
    trainer.fit(ts, ds, ds, epochs=2)
    _, params, _, _, _, meta = load_checkpoint(
        str(tmp_path / model.name))
    assert isinstance(meta["val_acc"], float)
    assert jax.tree_util.tree_leaves(params)


def test_trainer_fit_resident_with_augment():
    from dcnn_tpu.core.config import TrainingConfig

    x, y = _blob_data(n=64, seed=4)
    aug = (DeviceAugmentBuilder("NHWC")
           .horizontal_flip(0.5).random_crop(1).brightness(0.05, 0.3)
           .build())
    ds = DeviceDataset(x, y, 4, batch_size=16, augment=aug)
    model = _small_model()
    opt = Adam(2e-3)
    trainer = Trainer(model, opt, "softmax_crossentropy",
                      config=TrainingConfig(learning_rate=2e-3,
                                            snapshot_dir=None))
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ts = trainer.fit(ts, ds, ds, epochs=3)
    assert np.isfinite(trainer.history[-1]["train_loss"])


# ------------------------------------------------- data-parallel resident

def _dp_mesh(d):
    from dcnn_tpu.core.mesh import DATA_AXIS, make_mesh
    return make_mesh((d,), (DATA_AXIS,), devices=jax.devices()[:d])


def test_resident_dp_one_step_matches_manual_pmean():
    """One DP resident step == host-computed pmean of per-shard gradients
    applied with the shared optimizer update (exact; SGD, no augment)."""
    from dcnn_tpu.data.device_dataset import make_resident_epoch_dp, stage_sharded
    from dcnn_tpu.ops.losses import softmax_cross_entropy as ce

    D = 4
    mesh = _dp_mesh(D)
    n_local, lb = 8, 8                     # one step per epoch: k=1
    x, y = _blob_data(n=n_local * D, hw=8)
    model = _small_model()
    opt = SGD(0.05)
    key = jax.random.PRNGKey(3)
    ts0 = create_train_state(model, opt, key)
    ts0b = create_train_state(model, opt, key)

    epoch_fn = make_resident_epoch_dp(model, ce, opt, num_classes=4,
                                      batch_size=lb * D, mesh=mesh)
    # shuffle off: the host replica below assumes contiguous shard slices
    xs, ys = stage_sharded(x, y, mesh, global_shuffle_seed=None)
    rng = jax.random.PRNGKey(7)
    ts1, loss1 = epoch_fn(ts0, xs, ys, rng, 0.05)

    # replicate on host: same per-device permutation derivation
    kperm, kstep = jax.random.split(rng)
    grads_sum = None
    losses = []

    def fwd(params, state, xb, yb, r):
        logits, new_state = model.apply(params, state, xb, training=True, rng=r)
        return ce(logits.astype(jnp.float32), yb), new_state

    states = []
    for dev in range(D):
        perm = np.asarray(jax.random.permutation(
            jax.random.fold_in(kperm, dev), n_local))
        bidx = perm[:lb]
        shard = slice(dev * n_local, (dev + 1) * n_local)
        xb = jnp.asarray(x[shard][bidx].astype(np.float32) / 255.0)
        yb = jnp.asarray(one_hot(y[shard][bidx], 4))
        r = jax.random.fold_in(jax.random.fold_in(kstep, 0), dev)
        (loss, new_state), grads = jax.value_and_grad(
            fwd, has_aux=True)(ts0b.params, ts0b.state, xb, yb, r)
        losses.append(float(loss))
        states.append(new_state)
        grads_sum = grads if grads_sum is None else jax.tree_util.tree_map(
            jnp.add, grads_sum, grads)

    grads_mean = jax.tree_util.tree_map(lambda g: g / D, grads_sum)
    new_params, _ = opt.update(grads_mean, ts0b.opt_state, ts0b.params, 0.05)

    assert float(loss1) == pytest.approx(np.mean(losses), abs=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.params),
                    jax.tree_util.tree_leaves(new_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    # BN state = pmean of per-shard updated stats
    mean_state = jax.tree_util.tree_map(
        lambda *leaves: sum(leaves) / D, *states)
    for a, b in zip(jax.tree_util.tree_leaves(ts1.state),
                    jax.tree_util.tree_leaves(mean_state)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)


def test_resident_dp_trains_to_convergence():
    from dcnn_tpu.data.device_dataset import make_resident_epoch_dp, stage_sharded
    from dcnn_tpu.ops.losses import softmax_cross_entropy as ce

    D = 8
    mesh = _dp_mesh(D)
    x, y = _blob_data(n=256, hw=8, seed=3)
    model = _small_model()
    opt = Adam(2e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    epoch_fn = make_resident_epoch_dp(model, ce, opt, num_classes=4,
                                      batch_size=32, mesh=mesh)
    xs, ys = stage_sharded(x, y, mesh)
    losses = []
    for e in range(15):
        ts, loss = epoch_fn(ts, xs, ys, jax.random.PRNGKey(e), 2e-3)
        losses.append(float(loss))
    assert losses[-1] < 0.5 * losses[0]

    # replicated eval on the gathered split confirms real accuracy
    ds = DeviceDataset(x, y, 4, batch_size=32)
    _, acc = evaluate_classification(
        model, ts.params, ts.state, ce, ds)
    assert acc > 0.9


def test_trainer_fit_sharded_dataset_end_to_end():
    """ShardedDeviceDataset through the normal Trainer: DP resident epochs
    train to high accuracy, val via a replicated DeviceDataset."""
    from dcnn_tpu.core.config import TrainingConfig
    from dcnn_tpu.data import ShardedDeviceDataset

    mesh = _dp_mesh(8)
    x, y = _blob_data(n=256, hw=8, seed=3)
    xv, yv = _blob_data(n=64, hw=8, seed=9)
    model = _small_model()
    opt = Adam(2e-3)
    cfg = TrainingConfig(learning_rate=2e-3, snapshot_dir=None)
    trainer = Trainer(model, opt, "softmax_crossentropy", config=cfg)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    train_ds = ShardedDeviceDataset(x, y, 4, batch_size=32, mesh=mesh)
    assert len(train_ds) == 8   # 32 local samples / 4 local batch
    val_ds = DeviceDataset(xv, yv, 4, batch_size=32)
    ts = trainer.fit(ts, train_ds, val_ds, epochs=12)
    assert trainer.history[-1]["val_acc"] >= 0.9
    assert (trainer.history[-1]["train_loss"]
            < trainer.history[0]["train_loss"])

    # guards: sharded val is rejected with a pointed message; mismatched
    # x/y lengths rejected at construction
    with pytest.raises(TypeError, match="replicated"):
        evaluate_classification(model, ts.params, ts.state,
                                softmax_cross_entropy, train_ds)
    with pytest.raises(ValueError, match="length mismatch"):
        ShardedDeviceDataset(x, y[:-5], 4, batch_size=32, mesh=mesh)


def test_resident_dp_rejects_bad_batch():
    from dcnn_tpu.data.device_dataset import make_resident_epoch_dp
    from dcnn_tpu.ops.losses import softmax_cross_entropy as ce

    mesh = _dp_mesh(4)
    with pytest.raises(ValueError, match="data size"):
        make_resident_epoch_dp(_small_model(), ce, SGD(0.1), num_classes=4,
                               batch_size=30, mesh=mesh)

    # shard smaller than the local batch: raise, don't scan 0 steps to NaN
    from dcnn_tpu.data.device_dataset import stage_sharded
    x, y = _blob_data(n=16)
    model = _small_model()
    opt = SGD(0.1)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    epoch_fn = make_resident_epoch_dp(model, ce, opt, num_classes=4,
                                      batch_size=32, mesh=mesh)
    xs, ys = stage_sharded(x, y, mesh)   # 4 samples/device < local batch 8
    with pytest.raises(ValueError, match="local batch"):
        epoch_fn(ts, xs, ys, jax.random.PRNGKey(1), 0.1)


# ------------------------------------------------- device augmentation ops

@pytest.fixture
def img_batch():
    rng = np.random.default_rng(0)
    return jnp.asarray(rng.random((6, 10, 12, 3)).astype(np.float32))


def test_device_augment_determinism_and_p0(img_batch):
    key = jax.random.PRNGKey(5)
    aug = (DeviceAugmentBuilder("NHWC")
           .brightness().contrast().cutout(4).gaussian_noise()
           .horizontal_flip().vertical_flip().random_crop(2).rotation(20)
           .build())
    a = aug(img_batch, key)
    b = aug(img_batch, key)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert a.shape == img_batch.shape and a.dtype == img_batch.dtype

    # p=0 everywhere is the identity (crop offset pins to center=padding)
    ident = DeviceAugment([
        ad.brightness(p=0), ad.contrast(p=0), ad.cutout(4, p=0),
        ad.gaussian_noise(p=0), ad.horizontal_flip(p=0),
        ad.vertical_flip(p=0), ad.random_crop(2, p=0),
        ad.rotation(20, p=0)])
    np.testing.assert_allclose(np.asarray(ident(img_batch, key)),
                               np.asarray(img_batch), atol=1e-6)


def test_device_flip_p1_matches_jnp_flip(img_batch):
    key = jax.random.PRNGKey(0)
    np.testing.assert_array_equal(
        np.asarray(ad.horizontal_flip(p=1.0, data_format="NHWC")(img_batch, key)),
        np.asarray(jnp.flip(img_batch, axis=2)))
    np.testing.assert_array_equal(
        np.asarray(ad.vertical_flip(p=1.0, data_format="NHWC")(img_batch, key)),
        np.asarray(jnp.flip(img_batch, axis=1)))


def test_device_normalization_matches_host(img_batch):
    mean, std = (0.5, 0.4, 0.3), (0.2, 0.25, 0.3)
    from dcnn_tpu.data.augment import normalization as host_norm
    dev = ad.normalization(mean, std, "NHWC")(img_batch, jax.random.PRNGKey(0))
    host = host_norm(mean, std, "NHWC")(np.asarray(img_batch),
                                        np.random.default_rng(0))
    np.testing.assert_allclose(np.asarray(dev), host, rtol=1e-5, atol=1e-6)


def test_device_cutout_zeroes_a_box():
    x = jnp.ones((4, 16, 16, 1), jnp.float32)
    out = np.asarray(ad.cutout(6, p=1.0, data_format="NHWC")(
        x, jax.random.PRNGKey(2)))
    for i in range(4):
        zeros = int((out[i] == 0).sum())
        assert 0 < zeros <= 36  # box clipped at edges can be smaller


def test_device_random_crop_shifts_content():
    # an impulse image: crop relocates the impulse, never loses shape
    x = np.zeros((8, 9, 9, 1), np.float32)
    x[:, 4, 4, 0] = 1.0
    out = np.asarray(ad.random_crop(3, p=1.0, data_format="NHWC")(
        jnp.asarray(x), jax.random.PRNGKey(0)))
    assert out.shape == x.shape
    assert ((out == 1).sum(axis=(1, 2, 3)) <= 1).all()


def test_device_rotation_small_angle_close_and_nchw():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.random((3, 2, 12, 12)).astype(np.float32))
    out = ad.rotation(1e-4, p=1.0, data_format="NCHW")(x, jax.random.PRNGKey(0))
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), atol=1e-3)


def test_stage_sharded_global_shuffle_debiases_sorted_data():
    """Class-sorted splits must not map whole classes to single devices: the
    seeded global permutation in stage_sharded mixes classes across shards
    (ADVICE r3 #1 — the local per-epoch shuffle cannot fix a biased shard)."""
    from dcnn_tpu.data.device_dataset import stage_sharded

    D = 4
    mesh = _dp_mesh(D)
    n = 32
    x = np.zeros((n, 4, 4, 1), np.uint8)
    y = np.repeat(np.arange(D), n // D)        # class-sorted: device d ↔ class d
    xs, ys = stage_sharded(x, y, mesh)
    per_shard = np.asarray(ys).reshape(D, n // D)
    # every shard should see >1 class; unshuffled staging would see exactly 1
    assert all(len(np.unique(s)) > 1 for s in per_shard)
    # and the permutation is deterministic for a fixed seed
    _, ys2 = stage_sharded(x, y, mesh)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(ys2))
    # opt-out restores contiguous placement
    _, ys3 = stage_sharded(x, y, mesh, global_shuffle_seed=None)
    assert all(len(np.unique(s)) == 1
               for s in np.asarray(ys3).reshape(D, n // D))
