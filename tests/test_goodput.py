"""Goodput plane tests (obs/goodput.py, obs/anomaly.py — PR 18).

Contracts, all sleep-free via injectable clocks/detectors/profilers:

- **ledger exactness**: exclusive attribution over a synthetic span set —
  overlap resolved by claim order (an H2D put under compute is hidden,
  only the exposed tail is a stall), union math never double counts, and
  a fully-instrumented window has ``unattributed ≈ 0``;
- **BENCH_r05 replay**: the r5 capture's shape (8.1 s of exposed
  ``h2d.put`` in an 8.8 s wall) classifies ``feed_bound`` — the
  acceptance scenario;
- **classifier hysteresis**: boundary noise around the entry threshold
  cannot flap the state (exit margin), and a real shift flips only after
  ``confirm_windows`` consecutive windows;
- **anomaly episodes**: a step-time band breach fires exactly one
  capture per episode — a sustained regression captures once, not once
  per step — and :func:`~dcnn_tpu.obs.anomaly.suppress` fences expected
  stalls; the xprof profile opens through the non-raising ``try_trace``
  and the busy path is counted, never raised;
- **/goodput endpoint**: real HTTP GET against a live TelemetryServer;
- **serving slot goodput**: time-weighted occupied/idle/draining
  decomposition in ServeMetrics and the fleet aggregation;
- **GP01 lint**: the live package maps every recorded span, and an
  unmapped span in a synthetic package is a finding.
"""

import json
import urllib.request

import pytest

from dcnn_tpu.obs import MetricsRegistry, TelemetryServer
from dcnn_tpu.obs.anomaly import AnomalyMonitor, EwmaBand, suppress
from dcnn_tpu.obs.goodput import (BUCKETS, SPAN_BUCKETS, STATE_CODES,
                                  BottleneckClassifier, GoodputLedger,
                                  GoodputMonitor, attribute, bucket_of,
                                  classify_window, summarize)
from dcnn_tpu.obs.tracer import Tracer


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _ev(name, t0, dur, **args):
    """A Tracer.events()-shaped dict."""
    return {"name": name, "ts_s": t0, "dur_s": dur, "track": "t",
            "args": args}


# ------------------------------------------------------------ attribution

def test_attribute_exclusive_overlap_claim_order():
    """compute 0–1 fully hides the first half of an h2d.put 0.5–1.5;
    only the exposed 0.5 s lands in h2d, and the exposed feed tail in
    feed_stall. Every second attributed exactly once."""
    doc = attribute([
        _ev("train.step", 0.0, 1.0),
        _ev("h2d.put", 0.5, 1.0),       # 0.5 hidden under compute
        _ev("feed.gather", 1.5, 0.5),   # fully exposed
    ])
    assert doc["wall_s"] == pytest.approx(2.0)
    assert doc["buckets"]["compute"] == pytest.approx(1.0)
    assert doc["buckets"]["h2d"] == pytest.approx(0.5)
    assert doc["buckets"]["feed_stall"] == pytest.approx(0.5)
    assert doc["unattributed_s"] == pytest.approx(0.0)
    assert doc["goodput_fraction"] == pytest.approx(0.5)
    # total conservation: buckets + unattributed == wall
    assert (sum(doc["buckets"].values()) + doc["unattributed_s"]
            == pytest.approx(doc["wall_s"]))


def test_attribute_union_never_double_counts():
    """Three overlapping same-bucket spans count their union once."""
    doc = attribute([_ev("feed.gather", 0.0, 1.0),
                     _ev("feed.augment", 0.5, 1.0),
                     _ev("feed.pack", 1.0, 1.0)])
    assert doc["buckets"]["feed_stall"] == pytest.approx(2.0)
    assert doc["attributed_s"] == pytest.approx(2.0)


def test_attribute_structural_spans_excluded():
    """train.epoch is a container: its children carry the time, the
    envelope itself must not double-attribute (or widen the extent)."""
    doc = attribute([_ev("train.epoch", 0.0, 10.0),
                     _ev("train.step", 1.0, 2.0)])
    assert doc["wall_s"] == pytest.approx(2.0)   # extent = the step span
    assert doc["buckets"]["compute"] == pytest.approx(2.0)
    assert doc["unattributed_s"] == pytest.approx(0.0)


def test_attribute_window_clipping():
    doc = attribute([_ev("train.step", 0.0, 10.0)], t0=4.0, t1=6.0)
    assert doc["wall_s"] == pytest.approx(2.0)
    assert doc["buckets"]["compute"] == pytest.approx(2.0)
    # and a gap the spans don't cover is unattributed, not invented
    doc = attribute([_ev("train.step", 0.0, 1.0)], t0=0.0, t1=4.0)
    assert doc["unattributed_s"] == pytest.approx(3.0)
    assert doc["goodput_fraction"] == pytest.approx(0.25)


def test_bench_r05_shape_classifies_feed_bound():
    """The r5 capture: 8.1 s of exposed put against a 0.7 s step in an
    8.8 s wall — the ledger must call it feed-bound (acceptance)."""
    doc = summarize([_ev("h2d.put", 0.0, 8.1),
                     _ev("train.step", 8.1, 0.7)], t0=0.0, t1=8.8)
    assert doc["verdict"] == "feed_bound"
    assert doc["buckets"]["h2d"] == pytest.approx(8.1)


def test_classify_window_rule_order():
    def doc(**b):
        buckets = {k: 0.0 for k in BUCKETS}
        buckets.update(b)
        return {"wall_s": 10.0, "buckets": buckets}
    assert classify_window(doc(compute=9.0)) == "compute_bound"
    assert classify_window(doc(compile=4.0, compute=6.0)) == "compile_bound"
    assert classify_window(doc(feed_stall=3.0, h2d=2.5)) == "feed_bound"
    assert classify_window(doc(checkpoint=3.0, recovery=2.5)) == "io_bound"
    assert classify_window(doc(compute=3.0)) == "healthy"
    assert classify_window({"wall_s": 0.0, "buckets": {}}) == "healthy"


def test_bucket_of_globs_and_unknown():
    assert bucket_of("train.step") == "compute"
    assert bucket_of("nobody.knows.this") is None
    assert bucket_of("demo.9", {"demo.*": "compute"}) == "compute"


def test_span_buckets_values_are_buckets():
    """Every non-None value in the normative table is a real bucket."""
    assert set(v for v in SPAN_BUCKETS.values() if v is not None) <= \
        set(BUCKETS)


# ------------------------------------------------------------- classifier

def _window(wall, **b):
    buckets = {k: 0.0 for k in BUCKETS}
    buckets.update(b)
    return {"wall_s": wall, "buckets": buckets}


class RecordingStore:
    def __init__(self):
        self.series = {}

    def add(self, name, value, **kw):
        self.series.setdefault(name, []).append(value)


def test_classifier_boundary_noise_does_not_flap():
    """Feed fraction oscillating 0.48↔0.55 around the 0.50 entry: once
    feed-bound, the exit margin (0.50 − 0.15) holds the state."""
    c = BottleneckClassifier(confirm_windows=2)
    for _ in range(2):
        c.observe(_window(10.0, feed_stall=5.5, compute=4.5))
    assert c.state == "feed_bound" and c.flips == 1
    for frac in (4.8, 5.5, 4.6, 5.2, 4.8):   # noise inside the band
        c.observe(_window(10.0, feed_stall=frac, compute=10.0 - frac))
    assert c.state == "feed_bound" and c.flips == 1


def test_classifier_real_shift_flips_after_confirm_windows():
    flips = []
    store = RecordingStore()
    c = BottleneckClassifier(store=store, confirm_windows=3,
                             on_change=lambda o, n: flips.append((o, n)))
    for _ in range(3):
        c.observe(_window(10.0, feed_stall=7.0, compute=3.0))
    assert c.state == "feed_bound"
    # genuinely compute-dominated now: feed drops below 0.35 exit line
    for i in range(3):
        c.observe(_window(10.0, compute=9.0, feed_stall=1.0))
        if i < 2:
            assert c.state == "feed_bound"   # still dwelling
    assert c.state == "compute_bound"
    assert flips == [("healthy", "feed_bound"),
                     ("feed_bound", "compute_bound")]
    # tsdb series: the state code plus the 0/1 per-state series the
    # shipped alert rules consume
    assert store.series["goodput_bottleneck_state"][-1] == \
        float(STATE_CODES["compute_bound"])
    assert store.series["goodput_bottleneck_compute_bound"][-1] == 1.0
    assert store.series["goodput_bottleneck_feed_bound"][-1] == 0.0


def test_classifier_interrupted_streak_resets_dwell():
    c = BottleneckClassifier(confirm_windows=2)
    c.observe(_window(10.0, feed_stall=7.0, compute=3.0))
    c.observe(_window(10.0, compute=3.0))              # healthy interlude
    c.observe(_window(10.0, feed_stall=7.0, compute=3.0))
    assert c.state == "healthy"                        # streak broken
    c.observe(_window(10.0, feed_stall=7.0, compute=3.0))
    assert c.state == "feed_bound"


# ---------------------------------------------------------------- ledger

def _make_tracer(clock):
    return Tracer(capacity=4096, clock=clock, enabled=True)


def test_ledger_snapshot_publishes_gauges():
    clock = FakeClock(100.0)
    tr = _make_tracer(clock)          # epoch = 100.0
    reg = MetricsRegistry()
    led = GoodputLedger(tracer=tr, registry=reg)
    tr.record_span("train.step", 100.0, 101.0)
    tr.record_span("h2d.put", 101.0, 101.5, bytes=5 * 10**9)
    clock.t = 102.0
    doc = led.snapshot(t0=0.0, t1=2.0, publish=True)
    snap = reg.snapshot()
    assert snap["goodput_fraction"] == pytest.approx(0.5)
    assert snap["goodput_wall_seconds"] == pytest.approx(2.0)
    assert snap["goodput_compute_seconds"] == pytest.approx(1.0)
    assert snap["goodput_h2d_seconds"] == pytest.approx(0.5)
    assert snap["goodput_unattributed_seconds"] == pytest.approx(0.5)
    # live bandwidth over the put union: 5 GB in 0.5 s = 10 GB/s
    assert snap["goodput_h2d_gbps"] == pytest.approx(10.0)
    assert doc["steps"] == pytest.approx(1.0)
    # no model costs wired -> the gauge is absent, not a lying 0.0
    assert "mfu_live" not in snap and doc["mfu_live"] is None


def test_ledger_trailing_window_and_abs_anchor():
    clock = FakeClock(50.0)
    tr = _make_tracer(clock)
    led = GoodputLedger(tracer=tr, registry=MetricsRegistry())
    tr.record_span("train.step", 50.0, 51.0)    # rel 0..1
    tr.record_span("train.step", 58.0, 59.0)    # rel 8..9
    clock.t = 60.0
    # trailing 5 s window ending "now" (rel 10): only the second step
    doc = led.snapshot(window_s=5.0)
    assert doc["buckets"]["compute"] == pytest.approx(1.0)
    assert doc["wall_s"] == pytest.approx(5.0)
    # clock-domain anchor (an epoch-start perf_counter stamp)
    doc = led.snapshot(t0_abs=50.0)
    assert doc["wall_s"] == pytest.approx(10.0)
    assert doc["buckets"]["compute"] == pytest.approx(2.0)


def test_ledger_mfu_live_and_chunk_steps():
    clock = FakeClock(0.0)
    tr = _make_tracer(clock)
    reg = MetricsRegistry()
    led = GoodputLedger(tracer=tr, registry=reg)
    led.set_model_costs(flops_per_sample=1e9, peak_tflops=1.0,
                        samples_per_step=100.0)
    # a chunk span covering 10 inner steps in 2 s -> 5 steps/s
    tr.record_span("train.chunk", 0.0, 2.0, steps=10)
    clock.t = 2.0
    doc = led.snapshot(t0=0.0, t1=2.0, publish=True)
    assert doc["steps"] == pytest.approx(10.0)
    assert doc["step_rate"] == pytest.approx(5.0)
    # 5 steps/s × 100 samples × 1e9 flops = 5e11 flop/s vs 1e12 peak
    assert doc["mfu_live"] == pytest.approx(0.5)
    assert reg.snapshot()["mfu_live"] == pytest.approx(0.5)


# --------------------------------------------------------------- anomaly

class FakeFlight:
    def __init__(self, path="/tmp/bundle"):
        self.calls = []
        self.path = path

    def record(self, trigger, **kw):
        self.calls.append((trigger, kw))
        return self.path


class FakeProfileCM:
    def __init__(self, log):
        self.log = log

    def __enter__(self):
        self.log.append("enter")
        return "/tmp/prof"

    def __exit__(self, *exc):
        self.log.append("exit")
        return False


def _anomaly(flight=None, profiler=None, **kw):
    kw.setdefault("detector", EwmaBand(warmup=4, min_rel=0.5))
    return AnomalyMonitor(registry=MetricsRegistry(),
                          flight=flight if flight is not None
                          else FakeFlight(),
                          profiler=profiler, **kw)


def test_ewma_band_warmup_and_regression_does_not_learn():
    band = EwmaBand(warmup=4, min_rel=0.5, band=3.0)
    assert band.threshold() is None
    for _ in range(4):
        assert band.observe(1.0) is False     # warmup never breaches
    thr = band.threshold()
    assert thr == pytest.approx(1.5)          # rel floor dominates
    mean_before = band.mean
    for _ in range(10):
        assert band.observe(5.0) is True      # sustained regression
    assert band.mean == pytest.approx(mean_before)  # band didn't learn it


def test_anomaly_exactly_one_capture_per_episode():
    log = []
    flight = FakeFlight()
    mon = _anomaly(flight=flight, profiler=lambda d: FakeProfileCM(log),
                   profile_steps=2, recover_samples=3)
    for _ in range(4):
        assert mon.observe_step(1.0) is False
    # sustained 9x regression: first sample opens THE episode
    assert mon.observe_step(9.0) is True
    for _ in range(5):
        assert mon.observe_step(9.0) is False   # same episode, no refire
    st = mon.stats()
    assert st["episodes"] == 1 and st["captures"] == 1
    assert len(flight.calls) == 1
    trigger, kw = flight.calls[0]
    assert trigger == "goodput_anomaly"
    assert kw["extra"]["trigger_kind"] == "step_time_breach"
    # profile entered on capture, closed after profile_steps further steps
    assert log == ["enter", "exit"]
    # recovery closes the episode; the NEXT breach is a new one
    for _ in range(3):
        mon.observe_step(1.0)
    assert mon.observe_step(9.0) is True
    assert mon.stats()["episodes"] == 2 and len(flight.calls) == 2


def test_anomaly_recovery_requires_consecutive_in_band():
    mon = _anomaly(profiler=lambda d: None, recover_samples=3)
    for _ in range(4):
        mon.observe_step(1.0)
    mon.observe_step(9.0)
    # 2 ok, then a breach: streak resets, episode stays open
    mon.observe_step(1.0)
    mon.observe_step(1.0)
    assert mon.observe_step(9.0) is False
    assert mon.stats()["episodes"] == 1


def test_anomaly_ledger_snapshot_rides_the_bundle():
    flight = FakeFlight()
    mon = _anomaly(flight=flight, profiler=lambda d: None)
    for _ in range(4):
        mon.observe_step(1.0)
    mon.observe_step(9.0, ledger_doc={"wall_s": 30.0, "bottleneck": "x"})
    assert flight.calls[0][1]["extra"]["ledger"]["wall_s"] == 30.0


def test_anomaly_suppress_fences_expected_stalls():
    mon = _anomaly(profiler=lambda d: None)
    for _ in range(4):
        mon.observe_step(1.0)
    mean_before = mon.detector.mean
    with suppress():
        with suppress():                      # re-entrant
            for _ in range(10):
                assert mon.observe_step(50.0) is False
        assert mon.observe_step(50.0) is False
    assert mon.stats()["episodes"] == 0
    assert mon.detector.mean == pytest.approx(mean_before)
    # fence lifted: the same sample now opens an episode
    assert mon.observe_step(50.0) is True


def test_anomaly_profiler_busy_counted_not_raised():
    reg = MetricsRegistry()
    mon = AnomalyMonitor(registry=reg, flight=FakeFlight(),
                         detector=EwmaBand(warmup=2),
                         profiler=lambda d: None)   # always busy
    mon.observe_step(1.0)
    mon.observe_step(1.0)
    mon.observe_step(9.0)
    assert reg.snapshot()["goodput_capture_profile_skipped_total"] == 1
    assert reg.snapshot()["goodput_anomaly_episodes_total"] == 1


def test_anomaly_flip_capture_and_opt_out():
    flight = FakeFlight()
    mon = _anomaly(flight=flight, profiler=lambda d: None)
    mon.on_classification_flip("healthy", "feed_bound",
                               ledger_doc={"wall_s": 1.0})
    assert len(flight.calls) == 1
    assert flight.calls[0][1]["extra"]["detail"]["transition"] == \
        "healthy->feed_bound"
    quiet = _anomaly(flight=FakeFlight(), profiler=lambda d: None,
                     flip_captures=False)
    quiet.on_classification_flip("healthy", "feed_bound")
    assert quiet.stats()["episodes"] == 0


def test_anomaly_close_exits_open_profile():
    log = []
    mon = _anomaly(profiler=lambda d: FakeProfileCM(log),
                   profile_steps=100)
    for _ in range(4):
        mon.observe_step(1.0)
    mon.observe_step(9.0)
    assert log == ["enter"]
    mon.close()
    assert log == ["enter", "exit"]


# ------------------------------------------------------------- try_trace

def test_try_trace_claim_and_busy_counter(tmp_path, monkeypatch):
    from dcnn_tpu.obs import get_registry
    from dcnn_tpu.train import profiling
    import jax
    monkeypatch.setattr(jax.profiler, "start_trace", lambda p: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    busy0 = get_registry().snapshot().get("profiler_trace_busy_total", 0)
    cm = profiling.try_trace(str(tmp_path))
    assert cm is not None                     # claim taken at call time
    with cm:
        # slot held: the concurrent claim loses politely
        assert profiling.try_trace(str(tmp_path)) is None
        with pytest.raises(RuntimeError):
            profiling.trace(str(tmp_path))    # raising form still raises
    assert get_registry().snapshot()["profiler_trace_busy_total"] == \
        busy0 + 1
    # released on exit: the next claim wins again
    cm2 = profiling.try_trace(str(tmp_path))
    assert cm2 is not None
    with cm2 as path:
        assert str(tmp_path) in path


# ------------------------------------------------- monitor + /goodput

def test_monitor_poll_flip_feeds_anomaly_and_endpoint():
    clock = FakeClock(0.0)
    tr = _make_tracer(clock)
    reg = MetricsRegistry()
    store = RecordingStore()
    flight = FakeFlight()
    anomaly = AnomalyMonitor(registry=reg, flight=flight,
                             detector=EwmaBand(warmup=4),
                             profiler=lambda d: None)
    mon = GoodputMonitor(tracer=tr, registry=reg, store=store,
                         window_s=10.0, anomaly=anomaly,
                         classifier=BottleneckClassifier(
                             store=store, confirm_windows=1))
    tr.record_span("h2d.put", 0.0, 8.0)
    clock.t = 10.0
    doc = mon.poll()
    assert doc["bottleneck"] == "feed_bound"
    assert reg.snapshot()["goodput_bottleneck_state"] == \
        float(STATE_CODES["feed_bound"])
    # the confirmed flip fired one anomaly capture through the chain
    assert len(flight.calls) == 1
    assert flight.calls[0][1]["extra"]["trigger_kind"] == "bottleneck_flip"

    srv = TelemetryServer(registry=reg, port=0)
    mon.attach(srv)
    srv.start()
    try:
        with urllib.request.urlopen(srv.url + "/goodput", timeout=10) as r:
            body = json.loads(r.read())
    finally:
        srv.stop()
    assert body["window_s"] == 10.0
    assert body["bottleneck"]["state"] == "feed_bound"
    assert body["bottleneck"]["confirm_windows"] == 1
    assert set(body["ledger"]["buckets"]) == set(BUCKETS)
    assert body["anomaly"]["episodes"] == 1
    mon.close()


def test_monitor_observe_step_routes_to_detector():
    reg = MetricsRegistry()
    anomaly = AnomalyMonitor(registry=reg, flight=FakeFlight(),
                             detector=EwmaBand(warmup=2),
                             profiler=lambda d: None)
    mon = GoodputMonitor(tracer=Tracer(clock=FakeClock(), enabled=True),
                         registry=reg, window_s=1.0, anomaly=anomaly)
    mon.observe_step(1.0)
    mon.observe_step(1.0)
    mon.observe_step(9.0)
    assert anomaly.stats()["episodes"] == 1


# ------------------------------------------------------ shipped alerts

def test_goodput_alert_rules_fire_on_sustained_feed_bound():
    from dcnn_tpu.obs.rules import RuleEngine, goodput_alert_rules
    from dcnn_tpu.obs.tsdb import TimeSeriesStore
    clock = FakeClock(1000.0)
    store = TimeSeriesStore(clock=clock)
    engine = RuleEngine(store, registry=MetricsRegistry(),
                        flight=FakeFlight(), clock=clock)
    for rule in goodput_alert_rules(window_s=60.0, for_s=30.0):
        engine.add_alert(rule)
    # classifier holding feed-bound: 0/1 series pinned at 1 long enough
    for _ in range(8):
        store.add("goodput_bottleneck_feed_bound", 1.0)
        store.add("goodput_bottleneck_compile_bound", 0.0)
        store.add("goodput_fraction", 0.9)
        engine.evaluate()
        clock.advance(10.0)
    assert engine.firing() == ["goodput_feed_bound_sustained"]
    # a single healthy window resolves it (min_over_time drops below 1)
    store.add("goodput_bottleneck_feed_bound", 0.0)
    engine.evaluate()
    assert engine.firing() == []


# ------------------------------------------------- serving slot goodput

def test_serve_metrics_slot_occupancy_decomposition():
    from dcnn_tpu.serve.metrics import ServeMetrics
    clock = FakeClock(0.0)
    m = ServeMetrics(clock=clock)
    assert m.snapshot()["slot_goodput"] is None   # no data != 100% idle
    m.record_slot_state("idle")
    clock.advance(3.0)
    m.record_slot_state("occupied")
    clock.advance(6.0)
    m.record_slot_state("draining")
    clock.advance(1.0)
    s = m.snapshot()
    assert s["slot_state"] == "draining"
    # the OPEN draining interval is credited too: 3 + 6 + 1 = 10
    assert s["slot_seconds"] == pytest.approx(
        {"idle": 3.0, "occupied": 6.0, "draining": 1.0})
    assert s["slot_goodput"] == pytest.approx(0.6)
    with pytest.raises(ValueError):
        m.record_slot_state("on_fire")

    def scalar(text, name):
        line = [l for l in text.splitlines()
                if l.startswith(name + " ")][0]
        return float(line.split()[-1])
    text = m.prometheus()
    assert scalar(text, "serve_slot_goodput") == pytest.approx(0.6)
    assert scalar(text, "serve_slot_occupied_seconds_total") == \
        pytest.approx(6.0)
    assert scalar(text, "serve_slot_idle_seconds_total") == \
        pytest.approx(3.0)


class SlotFakeEngine:
    """Batcher-compatible engine without jax (tests/test_router idiom)."""

    input_shape = (4,)
    max_batch = 8
    bucket_sizes = [1, 2, 4, 8]
    name = "slotfake"
    batch_invariant = True

    def bucket_for(self, n):
        for b in self.bucket_sizes:
            if b >= n:
                return b
        raise ValueError(n)

    def pad_to_bucket(self, x):
        import numpy as np
        x = np.asarray(x, np.float32)
        n = x.shape[0]
        b = self.bucket_for(n)
        if b > n:
            x = np.concatenate([x, np.zeros((b - n, 4), np.float32)])
        return x, n

    def run_padded(self, x):
        import numpy as np
        return np.asarray(x, np.float32)


def test_batcher_marks_slot_states_over_lifecycle():
    # start=False + step(): the occupied->idle transition happens
    # synchronously under the test's control (the threaded loop flips
    # back to idle the instant a batch completes — unobservable reliably)
    import numpy as np
    from dcnn_tpu.serve.batcher import DynamicBatcher
    b = DynamicBatcher(SlotFakeEngine(), max_wait_ms=1.0, start=False)
    assert b.metrics.snapshot()["slot_state"] == "idle"   # from birth
    fut = b.submit(np.ones((1, 4), np.float32))
    assert b.step() == 1
    fut.result(timeout=10)
    snap = b.metrics.snapshot()
    assert snap["slot_state"] == "idle"       # batch done, slot free
    assert snap["slot_seconds"]["occupied"] > 0.0
    b.shutdown()
    assert b.metrics.snapshot()["slot_state"] == "draining"


def test_fleet_slot_goodput_aggregation_skips_non_serving():
    from dcnn_tpu.obs.fleet import FleetAggregator
    last = {
        "replica-a": {"values": {"serve_slot_occupied_seconds_total": 6.0,
                                 "serve_slot_idle_seconds_total": 3.0,
                                 "serve_slot_draining_seconds_total": 1.0}},
        "replica-b": {"values": {"serve_slot_occupied_seconds_total": 2.0,
                                 "serve_slot_idle_seconds_total": 8.0,
                                 "serve_slot_draining_seconds_total": 0.0}},
        "trainer": {"values": {"goodput_fraction": 0.9}},  # no slot series
    }
    doc = FleetAggregator._slot_goodput(last)
    assert set(doc["replicas"]) == {"replica-a", "replica-b"}
    assert doc["replicas"]["replica-a"]["goodput"] == pytest.approx(0.6)
    assert doc["fleet"]["goodput"] == pytest.approx(8.0 / 20.0)


# ------------------------------------------------------------- GP01 lint

def test_gp01_live_package_fully_mapped():
    """Every span the package records maps to a bucket — the contract
    that keeps live attribution exhaustive."""
    from dcnn_tpu.analysis.coverage import check_span_coverage
    findings = check_span_coverage("dcnn_tpu")
    assert [f for f in findings if not f.suppressed] == []


def test_gp01_unmapped_span_is_a_finding(tmp_path):
    import textwrap
    from dcnn_tpu.analysis.coverage import check_span_coverage
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "prod.py").write_text(textwrap.dedent("""
        def f(tracer, k):
            with tracer.span("demo.mystery"):
                pass
            with tracer.span(f"demo.shard_{k}"):
                pass
        """))
    findings = check_span_coverage(
        str(pkg), mapping={"demo.shard_*": "h2d"})
    assert [f.detail for f in findings if not f.suppressed] == \
        ["demo.mystery"]
    # mapped -> clean; inline disable -> suppressed, not gone
    assert not check_span_coverage(
        str(pkg), mapping={"demo.mystery": "compute",
                           "demo.shard_*": "h2d"})
    (pkg / "prod.py").write_text(textwrap.dedent("""
        def f(tracer):
            with tracer.span("demo.mystery"):  # dcnn: disable=GP01
                pass
        """))
    findings = check_span_coverage(str(pkg), mapping={})
    assert findings and all(f.suppressed for f in findings)


def test_gp01_dynamic_span_name_unresolvable(tmp_path):
    import textwrap
    from dcnn_tpu.analysis.coverage import check_span_coverage
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "prod.py").write_text(textwrap.dedent("""
        def f(tracer, name):
            with tracer.span(name):
                pass
        """))
    findings = check_span_coverage(str(pkg), mapping={})
    assert any(f.detail == "<unresolvable>" for f in findings)
    # non-span .begin() APIs (no dotted family.name literal) don't trip
    (pkg / "prod.py").write_text(textwrap.dedent("""
        def f(txn):
            txn.begin("readwrite")
        """))
    assert not check_span_coverage(str(pkg), mapping={})


def test_regress_gate_carries_goodput_fraction_spec():
    """The r06+ capture gate knows the metric, at the wide tolerance a
    scheduling-noisy fraction needs; pre-r06 captures simply lack the
    path (skip-not-lie — compare.py skips absent metrics)."""
    from dcnn_tpu.obs.regress import DEFAULT_METRICS
    spec = {m.name: m for m in DEFAULT_METRICS}["goodput_fraction"]
    assert spec.path == "telemetry_essentials.goodput.goodput_fraction"
    assert spec.higher_is_better and spec.tolerance == 0.25


# ------------------------------------------- live streaming attribution

def test_streaming_run_attributes_wall_time():
    """Acceptance: an instrumented streaming epoch's span extent is
    ≥ 95% attributed — the feed/transfer/step spans cover the wall."""
    import numpy as np
    import jax
    from dcnn_tpu.data import StreamingDeviceDataset, make_shard_step, \
        train_streaming_epoch
    from dcnn_tpu.nn.builder import SequentialBuilder
    from dcnn_tpu.obs import configure, get_tracer
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.train.trainer import create_train_state

    rng = np.random.default_rng(0)
    x = rng.integers(0, 255, size=(512, 28, 28, 1)).astype(np.uint8)
    y = rng.integers(0, 10, size=512).astype(np.int64)
    model = (SequentialBuilder(name="gp_cnn", data_format="NHWC")
             .input((28, 28, 1))
             .conv2d(16, 3, padding=1).activation("relu")
             .flatten().dense(10).build())
    opt = SGD(0.05)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    ds = StreamingDeviceDataset(x, y, 10, batch_size=32, shard_batches=4)
    step = make_shard_step(model, softmax_cross_entropy, opt,
                           num_classes=10, batch_size=32, shard_batches=4)
    t = configure(enabled=True)
    t.clear()
    try:
        train_streaming_epoch(step, ts, ds, jax.random.PRNGKey(1), 0.05)
        doc = attribute(get_tracer().events())
    finally:
        configure(enabled=False)
        t.clear()  # the global buffer: later tests assert it empty
    assert doc["wall_s"] > 0
    assert doc["unattributed_s"] < 0.05 * doc["wall_s"], doc
