"""Monitoring-plane store tests: ring eviction, downsample tier, query
ops, persistence atomicity, sampler overhead bounds, flight-bundle
history attachment, and the postmortem CLI (dcnn_tpu/obs/tsdb.py)."""

import json
import os
import threading

import pytest

from dcnn_tpu.obs.flight import FlightRecorder
from dcnn_tpu.obs.registry import MetricsRegistry, get_registry
from dcnn_tpu.obs.trace import inspect_bundle
from dcnn_tpu.obs.tsdb import (TimeSeriesStore, TsdbSampler, load_history,
                               main as tsdb_main, render_series_key,
                               series_stats, sparkline, summarize_history)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def make_store(fc, **kw):
    kw.setdefault("retention", 8)
    kw.setdefault("downsample", 4)
    kw.setdefault("coarse_retention", 3)
    return TimeSeriesStore(clock=fc, **kw)


# ------------------------------------------------------------ ring buffers

def test_ring_eviction_fixed_memory():
    """Fine tier holds exactly `retention` points no matter how many are
    written — memory is fixed by (series x retention), not run length."""
    fc = FakeClock()
    store = make_store(fc, retention=8)
    for i in range(100):
        fc.advance(1.0)
        store.add("g", float(i))
    pts = store.range("g")
    assert len(pts) == 8
    assert [v for _, v in pts] == [float(i) for i in range(92, 100)]
    assert store.points() == 8


def test_downsample_tier_correctness():
    """Every `downsample` fine points flush one coarse (t, min, max,
    mean, count) entry; the coarse ring evicts at its own capacity."""
    fc = FakeClock()
    store = make_store(fc, retention=8, downsample=4, coarse_retention=3)
    for i in range(1, 21):                      # 20 points -> 5 buckets
        fc.advance(1.0)
        store.add("g", float(i))
    coarse = store.range("g", tier="coarse")
    assert len(coarse) == 3                     # capacity, oldest evicted
    # newest bucket covers points 17..20
    t, mn, mx, mean, n = coarse[-1]
    assert (t, mn, mx, mean, n) == (20.0, 17.0, 20.0, 18.5, 4)
    # a partial bucket is not flushed early
    fc.advance(1.0)
    store.add("g", 99.0)
    assert len(store.range("g", tier="coarse")) == 3


def test_labeled_series_keys_and_cardinality_bound():
    fc = FakeClock()
    store = make_store(fc, max_series=2)
    assert render_series_key("m", {"b": "2", "a": "1"}) == 'm{a="1",b="2"}'
    store.add("m", 1.0, labels={"replica": "r0"})
    store.add("m", 2.0, labels={"replica": "r1"})
    store.add("m", 3.0, labels={"replica": "r2"})  # past the bound
    assert len(store.series_names()) == 2
    assert store.dropped_series == 1
    # existing series still accept points past the bound
    store.add("m", 9.0, labels={"replica": "r0"})
    assert store.latest('m{replica="r0"}')[1] == 9.0


# ------------------------------------------------------------- query ops

def test_query_ops_delta_rate_over_time():
    fc = FakeClock()
    store = make_store(fc, retention=64)
    for i in range(10):
        fc.advance(1.0)
        store.add("c_total", 5.0 * (i + 1))    # +5/s counter
        store.add("g", float(i % 4))
    # window [5, 10]: six points, values 30..50 -> delta 25 over 5 s
    assert store.delta("c_total", 5.0) == pytest.approx(25.0)
    assert store.rate("c_total", 5.0) == pytest.approx(5.0)
    assert store.max_over_time("g", 4.0) == 3.0
    assert store.min_over_time("g", 4.0) == 0.0
    assert store.avg_over_time("g", 100.0) == pytest.approx(1.3)
    assert store.latest("g")[1] == 1.0
    # windows with too few points answer None, not garbage
    assert store.delta("c_total", 0.5) is None
    assert store.rate("nope", 5.0) is None


def test_quantile_over_time_from_bucket_deltas():
    """Windowed histogram quantile: only observations INSIDE the window
    count, so an old latency spike ages out of the p99."""
    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    store = TimeSeriesStore(retention=64, clock=fc)
    h = reg.histogram("lat_seconds", start=1e-3, factor=2.0, buckets=12)
    sampler = TsdbSampler(store, registry=reg, clock=fc)
    # phase 1: slow traffic (~0.1 s)
    for _ in range(10):
        fc.advance(1.0)
        h.observe(0.1)
        sampler.sample_once()
    # phase 2: fast traffic (~2 ms)
    for _ in range(10):
        fc.advance(1.0)
        h.observe(0.002)
        sampler.sample_once()
    recent = store.quantile_over_time("lat_seconds", 0.99, 8.0)
    overall = store.quantile_over_time("lat_seconds", 0.99, 100.0)
    assert recent is not None and recent < 0.01     # spike aged out
    assert overall is not None and overall > 0.05   # still in long window
    assert store.quantile_over_time("lat_seconds", 0.5, 8.0) < 0.01
    with pytest.raises(ValueError):
        store.quantile_over_time("lat_seconds", 1.5, 8.0)
    assert store.quantile_over_time("absent", 0.9, 8.0) is None


def test_sample_registry_counters_gauges_histograms():
    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    store = TimeSeriesStore(clock=fc)
    reg.counter("c_total").inc(3)
    reg.gauge("g").set(7.0)
    reg.histogram("h_seconds").observe(0.5)
    fc.advance(1.0)
    wrote = store.sample_registry(reg)
    assert wrote >= 4
    assert store.latest("c_total")[1] == 3.0
    assert store.latest("g")[1] == 7.0
    assert store.latest("h_seconds_count")[1] == 1.0
    assert store.latest("h_seconds_sum")[1] == 0.5
    assert any(k.startswith("h_seconds_bucket{le=")
               for k in store.series_names())


# ---------------------------------------------------------- sampler bounds

def test_sampler_tick_under_5ms_on_live_registry():
    """The acceptance overhead bound: one sampling pass over the live
    process-global registry (plus a realistically-instrumented private
    one) stays under 5 ms."""
    import timeit

    reg = MetricsRegistry()
    for i in range(80):
        reg.counter(f"c{i}_total").inc(i)
    for i in range(12):
        h = reg.histogram(f"h{i}_seconds")
        for j in range(64):
            h.observe(0.001 * (j + 1))
    store = TimeSeriesStore()
    sampler = TsdbSampler(store, registry=reg)
    best = min(timeit.repeat(sampler.sample_once, number=1, repeat=5))
    assert best < 0.005, f"sampler tick took {best * 1e3:.2f} ms"
    # and the live global registry (whatever this test process holds)
    live = TsdbSampler(TimeSeriesStore(), registry=get_registry())
    best = min(timeit.repeat(live.sample_once, number=1, repeat=5))
    assert best < 0.005, f"live-registry tick took {best * 1e3:.2f} ms"


def test_sampler_disabled_zero_threads():
    """Not starting the sampler costs nothing: no threads, no points."""
    before = threading.active_count()
    store = TimeSeriesStore()
    TsdbSampler(store, registry=MetricsRegistry())
    assert threading.active_count() == before
    assert not [t for t in threading.enumerate()
                if "tsdb-sampler" in t.name]
    assert store.points() == 0


def test_sampler_thread_lifecycle():
    reg = MetricsRegistry()
    reg.gauge("g").set(1.0)
    sampler = TsdbSampler(TimeSeriesStore(), registry=reg,
                          interval_s=0.01)
    sampler.start()
    assert sampler.start() is sampler  # idempotent
    assert [t for t in threading.enumerate()
            if "tsdb-sampler" in t.name]
    sampler.stop()
    assert not [t for t in threading.enumerate()
                if "tsdb-sampler" in t.name]
    sampler.stop()  # idempotent


def test_fixed_memory_independent_of_run_length():
    """Total retained points are bounded by series x retention: 10x more
    samples do not grow the store."""
    fc = FakeClock()
    reg = MetricsRegistry(clock=fc)
    reg.counter("c_total")
    reg.gauge("g")
    store = TimeSeriesStore(retention=16, coarse_retention=4, clock=fc)
    # tick_clock too: with real perf_counter a slow pass on a loaded host
    # lands tsdb_sample_seconds in a NEW (lazily-exported) bucket mid-run,
    # which is one extra series — and this test counts retained points
    sampler = TsdbSampler(store, registry=reg, clock=fc, tick_clock=fc)
    reg.counter("c_total").inc()

    def run(n):
        for _ in range(n):
            fc.advance(1.0)
            reg.counter("c_total").inc()
            sampler.sample_once()
        return store.points()

    p1 = run(50)
    p2 = run(500)
    assert p1 == p2
    n_series = len(store.series_names())
    assert p2 <= n_series * 16


# ------------------------------------------------------------- persistence

def test_persist_load_round_trip_atomic(tmp_path):
    fc = FakeClock()
    store = make_store(fc, retention=32)
    for i in range(12):
        fc.advance(1.0)
        store.add("a_total", float(i))
        store.add("m", float(i * 2), labels={"replica": "r0"})
    path = str(tmp_path / "history.jsonl")
    store.persist(path)
    # atomic publish: no tmp siblings survive
    assert [n for n in os.listdir(tmp_path)] == ["history.jsonl"]
    meta, series = load_history(path)
    assert meta["schema"] == 1 and meta["retention"] == 32
    assert "wall_anchor" in meta
    assert set(series) == {"a_total", 'm{replica="r0"}'}
    assert series['m{replica="r0"}']["labels"] == {"replica": "r0"}
    pts = series["a_total"]["points"]
    assert [v for _, v in pts] == [float(i) for i in range(12)]
    summ = summarize_history(path)
    assert summ["series"] == 2 and summ["points"] == 24
    assert summ["span_s"] == pytest.approx(11.0)


def test_load_history_rejects_garbage(tmp_path):
    p = tmp_path / "bad.jsonl"
    p.write_text('{"tsdb": {"schema": 1}}\nnot json\n')
    with pytest.raises(ValueError):
        load_history(str(p))
    p2 = tmp_path / "bad2.jsonl"
    p2.write_text('{"neither": 1}\n')
    with pytest.raises(ValueError):
        load_history(str(p2))


def test_series_stats_and_sparkline():
    assert series_stats([])["points"] == 0
    st = series_stats([(0.0, 1.0), (1.0, 3.0), (2.0, 2.0)])
    assert (st["min"], st["max"], st["last"]) == (1.0, 3.0, 2.0)
    assert st["mean"] == pytest.approx(2.0)
    s = sparkline([0.0, 1.0, 2.0, 3.0], width=4)
    assert len(s) == 4 and s[0] == " " and s[-1] == "@"
    assert sparkline([]) == ""
    assert len(sparkline(list(range(1000)), width=50)) == 50


# -------------------------------------------------- flight-bundle history

def _fired_store(fc):
    store = make_store(fc, retention=64)
    for i in range(10):
        fc.advance(1.0)
        store.add("p99_ms", 100.0 + i)
    return store


def test_flight_bundle_carries_history_and_inspect_summarizes(tmp_path):
    """Every bundle from a tsdb-attached recorder carries history.jsonl
    (the minutes BEFORE the trigger), and `trace inspect` summarizes
    it."""
    fc = FakeClock()
    store = _fired_store(fc)
    reg = MetricsRegistry(clock=fc)
    fl = FlightRecorder(str(tmp_path), registry=reg, clock=fc,
                        min_interval_s=0.0).attach_tsdb(store)
    path = fl.record("watchdog_stall", reasons=["test"])
    assert path is not None
    files = sorted(os.listdir(path))
    assert "history.jsonl" in files
    _meta, series = load_history(os.path.join(path, "history.jsonl"))
    assert [v for _, v in series["p99_ms"]["points"]][-1] == 109.0
    manifest = json.load(open(os.path.join(path, "MANIFEST.json")))
    assert manifest["history_series"] == 1
    out = inspect_bundle(path)
    assert out["history"]["series"] == 1
    assert out["history"]["points"] == 10
    # detach: the next bundle has no history file
    fl.attach_tsdb(None)
    fc.advance(100.0)
    path2 = fl.record("watchdog_stall", reasons=["again"])
    assert "history.jsonl" not in os.listdir(path2)
    assert "history" not in inspect_bundle(path2)


# -------------------------------------------------------------------- CLI

def _write_history(tmp_path):
    fc = FakeClock()
    store = _fired_store(fc)
    path = str(tmp_path / "history.jsonl")
    store.persist(path)
    return path


def test_cli_report(tmp_path, capsys):
    path = _write_history(tmp_path)
    assert tsdb_main(["report", path]) == 0
    out = capsys.readouterr().out
    assert "p99_ms" in out and "mean=" in out and "1 series" in out


def test_cli_export(tmp_path, capsys):
    path = _write_history(tmp_path)
    out_path = str(tmp_path / "out.json")
    assert tsdb_main(["export", path, "-o", out_path]) == 0
    doc = json.load(open(out_path))
    assert "p99_ms" in doc["series"]
    assert tsdb_main(["export", path]) == 0  # stdout variant
    assert "p99_ms" in capsys.readouterr().out


def test_cli_plot_and_errors(tmp_path, capsys):
    path = _write_history(tmp_path)
    assert tsdb_main(["plot", path, "p99_ms"]) == 0
    out = capsys.readouterr().out
    assert "|" in out and "p99_ms" in out
    assert tsdb_main(["plot", path, "absent"]) == 1
    assert tsdb_main(["report", str(tmp_path / "missing.jsonl")]) == 1
    assert tsdb_main([]) == 2


# ------------------------------------------------------------- validation

def test_constructor_validation():
    with pytest.raises(ValueError):
        TimeSeriesStore(retention=1)
    with pytest.raises(ValueError):
        TimeSeriesStore(downsample=0)
    with pytest.raises(ValueError):
        TimeSeriesStore(max_series=0)
    with pytest.raises(ValueError):
        TsdbSampler(TimeSeriesStore(), registry=MetricsRegistry(),
                    interval_s=0)
    with pytest.raises(ValueError):
        TimeSeriesStore().range("x", tier="weird")
