"""Multi-host control-plane tests.

Runs real multi-process jax.distributed coordination in subprocesses (CPU
backend) — the analog of the reference exercising coordinator/worker over
docker-compose on one machine (SURVEY.md §4.7).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dcnn_tpu.parallel import multihost

    pid = int(sys.argv[1])
    multihost.initialize("127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert multihost.process_count() == 2
    assert multihost.is_coordinator() == (pid == 0)

    # coordinator ships a stage config; worker receives it (CONFIG_TRANSFER)
    cfg = multihost.broadcast_config(
        "stage_cfg", {{"layers": [{{"type": "flatten", "name": "f"}}], "pid": 0}})
    assert cfg["layers"][0]["type"] == "flatten", cfg

    multihost.barrier("ready")
    print(f"proc {{pid}} OK", flush=True)
    multihost.shutdown()
""")


@pytest.mark.slow
def test_two_process_config_broadcast_and_barrier(tmp_path):
    # ephemeral port: a fixed one collides under parallel/concurrent test runs
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER.format(repo=REPO, port=port))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              env=env, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} OK" in out
