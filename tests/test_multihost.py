"""Multi-host control-plane tests.

Runs real multi-process jax.distributed coordination in subprocesses (CPU
backend) — the analog of the reference exercising coordinator/worker over
docker-compose on one machine (SURVEY.md §4.7).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from dcnn_tpu.parallel import multihost

    pid = int(sys.argv[1])
    multihost.initialize("127.0.0.1:{port}", num_processes=2, process_id=pid)
    assert multihost.process_count() == 2
    assert multihost.is_coordinator() == (pid == 0)

    # coordinator ships a stage config; worker receives it (CONFIG_TRANSFER)
    cfg = multihost.broadcast_config(
        "stage_cfg", {{"layers": [{{"type": "flatten", "name": "f"}}], "pid": 0}})
    assert cfg["layers"][0]["type"] == "flatten", cfg

    multihost.barrier("ready")
    print(f"proc {{pid}} OK", flush=True)
    multihost.shutdown()
""")


def _run_two_procs(tmp_path, script_text, extra_env=None, timeout=240):
    # ephemeral port: a fixed one collides under parallel/concurrent test runs
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(script_text.format(repo=REPO, port=port))
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(extra_env or {}))
    procs = [subprocess.Popen([sys.executable, str(script), str(i)],
                              stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                              env=env, text=True)
             for i in range(2)]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=timeout)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out}"
        assert f"proc {i} OK" in out
    return outs


@pytest.mark.slow
def test_two_process_config_broadcast_and_barrier(tmp_path):
    _run_two_procs(tmp_path, WORKER)


DATA_PLANE_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from dcnn_tpu.parallel import multihost

    pid = int(sys.argv[1])
    multihost.initialize("127.0.0.1:{port}", num_processes=2, process_id=pid)

    # global device view: 2 processes x 2 forced host devices = 4
    devs = jax.devices()
    assert len(devs) == 4, devs
    assert jax.local_device_count() == 2

    # cross-process all-reduce over the global mesh — the collective the
    # reference routes through NCCL/MPI rides the XLA comm backend here
    mesh = Mesh(np.array(devs), ("data",))
    sh = NamedSharding(mesh, P("data"))
    x = jax.make_array_from_callback(
        (4,), sh, lambda idx: np.asarray([float(idx[0].start)], np.float32))
    f = jax.jit(jax.shard_map(
        lambda v: jax.lax.psum(jnp.sum(v), "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P()))
    total = f(x)
    got = float(np.asarray(total.addressable_shards[0].data))
    assert got == 6.0, got  # 0+1+2+3 on every process

    multihost.barrier("done")
    print(f"proc {{pid}} OK", flush=True)
    multihost.shutdown()
""")


@pytest.mark.slow
def test_two_process_cross_process_psum(tmp_path):
    """A real 2-process all-reduce: global mesh spanning both processes'
    devices, psum through the XLA collective backend (SURVEY §5.8 — the
    NCCL/MPI-scale path, exercised multi-process without a TPU)."""
    _run_two_procs(
        tmp_path, DATA_PLANE_WORKER,
        extra_env={"XLA_FLAGS": "--xla_force_host_platform_device_count=2"})
