"""Test config: force the JAX CPU backend with 8 virtual devices.

Mirrors the reference's test strategy of exercising multi-stage machinery
in-process without real hardware (SURVEY.md §4.7): the pipeline/sharding test
suites run over an 8-device CPU mesh exactly as they would over a v5e-8.
Must run before jax is imported anywhere.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

# The environment's TPU-tunnel plugin registers itself from sitecustomize and
# force-sets jax_platforms="axon,cpu" (overriding the env var), so the config
# must be re-overridden here — after the jax import — or every jax.devices()
# call dials the tunnel instead of creating the 8 virtual CPU devices.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: recompiling every jitted step on a 1-core host
# dominates test time; the cache makes reruns near-instant.
from dcnn_tpu.utils import enable_compile_cache  # noqa: E402

enable_compile_cache()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
