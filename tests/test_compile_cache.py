"""Session-integrity protocol of the shared persistent-compile-cache.

Background (dcnn_tpu/utils/compile_cache.py): a process that corrupts
its own memory can mint a *structurally valid* cache entry whose replay
crashes every later process, so an entry only survives the enable-time
sweep if the session that minted it exited cleanly. These tests drive
the pure helpers directly against tmp_path roots — no jax, no
subprocesses, no sleeps.
"""

import os

import pytest

from dcnn_tpu.utils import compile_cache as cc


def _mint(root, stem, atime=True):
    with open(os.path.join(root, f"{stem}-cache"), "wb") as f:
        f.write(b"\x78\x9cpayload")
    if atime:
        with open(os.path.join(root, f"{stem}-atime"), "wb") as f:
            f.write(b"0")


def _mark_inflight(root, pid):
    d = os.path.join(root, cc._INFLIGHT)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, str(pid)), "w", encoding="utf-8") as f:
        f.write("")


@pytest.fixture(autouse=True)
def _isolated_sessions(monkeypatch):
    # never let a test leak registered roots into the process-wide
    # atexit commit (conftest enables the real cache for the suite)
    monkeypatch.setattr(cc, "_SESSIONS", {})


class TestManifestIO:
    def test_roundtrip(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, {"b-cache", "a-cache"})
        assert cc._read_committed(root) == {"a-cache", "b-cache"}

    def test_missing_manifest_reads_empty(self, tmp_path):
        assert cc._read_committed(str(tmp_path)) == set()

    def test_write_is_atomic_no_tmp_left_behind(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, {"a-cache"})
        assert [n for n in os.listdir(root) if ".tmp." in n] == []


class TestSweepUncommitted:
    def test_no_manifest_grandfathers_present_entries(self, tmp_path):
        root = str(tmp_path)
        _mint(root, "jit_fwd-aa")
        assert cc._sweep_uncommitted(root) == 0
        # wholesale-committed, like the pre-fingerprint rotate policy
        assert cc._read_committed(root) == {"jit_fwd-aa-cache"}
        assert os.path.exists(os.path.join(root, "jit_fwd-aa-cache"))

    def test_no_manifest_empty_root_still_arms_the_sweep(self, tmp_path):
        # first-ever session on a fresh root crashes after minting: the
        # empty manifest written at its enable is what lets the NEXT
        # session recognise those mints as uncommitted
        root = str(tmp_path)
        assert cc._sweep_uncommitted(root) == 0
        assert os.path.exists(os.path.join(root, cc._COMMITTED))
        _mint(root, "jit_update-poison")  # the crashed session's mint
        assert cc._sweep_uncommitted(root) == 1
        assert not os.path.exists(os.path.join(root,
                                               "jit_update-poison-cache"))

    def test_uncommitted_entry_from_dead_writer_swept(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, {"jit_fwd-ok-cache"})
        _mint(root, "jit_fwd-ok")
        _mint(root, "jit_update-poison")
        assert cc._sweep_uncommitted(root) == 1
        assert os.path.exists(os.path.join(root, "jit_fwd-ok-cache"))
        assert not os.path.exists(os.path.join(root,
                                               "jit_update-poison-cache"))
        # the -atime sibling goes with it
        assert not os.path.exists(os.path.join(root,
                                               "jit_update-poison-atime"))

    def test_live_other_enabler_blocks_sweep(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, set())
        _mint(root, "jit_bwd-fresh")
        _mark_inflight(root, 1)  # pid 1: always alive, never ours
        assert cc._sweep_uncommitted(root) == 0
        assert os.path.exists(os.path.join(root, "jit_bwd-fresh-cache"))

    def test_dead_enabler_marker_pruned_and_entry_swept(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, set())
        _mint(root, "jit_bwd-stale")
        dead = 2 ** 22 - 7  # beyond this box's pid space
        _mark_inflight(root, dead)
        assert cc._sweep_uncommitted(root) == 1
        assert not os.path.exists(os.path.join(root, cc._INFLIGHT,
                                               str(dead)))

    def test_own_pid_marker_does_not_block(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, set())
        _mint(root, "jit_fwd-mine")
        _mark_inflight(root, os.getpid())
        assert cc._sweep_uncommitted(root) == 1


class TestFinishSessions:
    def test_commits_only_new_names_and_prunes_absent(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, {"gone-cache", "kept-cache"})
        _mint(root, "kept")
        cc._SESSIONS[root] = cc._cache_names(root)  # session start
        _mint(root, "minted-now")
        _mark_inflight(root, os.getpid())
        cc._finish_sessions()
        assert cc._read_committed(root) == {"kept-cache",
                                            "minted-now-cache"}
        # own inflight marker removed, registry drained
        assert not os.path.exists(os.path.join(root, cc._INFLIGHT,
                                               str(os.getpid())))
        assert cc._SESSIONS == {}

    def test_clean_exit_then_next_enable_keeps_entries(self, tmp_path):
        root = str(tmp_path)
        cc._write_committed(root, set())
        cc._SESSIONS[root] = cc._cache_names(root)
        _mint(root, "jit_scan-warm")
        cc._finish_sessions()
        assert cc._sweep_uncommitted(root) == 0
        assert os.path.exists(os.path.join(root, "jit_scan-warm-cache"))


class TestTornSweepStillWorks:
    def test_payload_without_atime_sibling_dropped(self, tmp_path):
        root = str(tmp_path)
        _mint(root, "whole")
        _mint(root, "torn", atime=False)
        assert cc._sweep_torn_entries(root) == 1
        assert os.path.exists(os.path.join(root, "whole-cache"))
        assert not os.path.exists(os.path.join(root, "torn-cache"))

    def test_missing_root_is_zero(self, tmp_path):
        assert cc._sweep_torn_entries(str(tmp_path / "nope")) == 0
        assert cc._sweep_uncommitted(str(tmp_path / "nope")) == 0


class TestRegisterSession:
    def test_snapshot_and_marker(self, tmp_path):
        root = str(tmp_path)
        _mint(root, "preexisting")
        cc._register_session(root)
        assert cc._SESSIONS[root] == {"preexisting-cache"}
        assert os.path.exists(os.path.join(root, cc._INFLIGHT,
                                           str(os.getpid())))

    def test_idempotent_snapshot_not_retaken(self, tmp_path):
        root = str(tmp_path)
        cc._register_session(root)
        _mint(root, "after-register")
        cc._register_session(root)
        assert cc._SESSIONS[root] == set()
