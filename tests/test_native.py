"""Native C++ data-kernel tests: build via g++, compare against numpy.

Reference analog: the reference's data layer is native C++; these tests hold
the ctypes bindings to the same numbers the pure-numpy fallback produces.
"""

import numpy as np
import pytest

from dcnn_tpu import native


requires_native = pytest.mark.skipif(not native.available(),
                                     reason="g++ toolchain unavailable")


@requires_native
def test_u8_to_f32_matches_numpy(rng):
    src = rng.integers(0, 256, size=(3, 17, 5), dtype=np.uint8)
    got = native.u8_to_f32(src)
    np.testing.assert_allclose(got, src.astype(np.float32) / 255.0, rtol=1e-7)
    assert got.dtype == np.float32 and got.shape == src.shape


@requires_native
def test_decode_label_records_cifar10_layout(rng):
    n, img = 9, 3 * 32 * 32
    labels = rng.integers(0, 10, size=n, dtype=np.uint8)
    recs = []
    for lb in labels:
        recs.append(np.concatenate([[lb], rng.integers(0, 256, size=img,
                                                       dtype=np.uint8)]))
    raw = np.concatenate(recs).astype(np.uint8)
    images, got_labels = native.decode_label_records(raw, n, 1, 0, img)
    np.testing.assert_array_equal(got_labels, labels)
    ref = raw.reshape(n, 1 + img)[:, 1:].astype(np.float32) / 255.0
    np.testing.assert_allclose(images, ref, rtol=1e-7)


@requires_native
def test_decode_label_records_cifar100_fine(rng):
    n, img = 4, 3 * 32 * 32
    coarse = rng.integers(0, 20, size=n, dtype=np.uint8)
    fine = rng.integers(0, 100, size=n, dtype=np.uint8)
    recs = []
    for c, f in zip(coarse, fine):
        recs.append(np.concatenate([[c, f], rng.integers(0, 256, size=img,
                                                         dtype=np.uint8)]))
    raw = np.concatenate(recs).astype(np.uint8)
    _, got = native.decode_label_records(raw, n, 2, 1, img)
    np.testing.assert_array_equal(got, fine)


@requires_native
def test_decode_short_buffer_raises(rng):
    with pytest.raises(ValueError):
        native.decode_label_records(np.zeros(10, np.uint8), 4, 1, 0, 3072)


@requires_native
def test_parse_label_csv_matches_numpy(tmp_path, rng):
    n, px = 6, 784
    labels = rng.integers(0, 10, size=n)
    pixels = rng.integers(0, 256, size=(n, px))
    lines = ["label," + ",".join(f"p{i}" for i in range(px))]
    for lb, row in zip(labels, pixels):
        lines.append(",".join([str(lb)] + [str(v) for v in row]))
    path = tmp_path / "mnist.csv"
    path.write_text("\n".join(lines) + "\n")
    got_px, got_lb = native.parse_label_csv(str(path), px)
    np.testing.assert_array_equal(got_lb, labels)
    np.testing.assert_allclose(got_px, pixels.astype(np.float32) / 255.0,
                               rtol=1e-7)


@requires_native
def test_parse_label_csv_unparseable_defers_to_fallback(tmp_path):
    # missing a pixel column / float pixels → the strict fast parser declines
    # (returns None) so callers run the tolerant numpy path instead
    path = tmp_path / "bad.csv"
    path.write_text("label,p0,p1\n3,12\n")
    assert native.parse_label_csv(str(path), 2) is None
    path2 = tmp_path / "floats.csv"
    path2.write_text("label,p0,p1\n3,0.5,1.0\n")
    assert native.parse_label_csv(str(path2), 2) is None
    # extra columns (row longer than pixels_per_row) must decline too, not
    # silently truncate to the first pixels_per_row values
    path3 = tmp_path / "extra.csv"
    path3.write_text("label,p0,p1\n3,10,20,30\n")
    assert native.parse_label_csv(str(path3), 2) is None


@requires_native
def test_loaders_use_native_and_match_fallback(tmp_path, rng, monkeypatch):
    """MNIST/CIFAR loaders must produce identical tensors through the native
    and numpy paths."""
    from dcnn_tpu.data import CIFAR10DataLoader

    # CIFAR
    n = 5
    recs = [np.concatenate([[rng.integers(0, 10)],
                            rng.integers(0, 256, size=3072)]).astype(np.uint8)
            for _ in range(n)]
    binpath = tmp_path / "batch.bin"
    np.concatenate(recs).tofile(binpath)

    l1 = CIFAR10DataLoader(str(binpath), batch_size=n, shuffle=False, drop_last=False)
    l1.load_data()
    monkeypatch.setattr(native, "decode_label_records", lambda *a, **k: None)
    l2 = CIFAR10DataLoader(str(binpath), batch_size=n, shuffle=False, drop_last=False)
    l2.load_data()
    np.testing.assert_allclose(l1._x, l2._x, rtol=1e-7)
    np.testing.assert_array_equal(l1._y, l2._y)


# -- LZ4 block codec (lz4codec.cpp; reference internal_compressor.hpp:5-15) --

@requires_native
def test_lz4_roundtrip_payload_classes(rng):
    payloads = [
        b"",
        b"x",
        b"abc",                                   # below min-match, all literal
        b"a" * 100_000,                           # max-compressible RLE
        bytes(rng.integers(0, 256, 70_000, dtype=np.uint8)),  # incompressible
        np.arange(4096, dtype=np.float32).tobytes(),          # structured
        (b"the quick brown fox " * 5000),         # long-range repeats > 64k window
    ]
    for p in payloads:
        c = native.lz4_compress(p)
        assert native.lz4_decompress(c, len(p)) == p
    # repetitive data must actually compress
    assert len(native.lz4_compress(b"a" * 100_000)) < 1000


@requires_native
def test_lz4_hc_roundtrip_and_ratio(rng):
    """HC level (hash-chain + lazy match, reference Lz4hc slot
    internal_compressor.hpp:10-15): same block format — the plain decoder
    reads it — and a ratio at least as good as greedy everywhere, strictly
    better on structured sparse payloads."""
    payloads = [
        b"", b"x", b"abc", b"a" * 100_000,
        bytes(rng.integers(0, 256, 70_000, dtype=np.uint8)),
        np.arange(4096, dtype=np.float32).tobytes(),
        (b"the quick brown fox " * 5000),
    ]
    for lvl in (1, 9, 13):
        for p in payloads:
            c = native.lz4_compress(p, level=lvl)
            assert native.lz4_decompress(c, len(p)) == p
    # sparse-gradient-shaped payload: chained search must beat greedy
    n = 65536
    sg = (rng.standard_normal(n) * (rng.random(n) < 0.05)).astype(np.float32)
    data = sg.tobytes()
    greedy = native.lz4_compress(data)
    hc = native.lz4_compress(data, level=9)
    assert native.lz4_decompress(hc, len(data)) == data
    assert len(hc) < len(greedy) * 0.75, (len(hc), len(greedy))
    # ratio never worse than greedy on any payload class
    for p in payloads:
        if p:
            assert len(native.lz4_compress(p, level=9)) <= \
                len(native.lz4_compress(p)) + 8


@requires_native
def test_lz4_decompress_spec_vector():
    """Hand-encoded stream per the public LZ4 block spec: token 0x17 =
    1 literal + (7+4)-byte match at offset 1 → 12 × 'a'."""
    stream = bytes([0x17]) + b"a" + bytes([0x01, 0x00])
    assert native.lz4_decompress(stream, 12) == b"a" * 12


@requires_native
def test_lz4_malformed_stream_raises():
    # offset 2 with only 1 byte of history → must be rejected, not OOB-read
    bad = bytes([0x17]) + b"a" + bytes([0x02, 0x00])
    with pytest.raises(ValueError):
        native.lz4_decompress(bad, 12)
    with pytest.raises(ValueError):  # truncated literals
        native.lz4_decompress(bytes([0xF0, 0xFF]), 300)


@requires_native
def test_lz4_via_meta_compressor():
    from dcnn_tpu.utils.compression import Lz4Compressor, MetaCompressor
    mc = MetaCompressor()
    assert 3 not in mc.codecs  # not eager: construction must stay import-cheap
    payload = np.arange(10_000, dtype=np.int32).tobytes()
    blob = mc.compress(payload, Lz4Compressor())
    assert blob[0] == 3
    assert mc.decompress(blob) == payload  # lazily registered on first id-3
    assert 3 in mc.codecs


# -- byte-shuffle filter + Blosc-analog codec (shuffle.cpp) --

@requires_native
def test_byte_shuffle_roundtrip_and_layout(rng):
    data = np.arange(40, dtype=np.uint8).tobytes()
    sh = native.byte_shuffle(data, 4)
    # plane 0 = every 4th byte starting at 0
    assert sh[:10] == bytes(range(0, 40, 4))
    assert native.byte_shuffle(sh, 4, inverse=True) == data
    with pytest.raises(ValueError):
        native.byte_shuffle(b"12345", 4)   # 5 % 4 != 0


@requires_native
def test_shuffle_zstd_codec_beats_plain_zstd_on_floats(rng):
    # the codec is an optional-dependency wrapper: without the zstandard
    # wheel the constructor raises by design — that's an environment
    # without the feature, not a shuffle-filter regression, so skip (the
    # shuffle filter itself is covered dependency-free above)
    pytest.importorskip("zstandard")
    from dcnn_tpu.utils.compression import (
        MetaCompressor, ShuffleZstdCompressor, ZstdCompressor)

    # smooth float data: byte-plane correlation is what the shuffle exploits
    payload = np.cumsum(rng.normal(size=50_000)).astype(np.float32).tobytes()
    mc = MetaCompressor()
    blob = mc.compress(payload, ShuffleZstdCompressor(typesize=4))
    assert blob[0] == 4
    assert mc.decompress(blob) == payload      # lazy registration path
    plain = mc.compress(payload, ZstdCompressor())
    assert len(blob) < len(plain), (len(blob), len(plain))
    # non-multiple-of-typesize payloads fall back to typesize 1
    odd = payload[:4093]
    blob2 = mc.compress(odd, ShuffleZstdCompressor(typesize=4))
    assert mc.decompress(blob2) == odd


@requires_native
def test_gather_rows_matches_fancy_index(rng):
    """The chunk-parallel row gather feeding the transfer engine must be
    bit-identical to numpy fancy indexing for any dtype/row size."""
    for src in (rng.integers(0, 256, size=(50, 6, 6, 3), dtype=np.uint8),
                rng.normal(size=(40, 17)).astype(np.float32),
                rng.integers(0, 9, size=37).astype(np.int32)):
        idx = rng.integers(0, src.shape[0], size=23).astype(np.int64)
        np.testing.assert_array_equal(native.gather_rows(src, idx), src[idx])
    # empty selection
    empty = native.gather_rows(np.arange(12).reshape(4, 3),
                               np.empty(0, np.int64))
    assert empty.shape == (0, 3)


@requires_native
def test_gather_rows_out_of_range_raises(rng):
    src = rng.integers(0, 256, size=(10, 4), dtype=np.uint8)
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([0, 10], np.int64))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([-1], np.int64))


def test_gather_rows_numpy_fallback_parity(monkeypatch, rng):
    """With the native library unavailable the MANDATORY numpy fallback
    must produce the same bytes (the transfer engine's bit-identity
    guarantee cannot depend on the toolchain)."""
    src = rng.integers(0, 256, size=(30, 5, 2), dtype=np.uint8)
    idx = rng.integers(0, 30, size=12).astype(np.int64)
    want = native.gather_rows(src, idx)
    monkeypatch.setattr(native, "lib", lambda: None)
    assert not native.gather_available()
    got = native.gather_rows(src, idx)
    np.testing.assert_array_equal(got, want)
    np.testing.assert_array_equal(got, src[idx])
    # out-of-range (incl. negative) indices raise on the fallback path too —
    # behavior must not depend on whether the toolchain is present
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([-1], np.int64))
    with pytest.raises(IndexError):
        native.gather_rows(src, np.array([30], np.int64))
