"""Tensor/elementwise-op throughput benchmark (effective HBM GB/s).

Reference equivalent: ``/root/reference/benchmarks/tensor_ops_benchmark.cpp``
(739 LoC of per-op timing sections). Each op is gated against numpy fp64 and
rated in effective memory bandwidth (bytes read + written / second) — the
meaningful roofline axis for elementwise work on TPU, where the VPU is
bandwidth-bound.
"""

from __future__ import annotations

import sys

import numpy as np

from common import Result, check_match, print_table, report, time_callable, tiny_mode

TOL = 1e-5


def run() -> dict:
    import jax

    from dcnn_tpu.ops import elementwise as ew

    n = (1 << 20) if tiny_mode() else (1 << 26)   # 4 MiB / 256 MiB fp32
    steps = 5 if tiny_mode() else 10
    rng = np.random.default_rng(0)
    a = rng.standard_normal(n).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    c = rng.standard_normal(n).astype(np.float32)
    da, db, dc = map(jax.device_put, (a, b, c))
    a64, b64, c64 = a.astype(np.float64), b.astype(np.float64), c.astype(np.float64)
    itemsize = 4

    # (name, jitted fn, host oracle, arrays touched r+w)
    cases = [
        ("add", jax.jit(ew.add), lambda: a64 + b64, 3),
        ("fmadd", jax.jit(ew.fmadd), lambda: a64 * b64 + c64, 4),
        ("axpy", jax.jit(lambda x, y: ew.axpy(2.5, x, y)),
         lambda: 2.5 * a64 + b64, 3),
        ("sqrt_abs", jax.jit(lambda x: ew.sqrt(ew.abs(x))),
         lambda: np.sqrt(np.abs(a64)), 2),
        ("clamp", jax.jit(lambda x: ew.clamp(x, -1.0, 1.0)),
         lambda: np.clip(a64, -1.0, 1.0), 2),
        ("sum", jax.jit(ew.sum), lambda: a64.sum(), 1),
        ("dot_product", jax.jit(ew.dot_product), lambda: a64 @ b64, 2),
    ]
    results = []
    for name, fn, oracle, n_arrays in cases:
        args = {"add": (da, db), "fmadd": (da, db, dc), "axpy": (da, db),
                "dot_product": (da, db)}.get(name, (da,))
        got = fn(*args)
        # reductions over 2^26 elements accumulate ~n*eps error; scale tol
        tol = TOL * (np.sqrt(n) / 100 if n_arrays < 3 and np.ndim(got) == 0 else 1.0)
        ok, err = check_match(got, oracle(), tol)
        dt = time_callable(lambda: fn(*args), steps=steps)
        gbps = n_arrays * n * itemsize / dt / 1e9
        results.append(Result(f"ew_{name}", dt, gbps, "GB/s", ok, err))

    # layout moves (the reference's nchw<->cnhw/nhwc transposes — on TPU
    # these are real HBM-bound relayouts worth tracking)
    shape = (8, 64, 32, 32) if tiny_mode() else (64, 128, 64, 64)
    x4 = rng.standard_normal(shape).astype(np.float32)
    dx4 = jax.device_put(x4)
    for name, fn, oracle in [
        ("nchw_to_nhwc", jax.jit(ew.nchw_to_nhwc),
         lambda: x4.transpose(0, 2, 3, 1)),
        ("nchw_to_cnhw", jax.jit(ew.nchw_to_cnhw),
         lambda: x4.transpose(1, 0, 2, 3)),
    ]:
        got = fn(dx4)
        ok, err = check_match(got, oracle(), TOL)
        dt = time_callable(lambda: fn(dx4), steps=steps)
        gbps = 2 * x4.nbytes / dt / 1e9
        results.append(Result(f"layout_{name}", dt, gbps, "GB/s", ok, err))
    return report("tensor_ops", results, meta={"elements": n})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
