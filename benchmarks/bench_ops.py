"""Tensor/elementwise-op throughput benchmark (effective HBM GB/s).

Reference equivalent: ``/root/reference/benchmarks/tensor_ops_benchmark.cpp``
(739 LoC of per-op timing sections). Each op is gated against numpy fp64 and
rated in effective memory bandwidth (bytes read + written / second) — the
meaningful roofline axis for elementwise work on TPU, where the VPU is
bandwidth-bound.
"""

from __future__ import annotations

import sys

import numpy as np

from common import (Result, check_match, dep_feed, print_table, replace_feed,
                    report, time_chained, tiny_mode)

TOL = 1e-5


def run() -> dict:
    import jax

    from dcnn_tpu.ops import elementwise as ew

    n = (1 << 20) if tiny_mode() else (1 << 26)   # 4 MiB / 256 MiB fp32
    length = 8 if tiny_mode() else 64
    rng = np.random.default_rng(0)
    a = rng.standard_normal(n).astype(np.float32)
    b = rng.standard_normal(n).astype(np.float32)
    c = rng.standard_normal(n).astype(np.float32)
    da, db, dc = map(jax.device_put, (a, b, c))
    a64, b64, c64 = a.astype(np.float64), b.astype(np.float64), c.astype(np.float64)
    itemsize = 4

    # (name, fn, host oracle, arrays touched r+w). Full-size outputs use
    # replace_feed (output becomes next input: full consumption, zero
    # overhead); scalar-output reductions use dep_feed (the reduction itself
    # is the full consumption, and the feed's extra work is O(1)).
    cases = [
        ("add", ew.add, lambda: a64 + b64, 3),
        ("fmadd", ew.fmadd, lambda: a64 * b64 + c64, 4),
        ("axpy", lambda x, y: ew.axpy(2.5, x, y), lambda: 2.5 * a64 + b64, 3),
        ("sqrt_abs", lambda x: ew.sqrt(ew.abs(x)),
         lambda: np.sqrt(np.abs(a64)), 2),
        ("clamp", lambda x: ew.clamp(x, -1.0, 1.0),
         lambda: np.clip(a64, -1.0, 1.0), 2),
        ("sum", ew.sum, lambda: a64.sum(), 1),
        ("dot_product", ew.dot_product, lambda: a64 @ b64, 2),
    ]
    results = []
    for name, fn, oracle, n_arrays in cases:
        args = {"add": (da, db), "fmadd": (da, db, dc), "axpy": (da, db),
                "dot_product": (da, db)}.get(name, (da,))
        got = jax.jit(fn)(*args)
        scalar_out = np.ndim(got) == 0
        # reductions over 2^26 elements accumulate ~n*eps error; scale tol
        tol = TOL * (np.sqrt(n) / 100 if scalar_out else 1.0)
        ok, err = check_match(got, oracle(), tol)
        feed = dep_feed(0) if scalar_out else replace_feed(0)
        dt, _ = time_chained(fn, args, feed, length=length)
        gbps = n_arrays * n * itemsize / dt / 1e9
        results.append(Result(f"ew_{name}", dt, gbps, "GB/s", ok, err))

    # layout moves (the reference's nchw<->cnhw/nhwc transposes — on TPU
    # these are real HBM-bound relayouts worth tracking). Shapes chosen so
    # the permutation preserves the array shape (B==C for the swap,
    # C==H==W for the cycle): the output feeds back as the input
    # (replace_feed = full consumption), while each scan body still executes
    # one real data movement.
    for name, fn, shape, perm in [
        ("nchw_to_nhwc", ew.nchw_to_nhwc,
         (8, 16, 16, 16) if tiny_mode() else (32, 64, 64, 64), (0, 2, 3, 1)),
        ("nchw_to_cnhw", ew.nchw_to_cnhw,
         (16, 16, 12, 12) if tiny_mode() else (64, 64, 48, 48), (1, 0, 2, 3)),
    ]:
        x4 = rng.standard_normal(shape).astype(np.float32)
        dx4 = jax.device_put(x4)
        got = jax.jit(fn)(dx4)
        ok, err = check_match(got, x4.transpose(perm), TOL)
        dt, _ = time_chained(fn, (dx4,), replace_feed(0), length=length)
        gbps = 2 * x4.nbytes / dt / 1e9
        results.append(Result(f"layout_{name}", dt, gbps, "GB/s", ok, err))
    return report("tensor_ops", results, meta={"elements": n})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
