"""Shared microbenchmark harness.

Reference equivalent: the timing + correctness-gate pattern of
``/root/reference/benchmarks/gemm_benchmark.cpp:16-50`` (every timed kernel
is first checked against a trusted reference implementation — a benchmark
that produces wrong numbers fast is a bug, not a result) and the
section-per-op layout of ``tensor_ops_benchmark.cpp``.

TPU specifics: all timing is fenced with ``core.fence.hard_fence`` (a real
device->host transfer — ``block_until_ready`` can return early on tunnelled
PJRT backends), jitted callables are warmed before timing, and throughput is
best-of-reps (steady-state capability, robust to dispatch jitter).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dcnn_tpu.core.fence import hard_fence


from dcnn_tpu.utils import enable_compile_cache

enable_compile_cache()


@dataclass
class Result:
    """One benchmark row: name, timing, derived rate, correctness verdict."""

    name: str
    seconds: float
    rate: Optional[float] = None        # work / second (unit below)
    unit: Optional[str] = None
    correct: Optional[bool] = None      # None = no gate for this row
    max_err: Optional[float] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        out = {"name": self.name, "seconds": round(self.seconds, 6)}
        if self.rate is not None:
            out["rate"] = round(self.rate, 3)
            out["unit"] = self.unit
        if self.correct is not None:
            # np.array_equal & co. return np.bool (numpy 2), which the json
            # encoder rejects — coerce at the boundary
            out["correct"] = bool(self.correct)
            out["max_err"] = (None if self.max_err is None
                              else float(f"{self.max_err:.3e}"))
        out.update(self.extra)
        return out


def check_match(got, want, tol: float, name: str = "") -> tuple:
    """Correctness gate (reference ``gemm_benchmark.cpp:21-34`` check_match):
    elementwise compare against the trusted reference; returns
    (passed, max_abs_err). Relative tolerance scaled by the magnitude of
    ``want`` so fp32-vs-bf16 comparisons use a meaningful threshold."""
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    if got.shape != want.shape:
        return False, float("inf")
    denom = max(1.0, float(np.max(np.abs(want))))
    err = float(np.max(np.abs(got - want))) / denom
    return bool(err <= tol), err


def time_callable(fn: Callable[[], Any], steps: int = 10, reps: int = 3,
                  warmup: int = 2) -> float:
    """Best-of-reps seconds for ``steps`` dispatches of ``fn``.

    ``fn`` must return (a pytree containing) the device array(s) produced, so
    the fence can await them. Warmup covers compile + cache effects."""
    out = None
    for _ in range(warmup):
        out = fn()
    hard_fence(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn()
        hard_fence(out)
        best = min(best, time.perf_counter() - t0)
    return best / steps


def time_chained(op: Callable, args: tuple, feed: Callable,
                 length: int = 32, reps: int = 5, roofline=None):
    """Per-iteration seconds for ``length`` data-dependent iterations of
    ``op`` inside ONE jitted dispatch (``lax.scan``).

    Returns ``(seconds, sane)`` — ALWAYS a tuple, with or without
    ``roofline`` (the r5 polymorphic bare-float return invited silent
    tuple-as-number bugs, ADVICE r5); ``sane`` is True whenever no gate
    fired.

    ``roofline=(flops_per_iteration, peak_flops_or_None)``: physical sanity
    gate. One capture of a short inference chain measured an implied 232
    TF/s bf16 forward — above the 197 TF/s v5e peak, i.e. impossible: the
    two-length delta occasionally lands on correlated tunnel jitter. With
    ``roofline`` set the measurement is retried up to twice while the
    implied FLOP rate exceeds 1.05× peak, and ``sane`` becomes False when a
    persistently impossible number remains, so callers can flag (never
    silently report) it. ``peak=None`` skips the check.

    On tunnelled/remote PJRT backends a single dispatch costs ~10 ms wall
    regardless of the op, so ``time_callable`` measures the tunnel, not the
    chip, for any op under ~10 ms. Chaining amortizes the dispatch to
    ``1/length`` while the data dependency (``feed(out, args) -> args`` must
    thread the output back into the next iteration's inputs) stops XLA from
    collapsing the loop. ``feed`` must preserve the args pytree
    structure/shapes/dtypes (scan carry invariant).

    Even one fence is expensive through the tunnel (~30-100 ms round trips —
    measured: a scalar pull on an already-ready array costs ~99 ms), so a
    single-length measurement is still constant-biased. This uses the
    **two-length difference method**: time the scan at ``length`` and at
    ``length // 4`` and divide the delta by the iteration delta — every
    constant cost (dispatch RPC, fence RTT, first/last-iteration DCE
    asymmetries) cancels exactly. The fence probe is a scalar computed
    *inside* the jit (one element per carry leaf), so awaiting it is a single
    D2H round trip.

    On the CPU backend this falls back to per-dispatch timing: local dispatch
    costs ~µs (no tunnel to amortize), while XLA:CPU runs loop bodies
    single-threaded, which would make chained numbers 10-20x worse than the
    op's real multi-threaded performance."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _gated(measure):
        dt = measure()
        if roofline is None:
            return dt, True
        flops, peak = roofline
        tries = 0
        while peak and flops / dt > 1.05 * peak and tries < 2:
            dt = measure()
            tries += 1
        return dt, not (peak and flops / dt > 1.05 * peak)

    if jax.default_backend() == "cpu":
        jfn = jax.jit(lambda a: op(*a))
        return _gated(lambda: time_callable(
            lambda: jfn(args), steps=min(length, 10), reps=reps))

    @jax.jit
    def run(a, n):
        # RUNTIME trip count (n is traced, not static): one executable
        # serves both lengths, so the difference method compares literally
        # identical code — a static length would let XLA pick different
        # unroll regimes for the long and short runs, breaking the
        # equal-constant-cost assumption (observed as impossible TFLOP/s on
        # small fast-mode matmuls).
        def body(i, c):
            return feed(op(*c), c)

        c = lax.fori_loop(0, n, body, a)
        # in-jit scalar probe: a FULL reduction of every carry leaf. A
        # single-element probe is not enough — XLA slice-sinks through the
        # carried matmul chain (a[0,0] needs only row 0 of the previous
        # carry, inductively collapsing every iteration to row@matrix; we
        # measured impossible >500 TFLOP/s numbers that way). A full sum
        # needs every element of the final carry, so every iteration runs at
        # full width; its own cost is one reduction per *run*, amortized to
        # nothing by the difference method. Awaiting the scalar is one D2H
        # round trip.
        return sum(jnp.sum(l).astype(jnp.float32)
                   for l in jax.tree_util.tree_leaves(c))

    length = max(2, length)   # the difference method needs short < length

    def one(n: int) -> float:
        t0 = time.perf_counter()
        jax.device_get(run(args, jnp.int32(n)))
        return time.perf_counter() - t0

    # compile + warm (single executable for all lengths)
    jax.device_get(run(args, jnp.int32(length)))

    # PAIRED differences, median-combined: taking independent best-of-reps
    # for each length lets slow tunnel drift between the two measurement
    # groups fake the delta (observed: impossible >300 TFLOP/s on small
    # matmuls). Back-to-back pairs see the same tunnel conditions; the
    # median rejects outlier round trips. If the delta is still below the
    # tunnel noise floor (several ms of RTT jitter), escalate the iteration
    # count — the runtime trip count makes longer runs free of recompiles.
    NOISE_FLOOR = 0.05           # seconds the delta must clear
    MAX_LENGTH = 1 << 16
    MAX_RUN_WALL = 8.0           # never schedule a device loop much past
                                 # this — long single kernels can trip the
                                 # TPU watchdog and kill the worker process

    def measure() -> float:
        nonlocal length
        while True:
            short = max(1, length // 4)
            t_longs, diffs = [], []
            for _ in range(reps):
                tl = one(length)
                diffs.append(tl - one(short))
                t_longs.append(tl)
            diffs.sort()
            delta = diffs[len(diffs) // 2]
            t_long = sorted(t_longs)[len(t_longs) // 2]
            if (delta >= NOISE_FLOOR or length >= MAX_LENGTH
                    or t_long >= MAX_RUN_WALL):
                break
            if delta > 0:
                # scale so the next delta lands ~2x the floor, bounded by
                # the per-run wall guard (measured t_long is the ground
                # truth for how expensive this loop really is)
                est = delta / (length - short)
                target = max(length * 2, int(2 * NOISE_FLOOR / est * 1.34))
                wall_cap = max(length * 2,
                               int(length * MAX_RUN_WALL / max(t_long, 1e-3)))
                length = min(MAX_LENGTH, target, wall_cap)
            else:
                # delta lost in jitter: escalate gently — a huge jump here
                # (est~0 => max length) once produced a
                # quarter-million-iteration kernel that crashed the TPU
                # worker
                length = min(MAX_LENGTH, length * 4)
        if delta > 0:
            return delta / (length - short)
        # degenerate (op so cheap it drowns in jitter even at MAX_LENGTH):
        # fall back to the long-run average, which at worst over-reports
        return one(length) / length

    return _gated(measure)


def e2e_chain_length(short_length: int) -> int:
    """Chain length for end-to-end model rows (both bench entry points).

    On TPU, 1024 iterations put ~1-2 s of device work behind the two-length
    delta: at the default-escalated ~100 ms delta the tunnel's ±10-20 ms
    correlated jitter was a ±10-20% multiplier on these rows (observed int8
    e2e spread 203-264k img/s; ±0.4% after this change). Tiny mode and CPU
    keep the caller's short length — the CPU fallback is per-dispatch
    timing and tiny mode must stay CI-sized on any backend."""
    import jax

    if tiny_mode() or jax.default_backend() != "tpu":
        return short_length
    return 1024


def replace_feed(i: int = 0):
    """Feed for time_chained when the op output has the same shape/dtype as
    ``args[i]``: the output simply becomes the next iteration's input. Full
    consumption of the output (XLA cannot dead-code or slice-sink any of the
    timed work) at zero added cost. Values may drift to inf over iterations —
    harmless for timing; TPU float arithmetic is constant-time."""

    def feed(out, args):
        new = list(args)
        new[i] = out
        return tuple(new)

    return feed


def outputs_as_args_feed():
    """Feed for ops whose output tuple matches the args tuple elementwise
    (e.g. a grad function over its own inputs)."""

    def feed(out, args):
        return tuple(out)

    return feed


def dep_feed(i: int):
    """Generic feed for shape-mismatched ops: fold a FULL reduction of every
    output leaf into a one-element perturbation of args[i].

    The full ``jnp.sum`` matters: consuming a single output element would let
    XLA slice-sink through the (single-user) producer and shrink the timed op
    to the one element the probe reads — e.g. a GEMM collapses to one K-dot.
    A whole-output reduction forces every element to exist. Cost: one extra
    read of the output per iteration — negligible for FLOP-bound ops; prefer
    :func:`replace_feed` (zero-cost) whenever shapes allow."""
    import jax
    import jax.numpy as jnp

    def feed(out, args):
        leaves = ([out] if hasattr(out, "dtype")
                  else jax.tree_util.tree_leaves(out))
        eps = sum(jnp.sum(l).astype(jnp.float32) for l in leaves) * 1e-30
        new = list(args)
        a = new[i]
        new[i] = a.at[(0,) * a.ndim].add(eps.astype(a.dtype))
        return tuple(new)

    return feed


def report(section: str, results: List[Result], out_path: Optional[str] = None,
           meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble + optionally persist one section's machine-readable report."""
    import jax

    doc = {
        "section": section,
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "results": [r.to_json() for r in results],
        "all_correct": bool(all(r.correct for r in results
                                if r.correct is not None)),
    }
    if meta:
        doc["meta"] = meta
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc


def print_table(doc: Dict[str, Any]) -> None:
    print(f"== {doc['section']} [{doc['device']}] ==")
    for r in doc["results"]:
        gate = ("" if "correct" not in r
                else ("  OK" if r["correct"] else "  **MISMATCH**"))
        rate = (f"  {r['rate']:>12.3f} {r['unit']}" if "rate" in r else "")
        print(f"  {r['name']:<42s} {r['seconds'] * 1e3:>9.3f} ms{rate}{gate}")


def tiny_mode() -> bool:
    """BENCH_TINY=1 shrinks problem sizes so the suite doubles as a CI test
    (the reference runs its benchmarks as manual executables; here the same
    code is importable and pytest-runnable)."""
    return os.environ.get("BENCH_TINY", "0") == "1"
