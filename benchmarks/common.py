"""Shared microbenchmark harness.

Reference equivalent: the timing + correctness-gate pattern of
``/root/reference/benchmarks/gemm_benchmark.cpp:16-50`` (every timed kernel
is first checked against a trusted reference implementation — a benchmark
that produces wrong numbers fast is a bug, not a result) and the
section-per-op layout of ``tensor_ops_benchmark.cpp``.

TPU specifics: all timing is fenced with ``core.fence.hard_fence`` (a real
device->host transfer — ``block_until_ready`` can return early on tunnelled
PJRT backends), jitted callables are warmed before timing, and throughput is
best-of-reps (steady-state capability, robust to dispatch jitter).
"""

from __future__ import annotations

import json
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from dcnn_tpu.core.fence import hard_fence


@dataclass
class Result:
    """One benchmark row: name, timing, derived rate, correctness verdict."""

    name: str
    seconds: float
    rate: Optional[float] = None        # work / second (unit below)
    unit: Optional[str] = None
    correct: Optional[bool] = None      # None = no gate for this row
    max_err: Optional[float] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> Dict[str, Any]:
        out = {"name": self.name, "seconds": round(self.seconds, 6)}
        if self.rate is not None:
            out["rate"] = round(self.rate, 3)
            out["unit"] = self.unit
        if self.correct is not None:
            # np.array_equal & co. return np.bool (numpy 2), which the json
            # encoder rejects — coerce at the boundary
            out["correct"] = bool(self.correct)
            out["max_err"] = (None if self.max_err is None
                              else float(f"{self.max_err:.3e}"))
        out.update(self.extra)
        return out


def check_match(got, want, tol: float, name: str = "") -> tuple:
    """Correctness gate (reference ``gemm_benchmark.cpp:21-34`` check_match):
    elementwise compare against the trusted reference; returns
    (passed, max_abs_err). Relative tolerance scaled by the magnitude of
    ``want`` so fp32-vs-bf16 comparisons use a meaningful threshold."""
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    if got.shape != want.shape:
        return False, float("inf")
    denom = max(1.0, float(np.max(np.abs(want))))
    err = float(np.max(np.abs(got - want))) / denom
    return bool(err <= tol), err


def time_callable(fn: Callable[[], Any], steps: int = 10, reps: int = 3,
                  warmup: int = 2) -> float:
    """Best-of-reps seconds for ``steps`` dispatches of ``fn``.

    ``fn`` must return (a pytree containing) the device array(s) produced, so
    the fence can await them. Warmup covers compile + cache effects."""
    out = None
    for _ in range(warmup):
        out = fn()
    hard_fence(out)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(steps):
            out = fn()
        hard_fence(out)
        best = min(best, time.perf_counter() - t0)
    return best / steps


def report(section: str, results: List[Result], out_path: Optional[str] = None,
           meta: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """Assemble + optionally persist one section's machine-readable report."""
    import jax

    doc = {
        "section": section,
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "results": [r.to_json() for r in results],
        "all_correct": bool(all(r.correct for r in results
                                if r.correct is not None)),
    }
    if meta:
        doc["meta"] = meta
    if out_path:
        with open(out_path, "w") as f:
            json.dump(doc, f, indent=1)
    return doc


def print_table(doc: Dict[str, Any]) -> None:
    print(f"== {doc['section']} [{doc['device']}] ==")
    for r in doc["results"]:
        gate = ("" if "correct" not in r
                else ("  OK" if r["correct"] else "  **MISMATCH**"))
        rate = (f"  {r['rate']:>12.3f} {r['unit']}" if "rate" in r else "")
        print(f"  {r['name']:<42s} {r['seconds'] * 1e3:>9.3f} ms{rate}{gate}")


def tiny_mode() -> bool:
    """BENCH_TINY=1 shrinks problem sizes so the suite doubles as a CI test
    (the reference runs its benchmarks as manual executables; here the same
    code is importable and pytest-runnable)."""
    return os.environ.get("BENCH_TINY", "0") == "1"
