"""Pallas implicit-GEMM conv vs XLA conv on the ResNet-18 shape class.

The VERDICT-r3 top-item experiment: ResNet-18's conv fusions run at ~55% MXU
while active (xprof, RESULTS.md) — is a hand-written implicit-GEMM conv
faster, or is 55% the shape's ceiling? Each row races
`dcnn_tpu.ops.pallas.conv.conv3x3_s1` (batch-tile swept) against
`lax.conv_general_dilated` on one (B, H, W, Cin->Cout) 3x3 stride-1 bf16
shape with the chained-timing harness; correctness-gated vs XLA at fp32
tolerance. Run on TPU (`python bench_pallas_conv.py`); results feed
RESULTS.md either as the win + dispatch rule or as the documented negative
result that closes the claim (reference kernel family:
``src/nn/layers_impl/cuda/conv2d_ops.cu``).
"""

from __future__ import annotations

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from common import Result, print_table, report, time_chained, tiny_mode  # noqa: E402


def _shapes():
    if tiny_mode():
        return [(8, 8, 8, 16, 16)]
    # (B, H, W, Cin, Cout): the ResNet-18 Tiny-ImageNet 3x3-s1 bodies
    return [
        (256, 64, 64, 64, 64),     # layer1 (B capped to keep VMEM/HBM sane)
        (256, 32, 32, 128, 128),   # layer2
        (256, 16, 16, 256, 256),   # layer3
        (256, 8, 8, 512, 512),     # layer4
    ]


def run():
    import jax.numpy as jnp
    from jax import lax

    from dcnn_tpu.ops.pallas.conv import conv3x3_s1, conv3x3_s1_pairs

    results = []
    rng = np.random.default_rng(0)
    for (b, h, w, cin, cout) in _shapes():
        x = jnp.asarray(rng.normal(size=(b, h, w, cin)), jnp.bfloat16)
        wt = jnp.asarray(rng.normal(size=(3, 3, cin, cout)) * 0.05,
                         jnp.bfloat16)
        flops = 2 * b * h * w * 9 * cin * cout

        def feed(out, args):
            # thread output back: re-scale into the input's magnitude
            xx, ww_ = args
            return (out[..., :cin].astype(jnp.bfloat16) * 0.001 + xx, ww_)

        def xla_conv(xx, ww_):
            return lax.conv_general_dilated(
                xx, ww_, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32).astype(jnp.bfloat16)

        ref = np.asarray(xla_conv(x, wt), np.float32)
        dt_xla, _ = time_chained(xla_conv, (x, wt), feed)
        results.append(Result(
            f"xla_conv_{h}x{w}x{cin}", dt_xla, flops / dt_xla / 1e12,
            "TF/s", True, 0.0, extra={"B": b}))

        variants = {"pallas_conv": lambda xx, ww_, _bt: conv3x3_s1(
            xx, ww_, batch_tile=_bt)}
        if cout < 128 and w % 2 == 0:
            # narrow-Cout shapes: also race the output-column-pair
            # formulation (N = 2K fills the MXU width K alone leaves idle)
            variants["pallas_conv_pairs"] = lambda xx, ww_, _bt: \
                conv3x3_s1_pairs(xx, ww_, batch_tile=_bt)
        for vname, fn in variants.items():
            best = None
            for bt in (1, 2, 4, 8):
                if b % bt:
                    continue
                try:
                    def pk(xx, ww_, _bt=bt, _fn=fn):
                        return _fn(xx, ww_, _bt)
                    got = np.asarray(pk(x, wt), np.float32)
                    err = float(np.max(np.abs(got - ref)))
                    ok = err < 0.75  # bf16 on K up to 4608
                    dt, _ = time_chained(pk, (x, wt), feed)
                    if best is None or dt < best[0]:
                        best = (dt, bt, ok, err)
                except Exception as e:  # noqa: BLE001 — record, keep going.
                    # correct=None: an infeasible batch_tile (VMEM overflow)
                    # is sweep information, not a correctness failure — it
                    # must not flip all_correct when another bt passes
                    results.append(Result(
                        f"{vname}_{h}x{w}x{cin}_bt{bt}_FAILED", 0.0, 0.0,
                        "TF/s", None, None,
                        extra={"error": str(e)[:200]}))
            if best:
                dt, bt, ok, err = best
                results.append(Result(
                    f"{vname}_{h}x{w}x{cin}", dt, flops / dt / 1e12,
                    "TF/s", ok, err,
                    extra={"B": b, "batch_tile": bt,
                           "vs_xla": round(dt_xla / dt, 3)}))
    return report("pallas_conv", results)


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
