"""int8 PTQ benchmark: MXU int8 convs vs bf16, and end-to-end quantized
ResNet-18 inference vs the BN-folded float graph.

Beyond the reference (no quantized path there); the measurement behind
``ops/quant.py`` / ``nn/quantize.py``. v5e book peak for int8 is ~394 TOP/s —
2× the bf16 197 TFLOP/s — and XLA lowers int8 ``conv_general_dilated`` with
``preferred_element_type=int32`` onto it directly.

Gates: the int8 conv kernel is EXACT integer arithmetic, gated elementwise
against a float64 torch conv of the same int values (products ≤ 127², sums
≤ K·127² ≪ 2⁵³ — the double oracle is exact); the end-to-end quantized model
is gated on logit cosine + top-1 agreement against the float folded model on
a briefly-trained net (PTQ is lossy by design; exactness lives in the kernel
gate, fidelity in the model gate).
"""

from __future__ import annotations

import sys

import numpy as np

from common import (Result, dep_feed, e2e_chain_length, print_table, report,
                    time_chained, tiny_mode)

# (cin, cout, hw) 3×3 s1 p1 ResNet-18 body shapes (the stem is
# channel-starved in any dtype; the body is where the MXU time goes)
SHAPES = [(64, 64, 64), (256, 256, 16), (512, 512, 8)]


def _torch_conv_int_exact(x_q, w_q, stride, pad):
    import torch

    with torch.no_grad():
        out = torch.nn.functional.conv2d(
            torch.from_numpy(x_q.astype(np.float64)),
            torch.from_numpy(w_q.astype(np.float64)),
            stride=stride, padding=pad)
    return out.numpy().astype(np.int64)


def _conv_micro(results, rng, batch, length):
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.precision import set_precision
    from dcnn_tpu.ops import conv as conv_ops

    oracle_batch = 4
    for cin, cout, hw in (SHAPES[:1] if tiny_mode() else SHAPES):
        x_q = rng.integers(-127, 128, (batch, cin, hw, hw)).astype(np.int8)
        w_q = rng.integers(-127, 128, (cout, cin, 3, 3)).astype(np.int8)
        dx, dw = jax.device_put(x_q), jax.device_put(w_q)
        flops = 2.0 * batch * cout * cin * 9 * hw * hw
        tag = f"{cin}x{hw}x{hw}->{cout}"

        fwd8 = jax.jit(lambda xx, ww: conv_ops.conv2d_int8(
            xx, ww, stride=1, padding=1, data_format="NCHW"))
        got = np.asarray(fwd8(dx[:oracle_batch], dw), np.int64)
        want = _torch_conv_int_exact(x_q[:oracle_batch], w_q, 1, 1)
        ok = bool(np.array_equal(got, want))
        err = float(np.abs(got - want).max()) if not ok else 0.0
        dt, _ = time_chained(fwd8, (dx, dw), dep_feed(0), length=length)
        results.append(Result(f"conv_int8_{tag}", dt, flops / dt / 1e12,
                              "TOP/s", ok, err))

        # bf16 twin of the same shape/feed for the apples-to-apples ratio.
        # XLA:CPU emulates bf16 orders of magnitude slower than f32, so the
        # CPU smoke path keeps f32 storage (the ratio is a TPU artifact)
        set_precision("fast")
        ftype = jnp.bfloat16 if jax.default_backend() == "tpu" else jnp.float32
        xb = jax.device_put(rng.standard_normal(
            (batch, cin, hw, hw)).astype(np.float32)).astype(ftype)
        wb = jax.device_put((rng.standard_normal(
            (cout, cin, 3, 3)) / np.sqrt(cin * 9)).astype(np.float32)
        ).astype(ftype)
        fwd16 = jax.jit(lambda xx, ww: conv_ops.conv2d(
            xx, ww, stride=1, padding=1, data_format="NCHW"))
        dt, _ = time_chained(fwd16, (xb, wb), dep_feed(0), length=length)
        set_precision("parity")
        results.append(Result(f"conv_bf16_{tag}", dt, flops / dt / 1e12,
                              "TFLOP/s", True, 0.0))


def _model_end_to_end(results, rng, length):
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.models import create_mnist_trainer, create_resnet18_tiny_imagenet
    from dcnn_tpu.nn import fold_batchnorm, quantize_model
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.train.trainer import create_train_state, make_train_step

    # tiny mode (the CPU smoke path) swaps in the MNIST CNN: the resnet
    # train-step compiles alone take minutes on a 1-core host, and the
    # residual-recursion coverage already lives in tests/test_quantize.py
    if tiny_mode():
        model, img, cin, n_cls = create_mnist_trainer("NHWC"), 28, 1, 10
    else:
        model, img, cin, n_cls = (create_resnet18_tiny_imagenet("NHWC"),
                                  64, 3, 200)
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(0))
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    # a few real steps so BN stats/weights are non-trivial and logits
    # differentiate (the fidelity gate is meaningless on a random net)
    bs_train = 8 if tiny_mode() else 16
    for i in range(2 if tiny_mode() else 6):
        x = jnp.asarray(rng.normal(size=(bs_train, img, img, cin)),
                        jnp.float32)
        y = jnp.asarray(np.eye(n_cls, dtype=np.float32)[
            rng.integers(0, n_cls, size=bs_train)])
        ts, _, _ = step(ts, x, y, jax.random.fold_in(jax.random.PRNGKey(1), i),
                        1e-3)

    batch = 16 if tiny_mode() else 256
    xf = jnp.asarray(rng.normal(size=(batch, img, img, cin)), jnp.float32)

    fmodel, fp, fs = fold_batchnorm(model, ts.params, ts.state)
    qmodel, qp, qs = quantize_model(model, ts.params, ts.state, xf)

    from dcnn_tpu.core.precision import set_precision

    # the float baseline runs the production inference precision (bf16 mixed
    # — Sequential casts params/activations at point of use)
    def fwd_f_impl(xx):
        return fmodel.apply(fp, fs, xx, training=False)[0]

    def fwd_q_impl(xx):
        return qmodel.apply(qp, qs, xx, training=False)[0]

    # production inference precision is bf16 mixed; on the CPU smoke path
    # bf16 is emulated (and glacial), so the float twin stays in fast-f32
    on_tpu = jax.default_backend() == "tpu"
    set_precision("bf16" if on_tpu else "fast")
    try:
        fwd_f = jax.jit(fwd_f_impl)
        fwd_q = jax.jit(fwd_q_impl)

        y_f = np.asarray(fwd_f(xf), np.float64)
        y_q = np.asarray(fwd_q(xf), np.float64)
        cos = float((y_f.ravel() @ y_q.ravel())
                    / (np.linalg.norm(y_f) * np.linalg.norm(y_q) + 1e-12))
        top1 = float(np.mean(y_f.argmax(-1) == y_q.argmax(-1)))
        ok = cos > 0.95 and top1 >= 0.85

        # Roofline sanity gate (time_chained roofline= — see common.py): a
        # capture of this section once measured an implied 232 TF/s bf16
        # forward, above the 197 TF/s v5e peak. int8 peak is 2x bf16.
        # Chain length: common.e2e_chain_length (jitter rationale there).
        fwd_flops = float(model.forward_complexity()) * batch
        e2e_len = e2e_chain_length(length)
        bf16_peak = 197e12 if on_tpu else None
        dt_f, f_sane = time_chained(
            fwd_f, (xf,), dep_feed(0), length=e2e_len,
            roofline=(fwd_flops, bf16_peak))
        dt_q, q_sane = time_chained(
            fwd_q, (xf,), dep_feed(0), length=e2e_len,
            roofline=(fwd_flops, bf16_peak * 2 if bf16_peak else None))
    finally:
        set_precision("parity")
    net = "mnist_cnn" if tiny_mode() else "resnet18"
    results.append(Result(f"{net}_infer_bf16_folded", dt_f, batch / dt_f,
                          "img/s", f_sane, 0.0))
    results.append(Result(f"{net}_infer_int8_ptq", dt_q, batch / dt_q,
                          "img/s", ok and q_sane, 1.0 - cos))
    results.append(Result(f"{net}_int8_speedup", dt_q, dt_f / dt_q,
                          "x_vs_bf16", ok and f_sane and q_sane, 1.0 - top1))


def _mha_end_to_end(results, rng, length):
    """Attention-family PTQ: the zoo mha_classifier's projections w8a8
    (QuantMultiHeadAttentionLayer), float attention core — vs the float
    model at the production inference precision."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.precision import set_precision
    from dcnn_tpu.models import create_mha_classifier
    from dcnn_tpu.nn import quantize_model
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.train.trainer import create_train_state, make_train_step

    model = create_mha_classifier()
    opt = Adam(1e-3)
    ts = create_train_state(model, opt, jax.random.PRNGKey(2))
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    bs_train = 8
    for i in range(2 if tiny_mode() else 4):
        x = jnp.asarray(rng.normal(size=(bs_train, 32, 64)), jnp.float32)
        y = jnp.asarray(np.eye(10, dtype=np.float32)[
            rng.integers(0, 10, size=bs_train)])
        ts, _, _ = step(ts, x, y, jax.random.fold_in(jax.random.PRNGKey(4), i),
                        1e-3)

    batch = 32 if tiny_mode() else 1024
    xf = jnp.asarray(rng.normal(size=(batch, 32, 64)), jnp.float32)
    qmodel, qp, qs = quantize_model(model, ts.params, ts.state, xf)

    on_tpu = jax.default_backend() == "tpu"
    set_precision("bf16" if on_tpu else "fast")
    try:
        fwd_f = jax.jit(lambda xx: model.apply(
            ts.params, ts.state, xx, training=False)[0])
        fwd_q = jax.jit(lambda xx: qmodel.apply(qp, qs, xx,
                                                training=False)[0])
        y_f = np.asarray(fwd_f(xf), np.float64)
        y_q = np.asarray(fwd_q(xf), np.float64)
        cos = float((y_f.ravel() @ y_q.ravel())
                    / (np.linalg.norm(y_f) * np.linalg.norm(y_q) + 1e-12))
        ok = cos > 0.95
        fwd_flops = float(model.forward_complexity()) * batch
        e2e_len = e2e_chain_length(length)
        bf16_peak = 197e12 if on_tpu else None
        dt_f, f_sane = time_chained(fwd_f, (xf,), dep_feed(0), length=e2e_len,
                                    roofline=(fwd_flops, bf16_peak))
        dt_q, q_sane = time_chained(
            fwd_q, (xf,), dep_feed(0), length=e2e_len,
            roofline=(fwd_flops, bf16_peak * 2 if bf16_peak else None))
    finally:
        set_precision("parity")
    results.append(Result("mha_infer_float", dt_f, batch / dt_f,
                          "seq/s", f_sane, 0.0))
    results.append(Result("mha_infer_int8_ptq", dt_q, batch / dt_q,
                          "seq/s", ok and q_sane, 1.0 - cos))


def run() -> dict:
    batch = 16 if tiny_mode() else 128
    length = 4 if tiny_mode() else 16
    rng = np.random.default_rng(0)
    results = []
    _conv_micro(results, rng, batch, length)
    _model_end_to_end(results, rng, length)
    _mha_end_to_end(results, rng, length)
    return report("int8", results, meta={"batch": batch})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
