"""Pipeline-schedule benchmark: single-device fused step vs the host-driven
pipeline schedules (sync / semi-async) vs the compiled GPipe engine, with a
loss-parity gate between the pipelined and unpipelined runs.

Reference equivalent: the sync-vs-semi-async coordinator comparison the
reference stages via docker profiles (``docker-compose.yml``,
``examples/sync_pipeline_coordinator.cpp`` vs
``semi_async_pipeline_coordinator.cpp``); the gate mirrors how
``tests/test_pipeline.py`` pins the sync schedule to the unpipelined step.

Run on N>=2 devices (the 8-virtual-device CPU mesh, or a TPU slice) to see
schedule overlap; on one chip it measures pure schedule overhead.
"""

from __future__ import annotations

import sys

import numpy as np

from common import Result, check_match, print_table, report, time_callable, tiny_mode


def run() -> dict:
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.models.zoo import create_resnet9_cifar10, create_mnist_trainer
    from dcnn_tpu.ops.losses import get_loss
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel import InProcessPipelineCoordinator
    from dcnn_tpu.train import make_train_step
    from dcnn_tpu.train.trainer import create_train_state

    batch = 16 if tiny_mode() else 128
    steps = 2 if tiny_mode() else 5
    num_stages = min(4, len(jax.devices()))
    num_micro = 4
    build = create_mnist_trainer if tiny_mode() else create_resnet9_cifar10
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)

    model = build()
    c, h, w = model.input_shape
    x = rng.standard_normal((batch, c, h, w)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    dx, dy = jax.device_put(x), jax.device_put(y)

    results = []

    # single-device fused train step (the thing pipelining must justify
    # itself against)
    opt = SGD(1e-2)
    step = make_train_step(model, get_loss("softmax_crossentropy"), opt)
    ts = create_train_state(model, opt, key)

    # parity oracle: microbatched grad accumulation — the pipeline computes
    # per-microbatch BN stats, so the fused whole-batch step is NOT the same
    # math (tests/test_pipeline.py pins the same criterion)
    ref_step = make_train_step(model, get_loss("softmax_crossentropy"), opt,
                               num_microbatches=num_micro, donate=False)
    ref_ts = create_train_state(model, opt, key)
    _, ref_loss, _ = ref_step(ref_ts, dx, dy, key, 1e-2)
    ref_loss = float(ref_loss)

    def run_single():
        nonlocal ts
        ts, loss, _ = step(ts, dx, dy, key, 1e-2)
        return loss

    dt = time_callable(run_single, steps=steps, reps=2)
    results.append(Result("single_device_step", dt, batch / dt, "img/s",
                          True, 0.0))

    for schedule in ("sync", "semi_async"):
        coord = InProcessPipelineCoordinator(
            build(), SGD(1e-2), "softmax_crossentropy",
            num_stages=num_stages, num_microbatches=num_micro,
            track_load=False)  # zero telemetry fences in the timed path
        coord.deploy_stages(key)
        fn = (coord.train_batch_sync if schedule == "sync"
              else coord.train_batch_semi_async)
        # gate: first-step loss must match the unpipelined step (same init)
        loss0, _ = fn(x, y, 1e-2, key)
        ok, err = check_match(np.array(loss0), np.array(ref_loss), 1e-4)

        def run_pipelined(fn=fn, coord=coord):
            loss, _ = fn(x, y, 1e-2, key)
            # the schedule dispatches stage updates AFTER the loss ops; the
            # fence must await post-update device state, not just the (host)
            # loss, or the last step's optimizer work escapes the timer
            return [s.params for s in coord.stages]

        dt = time_callable(run_pipelined, steps=steps, reps=2)
        results.append(Result(
            f"pipeline_{schedule}_{num_stages}stages", dt, batch / dt,
            "img/s", ok, err,
            extra={"stages": num_stages, "microbatches": num_micro}))

    results += _scaling_rows()
    results += _hetero_padding_rows()
    results += _1f1b_rows()

    return report("pipeline", results,
                  meta={"batch": batch, "devices": len(jax.devices()),
                        "model": model.name})


def _scaling_rows():
    """Three pipeline engines on the SAME model at 2/4/8 stages (VERDICT r2
    #6): host-driven sync schedule vs compiled-homogeneous vs
    hetero-compiled. The model is a stack of identical GroupNorm residual
    blocks (stateless + shape-preserving, so all three engines can run it);
    loss is elementwise MSE on the output activation. Host-driven and hetero
    rows share one init key, so their first-step losses gate each other."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
    from dcnn_tpu.nn import Conv2DLayer, GroupNormLayer, ResidualBlock, Sequential
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel import InProcessPipelineCoordinator
    from dcnn_tpu.parallel.compiled_pipeline import (
        HeteroCompiledPipeline, SequentialStageStack,
        make_compiled_pipeline_train_step, shard_stacked)

    ch, hw = (4, 8) if tiny_mode() else (16, 8)
    mb = 2 if tiny_mode() else 4
    M = 4 if tiny_mode() else 8
    steps = 2 if tiny_mode() else 5
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)

    def block():
        return ResidualBlock(
            layers=[Conv2DLayer(ch, 3, 1, 1), GroupNormLayer(2)],
            shortcut=[], activation="relu")

    def stack_model(s):
        return Sequential([block() for _ in range(s)], name=f"gnstack{s}",
                          input_shape=(ch, hw, hw))

    def mse(pred, tgt):
        return jnp.mean((pred - tgt) ** 2)

    rows = []
    stage_counts = [s for s in (2, 4, 8) if s <= len(jax.devices())]
    for S in stage_counts:
        batch = mb * M
        x = rng.standard_normal((batch, ch, hw, hw)).astype(np.float32)
        y = rng.standard_normal((batch, ch, hw, hw)).astype(np.float32)
        mb_x = jnp.asarray(x.reshape(M, mb, ch, hw, hw))
        mb_y = jnp.asarray(y.reshape(M, mb, ch, hw, hw))
        mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])

        # host-driven sync schedule
        coord = InProcessPipelineCoordinator(
            stack_model(S), SGD(1e-2), "mse", num_stages=S,
            num_microbatches=M, track_load=False)
        coord.deploy_stages(key)
        ref_loss, _ = coord.train_batch_sync(x, y, 1e-2, key)

        def run_host(coord=coord):
            loss, _ = coord.train_batch_sync(x, y, 1e-2, key)
            return [s.params for s in coord.stages]

        dt = time_callable(run_host, steps=steps, reps=2)
        rows.append(Result(f"scaling_host_sync_S{S}", dt, batch / dt,
                           "img/s", True, 0.0,
                           extra={"stages": S, "microbatches": M}))

        # hetero-compiled engine (same model/init -> loss parity gate)
        pipe = HeteroCompiledPipeline(stack_model(S), S, M, mesh)
        opt = SGD(1e-2)
        fp, fs = pipe.init(key)
        opt_state = opt.init(fp)
        hstep = pipe.make_train_step(mse, opt)
        fp, opt_state, fs, loss0, _ = hstep(fp, opt_state, fs, mb_x, mb_y,
                                            key, jnp.float32(1e-2))
        ok, err = check_match(np.array(float(loss0)), np.array(ref_loss), 1e-4)

        def run_hetero():
            nonlocal fp, opt_state, fs
            fp, opt_state, fs, loss, _ = hstep(fp, opt_state, fs, mb_x, mb_y,
                                               key, jnp.float32(1e-2))
            return loss
        dt = time_callable(run_hetero, steps=steps, reps=2)
        rows.append(Result(f"scaling_hetero_compiled_S{S}", dt, batch / dt,
                           "img/s", ok, err,
                           extra={"stages": S, "microbatches": M}))

        # compiled-homogeneous engine (own per-stage init; finite-loss gate)
        stack = SequentialStageStack(block(), S, (ch, hw, hw))
        sp = shard_stacked(stack.init(key), mesh)
        opt2 = SGD(1e-2)
        ostate2 = opt2.init(sp)
        cstep = make_compiled_pipeline_train_step(
            stack.stage_fn, mse, opt2, S, M, mesh)
        sp, ostate2, closs, _ = cstep(sp, ostate2, mb_x, mb_y,
                                      jnp.float32(1e-2))

        def run_homog():
            nonlocal sp, ostate2
            sp, ostate2, loss, _ = cstep(sp, ostate2, mb_x, mb_y,
                                         jnp.float32(1e-2))
            return loss
        dt = time_callable(run_homog, steps=steps, reps=2)
        rows.append(Result(f"scaling_homog_compiled_S{S}", dt, batch / dt,
                           "img/s", bool(np.isfinite(float(closs))),
                           0.0, extra={"stages": S, "microbatches": M}))
    return rows


def _hetero_padding_rows():
    """Quantify the hetero engine's padded-flat-buffer overhead on a REAL
    heterogeneous model (stage boundary activations differ in size) and
    measure the bf16 wire prototype (VERDICT r2 weak #4): bytes shipped per
    ppermute hop vs useful bytes, and fp32- vs bf16-wire step time."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
    from dcnn_tpu.models.zoo import create_mnist_trainer, create_resnet9_cifar10
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel.compiled_pipeline import HeteroCompiledPipeline

    S = min(4, len(jax.devices()))
    M = 4
    mb = 2 if tiny_mode() else 4
    build = create_mnist_trainer if tiny_mode() else create_resnet9_cifar10
    model = build()
    mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)
    c, h, w = model.input_shape
    mb_x = jnp.asarray(rng.standard_normal((M, mb, c, h, w)).astype(np.float32))
    mb_y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, (M, mb))])
    steps = 2 if tiny_mode() else 4

    rows = []
    losses = {}
    for wire_name, wire in (("fp32", jnp.float32), ("bf16", jnp.bfloat16)):
        pipe = HeteroCompiledPipeline(build(), S, M, mesh, wire_dtype=wire)
        opt = SGD(1e-2)
        fp, fs = pipe.init(key)
        opt_state = opt.init(fp)
        step = pipe.make_train_step(softmax_cross_entropy, opt)
        fp, opt_state, fs, loss0, _ = step(fp, opt_state, fs, mb_x, mb_y,
                                           key, jnp.float32(1e-2))
        losses[wire_name] = float(loss0)

        # wire-traffic accounting, MEASURED from the lowered program: the
        # collective-permute operand widths are what actually crosses the
        # wire. overhead = shipped / useful, where useful is each boundary
        # activation's exact bytes (pipe.boundary_elems — shared with the
        # engine). A regression back to padded-width shipping shows up as
        # overhead > 1 AND flips this row's correctness gate.
        import re
        bpe = jnp.dtype(wire).itemsize
        bw = pipe.boundary_elems(mb)
        lowered = step.lower(fp, opt_state, fs, mb_x, mb_y, key,
                             jnp.float32(1e-2)).as_text()
        hlo_widths = set()
        for ln in lowered.splitlines():
            if "collective_permute" in ln:
                m = re.search(r"\(tensor<(\d+)x(?:f32|bf16|f16)>\)", ln)
                if m:
                    hlo_widths.add(int(m.group(1)))
        wire_exact = hlo_widths == set(bw)
        # each boundary ships at the smallest compiled width >= its own
        # (exact-match bucketing ⇒ identity when wire_exact holds)
        shipped_per_tick = sum(
            min((h for h in hlo_widths if h >= w), default=max(hlo_widths or [0]))
            for w in bw) * bpe
        useful = [w * bpe for w in bw]
        shipped = shipped_per_tick // max(len(bw), 1)   # avg per hop
        overhead = shipped_per_tick / max(sum(useful), 1)

        def run(step=step):
            nonlocal fp, opt_state, fs
            fp, opt_state, fs, loss, _ = step(fp, opt_state, fs, mb_x, mb_y,
                                              key, jnp.float32(1e-2))
            return loss
        dt = time_callable(run, steps=steps, reps=2)
        batch = mb * M
        rows.append(Result(
            f"hetero_wire_{wire_name}_S{S}", dt, batch / dt, "img/s",
            bool(np.isfinite(losses[wire_name])) and wire_exact, 0.0,
            extra={"stages": S, "wire_bytes_per_hop": int(shipped),
                   "padding_overhead_x": round(float(overhead), 2),
                   "hlo_wire_widths_exact": wire_exact,
                   "model": pipe.model.name}))
    # bf16 wire must track fp32 loss to bf16 tolerance — composed with the
    # wire-exactness gate, not replacing it (review r4 #1)
    rows[-1].correct = rows[-1].correct and \
        bool(abs(losses["bf16"] - losses["fp32"]) < 0.05)
    rows[-1].max_err = abs(losses["bf16"] - losses["fp32"])
    return rows


def _1f1b_rows():
    """1F1B vs GPipe as a *benchmark artifact* (VERDICT r4 #2): same model,
    same init, S=2/4/8 at M=8 — steps/s, schedule tick counts, and the
    compiled step's peak temp bytes from ``memory_analysis``, with the loss
    parity between the two engines as the correctness gate. The pytest suite
    pins pass/fail; these rows put numbers of record next to them."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.mesh import STAGE_AXIS, make_mesh
    from dcnn_tpu.nn import Conv2DLayer, GroupNormLayer, ResidualBlock, Sequential
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel.compiled_pipeline import HeteroCompiledPipeline

    ch, hw = (4, 8) if tiny_mode() else (16, 8)
    mb = 2 if tiny_mode() else 4
    M = 4 if tiny_mode() else 8
    steps = 2 if tiny_mode() else 5
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)

    def stack_model(s):
        blocks = [ResidualBlock(
            layers=[Conv2DLayer(ch, 3, 1, 1), GroupNormLayer(2)],
            shortcut=[], activation="relu") for _ in range(s)]
        return Sequential(blocks, name=f"gnstack{s}",
                          input_shape=(ch, hw, hw))

    def mse(pred, tgt):
        return jnp.mean((pred - tgt) ** 2)

    rows = []
    for S in (s for s in (2, 4, 8) if s <= len(jax.devices())):
        mesh = make_mesh((S,), (STAGE_AXIS,), devices=jax.devices()[:S])
        mb_x = jnp.asarray(rng.standard_normal(
            (M, mb, ch, hw, hw)).astype(np.float32))
        mb_y = jnp.asarray(rng.standard_normal(
            (M, mb, ch, hw, hw)).astype(np.float32))
        losses = {}
        for name, maker, ticks in (
                ("gpipe", "make_train_step", M + S - 1),
                ("1f1b", "make_train_step_1f1b", 2 * (M + S - 1))):
            pipe = HeteroCompiledPipeline(stack_model(S), S, M, mesh)
            opt = SGD(1e-2)
            fp, fs = pipe.init(key)
            ost = opt.init(fp)
            step = getattr(pipe, maker)(mse, opt)
            compiled = step.lower(fp, ost, fs, mb_x, mb_y, key,
                                  jnp.float32(1e-2)).compile()
            ma = compiled.memory_analysis()
            peak = (int(ma.temp_size_in_bytes)
                    if ma is not None and hasattr(ma, "temp_size_in_bytes")
                    else None)
            fp, ost, fs, loss0, _ = step(fp, ost, fs, mb_x, mb_y, key,
                                         jnp.float32(1e-2))
            losses[name] = float(loss0)

            def run(step=step):
                nonlocal fp, ost, fs
                fp, ost, fs, loss, _ = step(fp, ost, fs, mb_x, mb_y, key,
                                            jnp.float32(1e-2))
                return loss
            dt = time_callable(run, steps=steps, reps=2)
            # gate: both engines must produce the same schedule math
            ok = abs(losses[name] - losses["gpipe"]) < 1e-5
            rows.append(Result(
                f"engine_{name}_S{S}", dt, mb * M / dt, "img/s", ok,
                abs(losses[name] - losses["gpipe"]),
                extra={"stages": S, "microbatches": M, "ticks": ticks,
                       "peak_temp_bytes": peak}))
        # memory headline: 1F1B's stash must beat GPipe's autodiff liveness
        g, f = rows[-2], rows[-1]
        if g.extra["peak_temp_bytes"] and f.extra["peak_temp_bytes"]:
            f.extra["mem_vs_gpipe_x"] = round(
                f.extra["peak_temp_bytes"] / g.extra["peak_temp_bytes"], 3)
    return rows


if __name__ == "__main__":
    # optional positional arg: persist the section doc (the committed
    # `results_cpu_mesh.json` is this file run under
    # JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8)
    import json
    doc = run()
    print_table(doc)
    if len(sys.argv) > 1:
        with open(sys.argv[1], "w") as f:
            json.dump(doc, f, indent=1)
        print(f"wrote {sys.argv[1]}")
    sys.exit(0 if doc["all_correct"] else 1)
