"""Pipeline-schedule benchmark: single-device fused step vs the host-driven
pipeline schedules (sync / semi-async) vs the compiled GPipe engine, with a
loss-parity gate between the pipelined and unpipelined runs.

Reference equivalent: the sync-vs-semi-async coordinator comparison the
reference stages via docker profiles (``docker-compose.yml``,
``examples/sync_pipeline_coordinator.cpp`` vs
``semi_async_pipeline_coordinator.cpp``); the gate mirrors how
``tests/test_pipeline.py`` pins the sync schedule to the unpipelined step.

Run on N>=2 devices (the 8-virtual-device CPU mesh, or a TPU slice) to see
schedule overlap; on one chip it measures pure schedule overhead.
"""

from __future__ import annotations

import sys

import numpy as np

from common import Result, check_match, print_table, report, time_callable, tiny_mode


def run() -> dict:
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.models.zoo import create_resnet9_cifar10, create_mnist_trainer
    from dcnn_tpu.ops.losses import get_loss
    from dcnn_tpu.optim import SGD
    from dcnn_tpu.parallel import InProcessPipelineCoordinator
    from dcnn_tpu.train import make_train_step
    from dcnn_tpu.train.trainer import create_train_state

    batch = 16 if tiny_mode() else 128
    steps = 2 if tiny_mode() else 5
    num_stages = min(4, len(jax.devices()))
    num_micro = 4
    build = create_mnist_trainer if tiny_mode() else create_resnet9_cifar10
    key = jax.random.PRNGKey(0)
    rng = np.random.default_rng(0)

    model = build()
    c, h, w = model.input_shape
    x = rng.standard_normal((batch, c, h, w)).astype(np.float32)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, batch)]
    dx, dy = jax.device_put(x), jax.device_put(y)

    results = []

    # single-device fused train step (the thing pipelining must justify
    # itself against)
    opt = SGD(1e-2)
    step = make_train_step(model, get_loss("softmax_crossentropy"), opt)
    ts = create_train_state(model, opt, key)

    # parity oracle: microbatched grad accumulation — the pipeline computes
    # per-microbatch BN stats, so the fused whole-batch step is NOT the same
    # math (tests/test_pipeline.py pins the same criterion)
    ref_step = make_train_step(model, get_loss("softmax_crossentropy"), opt,
                               num_microbatches=num_micro, donate=False)
    ref_ts = create_train_state(model, opt, key)
    _, ref_loss, _ = ref_step(ref_ts, dx, dy, key, 1e-2)
    ref_loss = float(ref_loss)

    def run_single():
        nonlocal ts
        ts, loss, _ = step(ts, dx, dy, key, 1e-2)
        return loss

    dt = time_callable(run_single, steps=steps, reps=2)
    results.append(Result("single_device_step", dt, batch / dt, "img/s",
                          True, 0.0))

    for schedule in ("sync", "semi_async"):
        coord = InProcessPipelineCoordinator(
            build(), SGD(1e-2), "softmax_crossentropy",
            num_stages=num_stages, num_microbatches=num_micro,
            track_load=False)  # zero telemetry fences in the timed path
        coord.deploy_stages(key)
        fn = (coord.train_batch_sync if schedule == "sync"
              else coord.train_batch_semi_async)
        # gate: first-step loss must match the unpipelined step (same init)
        loss0, _ = fn(x, y, 1e-2, key)
        ok, err = check_match(np.array(loss0), np.array(ref_loss), 1e-4)

        def run_pipelined(fn=fn, coord=coord):
            loss, _ = fn(x, y, 1e-2, key)
            # the schedule dispatches stage updates AFTER the loss ops; the
            # fence must await post-update device state, not just the (host)
            # loss, or the last step's optimizer work escapes the timer
            return [s.params for s in coord.stages]

        dt = time_callable(run_pipelined, steps=steps, reps=2)
        results.append(Result(
            f"pipeline_{schedule}_{num_stages}stages", dt, batch / dt,
            "img/s", ok, err,
            extra={"stages": num_stages, "microbatches": num_micro}))

    return report("pipeline", results,
                  meta={"batch": batch, "devices": len(jax.devices()),
                        "model": model.name})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
