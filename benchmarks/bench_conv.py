"""Conv2D microbenchmark: ResNet-18/Tiny-ImageNet layer shapes, forward plus
the three backward kernels, each with a correctness gate.

Reference equivalent: the conv hot path the reference hand-optimizes
(``include/nn/layers_impl/cpu/conv2d_ops.hpp:8-29`` im2col→GEMM,
``src/nn/layers_impl/cuda/cudnn_conv2d_ops.cu``) and its benchmark-with-gate
pattern (``benchmarks/gemm_benchmark.cpp:21-34``). Forward is gated against
fp64 PyTorch (the same oracle the unit tests use); the explicit
weight/input-grad kernels are gated against jax autodiff of the forward.
"""

from __future__ import annotations

import functools
import sys

import numpy as np

from common import (Result, check_match, dep_feed, print_table, report,
                    time_chained, tiny_mode)

# (cin, cout, hw, kernel, stride, pad) — ResNet-18 tiny-imagenet trunk shapes
# (models/zoo.py create_resnet18_tiny_imagenet)
SHAPES = [
    (3, 64, 64, 3, 1, 1),      # stem
    (64, 64, 64, 3, 1, 1),     # stage 1 block conv
    (64, 128, 32, 3, 2, 1),    # stage 2 downsample
    (128, 128, 32, 3, 1, 1),
    (256, 256, 16, 3, 1, 1),
    (512, 512, 8, 3, 1, 1),    # stage 4 block conv
]
TOLS = {"parity": 5e-5, "fast": 3e-2}


ORACLE_BATCH = 8   # conv is per-sample independent: gating a batch slice is
                   # exact and keeps the 1-core fp64 oracle tractable


def _torch_conv_fp64(x, w, stride, pad):
    import torch

    with torch.no_grad():
        out = torch.nn.functional.conv2d(
            torch.from_numpy(x).double(), torch.from_numpy(w).double(),
            stride=stride, padding=pad)
    return out.numpy()


def run() -> dict:
    import jax

    from dcnn_tpu.core.precision import set_precision
    from dcnn_tpu.ops import conv as conv_ops

    batch = 16 if tiny_mode() else 128
    shapes = SHAPES[:3] if tiny_mode() else SHAPES
    length = 4 if tiny_mode() else 16
    results = []
    rng = np.random.default_rng(0)
    for mode in ("parity", "fast"):
        set_precision(mode)
        fwd = jax.jit(functools.partial(conv_ops.conv2d, data_format="NCHW"),
                      static_argnames=("stride", "padding"))
        wgrad = jax.jit(functools.partial(conv_ops.conv2d_weight_grad,
                                          data_format="NCHW"),
                        static_argnames=("kernel_hw", "stride", "padding"))
        igrad = jax.jit(functools.partial(conv_ops.conv2d_input_grad,
                                          data_format="NCHW"),
                        static_argnames=("input_shape", "stride", "padding"))
        for cin, cout, hw, k, s, p in shapes:
            x = rng.standard_normal((batch, cin, hw, hw), np.float32)
            w = rng.standard_normal((cout, cin, k, k), np.float32) / np.sqrt(cin * k * k)
            dx, dw = jax.device_put(x), jax.device_put(w)
            tag = f"{cin}x{hw}x{hw}->{cout}_s{s}_{mode}"

            got = fwd(dx, dw, stride=s, padding=p)
            ok, err = check_match(
                np.asarray(got[:ORACLE_BATCH]),
                _torch_conv_fp64(x[:ORACLE_BATCH], w, s, p), TOLS[mode])
            oh = got.shape[2]
            flops = 2.0 * batch * cout * cin * k * k * oh * oh
            dt, _ = time_chained(
                lambda xx, ww, _s=s, _p=p: fwd(xx, ww, stride=_s, padding=_p),
                (dx, dw), dep_feed(0), length=length)
            results.append(Result(f"conv_fwd_{tag}", dt, flops / dt / 1e12,
                                  "TFLOP/s", ok, err))

            g = rng.standard_normal(got.shape, np.float32)
            dg = jax.device_put(g)
            # autodiff oracle for the explicit backward kernels (same-device,
            # parity precision) — these are distinct code paths in ops/conv.py
            set_precision("parity")
            _, vjp = jax.vjp(lambda xx, ww, _s=s, _p=p: conv_ops.conv2d(
                xx, ww, stride=_s, padding=_p, data_format="NCHW"), dx, dw)
            want_ig, want_wg = jax.device_get(vjp(dg))
            set_precision(mode)

            got_wg = wgrad(dx, dg, kernel_hw=(k, k), stride=s, padding=p)
            ok, err = check_match(got_wg, want_wg, TOLS[mode])
            dt, _ = time_chained(
                lambda xx, gg, _k=k, _s=s, _p=p: wgrad(
                    xx, gg, kernel_hw=(_k, _k), stride=_s, padding=_p),
                (dx, dg), dep_feed(0), length=length)
            results.append(Result(f"conv_wgrad_{tag}", dt, flops / dt / 1e12,
                                  "TFLOP/s", ok, err))

            got_ig = igrad(dw, dg, input_shape=x.shape, stride=s, padding=p)
            ok, err = check_match(got_ig, want_ig, TOLS[mode])
            dt, _ = time_chained(
                lambda ww, gg, _s=s, _p=p: igrad(
                    ww, gg, input_shape=x.shape, stride=_s, padding=_p),
                (dw, dg), dep_feed(0), length=length)
            results.append(Result(f"conv_igrad_{tag}", dt, flops / dt / 1e12,
                                  "TFLOP/s", ok, err))
    set_precision("parity")
    return report("conv", results, meta={"batch": batch})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
