"""GEMM microbenchmark with correctness gate.

Reference equivalent: ``/root/reference/benchmarks/gemm_benchmark.cpp:16-50``
(AVX2-blocked SGEMM vs MKL cblas_sgemm, gated by ``check_match``). Here the
"kernel under test" is the MXU via ``jnp.matmul`` at each precision policy
(parity = fp32-equivalent multi-pass, fast/bf16 = native bf16 passes), gated
against fp64 numpy.
"""

from __future__ import annotations

import functools
import sys

import numpy as np

from common import (Result, check_match, print_table, replace_feed, report,
                    time_chained, tiny_mode)

SIZES = [(512, 512, 512), (1024, 1024, 1024), (2048, 2048, 2048),
         (4096, 4096, 4096)]
TOLS = {"parity": 2e-5, "fast": 2e-2}


def run() -> dict:
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.precision import get_precision, set_precision

    sizes = SIZES[:2] if tiny_mode() else SIZES
    results = []
    rng = np.random.default_rng(0)
    for mode in ("parity", "fast"):
        set_precision(mode)

        @functools.partial(jax.jit, static_argnums=())
        def mm(a, b):
            return jnp.matmul(a, b, precision=get_precision())

        for m, n, k in sizes:
            a = rng.standard_normal((m, k), np.float32)
            b = rng.standard_normal((k, n), np.float32)
            da, db = jax.device_put(a), jax.device_put(b)
            got = mm(da, db)
            ok, err = check_match(got, a.astype(np.float64) @ b, TOLS[mode])
            # iteration count scaled inversely with FLOPs so the timed delta
            # stays well above tunnel jitter even for sub-ms matmuls
            length = (8 if tiny_mode()
                      else max(32, min(2048, int(32 * (4096 / m) ** 2))))
            # square matmul: the output IS the next iteration's lhs — full
            # consumption, zero dependency overhead
            dt, _ = time_chained(mm, (da, db), replace_feed(0),
                                 length=length)
            gflops = 2.0 * m * n * k / dt / 1e9
            results.append(Result(
                name=f"gemm_{m}x{n}x{k}_{mode}", seconds=dt, rate=gflops,
                unit="GFLOP/s", correct=ok, max_err=err))
    set_precision("parity")
    return report("gemm", results)


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
