"""Replay-vs-reality profiling skew (VERDICT r4 #9).

``LayerProfiler`` measures layers by re-running the chain eagerly with a
device fence per layer (a *replay* — the only way to get per-layer walls
when XLA fuses the real step). This study quantifies, once, how that replay's
per-layer ranking compares against the *fused* train step's ground truth
from an xprof trace:

1. replay: ``profile_forward`` + ``profile_backward`` on ResNet-9 (one
   batch) → per-layer fwd+bwd µs shares;
2. fused: ``jax.profiler.trace`` around real train steps → parse the
   ``.xplane.pb`` with xprof's ``framework_op_stats`` and aggregate op
   self-time by the per-layer ``jax.named_scope`` tags Sequential.apply
   emits;
3. report both shares side by side + the Spearman rank correlation.

Writes ``benchmarks/results_profiling_skew.json``; the table of record goes
to RESULTS.md. Run on the TPU host: ``python benchmarks/profiling_skew.py``.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys

import numpy as np

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)
OUT = os.path.join(ROOT, "benchmarks", "results_profiling_skew.json")


def replay_shares(model, params, state, x, y, key):
    from dcnn_tpu.core.config import ProfilerType
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.train.profiling import LayerProfiler

    import jax
    import jax.numpy as jnp

    prof = LayerProfiler(ProfilerType.CUMULATIVE)
    logits, _ = prof.profile_forward(model, params, state, x,
                                     training=True, rng=key)
    g = jax.grad(lambda o: softmax_cross_entropy(o, jnp.asarray(y)))(logits)
    prof.profile_backward(model, params, state, x, g, rng=key)
    total = {n: prof.forward_us.get(n, 0.0) + prof.backward_us.get(n, 0.0)
             for n in set(prof.forward_us) | set(prof.backward_us)}
    s = sum(total.values())
    return {n: v / s for n, v in total.items()}


def fused_shares(model, params, state, x, y, key, trace_dir):
    """Trace N fused steps, aggregate HLO self-time by layer scope."""
    import jax
    import jax.numpy as jnp

    from dcnn_tpu.core.fence import hard_fence
    from dcnn_tpu.ops.losses import softmax_cross_entropy
    from dcnn_tpu.optim import Adam
    from dcnn_tpu.train import make_train_step
    from dcnn_tpu.train.trainer import create_train_state

    opt = Adam(1e-3)
    ts = create_train_state(model, opt, key)
    step = make_train_step(model, softmax_cross_entropy, opt, donate=False)
    for i in range(3):   # compile + warm
        ts, loss, _ = step(ts, x, y, jax.random.fold_in(key, i), 1e-3)
    hard_fence(loss)
    with jax.profiler.trace(trace_dir):
        for i in range(5):
            ts, loss, _ = step(ts, x, y, jax.random.fold_in(key, 10 + i), 1e-3)
        hard_fence(loss)

    planes = glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                       recursive=True)
    if not planes:
        raise SystemExit(f"no .xplane.pb under {trace_dir}")
    from xprof.convert import raw_to_tool_data as rtd
    data, _ = rtd.xspace_to_tool_data(planes, "framework_op_stats", {})
    if isinstance(data, bytes):
        data = data.decode()
    rows = _op_rows(json.loads(data) if isinstance(data, str) else data)
    layer_names = [l.name for l in model.layers]
    agg = {n: 0.0 for n in layer_names}
    other = 0.0
    for name, t in rows:
        hit = None
        for ln in layer_names:
            if re.search(rf"(^|/){re.escape(ln)}(/|$|\.)", name) or ln in name:
                hit = ln
                break
        if hit:
            agg[hit] += t
        else:
            other += t
    s = sum(agg.values())
    return ({n: v / s for n, v in agg.items()} if s else {}), \
        other / max(s + other, 1e-9)


def _op_rows(parsed):
    """Extract (op_name_with_scope, self_time) pairs from the
    framework_op_stats payload. The plugin ships gviz DataTables — possibly
    a list of them (device table first) — with column ids/labels naming an
    operation column and a self-time column; tolerate either shape."""
    tables = parsed if isinstance(parsed, list) else [parsed]
    out = []
    for tab in tables:
        if not isinstance(tab, dict) or "cols" not in tab:
            continue
        ids = [(c.get("id") or "").lower() for c in tab["cols"]]
        labels = [(c.get("label") or "").lower() for c in tab["cols"]]

        def find(*cands):
            # exact column-id match first ("operation" must not hit the
            # "type" column whose LABEL is "Operation Type"), then a
            # substring fallback over ids+labels for other xprof versions
            for cand in cands:
                if cand in ids:
                    return ids.index(cand)
            for cand in cands:
                spaced = cand.replace("_", " ")
                hyphened = cand.replace("_", "-")
                for i, (cid, lab) in enumerate(zip(ids, labels)):
                    if (cand in cid or spaced in lab or hyphened in lab
                            or spaced.replace(" time", "-time") in lab):
                        return i
            return None
        c_name = find("operation", "op_name")
        c_time = find("total_self_time", "self_time")
        c_side = find("host_or_device")
        c_type = find("type")
        if c_name is None or c_time is None:
            continue
        used = [i for i in (c_name, c_time, c_side, c_type) if i is not None]
        for row in tab.get("rows", []):
            # gviz rows may carry null cells in columns we never read
            cells = [(c or {}).get("v") for c in row.get("c", [])]
            if len(cells) <= max(used):
                continue
            if c_side is not None and cells[c_side] != "Device":
                continue
            if c_type is not None and cells[c_type] == "IDLE":
                continue
            name, t = cells[c_name], cells[c_time]
            if isinstance(name, str) and isinstance(t, (int, float)):
                out.append((name, float(t)))
        if out:
            break  # device rows of the first parseable table
    if not out:
        raise SystemExit(
            f"could not parse framework_op_stats payload: "
            f"{str(parsed)[:400]}")
    return out


def spearman(a, b):
    ra = np.argsort(np.argsort(a))
    rb = np.argsort(np.argsort(b))
    if len(a) < 2:
        return 1.0
    return float(np.corrcoef(ra, rb)[0, 1])


def main():
    import tempfile

    import jax
    import jax.numpy as jnp

    from dcnn_tpu.models.zoo import create_resnet9_cifar10

    fmt = "NHWC" if jax.default_backend() == "tpu" else "NCHW"
    model = create_resnet9_cifar10(fmt)
    key = jax.random.PRNGKey(0)
    params, state = model.init(key)
    rng = np.random.default_rng(0)
    batch = int(os.environ.get("SKEW_BATCH", "128"))
    shape = ((batch, 3, 32, 32) if fmt == "NCHW" else (batch, 32, 32, 3))
    x = jnp.asarray(rng.standard_normal(shape).astype(np.float32))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[
        rng.integers(0, 10, batch)])

    rep = replay_shares(model, params, state, x, y, key)
    with tempfile.TemporaryDirectory(prefix="skew_trace_") as td:
        fus, unattributed = fused_shares(model, params, state, x, y, key, td)

    names = [l.name for l in model.layers if l.name in rep]
    rep_v = np.array([rep.get(n, 0.0) for n in names])
    fus_v = np.array([fus.get(n, 0.0) for n in names])
    rho = spearman(rep_v, fus_v)

    print(f"{'layer':<16s} {'replay %':>9s} {'fused %':>9s}")
    for n in sorted(names, key=lambda n: -rep.get(n, 0)):
        print(f"{n:<16s} {100 * rep.get(n, 0):>8.1f}% "
              f"{100 * fus.get(n, 0):>8.1f}%")
    print(f"spearman rank correlation: {rho:.3f}; "
          f"unattributed fused time: {100 * unattributed:.1f}%")

    doc = {"section": "profiling_skew", "model": model.name, "batch": batch,
           "format": fmt,
           "device": jax.devices()[0].device_kind,
           "replay_share": {n: round(rep.get(n, 0.0), 4) for n in names},
           "fused_share": {n: round(fus.get(n, 0.0), 4) for n in names},
           "spearman_rank_corr": round(rho, 4),
           "fused_unattributed_frac": round(unattributed, 4)}
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
