"""Attention microbenchmark: naive (materialising) vs blockwise (online
softmax) vs Pallas flash kernel, forward and forward+backward, gated against
the naive oracle.

The reference has no attention (SURVEY.md §5.7) — this benches the
long-context subsystem the TPU build adds (ops/attention.py) and provides the
profiling evidence SURVEY Stage 4 prescribes for the Pallas path: flash must
beat (or match) XLA's blockwise scan at these sizes to earn its place.
"""

from __future__ import annotations

import functools
import sys

import numpy as np

from common import (Result, check_match, outputs_as_args_feed, print_table,
                    replace_feed, report, time_chained, tiny_mode)

TOL = 5e-3   # bf16-accumulator-free paths all keep fp32 stats; loose enough
             # for bf16 MXU scores at S=2048


def run() -> dict:
    import importlib

    import jax

    # NB: plain ``import dcnn_tpu.ops.attention`` resolves to the re-exported
    # *function* (the package __init__ rebinds the name); go via sys.modules
    att = importlib.import_module("dcnn_tpu.ops.attention")

    b, h, d = (2, 4, 64)
    seqs = [256] if tiny_mode() else [1024, 4096]
    length = 2 if tiny_mode() else 8
    results = []
    rng = np.random.default_rng(0)
    on_tpu = jax.default_backend() == "tpu"

    impls = {
        "naive": jax.jit(functools.partial(att.attention, causal=True)),
        "blockwise": jax.jit(functools.partial(att.blockwise_attention, causal=True)),
    }
    if on_tpu:
        impls["flash"] = jax.jit(functools.partial(att.flash_attention, causal=True))

    for s in seqs:
        q = rng.standard_normal((b, h, s, d), np.float32)
        k = rng.standard_normal((b, h, s, d), np.float32)
        v = rng.standard_normal((b, h, s, d), np.float32)
        dq, dk, dv = map(jax.device_put, (q, k, v))
        want = jax.device_get(impls["naive"](dq, dk, dv))
        # causal attention FLOPs: ~0.5 * 4 * b*h*s^2*d (QK^T + PV, half masked)
        flops = 2.0 * b * h * s * s * d
        for name, fn in impls.items():
            got = fn(dq, dk, dv)
            ok, err = check_match(got, want, TOL)
            # attention output has q's shape: feed it back as q
            dt, _ = time_chained(fn, (dq, dk, dv), replace_feed(0),
                                 length=length)
            results.append(Result(f"attn_fwd_{name}_S{s}", dt,
                                  flops / dt / 1e12, "TFLOP/s", ok, err))

        # forward+backward (grad wrt q,k,v) — flash's VJP runs the Pallas
        # dq/dk/dv kernels (round 3); this measures what training pays
        grads = {
            name: jax.jit(jax.grad(lambda a, b_, c, f=fn: f(a, b_, c).sum(),
                                   argnums=(0, 1, 2)))
            for name, fn in impls.items()
        }
        want_g = jax.device_get(grads["naive"](dq, dk, dv))
        for name, gfn in grads.items():
            got_g = gfn(dq, dk, dv)
            oks, errs = zip(*(check_match(gg, wg, TOL)
                              for gg, wg in zip(got_g, want_g)))
            # (dq,dk,dv) grads match (q,k,v) shapes: full tuple replacement
            dt, _ = time_chained(gfn, (dq, dk, dv), outputs_as_args_feed(),
                              length=length)
            results.append(Result(f"attn_bwd_{name}_S{s}", dt,
                                  3.5 * flops / dt / 1e12, "TFLOP/s",
                                  all(oks), max(errs)))
    return report("attention", results,
                  meta={"batch": b, "heads": h, "head_dim": d,
                        "flash_available": on_tpu})


if __name__ == "__main__":
    doc = run()
    print_table(doc)
    sys.exit(0 if doc["all_correct"] else 1)
