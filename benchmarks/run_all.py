"""Run every microbenchmark section and persist one machine-readable report.

Reference equivalent: the ``benchmarks/`` executables of the reference
(gemm / tensor-ops / serialization / compression), unified behind one
command. Usage::

    python benchmarks/run_all.py [--out benchmarks/results.json]
    BENCH_TINY=1 python benchmarks/run_all.py      # CI-sized problems

Exit code is non-zero if any section's correctness gate fails — wrong-fast
is a bug, not a result (gemm_benchmark.cpp:21-34).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from common import print_table

SECTIONS = ("bench_gemm", "bench_conv", "bench_ops", "bench_attention",
            "bench_serialization", "bench_pipeline", "bench_pallas_conv",
            "bench_int8")


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results.json"))
    ap.add_argument("--only", nargs="*", default=None,
                    help="subset of section module names")
    ap.add_argument("--merge", action="store_true",
                    help="merge the sections that ran into an existing --out "
                         "report instead of replacing it (for --only reruns)")
    args = ap.parse_args()

    import importlib

    import jax

    jax.config.update("jax_compilation_cache_dir", "/tmp/jax_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    docs = []
    ok = True
    for mod_name in (args.only or SECTIONS):
        t0 = time.perf_counter()
        doc = importlib.import_module(mod_name).run()
        doc["wall_seconds"] = round(time.perf_counter() - t0, 1)
        print_table(doc)
        docs.append(doc)
        ok = ok and doc["all_correct"]

    out = {
        "suite": "dcnn_tpu_microbenchmarks",
        "device": jax.devices()[0].device_kind,
        "backend": jax.default_backend(),
        "tiny": os.environ.get("BENCH_TINY", "0") == "1",
        "all_correct": ok,
        "sections": docs,
    }
    if args.merge and os.path.exists(args.out):
        # refresh only the sections that ran (--only reruns), keep the rest,
        # and recompute the top-level gate — no hand-splicing of the report
        with open(args.out) as f:
            prev = json.load(f)
        merged = {s["section"]: s for s in prev.get("sections", [])}
        merged.update({s["section"]: s for s in docs})
        out["sections"] = list(merged.values())
        out["all_correct"] = ok = bool(
            all(s["all_correct"] for s in out["sections"]))
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
    print(f"\nwrote {args.out}  all_correct={ok}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
